"""automerge_tpu — a TPU-native CRDT framework for collaborative documents.

Same capabilities as the reference Automerge library (v0.9.2,
`/root/reference/src/automerge.js`): every peer holds a full copy of a JSON
document, edits it locally/offline, and merging any two copies converges
automatically. The frontend/session/sync semantics match the reference; the
backend CRDT engine additionally has a batched device path
(:mod:`automerge_tpu.device`) that resolves causal graphs for thousands of
documents at once on TPU via JAX/XLA, with documents sharded over a device
mesh (:mod:`automerge_tpu.parallel`).

Public API parity (src/automerge.js:122-134): ``init, change, empty_change,
undo, redo, load, save, merge, diff, get_changes, apply_changes,
get_missing_deps, equals, inspect, get_history, uuid, Frontend, Backend,
DocSet, WatchableDoc, Connection, Text`` plus the frontend re-exports
``can_undo, can_redo, get_actor_id, set_actor_id, get_conflicts``.
camelCase aliases are provided for users coming from the reference.
"""

import json as _json

from . import frontend as Frontend
from . import backend as Backend
from .common import ROOT_ID, is_object, less_or_equal
from .text import Text
from .uuid import uuid

__version__ = '0.9.2'


def doc_from_changes(actor_id, changes):
    """Construct a frontend document reflecting `changes`
    (src/automerge.js:10-17)."""
    if not actor_id:
        raise ValueError('actor_id is required in doc_from_changes')
    doc = Frontend.init({'actorId': actor_id, 'backend': Backend})
    state, _ = Backend.apply_changes(Backend.init(actor_id), changes)
    patch = Backend.get_patch(state)
    patch['state'] = state
    return Frontend.apply_patch(doc, patch)


def init(actor_id=None):
    """A new empty document with an immediate in-process backend
    (src/automerge.js:21-23)."""
    return Frontend.init({'actorId': actor_id, 'backend': Backend})


def change(doc, message=None, callback=None):
    """Edit `doc` via a mutable proxy in `callback`; returns the new document
    (src/automerge.js:25-28)."""
    new_doc, _ = Frontend.change(doc, message, callback)
    return new_doc


def empty_change(doc, message=None):
    new_doc, _ = Frontend.empty_change(doc, message)
    return new_doc


def undo(doc, message=None):
    new_doc, _ = Frontend.undo(doc, message)
    return new_doc


def redo(doc, message=None):
    new_doc, _ = Frontend.redo(doc, message)
    return new_doc


def load(data, actor_id=None):
    """Deserialize a document saved with :func:`save` (src/automerge.js:45-47).

    The reference serializes with transit-immutable-js; this framework uses a
    plain-JSON envelope of the change history (the wire format of changes is
    identical, so histories interoperate at the change level).
    """
    payload = _json.loads(data)
    if isinstance(payload, dict):
        changes = payload['changes']
    else:
        changes = payload
    return doc_from_changes(actor_id or uuid(), changes)


def save(doc):
    """Serialize the full change history (src/automerge.js:49-52).

    Works for host-oracle and device-backed documents alike: both backend
    states expose the SharedChangeLog surface (the device state directly,
    the oracle via its op_set). A document resumed from a packed snapshot
    no longer holds pre-snapshot change bodies, so saving it here would
    silently produce a log that cannot replay — that case raises; use
    :func:`save_snapshot` for such documents."""
    state = Frontend.get_backend_state(doc)
    log = state.op_set if hasattr(state, 'op_set') else state
    if getattr(log, 'log_truncated', False):
        raise ValueError(
            'this document was resumed from a packed snapshot and no '
            'longer holds its full change log; persist it with '
            'save_snapshot() instead')
    history = log.get_history()
    return _json.dumps({'format': 'automerge-tpu@1', 'changes': history})


def _backend_of(state):
    """The backend module a state belongs to — the facade works uniformly
    over host-oracle and device-backed documents (and mixes of the two:
    changes are the wire format either way)."""
    if hasattr(state, 'op_set'):
        return Backend
    from .device import backend as DeviceBackend
    return DeviceBackend


def merge(local_doc, remote_doc):
    """Apply changes from `remote_doc` missing in `local_doc`
    (src/automerge.js:54-64). The two documents may use different
    backends (oracle or device) — the change wire format is shared."""
    if Frontend.get_actor_id(local_doc) == Frontend.get_actor_id(remote_doc):
        raise ValueError('Cannot merge an actor with itself')
    local_state = Frontend.get_backend_state(local_doc)
    remote_state = Frontend.get_backend_state(remote_doc)
    changes = _backend_of(remote_state).get_missing_changes(
        remote_state, local_state.clock)
    state, patch = _backend_of(local_state).apply_changes(local_state,
                                                          changes)
    if not patch['diffs']:
        return local_doc
    patch['state'] = state
    return Frontend.apply_patch(local_doc, patch)


def diff(old_doc, new_doc):
    """Diffs that transform `old_doc`'s tree into `new_doc`'s
    (src/automerge.js:66-72)."""
    old_state = Frontend.get_backend_state(old_doc)
    changes = get_changes(old_doc, new_doc)
    _, patch = _backend_of(old_state).apply_changes(old_state, changes)
    return patch['diffs']


def get_changes(old_doc, new_doc):
    old_state = Frontend.get_backend_state(old_doc)
    new_state = Frontend.get_backend_state(new_doc)
    if not less_or_equal(dict(old_state.clock), dict(new_state.clock)):
        raise ValueError('Cannot diff two states that have diverged')
    return _backend_of(new_state).get_missing_changes(new_state,
                                                      old_state.clock)


def apply_changes(doc, changes):
    old_state = Frontend.get_backend_state(doc)
    new_state, patch = _backend_of(old_state).apply_changes(old_state,
                                                            changes)
    patch['state'] = new_state
    return Frontend.apply_patch(doc, patch)


def get_missing_deps(doc):
    state = Frontend.get_backend_state(doc)
    return _backend_of(state).get_missing_deps(state)


def equals(val1, val2):
    """Deep equality on document values, ignoring CRDT metadata
    (src/automerge.js:91-100)."""
    if isinstance(val1, Text) or isinstance(val2, Text):
        return isinstance(val1, Text) and isinstance(val2, Text) and list(val1) == list(val2)
    if isinstance(val1, dict) and isinstance(val2, dict):
        if sorted(val1.keys()) != sorted(val2.keys()):
            return False
        return all(equals(val1[k], val2[k]) for k in val1)
    if isinstance(val1, list) and isinstance(val2, list):
        return len(val1) == len(val2) and all(equals(a, b) for a, b in zip(val1, val2))
    return val1 == val2


def inspect(doc):
    """Plain JSON-like copy of the document without CRDT metadata
    (src/automerge.js:102-104)."""
    def clean(value):
        if isinstance(value, Text):
            return ''.join(str(v) for v in value)
        if isinstance(value, dict):
            return {k: clean(v) for k, v in value.items()}
        if isinstance(value, list):
            return [clean(v) for v in value]
        return value
    return clean(doc)


class _HistoryEntry:
    """One change in the history, with a lazily-built document snapshot
    (src/automerge.js:106-120)."""

    __slots__ = ('_actor', '_history', '_index')

    def __init__(self, actor, history, index):
        self._actor = actor
        self._history = history
        self._index = index

    @property
    def change(self):
        return self._history[self._index]

    @property
    def snapshot(self):
        return doc_from_changes(self._actor, self._history[:self._index + 1])


def get_history(doc):
    state = Frontend.get_backend_state(doc)
    actor = Frontend.get_actor_id(doc)
    log = state.op_set if hasattr(state, 'op_set') else state
    history = log.get_history()
    return [_HistoryEntry(actor, history, i) for i in range(len(history))]


# Frontend re-exports (src/automerge.js:137-139)
can_undo = Frontend.can_undo
can_redo = Frontend.can_redo
get_actor_id = Frontend.get_actor_id
set_actor_id = Frontend.set_actor_id
get_conflicts = Frontend.get_conflicts
get_object_id = Frontend.get_object_id
get_element_ids = Frontend.get_element_ids

from .config import Options                 # noqa: E402
from .snapshot import (save_snapshot, load_snapshot,  # noqa: E402
                       SnapshotCorruptError)
from .sync.doc_set import DocSet            # noqa: E402
from .sync.watchable_doc import WatchableDoc  # noqa: E402
from .sync.connection import Connection, MessageRejected  # noqa: E402
from .sync.resilient import ResilientConnection  # noqa: E402

# camelCase aliases (reference API parity)
emptyChange = empty_change
getChanges = get_changes
applyChanges = apply_changes
getMissingDeps = get_missing_deps
getHistory = get_history
docFromChanges = doc_from_changes
canUndo = can_undo
canRedo = can_redo
getActorId = get_actor_id
setActorId = set_actor_id
getConflicts = get_conflicts
getObjectId = get_object_id
getElementIds = get_element_ids
