"""Backend facade: state management, change application, patch construction.

Parity with `/root/reference/backend/index.js` — the public surface is
``init, apply_changes, apply_local_change, get_patch, get_changes,
get_changes_for_actor, get_missing_changes, get_missing_deps, merge``
(backend/index.js:310-313), plus the undo/redo executors. camelCase
aliases are exported for users coming from the reference API.

The state handed out here is a :class:`BackendState` snapshot wrapping an
:class:`~automerge_tpu.backend.op_set.OpSet`; every apply produces a new
snapshot and old ones remain valid (persistent semantics, like the
reference's Immutable.js state).
"""

from ..common import ROOT_ID, is_object, less_or_equal
from ..utils.metrics import metrics
from . import op_set as OpSet


class BackendState:
    """Immutable-by-convention snapshot of the backend."""

    __slots__ = ('op_set',)

    def __init__(self, op_set):
        self.op_set = op_set

    @property
    def clock(self):
        """Uniform clock accessor shared with the device backend state, so
        protocol layers (Connection) need no per-backend special cases."""
        return self.op_set.clock


class MaterializationContext:
    """Builds the diff list that instantiates a whole document tree
    (backend/index.js:5-117). Children are emitted before parents so the
    frontend can resolve links as it applies the patch."""

    def __init__(self):
        self.diffs = {}
        self.children = {}

    def _unpack_value(self, parent_id, diff, value):
        if isinstance(value, dict) and 'objectId' in value:
            diff['value'] = value['objectId']
            diff['link'] = True
            self.children[parent_id].append(value['objectId'])
        else:
            diff['value'] = value

    def _unpack_conflicts(self, parent_id, diff, conflicts):
        if conflicts:
            diff['conflicts'] = []
            for actor, value in conflicts.items():
                conflict = {'actor': actor}
                self._unpack_value(parent_id, conflict, value)
                diff['conflicts'].append(conflict)

    def _instantiate_map(self, ops, object_id):
        diffs = self.diffs[object_id]
        if object_id != ROOT_ID:
            diffs.append({'obj': object_id, 'type': 'map', 'action': 'create'})

        conflicts = OpSet.get_object_conflicts(ops, object_id, self)
        for key in OpSet.get_object_fields(ops, object_id):
            diff = {'obj': object_id, 'type': 'map', 'action': 'set', 'key': key}
            self._unpack_value(object_id, diff, OpSet.get_object_field(ops, object_id, key, self))
            self._unpack_conflicts(object_id, diff, conflicts.get(key))
            diffs.append(diff)

    def _instantiate_list(self, ops, object_id, obj_type):
        diffs = self.diffs[object_id]
        # maxElem rides on the create diff: visible inserts alone
        # under-count it when the highest-counter element is a tombstone,
        # and a frontend resuming from this patch would mint colliding
        # elemIds. (The reference omits this and has that latent bug.)
        diffs.append({'obj': object_id, 'type': obj_type, 'action': 'create',
                      'maxElem': ops.by_object[object_id].max_elem})

        conflicts = OpSet.list_iterator(ops, object_id, 'conflicts', self)
        values = OpSet.list_iterator(ops, object_id, 'values', self)
        for index, elem_id in OpSet.list_iterator(ops, object_id, 'elems', self):
            diff = {'obj': object_id, 'type': obj_type, 'action': 'insert',
                    'index': index, 'elemId': elem_id}
            self._unpack_value(object_id, diff, next(values))
            self._unpack_conflicts(object_id, diff, next(conflicts))
            diffs.append(diff)

    def instantiate_object(self, ops, object_id):
        if object_id in self.diffs:
            return {'objectId': object_id}

        obj_type = ops.by_object[object_id].init_action
        self.diffs[object_id] = []
        self.children[object_id] = []

        if object_id == ROOT_ID or obj_type == 'makeMap':
            self._instantiate_map(ops, object_id)
        elif obj_type == 'makeList':
            self._instantiate_list(ops, object_id, 'list')
        elif obj_type == 'makeText':
            self._instantiate_list(ops, object_id, 'text')
        else:
            raise ValueError(f'Unknown object type: {obj_type}')
        return {'objectId': object_id}

    def make_patch(self, object_id, diffs):
        for child_id in self.children[object_id]:
            self.make_patch(child_id, diffs)
        diffs.extend(self.diffs[object_id])


def init(_actor_id=None):
    """Empty backend state (backend/index.js:123-125). The optional actor
    argument is accepted for reference-API compatibility and ignored."""
    return BackendState(OpSet.init())


def _make_patch(state, diffs):
    ops = state.op_set
    return {'clock': dict(ops.clock), 'deps': dict(ops.deps),
            'canUndo': ops.undo_pos > 0, 'canRedo': bool(ops.redo_stack),
            'diffs': diffs}


def _normalize_change(change):
    return {k: v for k, v in change.items() if k != 'requestType'}


def _apply(state, changes, undoable):
    ops = state.op_set.clone()
    diffs = []
    n_ops = 0
    for change in changes:
        n_ops += len(change.get('ops', []))
        diffs.extend(OpSet.add_change(ops, _normalize_change(change), undoable))
    state = BackendState(ops)

    m = metrics
    m.bump('changes_applied', len(changes))
    m.bump('ops_applied', n_ops)
    m.bump('conflicts_detected',
           sum(1 for d in diffs if d.get('conflicts')))
    m.set_gauge('queue_depth', len(ops.queue))
    if m.active:
        m.emit('apply', changes=len(changes), ops=n_ops, diffs=len(diffs),
               queued=len(ops.queue), undoable=undoable)
    return state, _make_patch(state, diffs)


def apply_changes(state, changes):
    """Apply remote changes; returns (state, patch) (backend/index.js:161-163)."""
    return _apply(state, changes, False)


def apply_local_change(state, change):
    """Apply one local change request, recording undo history
    (backend/index.js:173-195)."""
    if not isinstance(change.get('actor'), str) or not isinstance(change.get('seq'), int):
        raise TypeError('Change request requires `actor` and `seq` properties')
    if change['seq'] <= state.op_set.clock.get(change['actor'], 0):
        raise ValueError('Change request has already been applied')

    request_type = change.get('requestType')
    if request_type == 'change':
        state, patch = _apply(state, [change], True)
    elif request_type == 'undo':
        state, patch = undo(state, change)
    elif request_type == 'redo':
        state, patch = redo(state, change)
    else:
        raise ValueError(f'Unknown requestType: {request_type}')
    patch['actor'] = change['actor']
    patch['seq'] = change['seq']
    return state, patch


def get_patch(state):
    """Patch that builds the whole document from empty (backend/index.js:201-207)."""
    diffs = []
    context = MaterializationContext()
    context.instantiate_object(state.op_set, ROOT_ID)
    context.make_patch(ROOT_ID, diffs)
    return _make_patch(state, diffs)


def get_changes(old_state, new_state):
    old_clock = old_state.op_set.clock
    new_clock = new_state.op_set.clock
    if not less_or_equal(old_clock, new_clock):
        raise ValueError('Cannot diff two states that have diverged')
    return OpSet.get_missing_changes(new_state.op_set, old_clock)


def get_changes_for_actor(state, actor_id):
    return OpSet.get_changes_for_actor(state.op_set, actor_id)


def get_missing_changes(state, clock):
    return OpSet.get_missing_changes(state.op_set, clock)


def get_missing_deps(state):
    return OpSet.get_missing_deps(state.op_set)


def merge(local, remote):
    """Pull changes present in `remote` but not `local` (backend/index.js:240-243)."""
    changes = OpSet.get_missing_changes(remote.op_set, local.op_set.clock)
    return apply_changes(local, changes)


def undo(state, request):
    """Apply the inverse ops from the undo stack as a new change
    (backend/index.js:252-285)."""
    ops = state.op_set
    undo_pos = ops.undo_pos
    undo_ops = ops.undo_stack[undo_pos - 1] if undo_pos >= 1 else None
    if undo_pos < 1 or undo_ops is None:
        raise ValueError('Cannot undo: there is nothing to be undone')

    change = {'actor': request['actor'], 'seq': request['seq'],
              'deps': dict(request.get('deps', {})), 'ops': undo_ops}
    if request.get('message') is not None:
        change['message'] = request['message']

    redo_ops = []
    for op in undo_ops:
        if op['action'] not in ('set', 'del', 'link'):
            raise ValueError(f'Unexpected operation type in undo history: {op}')
        field_ops = OpSet.get_field_ops(ops, op['obj'], op['key'])
        if not field_ops:
            redo_ops.append({'action': 'del', 'obj': op['obj'], 'key': op['key']})
        else:
            for field_op in field_ops:
                redo_ops.append({k: v for k, v in field_op.items()
                                 if k not in ('actor', 'seq')})

    new_ops = ops.clone()
    new_ops.undo_pos = undo_pos - 1
    new_ops.redo_stack = new_ops.redo_stack + [redo_ops]
    diffs = OpSet.add_change(new_ops, change, False)
    state = BackendState(new_ops)
    return state, _make_patch(state, diffs)


def redo(state, request):
    """Re-apply the ops reverted by the last undo (backend/index.js:293-308)."""
    redo_ops = state.op_set.redo_stack[-1] if state.op_set.redo_stack else None
    if redo_ops is None:
        raise ValueError('Cannot redo: the last change was not an undo')

    change = {'actor': request['actor'], 'seq': request['seq'],
              'deps': dict(request.get('deps', {})), 'ops': redo_ops}
    if request.get('message') is not None:
        change['message'] = request['message']

    new_ops = state.op_set.clone()
    new_ops.undo_pos += 1
    new_ops.redo_stack = new_ops.redo_stack[:-1]
    diffs = OpSet.add_change(new_ops, change, False)
    state = BackendState(new_ops)
    return state, _make_patch(state, diffs)


# camelCase aliases (reference API parity)
applyChanges = apply_changes
applyLocalChange = apply_local_change
getPatch = get_patch
getChanges = get_changes
getChangesForActor = get_changes_for_actor
getMissingChanges = get_missing_changes
getMissingDeps = get_missing_deps
