"""Host-side CRDT engine ("OpSet") with exact reference semantics.

This module is the semantics oracle for the framework: change JSON in,
patch/diff JSON out, byte-compatible with the reference engine
(`/root/reference/backend/op_set.js`). The TPU device engine
(:mod:`automerge_tpu.device`) is differentially tested against this module
and takes over for batched workloads; this oracle owns the incremental
single-change path and all recursive/host-only logic (materialization,
string keys, nested object graphs).

Design notes (how this differs structurally from the reference):

* The reference stores everything in one Immutable.js map with persistent
  structural sharing.  Here an :class:`OpSet` is a snapshot object using
  *append-only sharing*: per-actor change logs and the history log are
  shared grow-only lists with a per-snapshot visible length, and object
  records are copy-on-write cloned at most once per apply session.  Old
  snapshots (old document versions) stay valid, which the public API
  relies on (``diff(old_doc, new_doc)``, ``getChanges``).
* Field-op lists are treated as immutable values: they are replaced, never
  mutated, so clones can share them.

Key semantic anchors (reference citations):

* concurrency test          -> op_set.js:7-16
* causal readiness          -> op_set.js:20-27
* transitive deps           -> op_set.js:29-37
* make/ins/assign handlers  -> op_set.js:63-219
* conflict ordering         -> op_set.js:211 (sort by actor, descending)
* queued fixed-point apply  -> op_set.js:267-283
* insertion-tree ordering   -> op_set.js:371-425 (Lamport-descending RGA)
"""

import re

from ..common import ROOT_ID
from ..native import make_seq_index, clone_index

_ELEMID_RE = re.compile(r'^(.*):(\d+)$')


def lamport_compare(op1, op2):
    """Order by (elem, actor); reference op_set.js:371-377."""
    if op1['elem'] < op2['elem']:
        return -1
    if op1['elem'] > op2['elem']:
        return 1
    if op1['actor'] < op2['actor']:
        return -1
    if op1['actor'] > op2['actor']:
        return 1
    return 0


class ObjectRecord:
    """Per-object CRDT state: field ops, insertion tree, sequence index.

    Mirrors the per-object entry in the reference's ``byObject`` map
    (op_set.js:63-93,180-219): ``fields`` maps key/elemId -> ops (winner
    first), ``following`` is the insertion tree adjacency, ``insertion``
    maps elemId -> its 'ins' op, ``elem_ids`` is the order-statistic index
    (visible elements in document order).
    """

    __slots__ = ('init_action', 'inbound', 'fields', 'following',
                 'insertion', 'max_elem', 'elem_ids')

    SEQUENCE_ACTIONS = ('makeList', 'makeText')

    def __init__(self, init_action=None):
        self.init_action = init_action          # 'makeMap'/'makeList'/'makeText'/None(root)
        self.inbound = []                       # list of link ops referencing this object
        self.fields = {}                        # key -> list of ops (winner first)
        self.following = {}                     # parent elemId/'_head' -> list of 'ins' ops
        self.insertion = {}                     # elemId -> 'ins' op
        self.max_elem = 0
        # Visible elemIds in document order. For sequences this is the
        # order-statistic index — natively a C++ skip list with O(1) COW
        # snapshots (native.py), matching the role of skip_list.js; plain
        # list fallback when the native library is unavailable.
        self.elem_ids = (make_seq_index()
                         if init_action in self.SEQUENCE_ACTIONS else [])

    def clone(self):
        rec = ObjectRecord.__new__(ObjectRecord)  # skip __init__: elem_ids
        rec.init_action = self.init_action        # comes from the snapshot
        rec.inbound = list(self.inbound)
        rec.fields = dict(self.fields)          # op lists are shared (immutable by convention)
        rec.following = dict(self.following)
        rec.insertion = dict(self.insertion)
        rec.max_elem = self.max_elem
        rec.elem_ids = clone_index(self.elem_ids)
        return rec

    def is_sequence(self):
        return self.init_action in self.SEQUENCE_ACTIONS


class SharedChangeLog:
    """Append-only shared change log with per-snapshot visible lengths.

    Mixed into both the host :class:`OpSet` and the device backend state
    (:class:`automerge_tpu.device.backend.DeviceBackendState`): the host
    class must define ``states``, ``state_lens``, ``history`` and
    ``history_len``. Old snapshots stay valid after a successor appends —
    a snapshot sees only its recorded visible length, and a divergent
    sibling branches a private copy of the log.
    """

    __slots__ = ()

    def actor_states(self, actor):
        return self.states.get(actor, []), self.state_lens.get(actor, 0)

    def actor_state(self, actor, index):
        lst, n = self.actor_states(actor)
        if index < 0 or index >= n:
            return None
        return lst[index]

    def _append_state(self, actor, entry):
        lst, n = self.actor_states(actor)
        if len(lst) != n:
            # A sibling snapshot extended this log differently; branch a copy.
            lst = lst[:n]
        if actor not in self.states or lst is not self.states[actor]:
            self.states[actor] = lst
        lst.append(entry)
        self.state_lens[actor] = n + 1

    def _append_history(self, change):
        if len(self.history) != self.history_len:
            self.history = self.history[:self.history_len]
        self.history.append(change)
        self.history_len += 1

    def get_history(self):
        return self.history[:self.history_len]


class OpSet(SharedChangeLog):
    """One snapshot of the CRDT engine state (reference op_set.js:298-310)."""

    __slots__ = ('states', 'state_lens', 'history', 'history_len',
                 'by_object', 'clock', 'deps', 'queue',
                 'undo_pos', 'undo_stack', 'redo_stack', 'undo_local',
                 '_owned')

    def __init__(self):
        self.states = {}            # actor -> grow-only list of {'change','all_deps'}
        self.state_lens = {}        # actor -> visible length in this snapshot
        self.history = []           # grow-only list of changes
        self.history_len = 0
        self.by_object = {ROOT_ID: ObjectRecord(None)}
        self.clock = {}             # actor -> seq
        self.deps = {}              # actor -> seq (current frontier heads)
        self.queue = []             # causally-unready buffered changes
        self.undo_pos = 0
        self.undo_stack = []        # list of op-lists
        self.redo_stack = []
        self.undo_local = None      # op accumulation during an undoable apply
        self._owned = {ROOT_ID}     # objectIds whose records are private to this snapshot

    # -- snapshot management ------------------------------------------------

    def clone(self):
        new = OpSet.__new__(OpSet)
        new.states = dict(self.states)
        new.state_lens = dict(self.state_lens)
        new.history = self.history
        new.history_len = self.history_len
        new.by_object = dict(self.by_object)
        new.clock = dict(self.clock)
        new.deps = dict(self.deps)
        new.queue = list(self.queue)
        new.undo_pos = self.undo_pos
        new.undo_stack = list(self.undo_stack)
        new.redo_stack = list(self.redo_stack)
        new.undo_local = None
        new._owned = set()
        return new

    def _writable(self, object_id):
        """Copy-on-write access to an object record (cloned once per snapshot)."""
        if object_id not in self._owned:
            self.by_object[object_id] = self.by_object[object_id].clone()
            self._owned.add(object_id)
        return self.by_object[object_id]


# -- causality helpers ------------------------------------------------------

def is_concurrent(op_set, op1, op2):
    """True if neither op happened-before the other (op_set.js:7-16)."""
    actor1, seq1 = op1.get('actor'), op1.get('seq')
    actor2, seq2 = op2.get('actor'), op2.get('seq')
    if not actor1 or not actor2 or not seq1 or not seq2:
        return False
    clock1 = op_set.actor_state(actor1, seq1 - 1)['all_deps']
    clock2 = op_set.actor_state(actor2, seq2 - 1)['all_deps']
    return clock1.get(actor2, 0) < seq2 and clock2.get(actor1, 0) < seq1


def causally_ready(op_set, change):
    """All causal predecessors already applied? (op_set.js:20-27)"""
    deps = dict(change['deps'])
    deps[change['actor']] = change['seq'] - 1
    return all(op_set.clock.get(actor, 0) >= seq for actor, seq in deps.items())


def transitive_deps(op_set, base_deps):
    """Transitive closure of a deps map (op_set.js:29-37)."""
    deps = {}
    for dep_actor, dep_seq in base_deps.items():
        if dep_seq <= 0:
            continue
        # An unknown actor contributes no transitive deps but keeps its own
        # entry (the reference merges an absent lookup as an empty clock).
        entry = op_set.actor_state(dep_actor, dep_seq - 1)
        transitive = entry['all_deps'] if entry else {}
        for actor, seq in transitive.items():
            deps[actor] = max(deps.get(actor, 0), seq)
        deps[dep_actor] = dep_seq
    return deps


# -- object-graph helpers ---------------------------------------------------

def get_path(op_set, object_id):
    """Path of keys/indexes from root to object, or None (op_set.js:43-60)."""
    path = []
    while object_id != ROOT_ID:
        rec = op_set.by_object.get(object_id)
        if rec is None or not rec.inbound:
            return None
        ref = rec.inbound[0]
        object_id = ref['obj']
        parent = op_set.by_object[object_id]
        if parent.is_sequence():
            try:
                index = parent.elem_ids.index(ref['key'])
            except ValueError:
                return None
            path.insert(0, index)
        else:
            path.insert(0, ref['key'])
    return path


def get_field_ops(op_set, object_id, key):
    rec = op_set.by_object.get(object_id)
    if rec is None:
        return []
    return rec.fields.get(key, [])


def get_parent(op_set, object_id, key):
    """Parent elemId in the insertion tree (op_set.js:364-369)."""
    if key == '_head':
        return None
    insertion = op_set.by_object[object_id].insertion.get(key)
    if insertion is None:
        raise TypeError('Missing index entry for list element ' + key)
    return insertion['key']


def insertions_after(op_set, object_id, parent_id, child_id=None):
    """Children of parent_id in Lamport-descending order (op_set.js:379-390).

    lamport_compare orders by (elem, actor), which is exactly Python tuple
    comparison, so a key-based sort suffices (no cmp_to_key in this hot path).
    """
    child_key = None
    if child_id:
        match = _ELEMID_RE.match(child_id)
        if match:
            child_key = (int(match.group(2)), match.group(1))

    ops = [op for op in op_set.by_object[object_id].following.get(parent_id, [])
           if op['action'] == 'ins']
    if child_key is not None:
        ops = [op for op in ops if (op['elem'], op['actor']) < child_key]
    ops.sort(key=lambda op: (op['elem'], op['actor']), reverse=True)
    return [f"{op['actor']}:{op['elem']}" for op in ops]


def get_next(op_set, object_id, key):
    """Successor in document order (op_set.js:392-404)."""
    children = insertions_after(op_set, object_id, key)
    if children:
        return children[0]
    while True:
        ancestor = get_parent(op_set, object_id, key)
        if not ancestor:
            return None
        siblings = insertions_after(op_set, object_id, ancestor, key)
        if siblings:
            return siblings[0]
        key = ancestor


def get_previous(op_set, object_id, key):
    """Predecessor in document order, or None at head (op_set.js:408-425)."""
    parent_id = get_parent(op_set, object_id, key)
    children = insertions_after(op_set, object_id, parent_id if parent_id else '_head')
    if children and children[0] == key:
        return None if (parent_id is None or parent_id == '_head') else parent_id

    prev_id = None
    for child in children:
        if child == key:
            break
        prev_id = child
    while True:
        children = insertions_after(op_set, object_id, prev_id)
        if not children:
            return prev_id
        prev_id = children[-1]


# -- op application ---------------------------------------------------------

def _apply_make(op_set, op):
    """'makeMap'/'makeList'/'makeText' (op_set.js:63-78)."""
    object_id = op['obj']
    if object_id in op_set.by_object:
        raise ValueError('Duplicate creation of object ' + object_id)

    edit = {'action': 'create', 'obj': object_id}
    if op['action'] == 'makeMap':
        edit['type'] = 'map'
    else:
        edit['type'] = 'text' if op['action'] == 'makeText' else 'list'

    op_set.by_object[object_id] = ObjectRecord(op['action'])
    op_set._owned.add(object_id)
    return [edit]


def _apply_insert(op_set, op):
    """'ins': register in the insertion tree; no visible diff (op_set.js:83-93)."""
    object_id, elem = op['obj'], op['elem']
    elem_id = f"{op['actor']}:{elem}"
    if object_id not in op_set.by_object:
        raise ValueError('Modification of unknown object ' + object_id)
    rec = op_set._writable(object_id)
    if elem_id in rec.insertion:
        raise ValueError('Duplicate list element ID ' + elem_id)

    rec.following[op['key']] = rec.following.get(op['key'], []) + [op]
    rec.max_elem = max(elem, rec.max_elem)
    rec.insertion[elem_id] = op
    return []


def _get_conflicts(ops):
    """Conflict entries for all non-winning ops (op_set.js:95-103)."""
    conflicts = []
    for op in ops[1:]:
        conflict = {'actor': op['actor'], 'value': op.get('value')}
        if op['action'] == 'link':
            conflict['link'] = True
        conflicts.append(conflict)
    return conflicts


def _patch_list(op_set, object_id, index, elem_id, action, ops):
    """Sequence-index maintenance + list diff emission (op_set.js:105-130)."""
    rec = op_set._writable(object_id)
    obj_type = 'text' if rec.init_action == 'makeText' else 'list'
    first_op = ops[0] if ops else None
    edit = {'action': action, 'type': obj_type, 'obj': object_id,
            'index': index, 'path': get_path(op_set, object_id)}
    if first_op and first_op['action'] == 'link':
        edit['link'] = True

    if action == 'insert':
        rec.elem_ids.insert(index, first_op['key'])
        edit['elemId'] = elem_id
        edit['value'] = first_op.get('value')
    elif action == 'set':
        edit['value'] = first_op.get('value')
    elif action == 'remove':
        del rec.elem_ids[index]
    else:
        raise ValueError('Unknown action type: ' + action)

    if ops and len(ops) > 1:
        edit['conflicts'] = _get_conflicts(ops)
    return [edit]


def _update_list_element(op_set, object_id, elem_id):
    """Re-derive the visible state of one list element (op_set.js:132-159)."""
    ops = get_field_ops(op_set, object_id, elem_id)
    elem_ids = op_set.by_object[object_id].elem_ids
    try:
        index = elem_ids.index(elem_id)
    except ValueError:
        index = -1

    if index >= 0:
        if not ops:
            return _patch_list(op_set, object_id, index, elem_id, 'remove', None)
        return _patch_list(op_set, object_id, index, elem_id, 'set', ops)

    if not ops:
        return []  # deleting a non-existent element = no-op

    # find the index of the closest preceding visible list element
    prev_id = elem_id
    while True:
        index = -1
        prev_id = get_previous(op_set, object_id, prev_id)
        if not prev_id:
            break
        try:
            index = elem_ids.index(prev_id)
        except ValueError:
            index = -1
        if index >= 0:
            break
    return _patch_list(op_set, object_id, index + 1, elem_id, 'insert', ops)


def _update_map_key(op_set, object_id, key):
    """Map-key diff after assignment resolution (op_set.js:161-177)."""
    ops = get_field_ops(op_set, object_id, key)
    edit = {'action': '', 'type': 'map', 'obj': object_id, 'key': key,
            'path': get_path(op_set, object_id)}
    if not ops:
        edit['action'] = 'remove'
    else:
        edit['action'] = 'set'
        edit['value'] = ops[0].get('value')
        if ops[0]['action'] == 'link':
            edit['link'] = True
        if len(ops) > 1:
            edit['conflicts'] = _get_conflicts(ops)
    return [edit]


def _apply_assign(op_set, op, top_level):
    """'set'/'del'/'link': concurrency partition + conflict resolution
    (op_set.js:180-219). Winners are ordered actor-descending (op_set.js:211).
    """
    object_id = op['obj']
    if object_id not in op_set.by_object:
        raise ValueError('Modification of unknown object ' + object_id)
    rec = op_set._writable(object_id)
    obj_type = rec.init_action

    if op_set.undo_local is not None and top_level:
        undo_ops = [{k: v for k, v in prior.items()
                     if k in ('action', 'obj', 'key', 'value')}
                    for prior in rec.fields.get(op['key'], [])]
        if not undo_ops:
            undo_ops = [{'action': 'del', 'obj': object_id, 'key': op['key']}]
        op_set.undo_local = op_set.undo_local + undo_ops

    prior = rec.fields.get(op['key'], [])
    overwritten = [other for other in prior if not is_concurrent(op_set, other, op)]
    remaining = [other for other in prior if is_concurrent(op_set, other, op)]

    # Overwritten links leave the inbound index of their target
    for old in overwritten:
        if old['action'] == 'link':
            target = op_set._writable(old['value'])
            target.inbound = [ref for ref in target.inbound if ref != old]

    if op['action'] == 'link':
        target = op_set._writable(op['value'])
        if op not in target.inbound:
            target.inbound = target.inbound + [op]
    if op['action'] != 'del':
        remaining = remaining + [op]
    remaining = sorted(remaining, key=lambda o: o['actor'], reverse=True)
    rec.fields[op['key']] = remaining

    if obj_type in ('makeList', 'makeText'):
        return _update_list_element(op_set, object_id, op['key'])
    return _update_map_key(op_set, object_id, op['key'])


def _apply_ops(op_set, ops):
    """Dispatch one change's ops (op_set.js:221-238)."""
    all_diffs, new_objects = [], set()
    for op in ops:
        action = op['action']
        if action in ('makeMap', 'makeList', 'makeText'):
            new_objects.add(op['obj'])
            diffs = _apply_make(op_set, op)
        elif action == 'ins':
            diffs = _apply_insert(op_set, op)
        elif action in ('set', 'del', 'link'):
            diffs = _apply_assign(op_set, op, op['obj'] not in new_objects)
        else:
            raise ValueError(f'Unknown operation type {action}')
        all_diffs.extend(diffs)
    return all_diffs


def _apply_change(op_set, change):
    """Apply one causally-ready change (op_set.js:240-265)."""
    actor, seq = change['actor'], change['seq']
    _, prior_len = op_set.actor_states(actor)
    if seq <= prior_len:
        if op_set.actor_state(actor, seq - 1)['change'] != change:
            raise ValueError(f'Inconsistent reuse of sequence number {seq} by {actor}')
        return []  # change already applied

    base_deps = dict(change['deps'])
    base_deps[actor] = seq - 1
    all_deps = transitive_deps(op_set, base_deps)
    op_set._append_state(actor, {'change': change, 'all_deps': all_deps})

    ops = [{**op, 'actor': actor, 'seq': seq} for op in change['ops']]
    diffs = _apply_ops(op_set, ops)

    remaining_deps = {dep_actor: dep_seq for dep_actor, dep_seq in op_set.deps.items()
                      if dep_seq > all_deps.get(dep_actor, 0)}
    remaining_deps[actor] = seq
    op_set.deps = remaining_deps
    op_set.clock[actor] = seq
    op_set._append_history(change)
    return diffs


def apply_queued_ops(op_set):
    """Fixed-point causal delivery over the buffer (op_set.js:267-283)."""
    diffs = []
    while True:
        queue = []
        for change in op_set.queue:
            if causally_ready(op_set, change):
                diffs.extend(_apply_change(op_set, change))
            else:
                queue.append(change)
        if len(queue) == len(op_set.queue):
            return diffs
        op_set.queue = queue


def _push_undo_history(op_set):
    """Record captured inverse ops on the undo stack (op_set.js:285-296)."""
    op_set.undo_stack = op_set.undo_stack[:op_set.undo_pos] + [op_set.undo_local]
    op_set.undo_pos += 1
    op_set.redo_stack = []
    op_set.undo_local = None


def init():
    return OpSet()


def add_change(op_set, change, is_undoable):
    """Queue + deliver one change; optionally capture undo ops
    (op_set.js:312-325). Mutates `op_set` (callers clone snapshots first).
    """
    op_set.queue = op_set.queue + [change]
    if is_undoable:
        op_set.undo_local = []
        diffs = apply_queued_ops(op_set)
        _push_undo_history(op_set)
        return diffs
    return apply_queued_ops(op_set)


# -- change-log queries -----------------------------------------------------

def get_missing_changes(op_set, have_deps):
    """Changes the peer with clock `have_deps` lacks (op_set.js:327-334)."""
    all_deps = transitive_deps(op_set, dict(have_deps))
    changes = []
    for actor in op_set.states:
        lst, n = op_set.actor_states(actor)
        for entry in lst[all_deps.get(actor, 0):n]:
            changes.append(entry['change'])
    return changes


def get_changes_for_actor(op_set, for_actor, after_seq=0):
    lst, n = op_set.actor_states(for_actor)
    return [entry['change'] for entry in lst[after_seq:n]]


def get_missing_deps(op_set):
    """Aggregate unmet dependencies of the queued changes (op_set.js:347-358)."""
    missing = {}
    for change in op_set.queue:
        deps = dict(change['deps'])
        deps[change['actor']] = change['seq'] - 1
        for dep_actor, dep_seq in deps.items():
            if op_set.clock.get(dep_actor, 0) < dep_seq:
                missing[dep_actor] = max(dep_seq, missing.get(dep_actor, 0))
    return missing


# -- document queries (used by materialization) -----------------------------

def _valid_field_name(key):
    return isinstance(key, str) and key != '' and not key.startswith('_')


def get_object_fields(op_set, object_id):
    rec = op_set.by_object[object_id]
    return [key for key, ops in rec.fields.items()
            if _valid_field_name(key) and ops]


def _get_op_value(op_set, op, context):
    if op['action'] == 'set':
        return op.get('value')
    if op['action'] == 'link':
        return context.instantiate_object(op_set, op['value'])
    return None


def get_object_field(op_set, object_id, key, context):
    if not _valid_field_name(key):
        return None
    ops = get_field_ops(op_set, object_id, key)
    if ops:
        return _get_op_value(op_set, ops[0], context)
    return None


def get_object_conflicts(op_set, object_id, context):
    """Per-key actor->value maps for multiply-assigned fields (op_set.js:456-462)."""
    rec = op_set.by_object[object_id]
    conflicts = {}
    for key, ops in rec.fields.items():
        if _valid_field_name(key) and len(ops) > 1:
            conflicts[key] = {op['actor']: _get_op_value(op_set, op, context)
                              for op in ops[1:]}
    return conflicts


def list_elem_by_index(op_set, object_id, index, context):
    rec = op_set.by_object[object_id]
    if 0 <= index < len(rec.elem_ids):
        ops = get_field_ops(op_set, object_id, rec.elem_ids[index])
        if ops:
            return _get_op_value(op_set, ops[0], context)
    return None


def list_length(op_set, object_id):
    return len(op_set.by_object[object_id].elem_ids)


def list_iterator(op_set, list_id, mode, context):
    """Walk visible elements in document order (op_set.js:476-507)."""
    elem, index = '_head', -1
    while True:
        elem = get_next(op_set, list_id, elem)
        if not elem:
            return
        ops = get_field_ops(op_set, list_id, elem)
        if not ops:
            continue
        value = _get_op_value(op_set, ops[0], context)
        index += 1
        if mode == 'keys':
            yield index
        elif mode == 'values':
            yield value
        elif mode == 'entries':
            yield (index, value)
        elif mode == 'elems':
            yield (index, elem)
        elif mode == 'conflicts':
            conflict = None
            if len(ops) > 1:
                conflict = {op['actor']: _get_op_value(op_set, op, context)
                            for op in ops[1:]}
            yield conflict
