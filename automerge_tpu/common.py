"""Shared constants and small utilities.

TPU-native reimplementation of the helpers in the reference's ``src/common.js``
(`/root/reference/src/common.js:1-22`): the all-zeros root object ID, the
object test, and the vector-clock partial order. Clocks here are plain
``dict[str, int]`` on the host; the device-side dense-array clock kernels live
in :mod:`automerge_tpu.device.clock`.
"""

ROOT_ID = '00000000-0000-0000-0000-000000000000'


def is_object(value):
    """True for container values (dict / list / CRDT objects), false for primitives."""
    from .text import Text
    from .frontend.datatypes import AmMap, AmList
    return isinstance(value, (dict, list, Text, AmMap, AmList))


def less_or_equal(clock1, clock2):
    """Vector-clock partial order: every component of clock1 <= clock2.

    Mirrors ``lessOrEqual`` (reference ``src/common.js:14-18``).
    """
    for key in set(clock1) | set(clock2):
        if clock1.get(key, 0) > clock2.get(key, 0):
            return False
    return True
