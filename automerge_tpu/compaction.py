"""Tiered doc storage: per-doc state snapshots + the history
compaction engine.

Every durability and bootstrap surface used to carry the FULL retained
change log: snapshots, park shards, journal replay and new-peer sync
all replayed history, which is why eviction on a snapshot-resumed
store was refused (``serving_evictions_blocked_truncated``) and why a
10k-doc first contact shipped entire histories. This module folds
history into compact per-doc **state snapshots** behind an explicit
**compaction horizon** (Okapi's cheap-causal-metadata framing,
PAPERS.md: replicas stay consistent shipping compact state, not
history; Jiffy's batch snapshots are the model for cutting a
consistent state without stopping ingest):

- A **state snapshot** is one document's complete CRDT state as
  columnar op planes — surviving entries, insertion-tree nodes with
  their current visibility, the object table, the causal-closure log
  rows (compact ``(actor, seq)`` metadata, no op bodies), interned
  tables and values — plus its clock and the PR 8 blake2b state
  digest, zlib-packed inside the checksummed
  :func:`~automerge_tpu.durability.pack_snapshot` container.
- :func:`compact_docset` advances the **horizon** to the current
  clock: per-doc state snapshots are extracted from the live store
  (no stop-the-world — ingest admitted after the cut lands in the
  tail), the retained log shrinks to the post-horizon tail, and the
  folded change bodies are released. ``get_missing_changes*`` then
  raises :class:`~automerge_tpu.device.blocks.HorizonTruncated` for
  peers whose clock predates the horizon, and the sync layer answers
  with a ``'state'`` message (snapshot + tail) — cold-peer bootstrap
  becomes O(state + divergence) instead of O(history).
- :func:`absorb_doc_states` is the restore path shared by every
  consumer: the wire ``'state'`` receive
  (:meth:`GeneralDocSet.apply_states <automerge_tpu.sync.
  general_doc_set.GeneralDocSet.apply_states>`), park-shard fault-in,
  tiered snapshot resume and journal recovery. A doc restored from
  ``state + tail`` is digest- and materialize-identical to one
  rebuilt from the full log (asserted by ``tests/test_compaction.py``
  against the host oracle, including under chaos).

Durable artifacts only change through the existing atomic
tmp+fsync+rename containers (PR 4/6): compaction itself is an
in-memory fold, and :func:`compact_and_checkpoint` makes it durable
through ``DurableDocSet.checkpoint`` — a crash anywhere in between
leaves the pre-compaction tiers (old snapshot + journal) intact.
"""

import json
import struct
import time
import zlib

import numpy as np

from .common import ROOT_ID
from .device import general as _general
from .device.blocks import HorizonTruncated, _span_indices  # noqa: F401
from .utils.metrics import metrics

STATE_FORMAT = 'automerge-tpu-doc-state@1'
_STATE_MAGIC = b'AMDST1\n'
_LEN = struct.Struct('>I')
_ELEM_BIT = np.int64(1) << 31
_SEQ_BITS = 20          # blocks._SEQ_BITS (change_key packing)
_ACTOR_BITS = 21

# the serialized column order — decode reconstructs by this manifest
_ARRAYS = (
    # surviving entries (doc-local object/actor/key/value/log refs)
    ('e_obj', '<i4'), ('e_key', '<i8'), ('e_actor', '<i4'),
    ('e_seq', '<i4'), ('e_value', '<i4'), ('e_link', 'u1'),
    ('e_change', '<i4'),
    # causal-closure log rows (append order; compact (actor, seq)
    # pairs + dep CSR — the metadata every future admission and
    # conflict resolution reads, with no op bodies)
    ('lg_actor', '<i4'), ('lg_seq', '<i4'), ('lg_dep_ptr', '<i4'),
    ('lg_dep_actor', '<i4'), ('lg_dep_seq', '<i4'),
    # insertion-tree nodes (per seq object, local order, with the
    # CURRENT visibility — the mirror encoders rebuild device planes
    # from exactly these columns on restore)
    ('nd_obj', '<i4'), ('nd_local', '<i4'), ('nd_parent', '<i4'),
    ('nd_actor', '<i4'), ('nd_elemc', '<i4'), ('nd_vis', 'u1'),
    ('nd_visidx', '<i4'))

# v2 column extension: the device-resident sequence index (tree_pos
# per node) rides the state snapshot, so a restore rebuilds the
# mirror WITH a valid 'tp' plane and skips the whole-object
# _rga_order rebuild on first touch. The header's 'idx' flag says
# whether the column is a live index (every seq object of the doc had
# idx_ok at extraction) or mere padding. Old payloads (len(lens) ==
# len(_ARRAYS)) decode exactly as before, with no index claim.
_ARRAYS_V2 = _ARRAYS + (('nd_tpos', '<i4'),)


def encode_state_snapshot(st):
    """Serialize one extracted doc state (the dict
    :func:`extract_doc_states` builds) into the checksummed container:
    JSON header + raw little-endian column planes, zlib-compressed,
    framed by :func:`~automerge_tpu.durability.pack_snapshot` (magic +
    length + CRC32 — truncation and bit rot surface as a clean
    :class:`~automerge_tpu.snapshot.SnapshotCorruptError`)."""
    from .durability import pack_snapshot
    if 'nd_tpos' not in st:
        st = dict(st)
        st['nd_tpos'] = np.zeros(len(st['nd_obj']), np.int32)
        st.setdefault('idx', False)
    header = {'format': STATE_FORMAT, 'clock': st['clock'],
              'digest': st['digest'], 'actors': st['actors'],
              'keys': st['keys'], 'values': st['values'],
              'objs': st['objs'], 'inbound': st['inbound'],
              'idx': bool(st.get('idx', False)),
              'lens': [int(len(st[name])) for name, _ in _ARRAYS_V2]}
    head = json.dumps(header, separators=(',', ':')).encode()
    body = b''.join([_LEN.pack(len(head)), head] +
                    [np.ascontiguousarray(
                        st[name].astype(dtype)).tobytes()
                     for name, dtype in _ARRAYS_V2])
    return pack_snapshot(_STATE_MAGIC + zlib.compress(body, 6))


def state_warm_literals(chunks, budget=64 * 1024):
    """Deterministic wire-v3 warm-up literal list from ``'state'``
    bootstrap payloads: the actor/key strings of each snapshot's JSON
    header, as tagged wire literals, in docs order with actors before
    keys, first occurrence winning, capped at ``budget`` literal
    bytes. BOTH ends of a bootstrap derive this list from the same
    payload bytes — the serving peer from the chunks it ships, the
    bootstrapping peer from the chunks it receives — so sequential
    refs assigned from 0 in list order agree by construction
    (:meth:`~automerge_tpu.wire.SessionStringTable.warm` /
    the receiver's enumerate seed). Header-only: a ``decompressobj``
    inflates just each container's JSON head, never the column
    planes. A payload that fails to parse contributes nothing (it
    will quarantine at absorb time; warm-up must never raise)."""
    from .durability import unpack_snapshot
    from .snapshot import SnapshotCorruptError
    from .wire import _TAG_STR
    lits, seen, cost = [], set(), 0
    for chunk in chunks:
        try:
            payload = unpack_snapshot(bytes(chunk))
            if payload[:len(_STATE_MAGIC)] != _STATE_MAGIC:
                continue
            d = zlib.decompressobj()
            head = d.decompress(payload[len(_STATE_MAGIC):], 4)
            (hlen,) = _LEN.unpack_from(head, 0)
            body = head[4:]
            while len(body) < hlen and d.unconsumed_tail:
                body += d.decompress(d.unconsumed_tail,
                                     hlen - len(body))
            header = json.loads(body[:hlen].decode())
        except (SnapshotCorruptError, zlib.error, struct.error,
                ValueError, UnicodeDecodeError):
            continue
        if not isinstance(header, dict) or \
                header.get('format') != STATE_FORMAT:
            continue
        for field in ('actors', 'keys'):
            strs = header.get(field)
            if not isinstance(strs, list):
                continue
            for s in strs:
                if not isinstance(s, str) or not s:
                    continue
                lit = bytes([_TAG_STR]) + s.encode('utf-8')
                if lit in seen:
                    continue
                if cost + len(lit) > budget:
                    return lits
                seen.add(lit)
                cost += len(lit)
                lits.append(lit)
    return lits


def decode_state_snapshot(data):
    """Validate + decode an :func:`encode_state_snapshot` payload back
    into the column dict. Raises
    :class:`~automerge_tpu.snapshot.SnapshotCorruptError` on
    truncation/bit rot/format mismatch."""
    from .durability import unpack_snapshot
    from .snapshot import SnapshotCorruptError
    payload = unpack_snapshot(bytes(data))
    if payload[:len(_STATE_MAGIC)] != _STATE_MAGIC:
        raise SnapshotCorruptError(
            'not a doc-state snapshot (bad inner magic)')
    try:
        body = zlib.decompress(payload[len(_STATE_MAGIC):])
        (hlen,) = _LEN.unpack_from(body, 0)
        header = json.loads(body[4:4 + hlen].decode())
    except (zlib.error, struct.error, ValueError,
            UnicodeDecodeError) as err:
        raise SnapshotCorruptError(
            f'doc-state snapshot body undecodable ({err})') from None
    if not isinstance(header, dict) or \
            header.get('format') != STATE_FORMAT:
        raise SnapshotCorruptError('not a doc-state snapshot')
    lens = header.get('lens')
    if not isinstance(lens, list) or \
            len(lens) not in (len(_ARRAYS), len(_ARRAYS_V2)):
        raise SnapshotCorruptError(
            "doc-state snapshot: missing field 'lens'")
    manifest = _ARRAYS_V2 if len(lens) == len(_ARRAYS_V2) else _ARRAYS
    out = {'clock': header.get('clock') or {},
           'digest': header.get('digest'),
           'actors': header.get('actors') or [],
           'keys': header.get('keys') or [],
           'values': header.get('values') or [],
           'objs': header.get('objs') or [],
           'inbound': header.get('inbound') or {},
           'idx': bool(header.get('idx', False))
           and len(lens) == len(_ARRAYS_V2)}
    pos = 4 + hlen
    for (name, dtype), n in zip(manifest, lens):
        try:
            arr = np.frombuffer(body, dtype=dtype, count=n,
                                offset=pos)
        except ValueError:
            raise SnapshotCorruptError(
                'doc-state snapshot truncated: column planes '
                'short') from None
        pos += arr.nbytes
        out[name] = arr
    if pos > len(body):
        raise SnapshotCorruptError(
            'doc-state snapshot truncated: column planes short')
    _validate_decoded(out)
    return out


def _validate_decoded(st):
    """Bounds-check every cross-reference of a decoded state payload
    BEFORE any store mutation — a CRC-valid but internally
    inconsistent payload (a buggy or hostile encoder) must fail here
    as a clean :class:`SnapshotCorruptError` that quarantines only
    its doc, never an IndexError mid-absorb that could tear the
    batch."""
    from .snapshot import SnapshotCorruptError

    def bad(what):
        raise SnapshotCorruptError(
            f'doc-state snapshot inconsistent: {what}')

    n_actors = len(st['actors'])
    n_keys = len(st['keys'])
    n_values = len(st['values'])
    n_objs = len(st['objs'])
    n_log = len(st['lg_seq'])

    def check(arr, lo, hi, what):
        if len(arr) and (int(arr.min()) < lo or
                         int(arr.max()) >= hi):
            bad(what)

    if len(st['lg_actor']) != n_log:
        bad('log column lengths disagree')
    check(st['lg_actor'], 0, max(n_actors, 1), 'log actor ref')
    ptr = st['lg_dep_ptr']
    if len(ptr) != n_log + 1:
        bad('log dep ptr length')
    if int(ptr[0]) != 0 or (np.diff(ptr) < 0).any() or \
            int(ptr[-1]) != len(st['lg_dep_actor']):
        bad('log dep CSR malformed')
    check(st['lg_dep_actor'], 0, max(n_actors, 1), 'log dep actor')
    n_ent = len(st['e_seq'])
    for name in ('e_obj', 'e_key', 'e_actor', 'e_value', 'e_link',
                 'e_change'):
        if len(st[name]) != n_ent:
            bad('entry column lengths disagree')
    check(st['e_obj'], 0, max(n_objs, 1), 'entry object ref')
    check(st['e_actor'], 0, max(n_actors, 1), 'entry actor ref')
    check(st['e_value'], -1, max(n_values, 1), 'entry value ref')
    check(st['e_change'], -1, max(n_log, 1), 'entry log ref')
    raw_key = np.asarray(st['e_key'], np.int64)
    map_keys = raw_key[(raw_key & _ELEM_BIT) == 0]
    check(map_keys, 0, max(n_keys, 1), 'entry key ref')
    n_nodes = len(st['nd_obj'])
    for name in ('nd_local', 'nd_parent', 'nd_actor', 'nd_elemc',
                 'nd_vis', 'nd_visidx'):
        if len(st[name]) != n_nodes:
            bad('node column lengths disagree')
    if 'nd_tpos' in st and len(st['nd_tpos']) != n_nodes:
        bad('node column lengths disagree')
    check(st['nd_obj'], 0, max(n_objs, 1), 'node object ref')
    check(st['nd_actor'], -1, max(n_actors, 1), 'node actor ref')
    check(st['nd_local'], 0, 1 << 22, 'node local index')
    if 'nd_tpos' in st:
        check(st['nd_tpos'], 0, 1 << 22, 'node tree position')
    for obj in st['objs']:
        if not (isinstance(obj, list) and len(obj) == 2 and
                isinstance(obj[0], str)):
            bad('object table entry')
    for li_s, edges in st['inbound'].items():
        try:
            li = int(li_s)
        except (TypeError, ValueError):
            bad('inbound key')
        if not 0 <= li < max(n_objs, 1):
            bad('inbound object ref')
        for edge in edges:
            if not (isinstance(edge, list) and len(edge) == 2 and
                    isinstance(edge[0], int) and
                    0 <= edge[0] < n_objs):
                bad('inbound parent ref')
    for actor, seq in st['clock'].items():
        if not isinstance(actor, str) or not isinstance(seq, int) \
                or isinstance(seq, bool) or seq < 0:
            bad('clock entry')


# -- extraction (live store -> per-doc state) ---------------------------------

def extract_doc_states(store, idxs):
    """Extract the complete current state of each doc index in
    ``idxs`` from a live :class:`~automerge_tpu.device.general.
    GeneralStore`, as ``{idx: {'clock', 'digest', 'state': bytes}}``
    (the horizon-record shape). One batched CSR pass over each state
    family, then O(doc state) slicing per doc — never O(fleet) per
    doc. Digests ride only when the store's digest history is valid.
    """
    store._commit_pending()
    store.pool.sync()
    store.pool.sync_index()      # the order index rides the state
    #                              snapshot (docs with idx_ok claims)
    store._fold_digests()
    pool = store.pool
    digests_ok = getattr(store, '_digest_valid', False)

    # batched group-by-doc CSRs over entries, objects and log rows
    e_order = np.argsort(store.e_doc, kind='stable')
    e_sorted = store.e_doc[e_order]
    obj_doc_arr, obj_type_arr = store.obj_arrays()
    o_order = np.argsort(obj_doc_arr, kind='stable') \
        if len(obj_doc_arr) else np.zeros(0, np.int64)
    o_sorted = obj_doc_arr[o_order] if len(obj_doc_arr) else \
        np.zeros(0, np.int32)
    l_doc = (store.l_key >> (_ACTOR_BITS + _SEQ_BITS)).astype(np.int64)
    l_order = np.argsort(l_doc, kind='stable')
    l_sorted = l_doc[l_order]

    out = {}
    for d in idxs:
        out[d] = _extract_one(store, pool, d, e_order, e_sorted,
                              o_order, o_sorted, l_order, l_sorted,
                              obj_type_arr, digests_ok)
    return out


def _extract_one(store, pool, d, e_order, e_sorted, o_order, o_sorted,
                 l_order, l_sorted, obj_type_arr, digests_ok):
    actors, actor_of = [], {}
    keys, key_of = [], {}

    def amap(ids):
        ids = np.asarray(ids, np.int64)
        out = np.empty(len(ids), np.int32)
        tab = store.actors
        for i, a in enumerate(ids.tolist()):
            if a < 0:
                out[i] = -1
                continue
            s = tab[a]
            j = actor_of.get(s)
            if j is None:
                j = actor_of[s] = len(actors)
                actors.append(s)
            out[i] = j
        return out

    # objects of the doc, ascending global row order -> local index
    lo, hi = np.searchsorted(o_sorted, [d, d + 1])
    obj_rows = np.sort(o_order[lo:hi]).astype(np.int64)
    objs = [[store.obj_uuid[r], int(obj_type_arr[r])]
            for r in obj_rows.tolist()]
    inbound = {}
    for li, r in enumerate(obj_rows.tolist()):
        edges = store.obj_inbound.get(r)
        if edges:
            pos = np.searchsorted(obj_rows, [p for p, _ in edges])
            inbound[str(li)] = [[int(p), k]
                                for p, (_, k) in zip(pos.tolist(),
                                                     edges)]

    # insertion-tree nodes of the doc's sequence objects
    seq_objs = obj_rows[np.isin(obj_type_arr[obj_rows],
                                (_general._TYPE_LIST,
                                 _general._TYPE_TEXT))] \
        if len(obj_rows) else obj_rows
    if len(seq_objs):
        rows, counts = pool.rows_of_objs(seq_objs)
        nd_obj = np.repeat(
            np.searchsorted(obj_rows, seq_objs).astype(np.int32),
            counts)
        nd_local = pool.local[rows]
        nd_parent = pool.parent[rows]
        nd_actor = amap(pool.actor[rows])
        nd_elemc = pool.elemc[rows]
        nd_vis = pool.visible[rows].astype(np.uint8)
        nd_visidx = pool.vis_index[rows]
        nd_tpos = pool.tpos[rows]
        # a live index claim only when EVERY seq object of the doc is
        # index-valid (absorb sets idx_ok per object anyway; the
        # all-or-nothing flag keeps the header one bit)
        idx_ok = bool(pool.idx_ok[seq_objs].all()) \
            if len(pool.idx_ok) > int(seq_objs.max()) else False
    else:
        z = np.zeros(0, np.int32)
        nd_obj = nd_local = nd_parent = nd_actor = nd_elemc = \
            nd_visidx = nd_tpos = z
        nd_vis = np.zeros(0, np.uint8)
        idx_ok = True            # vacuously: nothing to rebuild

    # causal-closure log rows (append order within the doc)
    llo, lhi = np.searchsorted(l_sorted, [d, d + 1])
    log_rows = np.sort(l_order[llo:lhi]).astype(np.int64)
    lkeys = store.l_key[log_rows]
    lg_actor = amap((lkeys >> _SEQ_BITS) & ((1 << _ACTOR_BITS) - 1))
    lg_seq = (lkeys & ((1 << _SEQ_BITS) - 1)).astype(np.int32)
    dep_counts = (store.l_dep_ptr[log_rows + 1] -
                  store.l_dep_ptr[log_rows]).astype(np.int64)
    lg_dep_ptr = np.zeros(len(log_rows) + 1, np.int32)
    if len(log_rows):
        np.cumsum(dep_counts, out=lg_dep_ptr[1:])
    dep_idx = _span_indices(store.l_dep_ptr[log_rows].astype(np.int64),
                            dep_counts)
    lg_dep_actor = amap(store.l_dep_actor[dep_idx])
    lg_dep_seq = store.l_dep_seq[dep_idx]
    log_local = {int(r): i for i, r in enumerate(log_rows.tolist())}

    # surviving entries
    elo, ehi = np.searchsorted(e_sorted, [d, d + 1])
    ent = e_order[elo:ehi]
    raw_key = store.e_key[ent].astype(np.int64)
    is_elem = (raw_key & _ELEM_BIT) != 0
    e_key = raw_key.copy()
    for i in np.flatnonzero(~is_elem).tolist():
        s = store.keys[int(raw_key[i])]
        j = key_of.get(s)
        if j is None:
            j = key_of[s] = len(keys)
            keys.append(s)
        e_key[i] = j
    e_obj = np.searchsorted(obj_rows,
                            store.e_obj[ent]).astype(np.int32)
    e_actor = amap(store.e_actor[ent])
    e_seq = store.e_seq[ent]
    raw_val = store.e_value[ent]
    values = []
    vmap = {}
    e_value = np.empty(len(ent), np.int32)
    for i, v in enumerate(raw_val.tolist()):
        if v < 0:
            e_value[i] = -1
            continue
        j = vmap.get(v)
        if j is None:
            j = vmap[v] = len(values)
            values.append(store.values[v])
        e_value[i] = j
    e_link = store.e_link[ent].astype(np.uint8)
    e_change = np.asarray(
        [log_local.get(int(c), -1)
         for c in store.e_change[ent].tolist()], np.int32)

    st = {'clock': store.clock_of(d),
          'digest': store.digest_of(d) if digests_ok else None,
          'actors': actors, 'keys': keys, 'values': values,
          'objs': objs, 'inbound': inbound,
          'e_obj': e_obj, 'e_key': e_key, 'e_actor': e_actor,
          'e_seq': np.asarray(e_seq, np.int32), 'e_value': e_value,
          'e_link': e_link, 'e_change': e_change,
          'lg_actor': lg_actor, 'lg_seq': lg_seq,
          'lg_dep_ptr': lg_dep_ptr, 'lg_dep_actor': lg_dep_actor,
          'lg_dep_seq': np.asarray(lg_dep_seq, np.int32),
          'nd_obj': nd_obj, 'nd_local': nd_local,
          'nd_parent': nd_parent, 'nd_actor': nd_actor,
          'nd_elemc': nd_elemc, 'nd_vis': nd_vis,
          'nd_visidx': nd_visidx, 'nd_tpos': nd_tpos,
          'idx': idx_ok}
    return {'clock': st['clock'], 'digest': st['digest'],
            'state': encode_state_snapshot(st)}


# -- absorption (state -> live store) -----------------------------------------

def absorb_doc_states(store, items):
    """Restore per-doc state snapshots into a live store: ``items`` is
    ``[(idx, payload_bytes, decoded)]`` (``decoded`` optional — pass
    None to decode here). Every target doc index must be EMPTY in the
    store (no admitted changes) — callers replace a non-empty doc by
    dropping its state first. All docs' columns append in ONE bulk
    pass per state family (a 10k-doc state bootstrap is one concat,
    not 10k), the clock merges once, and the device mirror rebuilds
    once at the end. Each absorbed doc's horizon record is installed
    (clock + digest + the payload itself), so a bootstrapped replica
    can itself serve further cold peers from the same snapshot."""
    if not items:
        return
    items = [(idx, payload,
              decoded if decoded is not None
              else decode_state_snapshot(payload))
             for idx, payload, decoded in items]
    store._commit_pending()
    store.pool.sync()
    store.pool.sync_index()      # existing docs' order index must be
    #                              host-current BEFORE the mirror
    #                              rebuilds from the host columns
    store._fold_digests()
    pool = store.pool

    for idx, _, _ in items:
        if store.clock_of(idx):
            raise ValueError(
                f'absorb target doc {idx} is not empty; drop its '
                f'state first (apply_states handles the replace '
                f'path)')

    ent_chunks = {n: [] for n, _ in _ARRAYS}
    ent_doc = []
    pool_obj, pool_local, pool_parent, pool_actor = [], [], [], []
    pool_elemc, pool_vis, pool_visidx = [], [], []
    pool_tpos, idx_claims = [], []
    l_keys, l_dep_counts, l_dep_actor, l_dep_seq = [], [], [], []
    ck_doc, ck_actor, ck_seq = [], [], []
    l_base = len(store.l_key)
    v_base = len(store.values)
    any_digest_missing = False

    for idx, payload, st in items:
        a_map = store.intern(st['actors'], store.actors,
                             store.actor_of).astype(np.int64)
        k_map = store.intern(st['keys'], store.keys,
                             store.key_of).astype(np.int64)
        # object rows (appended in local order -> ascending global)
        obj_map = np.empty(len(st['objs']), np.int64)
        for li, (uuid, otype) in enumerate(st['objs']):
            row = len(store.obj_uuid)
            store.obj_of[(idx, uuid)] = row
            store.obj_uuid.append(uuid)
            store.obj_doc.append(idx)
            store.obj_type.append(int(otype))
            if uuid == ROOT_ID:
                store._root_row[idx] = row
            obj_map[li] = row
        for li_s, edges in st['inbound'].items():
            store.obj_inbound[int(obj_map[int(li_s)])] = \
                [(int(obj_map[p]), k) for p, k in edges]
        # nodes (per-object local order preserved; parents are local)
        if len(st['nd_obj']):
            pool_obj.append(obj_map[st['nd_obj']].astype(np.int32))
            pool_local.append(np.asarray(st['nd_local'], np.int32))
            pool_parent.append(np.asarray(st['nd_parent'], np.int32))
            na = np.asarray(st['nd_actor'], np.int64)
            pool_actor.append(np.where(
                na >= 0, a_map[np.maximum(na, 0)], -1)
                .astype(np.int32))
            pool_elemc.append(np.asarray(st['nd_elemc'], np.int32))
            pool_vis.append(np.asarray(st['nd_vis'], np.uint8)
                            .astype(bool))
            pool_visidx.append(np.asarray(st['nd_visidx'], np.int32))
            if 'nd_tpos' in st:
                pool_tpos.append(np.asarray(st['nd_tpos'], np.int32))
            else:
                pool_tpos.append(np.zeros(len(st['nd_obj']),
                                          np.int32))
            if st.get('idx'):
                # the snapshot shipped a live order index for this
                # doc's seq objects: claim it after grow_objects
                idx_claims.append(
                    obj_map[np.unique(st['nd_obj'])].astype(np.int64))
        # log rows
        n_log = len(st['lg_seq'])
        if n_log:
            doc_col = np.full(n_log, idx, np.int64)
            l_keys.append(store.change_key(
                doc_col, a_map[np.asarray(st['lg_actor'], np.int64)],
                np.asarray(st['lg_seq'], np.int64)))
            l_dep_counts.append(np.diff(
                np.asarray(st['lg_dep_ptr'], np.int64)))
            la = np.asarray(st['lg_dep_actor'], np.int64)
            l_dep_actor.append(a_map[la].astype(np.int32)
                               if len(la) else np.zeros(0, np.int32))
            l_dep_seq.append(np.asarray(st['lg_dep_seq'], np.int32))
        else:
            l_keys.append(np.zeros(0, np.int64))
            l_dep_counts.append(np.zeros(0, np.int64))
            l_dep_actor.append(np.zeros(0, np.int32))
            l_dep_seq.append(np.zeros(0, np.int32))
        # entries
        n_ent = len(st['e_seq'])
        if n_ent:
            ent_doc.append(np.full(n_ent, idx, np.int32))
            ent_chunks['e_obj'].append(
                obj_map[np.asarray(st['e_obj'], np.int64)]
                .astype(np.int32))
            raw_key = np.asarray(st['e_key'], np.int64)
            is_elem = (raw_key & _ELEM_BIT) != 0
            ent_chunks['e_key'].append(np.where(
                is_elem, raw_key,
                k_map[np.maximum(np.where(is_elem, 0, raw_key), 0)]))
            ent_chunks['e_actor'].append(
                a_map[np.asarray(st['e_actor'], np.int64)]
                .astype(np.int32))
            ent_chunks['e_seq'].append(
                np.asarray(st['e_seq'], np.int32))
            raw_val = np.asarray(st['e_value'], np.int64)
            ent_chunks['e_value'].append(np.where(
                raw_val >= 0, raw_val + v_base, -1).astype(np.int32))
            ent_chunks['e_link'].append(
                np.asarray(st['e_link'], np.uint8).astype(bool))
            raw_ch = np.asarray(st['e_change'], np.int64)
            ent_chunks['e_change'].append(np.where(
                raw_ch >= 0, raw_ch + (l_base - len(st['lg_seq'])
                                       + sum(len(k) for k in l_keys)),
                -1).astype(np.int32))
        store.values.extend(list(st['values']))
        v_base = len(store.values)
        # clock rows
        for a, s in st['clock'].items():
            ck_doc.append(idx)
            ck_actor.append(store.intern([a], store.actors,
                                         store.actor_of)[0])
            ck_seq.append(s)
        if st['digest'] is None:
            any_digest_missing = True

    # -- bulk appends ---------------------------------------------------------
    if ent_doc:
        store.e_doc = np.concatenate([store.e_doc] + ent_doc)
        for name in ('e_obj', 'e_key', 'e_actor', 'e_seq', 'e_value',
                     'e_link', 'e_change'):
            setattr(store, name, np.concatenate(
                [getattr(store, name)] + ent_chunks[name]))
    if pool_obj:
        base = len(pool.obj)
        obj_cat = np.concatenate(pool_obj)
        local_cat = np.concatenate(pool_local)
        pool.obj = np.concatenate([pool.obj, obj_cat])
        pool.local = np.concatenate([pool.local, local_cat])
        pool.parent = np.concatenate(
            [pool.parent] + pool_parent)
        pool.actor = np.concatenate([pool.actor] + pool_actor)
        elemc_cat = np.concatenate(pool_elemc)
        pool.elemc = np.concatenate([pool.elemc, elemc_cat])
        pool.visible = np.concatenate([pool.visible] + pool_vis)
        pool.vis_index = np.concatenate(
            [pool.vis_index] + pool_visidx)
        pool.tpos = np.concatenate([pool.tpos] + pool_tpos)
        # new object rows are strictly above every existing one, so
        # the position keys append at the tail of the sorted index
        keys = (obj_cat.astype(np.int64) << 32) | local_cat
        pool.pos_sorted = np.concatenate([pool.pos_sorted, keys])
        pool.pos_row = np.concatenate(
            [pool.pos_row,
             base + np.arange(len(keys), dtype=np.int64)])
        pool.grow_objects(int(obj_cat.max()) + 1)
        starts = np.flatnonzero(np.concatenate(
            [[True], obj_cat[1:] != obj_cat[:-1]]))
        ends = np.append(starts[1:], len(obj_cat)) - 1
        uo = obj_cat[starts].astype(np.int64)
        pool.n_of[uo] = local_cat[ends].astype(np.int64) + 1
        seg_max = np.maximum.reduceat(elemc_cat, starts)
        pool.max_elem_of[uo] = np.maximum(pool.max_elem_of[uo],
                                          seg_max)
        pool.max_tree = max(pool.max_tree,
                            int(local_cat[ends].max()) + 1)
        pool.max_elem = max(pool.max_elem, int(seg_max.max()))
        # chain-shape bit for the absorbed objects: grow_objects pads
        # it False, but a restored chain doc must stay window-eligible
        par_cat = pool.parent[base:]
        ok_chain = (local_cat == 0) | (par_cat == local_cat - 1)
        pool.idx_linear[uo] = np.logical_and.reduceat(ok_chain, starts)
    # per-object counters must cover node-less objects (maps) too —
    # rows_of_objs and friends index n_of by object row
    pool.grow_objects(len(store.obj_uuid))
    for rows_c in idx_claims:
        pool.idx_ok[rows_c] = True
    new_l = np.concatenate(l_keys)
    if len(new_l):
        dep_counts = np.concatenate(l_dep_counts)
        ptr_new = np.cumsum(dep_counts).astype(np.int32)
        store.l_key = np.concatenate([store.l_key, new_l])
        store.l_dep_ptr = np.concatenate(
            [store.l_dep_ptr, store.l_dep_ptr[-1] + ptr_new])
        store.l_dep_actor = np.concatenate(
            [store.l_dep_actor] + l_dep_actor)
        store.l_dep_seq = np.concatenate(
            [store.l_dep_seq] + l_dep_seq)
        store._l_pending.append((new_l, l_base))
    if ck_doc:
        store.clock_merge(np.asarray(ck_doc, np.int64),
                          np.asarray(ck_actor, np.int64),
                          np.asarray(ck_seq, np.int32))
    # digests: copy-on-write like _fold_digests, so concurrent readers
    # never see a half-written array
    dig = store._digest.copy()
    for idx, payload, st in items:
        if st['digest'] is not None:
            dig[idx] = np.uint64(st['digest'])
        store.horizon[idx] = {'clock': dict(st['clock']),
                              'digest': st['digest'],
                              'state': bytes(payload)}
    store._digest = dig
    if any_digest_missing:
        store._digest_valid = False
    store._bump_doc_versions(
        np.unique(np.asarray([i for i, _, _ in items], np.int64)))
    store._obj_arr_cache = (0, None, None)
    store._wire_obj_cache = None
    # the device mirror is host-stale after a bulk pool append outside
    # the fused apply path: rebuild it from the (current) host columns
    # exactly like a snapshot resume
    store._materialize_mirror()
    metrics.set_gauge('mem_state_snapshot_bytes',
                      store.state_snapshot_bytes())


# -- the compaction engine ----------------------------------------------------

def _unwrap_general(doc_set):
    """(general_doc_set, serving_or_None) from any wrapper stack."""
    serving = doc_set if hasattr(doc_set, '_evicted') else None
    inner = getattr(doc_set, 'inner', None)
    if inner is None:
        inner = getattr(doc_set, 'doc_set', doc_set)
        inner = getattr(inner, 'doc_set', inner)
    return inner, serving


def compact_docset(doc_set, doc_ids=None):
    """Advance the compaction horizon of a general doc set (or its
    Durable/Serving wrapper) to the CURRENT clock: extract per-doc
    state snapshots from the live store, install horizon records,
    shrink the retained log to the post-horizon tail (empty for the
    docs just folded; untouched history for docs left out) and release
    the folded change bodies and their encode-cache entries. Evicted
    docs of a serving stack are skipped (their park shard already IS
    their state tier). A snapshot-resumed (``log_truncated``) store
    comes out fully servable: peers behind the horizon get state,
    everyone else gets the tail — and eviction is unblocked. Returns
    ``{'docs', 'ops_folded', 'ms'}``."""
    inner, serving = _unwrap_general(doc_set)
    store = inner.store
    t0 = time.perf_counter()
    store._commit_pending()
    store.pool.sync()
    store._fold_digests()
    clocks = store.clocks_all()
    skip = set()
    if serving is not None:
        skip = {inner.id_of[d] for d in serving._evicted
                if d in inner.id_of}
    if doc_ids is None:
        idxs = [i for i in sorted(clocks) if i not in skip]
    else:
        idxs = [inner.id_of[d] for d in doc_ids
                if d in inner.id_of and
                clocks.get(inner.id_of[d]) and
                inner.id_of[d] not in skip]
    recs = extract_doc_states(store, idxs)
    folded = set(idxs)
    ops_folded = 0
    keep = {}
    for block, rows, docs in store.retained:
        opc = np.diff(block.op_ptr)
        for c, d in zip(rows.tolist(), docs.tolist()):
            if d in folded:
                ops_folded += int(opc[c])
            else:
                keep.setdefault(d, []).append(block.change_dict(c))
    store.horizon.update(recs)
    store.retained = _encode_retained(store, keep)
    store._body_index_cache = (0, None)
    # release folded docs' encode-cache entries with their bodies
    for cache in (store._wire_cache, store._wire_cache_v2):
        for k in [k for k in cache if k[0] in folded]:
            del cache[k]
    from .device.blocks import _wire_entry_bytes
    store._wire_cache_bytes = \
        sum(len(v) for v in store._wire_cache.values()) + \
        sum(_wire_entry_bytes(v)
            for v in store._wire_cache_v2.values())
    metrics.set_gauge('sync_wire_cache_bytes', store._wire_cache_bytes)
    # the blunt snapshot-resume refusal lifts only once EVERY doc with
    # history has a horizon record — a partial (doc_ids=...) fold of a
    # truncated store must keep raising the loud retention error for
    # the docs it did not cover, never silently serve them an
    # empty/incomplete history
    if store.log_truncated and \
            all(d in store.horizon for d in clocks):
        store.log_truncated = False
    dt_ms = (time.perf_counter() - t0) * 1e3
    metrics.bump('compaction_runs')
    metrics.bump('compaction_ops_folded', ops_folded)
    metrics.observe('compaction_ms', dt_ms)
    metrics.set_gauge('mem_state_snapshot_bytes',
                      store.state_snapshot_bytes())
    if metrics.active:
        metrics.emit('compaction', docs=len(idxs),
                     ops_folded=ops_folded)
    return {'docs': len(idxs), 'ops_folded': ops_folded, 'ms': dt_ms}


def _encode_retained(store, keep):
    """Re-encode surviving per-doc change-dict lists into ONE fresh
    retained block (admission order per doc, doc-major rows) — the
    shared tail-rebuild of compaction and tiered-snapshot load. The
    old blocks (and the folded bodies they pin) are released."""
    if not keep:
        return []
    per_doc = [keep.get(i, []) for i in range(max(keep) + 1)]
    block = store.encode_changes(per_doc, n_docs=store.n_docs)
    rows = np.arange(block.n_changes, dtype=np.int64)
    return [(block, rows, block.doc.astype(np.int64))]


def compact_and_checkpoint(serving_or_durable, doc_ids=None):
    """Compact, then make the new tiers durable through the existing
    atomic checkpoint (tmp + fsync + rename, PR 4): a crash BEFORE the
    rename leaves the old snapshot + journal — the pre-compaction
    tiers — fully intact, and recovery replays them as if the
    compaction never happened."""
    out = compact_docset(serving_or_durable, doc_ids=doc_ids)
    serving_or_durable.checkpoint()
    return out
