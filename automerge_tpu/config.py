"""Framework configuration: one small Options dataclass.

The reference keeps configuration minimal — `init(options)` takes
`actorId`/`deferActorId`/`backend` (frontend/index.js:197-221) and that is
the whole flag surface. This framework mirrors that restraint: everything
device-related (mesh shape, batch padding, dtype widths, actor-table
capacity, kernel choice) lives in ONE dataclass threaded through the
engines, instead of scattered kwargs.

Padding fields exist because XLA compiles per shape: a fixed `op_pad` /
`actor_pad` pins the jit cache to one bucket across batches; `None` means
"next power of two of what the batch needs" (shared cache across batches
of similar size, no recompilation storm — SURVEY §7 "padding + bucketing").
"""

from dataclasses import dataclass, replace
from typing import Optional

import numpy as np


@dataclass(frozen=True)
class Options:
    """Device/engine configuration.

    Attributes:
      kernel: field-resolution kernel — 'auto' (pallas on TPU when the
        working set fits VMEM, xla otherwise), 'xla', or 'pallas'.
      n_devices: mesh size for sharded engines (None = every device).
      op_pad: fixed op-axis padding per document batch (None = next pow2).
      seg_pad: fixed segment (field) capacity (None = next pow2).
      node_pad: fixed sequence-tree node capacity for the RGA ordering
        pass (None = next pow2 of the largest dirty tree).
      actor_pad: actor-table capacity — clocks are dense [actor_pad]
        vectors on device (None = next pow2 of the batch's actor count).
      clock_dtype / index_dtype: device array widths for clocks/seq
        counters and segment/actor/node indexes. int32 everywhere by
        default: TPU VPU lanes are 32-bit and none of the CRDT counters
        (seq numbers, list indexes) approach 2^31. Widening to int64
        additionally requires jax's x64 mode.
    """

    kernel: str = 'auto'
    # bulk-ingestion routing: DeviceBackend.apply_changes on a FRESH
    # document routes batches of at least this many ops through the
    # general bulk engine (one fused block apply) instead of the
    # per-change staging loop. Threshold from the measured crossover on
    # the config-2 interactive benchmark (~20k-op merges: per-doc
    # ~0.29s, bulk ~0.15s; sub-1k batches favor per-doc staging).
    # None disables routing.
    bulk_route_min_ops: Optional[int] = 3000
    n_devices: Optional[int] = None
    op_pad: Optional[int] = None
    seg_pad: Optional[int] = None
    actor_pad: Optional[int] = None
    node_pad: Optional[int] = None
    clock_dtype: np.dtype = np.dtype(np.int32)
    index_dtype: np.dtype = np.dtype(np.int32)

    def __post_init__(self):
        if self.kernel not in ('auto', 'xla', 'pallas'):
            raise ValueError(f'unknown kernel {self.kernel!r}')
        for name in ('n_devices', 'op_pad', 'seg_pad', 'actor_pad',
                     'node_pad'):
            v = getattr(self, name)
            if v is not None and v < 1:
                raise ValueError(f'{name} must be >= 1, got {v}')
        # bit-packed flag planes split at n >> 3 bytes and reshape(-1, 8):
        # a pad that is not a multiple of 8 would fail opaquely inside the
        # jitted program, so reject it at construction
        for name in ('op_pad', 'node_pad'):
            v = getattr(self, name)
            if v is not None and v % 8:
                raise ValueError(
                    f'{name} must be a multiple of 8 (bit-packed flag '
                    f'planes), got {v}')

    def pad_ops(self, n):
        """Op-axis size for a batch needing `n` rows."""
        return self._pad(self.op_pad, n, 'op_pad')

    def pad_segments(self, n):
        return self._pad(self.seg_pad, n, 'seg_pad')

    def pad_actors(self, n):
        return self._pad(self.actor_pad, n, 'actor_pad')

    def pad_nodes(self, n):
        return self._pad(self.node_pad, n, 'node_pad')

    @staticmethod
    def _pad(fixed, n, name):
        """Bucket `n` up to the next {2^k, 3*2^(k-1)} size (half-step
        buckets: at most 2 compiled shapes per octave, and never more
        than 33% padding waste — a plain pow2 wastes up to 100%, which
        is real wire bytes on a slow host<->device link)."""
        if fixed is not None:
            if n > fixed:
                raise ValueError(
                    f'batch needs {n} but {name} is fixed at {fixed}')
            return fixed
        n = max(n, 1)
        p = 1
        while p < n:
            p <<= 1
        half = (p >> 1) + (p >> 2)       # 3 * 2^(k-2), multiple of 8 for p>=32
        if n <= half and half % 8 == 0:
            return half
        return p

    def make_mesh(self):
        """Document-axis mesh of `n_devices` (parallel.mesh.make_mesh)."""
        from .parallel.mesh import make_mesh
        return make_mesh(n_devices=self.n_devices)

    def make_peer_mesh(self):
        """Peer-axis mesh for ICI replica sync (parallel.ici_sync)."""
        from .parallel.ici_sync import make_peer_mesh
        return make_peer_mesh(n_peers=self.n_devices)

    def with_(self, **kw):
        """Functional update (the dataclass is frozen)."""
        return replace(self, **kw)


DEFAULT = Options()
