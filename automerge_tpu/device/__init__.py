"""TPU device engine: batched CRDT resolution kernels.

This package is the TPU-native replacement for the reference's hot core —
the per-op JavaScript loops of `backend/op_set.js` and the pointer-chasing
`backend/skip_list.js`. State is struct-of-arrays in device memory:
interned integer actor/object/key IDs, ops as fixed-width int32 columns,
clocks as dense ``[n_actors]`` vectors, sequences as tombstoned arrays with
scan-built index maps.

Kernels (all pure, jittable, static-shaped; designed for the MXU/VPU and
XLA fusion rather than per-element control flow):

* :mod:`.clock`    — dense vector-clock ops (readiness, union, compare)
* :mod:`.merge`    — batched map-field conflict resolution
  (segment-reductions replace the reference's `applyAssign` loop,
  op_set.js:180-219)
* :mod:`.sequence` — RGA insertion-tree ordering via sort + pointer
  doubling (replaces `insertionsAfter`/`getNext` tree walks,
  op_set.js:379-425, and the SkipList order-statistic index)
* :mod:`.pallas_merge` — hand-scheduled Pallas/Mosaic variant of the merge
  kernel (one-hot MXU clock gather + VPU masked maxes, VMEM-resident)
* :mod:`.packing`  — host-side interning and struct-of-arrays packing
* :mod:`.engine`   — the batched document-store engine driving the kernels
* :mod:`.backend`  — the batched device backend speaking the change/patch
  protocol (wire changes in, reference-format patches out)
* :mod:`.blocks`   — columnar ChangeBlock/PatchBlock wire encoding + the
  vectorized host-orchestrated bulk apply (unbounded capacities)
* :mod:`.dense_store` — device-resident dense DocSet store: applyChanges
  as scatter-max into HBM-resident planes (the collab-server engine)
* :mod:`.text_block` — bulk text replay: columnar editing traces (no
  string interning — elemIds are structured pairs) resolved with
  vectorized staging + one RGA call (the long-context engine)

Batching model: one program, N documents — ``vmap`` over the leading doc
axis; sharding over a device mesh is layered on top in
:mod:`automerge_tpu.parallel`.
"""

from .engine import DocStore, batch_merge_docs, pick_resolve_kernel
from .blocks import ChangeBlock, PatchBlock, BlockStore, apply_block
from .dense_store import DenseMapStore, DensePatch
from .text_block import TextBlock, replay_text_block

__all__ = ['DocStore', 'batch_merge_docs', 'pick_resolve_kernel',
           'ChangeBlock', 'PatchBlock', 'BlockStore', 'apply_block',
           'DenseMapStore', 'DensePatch', 'TextBlock',
           'replay_text_block']
