"""Batched device backend: wire changes in, patches out, TPU in between.

This module puts the device engine behind the frontend<->backend
change/patch protocol (the reference's `backend/index.js:161-163` surface):
``apply_changes_batch`` takes per-document wire changes and returns
per-document **patches** — diffs with obj/key/value/conflicts exactly as
the reference's diff emission produces them (`backend/op_set.js:105-177`)
— while the heavy resolution work for every document in the batch runs in
ONE fused jitted device call: a segment-reduction pass resolving every
touched field (:mod:`.merge`), element visibility derived on device from
the survivors, and an RGA ordering pass recomputing document order for
every dirty list/text object (:mod:`.sequence`) — no host round-trip
between resolution and ordering. Map-only batches keep the standalone
resolve (Pallas-eligible) dispatch.

State model. :class:`DeviceBackendState` is a persistent snapshot (old
snapshots stay valid after applies, like the oracle): per-field surviving
op entries (winner first), per-object records (inbound links; for
sequences the insertion tree as packable columns plus the visible-order
index), the applied-change log per actor, vector clock, dep frontier,
causal buffer. Each apply packs *prior surviving entries of the touched
fields* plus the new assignment ops into dense arrays; the kernel
re-resolves those fields; the unpacked winners become the new field state.
Untouched fields are never re-packed, so the assignment phase is
O(touched), not O(doc). Dirty sequence objects are re-ordered whole by the
RGA kernel — O(n log n) parallel device work replacing the oracle's
per-element pointer walks — and the patch carries the remove/insert/set
list edits derived from the kernel's visible indexes.

Sequence diffs are emitted as a compaction of the oracle's per-op diff
stream: removes (descending old index), then inserts (ascending final
index), then sets (final index). Applying either stream through
``Frontend.apply_patch`` yields the identical document.
"""

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

from ..common import ROOT_ID
from ..backend.op_set import SharedChangeLog, causally_ready, transitive_deps
from ..utils.metrics import metrics
from . import engine as _engine


class _ObjRecord:
    """Per-object device-backend state (counterpart of op_set.js:63-93).

    For sequences the insertion tree is stored as columnar node arrays —
    node 0 is the virtual ``'_head'`` — ready to pack for the RGA kernel,
    plus ``elem_ids``, the visible document order (the order-statistic
    index the reference keeps in its SkipList).
    """

    __slots__ = ('type', 'inbound', 'nodes', 'node_of', 'node_parent',
                 'node_elem', 'node_actor', 'elem_ids')

    SEQUENCE_TYPES = ('makeList', 'makeText')

    def __init__(self, type_=None):
        self.type = type_        # None (root) / 'makeMap'/'makeList'/'makeText'
        self.inbound = []        # (obj, key) fields holding a link to this object
        if type_ in self.SEQUENCE_TYPES:
            self.nodes = ['_head']        # node index -> elemId
            self.node_of = {'_head': 0}   # elemId -> node index
            self.node_parent = [0]        # node index -> parent node index
            self.node_elem = [0]          # node index -> Lamport elem counter
            self.node_actor = ['']        # node index -> actor id string
            self.elem_ids = []            # visible elemIds in document order
        else:
            self.nodes = None

    def is_sequence(self):
        return self.type in self.SEQUENCE_TYPES

    def clone(self):
        rec = _ObjRecord.__new__(_ObjRecord)
        rec.type = self.type
        rec.inbound = list(self.inbound)
        if self.nodes is not None:
            rec.nodes = list(self.nodes)
            rec.node_of = dict(self.node_of)
            rec.node_parent = list(self.node_parent)
            rec.node_elem = list(self.node_elem)
            rec.node_actor = list(self.node_actor)
            rec.elem_ids = list(self.elem_ids)
        else:
            rec.nodes = None
        return rec


class DeviceBackendState(SharedChangeLog):
    """Persistent snapshot of one document's device-resident CRDT state.

    Mirrors what the oracle keeps in an OpSet (op_set.js:298-310), but with
    field state stored as packable entry tuples and insertion trees as
    columnar arrays. The change-log surface (actor_states/get_history/...)
    is shared with the oracle via :class:`SharedChangeLog`.
    """

    __slots__ = ('objects', 'fields', 'states', 'state_lens', 'clock',
                 'deps', 'queue', 'history', 'history_len', '_owned',
                 'log_truncated', 'undo_pos', 'undo_stack', 'redo_stack',
                 'link_fields')

    def __init__(self):
        self.objects = {ROOT_ID: _ObjRecord(None)}
        # (obj, key) -> tuple of entries, winner first (actor-descending).
        # entry = {'actor','seq','all_deps','action'('set'|'link'),'value'}
        self.fields = {}
        self.states = {}        # actor -> grow-only [{'change','all_deps'}]
        self.state_lens = {}    # actor -> visible length in this snapshot
        self.clock = {}
        self.deps = {}
        self.queue = []         # causally-unready buffered changes
        self.history = []       # grow-only applied-change log
        self.history_len = 0
        self._owned = {ROOT_ID}  # objectIds private to this snapshot
        self.log_truncated = False  # True after a snapshot resume
        self.undo_pos = 0
        self.undo_stack = []     # per local change: list of inverse ops
        self.redo_stack = []
        self.link_fields = set()  # fields currently holding link entries

    def clone(self):
        new = DeviceBackendState.__new__(DeviceBackendState)
        new.objects = dict(self.objects)   # records copy-on-write
        new.fields = dict(self.fields)     # entry tuples are immutable
        new.states = dict(self.states)
        new.state_lens = dict(self.state_lens)
        new.clock = dict(self.clock)
        new.deps = dict(self.deps)
        new.queue = list(self.queue)
        new.history = self.history
        new.history_len = self.history_len
        new._owned = set()
        new.log_truncated = self.log_truncated
        new.undo_pos = self.undo_pos
        new.undo_stack = list(self.undo_stack)
        new.redo_stack = list(self.redo_stack)
        new.link_fields = set(self.link_fields)
        return new

    def _writable(self, object_id):
        """Copy-on-write object record access (op_set.py _writable)."""
        if object_id not in self._owned:
            self.objects[object_id] = self.objects[object_id].clone()
            self._owned.add(object_id)
        return self.objects[object_id]

    def rebuild_link_fields(self):
        """Recompute the link-field registry from ``fields`` — every
        path that writes field entries DIRECTLY (snapshot restore,
        TextBlock bridging) must call this, or the link-free fast path
        in _update_fields would skip inbound maintenance."""
        self.link_fields = {
            f for f, entries in self.fields.items()
            if any(e['action'] == 'link' for e in entries)}


def init(_actor_id=None):
    """Empty backend state; the optional actor argument is accepted for
    reference-API compatibility and ignored (backend/index.js:123-125)."""
    return DeviceBackendState()


# -- host phase 1: causal ordering (op_set.js:267-283) -----------------------
# Readiness and transitive closure are the oracle's own helpers
# (op_set.causally_ready / transitive_deps) — both backends duck-type the
# same .clock / .actor_state surface, so causal-delivery semantics can
# never diverge between them.

def _admit_changes(state, changes):
    """Fixed-point causal delivery: returns [(change, all_deps)] of the
    ready changes in application order; the rest stay in state.queue.

    Duplicates (seq already applied) are dropped after verifying the change
    matches what was applied (op_set.js:243-248).
    """
    pending = state.queue + list(changes)
    state.queue = []
    ready = []
    while True:
        progress, remaining = False, []
        for change in pending:
            actor, seq = change['actor'], change['seq']
            if not isinstance(seq, int) or seq < 1:
                raise ValueError(
                    f'Change requires a positive integer seq, got {seq!r}')
            _, n = state.actor_states(actor)
            if seq <= n:
                prior = state.actor_state(actor, seq - 1)['change']
                # prior is None for snapshot-era entries (body dropped by
                # the packed checkpoint): drop the duplicate unverified
                if prior is not None and prior != change:
                    raise ValueError(
                        f'Inconsistent reuse of sequence number {seq} by {actor}')
                continue
            if not causally_ready(state, change):
                remaining.append(change)
                continue
            base_deps = dict(change['deps'])
            base_deps[actor] = seq - 1
            all_deps = transitive_deps(state, base_deps)
            state._append_state(actor, {'change': change, 'all_deps': all_deps})
            state.clock[actor] = seq
            new_deps = {a: s for a, s in state.deps.items()
                        if s > all_deps.get(a, 0)}
            new_deps[actor] = seq
            state.deps = new_deps
            state._append_history(change)
            ready.append((change, all_deps))
            progress = True
        pending = remaining
        if not progress:
            state.queue = remaining
            return ready


# -- host phase 2: collect structural ops + touched-field rows ---------------

_MAKE_KIND = {'makeMap': 'map', 'makeList': 'list', 'makeText': 'text'}


class _DocWork:
    """Per-document staging between the host phases and the device calls.

    Rows are kept as parallel columns: the per-row metadata of NEW ops is
    derived from per-change metadata (``changes_meta``) by vectorized
    gather in :func:`_pack_docs` — the per-op Python work is one dict
    lookup for the segment id and the entry-dict construction; everything
    per-row-numeric (actor rank, seq, clock) is a numpy gather. Prior
    entries of touched fields (usually few) append as explicit rows with
    ``row_change = -1``.
    """

    __slots__ = ('state', 'create_diffs', 'touched', 'dirty_seq',
                 'touched_by_obj', 'survivors', 'ins_dirty',
                 'changes_meta', 'row_field', 'row_entry', 'row_change',
                 'row_seg', 'row_node', 'row_objloc', 'row_is_del',
                 'n_new', 'has_links')

    def __init__(self, state):
        self.state = state
        self.has_links = False    # any link op staged this batch
        self.create_diffs = []
        self.touched = []         # (obj, key) in first-touch order
        self.dirty_seq = []       # sequence obj ids needing re-ordering
        self.touched_by_obj = {}  # obj -> [key] (first-touch order)
        self.survivors = {}       # field -> surviving entries (post-kernel)
        self.ins_dirty = set()    # seq objs that gained nodes this batch
        self.changes_meta = []    # per change: (actor, seq, all_deps)
        self.row_field = []       # field tuple per row
        self.row_entry = []       # entry dict per row
        self.row_change = []      # change index per row (-1 for priors)
        self.row_seg = []         # segment id per row
        self.row_node = []        # node index within its seq obj (-1: map)
        self.row_objloc = []      # index into dirty_seq (-1: map row)
        self.row_is_del = []
        self.n_new = 0

    @property
    def n_rows(self):
        return len(self.row_field)


def _stage_changes(work, admitted):
    state = work.state
    seg_of = {}                  # field -> segment id (first-touch order)
    dirty_of = {}                # seq obj -> index into dirty_seq
    objects = state.objects
    # bound-method locals: this loop runs per OP and dominates the host
    # side of interactive text batches
    touched_append = work.touched.append
    row_field_append = work.row_field.append
    row_entry_append = work.row_entry.append
    row_change_append = work.row_change.append
    row_seg_append = work.row_seg.append
    row_node_append = work.row_node.append
    row_objloc_append = work.row_objloc.append
    row_is_del_append = work.row_is_del.append
    seg_of_get = seg_of.get
    objects_get = objects.get
    ins_obj = None               # last ins target's bound caches
    ins_node_of = ins_nodes_append = ins_parent_append = None
    ins_elem_append = ins_actor_append = None
    ins_n = 0
    for ci, (change, all_deps) in enumerate(admitted):
        actor, seq = change['actor'], change['seq']
        work.changes_meta.append((actor, seq, all_deps))
        for op in change['ops']:
            action = op['action']
            if action in ('set', 'del', 'link'):
                obj = op['obj']
                rec = objects_get(obj)
                if rec is None:
                    raise ValueError('Modification of unknown object ' + obj)
                key = op['key']
                if rec.nodes is not None:       # sequence object
                    node = rec.node_of.get(key)
                    if node is None:
                        raise TypeError(
                            'Missing index entry for list element '
                            + str(key))
                    jl = dirty_of.get(obj)
                    if jl is None:
                        jl = dirty_of[obj] = len(work.dirty_seq)
                        work.dirty_seq.append(obj)
                else:
                    node = jl = -1
                field = (obj, key)
                seg = seg_of_get(field)
                if seg is None:
                    seg = seg_of[field] = len(work.touched)
                    touched_append(field)
                    work.touched_by_obj.setdefault(obj, []).append(key)
                if action == 'link':
                    work.has_links = True
                row_field_append(field)
                row_entry_append(
                    {'actor': actor, 'seq': seq, 'all_deps': all_deps,
                     'action': action, 'value': op.get('value')})
                row_change_append(ci)
                row_seg_append(seg)
                row_node_append(node)
                row_objloc_append(jl)
                row_is_del_append(action == 'del')
            elif action == 'ins':
                obj = op['obj']
                if obj != ins_obj:           # per-object bound caches
                    if obj not in objects:
                        raise ValueError(
                            'Modification of unknown object ' + obj)
                    rec = state._writable(obj)
                    if not rec.is_sequence():
                        raise ValueError(
                            'Insertion into non-sequence object ' + obj)
                    ins_obj = obj
                    ins_node_of = rec.node_of
                    ins_nodes_append = rec.nodes.append
                    ins_parent_append = rec.node_parent.append
                    ins_elem_append = rec.node_elem.append
                    ins_actor_append = rec.node_actor.append
                    ins_n = len(rec.nodes)
                    work.ins_dirty.add(obj)
                    if obj not in dirty_of:
                        dirty_of[obj] = len(work.dirty_seq)
                        work.dirty_seq.append(obj)
                elem = op['elem']
                elem_id = f'{actor}:{elem}'
                if elem_id in ins_node_of:
                    raise ValueError('Duplicate list element ID ' + elem_id)
                parent = ins_node_of.get(op['key'])
                if parent is None:
                    raise ValueError(
                        'List element insertion after unknown element '
                        + str(op['key']))
                ins_node_of[elem_id] = ins_n
                ins_n += 1
                ins_nodes_append(elem_id)
                ins_parent_append(parent)
                ins_elem_append(elem)
                ins_actor_append(actor)
            elif action in _MAKE_KIND:
                obj = op['obj']
                if obj in state.objects:
                    raise ValueError('Duplicate creation of object ' + obj)
                state.objects[obj] = _ObjRecord(action)
                state._owned.add(obj)
                work.create_diffs.append(
                    {'action': 'create', 'obj': obj,
                     'type': _MAKE_KIND[action]})
            else:
                raise ValueError(f'Unknown operation type {action}')

    # Prior surviving entries of every touched field join the batch so the
    # kernel can both supersede them and rank them against the new ops.
    work.n_new = len(work.row_field)
    for field in work.touched:
        entries = state.fields.get(field)
        if not entries:
            continue
        obj = field[0]
        rec = objects[obj]
        if rec.nodes is not None:
            node = rec.node_of[field[1]]
            jl = dirty_of[obj]
        else:
            node = jl = -1
        seg = seg_of[field]
        for entry in entries:
            work.row_field.append(field)
            work.row_entry.append(entry)
            work.row_change.append(-1)
            work.row_seg.append(seg)
            work.row_node.append(node)
            work.row_objloc.append(jl)
            work.row_is_del.append(False)


# -- device phase A: assignment resolution (pack, resolve, unpack) -----------

def _pack_docs(works, options, job_of=None, m_pad=0):
    """Pack every staged row of every doc into [D, n] planes.

    Per-row metadata of new ops is GATHERED from per-change columns
    (actor rank, seq, clock row) — the only per-row host loop left is
    over prior entries, which are few on incremental workloads. With
    `job_of` (a (work id, obj) -> sequence-job index map), each row
    touching a sequence element also gets a flat (job * m_pad + node)
    slot so the fused kernel can derive element visibility on device
    (-1 for map rows). Returns (arrays, n_segs, row_slot).
    """
    d = len(works)
    max_rows = max((w.n_rows for w in works), default=0)
    n = options.pad_ops(max_rows)
    seg_id = np.zeros((d, n), options.index_dtype)
    actor = np.zeros((d, n), options.index_dtype)
    seq = np.zeros((d, n), options.clock_dtype)
    is_del = np.zeros((d, n), bool)
    valid = np.zeros((d, n), bool)
    row_slot = np.full((d, n), -1, np.int32) if job_of is not None else None

    n_actors = 1
    clocks = []
    max_segs = 1
    for i, w in enumerate(works):
        n_rows, n_new = w.n_rows, w.n_new
        prior_entries = w.row_entry[n_new:]
        actor_names = sorted(
            {m[0] for m in w.changes_meta}
            | {e['actor'] for e in prior_entries})
        rank = {a: j for j, a in enumerate(actor_names)}
        a = max(len(actor_names), 1)
        n_actors = max(n_actors, a)
        max_segs = max(max_segs, len(w.touched))
        crows = np.zeros((n, a), options.clock_dtype)
        if n_rows:
            seg_id[i, :n_rows] = w.row_seg
            is_del[i, :n_rows] = w.row_is_del
            valid[i, :n_rows] = True
        if n_new:
            # per-change columns, gathered to rows
            C = len(w.changes_meta)
            ch_rank = np.empty(C, options.index_dtype)
            ch_seq = np.empty(C, options.clock_dtype)
            ch_clock = np.zeros((C, a), options.clock_dtype)
            for c, (a_name, s, all_deps) in enumerate(w.changes_meta):
                ch_rank[c] = rank[a_name]
                ch_seq[c] = s
                for da, ds in all_deps.items():
                    r = rank.get(da)
                    if r is not None:
                        ch_clock[c, r] = ds
            rows_change = np.asarray(w.row_change[:n_new], np.int64)
            actor[i, :n_new] = ch_rank[rows_change]
            seq[i, :n_new] = ch_seq[rows_change]
            crows[:n_new] = ch_clock[rows_change]
        for j in range(n_new, n_rows):            # prior entries (few)
            entry = w.row_entry[j]
            actor[i, j] = rank[entry['actor']]
            seq[i, j] = entry['seq']
            for da, ds in entry['all_deps'].items():
                r = rank.get(da)
                if r is not None:
                    crows[j, r] = ds
        if job_of is not None and n_rows:
            wid = id(w)
            loc2job = np.asarray(
                [job_of.get((wid, obj), -1) for obj in w.dirty_seq]
                + [-1], np.int64)
            objloc = np.asarray(w.row_objloc, np.int64)
            node = np.asarray(w.row_node, np.int64)
            job = loc2job[objloc]
            row_slot[i, :n_rows] = np.where(
                (objloc >= 0) & (job >= 0), job * m_pad + node, -1)
        clocks.append(crows)

    # pad the actor axis to a power of two as well: all three kernel-input
    # dims stay bucketed, so the jit cache is shared across batches
    n_actors = options.pad_actors(n_actors)
    clock = np.zeros((d, n, n_actors), options.clock_dtype)
    for i, crows in enumerate(clocks):
        clock[i, :, :crows.shape[1]] = crows

    n_segs = options.pad_segments(max_segs)
    return (seg_id, actor, seq, clock, is_del, valid), n_segs, row_slot


def _resolve_batch(arrays, n_segs, options):
    """Assignment-only resolution (pallas-eligible dispatch)."""
    resolve = _engine.pick_resolve_kernel(options.kernel)
    out = resolve(*(jnp.asarray(a) for a in arrays), num_segments=n_segs)
    return np.asarray(out['surviving'])


@partial(jax.jit, static_argnames=('num_segments',))
def _fused_step(seg_id, actor, seq, clock, is_del, valid, row_slot,
                s_parent, s_elem, s_actor, s_prior_vis, s_valid, *,
                num_segments):
    """Resolve assignments + derive element visibility + RGA-order every
    dirty sequence, in ONE device program (no host round-trip between
    conflict resolution and ordering).

    Element visibility after the batch: a node with any batch row keeps
    a value iff some row survived (dels never survive); untouched nodes
    keep their prior visibility.
    """
    from .merge import _resolve
    from .sequence import _rga_order
    out = jax.vmap(partial(_resolve, num_segments=num_segments))(
        seg_id, actor, seq, clock, is_del, valid)

    k, m = s_parent.shape
    flat = jnp.where(row_slot >= 0, row_slot, k * m).reshape(-1)
    vis_hit = jnp.zeros(k * m, bool).at[flat].max(
        out['surviving'].reshape(-1), mode='drop')
    touched = jnp.zeros(k * m, bool).at[flat].max(
        valid.reshape(-1), mode='drop')
    visible = jnp.where(touched.reshape(k, m), vis_hit.reshape(k, m),
                        s_prior_vis)
    visible = visible & s_valid

    ordered = jax.vmap(_rga_order)(s_parent, s_elem, s_actor, visible,
                                   s_valid)
    return out, visible, ordered


def _update_fields(work, surviving_row):
    """Fold kernel survivors back into field state + the inbound graph
    (the state effects of op_set.js:180-219); diff emission comes after."""
    state = work.state
    survivors_by_field = {f: [] for f in work.touched}
    row_field, row_entry = work.row_field, work.row_entry
    for j in np.flatnonzero(surviving_row[:work.n_rows]):
        survivors_by_field[row_field[j]].append(row_entry[j])

    # link bookkeeping only runs for fields where links are in play — a
    # text session touches thousands of fields per batch, none of them
    # links, even when the document ROOT holds link fields
    batch_links = work.has_links
    link_fields = state.link_fields
    fields = state.fields
    fields_get = fields.get
    work_survivors = work.survivors
    for field in work.touched:
        survivors = survivors_by_field[field]
        if len(survivors) > 1:
            survivors.sort(key=lambda e: e['actor'], reverse=True)

        if batch_links or field in link_fields:
            before = fields_get(field, ())
            # inbound maintenance: link refs that dropped out leave the
            # target, new surviving links join it (op_set.js:194-208).
            gone = [e for e in before
                    if e not in survivors and e['action'] == 'link']
            for e in gone:
                if e['value'] in state.objects:
                    target = state._writable(e['value'])
                    target.inbound = [r for r in target.inbound
                                      if r != field]
            has_link = False
            for e in survivors:
                if e['action'] == 'link':
                    has_link = True
                    target = state._writable(e['value'])
                    if field not in target.inbound:
                        target.inbound.append(field)
            if has_link:
                state.link_fields.add(field)
            else:
                state.link_fields.discard(field)

        fields[field] = tuple(survivors)
        work_survivors[field] = survivors


def _get_path(state, object_id):
    """Key path from root (op_set.js:43-60); list positions as indexes."""
    path = []
    while object_id != ROOT_ID:
        rec = state.objects.get(object_id)
        if rec is None or not rec.inbound:
            return None
        parent, key = rec.inbound[0]
        prec = state.objects[parent]
        if prec.is_sequence():
            try:
                path.insert(0, prec.elem_ids.index(key))
            except ValueError:
                return None
        else:
            path.insert(0, key)
        object_id = parent
    return path


def _conflict_entries(losers):
    out = []
    for entry in losers:
        conflict = {'actor': entry['actor'], 'value': entry['value']}
        if entry['action'] == 'link':
            conflict['link'] = True
        out.append(conflict)
    return out


def _emit_map_diffs(work):
    """Map-key diffs for every touched map field (op_set.js:161-177)."""
    state = work.state
    diffs = []
    for field in work.touched:
        obj, key = field
        if state.objects[obj].is_sequence():
            continue
        survivors = work.survivors[field]
        edit = {'action': 'set' if survivors else 'remove', 'type': 'map',
                'obj': obj, 'key': key, 'path': _get_path(state, obj)}
        if survivors:
            winner = survivors[0]
            edit['value'] = winner['value']
            if winner['action'] == 'link':
                edit['link'] = True
            if len(survivors) > 1:
                edit['conflicts'] = _conflict_entries(survivors[1:])
        diffs.append(edit)
    return diffs


# -- device phase B: sequence re-ordering (RGA kernel) -----------------------

def _collect_seq_jobs(works):
    """One job per dirty sequence object across the whole doc batch."""
    jobs = []
    for w in works:
        for obj in w.dirty_seq:
            rec = w.state._writable(obj)
            # prior visibility = the before-state order index (elem_ids
            # holds exactly the visible elements); the fused kernel
            # derives post-batch visibility for touched nodes on device
            vis_set = set(rec.elem_ids)
            prior_vis = np.fromiter((eid in vis_set for eid in rec.nodes),
                                    bool, len(rec.nodes))
            jobs.append((w, obj, rec, prior_vis))
    return jobs


def _pack_seq_jobs(jobs, m_pad, options):
    """Pack every dirty sequence object's insertion tree into [K, m]
    planes for the fused kernel."""
    k = len(jobs)
    parent = np.zeros((k, m_pad), options.index_dtype)
    elem = np.zeros((k, m_pad), options.clock_dtype)
    actor = np.zeros((k, m_pad), options.index_dtype)
    prior_vis = np.zeros((k, m_pad), bool)
    valid = np.zeros((k, m_pad), bool)
    for i, (_w, _obj, rec, pv) in enumerate(jobs):
        n = len(rec.nodes)
        parent[i, :n] = rec.node_parent
        elem[i, :n] = rec.node_elem
        # rank order must preserve actor-string order (op_set.js:371-377)
        names = sorted(set(rec.node_actor))
        rank = {a: j for j, a in enumerate(names)}
        actor[i, :n] = [rank[a] for a in rec.node_actor]
        prior_vis[i, :n] = pv
        valid[i, :n] = True
    return parent, elem, actor, prior_vis, valid


def _emit_seq_diffs(work, obj, rec, visible, vis_index):
    """remove/insert/set list edits from the kernel's final ordering.

    The oracle walks each touched element through the evolving SkipList
    (op_set.js:105-159); here the final visible index of every node is
    already on hand (``vis_index``), so the edit script is: removes at old
    indexes (descending), inserts at final indexes (ascending), sets at
    final indexes. Applied in that order the indexes are valid at every
    intermediate step, and the resulting document equals the oracle's.
    """
    state = work.state
    obj_type = 'text' if rec.type == 'makeText' else 'list'
    old_index = {eid: i for i, eid in enumerate(rec.elem_ids)}
    touched = work.touched_by_obj.get(obj, ())

    removes, inserts, sets = [], [], []
    for key in touched:
        node = rec.node_of[key]
        vis_after = visible[node]
        was_visible = key in old_index
        if was_visible and not vis_after:
            removes.append(old_index[key])
        elif vis_after:
            survivors = work.survivors[(obj, key)]
            winner = survivors[0]
            edit = {'type': obj_type, 'obj': obj,
                    'index': int(vis_index[node]), 'value': winner['value']}
            if winner['action'] == 'link':
                edit['link'] = True
            if len(survivors) > 1:
                edit['conflicts'] = _conflict_entries(survivors[1:])
            if was_visible:
                edit['action'] = 'set'
                sets.append(edit)
            else:
                edit['action'] = 'insert'
                edit['elemId'] = key
                inserts.append(edit)

    removes.sort(reverse=True)
    inserts.sort(key=lambda e: e['index'])
    sets.sort(key=lambda e: e['index'])

    diffs = []
    if obj in work.ins_dirty:
        # Batched diffs net out an element inserted AND deleted within
        # one apply — its counter would never reach the frontend, whose
        # next local insert would mint a colliding elemId. A maxElem
        # diff keeps the frontend's counter truthful (extension over the
        # reference, which has the latent collision; see README).
        diffs.append({'action': 'maxElem', 'type': obj_type, 'obj': obj,
                      'value': max(rec.node_elem)})
    for idx in removes:
        diffs.append({'action': 'remove', 'type': obj_type, 'obj': obj,
                      'index': idx})
    diffs.extend(inserts)
    diffs.extend(sets)

    # rebuild the order index wholesale from the kernel's final ordering
    # (incremental list insert/delete would be O(n) per edit); identical
    # to applying the removes/inserts above in order
    vis_nodes = np.flatnonzero(vis_index >= 0)
    new_ids = [None] * len(vis_nodes)
    nodes = rec.nodes
    for node in vis_nodes.tolist():
        new_ids[vis_index[node]] = nodes[node]
    rec.elem_ids = new_ids

    path = _get_path(state, obj)
    for edit in diffs:
        edit['path'] = path
    return diffs


# -- patch assembly ----------------------------------------------------------

def _make_patch(state, diffs):
    return {'clock': dict(state.clock), 'deps': dict(state.deps),
            'canUndo': state.undo_pos > 0,
            'canRedo': bool(state.redo_stack), 'diffs': diffs}


# -- public surface ----------------------------------------------------------

def apply_changes_batch(states, changes_per_doc, kernel=None, options=None):
    """Apply wire changes to a batch of documents in one device call.

    Args:
      states: list of :class:`DeviceBackendState`, one per document.
      changes_per_doc: list (parallel to `states`) of change lists.
      options: :class:`~automerge_tpu.config.Options`; `kernel` overrides
        just the kernel choice.

    Returns:
      (new_states, patches) — patches carry reference-format diffs. One
      diff per touched field / list element (the compaction of the
      oracle's per-op diff stream: applying either stream to a frontend
      yields the same doc).
    """
    from . import general_backend as _gb
    opts = _engine.as_options(options, kernel)
    works = []
    for i, (state, changes) in enumerate(zip(states, changes_per_doc)):
        if isinstance(state, _gb.GeneralBackendState):
            # a bulk-auto-routed document's token is served by the
            # general engine, not the per-doc staging loop — routing it
            # here would die deep inside _stage_changes with an opaque
            # AttributeError (r5 review: the auto-routing type leak)
            raise TypeError(
                f'states[{i}] is a GeneralBackendState (bulk-routed '
                f'document); apply through apply_changes / '
                f'general_backend.apply_changes, not '
                f'apply_changes_batch')
        state = state.clone()
        admitted = _admit_changes(state, changes)
        work = _DocWork(state)
        _stage_changes(work, admitted)
        works.append(work)

    total_rows = sum(w.n_rows for w in works)
    seq_jobs = _collect_seq_jobs(works)

    seq_vis = seq_out = None
    if seq_jobs:
        # ONE device program: resolve + visibility + RGA ordering
        m_pad = opts.pad_nodes(max(len(rec.nodes)
                                   for _, _, rec, _ in seq_jobs))
        job_of = {(id(w), obj): i
                  for i, (w, obj, _rec, _pv) in enumerate(seq_jobs)}
        arrays, n_segs, row_slot = _pack_docs(works, opts, job_of, m_pad)
        seq_arrays = _pack_seq_jobs(seq_jobs, m_pad, opts)
        out, visible, ordered = _fused_step(
            *(jnp.asarray(a) for a in arrays), jnp.asarray(row_slot),
            *(jnp.asarray(a) for a in seq_arrays), num_segments=n_segs)
        metrics.bump('device_backend_fused_calls')
        # one batched fetch (a single D2H round-trip, not three)
        surviving, seq_vis, seq_out = jax.device_get(
            (out['surviving'], visible, ordered['vis_index']))
    elif total_rows:
        arrays, n_segs, _ = _pack_docs(works, opts)
        surviving = _resolve_batch(arrays, n_segs, opts)
    else:
        surviving = np.zeros((len(works), 1), bool)
    for i, w in enumerate(works):
        _update_fields(w, surviving[i])

    seq_diffs_by_work = {}
    if seq_jobs:
        for i, (w, obj, rec, _pv) in enumerate(seq_jobs):
            n = len(rec.nodes)
            diffs = _emit_seq_diffs(w, obj, rec, seq_vis[i, :n],
                                    seq_out[i, :n])
            seq_diffs_by_work.setdefault(id(w), []).extend(diffs)

    new_states, patches = [], []
    for w in works:
        diffs = list(w.create_diffs)
        diffs.extend(_emit_map_diffs(w))
        diffs.extend(seq_diffs_by_work.get(id(w), ()))
        new_states.append(w.state)
        patches.append(_make_patch(w.state, diffs))

    metrics.bump('device_backend_batches')
    metrics.bump('device_backend_ops', total_rows)
    if seq_jobs:
        metrics.bump('device_backend_seq_objects', len(seq_jobs))
    return new_states, patches


def apply_changes(state, changes, kernel=None, options=None):
    """Single-document facade matching Backend.apply_changes
    (backend/index.js:161-163).

    Bulk ingests auto-route to the general block engine: a fresh
    document receiving >= ``Options.bulk_route_min_ops`` ops in one
    call (a clone, a resync, a large merge) takes ONE fused block apply
    instead of the per-change staging loop, and continues on the
    general-backed state for subsequent applies; local changes and
    undo/redo convert back to this per-doc state
    (:mod:`.general_backend`)."""
    from . import general_backend as _gb
    opts = _engine.as_options(options, kernel)
    if isinstance(state, _gb.GeneralBackendState):
        new_state, patch = _gb.apply_changes(state, changes,
                                             options=opts)
        patch['diffs'] = list(patch['diffs'])    # facade: plain list
        return new_state, patch
    thr = opts.bulk_route_min_ops
    if thr is not None and not state.clock and not state.queue \
            and state.undo_pos == 0 and not state.redo_stack:
        changes = list(changes)      # sizing must not consume iterators
        n_ops = sum(len(c.get('ops', ())) for c in changes)
        if n_ops >= thr:
            new_state, patch = _gb.apply_changes(_gb.init(), changes,
                                                 options=opts)
            # the public facade promises a PLAIN diff list —
            # json.dumps(patch) and `diffs + [...]` must work on an
            # auto-routed result exactly as on the per-doc path
            patch['diffs'] = list(patch['diffs'])
            return new_state, patch
    new_states, patches = apply_changes_batch([state], [changes],
                                              kernel=kernel, options=options)
    return new_states[0], patches[0]


def _capture_undo_ops(state, change):
    """Inverse ops for one local change: each touched pre-existing field's
    surviving entries (as plain set/link ops), or a del if the field was
    new (op_set.js:185-192)."""
    new_objects = set()
    undo_ops = []
    seen = set()
    for op in change.get('ops', ()):
        action = op['action']
        if action in _MAKE_KIND:
            new_objects.add(op['obj'])
        elif action in ('set', 'del', 'link') and op['obj'] not in new_objects:
            field = (op['obj'], op['key'])
            if field in seen:
                continue
            seen.add(field)
            undo_ops.extend(_field_ops_or_del(state, [op]))
    return undo_ops


def _field_ops_or_del(state, ref_ops):
    """Current field state of each op's field as plain ops (the redo
    capture of backend/index.js:262-276)."""
    out = []
    for op in ref_ops:
        if op['action'] not in ('set', 'del', 'link'):
            raise ValueError(
                f'Unexpected operation type in undo history: {op}')
        entries = state.fields.get((op['obj'], op['key']), ())
        if not entries:
            out.append({'action': 'del', 'obj': op['obj'],
                        'key': op['key']})
        else:
            for e in entries:
                out.append({'action': e['action'], 'obj': op['obj'],
                            'key': op['key'], 'value': e['value']})
    return out


def _undo(state, request, kernel=None, options=None):
    """Apply the inverse ops from the undo stack as a new change
    (backend/index.js:252-285)."""
    if state.undo_pos < 1:
        raise ValueError('Cannot undo: there is nothing to be undone')
    undo_ops = state.undo_stack[state.undo_pos - 1]
    change = {'actor': request['actor'], 'seq': request['seq'],
              'deps': dict(request.get('deps', {})), 'ops': undo_ops}
    if request.get('message') is not None:
        change['message'] = request['message']
    redo_ops = _field_ops_or_del(state, undo_ops)

    new_state, patch = apply_changes(state, [change], kernel=kernel,
                                     options=options)
    new_state.undo_pos = state.undo_pos - 1
    new_state.redo_stack = state.redo_stack + [redo_ops]
    patch['canUndo'] = new_state.undo_pos > 0
    patch['canRedo'] = True
    return new_state, patch


def _redo(state, request, kernel=None, options=None):
    """Re-apply the ops reverted by the last undo (backend/index.js:293-308)."""
    if not state.redo_stack:
        raise ValueError('Cannot redo: the last change was not an undo')
    redo_ops = state.redo_stack[-1]
    change = {'actor': request['actor'], 'seq': request['seq'],
              'deps': dict(request.get('deps', {})), 'ops': redo_ops}
    if request.get('message') is not None:
        change['message'] = request['message']

    new_state, patch = apply_changes(state, [change], kernel=kernel,
                                     options=options)
    new_state.undo_pos = state.undo_pos + 1
    new_state.redo_stack = state.redo_stack[:-1]
    patch['canUndo'] = True
    patch['canRedo'] = bool(new_state.redo_stack)
    return new_state, patch


def apply_local_change(state, request, kernel=None, options=None):
    """Apply one local change request, recording undo history
    (backend/index.js:173-195)."""
    # GeneralBackendState participates natively: its `fields` view
    # serves the undo capture, apply_changes routes to the bulk
    # engine, and the token carries the undo/redo stacks. A STALE
    # token forks FIRST so the capture reads exactly its lineage
    # (the shared columns may hold newer changes — r5 review).
    from . import general_backend as _gb
    if isinstance(state, _gb.GeneralBackendState):
        state = _gb.current_token(state)
    if not isinstance(request.get('actor'), str) or not isinstance(request.get('seq'), int):
        raise TypeError('Change request requires `actor` and `seq` properties')
    if request['seq'] <= state.clock.get(request['actor'], 0):
        raise ValueError('Change request has already been applied')
    request_type = request.get('requestType')
    if request_type == 'change':
        change = {k: v for k, v in request.items() if k != 'requestType'}
        undo_ops = _capture_undo_ops(state, change)
        new_state, patch = apply_changes(state, [change], kernel=kernel,
                                         options=options)
        new_state.undo_stack = \
            state.undo_stack[:state.undo_pos] + [undo_ops]
        new_state.undo_pos = state.undo_pos + 1
        new_state.redo_stack = []
        patch['canUndo'] = True
        patch['canRedo'] = False
    elif request_type == 'undo':
        new_state, patch = _undo(state, request, kernel=kernel,
                                 options=options)
    elif request_type == 'redo':
        new_state, patch = _redo(state, request, kernel=kernel,
                                 options=options)
    else:
        raise ValueError(f'Unknown requestType: {request_type}')
    patch['actor'] = request['actor']
    patch['seq'] = request['seq']
    return new_state, patch


def get_patch(state):
    """Whole-document patch from empty (backend/index.js:201-207): create
    diffs child-first, then field sets / element inserts, so the frontend
    can resolve links."""
    from . import general_backend as _gb
    if isinstance(state, _gb.GeneralBackendState):
        return _gb.get_patch(state)
    diffs = []
    emitted = set()
    # one pass over the field table, then per-object lookups are O(fields-of)
    fields_by_obj = {}
    for (obj, key), entries in state.fields.items():
        if entries:
            fields_by_obj.setdefault(obj, []).append((key, entries))

    def emit_entry_objects(entries):
        for e in entries:
            if e['action'] == 'link':
                emit_object(e['value'])

    def emit_object(obj_id):
        if obj_id in emitted:
            return
        emitted.add(obj_id)
        rec = state.objects[obj_id]
        obj_diffs = []
        if rec.is_sequence():
            obj_type = 'text' if rec.type == 'makeText' else 'list'
            obj_diffs.append({'action': 'create', 'obj': obj_id,
                              'type': obj_type,
                              'maxElem': max(rec.node_elem, default=0)})
            for index, elem_id in enumerate(rec.elem_ids):
                entries = state.fields[(obj_id, elem_id)]
                emit_entry_objects(entries)   # children first
                winner = entries[0]
                edit = {'action': 'insert', 'type': obj_type, 'obj': obj_id,
                        'index': index, 'elemId': elem_id,
                        'value': winner['value']}
                if winner['action'] == 'link':
                    edit['link'] = True
                if len(entries) > 1:
                    edit['conflicts'] = _conflict_entries(entries[1:])
                obj_diffs.append(edit)
        else:
            if obj_id != ROOT_ID:
                obj_diffs.append({'action': 'create', 'obj': obj_id,
                                  'type': 'map'})
            for key, entries in fields_by_obj.get(obj_id, ()):
                emit_entry_objects(entries)   # children first
                winner = entries[0]
                edit = {'action': 'set', 'type': 'map', 'obj': obj_id,
                        'key': key, 'value': winner['value']}
                if winner['action'] == 'link':
                    edit['link'] = True
                if len(entries) > 1:
                    edit['conflicts'] = _conflict_entries(entries[1:])
                obj_diffs.append(edit)
        diffs.extend(obj_diffs)

    emit_object(ROOT_ID)
    return _make_patch(state, diffs)


def get_missing_changes(state, have_deps):
    """Changes a peer with clock `have_deps` lacks (op_set.js:327-334)."""
    from . import general_backend as _gb
    if isinstance(state, _gb.GeneralBackendState):
        return _gb.get_missing_changes(state, have_deps)
    all_deps = transitive_deps(state, dict(have_deps))
    changes = []
    for actor in state.states:
        lst, n = state.actor_states(actor)
        for entry in lst[all_deps.get(actor, 0):n]:
            if entry['change'] is None:
                raise ValueError(
                    'change log truncated by a snapshot resume; a peer '
                    'this far behind needs the snapshot or the full log')
            changes.append(entry['change'])
    return changes


def get_changes_for_actor(state, for_actor, after_seq=0):
    from . import general_backend as _gb
    if isinstance(state, _gb.GeneralBackendState):
        return _gb.get_changes_for_actor(state, for_actor, after_seq)
    lst, n = state.actor_states(for_actor)
    out = []
    for entry in lst[after_seq:n]:
        if entry['change'] is None:
            raise ValueError(
                'change log truncated by a snapshot resume; a peer '
                'this far behind needs the snapshot or the full log')
        out.append(entry['change'])
    return out


def get_missing_deps(state):
    """Unmet dependencies of the buffered changes (op_set.js:347-358)."""
    from . import general_backend as _gb
    if isinstance(state, _gb.GeneralBackendState):
        return _gb.get_missing_deps(state)
    missing = {}
    for change in state.queue:
        deps = dict(change['deps'])
        deps[change['actor']] = change['seq'] - 1
        for a, s in deps.items():
            if state.clock.get(a, 0) < s:
                missing[a] = max(s, missing.get(a, 0))
    return missing


def merge(local, remote, kernel=None, options=None):
    """Pull changes present in `remote` but not `local`
    (backend/index.js:240-243)."""
    changes = get_missing_changes(remote, local.clock)
    return apply_changes(local, changes, kernel=kernel, options=options)


# camelCase aliases (reference API parity)
applyChanges = apply_changes
applyChangesBatch = apply_changes_batch
applyLocalChange = apply_local_change
getPatch = get_patch
getMissingChanges = get_missing_changes
getChangesForActor = get_changes_for_actor
getMissingDeps = get_missing_deps
