"""Batched device backend: wire changes in, patches out, TPU in between.

This module puts the device engine behind the frontend<->backend
change/patch protocol (the reference's `backend/index.js:161-163` surface):
``apply_changes_batch`` takes per-document wire changes and returns
per-document **patches** — diffs with obj/key/value/conflicts exactly as
the reference's diff emission produces them (`backend/op_set.js:161-177`)
— while the conflict resolution for every touched field of every document
runs in ONE jitted device call (:mod:`.merge`).

State model. :class:`DeviceBackendState` is a persistent snapshot (old
snapshots stay valid after applies, like the oracle): per-field surviving
op entries (winner first), the applied-change log per actor, vector clock,
dep frontier, causal buffer. Each apply packs *prior surviving entries of
the touched fields* plus the new assignment ops into dense arrays; the
segment-reduction kernel re-resolves those fields; the unpacked winners
become both the new field state and the patch diffs. Untouched fields are
never re-packed, so incremental applies are O(touched), not O(doc).

Scope: map documents, including nested maps via makeMap/link ops
(structural makeX ops are host-side create diffs; link assignments resolve
on device like sets). Documents containing sequence ops are migrated to
the host oracle by :class:`~automerge_tpu.sync.device_doc_set.DeviceDocSet`
(the batched sequence kernel itself lives in
:mod:`automerge_tpu.device.sequence`).
"""

import numpy as np
import jax.numpy as jnp

from ..common import ROOT_ID
from ..backend.op_set import SharedChangeLog, causally_ready, transitive_deps
from ..utils.metrics import metrics
from . import engine as _engine


class DeviceBackendState(SharedChangeLog):
    """Persistent snapshot of one document's device-resident CRDT state.

    Mirrors what the oracle keeps in an OpSet (op_set.js:298-310), but with
    field state stored as packable entry tuples instead of op dicts inside
    an object tree. The change-log surface (actor_states/get_history/...)
    is shared with the oracle via :class:`SharedChangeLog`.
    """

    __slots__ = ('objects', 'fields', 'states', 'state_lens', 'clock',
                 'deps', 'queue', 'history', 'history_len')

    def __init__(self):
        # obj_id -> {'type': 'makeMap'|None, 'inbound': list of (obj, key)}
        self.objects = {ROOT_ID: {'type': None, 'inbound': []}}
        # (obj, key) -> tuple of entries, winner first (actor-descending).
        # entry = {'actor','seq','all_deps','action'('set'|'link'),'value'}
        self.fields = {}
        self.states = {}        # actor -> grow-only [{'change','all_deps'}]
        self.state_lens = {}    # actor -> visible length in this snapshot
        self.clock = {}
        self.deps = {}
        self.queue = []         # causally-unready buffered changes
        self.history = []       # grow-only applied-change log
        self.history_len = 0

    def clone(self):
        new = DeviceBackendState.__new__(DeviceBackendState)
        new.objects = {k: {'type': v['type'], 'inbound': list(v['inbound'])}
                       for k, v in self.objects.items()}
        new.fields = dict(self.fields)     # entry tuples are immutable
        new.states = dict(self.states)
        new.state_lens = dict(self.state_lens)
        new.clock = dict(self.clock)
        new.deps = dict(self.deps)
        new.queue = list(self.queue)
        new.history = self.history
        new.history_len = self.history_len
        return new


def init():
    return DeviceBackendState()


# -- host phase 1: causal ordering (op_set.js:267-283) -----------------------
# Readiness and transitive closure are the oracle's own helpers
# (op_set.causally_ready / transitive_deps) — both backends duck-type the
# same .clock / .actor_state surface, so causal-delivery semantics can
# never diverge between them.

def _admit_changes(state, changes):
    """Fixed-point causal delivery: returns [(change, all_deps)] of the
    ready changes in application order; the rest stay in state.queue.

    Duplicates (seq already applied) are dropped after verifying the change
    matches what was applied (op_set.js:243-248).
    """
    pending = state.queue + list(changes)
    state.queue = []
    ready = []
    while True:
        progress, remaining = False, []
        for change in pending:
            actor, seq = change['actor'], change['seq']
            _, n = state.actor_states(actor)
            if seq <= n:
                if state.actor_state(actor, seq - 1)['change'] != change:
                    raise ValueError(
                        f'Inconsistent reuse of sequence number {seq} by {actor}')
                continue
            if not causally_ready(state, change):
                remaining.append(change)
                continue
            base_deps = dict(change['deps'])
            base_deps[actor] = seq - 1
            all_deps = transitive_deps(state, base_deps)
            state._append_state(actor, {'change': change, 'all_deps': all_deps})
            state.clock[actor] = seq
            new_deps = {a: s for a, s in state.deps.items()
                        if s > all_deps.get(a, 0)}
            new_deps[actor] = seq
            state.deps = new_deps
            state._append_history(change)
            ready.append((change, all_deps))
            progress = True
        pending = remaining
        if not progress:
            state.queue = remaining
            return ready


# -- host phase 2: collect structural ops + touched-field rows ---------------

class _DocWork:
    """Per-document staging between the host phases and the device call."""

    __slots__ = ('state', 'create_diffs', 'touched', 'rows')

    def __init__(self, state):
        self.state = state
        self.create_diffs = []
        self.touched = []      # (obj, key) in first-touch order
        self.rows = []         # (field, entry_dict, is_del, is_new)


def _stage_changes(work, admitted):
    state = work.state
    touched_set = set()
    for change, all_deps in admitted:
        actor, seq = change['actor'], change['seq']
        for op in change['ops']:
            action = op['action']
            if action == 'makeMap':
                obj = op['obj']
                if obj in state.objects:
                    raise ValueError('Duplicate creation of object ' + obj)
                state.objects[obj] = {'type': 'makeMap', 'inbound': []}
                work.create_diffs.append(
                    {'action': 'create', 'obj': obj, 'type': 'map'})
            elif action in ('makeList', 'makeText', 'ins'):
                raise NotImplementedError(
                    'sequence ops are not handled by the map backend; use '
                    'DeviceDocSet (which migrates sequence documents to the '
                    'host oracle) or the host backend directly')
            elif action in ('set', 'del', 'link'):
                if op['obj'] not in state.objects:
                    raise ValueError(
                        'Modification of unknown object ' + op['obj'])
                field = (op['obj'], op['key'])
                if field not in touched_set:
                    touched_set.add(field)
                    work.touched.append(field)
                entry = {'actor': actor, 'seq': seq, 'all_deps': all_deps,
                         'action': action, 'value': op.get('value')}
                work.rows.append((field, entry, action == 'del', True))
            else:
                raise ValueError(f'Unknown operation type {action}')

    # Prior surviving entries of every touched field join the batch so the
    # kernel can both supersede them and rank them against the new ops.
    for field in work.touched:
        for entry in state.fields.get(field, ()):
            work.rows.append((field, entry, False, False))


# -- device phase: pack, resolve, unpack -------------------------------------

def _pack_docs(works, options):
    """Pack every staged row of every doc, run ONE device resolution."""
    d = len(works)
    max_rows = max((len(w.rows) for w in works), default=0)
    n = options.pad_ops(max_rows)
    seg_id = np.zeros((d, n), np.int32)
    actor = np.zeros((d, n), np.int32)
    seq = np.zeros((d, n), np.int32)
    is_del = np.zeros((d, n), bool)
    valid = np.zeros((d, n), bool)

    n_actors = 1
    clocks = []
    max_segs = 1
    for i, w in enumerate(works):
        actor_names = sorted({r[1]['actor'] for r in w.rows})
        rank = {a: j for j, a in enumerate(actor_names)}
        seg_of = {f: j for j, f in enumerate(w.touched)}
        a = max(len(actor_names), 1)
        n_actors = max(n_actors, a)
        max_segs = max(max_segs, len(w.touched))
        crows = np.zeros((n, a), np.int32)
        for j, (field, entry, del_flag, _is_new) in enumerate(w.rows):
            seg_id[i, j] = seg_of[field]
            actor[i, j] = rank[entry['actor']]
            seq[i, j] = entry['seq']
            for da, ds in entry['all_deps'].items():
                if da in rank:
                    crows[j, rank[da]] = ds
            is_del[i, j] = del_flag
            valid[i, j] = True
        clocks.append(crows)

    # pad the actor axis to a power of two as well: all three kernel-input
    # dims stay bucketed, so the jit cache is shared across batches
    n_actors = options.pad_actors(n_actors)
    clock = np.zeros((d, n, n_actors), np.int32)
    for i, crows in enumerate(clocks):
        clock[i, :, :crows.shape[1]] = crows

    n_segs = options.pad_segments(max_segs)
    resolve = _engine.pick_resolve_kernel(options.kernel)
    out = resolve(jnp.asarray(seg_id), jnp.asarray(actor), jnp.asarray(seq),
                  jnp.asarray(clock), jnp.asarray(is_del), jnp.asarray(valid),
                  num_segments=n_segs)
    return np.asarray(out['surviving'])


def _get_path(state, object_id):
    """Key path from root (op_set.js:43-60), maps only."""
    path = []
    while object_id != ROOT_ID:
        rec = state.objects.get(object_id)
        if rec is None or not rec['inbound']:
            return None
        parent, key = rec['inbound'][0]
        path.insert(0, key)
        object_id = parent
    return path


def _conflict_entries(losers):
    out = []
    for entry in losers:
        conflict = {'actor': entry['actor'], 'value': entry['value']}
        if entry['action'] == 'link':
            conflict['link'] = True
        out.append(conflict)
    return out


def _unpack_doc(work, surviving_row):
    """Update field state + inbound graph, emit diffs (op_set.js:161-177)."""
    state = work.state
    survivors_by_field = {f: [] for f in work.touched}
    for j, (field, entry, _is_del, _is_new) in enumerate(work.rows):
        if surviving_row[j]:
            survivors_by_field[field].append(entry)

    diffs = list(work.create_diffs)
    for field in work.touched:
        obj, key = field
        before = state.fields.get(field, ())
        survivors = sorted(survivors_by_field[field],
                           key=lambda e: e['actor'], reverse=True)

        # inbound maintenance: link refs that dropped out leave the target,
        # new surviving links join it (op_set.js:194-208).
        gone = [e for e in before if e not in survivors and e['action'] == 'link']
        for e in gone:
            target = state.objects.get(e['value'])
            if target is not None:
                target['inbound'] = [r for r in target['inbound'] if r != field]
        for e in survivors:
            if e['action'] == 'link':
                target = state.objects[e['value']]
                if field not in target['inbound']:
                    target['inbound'].append(field)

        state.fields[field] = tuple(survivors)

        edit = {'action': 'set' if survivors else 'remove', 'type': 'map',
                'obj': obj, 'key': key, 'path': _get_path(state, obj)}
        if survivors:
            winner = survivors[0]
            edit['value'] = winner['value']
            if winner['action'] == 'link':
                edit['link'] = True
            if len(survivors) > 1:
                edit['conflicts'] = _conflict_entries(survivors[1:])
        diffs.append(edit)
    return diffs


def _make_patch(state, diffs):
    return {'clock': dict(state.clock), 'deps': dict(state.deps),
            'canUndo': False, 'canRedo': False, 'diffs': diffs}


# -- public surface ----------------------------------------------------------

def apply_changes_batch(states, changes_per_doc, kernel=None, options=None):
    """Apply wire changes to a batch of documents in one device call.

    Args:
      states: list of :class:`DeviceBackendState`, one per document.
      changes_per_doc: list (parallel to `states`) of change lists.
      options: :class:`~automerge_tpu.config.Options`; `kernel` overrides
        just the kernel choice.

    Returns:
      (new_states, patches) — patches carry reference-format diffs. One
      diff per touched field (the compaction of the oracle's per-op diff
      stream: applying either stream to a frontend yields the same doc).
    """
    opts = _engine.as_options(options, kernel)
    works = []
    for state, changes in zip(states, changes_per_doc):
        state = state.clone()
        admitted = _admit_changes(state, changes)
        work = _DocWork(state)
        _stage_changes(work, admitted)
        works.append(work)

    total_rows = sum(len(w.rows) for w in works)
    if total_rows:
        surviving = _pack_docs(works, opts)
    else:
        surviving = np.zeros((len(works), 1), bool)

    new_states, patches = [], []
    for i, w in enumerate(works):
        diffs = _unpack_doc(w, surviving[i])
        new_states.append(w.state)
        patches.append(_make_patch(w.state, diffs))

    metrics.bump('device_backend_batches')
    metrics.bump('device_backend_ops', total_rows)
    return new_states, patches


def apply_changes(state, changes, kernel=None, options=None):
    """Single-document facade matching Backend.apply_changes
    (backend/index.js:161-163)."""
    new_states, patches = apply_changes_batch([state], [changes],
                                              kernel=kernel, options=options)
    return new_states[0], patches[0]


def apply_local_change(state, request, kernel=None, options=None):
    """Apply one local change request (backend/index.js:173-195).

    The device backend does not keep op-level undo history; 'undo'/'redo'
    requests are rejected — documents needing undo use the oracle backend.
    """
    if not isinstance(request.get('actor'), str) or not isinstance(request.get('seq'), int):
        raise TypeError('Change request requires `actor` and `seq` properties')
    if request['seq'] <= state.clock.get(request['actor'], 0):
        raise ValueError('Change request has already been applied')
    if request.get('requestType') != 'change':
        raise NotImplementedError(
            'device backend supports requestType "change" only')
    change = {k: v for k, v in request.items() if k != 'requestType'}
    new_state, patch = apply_changes(state, [change], kernel=kernel,
                                     options=options)
    patch['actor'] = request['actor']
    patch['seq'] = request['seq']
    return new_state, patch


def get_patch(state):
    """Whole-document patch from empty (backend/index.js:201-207): create
    diffs child-first, then field sets, so the frontend can resolve links."""
    diffs = []
    emitted = set()
    # one pass over the field table, then per-object lookups are O(fields-of)
    fields_by_obj = {}
    for (obj, key), entries in state.fields.items():
        if entries:
            fields_by_obj.setdefault(obj, []).append((key, entries))

    def emit_object(obj_id):
        if obj_id in emitted:
            return
        emitted.add(obj_id)
        # children first (MaterializationContext.make_patch order)
        obj_diffs = []
        if obj_id != ROOT_ID:
            obj_diffs.append({'action': 'create', 'obj': obj_id, 'type': 'map'})
        for key, entries in fields_by_obj.get(obj_id, ()):
            winner = entries[0]
            if winner['action'] == 'link':
                emit_object(winner['value'])
            for e in entries[1:]:
                if e['action'] == 'link':
                    emit_object(e['value'])
            edit = {'action': 'set', 'type': 'map', 'obj': obj_id, 'key': key,
                    'value': winner['value']}
            if winner['action'] == 'link':
                edit['link'] = True
            if len(entries) > 1:
                edit['conflicts'] = _conflict_entries(entries[1:])
            obj_diffs.append(edit)
        diffs.extend(obj_diffs)

    emit_object(ROOT_ID)
    return _make_patch(state, diffs)


def get_missing_changes(state, have_deps):
    """Changes a peer with clock `have_deps` lacks (op_set.js:327-334)."""
    all_deps = transitive_deps(state, dict(have_deps))
    changes = []
    for actor in state.states:
        lst, n = state.actor_states(actor)
        for entry in lst[all_deps.get(actor, 0):n]:
            changes.append(entry['change'])
    return changes


def get_changes_for_actor(state, for_actor, after_seq=0):
    lst, n = state.actor_states(for_actor)
    return [entry['change'] for entry in lst[after_seq:n]]


def get_missing_deps(state):
    """Unmet dependencies of the buffered changes (op_set.js:347-358)."""
    missing = {}
    for change in state.queue:
        deps = dict(change['deps'])
        deps[change['actor']] = change['seq'] - 1
        for a, s in deps.items():
            if state.clock.get(a, 0) < s:
                missing[a] = max(s, missing.get(a, 0))
    return missing


def merge(local, remote, kernel=None, options=None):
    """Pull changes present in `remote` but not `local`
    (backend/index.js:240-243)."""
    changes = get_missing_changes(remote, local.clock)
    return apply_changes(local, changes, kernel=kernel, options=options)


# camelCase aliases (reference API parity)
applyChanges = apply_changes
applyChangesBatch = apply_changes_batch
applyLocalChange = apply_local_change
getPatch = get_patch
getMissingChanges = get_missing_changes
getChangesForActor = get_changes_for_actor
getMissingDeps = get_missing_deps
