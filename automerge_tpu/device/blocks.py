"""Columnar change/patch blocks: the bulk path of the change protocol.

The reference's wire protocol is per-change JSON (INTERNALS.md:142-146);
applying C changes costs O(C) JS object churn. This module defines the
same protocol messages in struct-of-arrays form for the bulk path — a
:class:`ChangeBlock` is a batch of changes across MANY documents encoded
as dense integer columns + interning tables, and a :class:`PatchBlock`
is the corresponding batch of patches. The two encodings are losslessly
interconvertible (:meth:`ChangeBlock.from_changes` /
:meth:`ChangeBlock.to_changes`, :meth:`PatchBlock.to_patches`), so block
users and dict users interoperate change-for-change.

:class:`BlockStore` is the struct-of-arrays document store of SURVEY §7:
per-field surviving entries as flat arrays (doc-major, field-grouped),
vector clocks as sorted columnar (doc, actor, seq) rows, per-change
transitive dependency closures as CSR. :func:`apply_block` is
`applyChanges` for the bulk path: causal admission as vectorized
fixed-point waves (the batch analogue of applyQueuedOps,
op_set.js:267-283), ONE device kernel call resolving every touched field
of every document (:mod:`.merge`), vectorized unpack back into the store,
patches out. The only Python-level loops run over *waves* (the longest
causal chain in the batch) and over queued/rare cross-block dependency
rows — every per-op computation is a numpy array pass, so a million-op
block packs in tens of milliseconds instead of tens of seconds.

Scope: flat map documents (set/del on root fields) — the DocSet bulk
merge shape of BASELINE config 5. Nested objects, links and sequences
take the per-document path (:mod:`.backend`), which speaks the same
change/patch protocol. A change carrying TWO assignments to the same key
(which the reference frontend never emits — `ensureSingleAssignment`,
frontend/index.js:46) matches the oracle: both survive, the first op
wins and the later ones surface as self-conflicts. Duplicate deliveries
are verified against the retained change bodies and an inconsistent
reuse of a seq number raises, exactly like the oracle (op_set.js:243-248);
with retention off or a truncated log the duplicate is dropped unverified.
"""

import bisect as _bisect
import json as _json

import numpy as np
import jax
import jax.numpy as jnp

from ..common import ROOT_ID
from ..utils.metrics import metrics
from . import engine as _engine

_SET, _DEL = 0, 1
_ACTION_NAMES = {'set': _SET, 'del': _DEL}
_ACTION_CODES = {v: k for k, v in _ACTION_NAMES.items()}

# general-block action codes (superset; flat blocks only ever carry 0/1)
_INS, _LINK, _MAKE_MAP, _MAKE_LIST, _MAKE_TEXT = 2, 3, 4, 5, 6
_GEN_ACTION_NAMES = {'set': _SET, 'del': _DEL, 'ins': _INS, 'link': _LINK,
                     'makeMap': _MAKE_MAP, 'makeList': _MAKE_LIST,
                     'makeText': _MAKE_TEXT}
_GEN_ACTION_CODES = {v: k for k, v in _GEN_ACTION_NAMES.items()}
# key kinds for general blocks
_KEY_STR, _KEY_ELEM, _KEY_HEAD, _KEY_NONE = 0, 1, 2, 3

_SEQ_BITS = 20    # seq numbers < 2^20 per actor (assert-guarded)


def _intern(table, index, item):
    """Intern one string/value into (list, id-dict); returns its id."""
    i = index.get(item)
    if i is None:
        i = len(table)
        index[item] = i
        table.append(item)
    return i


_MISSING = object()


class HorizonTruncated(ValueError):
    """A peer's clock predates this store's compaction horizon for a
    document: the change bodies it needs were folded into the per-doc
    state snapshot and no longer exist as history. The sync layer
    answers with a ``'state'`` message (the snapshot + the retained
    tail) instead of a change replay; callers that cannot ship state
    surface this as the clear serve error it is."""

    def __init__(self, doc, message=None):
        super().__init__(
            message or
            f'history of doc {doc} at or behind the compaction '
            f'horizon was folded into its state snapshot; serve the '
            f'peer a state bootstrap')
        self.doc = doc


def _wire_entry_bytes(entry):
    """Resident byte size of one encode-cache entry: v1 entries are
    plain JSON bytes, v2 entries are ``(body, lits)`` columnar
    pairs."""
    if isinstance(entry, (bytes, bytearray)):
        return len(entry)
    body, lits = entry
    return len(body) + sum(len(l) for l in lits)


def change_hash(change):
    """Canonical 64-bit content hash of one reference-format change
    dict — the unit the per-doc state digest XOR-folds. Hashing the
    sorted-key compact JSON makes the value independent of dict
    ordering and of which wire path delivered the change (the dict
    protocol, the columnar blob and a journal replay all reconstruct
    the same canonical form), so two replicas holding the same change
    content always agree — and an "evil twin" (same ``(actor, seq)``,
    different ops) never does."""
    import hashlib
    import json
    payload = json.dumps(change, sort_keys=True,
                         separators=(',', ':'), default=str)
    return int.from_bytes(
        hashlib.blake2b(payload.encode('utf-8'),
                        digest_size=8).digest(), 'big')


class LazyValues:
    """Op values as byte spans into a wire buffer, JSON-decoded on first
    access (the native wire codec never parses values — most are never
    materialized on the bulk path). A negative start marks a null value
    (a set op without a "value" member, matching the dict edge's
    ``op.get('value')``)."""

    __slots__ = ('_buf', '_starts', '_ends', '_cache')

    def __init__(self, buf, starts, ends):
        self._buf = buf
        self._starts = starts
        self._ends = ends
        self._cache = {}

    def __len__(self):
        return len(self._starts)

    def __getitem__(self, i):
        n = len(self._starts)
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(i)
        v = self._cache.get(i, _MISSING)
        if v is _MISSING:
            s = self._starts[i]
            v = None if s < 0 else self._decode(
                self._buf[s:self._ends[i]])
            self._cache[i] = v
        return v

    def _decode(self, raw):
        return _json.loads(raw.decode('utf-8'))

    def __iter__(self):
        for i in range(len(self._starts)):
            yield self[i]

    def compacted(self):
        """A copy whose buffer holds ONLY the value bytes — retaining a
        segment must not pin the whole wire message in memory."""
        keep = self._starts >= 0
        sizes = np.where(keep, self._ends - self._starts, 0)
        new_ends = np.cumsum(sizes)
        new_starts = np.where(keep, new_ends - sizes, -1)
        buf = b''.join(
            self._buf[self._starts[i]:self._ends[i]]
            for i in np.flatnonzero(keep))
        return type(self)(buf, new_starts, new_ends)


class TaggedValues(LazyValues):
    """Op values as TAGGED binary spans into a columnar v2 wire
    container (tag byte + payload — see ``wire.py``'s literal tags),
    decoded lazily on first access like their JSON twins. Only the
    composite tag (6) touches a JSON decoder, and only when such a
    value is actually materialized — the v2 apply path itself is
    JSON-free."""

    __slots__ = ()

    def _decode(self, raw):
        from .. import wire as _wire
        return _wire.decode_tagged_literal(raw)


class ValueTable:
    """The store's value store: plain appended values plus lazily-decoded
    wire segments, indexable in append order. ``extend`` of a
    :class:`LazyValues` keeps it as a segment (compacted — no decoding,
    no pinning of the full wire buffer); everything else lands in plain
    list segments."""

    __slots__ = ('_segs', '_offsets', '_len')

    def __init__(self):
        self._segs = []
        self._offsets = [0]
        self._len = 0

    def __len__(self):
        return self._len

    def extend(self, items):
        """Append ``items`` as one segment.

        OWNERSHIP TRANSFER: a plain ``list`` argument is stored as a
        shared segment WITHOUT copying — the caller must not mutate it
        afterwards (block value tables are immutable once built; a
        million-value block would otherwise pay a full list copy per
        apply). Pass any other iterable to get a private copy.
        """
        if isinstance(items, LazyValues):
            items = items.compacted()
        elif isinstance(items, ValueTable):
            for seg in items._segs:
                self.extend(seg)
            return
        elif type(items) is not list:
            items = list(items)
        if not len(items):
            return
        self._segs.append(items)
        self._len += len(items)
        self._offsets.append(self._len)

    def __getitem__(self, i):
        if i < 0:
            i += self._len
        if not 0 <= i < self._len:
            raise IndexError(i)
        seg = _bisect.bisect_right(self._offsets, i) - 1
        return self._segs[seg][i - self._offsets[seg]]

    def take(self, idx):
        """Values at `idx` (int array; -1 -> None) as a list — ONE
        vectorized segment search instead of a bisect per item (the
        diff-emission hot path reads tens of thousands per patch)."""
        idx = np.asarray(idx, np.int64)
        offs = np.asarray(self._offsets, np.int64)
        segs = np.searchsorted(offs, np.maximum(idx, 0),
                               side='right') - 1
        within = np.maximum(idx, 0) - offs[segs]
        stabs = self._segs
        return [None if i < 0 else stabs[s][w]
                for i, s, w in zip(idx.tolist(), segs.tolist(),
                                   within.tolist())]

    def __iter__(self):
        for seg in self._segs:
            yield from seg

    def _mark(self):
        """Opaque rollback token (see general._Txn)."""
        return (len(self._segs), self._len)

    def _restore(self, mark):
        n_segs, n_len = mark
        del self._segs[n_segs:]
        del self._offsets[n_segs + 1:]
        self._len = n_len


def check_block_ranges(store, block):
    """Composite-key range guards shared by every block consumer."""
    if block.n_docs != store.n_docs:
        raise ValueError(
            f'block is for {block.n_docs} docs, store holds {store.n_docs}')
    if block.n_changes:
        if int(block.seq.max()) >= (1 << _SEQ_BITS):
            raise ValueError(f'seq numbers must be < 2^{_SEQ_BITS}')
        if int(block.doc.max()) >= block.n_docs or \
                int(block.doc.min()) < 0:
            raise ValueError(
                f'block doc index out of range for {block.n_docs} docs')
    if store.n_docs >= (1 << 22):
        raise ValueError('store exceeds the 4M-document key space')


class ChangeBlock:
    """A batch of wire changes across documents, as columns.

    Change columns (length C, non-decreasing ``doc``):
      doc     int32 — document index within the batch
      actor   int32 — index into ``actors``
      seq     int32
      dep_ptr int32[C+1] — CSR over direct deps (dep_actor, dep_seq)
    Op columns (length N, CSR over changes via ``op_ptr``):
      action  int8  — 0 set, 1 del
      key     int32 — index into ``keys``
      value   int32 — index into ``values`` (-1 for del)
    Tables: ``actors`` (strings), ``keys`` (strings), ``values`` (host
    JSON values; never shipped to the device — ops reference them by row
    and winners map back on unpack).
    """

    __slots__ = ('n_docs', 'doc', 'actor', 'seq', 'dep_ptr', 'dep_actor',
                 'dep_seq', 'op_ptr', 'action', 'key', 'value',
                 'actors', 'keys', 'values', '_dup_keys',
                 'obj', 'key_kind', 'key_elem', 'elem', 'objs',
                 '_wire_lits')

    def __init__(self, n_docs, doc, actor, seq, dep_ptr, dep_actor, dep_seq,
                 op_ptr, action, key, value, actors, keys, values,
                 dup_keys=None, obj=None, key_kind=None, key_elem=None,
                 elem=None, objs=None):
        if len(doc) and (np.diff(doc) < 0).any():
            order = np.argsort(doc, kind='stable')
            dep_ptr, (dep_actor, dep_seq) = _csr_take(
                dep_ptr, order, (dep_actor, dep_seq))
            if obj is not None:
                op_ptr2, (obj, key_kind, key_elem, elem) = _csr_take(
                    op_ptr, order, (obj, key_kind, key_elem, elem))
            op_ptr, (action, key, value) = _csr_take(
                op_ptr, order, (action, key, value))
            doc, actor, seq = doc[order], actor[order], seq[order]
        self.n_docs = n_docs
        self.doc = doc
        self.actor = actor
        self.seq = seq
        self.dep_ptr = dep_ptr
        self.dep_actor = dep_actor
        self.dep_seq = dep_seq
        self.op_ptr = op_ptr
        self.action = action
        self.key = key
        self.value = value
        self.actors = actors
        self.keys = keys
        self.values = values
        self._dup_keys = dup_keys
        # general-op columns (None on flat root-map blocks): per-op
        # object row (into ``objs``), key kind (_KEY_*), elemId counter
        # for _KEY_ELEM keys (the actor rides in ``key``), ins counter
        self.obj = obj
        self.key_kind = key_kind
        self.key_elem = key_elem
        self.elem = elem
        self.objs = objs
        # pre-escaped JSON string-literal tables for the wire emitter
        # (wire.encode_change_rows), built lazily once per block
        self._wire_lits = None

    def is_general(self):
        """True when the block carries the general op schema (sequences,
        nested objects, links) — such blocks apply through
        :mod:`automerge_tpu.device.general`, not the flat-map paths."""
        return self.obj is not None

    def has_dup_keys(self):
        """True if any change assigns the same field more than once —
        the self-conflict shape the reference frontend never emits
        (ensureSingleAssignment, frontend/index.js:46) but hand-built
        changes can. Computed lazily, cached; the wire edges set it
        during their walk."""
        if self._dup_keys is None:
            if self.n_ops == 0:
                self._dup_keys = False
            else:
                op_change = np.repeat(
                    np.arange(self.n_changes, dtype=np.int64),
                    np.diff(self.op_ptr))
                if self.obj is None:
                    cell = op_change * max(len(self.keys), 1) + self.key
                    self._dup_keys = bool(
                        len(np.unique(cell)) < len(cell))
                else:
                    # general schema: field identity is (change, obj,
                    # kind, key, key_elem), assignment ops only — make
                    # and ins ops never collide
                    assign = (self.action <= _DEL) | \
                        (self.action == _LINK)
                    if not assign.any():
                        self._dup_keys = False
                    else:
                        cols = np.stack([
                            op_change[assign],
                            self.obj[assign].astype(np.int64),
                            self.key_kind[assign].astype(np.int64),
                            self.key[assign].astype(np.int64),
                            self.key_elem[assign].astype(np.int64)])
                        uniq = np.unique(cols, axis=1)
                        self._dup_keys = bool(
                            uniq.shape[1] < cols.shape[1])
        return self._dup_keys

    @property
    def n_changes(self):
        return len(self.doc)

    @property
    def n_ops(self):
        return len(self.action)

    @classmethod
    def from_changes(cls, changes_per_doc, n_docs=None):
        """Encode per-document dict changes (the JSON wire format) into one
        block. O(total ops) Python — the compatibility edge, not the bulk
        path. ``n_docs`` widens the block's document space beyond
        ``len(changes_per_doc)`` (a sparse tick touching few documents of
        a large store need not materialize one list per document)."""
        actors, actor_of = [], {}
        keys, key_of = [], {}
        values = []
        doc, actor, seq = [], [], []
        dep_ptr, dep_actor, dep_seq = [0], [], []
        op_ptr, action, key, value = [0], [], [], []

        def check_i32(v, what):
            # match the native codec: out-of-range wire counters are a
            # ValueError, never a silent int32 wraparound
            if not isinstance(v, int) or isinstance(v, bool) or \
                    not 0 <= v <= 0x7FFFFFFF:
                raise ValueError(
                    f'{what} {v!r} out of range (must fit int32)')
            return v

        dup_keys = False
        for d, changes in enumerate(changes_per_doc):
            for change in changes:
                if 'deps' not in change:
                    raise ValueError('change requires actor, seq and deps')
                doc.append(d)
                actor.append(_intern(actors, actor_of, change['actor']))
                seq.append(check_i32(change['seq'], 'change seq'))
                # dep order is semantic: the reference folds deps in dict
                # order and later entries can clobber earlier transitive
                # seqs (transitiveDeps, op_set.js:29-37)
                for da, ds in change['deps'].items():
                    dep_actor.append(_intern(actors, actor_of, da))
                    dep_seq.append(check_i32(ds, 'dep seq'))
                dep_ptr.append(len(dep_actor))
                change_keys = set()
                for op in change['ops']:
                    if op['action'] not in _ACTION_NAMES:
                        raise ValueError(
                            f"block path supports set/del ops only, got "
                            f"{op['action']!r} (use the per-document path)")
                    if op['obj'] != ROOT_ID:
                        raise ValueError(
                            'block path supports root-map fields only '
                            '(use the per-document path)')
                    action.append(_ACTION_NAMES[op['action']])
                    k = _intern(keys, key_of, op['key'])
                    if k in change_keys:
                        dup_keys = True
                    change_keys.add(k)
                    key.append(k)
                    if op['action'] == 'set':
                        value.append(len(values))
                        values.append(op.get('value'))
                    else:
                        value.append(-1)
                op_ptr.append(len(action))

        if n_docs is None:
            n_docs = len(changes_per_doc)
        elif n_docs < len(changes_per_doc):
            raise ValueError(
                f'n_docs={n_docs} < {len(changes_per_doc)} change lists')
        return cls(n_docs,
                   np.asarray(doc, np.int32), np.asarray(actor, np.int32),
                   np.asarray(seq, np.int32),
                   np.asarray(dep_ptr, np.int32),
                   np.asarray(dep_actor, np.int32),
                   np.asarray(dep_seq, np.int32),
                   np.asarray(op_ptr, np.int32),
                   np.asarray(action, np.int8), np.asarray(key, np.int32),
                   np.asarray(value, np.int32), actors, keys, values,
                   dup_keys=dup_keys)

    def to_changes(self):
        """Decode back to per-document dict change lists (lossless)."""
        out = [[] for _ in range(self.n_docs)]
        for c in range(self.n_changes):
            out[self.doc[c]].append(self.change_dict(c))
        return out

    def change_dict(self, c):
        """One change row as a reference-format dict."""
        deps = {self.actors[self.dep_actor[j]]: int(self.dep_seq[j])
                for j in range(self.dep_ptr[c], self.dep_ptr[c + 1])}
        ops = []
        if self.obj is None:                       # flat root-map block
            for j in range(self.op_ptr[c], self.op_ptr[c + 1]):
                op = {'action': _ACTION_CODES[int(self.action[j])],
                      'obj': ROOT_ID, 'key': self.keys[self.key[j]]}
                if self.action[j] == _SET:
                    op['value'] = self.values[self.value[j]]
                ops.append(op)
        else:
            for j in range(self.op_ptr[c], self.op_ptr[c + 1]):
                a = int(self.action[j])
                op = {'action': _GEN_ACTION_CODES[a],
                      'obj': self.objs[self.obj[j]]}
                kind = int(self.key_kind[j])
                if kind == _KEY_STR:
                    op['key'] = self.keys[self.key[j]]
                elif kind == _KEY_ELEM:
                    op['key'] = (f'{self.actors[self.key[j]]}:'
                                 f'{int(self.key_elem[j])}')
                elif kind == _KEY_HEAD:
                    op['key'] = '_head'
                if a == _INS:
                    op['elem'] = int(self.elem[j])
                if a in (_SET, _LINK):
                    op['value'] = self.values[self.value[j]]
                ops.append(op)
        return {'actor': self.actors[self.actor[c]],
                'seq': int(self.seq[c]), 'deps': deps, 'ops': ops}


def _csr_take(ptr, rows, payloads):
    """Gather CSR rows (returns new ptr + payload arrays)."""
    counts = np.diff(ptr)[rows]
    new_ptr = np.zeros(len(rows) + 1, np.int32)
    np.cumsum(counts, out=new_ptr[1:])
    idx = _span_indices(ptr[rows], counts)
    return new_ptr, tuple(p[idx] for p in payloads)


def _span_indices(starts, counts):
    """Concatenated [s, s+c) ranges, vectorized."""
    total = int(counts.sum())
    if total == 0:
        return np.zeros(0, np.int64)
    ends = np.cumsum(counts)
    pos = np.arange(total) - np.repeat(ends - counts, counts)
    return np.repeat(starts.astype(np.int64), counts) + pos


class PatchBlock:
    """A batch of patches (one per document), as columns.

    Field columns (length F, doc-major, sorted by (doc, key id)):
    ``f_doc``/``f_key``/``f_action`` (0 set, 1 remove)/``f_value`` (store
    value row, -1 for remove) plus winner actor ``f_actor``. The surviving
    non-winner entries (the conflicts, op_set.js:95-103) live in the
    entry columns ``s_field``/``s_actor``/``s_value``, grouped by field
    via ``s_ptr``. ``diffs``/``to_patches`` materialize reference-format
    dicts per document."""

    __slots__ = ('n_docs', 'f_ptr', 'f_doc', 'f_key', 'f_action', 'f_value',
                 'f_actor', 's_ptr', 's_actor', 's_value',
                 'keys', 'values', 'actors', 'c_doc', 'c_actor', 'c_seq')

    def __init__(self, n_docs, f_ptr, f_doc, f_key, f_action, f_value,
                 f_actor, s_ptr, s_actor, s_value, keys, values, actors,
                 c_doc, c_actor, c_seq):
        self.n_docs = n_docs
        self.f_ptr = f_ptr
        self.f_doc = f_doc
        self.f_key = f_key
        self.f_action = f_action
        self.f_value = f_value
        self.f_actor = f_actor
        self.s_ptr = s_ptr
        self.s_actor = s_actor
        self.s_value = s_value
        self.keys = keys
        self.values = values
        self.actors = actors
        self.c_doc = c_doc      # clock snapshot rows (doc-sorted)
        self.c_actor = c_actor
        self.c_seq = c_seq

    @property
    def n_fields(self):
        return len(self.f_doc)

    def clock_of(self, d):
        lo, hi = np.searchsorted(self.c_doc, [d, d + 1])
        return {self.actors[self.c_actor[j]]: int(self.c_seq[j])
                for j in range(lo, hi)}

    def diffs(self, d):
        """Reference-format diff list for one document."""
        out = []
        for f in range(self.f_ptr[d], self.f_ptr[d + 1]):
            key = self.keys[self.f_key[f]]
            if self.f_action[f] == _DEL:
                out.append({'action': 'remove', 'type': 'map',
                            'obj': ROOT_ID, 'key': key, 'path': []})
                continue
            edit = {'action': 'set', 'type': 'map', 'obj': ROOT_ID,
                    'key': key, 'path': [],
                    'value': self.values[self.f_value[f]]}
            lo, hi = self.s_ptr[f], self.s_ptr[f + 1]
            losers = [(self.actors[self.s_actor[j]],
                       self.values[self.s_value[j]]
                       if self.s_value[j] >= 0 else None)
                      for j in range(lo, hi)]
            # STABLE actor-descending (op_set.js:211): rank ties (self-
            # conflicts from one change) keep their op order
            losers.sort(key=lambda t: t[0], reverse=True)
            if losers:
                edit['conflicts'] = [{'actor': a, 'value': v}
                                     for a, v in losers]
            out.append(edit)
        return out

    def patch(self, d):
        clock = self.clock_of(d)
        return {'clock': clock, 'deps': dict(clock), 'canUndo': False,
                'canRedo': False, 'diffs': self.diffs(d)}

    def to_patches(self):
        return [self.patch(d) for d in range(self.n_docs)]


class BlockStore:
    """Struct-of-arrays state for a batch of flat map documents.

    The SURVEY §7 store. Entry columns are doc-major and field-grouped
    (sorted by compact field key), so prior entries of touched fields
    gather with boolean masks — no per-apply sorting of untouched state.
    Mutated in place by :func:`apply_block`; durability comes from the
    change log, exactly like the reference's save().
    """

    def __init__(self, n_docs, retain_log=True):
        self.n_docs = n_docs
        # Retained ChangeBlocks (shared references, one append per apply)
        # back get_missing_changes — the archival role the reference's
        # opSet.history plays, with the same grows-with-history contract.
        # retain_log=False drops retention: peers can then only be served
        # via snapshots.
        self.retain_log = retain_log
        self.actors = []                      # store actor table (strings)
        self.actor_of = {}
        self.keys = []                        # store key table (strings)
        self.key_of = {}
        self.values = ValueTable()            # host value store
        z32 = np.zeros(0, np.int32)
        # survivor entries (unordered; membership via compact field keys):
        self.e_doc = z32
        self.e_key = z32
        self.e_actor = z32                    # store actor id
        self.e_seq = z32
        self.e_value = z32                    # store value row (-1: none)
        self.e_change = z32                   # change-log row (closure ref)
        # vector clocks: rows sorted by (doc << 32 | actor); c_pure marks
        # chains whose transitive closure is OWN-ONLY ({actor: seq-1}) —
        # such closures are implicit (every consumer reconstructs the own
        # entry), so pure chains skip the closure fold and store zero
        # log entries. Purity is an optimization hint: a False for an
        # actually-pure chain only costs a no-op gather, never
        # correctness.
        self.c_doc = z32
        self.c_actor = z32
        self.c_seq = z32
        self.c_pure = np.zeros(0, bool)
        # applied-change log (append order) + closure CSR per change;
        # l_order keeps a sorted view over l_key for lookups
        self.l_key = np.zeros(0, np.int64)
        self.l_order = np.zeros(0, np.int64)
        self._l_sorted = np.zeros(0, np.int64)   # cache: l_key[l_order]
        self._l_pending = []    # appended-but-unmerged (keys, base) chunks
        self.l_dep_ptr = np.zeros(1, np.int32)
        self.l_dep_actor = z32
        self.l_dep_seq = z32
        self.queue = []                       # [(doc, change dict)] buffered
        # retained changes: [(block, rows, docs)] per apply — rows are
        # admitted block rows sorted by doc (admission order within each
        # doc), docs the parallel doc column; blocks are shared refs
        self.retained = []
        # per-change wire-encode cache over the retained log:
        # (doc, actor, seq) -> compact JSON bytes. Changes are immutable
        # once applied (an inconsistent seq reuse raises at admission),
        # so entries are never invalidated; they are built lazily at
        # serve time (get_missing_changes_wire), which means every key
        # references a COMMITTED change — a rolled-back apply can never
        # leave a stale body here. With N peers each change encodes
        # once and fans out N times; retransmits reuse the same bytes.
        # Three formats share the contract: v1 entries are compact JSON
        # bytes, v2 entries (_wire_cache_v2) are columnar
        # ``(body, lits)`` pairs, v3 entries (_wire_cache_v3) the same
        # shape with RLE bodies (the session-table remap happens at
        # message assembly, so the cached encoding stays session-
        # independent and shareable across peers) — a mixed-version
        # fleet encodes each change at most once PER FORMAT.
        self._wire_cache = {}
        self._wire_cache_v2 = {}
        self._wire_cache_v3 = {}
        self._wire_cache_bytes = 0
        self.wire_cache_hits = 0
        self.wire_cache_misses = 0
        # live wire-v3 session tables registered against this store
        # (weakrefs: a closed connection's table just drops) — cleared
        # alongside the wire caches so no session-table remap state
        # survives a cache invalidation
        self._wire_sessions = []
        self.log_truncated = False            # True after snapshot resume
        self._str_rank_cache = (0, None, None)
        # per-doc state digest: XOR fold of the content hashes of every
        # ADMITTED change (order-independent — both replicas of a
        # converged doc hold the same change set, so equal clocks must
        # mean equal digests; a mismatch is silent divergence). The
        # fold is LAZY: admission appends (block, rows, docs) refs here
        # (one list append per apply — nothing on the hot path), and
        # the first digest read folds them in, so the amortized cost is
        # one canonical hash per change, paid off the apply path like
        # the wire-encode cache.
        self._digest = np.zeros(n_docs, np.uint64)
        self._digest_pending = []
        # False when the digest history is unreconstructable (a resumed
        # snapshot that predates the digest field) — such a store must
        # not advertise digests (a zero digest vs a real one would be a
        # false divergence alarm)
        self._digest_valid = True
        # compaction horizon (tiered doc storage): doc index ->
        # {'clock': {actor: seq}, 'digest': int|None, 'state': bytes}.
        # History at or behind the horizon clock has been folded into
        # the doc's encoded state snapshot ('state' — the payload the
        # sync layer ships to peers whose clock predates the horizon);
        # the retained log holds only the TAIL (changes admitted after
        # the fold). Maintained by automerge_tpu.compaction.
        self.horizon = {}

    # -- interning / lookup helpers -----------------------------------------

    def intern(self, items, table, index):
        out = np.empty(len(items), np.int32)
        for i, s in enumerate(items):
            out[i] = _intern(table, index, s)
        return out

    def actor_str_ranks(self):
        """store actor id -> rank in string order (cached per table size).
        Conflict resolution sorts by actor string (op_set.js:211); device
        ranks must preserve that order."""
        n = len(self.actors)
        if self._str_rank_cache[0] != n:
            order = np.argsort(np.asarray(self.actors, dtype=object))
            rank = np.empty(n, np.int64)
            rank[order] = np.arange(n)
            self._str_rank_cache = (n, rank, order.astype(np.int32))
        return self._str_rank_cache[1]

    def actor_by_rank(self):
        self.actor_str_ranks()
        return self._str_rank_cache[2]       # string rank -> store actor id

    def change_key(self, doc, actor, seq):
        """Composite int64 key for (doc, actor, seq) rows."""
        assert len(self.actors) < (1 << 21), 'actor table exceeds key space'
        return (((doc.astype(np.int64) << 21) | actor) << _SEQ_BITS) | seq

    def _clock_table(self):
        """The packed (doc << 32 | actor) clock key table, memoized by
        column ref identity: the hit path of :meth:`clock_merge`
        scatters seqs in place (keys unchanged), so a warm tick reuses
        one packing across lookup/merge/purity instead of repacking
        the O(clock) table three times."""
        t = getattr(self, '_c_table', None)
        if t is not None and t[0] is self.c_doc \
                and t[1] is self.c_actor:
            return t[2]
        table = (self.c_doc.astype(np.int64) << 32) | self.c_actor
        self._c_table = (self.c_doc, self.c_actor, table)
        return table

    def clock_lookup(self, doc, actor):
        """Applied seq per (doc, actor) pair — vectorized."""
        if len(self.c_doc) == 0 or len(doc) == 0:
            return np.zeros(len(doc), np.int32)
        table = self._clock_table()
        probe = (doc.astype(np.int64) << 32) | actor
        pos = np.minimum(np.searchsorted(table, probe), len(table) - 1)
        return np.where(table[pos] == probe, self.c_seq[pos], 0) \
            .astype(np.int32)

    def clock_merge(self, doc, actor, seq, pure=None):
        """Scatter-max (doc, actor, seq) rows into the sorted clock
        table; `pure` carries the chain-purity flag of each row (the
        max-seq row's purity wins per key; None = impure)."""
        if len(doc) == 0:
            return
        if pure is None:
            pure = np.zeros(len(doc), bool)
        key_new = (doc.astype(np.int64) << 32) | actor
        order = np.argsort(key_new, kind='stable')
        key_new, seq, pure = key_new[order], seq[order], pure[order]
        # max seq per distinct key (segmented max over equal-key runs);
        # purity rides in the low bit so the max picks the winner's flag
        seg_start = np.concatenate([[True], key_new[1:] != key_new[:-1]])
        packed = (seq.astype(np.int64) << 1) | pure
        seg_max = np.maximum.reduceat(packed, np.flatnonzero(seg_start))
        key_new = key_new[seg_start]
        seq = (seg_max >> 1).astype(np.int32)
        pure = (seg_max & 1).astype(bool)
        table = self._clock_table()
        pos = np.minimum(np.searchsorted(table, key_new),
                         max(len(table) - 1, 0))
        hit = (table[pos] == key_new) if len(table) else \
            np.zeros(len(key_new), bool)
        if hit.any():
            sharers = getattr(self, '_c_sharers', None)
            if sharers:
                # a live patch snapshot aliases c_seq — copy before the
                # in-place scatter so its apply-time clock stays frozen
                self.c_seq = self.c_seq.copy()
                sharers.clear()
            jr = getattr(self, '_c_journal', None)
            if jr is not None:
                # O(delta) rollback record of the in-place scatter (the
                # _Txn undoes these instead of copying the whole table)
                ph = pos[hit]
                jr.append((ph, self.c_seq[ph].copy(),
                           self.c_pure[ph].copy(),
                           self.c_seq, self.c_pure))
            adv = seq[hit] > self.c_seq[pos[hit]]
            np.maximum.at(self.c_seq, pos[hit], seq[hit])
            self.c_pure[pos[hit][adv]] = pure[hit][adv]
        if (~hit).any():
            all_key = np.concatenate([table, key_new[~hit]])
            all_seq = np.concatenate([self.c_seq, seq[~hit]])
            all_pure = np.concatenate([self.c_pure, pure[~hit]])
            order = np.argsort(all_key, kind='stable')
            all_key, all_seq = all_key[order], all_seq[order]
            self.c_doc = (all_key >> 32).astype(np.int32)
            self.c_actor = (all_key & 0xFFFFFFFF).astype(np.int32)
            self.c_seq = all_seq.astype(np.int32)
            self.c_pure = all_pure[order]
            # the replaced arrays are frozen now — snapshots aliasing
            # them need no copy-on-write protection anymore
            sh = getattr(self, '_c_sharers', None)
            if sh:
                sh.clear()

    def clock_pure_lookup(self, doc, actor):
        """Chain purity per (doc, actor) pair (False on miss)."""
        if len(self.c_doc) == 0 or len(doc) == 0:
            return np.zeros(len(doc), bool)
        table = self._clock_table()
        probe = (doc.astype(np.int64) << 32) | actor
        pos = np.minimum(np.searchsorted(table, probe), len(table) - 1)
        return np.where(table[pos] == probe, self.c_pure[pos], False)

    def clock_of(self, d):
        lo, hi = np.searchsorted(self.c_doc, [d, d + 1])
        return {self.actors[self.c_actor[j]]: int(self.c_seq[j])
                for j in range(lo, hi) if self.c_seq[j] > 0}

    def doc_fields(self, d):
        """{key: [(actor, value), ...] winner first (actor-descending)}
        for one document — the test/inspection surface."""
        out = {}
        for j in np.flatnonzero(self.e_doc == d):
            key = self.keys[self.e_key[j]]
            out.setdefault(key, []).append(
                (self.actors[self.e_actor[j]],
                 self.values[self.e_value[j]] if self.e_value[j] >= 0
                 else None))
        return {k: sorted(v, key=lambda t: t[0], reverse=True)
                for k, v in out.items()}

    def log_sorted_keys(self):
        """l_key in sorted order. The sorted view merges lazily: appends
        park in ``_l_pending`` and fold in here, on DEMAND — pure chain
        streams never consult the log during admission, so they skip the
        O(log-size) merge every apply."""
        if self._l_pending:
            pend_keys = np.concatenate(
                [k for k, b in self._l_pending])
            pend_rows = np.concatenate(
                [b + np.arange(len(k), dtype=np.int64)
                 for k, b in self._l_pending])
            self._l_pending = []
            order_p = np.argsort(pend_keys, kind='stable')
            pend_sorted = pend_keys[order_p]
            if len(self._l_sorted) != len(self.l_order):
                self._l_sorted = self.l_key[self.l_order]
            pos = np.searchsorted(self._l_sorted, pend_sorted)
            self.l_order = np.insert(self.l_order, pos,
                                     pend_rows[order_p])
            self._l_sorted = np.insert(self._l_sorted, pos, pend_sorted)
        elif len(self._l_sorted) != len(self.l_order):
            # stale cache (e.g. a snapshot load set l_order directly)
            self._l_sorted = self.l_key[self.l_order]
        return self._l_sorted

    def merge_queued_into(self, block):
        """Fold this store's buffered queue into an incoming block (the
        general store overrides with its own encoder)."""
        return _merge_queued(block, self.queue)

    def get_missing_deps(self):
        """Unmet deps of buffered changes (op_set.js:347-358)."""
        missing = {}
        for d, change in self.queue:
            deps = dict(change['deps'])
            deps[change['actor']] = change['seq'] - 1
            clock = self.clock_of(d)
            for a, s in deps.items():
                if clock.get(a, 0) < s:
                    missing[a] = max(s, missing.get(a, 0))
        return missing

    def _missing_retained(self, d, have_deps):
        """Retained-log rows of document `d` a peer with clock
        `have_deps` lacks, in admission (causal) order: a list of
        ``(block, row, actor_str, seq)``. Shared by the dict serve path
        (:meth:`get_missing_changes`) and the wire serve path
        (:meth:`get_missing_changes_wire`); raises the same
        retention/truncation errors for both."""
        clock = self.clock_of(d)
        if all(have_deps.get(a, 0) >= s for a, s in clock.items()):
            return []
        rec = self.horizon.get(d)
        if rec is not None and \
                not all(have_deps.get(a, 0) >= s
                        for a, s in rec['clock'].items()):
            # the peer predates the fold point: the bodies it needs
            # were folded into the state snapshot — the sync layer
            # ships that (plus the tail below) instead of history
            raise HorizonTruncated(d)
        if not self.retain_log and not self.log_truncated:
            raise ValueError(
                'change-log retention is disabled on this store '
                '(retain_log=False); serve lagging peers a snapshot')
        out = []
        for block, rows, docs in self.retained:
            lo, hi = np.searchsorted(docs, [d, d + 1])
            for c in rows[lo:hi]:
                c = int(c)
                actor = block.actors[block.actor[c]]
                seq = int(block.seq[c])
                if seq > have_deps.get(actor, 0):
                    out.append((block, c, actor, seq))
        if self.log_truncated:
            # per actor the retained seqs run (resume point, clock]; a
            # peer needing anything below that range cannot be served
            min_seq = {}
            for _, _, a, s in out:
                min_seq[a] = min(min_seq.get(a, s), s)
            for a, s in clock.items():
                h = have_deps.get(a, 0)
                if h < s and (a not in min_seq or h + 1 < min_seq[a]):
                    raise ValueError(
                        'change log truncated by a snapshot resume; a '
                        'peer this far behind needs the snapshot or the '
                        'full log')
        return out

    def get_missing_changes(self, d, have_deps):
        """Changes applied to document `d` that a peer with clock
        `have_deps` lacks, in admission (causal) order — the Connection
        primitive for bulk stores (src/connection.js:58-66). The log is
        the retained ChangeBlocks (indexed per doc; converged peers
        short-circuit without touching it); after a snapshot resume it
        only goes back to the resume point (older gaps raise, like the
        per-doc backend)."""
        return [block.change_dict(c) for block, c, _, _
                in self._missing_retained(d, have_deps)]

    def get_missing_changes_wire(self, d, have_deps, version=1):
        """The wire-path twin of :meth:`get_missing_changes`: the same
        missing changes, as their compact wire encodings (one entry
        per change, admission order) served from the per-change encode
        cache — ``version=1`` JSON bytes, ``version=2`` columnar
        ``(body, lits)`` pairs. On a miss the encodings build in one
        batched emit per retained block (native C++ when available)
        and stay cached forever — a fan-out to N peers (or a
        retransmit) re-serves the same bytes with zero re-encode.
        Raises exactly the retention/truncation errors of the dict
        path."""
        blobs, errors = self.get_missing_changes_wire_batch(
            [(d, have_deps)], version=version)
        if d in errors:
            raise errors[d]
        return blobs[d]

    def get_missing_changes_wire_batch(self, wants, all_clocks=None,
                                       version=1):
        """Fleet-grained wire serve: ``wants`` is ``[(doc,
        have_deps)]``; returns ``({doc: [bytes, ...]}, {doc: error})``
        where ``error`` is the retention/truncation ValueError the dict
        path would raise for that doc (the caller's snapshot-fallback
        candidates — other docs still serve). ALL cache misses across
        every requested doc emit in ONE batched pass per retained
        block, so a multi-doc tick pays one native call, not one per
        document. ``all_clocks`` lets a caller that already swept the
        fleet clocks (``clocks_all``) share the pass."""
        sels, errors = {}, {}
        # fleet-grained converged short-circuit: ONE pass over the
        # clock rows replaces a clock_of (searchsorted + dict build)
        # per requested doc — on a steady-state tick most peers are
        # caught up and never reach the retained-log scan
        if all_clocks is None and len(wants) > 16 and \
                hasattr(self, 'clocks_all'):
            all_clocks = self.clocks_all()
        # bulk gather for EMPTY have-clocks (a fresh peer's full sync,
        # the 10k-doc bench shape): every retained row of the wanted
        # docs is missing by definition, so the rows of all such docs
        # gather per retained block in one vectorized pass instead of
        # a clock_of + searchsorted per document. Truncated/unretained
        # logs keep the per-doc path (its errors are per doc).
        fresh = [d for d, have_deps in wants
                 if not have_deps and d not in self.horizon] \
            if len(wants) > 16 and self.retain_log \
            and not self.log_truncated else []
        if fresh:
            for d in fresh:
                sels[d] = []
            want_arr = np.asarray(sorted(fresh), np.int64)
            for block, rows, docs in self.retained:
                lo = np.searchsorted(docs, want_arr)
                hi = np.searchsorted(docs, want_arr + 1)
                pos = _span_indices(lo, hi - lo)
                if not len(pos):
                    continue
                rr = rows[pos]
                dd = np.repeat(want_arr, hi - lo)
                actors = block.actors
                a_ids = block.actor[rr].tolist()
                seqs = block.seq[rr].tolist()
                for d, c, a, s in zip(dd.tolist(), rr.tolist(),
                                      a_ids, seqs):
                    sels[d].append((block, c, actors[a], s))
        for d, have_deps in wants:
            if d in sels:
                continue
            if all_clocks is not None:
                clock = all_clocks.get(d, {})
                if all(have_deps.get(a, 0) >= s
                       for a, s in clock.items()):
                    sels[d] = []
                    continue
            try:
                sels[d] = self._missing_retained(d, have_deps)
            except ValueError as err:
                errors[d] = err
        cache = self._wire_cache if version == 1 else \
            self._wire_cache_v2 if version == 2 else \
            self._wire_cache_v3
        out = {}
        # one cache probe per change: misses record their output slot
        # and are patched in place after the per-block batched emit
        misses = {}        # id(block) -> (block, [(row, key, lst, i)])
        n_total = 0
        for d, sel in sels.items():
            blobs = []
            for block, c, actor, seq in sel:
                key = (d, actor, seq)
                b = cache.get(key)
                if b is None:
                    misses.setdefault(id(block), (block, []))[1] \
                        .append((c, key, blobs, len(blobs)))
                blobs.append(b)
            out[d] = blobs
            n_total += len(blobs)
        n_miss = 0
        if misses:
            from .. import wire as _wire
            encoder = _wire.encode_change_rows if version == 1 \
                else _wire.encode_change_rows_columnar if version == 2 \
                else _wire.encode_change_rows_columnar_v3
            for block, entries in misses.values():
                n_miss += len(entries)
                encoded = encoder(block, [c for c, _, _, _ in entries])
                for (c, key, lst, i), blob in zip(entries, encoded):
                    cache[key] = blob
                    self._wire_cache_bytes += _wire_entry_bytes(blob)
                    lst[i] = blob
            metrics.set_gauge('sync_wire_cache_bytes',
                              self._wire_cache_bytes)
        self.wire_cache_misses += n_miss
        self.wire_cache_hits += n_total - n_miss
        metrics.bump('wire_encode_cache_misses', n_miss)
        metrics.bump('wire_encode_cache_hits', n_total - n_miss)
        return out, errors

    def adopt_wire_cache(self, old_store, drop_docs=()):
        """Carry the per-change encode caches (all three wire formats)
        across a store rebuild (doc eviction), DROPPING the evicted
        docs' entries. Safe under the cache's never-invalidate
        contract: every surviving entry was created at serve time from
        a committed retained change of ``old_store``, and this store
        was rebuilt by re-applying that same retained log — the same
        ``(doc, actor, seq)`` holds the same change body, so the
        cached bytes stay exact. Entries of ``drop_docs`` are released
        with the docs' store rows (an evicted doc that faults back in
        re-encodes on next serve). Live session-table registrations
        carry over too — their remap state is content-addressed, so a
        rebuild never invalidates it, but a clear must still reach
        them."""
        drop = set(int(d) for d in drop_docs)
        kept = {k: v for k, v in old_store._wire_cache.items()
                if k[0] not in drop}
        kept2 = {k: v for k, v in old_store._wire_cache_v2.items()
                 if k[0] not in drop}
        kept3 = {k: v for k, v in old_store._wire_cache_v3.items()
                 if k[0] not in drop}
        self._wire_cache = kept
        self._wire_cache_v2 = kept2
        self._wire_cache_v3 = kept3
        self._wire_cache_bytes = \
            sum(len(v) for v in kept.values()) + \
            sum(_wire_entry_bytes(v) for v in kept2.values()) + \
            sum(_wire_entry_bytes(v) for v in kept3.values())
        self.wire_cache_hits = old_store.wire_cache_hits
        self.wire_cache_misses = old_store.wire_cache_misses
        self._wire_sessions = [ref for ref in old_store._wire_sessions
                               if ref() is not None]
        metrics.set_gauge('sync_wire_cache_bytes',
                          self._wire_cache_bytes)

    def register_wire_session(self, table):
        """Track a live wire-v3 sender session table against this
        store (weakref — a closed connection's table just drops), so
        :meth:`clear_wire_cache` can reset session remap state along
        with the encodings it was built over."""
        import weakref
        self._wire_sessions = [ref for ref in self._wire_sessions
                               if ref() is not None]
        self._wire_sessions.append(weakref.ref(table))

    def clear_wire_cache(self):
        """Drop every cached change encoding (all formats) AND reset
        every registered wire-v3 session table (each mints a fresh
        epoch, so peers simply see a new sid and re-learn defs) — a
        bench/test hook; the caches refill lazily at next serve."""
        self._wire_cache.clear()
        self._wire_cache_v2.clear()
        self._wire_cache_v3.clear()
        self._wire_cache_bytes = 0
        self.wire_cache_hits = self.wire_cache_misses = 0
        for ref in self._wire_sessions:
            table = ref()
            if table is not None:
                table.reset()
        self._wire_sessions = [ref for ref in self._wire_sessions
                               if ref() is not None]
        metrics.set_gauge('sync_wire_cache_bytes', 0)

    # -- per-doc state digests ----------------------------------------------

    def _fold_digests(self):
        """Fold the admission-time pending refs into the digest array.
        The array is replaced (copy-on-fold), never mutated in place,
        so a rollback snapshot holding the pre-fold reference stays
        valid, and concurrent readers see either the old or the new
        fold, never a half-applied one."""
        pending, self._digest_pending = self._digest_pending, []
        if not pending:
            return
        dig = self._digest.copy()
        for block, rows, docs in pending:
            for c, d in zip(rows.tolist(), docs.tolist()):
                dig[d] ^= np.uint64(change_hash(block.change_dict(c)))
        self._digest = dig

    def digest_of(self, d):
        """The incremental state digest of document ``d`` (0 = no
        admitted changes)."""
        self._fold_digests()
        return int(self._digest[d])

    def digests_all(self):
        """The whole digest array (uint64, doc axis) after folding —
        the heartbeat surface reads every doc at once."""
        self._fold_digests()
        return self._digest

    def digest_recompute(self, d):
        """O(doc) from-scratch digest over the retained log — the
        parity oracle for the incremental fold (raises the usual
        retention errors when the log cannot serve the full history).
        On a compacted doc the fold starts from the digest recorded at
        the horizon and covers only the retained tail — the state
        snapshot carries the pre-horizon XOR exactly so this oracle
        keeps working after the bodies are gone."""
        rec = self.horizon.get(d)
        if rec is not None:
            if rec.get('digest') is None:
                raise ValueError(
                    f'doc {d} was compacted without a valid digest; '
                    f'its history digest cannot be recomputed')
            out = rec['digest']
            have = rec['clock']
        else:
            out = 0
            have = {}
        for change in self.get_missing_changes(d, have):
            out ^= change_hash(change)
        return out

    def state_snapshot_bytes(self):
        """Total resident bytes of the per-doc horizon state snapshots
        (the ``mem_state_snapshot_bytes`` gauge reads this)."""
        return sum(len(rec['state']) for rec in self.horizon.values()
                   if rec.get('state') is not None)


def init_store(n_docs):
    return BlockStore(n_docs)


# -- per-doc local actor coordinates -----------------------------------------

# delta-host master switch: False disables every persistent host-side
# staging fast path across the engine (the _LocalActors memo below,
# general.py's staging caches, sorted field index, commit slice path
# and suffix-window renumber) — the whole-plane A/B arm of
# bench_incremental_order's host_tick band and the parity oracle for
# the cached paths. None/True = on.
_DELTA_HOST = None


def _delta_host_on():
    return _DELTA_HOST is not False


class _LocalActors:
    """Per-document actor slots, ordered by actor STRING rank within each
    document — the rank order the conflict kernel relies on
    (op_set.js:211). Built once per apply from every (doc, actor) pair
    that can appear in a clock row."""

    def __init__(self, store, pair_doc, pair_actor):
        self.str_rank = store.actor_str_ranks()
        by_rank = store.actor_by_rank()
        key = (pair_doc.astype(np.int64) << 32) | self.str_rank[pair_actor]
        self.key = np.unique(key)
        la_doc = (self.key >> 32).astype(np.int32)
        self.store_id = by_rank[(self.key & 0xFFFFFFFF).astype(np.int64)]
        self.doc_start = np.searchsorted(
            la_doc, np.arange(store.n_docs + 1)).astype(np.int64)
        self.local = np.arange(len(self.key), dtype=np.int32) - \
            self.doc_start[la_doc].astype(np.int32)
        self.width = int(np.diff(self.doc_start).max()) \
            if len(self.key) else 1

    def local_of(self, doc, store_actor):
        """Local slot per (doc, store actor) pair — pairs must be in the
        universe (guaranteed by construction)."""
        key = (doc.astype(np.int64) << 32) | self.str_rank[store_actor]
        return self.local[np.searchsorted(self.key, key)]

    def store_of(self, doc, local):
        return self.store_id[self.doc_start[doc] + local]


def _local_actors_for(store, block, b_actor, dep_actor_store, dep_doc):
    """O(delta) _LocalActors for warm stores: the previous apply's
    universe is reused when the clock pair set (ref identity of
    c_doc/c_actor — replaced only when a NEW (doc, actor) pair merges)
    and the actor string table are unchanged and every pair this block
    mentions is already a member. The reused universe may be a strict
    superset of a cold build (pairs from since-buffered changes) —
    locals stay ordered by actor string rank within each doc, which is
    the only property the kernels rely on. Anything else rebuilds from
    the full clock (O(clock pairs log) — the legacy per-tick cost)."""
    cached = getattr(store, '_la_cache', None) if _delta_host_on() \
        else None
    if cached is not None:
        c_doc_ref, c_actor_ref, n_act, la = cached
        if (c_doc_ref is store.c_doc and c_actor_ref is store.c_actor
                and n_act == len(store.actors)):
            pd = np.concatenate([block.doc, dep_doc])
            pa = np.concatenate([b_actor, dep_actor_store])
            if not len(pd):
                return la
            if len(la.key):
                key = (pd.astype(np.int64) << 32) | la.str_rank[pa]
                p = np.minimum(np.searchsorted(la.key, key),
                               len(la.key) - 1)
                if (la.key[p] == key).all():
                    return la
    la = _LocalActors(
        store,
        np.concatenate([block.doc, dep_doc, store.c_doc]),
        np.concatenate([b_actor, dep_actor_store, store.c_actor]))
    store._la_cache = (store.c_doc, store.c_actor,
                       len(store.actors), la)
    return la


# -- vectorized causal admission ---------------------------------------------

def _body_index(store):
    """(doc, actor, seq) -> (block, row) over the retained blocks, built
    lazily on the first duplicate verification and EXTENDED incrementally
    as the (append-only) retained list grows — overlapping resyncs
    verify O(1) per duplicate instead of rescanning history."""
    seen, index = getattr(store, '_body_index_cache', (0, None))
    if index is None:
        index = {}
    for blk, rows, docs in store.retained[seen:]:
        actors = blk.actors
        b_actor, b_seq = blk.actor, blk.seq
        for r, d in zip(rows.tolist(), docs.tolist()):
            index[(d, actors[b_actor[r]], int(b_seq[r]))] = (blk, r)
    store._body_index_cache = (len(store.retained), index)
    return index


def _verify_duplicate(store, block, c):
    """A change whose seq is already applied must equal the applied one
    (op_set.js:243-248). Bodies live in the retained blocks; when the log
    is truncated (snapshot resume) or retention is off, the duplicate is
    dropped unverified — the same contract as the per-doc backend's
    snapshot-era entries."""
    d = int(block.doc[c])
    a = block.actors[block.actor[c]]
    s = int(block.seq[c])
    hit = _body_index(store).get((d, a, s))
    if hit is not None:
        blk, r = hit
        if blk.change_dict(int(r)) != block.change_dict(c):
            raise ValueError(
                f'Inconsistent reuse of sequence number {s} by {a}')


def _admit_block(store, block, b_actor, dep_actor_store, la):
    """Fixed-point causal delivery over the whole block (vectorized waves).

    Returns (admitted mask, leftover mask, R) where R[c] is the dense
    [C, A_loc] transitive-deps clock of change c in doc-local actor
    coordinates — the batch analogue of the oracle's per-change
    ``all_deps`` (op_set.js:29-37). Updates the store clock and change
    log. Duplicate changes — seq already applied, or a second copy of
    the same (doc, actor, seq) within the block — are verified against
    the applied body (raising on an inconsistent seq reuse, like the
    oracle, op_set.js:243-248) and dropped; with retention off or a
    truncated log the check is skipped and the duplicate drops
    unverified.
    """
    C = block.n_changes
    doc, seq = block.doc, block.seq
    a_pad = max(la.width, 1)
    R = np.zeros((C, a_pad), np.int32)

    in_key = store.change_key(doc, b_actor, seq)
    in_order = np.argsort(in_key, kind='stable')
    in_sorted = in_key[in_order]

    dep_change = np.repeat(np.arange(C, dtype=np.int64),
                           np.diff(block.dep_ptr))
    dep_seq = block.dep_seq
    b_local = la.local_of(doc, b_actor)
    dep_local = la.local_of(doc[dep_change], dep_actor_store)
    dep_key = store.change_key(doc[dep_change], dep_actor_store, dep_seq)

    def gather_closure_rows(sources_key, dest, out_idx, target_doc):
        """Fill dest[out_idx] with each source change's closure row (in
        doc-local coords). In-block sources read R (same doc => same
        local coords); prior-block sources read the store log CSR."""
        if len(sources_key) == 0:
            return
        pos = np.minimum(np.searchsorted(in_sorted, sources_key),
                         max(C - 1, 0))
        src = in_order[pos]
        in_hit = (in_sorted[pos] == sources_key) if C else \
            np.zeros(len(sources_key), bool)
        in_hit = in_hit & admitted[src]
        if in_hit.any():
            dest[out_idx[in_hit]] = R[src[in_hit]]
        rest = ~in_hit
        if not rest.any():
            return
        log_sorted = store.log_sorted_keys()  # lazy merge, on demand
        if len(log_sorted):
            lpos = np.minimum(np.searchsorted(log_sorted,
                                              sources_key[rest]),
                              len(log_sorted) - 1)
            lhit = log_sorted[lpos] == sources_key[rest]
            rows = store.l_order[lpos[lhit]]
            tgt = out_idx[rest][lhit]
            counts = store.l_dep_ptr[rows + 1] - store.l_dep_ptr[rows]
            if counts.sum():
                idx = _span_indices(store.l_dep_ptr[rows], counts)
                tgt_rep = np.repeat(tgt, counts)
                cols = la.local_of(target_doc[tgt_rep],
                                   store.l_dep_actor[idx])
                dest[tgt_rep, cols] = store.l_dep_seq[idx]

    def accumulate_closures(ready, ext, pure):
        """The reference's transitiveDeps fold, vectorized for one wave
        (op_set.js:29-37): for each ready change, deps are folded IN
        ORDER (own seq-1 appended last) as merge-max of the dep's
        closure followed by SET depActor = depSeq — the set can clobber
        a higher transitive seq, so the result is order-dependent and
        deliberately NOT a pure max. Equivalent closed form per dep j:
        final[a_j] = max(s_j, suffix-max over later deps' closures),
        and pure max for non-dep actors.

        Chain-EXTENSION changes (``ext``: admitted in the same wave as
        their own-actor predecessor) fold only their LISTED deps here —
        the own-prev merge is the run prefix-max applied afterwards,
        which is exactly the reference fold because own-prev comes last:
        R[s] = elementwise-max(D_s, R[s-1]) with R[s][own] = s-1.
        """
        rdep = ready[dep_change] if len(dep_change) else np.zeros(0, bool)
        # pure chains (own-only closure) skip the fold entirely: their R
        # row stays zero and every consumer reconstructs own = seq-1
        start = ready & ~ext & ~pure
        rows_start = np.flatnonzero(start)
        prev = seq[rows_start] - 1
        has_prev = prev > 0
        # combined dep rows: block deps (wire order), own-prev LAST
        t_change = np.concatenate([dep_change[rdep],
                                   rows_start[has_prev]])
        t_actor = np.concatenate([dep_local[rdep],
                                  b_local[rows_start[has_prev]]])
        t_seq = np.concatenate([dep_seq[rdep], prev[has_prev]])
        t_key = np.concatenate([dep_key[rdep],
                                store.change_key(
                                    doc[rows_start[has_prev]],
                                    b_actor[rows_start[has_prev]],
                                    prev[has_prev])])
        live = t_seq > 0                  # depSeq <= 0 rows are skipped
        t_change, t_actor = t_change[live], t_actor[live]
        t_seq, t_key = t_seq[live], t_key[live]
        if len(t_change) == 0:
            return
        # stable sort by target change: block-dep order and the
        # trailing own-prev position survive within each group
        order = np.argsort(t_change, kind='stable')
        t_change, t_actor = t_change[order], t_actor[order]
        t_seq, t_key = t_seq[order], t_key[order]

        n_r = len(t_change)
        a_pad_ = R.shape[1]
        D = np.zeros((n_r, a_pad_), np.int32)
        gather_closure_rows(t_key, D, np.arange(n_r), doc[t_change])

        # exclusive suffix max of D within each change's run (doubling:
        # S[x] covers rows (x, x+step] of its run; clocks are >= 0 so
        # zero is the identity)
        S = np.zeros_like(D)
        same1 = np.zeros(n_r, bool)
        same1[:-1] = t_change[1:] == t_change[:-1]
        j = np.flatnonzero(same1)
        S[j] = D[j + 1]
        step = 1
        while True:
            idx = np.arange(n_r) + step
            ok = idx < n_r
            ok &= np.where(ok, t_change[np.minimum(idx, n_r - 1)]
                           == t_change, False)
            if not ok.any():
                break
            upd = np.zeros_like(S)
            upd[ok] = S[idx[ok]]
            S = np.maximum(S, upd)
            step *= 2

        # merge-max part: rows are sorted by t_change, so the per-change
        # reduction is one reduceat (np.maximum.at is unbuffered and
        # ~50x slower at this size)
        run_starts = np.flatnonzero(np.concatenate(
            [[True], t_change[1:] != t_change[:-1]]))
        reduced = np.maximum.reduceat(D, run_starts, axis=0)
        uniq = t_change[run_starts]
        R[uniq] = np.maximum(R[uniq], reduced)
        R[t_change, t_actor] = np.maximum(           # the SET override
            t_seq, S[np.arange(n_r), t_actor])

    # changes with any LIVE listed dep can never be chain-pure
    has_deps = np.zeros(C, bool)
    if len(dep_change):
        live0 = dep_seq > 0
        dstart0 = np.flatnonzero(np.concatenate(
            [[True], dep_change[1:] != dep_change[:-1]]))
        has_deps[dep_change[dstart0]] = \
            np.logical_or.reduceat(live0, dstart0)

    duplicate = store.clock_lookup(doc, b_actor) >= seq
    # a duplicate must MATCH what was applied (op_set.js:243-248); check
    # before any store mutation so a mismatch leaves the store untouched
    for c in np.flatnonzero(duplicate):
        _verify_duplicate(store, block, int(c))
    # in-block duplicates: keep only the first row per (doc, actor, seq),
    # verifying the dropped copies equal the kept one
    if C:
        dup_sorted = np.zeros(C, bool)
        dup_sorted[1:] = in_sorted[1:] == in_sorted[:-1]
        if dup_sorted.any():
            first_of_run = np.maximum.accumulate(
                np.where(dup_sorted, -1, np.arange(C)))
            for i in np.flatnonzero(dup_sorted):
                kept = int(in_order[first_of_run[i]])
                dup = int(in_order[i])
                if not duplicate[kept] and \
                        block.change_dict(kept) != block.change_dict(dup):
                    raise ValueError(
                        f'Inconsistent reuse of sequence number '
                        f'{int(block.seq[dup])} by '
                        f'{block.actors[block.actor[dup]]}')
        duplicate[in_order[dup_sorted]] = True
    pending = ~duplicate
    admitted = np.zeros(C, bool)
    adm_waves = []                   # rows per wave -> admission order

    while True:                      # terminates: pending shrinks per wave
        if not pending.any():
            break
        own_prev = store.clock_lookup(doc, b_actor)
        chain_ok = seq == own_prev + 1
        dep_ok = np.ones(C, bool)
        if len(dep_change):
            dep_have = store.clock_lookup(doc[dep_change], dep_actor_store)
            sat = dep_have >= dep_seq
            # dep_change is sorted (a repeat of arange): per-change AND
            # via reduceat on the runs
            dstart = np.flatnonzero(np.concatenate(
                [[True], dep_change[1:] != dep_change[:-1]]))
            dep_ok[dep_change[dstart]] = \
                np.logical_and.reduceat(sat, dstart)
        # RUN admission: a maximal contiguous per-(doc, actor) seq run
        # whose every element's LISTED deps are satisfied by the
        # pre-wave clock admits as a unit — so a 100k-change single-
        # actor chain takes ONE wave, not 100k. (Waves now count only
        # cross-actor dependency depth within the block.)
        X = pending & dep_ok
        xs = X[in_order]
        ks = in_sorted
        start_ok_s = (pending & chain_ok & dep_ok)[in_order]
        brk = np.ones(C, bool)
        if C > 1:
            brk[1:] = (ks[1:] != ks[:-1] + 1) | ~xs[:-1]
        run_id = np.cumsum(brk) - 1
        run_start_ok = start_ok_s[np.flatnonzero(brk)]
        ready_s = xs & run_start_ok[run_id]
        if not ready_s.any():
            break
        ready = np.zeros(C, bool)
        ready[in_order[ready_s]] = True
        ext_s = ready_s & ~brk                   # chain extensions
        ext = np.zeros(C, bool)
        ext[in_order[ext_s]] = True

        # ---- chain purity, per sorted row: pure iff no live deps, and
        # the run start inherits purity (seq 1, or a pure clock chain);
        # one impure element poisons the rest of its run ----
        idxC = np.arange(C)
        start_s = ready_s & brk
        start_imp = np.zeros(C, bool)
        pos_s = np.flatnonzero(start_s)
        if len(pos_s):
            rows0 = in_order[pos_s]
            start_imp[pos_s] = np.where(
                seq[rows0] == 1, False,
                ~store.clock_pure_lookup(doc[rows0], b_actor[rows0]))
        base_imp = (has_deps[in_order] | start_imp) & ready_s
        run_first = np.maximum.accumulate(np.where(brk, idxC, -1))
        last_imp = np.maximum.accumulate(np.where(base_imp, idxC, -1))
        impure_s = ready_s & (last_imp >= run_first)
        pure = np.zeros(C, bool)
        pure[in_order[ready_s & ~impure_s]] = True

        accumulate_closures(ready, ext, pure)
        if ext_s.any():
            # segmented prefix max along runs (Hillis–Steele doubling),
            # then the exact own-seq SET (the fold's last step)
            Rs = R[in_order]
            idx = np.arange(C)
            step = 1
            while step < C:
                src = idx - step
                ok = (src >= 0) & ready_s
                srcc = np.maximum(src, 0)
                ok &= (run_id == run_id[srcc]) & ready_s[srcc]
                if ok.any():
                    np.maximum(Rs, np.where(ok[:, None], Rs[srcc], 0),
                               out=Rs)
                step <<= 1
            rows_ext = in_order[ext_s]
            R[rows_ext] = Rs[ext_s]
            imp_ext = rows_ext[~pure[rows_ext]]
            R[imp_ext, b_local[imp_ext]] = seq[imp_ext] - 1

        admitted |= ready
        pending &= ~ready
        adm_waves.append(in_order[ready_s])
        store.clock_merge(doc[ready], b_actor[ready], seq[ready],
                          pure=pure[ready])

    adm_order = np.concatenate(adm_waves) if adm_waves else \
        np.zeros(0, np.int64)
    cmap = _log_append(store, in_key, admitted, R, doc, la)
    return admitted, pending, R, cmap, adm_order


def _log_append(store, in_key, admitted, R, doc, la):
    """Append admitted changes + closures to the change log (append-order
    rows, sorted view refreshed). Returns cmap: block change row -> log
    row id (-1 for non-admitted)."""
    adm = np.flatnonzero(admitted)
    cmap = np.full(len(admitted), -1, np.int64)
    if not len(adm):
        return cmap
    base = len(store.l_key)
    cmap[adm] = base + np.arange(len(adm))
    Radm = R[adm]
    nz_r, nz_c = np.nonzero(Radm)
    ptr_new = np.zeros(len(adm), np.int32)
    counts = np.bincount(nz_r, minlength=len(adm)).astype(np.int32)
    np.cumsum(counts, out=ptr_new)
    la_actor = la.store_of(doc[adm[nz_r]], nz_c).astype(np.int32)
    la_seq = Radm[nz_r, nz_c]
    new_keys = in_key[adm]
    store.l_key = np.concatenate([store.l_key, new_keys])
    store.l_dep_ptr = np.concatenate([
        store.l_dep_ptr, store.l_dep_ptr[-1] + ptr_new])
    store.l_dep_actor = np.concatenate([store.l_dep_actor, la_actor])
    store.l_dep_seq = np.concatenate([store.l_dep_seq, la_seq])
    # the sorted view merges lazily on the next log LOOKUP
    # (log_sorted_keys) — pure chain streams never pay it
    store._l_pending.append((new_keys, base))
    return cmap


def _merge_queued(block, queue):
    """Fold buffered dict changes into an incoming block (small path).

    The block's values are NOT materialized: they carry over as a
    ValueTable segment (lazy spans stay lazy) and only the queued
    changes' values append as plain entries."""
    actors = list(block.actors)
    actor_of = {a: i for i, a in enumerate(actors)}
    keys = list(block.keys)
    key_of = {k: i for i, k in enumerate(keys)}
    values = ValueTable()
    values.extend(block.values)
    tail = []                      # queued changes' values (plain)

    doc, actor, seq = [], [], []
    dep_ptr = [int(block.dep_ptr[-1])]
    dep_actor, dep_seq = [], []
    op_ptr = [int(block.op_ptr[-1])]
    action, key, value = [], [], []
    for d, change in queue:
        doc.append(d)
        actor.append(_intern(actors, actor_of, change['actor']))
        seq.append(change['seq'])
        for da, ds in change['deps'].items():
            dep_actor.append(_intern(actors, actor_of, da))
            dep_seq.append(ds)
        dep_ptr.append(dep_ptr[0] + len(dep_actor))
        for op in change['ops']:
            action.append(_ACTION_NAMES[op['action']])
            key.append(_intern(keys, key_of, op['key']))
            if op['action'] == 'set':
                value.append(len(values) + len(tail))
                tail.append(op.get('value'))
            else:
                value.append(-1)
        op_ptr.append(op_ptr[0] + len(action))
    values.extend(tail)

    return ChangeBlock(
        block.n_docs,
        np.concatenate([block.doc, np.asarray(doc, np.int32)]),
        np.concatenate([block.actor, np.asarray(actor, np.int32)]),
        np.concatenate([block.seq, np.asarray(seq, np.int32)]),
        np.concatenate([block.dep_ptr,
                        np.asarray(dep_ptr[1:], np.int32)]),
        np.concatenate([block.dep_actor, np.asarray(dep_actor, np.int32)]),
        np.concatenate([block.dep_seq, np.asarray(dep_seq, np.int32)]),
        np.concatenate([block.op_ptr, np.asarray(op_ptr[1:], np.int32)]),
        np.concatenate([block.action, np.asarray(action, np.int8)]),
        np.concatenate([block.key, np.asarray(key, np.int32)]),
        np.concatenate([block.value, np.asarray(value, np.int32)]),
        actors, keys, values)


# -- shared host preamble -----------------------------------------------------

class _Staged:
    """Output of the shared admission preamble: the (possibly
    queue-merged) block, admission results, and the admitted ops as
    columns with store-id keys/actors and store value refs. For general
    blocks ``o_key`` is None (key semantics depend on the kind column);
    consumers use ``keep``/``a_tab``/``k_tab`` to map the raw columns."""

    __slots__ = ('block', 'admitted', 'R', 'cmap', 'la', 'b_actor',
                 'oc', 'o_doc', 'o_actor', 'o_seq', 'o_action', 'o_key',
                 'o_value', 'keep', 'a_tab', 'k_tab')


def _admit_and_stage(store, block, max_keys=None, max_actors=None):
    """Queue merge + interning + causal admission + admitted-op staging —
    the host phase shared by apply_block and DenseMapStore.

    Capacity limits are checked BEFORE any store mutation — a rejected
    block leaves the store usable AND its buffered queue intact. Values
    are interned for ADMITTED ops only — a change stuck in the queue does
    not grow ``store.values`` on every retry.
    """
    check_block_ranges(store, block)
    merged = store.merge_queued_into(block) if store.queue else block

    if max_keys is not None:
        n_keys = len(store.keys) + sum(1 for k in set(merged.keys)
                                       if k not in store.key_of)
        if n_keys > max_keys:
            raise ValueError(f'{n_keys} keys exceed key_capacity={max_keys}')
    if max_actors is not None:
        n_actors = len(store.actors) + sum(1 for a in set(merged.actors)
                                           if a not in store.actor_of)
        if n_actors > max_actors:
            raise ValueError(
                f'{n_actors} actors exceed actor_capacity={max_actors}')
    block = merged
    saved_queue, store.queue = store.queue, []

    a_tab = store.intern(block.actors, store.actors, store.actor_of)
    k_tab = store.intern(block.keys, store.keys, store.key_of)

    z32 = np.zeros(0, np.int32)
    b_actor = a_tab[block.actor] if block.n_changes else z32
    dep_actor_store = a_tab[block.dep_actor] if len(block.dep_actor) else z32

    # per-doc local actor universe: change + dep + already-applied
    # actors (memoized across applies — warm ticks reuse it in
    # O(block pairs))
    dep_doc = np.repeat(block.doc, np.diff(block.dep_ptr))
    la = _local_actors_for(store, block, b_actor, dep_actor_store,
                           dep_doc)

    try:
        admitted, leftover, R, cmap, adm_order = _admit_block(
            store, block, b_actor, dep_actor_store, la)
    except ValueError:
        # duplicate-content verification raises BEFORE any store
        # mutation; put the merged-away queue back so the store (and its
        # buffered changes) stay usable
        store.queue = saved_queue
        raise
    for c in np.flatnonzero(leftover):
        store.queue.append((int(block.doc[c]), block.change_dict(c)))
    if len(adm_order):
        # state-digest maintenance: remember the admitted rows; the
        # content hashes fold lazily on the first digest read (one
        # list append here — nothing on the apply hot path)
        store._digest_pending.append((block, adm_order,
                                      block.doc[adm_order]))
    if store.retain_log and len(adm_order):
        # doc-sorted, ADMISSION order within each doc (the causal order
        # get_missing_changes promises); stored whole — per-doc slices
        # resolve by binary search at read time, so retention is O(sort)
        doc_of = block.doc[adm_order]
        order = np.argsort(doc_of, kind='stable')
        store.retained.append((block, adm_order[order], doc_of[order]))

    # admitted ops as columns
    C = block.n_changes
    op_change = np.repeat(np.arange(C, dtype=np.int64),
                          np.diff(block.op_ptr))
    keep = admitted[op_change] if C else np.zeros(0, bool)
    oc = op_change[keep]

    st = _Staged()
    st.block = block
    st.admitted, st.R, st.cmap, st.la, st.b_actor = (admitted, R, cmap,
                                                     la, b_actor)
    st.oc = oc
    st.keep = keep
    st.a_tab, st.k_tab = a_tab, k_tab
    st.o_doc = block.doc[oc]
    st.o_actor = b_actor[oc]
    st.o_seq = block.seq[oc]
    st.o_action = block.action[keep]
    if block.is_general():
        st.o_key = None          # kind-dependent; the general engine maps
    else:
        st.o_key = k_tab[block.key[keep]] if keep.any() else z32

    # value interning, admitted ops only
    v_base = len(store.values)
    o_val = block.value[keep]
    refs = o_val[o_val >= 0]
    if admitted.all() and len(refs) == len(block.values):
        # fast path: every block value is referenced exactly once
        store.values.extend(block.values)
        st.o_value = np.where(o_val >= 0, o_val + v_base, -1) \
            .astype(np.int32)
    else:
        used = np.unique(refs)
        mapping = np.full(max(len(block.values), 1), -1, np.int64)
        mapping[used] = np.arange(len(used)) + v_base
        store.values.extend(block.values[i] for i in used.tolist())
        st.o_value = np.where(
            o_val >= 0, mapping[np.maximum(o_val, 0)], -1).astype(np.int32)
    return st


# -- apply: pack -> resolve -> unpack ----------------------------------------

def apply_block(store, block, options=None, return_timing=False):
    """`applyChanges` for the bulk path: ONE device resolution for every
    touched field of every document in the block.

    Mutates `store`; returns a :class:`PatchBlock` (or (patches, timing)
    with ``return_timing``). Duplicate changes are dropped; causally
    unready changes are buffered in ``store.queue`` (retried on the next
    apply; ``store.get_missing_deps()`` reports the gaps) — the block
    analogue of op_set.js:267-283, 347-358.
    """
    import time
    opts = _engine.as_options(options)
    if block.is_general():
        raise ValueError(
            'block carries general ops (sequences/nested objects); apply '
            'through automerge_tpu.device.general')
    t0 = time.perf_counter()
    st = _admit_and_stage(store, block)
    block = st.block
    admitted, R, cmap, la, b_actor = (st.admitted, st.R, st.cmap, st.la,
                                      st.b_actor)
    oc, o_doc, o_actor = st.oc, st.o_doc, st.o_actor
    o_seq, o_action, o_key, o_value = (st.o_seq, st.o_action, st.o_key,
                                       st.o_value)
    t1 = time.perf_counter()

    # ---- pack: admitted ops + prior entries of touched fields ----
    D = store.n_docs
    z32 = np.zeros(0, np.int32)
    if len(o_doc) == 0:
        empty = PatchBlock(
            D, np.zeros(D + 1, np.int32), z32, z32,
            np.zeros(0, np.int8), z32, z32, np.zeros(1, np.int32), z32, z32,
            store.keys, store.values, store.actors,
            store.c_doc.copy(), store.c_actor.copy(), store.c_seq.copy())
        return (empty, {'admit': t1 - t0, 'pack': 0.0, 'device': 0.0,
                        'unpack': 0.0}) if return_timing else empty

    K = max(len(store.keys), 1)
    fk_new = o_doc.astype(np.int64) * K + o_key
    e_fk = store.e_doc.astype(np.int64) * K + store.e_key
    if D * K <= (1 << 27):
        present = np.zeros(D * K, bool)
        present[fk_new] = True
        touched_fk = np.flatnonzero(present)           # sorted
        seg_of = np.full(D * K, -1, np.int64)
        seg_of[touched_fk] = np.arange(len(touched_fk))
        seg_new = seg_of[fk_new]
        prior_mask = present[e_fk] if len(e_fk) else np.zeros(0, bool)
        prior_rows = np.flatnonzero(prior_mask)
        seg_prior = seg_of[e_fk[prior_rows]]
    else:
        touched_fk, seg_new = np.unique(fk_new, return_inverse=True)
        if len(e_fk):
            pos = np.minimum(np.searchsorted(touched_fk, e_fk),
                             len(touched_fk) - 1)
            prior_mask = touched_fk[pos] == e_fk
            prior_rows = np.flatnonzero(prior_mask)
            seg_prior = pos[prior_rows]
        else:
            prior_mask = np.zeros(0, bool)
            prior_rows = np.zeros(0, np.int64)
            seg_prior = np.zeros(0, np.int64)
    F = len(touched_fk)
    f_doc = (touched_fk // K).astype(np.int32)
    f_key = (touched_fk % K).astype(np.int32)
    f_doc_start = np.searchsorted(f_doc, np.arange(D + 1)).astype(np.int64)
    S = opts.pad_segments(F)

    # flat segmented layout: no per-doc slots — the kernel reduces over
    # GLOBAL field segments, so packing is pure concatenation + padding
    p_doc = store.e_doc[prior_rows]
    n_new, n_prior = len(o_doc), len(prior_rows)
    n_rows = n_new + n_prior
    n_pad = opts.pad_ops(n_rows)
    A = opts.pad_actors(max(la.width, 1))

    def padded(new_vals, prior_vals, dtype):
        out = np.zeros(n_pad, dtype)
        out[:n_new] = new_vals
        out[n_new:n_rows] = prior_vals
        return out

    # per-op local actor ranks: computed per CHANGE for new ops (cheap),
    # per entry for priors
    rank_of_change = la.local_of(block.doc, b_actor) \
        if block.n_changes else z32
    seg_arr = padded(seg_new, seg_prior, np.int32)
    actor_arr = padded(rank_of_change[oc],
                       la.local_of(p_doc, store.e_actor[prior_rows]),
                       np.int32)
    seq_arr = padded(o_seq, store.e_seq[prior_rows], np.int32)
    del_arr = padded(o_action == _DEL, np.zeros(n_prior, bool), bool)
    valid_arr = np.zeros(n_pad, bool)
    valid_arr[:n_rows] = True
    # Clock rows: all-zero whenever every admitted change is wave-1
    # concurrent (no deps, seq 1) and no prior entries carry closures —
    # then the zeros are materialized ON DEVICE instead of shipping an
    # [n_pad, A] zero plane over PCIe.
    prior_nnz = 0
    if n_prior:
        e_log = store.e_change[prior_rows]
        prior_counts = (store.l_dep_ptr[e_log + 1]
                        - store.l_dep_ptr[e_log])
        prior_nnz = int(prior_counts.sum())
    r_any = bool(R.any())
    max_new_seq = int(o_seq.max()) if n_new else 0
    if r_any or prior_nnz or max_new_seq > 1:
        clock_arr = np.zeros((n_pad, A), np.int32)
        if r_any:
            new_clocks = R[oc]
            clock_arr[:n_new, :new_clocks.shape[1]] = new_clocks
        # the own-actor entry is IMPLICIT (always seq-1): pure chains
        # carry all-zero R rows, so reconstruct it here for every new op
        clock_arr[np.arange(n_new), actor_arr[:n_new]] = o_seq - 1
        if prior_nnz:
            idx = _span_indices(store.l_dep_ptr[e_log], prior_counts)
            rows_rep = np.repeat(np.arange(n_new, n_rows), prior_counts)
            doc_rep = np.repeat(p_doc, prior_counts)
            clock_arr[rows_rep,
                      la.local_of(doc_rep, store.l_dep_actor[idx])] = \
                store.l_dep_seq[idx]
        clock_dev = jnp.asarray(clock_arr)
    else:
        clock_dev = jnp.zeros((n_pad, A), jnp.int32)
    t2 = time.perf_counter()

    from .merge import resolve_assignments
    if opts.kernel == 'pallas':
        raise ValueError('the block path runs the flat XLA resolve kernel; '
                         'kernel="pallas" applies to the per-document path')
    out = resolve_assignments(
        jnp.asarray(seg_arr), jnp.asarray(actor_arr), jnp.asarray(seq_arr),
        clock_dev, jnp.asarray(del_arr),
        jnp.asarray(valid_arr), num_segments=S)
    surviving = np.asarray(out['surviving'])[:n_rows]
    w_row = np.asarray(out['winner'])[:F]          # flat row id, -1 if none
    t3 = time.perf_counter()

    # ---- unpack: patch block + store update ----
    r_value = np.concatenate([o_value, store.e_value[prior_rows]])
    r_actor_store = np.concatenate([o_actor, store.e_actor[prior_rows]])
    has_winner = w_row >= 0
    w_safe = np.maximum(w_row, 0)
    f_action = np.where(has_winner, _SET, _DEL).astype(np.int8)
    f_value = np.where(has_winner, r_value[w_safe], -1).astype(np.int32)
    f_actor = np.where(has_winner, r_actor_store[w_safe], -1) \
        .astype(np.int32)

    # conflicts: surviving losers grouped by field (radix argsort on the
    # int32 segment ids keeps this O(n))
    s_rows = np.flatnonzero(surviving)
    r_seg = seg_arr[:n_rows]
    ent_is_loser = s_rows != w_row[r_seg[s_rows]]
    loser_rows = s_rows[ent_is_loser]
    loser_rows = loser_rows[np.argsort(r_seg[loser_rows], kind='stable')]
    s_counts = np.bincount(r_seg[loser_rows], minlength=F) if F else \
        np.zeros(0, np.int64)
    s_ptr = np.zeros(F + 1, np.int32)
    np.cumsum(s_counts, out=s_ptr[1:])

    patches = PatchBlock(
        D, f_doc_start.astype(np.int32), f_doc, f_key, f_action, f_value,
        f_actor, s_ptr, r_actor_store[loser_rows], r_value[loser_rows],
        store.keys, store.values, store.actors,
        store.c_doc.copy(), store.c_actor.copy(), store.c_seq.copy())

    _store_update(
        store, prior_mask, s_rows,
        np.concatenate([o_doc, p_doc]),
        np.concatenate([o_key, store.e_key[prior_rows]]),
        r_actor_store,
        np.concatenate([o_seq, store.e_seq[prior_rows]]),
        r_value,
        np.concatenate([cmap[oc].astype(np.int32),
                        store.e_change[prior_rows]]))
    t4 = time.perf_counter()

    metrics.bump('block_batches')
    metrics.bump('block_ops', n_new)
    metrics.set_gauge('block_batch_occupancy', n_rows / max(n_pad, 1))
    if return_timing:
        return patches, {'admit': t1 - t0, 'pack': t2 - t1,
                         'device': t3 - t2, 'unpack': t4 - t3}
    return patches


def _store_update(store, prior_mask, s_rows, r_doc, r_key, r_actor, r_seq,
                  r_value, r_change):
    """Replace touched fields' entries with the surviving rows. Entries
    are unordered; closures live in the change log (``e_change`` refs),
    so the update is mask + concatenate — no scatters, no CSR copies."""
    keep = ~prior_mask if len(prior_mask) else np.zeros(0, bool)
    store.e_doc = np.concatenate([store.e_doc[keep], r_doc[s_rows]])
    store.e_key = np.concatenate([store.e_key[keep], r_key[s_rows]])
    store.e_actor = np.concatenate([store.e_actor[keep], r_actor[s_rows]])
    store.e_seq = np.concatenate([store.e_seq[keep], r_seq[s_rows]])
    store.e_value = np.concatenate([store.e_value[keep], r_value[s_rows]])
    store.e_change = np.concatenate([store.e_change[keep],
                                     r_change[s_rows]])
