"""Dense vector-clock kernels.

Host clocks are ``dict[actor, seq]``; on device a clock is a dense
``int32[n_actors]`` vector (index = interned actor rank). These are the
batched equivalents of `src/common.js:14-18` (lessOrEqual),
`op_set.js:20-27` (causallyReady) and `src/connection.js:9-12`
(clockUnion), vectorized over documents/changes.
"""

import jax
import jax.numpy as jnp


def less_or_equal(clock1, clock2):
    """Elementwise vector-clock partial order; broadcasts over leading axes."""
    return jnp.all(clock1 <= clock2, axis=-1)


def union(clock1, clock2):
    """Pointwise max (clock merge)."""
    return jnp.maximum(clock1, clock2)


def causally_ready(doc_clock, change_deps, change_actor, change_seq):
    """Readiness of a batch of changes against a document clock.

    doc_clock:    int32[A]         current applied clock
    change_deps:  int32[C, A]      each change's declared deps (dense)
    change_actor: int32[C]         originating actor rank
    change_seq:   int32[C]

    A change is ready when every dep is satisfied and its own predecessor
    (seq-1 from the same actor) has been applied (op_set.js:20-27).
    """
    deps_ok = jnp.all(change_deps <= doc_clock[None, :], axis=-1)
    own_ok = doc_clock[change_actor] >= change_seq - 1
    return deps_ok & own_ok


def advance(doc_clock, change_actor, change_seq, ready):
    """New document clock after applying the ready subset of changes."""
    seqs = jnp.where(ready, change_seq, 0)
    applied = jax.ops.segment_max(seqs, change_actor,
                                  num_segments=doc_clock.shape[0])
    return jnp.maximum(doc_clock, applied)
