"""Device-resident dense document store: the collab-server engine.

This is the SURVEY §7 architecture taken to its conclusion: the CRDT
state of a whole DocSet lives in HBM as dense planes, and `applyChanges`
for a million-op block is a handful of scatter-max ops — no host-side
state walk at all. Wire traffic per apply is the compressed change
columns in (a few bytes per op), and patches come back as device arrays
with lazy host materialization.

Representation. For flat map documents, a field's CRDT state is at most
one surviving assignment per actor (same-actor ops on one field are
always causally ordered, so the later one supersedes —
op_set.js:180-219). That makes the whole store dense (all int32 — the
TPU VPU's native lane width; no x64 anywhere):

* ``ESeqDel[f, a]`` — ``(seq << 1) | is_del``: actor `a`'s latest
  assignment to field `f` (0 = none). Applying an op is one scatter-max:
  a later seq always wins.
* ``EVal[f, a]`` — the value ref of that assignment, kept consistent
  with ESeqDel by resetting every updated cell and re-scattering the
  ops that achieved the new maximum.
* ``M[f, a]`` — the running max over *every* applied op's transitive
  closure clock. Supersession needs ``max over ops j on f of
  clock_j[a]``; a superseding op's closure contains its victim's
  closure, so the max over live ops equals the max over all ops ever
  applied — M can accumulate monotonically (scatter-max, no removal).

An entry (f, a) is **alive** iff ``seq > 0`` and ``M[f, a] < seq`` (not
superseded) and not a delete; the winner is the alive entry with the
highest actor string rank (op_set.js:211), the rest are the conflicts.

Causal admission (vector-clock waves) and string interning stay on the
host (:mod:`.blocks`); everything per-op runs on device. Capacities
(docs, keys, actor slots) are fixed at construction — the price of dense
addressing — with clear errors on overflow; the general unbounded path
is :func:`automerge_tpu.device.blocks.apply_block`. Actor slots are PER
DOCUMENT (``actor_capacity`` bounds the distinct actors editing one
document, not the store-wide actor population — a 10k-doc fleet with
10k distinct authors fits in 16 slots if no single doc has more than 16
collaborators); winner election reads a device-resident per-doc
string-rank plane.

One scope limit vs the block path: two assignments to the same key
within one change (never emitted by the reference frontend —
`ensureSingleAssignment`, frontend/index.js:46) need two surviving
entries in one (field, actor) cell, which the dense planes cannot hold;
such blocks are rejected before any mutation with a clear error and
take :func:`automerge_tpu.device.blocks.apply_block` instead.
"""

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

from ..utils.metrics import metrics
from . import blocks as _blocks
from .blocks import _SET, _DEL

_VAL_NONE = np.int32(-2147483648)      # "no value" sentinel for EVal


@partial(jax.jit, static_argnames=('n_fields', 'n_actors', 'seq_values'))
def _apply_kernel(eseq, eval_, m, change_doc, change_actor, change_seq,
                  coo_row, coo_col, coo_val, op_counts, op_key,
                  op_isdel_bits, op_value, n_ops, key_capacity, v_base, *,
                  n_fields, n_actors, seq_values):
    """One block apply: expand change columns to op rows ON DEVICE, then
    scatter-maxes into the resident planes.

    Wire-lean inputs: the change columns arrive in the narrowest dtype
    that fits (int16 docs/seqs, uint8 slots/counts — upcast here); the
    del mask arrives bit-packed (uint8, unpacked here); with
    ``seq_values`` the value refs are not shipped at all — set ops
    reference values sequentially from ``v_base`` (the layout
    ChangeBlock.from_changes and the workload generators produce), so the
    refs are a cumulative sum computed on device; and the closure clock
    plane is REBUILT ON DEVICE — a change's own-actor entry is always
    seq-1 (the transitiveDeps fold ends with that SET), so only the
    sparse cross-actor closure entries ship, as COO triples.
    """
    change_doc = change_doc.astype(jnp.int32)
    change_actor = change_actor.astype(jnp.int32)
    change_seq = change_seq.astype(jnp.int32)
    op_counts = op_counts.astype(jnp.int32)
    coo_col = coo_col.astype(jnp.int32)
    coo_val = coo_val.astype(jnp.int32)
    n_pad = op_key.shape[0]
    c_pad = change_doc.shape[0]
    change_clock = jnp.zeros((c_pad, n_actors), jnp.int32)
    change_clock = change_clock.at[
        jnp.arange(c_pad), change_actor].set(change_seq - 1)
    change_clock = change_clock.at[coo_row, coo_col].set(coo_val,
                                                         mode='drop')
    op_change = jnp.repeat(jnp.arange(c_pad, dtype=jnp.int32), op_counts,
                           total_repeat_length=n_pad)
    valid = jnp.arange(n_pad) < n_ops

    idx = jnp.arange(n_pad)
    op_isdel = ((op_isdel_bits[idx >> 3] >> (7 - (idx & 7))) & 1) \
        .astype(bool)
    if seq_values:
        sets = valid & ~op_isdel
        op_value = jnp.where(
            sets, v_base + jnp.cumsum(sets.astype(jnp.int32)) - 1, -1)

    fidx = change_doc[op_change] * key_capacity + op_key.astype(jnp.int32)
    # padding rows are parked at n_fields (out of bounds) and dropped by
    # the scatters — planes stay exactly [n_fields, A], which shards
    # cleanly over a doc-axis mesh (doc-major rows)
    fidx = jnp.where(valid, fidx, n_fields)
    aslot = change_actor[op_change]
    seq_op = change_seq[op_change]

    seqdel = (seq_op << 1) | op_isdel.astype(jnp.int32)
    seqdel = jnp.where(valid, seqdel, 0)
    new_eseq = eseq.at[fidx, aslot].max(seqdel, mode='drop')

    # cells whose max advanced get their value re-scattered by exactly
    # the ops that achieved the new maximum
    new_eval = jnp.where(new_eseq != eseq, _VAL_NONE, eval_)
    mine = valid & (seqdel == new_eseq.at[fidx, aslot].get(
        mode='fill', fill_value=0))
    new_eval = new_eval.at[jnp.where(mine, fidx, n_fields), aslot].max(
        op_value, mode='drop')

    clock_op = change_clock[op_change]                 # [n_pad, A]
    clock_op = jnp.where(valid[:, None], clock_op, -1)
    new_m = m.at[fidx].max(clock_op, mode='drop')
    return new_eseq, new_eval, new_m


@partial(jax.jit, static_argnames=('n_fields', 'n_actors', 'seq_values',
                                   'f_pad'))
def _apply_extract_kernel(eseq, eval_, m, chg_doc, chg_actor, chg_seq,
                          chg_counts, coo_row, coo_col, coo_val, op_key,
                          op_isdel_bits, op_value, n_ops, key_capacity,
                          v_base, rank_plane, touched_bits, *, n_fields,
                          n_actors, seq_values, f_pad):
    """Apply + patch extraction in ONE device program — a dense apply is
    a single dispatch, so each apply risks one link-latency spike, not
    two (p99 on a jittery link is dominated by per-dispatch outliers).
    Change columns arrive in narrow dtypes and the touched-field mask
    bit-packed — wire bytes per 1M-op apply drop ~3x, which is what p99
    rides on when the link bandwidth degrades."""
    new_eseq, new_eval, new_m = _apply_kernel.__wrapped__(
        eseq, eval_, m, chg_doc, chg_actor, chg_seq, coo_row,
        coo_col, coo_val, chg_counts, op_key, op_isdel_bits,
        op_value, n_ops, key_capacity, v_base, n_fields=n_fields,
        n_actors=n_actors, seq_values=seq_values)
    i = jnp.arange(n_fields)
    touched_mask = ((touched_bits[i >> 3] >> (7 - (i & 7))) & 1) \
        .astype(bool)
    extracted = _extract_kernel.__wrapped__(
        new_eseq, new_eval, new_m, rank_plane, key_capacity,
        touched_mask, f_pad=f_pad)
    return (new_eseq, new_eval, new_m) + extracted


@partial(jax.jit, static_argnames=('f_pad',))
def _extract_kernel(eseq, eval_, m, rank_plane, key_capacity,
                    touched_mask, *, f_pad):
    """Patch extraction for the touched fields, fully on device.

    ``rank_plane`` is the device-resident [n_docs, A] actor-string-rank
    table (slots are PER DOCUMENT); each touched field gathers its own
    document's row. Returns (touched fidx [f_pad], winner slot [f_pad],
    winner value [f_pad], alive mask [f_pad, A]); -1 fidx rows are
    padding.
    """
    (fidx,) = jnp.nonzero(touched_mask, size=f_pad, fill_value=-1)
    frow = jnp.maximum(fidx, 0)
    seqdel = eseq.at[frow].get(mode='fill', fill_value=0)  # [f_pad, A]
    mrows = m.at[frow].get(mode='fill', fill_value=-1)
    seq = seqdel >> 1
    is_del = (seqdel & 1) != 0
    alive = (seq > 0) & (mrows < seq) & ~is_del & (fidx >= 0)[:, None]

    f_rank = rank_plane[frow // key_capacity]          # [f_pad, A]
    rank = jnp.where(alive, f_rank, -1)
    winner_slot = jnp.argmax(rank, axis=1)
    has_winner = jnp.max(rank, axis=1) >= 0
    winner_slot = jnp.where(has_winner, winner_slot, -1)
    values = eval_[frow]                               # [f_pad, A]
    winner_value = jnp.take_along_axis(
        values, jnp.maximum(winner_slot, 0)[:, None], axis=1)[:, 0]
    winner_value = jnp.where(has_winner, winner_value, -1)
    return fidx, winner_slot, winner_value, alive, values


def _note_dense_dispatch(store, args, statics):
    """Shape-signature registry hook for the fused dense apply+extract
    dispatch (device/profiler.py): plane capacity + padded change/op/
    coo widths + the static args ARE the compile signature."""
    from . import profiler as _profiler
    _profiler.note_dispatch(
        'dense.apply_extract',
        (store.eseq.shape, args[0].shape, args[7].shape,
         args[4].shape, tuple(sorted(statics.items()))),
        rows=args[7].shape[0])


class DensePatch:
    """Patches from one dense apply, as device arrays; host
    materialization (`to_patch_block` / `diffs`) is lazy."""

    def __init__(self, store, fidx=None, winner_slot=None,
                 winner_value=None, alive=None, values=None):
        self._store = store
        self.fidx = fidx
        self.winner_slot = winner_slot
        self.winner_value = winner_value
        self.alive = alive
        self.values = values          # [f_pad, A] value refs per slot
        self._block = None
        self._event = None            # set by the async applier
        self._error = None

    def _resolve_async(self, outs):
        (self.fidx, self.winner_slot, self.winner_value, self.alive,
         self.values) = outs

    def _wait(self):
        if self._event is not None:
            self._event.wait()
            if self._error is not None:
                raise self._error

    def block_until_ready(self):
        self._wait()
        jax.block_until_ready(self.winner_value)
        return self

    def to_patch_block(self):
        """Fetch + reshape into a host :class:`~.blocks.PatchBlock`."""
        if self._block is not None:
            return self._block
        self._wait()
        store = self._store
        fidx = np.asarray(self.fidx)
        live = fidx >= 0
        fidx = fidx[live]
        order = np.argsort(fidx, kind='stable')
        fidx = fidx[order]
        w_slot = np.asarray(self.winner_slot)[live][order]
        w_value = np.asarray(self.winner_value)[live][order]
        alive = np.asarray(self.alive)[live][order]

        K = store.key_capacity
        f_doc = (fidx // K).astype(np.int32)
        f_key = (fidx % K).astype(np.int32)
        f_ptr = np.searchsorted(f_doc, np.arange(store.n_docs + 1)) \
            .astype(np.int32)
        has_winner = w_slot >= 0
        f_action = np.where(has_winner, _SET, _DEL).astype(np.int8)
        f_value = np.where(has_winner, w_value, -1).astype(np.int32)
        f_actor = np.where(has_winner,
                           store.slot_actor[f_doc, np.maximum(w_slot, 0)],
                           -1).astype(np.int32)

        # conflicts: alive minus winner, COO -> CSR per field
        losers = alive.copy()
        rows = np.arange(len(fidx))
        losers[rows[has_winner], w_slot[has_winner]] = False
        lf, ls = np.nonzero(losers)
        s_counts = np.bincount(lf, minlength=len(fidx))
        s_ptr = np.zeros(len(fidx) + 1, np.int32)
        np.cumsum(s_counts, out=s_ptr[1:])
        host = store.host
        s_actor = store.slot_actor[f_doc[lf], ls].astype(np.int32)
        values = np.asarray(self.values)[live][order]
        s_value = values[lf, ls].astype(np.int32)

        self._block = _blocks.PatchBlock(
            store.n_docs, f_ptr, f_doc, f_key, f_action, f_value, f_actor,
            s_ptr, s_actor, s_value, host.keys, host.values, host.actors,
            host.c_doc.copy(), host.c_actor.copy(), host.c_seq.copy())
        return self._block

    def diffs(self, d):
        return self.to_patch_block().diffs(d)

    def to_patches(self):
        return self.to_patch_block().to_patches()


class DenseMapStore:
    """A DocSet of flat map documents resident in device memory.

    With a ``mesh`` (a 1-D document-axis mesh), the planes live sharded
    across the devices — rows are doc-major, so splitting axis 0 places
    each document's fields wholly on one device and the apply scatters
    stay shard-local (dp for the dense engine). ``n_docs`` must divide
    evenly by the mesh size (doc-locality is the checked invariant).
    """

    def __init__(self, n_docs, key_capacity=64, actor_capacity=16,
                 options=None, mesh=None, retain_log=True):
        from .engine import as_options
        self.options = as_options(options)
        self.n_docs = n_docs
        self.key_capacity = key_capacity
        self.actor_capacity = actor_capacity
        self.n_fields = n_docs * key_capacity
        self.retain_log = retain_log
        # interning/clock/log/queue
        self.host = _blocks.BlockStore(n_docs, retain_log=retain_log)
        self._sharding = None
        if mesh is not None:
            from ..parallel.mesh import doc_sharding
            # whole documents per shard (doc-locality: apply scatters
            # stay shard-local), so the DOC count must divide
            if n_docs % mesh.devices.size:
                raise ValueError(
                    f'{n_docs} docs do not divide over '
                    f'{mesh.devices.size} devices')
            self._sharding = doc_sharding(mesh, ndim=2)
        self._applier = None          # lazy device-phase worker thread
        self._jobs = None
        self._last_async = None
        self._async_error = None      # first device-phase failure (fatal)
        self._alloc_planes()
        self._init_slots()

    def _init_slots(self):
        # per-DOC actor slots: actor_capacity bounds the number of
        # distinct actors per document, not store-wide. slot_actor is
        # the host mirror (doc, slot) -> store actor id; the string-rank
        # plane lives device-resident and re-ships only when it changes.
        self.slot_actor = np.full((self.n_docs, self.actor_capacity), -1,
                                  np.int32)
        self.slot_count = np.zeros(self.n_docs, np.int32)
        self._slot_keys = np.zeros(0, np.int64)   # sorted (doc<<32|actor)
        self._slot_vals = np.zeros(0, np.int32)   # parallel slot numbers
        self._rank_plane = None                   # device [D, A]
        self._rank_actors = -1    # actor-table size the plane was built at

    def _alloc_planes(self):
        shape = (self.n_fields, self.actor_capacity)
        self.eseq = jnp.zeros(shape, jnp.int32)
        self.eval_ = jnp.full(shape, _VAL_NONE, jnp.int32)
        self.m = jnp.full(shape, -1, jnp.int32)
        if self._sharding is not None:
            self.eseq = jax.device_put(self.eseq, self._sharding)
            self.eval_ = jax.device_put(self.eval_, self._sharding)
            self.m = jax.device_put(self.m, self._sharding)

    def reset(self):
        try:
            self.drain()
        except RuntimeError:
            pass          # reset discards the diverged planes anyway
        self._async_error = None
        self._alloc_planes()
        self.host = _blocks.BlockStore(self.n_docs,
                                       retain_log=self.retain_log)
        self._init_slots()

    # -- per-doc actor slots -------------------------------------------------

    def _slots_of(self, doc, actor, allocate=False):
        """Slot per (doc, store actor id) pair, vectorized; allocates
        fresh per-doc slots for unseen pairs when ``allocate``."""
        key = (doc.astype(np.int64) << 32) | actor
        pos = np.minimum(np.searchsorted(self._slot_keys, key),
                         max(len(self._slot_keys) - 1, 0))
        hit = (self._slot_keys[pos] == key) if len(self._slot_keys) \
            else np.zeros(len(key), bool)
        slots = np.full(len(key), -1, np.int32)
        if hit.any():
            slots[hit] = self._slot_vals[pos[hit]]
        miss = ~hit
        if allocate and miss.any():
            new_keys = np.unique(key[miss])
            new_docs = (new_keys >> 32).astype(np.int64)
            # per-doc sequential slot numbers continuing slot_count
            starts = np.flatnonzero(np.concatenate(
                [[True], new_docs[1:] != new_docs[:-1]]))
            run = np.arange(len(new_keys)) - np.repeat(
                starts, np.diff(np.append(starts, len(new_keys))))
            new_slots = (self.slot_count[new_docs] + run).astype(np.int32)
            if (new_slots >= self.actor_capacity).any():
                bad = int(new_docs[np.argmax(new_slots)])
                raise ValueError(
                    f'document {bad} exceeds actor_capacity='
                    f'{self.actor_capacity} distinct actors')
            self.slot_actor[new_docs, new_slots] = \
                (new_keys & 0xFFFFFFFF).astype(np.int32)
            np.maximum.at(self.slot_count, new_docs, new_slots + 1)
            merged = np.argsort(np.concatenate(
                [self._slot_keys, new_keys]), kind='stable')
            all_keys = np.concatenate([self._slot_keys, new_keys])
            all_vals = np.concatenate([self._slot_vals, new_slots])
            self._slot_keys = all_keys[merged]
            self._slot_vals = all_vals[merged]
            self._rank_actors = -1               # plane is stale
            # resolve the misses now that they exist
            pos = np.searchsorted(self._slot_keys, key[miss])
            slots[miss] = self._slot_vals[pos]
        elif miss.any():
            raise KeyError('unknown (doc, actor) pair in slot lookup')
        return slots

    def _rank_plane_dev(self):
        """Device-resident [D, A] actor string-rank plane, re-shipped
        only when slots were added or the actor table grew (global
        string ranks shift when a new actor interns)."""
        n_act = len(self.host.actors)
        if self._rank_plane is None or self._rank_actors != n_act:
            ranks = np.full((self.n_docs, self.actor_capacity), -1,
                            np.int64)
            filled = self.slot_actor >= 0
            ranks[filled] = self.host.actor_str_ranks()[
                self.slot_actor[filled]]
            plane = jnp.asarray(ranks.astype(np.int32))
            if self._sharding is not None:
                plane = jax.device_put(plane, self._sharding)
            self._rank_plane = plane
            self._rank_actors = n_act
        return self._rank_plane

    def _extract(self, mask):
        """Device patch extraction over a boolean field mask (shared by
        apply_block and extract_all)."""
        self.drain()
        f_pad = self.options.pad_segments(max(int(mask.sum()), 1))
        fidx, w_slot, w_value, alive, values = _extract_kernel(
            self.eseq, self.eval_, self.m, self._rank_plane_dev(),
            jnp.asarray(self.key_capacity), jnp.asarray(mask),
            f_pad=f_pad)
        return DensePatch(self, fidx, w_slot, w_value, alive, values)

    def extract_all(self):
        """Patch covering every populated field — materializes the whole
        store (the dense analogue of getPatch, backend/index.js:201-207)."""
        self.drain()
        populated = np.asarray((self.eseq != 0).any(axis=1))
        return self._extract(populated)

    # -- packed checkpoint (SURVEY §5: replay-free resume) -------------------

    def save_snapshot(self):
        """Serialize the packed device planes + host tables to bytes.

        Resume is replay-free: the planes load straight back into HBM.
        Size is the dense capacity plus the interned value table and the
        per-change closure CSR (both grow with applied history — the
        metadata that keeps future causal checks exact)."""
        import io
        import json
        self.drain()
        host = self.host
        host.log_sorted_keys()     # fold pending appends into l_order
        meta = {'format': 'automerge-tpu-dense-snapshot@1',
                'n_docs': self.n_docs,
                'key_capacity': self.key_capacity,
                'actor_capacity': self.actor_capacity,
                'retain_log': self.retain_log,
                'actors': host.actors, 'keys': host.keys,
                'values': list(host.values), 'queue': host.queue}
        buf = io.BytesIO()
        np.savez_compressed(
            buf,
            eseq=np.asarray(self.eseq), eval=np.asarray(self.eval_),
            m=np.asarray(self.m),
            slot_actor=self.slot_actor, slot_count=self.slot_count,
            c_doc=host.c_doc, c_actor=host.c_actor, c_seq=host.c_seq,
            l_key=host.l_key, l_order=host.l_order,
            l_dep_ptr=host.l_dep_ptr, l_dep_actor=host.l_dep_actor,
            l_dep_seq=host.l_dep_seq,
            meta=np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8))
        return buf.getvalue()

    @classmethod
    def load_snapshot(cls, data, options=None, mesh=None):
        """Rebuild a store from :meth:`save_snapshot` bytes.

        Meshes are runtime topology, not state, so the caller resupplies
        ``mesh`` to resume sharded (a store sized for a sharded HBM
        footprint should not be resumed single-device)."""
        import io
        import json
        with np.load(io.BytesIO(data)) as z:
            meta = json.loads(bytes(z['meta']).decode())
            if meta.get('format') != 'automerge-tpu-dense-snapshot@1':
                raise ValueError('not a dense-store snapshot')
            store = cls(meta['n_docs'],
                        key_capacity=meta['key_capacity'],
                        actor_capacity=meta['actor_capacity'],
                        options=options, mesh=mesh,
                        retain_log=meta.get('retain_log', True))
            want = (store.n_fields, store.actor_capacity)
            if z['eseq'].shape != want:
                raise ValueError(
                    f"incompatible snapshot: plane shape "
                    f"{z['eseq'].shape} != {want} (saved by an older "
                    f"format?)")
            def place(arr):
                if store._sharding is not None:
                    return jax.device_put(arr, store._sharding)
                return jnp.asarray(arr)
            store.eseq = place(z['eseq'])
            store.eval_ = place(z['eval'])
            store.m = place(z['m'])
            host = store.host
            host.actors = list(meta['actors'])
            host.actor_of = {a: i for i, a in enumerate(host.actors)}
            host.keys = list(meta['keys'])
            host.key_of = {k: i for i, k in enumerate(host.keys)}
            host.values = _blocks.ValueTable()
            host.values.extend(meta['values'])
            host.queue = [(d, ch) for d, ch in meta['queue']]
            host.c_doc = z['c_doc']
            host.c_actor = z['c_actor']
            host.c_seq = z['c_seq']
            # purity is an optimization hint; resumed chains re-derive
            # it conservatively (False costs a no-op closure gather)
            host.c_pure = np.zeros(len(host.c_doc), bool)
            host.l_key = z['l_key']
            host.l_order = z['l_order']
            host.l_dep_ptr = z['l_dep_ptr']
            host.l_dep_actor = z['l_dep_actor']
            host.l_dep_seq = z['l_dep_seq']
            # change bodies (retained blocks) are not serialized: the
            # resumed store can sync peers forward from here, but not
            # across the snapshot boundary
            host.log_truncated = True
            # dense snapshots do not carry state digests: the resumed
            # host store must not advertise zeros as real digests
            host._digest_valid = False
            if 'slot_actor' in z:
                store.slot_actor = z['slot_actor']
                store.slot_count = z['slot_count']
            else:
                # pre-slot snapshots used global slots == store ids
                n = len(host.actors)
                store.slot_actor[:, :n] = np.arange(n, dtype=np.int32)
                store.slot_count[:] = n
            # rebuild the sorted (doc<<32|actor) -> slot index
            docs, slots = np.nonzero(store.slot_actor >= 0)
            keys = (docs.astype(np.int64) << 32) \
                | store.slot_actor[docs, slots]
            order = np.argsort(keys, kind='stable')
            store._slot_keys = keys[order]
            store._slot_vals = slots[order].astype(np.int32)
        return store

    def _check_slot_capacity(self, block):
        """Reject a block whose (doc, actor) pairs would overflow any
        document's slot table — BEFORE any store mutation (conservative:
        counts queued and not-yet-admitted changes too)."""
        host = self.host
        tmp = {}

        def aid(name):
            """Stable counting id: store id, or a temporary for unseen."""
            i = host.actor_of.get(name)
            if i is None:
                i = tmp.get(name)
                if i is None:
                    i = tmp[name] = len(host.actors) + len(tmp)
            return i

        keys = np.zeros(0, np.int64)
        if block.n_changes:
            amap = np.asarray([aid(a) for a in block.actors], np.int64)
            keys = (block.doc.astype(np.int64) << 32) | amap[block.actor]
        if host.queue:
            qk = np.asarray(
                [(d << 32) | aid(ch['actor']) for d, ch in host.queue],
                np.int64)
            keys = np.concatenate([keys, qk])
        if not len(keys):
            return
        keys = np.unique(keys)
        pos = np.minimum(np.searchsorted(self._slot_keys, keys),
                         max(len(self._slot_keys) - 1, 0))
        exists = (self._slot_keys[pos] == keys) \
            if len(self._slot_keys) else np.zeros(len(keys), bool)
        fresh_docs = (keys[~exists] >> 32).astype(np.int64)
        if not len(fresh_docs):
            return
        counts = np.bincount(fresh_docs, minlength=self.n_docs)
        total = counts + self.slot_count
        if (total > self.actor_capacity).any():
            bad = int(np.argmax(total))
            raise ValueError(
                f'document {bad} would need {int(total[bad])} actor '
                f'slots, exceeding actor_capacity={self.actor_capacity}')

    def _stage_block(self, block):
        """Host phase of one apply: admission + wire-lean column
        packing. Returns (numpy kernel args, static kwargs) for
        :func:`_apply_extract_kernel` — the device phase (transfer +
        dispatch + plane swap) runs separately, either inline
        (:meth:`apply_block`) or on the applier thread
        (:meth:`apply_block_async`)."""
        import time
        host = self.host
        opts = self.options

        t0 = time.perf_counter()
        if block.is_general():
            raise ValueError(
                'block carries general ops (sequences/nested objects); '
                'apply through automerge_tpu.device.general')
        if block.has_dup_keys():
            # one dense cell per (field, actor) cannot hold two surviving
            # assignments from one change; reject BEFORE any mutation so
            # the store stays usable (the general path handles the shape)
            raise ValueError(
                'change assigns the same key twice (self-conflict shape); '
                'the dense store holds one entry per (field, actor) — '
                'apply through device.blocks.apply_block instead')
        _blocks.check_block_ranges(host, block)   # clear range errors
        self._check_slot_capacity(block)
        st = _blocks._admit_and_stage(host, block,
                                      max_keys=self.key_capacity)
        block = st.block
        t1 = time.perf_counter()

        # ---- compress + ship change columns (narrowest dtypes) ----
        adm = st.admitted
        rows = np.flatnonzero(adm)
        c_pad = opts.pad_ops(max(len(rows), 1))
        n_chg = len(rows)
        max_seq = int(block.seq[rows].max()) if n_chg else 0
        d_dtype = np.int16 if self.n_docs < (1 << 15) else np.int32
        a_dtype = np.uint8 if self.actor_capacity <= 256 else np.int32
        s_dtype = np.int16 if max_seq < (1 << 15) else np.int32
        counts = np.diff(block.op_ptr)[rows] if n_chg else \
            np.zeros(0, np.int32)
        k_dtype = np.uint8 if (n_chg == 0 or int(counts.max()) < 256) \
            else np.int32
        change_doc = np.zeros(c_pad, d_dtype)
        change_actor = np.zeros(c_pad, a_dtype)
        change_seq = np.zeros(c_pad, s_dtype)
        op_counts = np.zeros(c_pad, k_dtype)
        change_doc[:n_chg] = block.doc[rows]
        change_actor[:n_chg] = self._slots_of(
            block.doc[rows], st.b_actor[rows], allocate=True)
        change_seq[:n_chg] = block.seq[rows]
        # closure EXCEPTIONS in per-doc slot coordinates: the kernel
        # sets every change's own-actor entry to seq-1 itself, so only
        # the sparse cross-actor closure entries ship (zero for fully
        # concurrent batches AND for plain per-actor chains)
        A = self.actor_capacity
        R = st.R
        coo_row = coo_col = coo_val = np.zeros(0, np.int32)
        if R.any():
            Radm = R[rows]
            nz_r, nz_c = np.nonzero(Radm)
            store_id = st.la.store_of(block.doc[rows[nz_r]], nz_c)
            own = store_id == st.b_actor[rows[nz_r]]
            coo_row = nz_r[~own].astype(np.int32)
            # a closure actor always has an applied change on the doc,
            # hence a slot
            coo_col = self._slots_of(block.doc[rows[nz_r[~own]]],
                                     store_id[~own]).astype(np.int32)
            coo_val = Radm[nz_r[~own], nz_c[~own]].astype(np.int32)
        nnz_pad = opts.pad_ops(max(len(coo_row), 1))
        coo_row_p = np.full(nnz_pad, c_pad, np.int32)  # padding rows drop
        coo_row_p[:len(coo_row)] = coo_row
        coo_col_p = np.zeros(nnz_pad, a_dtype)
        coo_col_p[:len(coo_col)] = coo_col
        # closure seqs can reference PRIOR history beyond this block's
        # own seq range — bound the dtype by the actual values
        v_dtype = np.int16 if (len(coo_val) == 0
                               or int(coo_val.max()) < (1 << 15)) \
            else np.int32
        coo_val_p = np.zeros(nnz_pad, v_dtype)
        coo_val_p[:len(coo_val)] = coo_val

        op_counts[:n_chg] = counts
        rank_plane = self._rank_plane_dev()
        n_ops = len(st.oc)
        n_pad = opts.pad_ops(max(n_ops, 1))
        key_dtype = np.uint8 if self.key_capacity <= 256 else np.int32
        t2 = time.perf_counter()

        def finish_pack():
            # PURE reads of the (now-immutable) staged columns + fresh
            # array builds: safe to run on the applier thread, so a
            # pipelined caller's main thread pays only the state-
            # mutating phase above (admission, slots, rank plane)
            op_key = np.zeros(n_pad, key_dtype)
            op_key[:n_ops] = st.o_key
            is_del = st.o_action == _DEL
            op_isdel = np.zeros(n_pad, bool)
            op_isdel[:n_ops] = is_del
            # wire-lean fast path: sequential value refs reconstruct
            # on device
            v_base = int(st.o_value[~is_del][0]) if (~is_del).any() \
                else 0
            seq_values = bool(
                np.array_equal(st.o_value[~is_del],
                               np.arange(v_base,
                                         v_base + int((~is_del).sum()),
                                         dtype=np.int32)))
            if seq_values:
                op_value = np.zeros(1, np.int32)    # unused placeholder
            else:
                op_value = np.full(n_pad, -1, np.int32)
                op_value[:n_ops] = st.o_value
            # touched fields, bit-packed for the wire
            touched = np.zeros(self.n_fields, bool)
            fk = st.o_doc.astype(np.int64) * self.key_capacity + st.o_key
            touched[fk] = True
            # floor the extract bucket at 4096 so sparse ticks share
            # ONE compile of the fused kernel (f_pad is static; an
            # unfloored pow2 would recompile per touched-count bucket)
            f_pad = opts.pad_segments(
                max(int(touched.sum()), min(4096, self.n_fields)))
            args = (change_doc, change_actor, change_seq, op_counts,
                    coo_row_p, coo_col_p, coo_val_p, op_key,
                    np.packbits(op_isdel), op_value, np.int32(n_ops),
                    np.int32(self.key_capacity), np.int32(v_base),
                    rank_plane, np.packbits(touched))
            statics = dict(n_fields=self.n_fields, n_actors=A,
                           seq_values=seq_values, f_pad=f_pad)
            return args, statics

        metrics.bump('dense_batches')
        metrics.bump('dense_ops', n_ops)
        return finish_pack, (t0, t1, t2)

    def apply_block(self, block, return_timing=False):
        """Apply a :class:`~.blocks.ChangeBlock`; returns a
        :class:`DensePatch` (device-resident; materialize lazily)."""
        import time
        self.drain()
        finish_pack, (t0, t1, t2) = self._stage_block(block)
        args, statics = finish_pack()
        _note_dense_dispatch(self, args, statics)
        out = _apply_extract_kernel(self.eseq, self.eval_, self.m,
                                    *args, **statics)
        self.eseq, self.eval_, self.m = out[:3]
        patch = DensePatch(self, *out[3:])
        t3 = time.perf_counter()
        if return_timing:
            return patch, {'admit': t1 - t0, 'pack': t2 - t1,
                           'dispatch': t3 - t2}
        return patch

    def apply_block_async(self, block):
        """Apply with the device phase (H2D transfer + dispatch + plane
        swap) on the store's applier thread: the caller's next host
        staging overlaps this block's transfers and device program —
        the frontend/backend overlap the reference's split anticipates
        (frontend/index.js:91-104), engine-side. Returns a
        :class:`DensePatch` whose reads wait for the device phase.

        Host staging stays on the calling thread (store host state is
        single-writer); successive async applies are serialized by the
        applier queue. Synchronous readers (:meth:`apply_block`,
        :meth:`extract_all`, :meth:`reset`, :meth:`save_snapshot`)
        drain the queue first."""
        import threading
        if self._async_error is not None:
            raise RuntimeError(
                'a previous async apply failed on device; the device '
                'planes no longer match the host clock/log — restore '
                'from a snapshot or rebuild the store') \
                from self._async_error
        finish_pack, _ = self._stage_block(block)
        patch = DensePatch(self)
        patch._event = threading.Event()

        def job():
            try:
                if self._async_error is not None:
                    # a predecessor failed: the planes are behind the
                    # host clock/log; refuse rather than diverge further
                    raise RuntimeError(
                        'skipped: a previous async apply failed') \
                        from self._async_error
                args, statics = finish_pack()
                _note_dense_dispatch(self, args, statics)
                out = _apply_extract_kernel(self.eseq, self.eval_,
                                            self.m, *args, **statics)
                self.eseq, self.eval_, self.m = out[:3]
                patch._resolve_async(out[3:])
            except BaseException as e:       # surfaced on drain/reads
                patch._error = e
                if self._async_error is None:
                    self._async_error = e
            finally:
                patch._event.set()

        self._submit(job)
        self._last_async = patch
        return patch

    def _submit(self, job):
        if self._applier is None:
            import queue
            import threading
            self._jobs = queue.Queue()

            def run():
                while True:
                    j = self._jobs.get()
                    if j is None:
                        return
                    j()

            self._applier = threading.Thread(target=run, daemon=True)
            self._applier.start()
        self._jobs.put(job)

    def drain(self):
        """Wait for any in-flight async applies (device-phase order is
        the applier queue order, so waiting on the last one suffices).
        Raises the FIRST async failure — a failed device phase leaves
        the planes behind the already-committed host clock/log, which
        only a snapshot restore or rebuild can reconcile."""
        p = self._last_async
        if p is not None:
            self._last_async = None
            if p._event is not None:
                p._event.wait()
        if self._async_error is not None:
            raise RuntimeError(
                'an async apply failed on device; the planes are behind '
                'the committed host clock/log — reset() or restore from '
                'a snapshot') from self._async_error

    def close(self):
        """Stop the applier thread (after draining). The store remains
        usable synchronously; a later apply_block_async restarts it."""
        try:
            self.drain()
        finally:
            if self._applier is not None:
                self._jobs.put(None)
                self._applier.join()
                self._applier = None
                self._jobs = None
