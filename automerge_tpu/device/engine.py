"""Batched document-store engine: many documents, one device program.

This is the TPU realization of the north star: `applyChanges` vmap'd
across every document in a DocSet. The host packs change batches into
dense arrays (:mod:`.packing`), one jitted program resolves every field of
every document (:mod:`.merge`), and the winners map back to JSON values.

For workloads the oracle backend walks op-by-op (O(total ops) of Python/JS
dict churn), this path does two segment reductions and a couple of gathers
over the whole batch — the per-op cost is a few HBM-bandwidth-bound array
lanes, which is what makes million-op merges per chip feasible.
"""

import numpy as np
import jax
import jax.numpy as jnp

from ..config import DEFAULT as DEFAULT_OPTIONS
from ..utils.metrics import metrics
from . import merge as merge_kernel
from . import packing


def as_options(options=None, kernel=None):
    """Normalize (options, legacy kernel kwarg) into one Options."""
    if options is None:
        options = DEFAULT_OPTIONS
    if kernel is not None and kernel != options.kernel:
        options = options.with_(kernel=kernel)
    return options


def _pallas_wins(n_docs, n_ops, n_actors):
    """Whether the Pallas kernel should run for this shape.

    Feasibility: the kernel keeps one DOC_BLOCK of every plane resident
    — 5 int32 inputs + 3 outputs + 1 scratch + the [.., n_actors]
    clock, i.e. DOC_BLOCK * n_pad * (9 + n_actors) * 4 bytes — and
    unrolls ~3 * n_tiles^2 tile-pair bodies; past those bounds Mosaic
    either fails allocation or compiles pathologically.

    Profitability (measured on v5e, amortized per-dispatch, r3):
    pallas wins on LARGE doc batches with few op tiles — 2.26x at
    [10240 x 128 x 8] (46.5 vs 105.2 ms, 28M vs 12M ops/s), 1.5x at
    [1024 x 128 x 8] — and loses ~0.85x once the tile-pair unroll grows
    ([256 x 512 x 16], [8 x 1024 x 8]) or the doc grid is too small to
    fill the chip. Hence: tiles <= 2 AND docs >= 256.
    """
    from . import pallas_merge as pm
    n_pad = pm._round_up(max(n_ops, pm.OPS_TILE), pm.OPS_TILE)
    vmem_bytes = pm.DOC_BLOCK * n_pad * (9 + n_actors) * 4
    n_tiles = n_pad // pm.OPS_TILE
    return (vmem_bytes <= 8 * 1024 * 1024 and n_tiles <= 2
            and n_docs >= 256)


def pick_resolve_kernel(kernel='auto'):
    """Select the field-resolution kernel implementation.

    'xla'    — segment-reduction path (merge.py), runs everywhere.
    'pallas' — hand-scheduled VMEM-resident kernel (pallas_merge.py);
               requires a TPU backend (Mosaic).
    'auto'   — on TPU, pallas for the shapes where the measured A/B
               says it wins (large doc batches, few op tiles — see
               `_pallas_wins`), xla otherwise and on non-TPU backends.
    """
    if kernel == 'auto':
        if jax.default_backend() != 'tpu':
            return merge_kernel.resolve_assignments_batch

        def dispatch(seg_id, actor, seq, clock, is_del, valid, *, num_segments):
            if _pallas_wins(seg_id.shape[0], seg_id.shape[1],
                            clock.shape[2]):
                from . import pallas_merge
                fn = pallas_merge.resolve_assignments_batch_pallas
            else:
                fn = merge_kernel.resolve_assignments_batch
            return fn(seg_id, actor, seq, clock, is_del, valid,
                      num_segments=num_segments)
        return dispatch
    if kernel == 'pallas':
        from . import pallas_merge
        return pallas_merge.resolve_assignments_batch_pallas
    return merge_kernel.resolve_assignments_batch


class DocStore:
    """A batch of documents resolved on device.

    Round-1 scope: flat map documents (the DocSet batch-merge workload,
    BASELINE config 5). Nested object graphs and sequences run through the
    oracle backend or the sequence kernel respectively.
    """

    def __init__(self):
        self.resolved = []    # per doc: {(obj, key): {'value','action','conflicts'}}

    @classmethod
    def from_changes(cls, docs_changes):
        store = cls()
        store.resolved = batch_merge_docs(docs_changes)
        return store

    def materialize(self, doc_index, obj_id):
        """Plain {key: value} for one (flat) object of one document."""
        return {key: field['value']
                for (obj, key), field in self.resolved[doc_index].items()
                if obj == obj_id and field['action'] == 'set'}


def unpack_resolved(packed, surviving_row, winner_row):
    """Turn one document's kernel outputs back into JSON field state.

    Shared by the single-chip and sharded engines so the two can never
    diverge. O(N + S) per document: survivors are grouped by segment in one
    pass instead of rescanning the op array per field.
    """
    n_real = len(packed.op_meta)
    by_seg = {}
    for j in np.flatnonzero(surviving_row[:n_real]):
        by_seg.setdefault(int(packed.seg_id[j]), []).append(j)

    doc_fields = {}
    for s, field in enumerate(packed.segments):
        w = winner_row[s]
        if w < 0 or not surviving_row[w]:
            doc_fields[field] = {'action': 'remove', 'value': None,
                                 'conflicts': None}
            continue
        action, value = packed.op_meta[w]
        conflicts = None
        survivors = by_seg.get(s, [])
        if len(survivors) > 1:
            losers = sorted((j for j in survivors if j != w),
                            key=lambda j: packed.actor_names[packed.actor[j]],
                            reverse=True)
            conflicts = {packed.actor_names[packed.actor[j]]: packed.op_meta[j][1]
                         for j in losers}
        doc_fields[field] = {'action': 'set', 'value': value,
                             'conflicts': conflicts, 'link': action == 'link'}
    return doc_fields


def batch_merge_docs(docs_changes, return_timing=False, kernel=None,
                     options=None):
    """Merge a batch of change lists, one per document, on device.

    Args:
      docs_changes: list over documents; each entry is a list of changes
        (causally self-contained per document).
      return_timing: also return a dict of phase timings.
      options: :class:`~automerge_tpu.config.Options` (kernel choice and
        padding policy); `kernel` remains as a shorthand override.

    Returns:
      per-doc dict {(obj, key): {'action': 'set'|'remove', 'value', 'conflicts'}}
      matching exactly what the oracle's field state would be.
    """
    import time
    opts = as_options(options, kernel)
    t0 = time.perf_counter()
    packed = [packing.pack_assignments(changes) for changes in docs_changes]
    seg_id, actor, seq, clock, is_del, valid, n_pad = packing.pad_and_stack(
        packed, n_ops=opts.op_pad, n_actors=opts.actor_pad,
        index_dtype=opts.index_dtype, clock_dtype=opts.clock_dtype)
    n_segs = opts.pad_segments(max((p.n_segments for p in packed), default=1))
    t1 = time.perf_counter()

    resolve = pick_resolve_kernel(opts.kernel)
    from . import profiler as _profiler
    _profiler.note_dispatch(
        'engine.resolve',
        (getattr(resolve, '__name__', 'resolve'), seg_id.shape,
         clock.shape, str(seg_id.dtype), str(clock.dtype), n_segs),
        rows=seg_id.shape[0])
    out = resolve(
        jnp.asarray(seg_id), jnp.asarray(actor), jnp.asarray(seq),
        jnp.asarray(clock), jnp.asarray(is_del), jnp.asarray(valid),
        num_segments=n_segs)
    surviving = np.asarray(out['surviving'])
    winner = np.asarray(out['winner'])
    t2 = time.perf_counter()

    results = [unpack_resolved(p, surviving[i], winner[i])
               for i, p in enumerate(packed)]
    t3 = time.perf_counter()

    real_ops = int(valid.sum())
    metrics.bump('device_batches')
    metrics.bump('device_ops', real_ops)
    metrics.set_gauge('device_batch_occupancy',
                      real_ops / max(valid.size, 1))
    if metrics.active:
        metrics.emit('device_batch', docs=len(packed), ops=real_ops,
                     padded_ops=int(valid.size), pack_s=t1 - t0,
                     device_s=t2 - t1, unpack_s=t3 - t2)

    if return_timing:
        return results, {'pack': t1 - t0, 'device': t2 - t1, 'unpack': t3 - t2}
    return results
