"""General bulk engine: sequences, nested objects and links on the
million-op block path.

The flat block engine (:mod:`.blocks`) covers root-map documents; this
module is the same architecture — vectorized causal admission, ONE fused
device program, columnar patches — for the FULL op set of the reference
backend (`applyOps`, op_set.js:221-238): ``makeMap/makeList/makeText``,
``ins``, ``set/del/link`` on any object. A million-keystroke text
history with causal deps, nested object graphs across thousands of
documents, and plain map batches all take the same path.

Representation choices that make it columnar:

* **Objects** are store rows interned per (doc, uuid); the object table
  carries type/doc/inbound. Object count is bounded by 2^22 (same as
  the doc key space).
* **Field keys** pack into one int64: ``(obj_row << 32) | (is_elem <<
  31) | id`` where ``id`` is an interned string key (maps) or the
  element's LOCAL NODE INDEX in its object's insertion tree (node
  indexes are append-only, hence stable) — so field identity, touched-
  set membership and segment grouping are plain sorts/searchsorted on
  one integer column, never string or tuple comparisons.
* **Insertion trees** live POOLED across all sequence objects as
  store-level node columns (:class:`_SeqPool`) with a sorted
  (obj, local) position index — appends, elemId lookups, dup checks and
  RGA job-plane packing are whole-batch array passes over every dirty
  object at once, not per-object loops. The device-side RGA kernel
  (:mod:`.sequence`) orders each dirty object in O(log n) parallel
  rounds, replacing the reference's per-element skip-list walks
  (op_set.js:379-425, skip_list.js).
* **Resolution** of every touched field of every document is one flat
  segment-reduction program (:mod:`.merge`), with element visibility
  derived on device and every dirty sequence re-ordered in the same
  jitted call — the general-path analogue of the per-doc backend's
  fused step (backend.py `_fused_step`).

Conformance: same contracts as the flat path — causal buffering with
retry (op_set.js:267-283), duplicate verification (op_set.js:243-248),
self-conflicts for within-change double assignment, winner = highest
actor rank with stable first-op tie-break (op_set.js:211). Sequence
diffs are the compacted remove/insert/set stream of the per-doc backend
(remove at old indexes descending, insert at final indexes ascending,
then sets), plus the ``maxElem`` extension. A malformed block (unknown
object, duplicate creation, duplicate elemId, unknown parent element)
raises and leaves the store EXACTLY as it was — admission effects
(clock, log, queue, retained blocks, interned tables) roll back, so a
valid retry is never mis-dropped as a duplicate.

Undo/redo and local-change requests stay per-document
(:mod:`.backend`): this engine is the bulk ingestion path behind
``applyChanges`` — exactly the role `DocSet.applyChanges` plays in the
reference (src/doc_set.js:25-33), at block scale.
"""

import threading

import numpy as np
import jax
import jax.numpy as jnp

from functools import partial

from ..common import ROOT_ID
from ..utils.metrics import metrics
from . import engine as _engine
from . import profiler as _profiler
from . import blocks as _blocks
from .blocks import (
    ChangeBlock, BlockStore, ValueTable, _intern, _span_indices,
    _admit_and_stage, check_block_ranges,
    _SET, _DEL, _INS, _LINK, _MAKE_MAP, _MAKE_LIST, _MAKE_TEXT,
    _GEN_ACTION_NAMES, _KEY_STR, _KEY_ELEM, _KEY_HEAD, _KEY_NONE)

_TYPE_MAP, _TYPE_LIST, _TYPE_TEXT = 0, 1, 2
_MAKE_TYPE = {_MAKE_MAP: _TYPE_MAP, _MAKE_LIST: _TYPE_LIST,
              _MAKE_TEXT: _TYPE_TEXT}
_TYPE_NAME = {_TYPE_MAP: 'map', _TYPE_LIST: 'list', _TYPE_TEXT: 'text'}

_ELEM_BIT = np.int64(1) << 31
_HEAD_KEY = np.int64(-1) << 32        # pool key of a head node (actor -1)


class _SeqPool:
    """ALL sequence objects' insertion trees, pooled into store-level
    node columns (the batch-vectorized replacement for per-object
    states; VERDICT r3 #1).

    Columns are global over every node of every list/text object:
    ``obj`` (owning object row), ``local`` (node index within the
    object; 0 is the virtual head), ``parent`` (LOCAL index), ``actor``
    (store actor id, -1 for heads), ``elemc`` (elem counter), and the
    CURRENT visibility/order (``visible``/``vis_index``, -1 hidden).
    ``pos_row`` holds global row ids sorted by the packed (obj << 32 |
    local) position key (``pos_sorted``) — so any set of objects'
    node rows gather as contiguous spans, in local order, with one
    searchsorted: per-object views, elemId resolution tables and RGA
    job planes are all single vectorized gathers.

    The trees are DEVICE-RESIDENT between applies (``mirror``): the
    node columns live in HBM in POSITION order (obj-major, so every
    object's nodes are one contiguous slice), each apply ships only the
    NEW nodes plus their insert positions, and the fused program
    rebuilds the order, gathers its own job planes, and scatters the
    updated visibility back — a growing collab session ships O(block)
    bytes per apply, not O(total tree). The host visibility columns
    materialize lazily from the mirror (``sync``), so an apply-only
    pipeline never pays a D2H. Appends come in whole-batch calls
    (obj-grouped, local-ascending), merged into the position index with
    one searchsorted + insert.
    """

    __slots__ = ('obj', 'local', 'parent', 'actor', 'elemc', 'visible',
                 'vis_index', 'tpos', 'idx_ok', 'idx_linear',
                 'pos_sorted', 'pos_row',
                 'n_of', 'max_elem_of', 'max_tree', 'max_elem',
                 'mirror', '_epoch', '_host_epoch', '_tpos_epoch',
                 '_lock', '_elem_cache')

    def __init__(self):
        # host lock shared with the owning store: serializes the apply
        # host phase, the deferred commit and this sync against patch
        # extraction running on another thread (apply_general_block_async)
        self._lock = threading.RLock()
        z32 = np.zeros(0, np.int32)
        self.obj = z32
        self.local = z32
        self.parent = z32
        self.actor = z32
        self.elemc = z32
        self.visible = np.zeros(0, bool)
        self.vis_index = z32
        # host materialization of the device-resident ORDER index
        # (tree_pos per node; fetched on demand by sync_index — the
        # snapshot/compaction path, never the per-tick read path)
        self.tpos = z32
        # per-OBJECT: the mirror's 'tp' plane holds this object's true
        # tree positions (the incremental-update eligibility bit; False
        # forces a whole-object _rga_order rebuild on next touch)
        self.idx_ok = np.zeros(0, bool)
        # per-OBJECT: the tree is a pure chain (parent[local] ==
        # local - 1 for every real node), so tree position == local
        # and a suffix of locals is a suffix of tree positions — the
        # eligibility bit of the suffix-bounded visibility renumber.
        # Maintained in O(appended) by _append; never un-falsed (a
        # branch is permanent until compaction rebuilds the object).
        self.idx_linear = np.zeros(0, bool)
        # per-OBJECT staging cache: obj -> [keys_sorted, locals], the
        # sorted (actor << 32 | elem) -> local index both stagers
        # consult in O(delta) instead of re-tabulating every node of
        # every dirty object per tick. Built post-apply for dirty
        # objects, extended in place by append_batch, dropped
        # wholesale on rollback (see _Txn) and on snapshot restore
        # (fresh pool). Heads are excluded (never a lookup target).
        self._elem_cache = {}
        self.pos_sorted = np.zeros(0, np.int64)
        self.pos_row = np.zeros(0, np.int64)
        self.n_of = np.zeros(0, np.int64)        # per OBJECT row
        self.max_elem_of = np.zeros(0, np.int64)
        self.max_tree = 0        # pool-wide max n_of (packed-fmt guard)
        self.max_elem = 0        # pool-wide max elemc (packed-fmt guard)
        # device mirror: {'cap', 'n', 'parent', 'elemc', 'actor',
        # 'visible', 'vis_index' (device arrays, POS order), 'rank_n'}
        # (+ 'tp': int32 tree_pos per node on packed/wide — the
        # persistent order-statistic index the incremental update
        # maintains across ticks)
        self.mirror = None
        self._epoch = 0          # bumped per apply that dirtied trees
        self._host_epoch = 0     # host visible/vis_index currency
        self._tpos_epoch = 0     # host tpos currency (sync_index)

    @property
    def n_nodes(self):
        return len(self.obj)

    def grow_objects(self, n_objs):
        if len(self.n_of) < n_objs:
            pad = n_objs - len(self.n_of)
            self.n_of = np.concatenate(
                [self.n_of, np.zeros(pad, np.int64)])
            self.max_elem_of = np.concatenate(
                [self.max_elem_of, np.zeros(pad, np.int64)])
            # a fresh object has no device-resident index yet
            self.idx_ok = np.concatenate(
                [self.idx_ok, np.zeros(pad, bool)])
            self.idx_linear = np.concatenate(
                [self.idx_linear, np.zeros(pad, bool)])

    def _append(self, obj, local, parent, actor, elemc):
        base = len(self.obj)
        n = len(obj)
        self.obj = np.concatenate([self.obj, obj])
        self.local = np.concatenate([self.local, local])
        self.parent = np.concatenate([self.parent, parent])
        self.actor = np.concatenate([self.actor, actor])
        self.elemc = np.concatenate([self.elemc, elemc])
        self.visible = np.concatenate([self.visible, np.zeros(n, bool)])
        self.vis_index = np.concatenate(
            [self.vis_index, np.full(n, -1, np.int32)])
        self.tpos = np.concatenate([self.tpos, np.zeros(n, np.int32)])
        keys = (obj.astype(np.int64) << 32) | local
        new_rows = base + np.arange(n, dtype=np.int64)
        m = len(self.pos_sorted)
        if (m == 0 or keys[0] > self.pos_sorted[-1]) and \
                (n == 1 or (keys[1:] > keys[:-1]).all()):
            # tail append (sequential typing): skip np.insert's fancy
            # index handling — a plain concat keeps the order
            self.pos_sorted = np.concatenate([self.pos_sorted, keys])
            self.pos_row = np.concatenate([self.pos_row, new_rows])
        else:
            pos = np.searchsorted(self.pos_sorted, keys)
            self.pos_sorted = np.insert(self.pos_sorted, pos, keys)
            self.pos_row = np.insert(self.pos_row, pos, new_rows)
        # chain-shape maintenance, O(appended): any node whose parent
        # is not its predecessor permanently branches the object
        ok_chain = (local == 0) | (parent == local - 1)
        np.logical_and.at(self.idx_linear, obj, ok_chain)

    def create_heads(self, rows):
        """Batch-create the virtual head node of NEW sequence objects
        (`rows` ascending)."""
        if not len(rows):
            return
        self.grow_objects(int(rows.max()) + 1)
        z = np.zeros(len(rows), np.int32)
        self._append(rows.astype(np.int32), z, z,
                     np.full(len(rows), -1, np.int32), z)
        self.n_of[rows] = 1
        self.idx_linear[rows] = True     # a lone head is a chain
        self.max_tree = max(self.max_tree, 1)

    def append_batch(self, obj, local, parent_local, actor, elemc):
        """Append new nodes, whole batch: `obj` ascending, `local`
        ascending within each object (= n_of[obj] + position)."""
        if not len(obj):
            return
        self._append(obj.astype(np.int32), local.astype(np.int32),
                     parent_local.astype(np.int32), actor.astype(np.int32),
                     elemc.astype(np.int32))
        run_start = np.concatenate([[True], obj[1:] != obj[:-1]])
        starts = np.flatnonzero(run_start)
        ends = np.append(starts[1:], len(obj)) - 1
        uo = obj[starts]
        self.n_of[uo] = local[ends] + 1
        seg_max = np.maximum.reduceat(elemc, starts)
        self.max_elem_of[uo] = np.maximum(self.max_elem_of[uo], seg_max)
        self.max_tree = max(self.max_tree, int(local[ends].max()) + 1)
        self.max_elem = max(self.max_elem, int(seg_max.max()))
        # staging-cache upkeep in O(new): resident per-object elemId
        # indexes absorb the appended nodes (sequential typing appends
        # ascending keys — a pure tail concat)
        if self._elem_cache:
            for k, o in enumerate(uo.tolist()):
                ent = self._elem_cache.get(o)
                if ent is None:
                    continue
                s, e = starts[k], ends[k] + 1
                nk = (actor[s:e].astype(np.int64) << 32) | \
                    elemc[s:e].astype(np.int64)
                nl = local[s:e].astype(np.int64)
                if len(nk) > 1 and not (nk[1:] > nk[:-1]).all():
                    o2 = np.argsort(nk, kind='stable')
                    nk, nl = nk[o2], nl[o2]
                keys0, locs0 = ent
                if not len(keys0) or nk[0] > keys0[-1]:
                    ent[0] = np.concatenate([keys0, nk])
                    ent[1] = np.concatenate([locs0, nl])
                else:
                    p = np.searchsorted(keys0, nk)
                    ent[0] = np.insert(keys0, p, nk)
                    ent[1] = np.insert(locs0, p, nl)

    def rows_of_objs(self, objs):
        """(global rows, node counts): all nodes of `objs`, grouped in
        the given object order, local-ascending within each."""
        objs = np.asarray(objs, np.int64)
        lo = np.searchsorted(self.pos_sorted, objs << 32)
        counts = self.n_of[objs]
        return self.pos_row[_span_indices(lo, counts)], counts

    def row_at(self, obj, local):
        """Global row of one (obj, local) node."""
        pos = np.searchsorted(self.pos_sorted,
                              (np.int64(obj) << 32) | np.int64(local))
        return int(self.pos_row[pos])

    def node_keys(self, rows):
        """Packed (actor << 32 | elem) elemId keys of `rows` (heads get
        the _HEAD_KEY sentinel, distinct from every real key)."""
        return (self.actor[rows].astype(np.int64) << 32) | \
            self.elemc[rows].astype(np.int64)

    def elem_index(self, obj):
        """The staging cache of one object: sorted ``(actor << 32 |
        elem)`` keys and their node locals (heads excluded). Builds
        once in O(n_of[obj]); ``append_batch`` extends resident
        entries in O(new), so warm-doc stagers resolve parents and
        check duplicates in O(delta log n)."""
        ent = self._elem_cache.get(obj)
        if ent is None:
            rows, _ = self.rows_of_objs(np.asarray([obj], np.int64))
            real = self.actor[rows] >= 0
            rows = rows[real]
            keys = (self.actor[rows].astype(np.int64) << 32) | \
                self.elemc[rows].astype(np.int64)
            order = np.argsort(keys, kind='stable')
            ent = [keys[order],
                   self.local[rows][order].astype(np.int64)]
            self._elem_cache[obj] = ent
        return ent

    def sync(self):
        """Materialize the device mirror's visibility/order into the
        host columns (once per apply epoch; idempotent). The mirror is
        pos-ordered; ``pos_row`` maps it back to global row coords.
        Nodes appended since the mirror's last apply keep their
        initial (hidden) host state — the mirror rows cover exactly
        the first ``mirror['n']`` positions."""
        with self._lock:
            if self._host_epoch == self._epoch or self.mirror is None:
                return
            self._host_epoch = self._epoch
            n = self.mirror['n']
            fmt = self.mirror.get('fmt')
            if fmt == 'packed':
                # ONE 4B/node fetch; the vis word host-unpacks for free
                w2 = np.asarray(jax.device_get(self.mirror['w2'][:n]))
                vis, idx = unpack_w2_word(w2)
            elif fmt == 'wide':
                # same 4B/node fetch: W2 carries visible + vis_index
                w2 = np.asarray(jax.device_get(self.mirror['w2'][:n]))
                vis, idx = unpack_wide_word(w2)
            else:
                vis, idx = jax.device_get(
                    (self.mirror['visible'][:n],
                     self.mirror['vis_index'][:n]))
            # the mirror's OWN pos_row snapshot: appends since the apply
            # (e.g. single obj_row creates) must not shift the mapping
            rows = self.mirror['pos_row'][:n]
            self.visible[rows] = np.asarray(vis)
            self.vis_index[rows] = np.asarray(idx)

    def sync_index(self):
        """Materialize the device-resident ORDER index (the mirror's
        'tp' tree_pos plane) into the host ``tpos`` column — the
        snapshot/compaction counterpart of :meth:`sync`, fetched on
        demand so the per-tick read path never pays the extra D2H.
        Host ``tpos`` values are meaningful exactly for objects whose
        ``idx_ok`` bit is set (the same validity contract as the
        device plane)."""
        with self._lock:
            if self.mirror is None or 'tp' not in self.mirror:
                return
            if self._tpos_epoch == self._epoch:
                return
            self._tpos_epoch = self._epoch
            n = self.mirror['n']
            tp = np.asarray(jax.device_get(self.mirror['tp'][:n]))
            rows = self.mirror['pos_row'][:n]
            self.tpos[rows] = tp


def _exact_lookup(t_obj, t_key, t_val, q_obj, q_key, n_objs):
    """Exact-match (obj, key) -> val lookup, whole batch: `t_*` is an
    UNSORTED table with unique (obj, key) rows, `*_obj` are DENSE object
    indexes < n_objs. One composite sort when the pair packs into
    uint64, one lexsort otherwise. Returns per-query val (-1 miss) and
    a within-table duplicate flag (True if the table itself held two
    equal (obj, key) rows — the caller's dup check)."""
    q = len(q_key)
    n = len(t_key)
    out = np.full(q, -1, np.int64)
    if n == 0:
        return out, False
    # keys shift to >= 0: real keys are >= 0, the head sentinel maps to 0
    t_k = np.where(t_key == _HEAD_KEY, 0, t_key + 1)
    q_k = np.where(q_key == _HEAD_KEY, 0, q_key + 1)
    kmax = max(int(t_k.max()), int(q_k.max()) if q else 0)
    if n_objs <= (1 << 11) and kmax < (1 << 53):
        # composite: key < 2^53 (actor < 2^21, elem < 2^31), obj < 2^11
        t_comp = (t_obj.astype(np.uint64) << np.uint64(53)) | \
            t_k.astype(np.uint64)
        order = np.argsort(t_comp, kind='stable')
        t_sorted = t_comp[order]
        dup = bool(n > 1 and (t_sorted[1:] == t_sorted[:-1]).any())
        if q:
            q_comp = (q_obj.astype(np.uint64) << np.uint64(53)) | \
                q_k.astype(np.uint64)
            pos = np.minimum(np.searchsorted(t_sorted, q_comp), n - 1)
            hit = t_sorted[pos] == q_comp
            out[hit] = t_val[order[pos[hit]]]
        return out, dup
    # wide path: objects do not fit the packed composite
    isq = np.zeros(n + q, bool)
    isq[n:] = True
    obj = np.concatenate([t_obj, q_obj])
    key = np.concatenate([t_k, q_k])
    order = np.lexsort((isq, key, obj))
    is_t = ~isq[order]
    t_pos = np.flatnonzero(is_t)
    dup = bool(len(t_pos) > 1 and
               ((obj[order[t_pos[1:]]] == obj[order[t_pos[:-1]]]) &
                (key[order[t_pos[1:]]] == key[order[t_pos[:-1]]])).any())
    if q:
        last_t = np.maximum.accumulate(
            np.where(is_t, np.arange(n + q), -1))
        qsel = np.flatnonzero(isq[order])
        cand = last_t[qsel]
        qidx = order[qsel] - n
        ok = cand >= 0
        cnd = order[np.maximum(cand, 0)]
        ok &= (obj[cnd] == q_obj[qidx]) & (key[cnd] == q_k[qidx])
        out[qidx[ok]] = t_val[cnd[ok]]
    return out, dup


class _Txn:
    """Rollback snapshot for the store-intact-on-error contract: a
    malformed block that fails validation AFTER admission merged it into
    the clock/log must leave the store exactly as before the apply (else
    a later valid retry is silently dropped as a duplicate — the r3
    advisor's data-loss finding). Capture is O(changed-state refs) plus
    two small copies (clock seqs, pool per-object counters)."""

    def __init__(self, store):
        pool = store.pool
        self.pending = store._pending_commit
        self.pool_mirror = pool.mirror
        self.pool_epochs = (pool._epoch, pool._host_epoch)
        self.queue = list(store.queue)
        # clock rollback is journaled, not copied: clock_merge records
        # (positions, old seqs, old purity, array refs) for every
        # in-place scatter, so the snapshot is the refs + an empty
        # journal — O(delta) per apply instead of O(clock table)
        self.c_doc, self.c_actor = store.c_doc, store.c_actor
        self.c_seq, self.c_pure = store.c_seq, store.c_pure
        store._c_journal = []
        self.log = (store.l_key, store.l_order, store._l_sorted,
                    list(store._l_pending), store.l_dep_ptr,
                    store.l_dep_actor, store.l_dep_seq)
        self.n_retained = len(store.retained)
        self.n_actors = len(store.actors)
        self.n_keys = len(store.keys)
        self.v_mark = store.values._mark()
        self.n_objs = len(store.obj_uuid)
        self.root_row = store._root_row.copy()
        self.entries = (store.e_doc, store.e_obj, store.e_key,
                        store.e_actor, store.e_seq, store.e_value,
                        store.e_link, store.e_change)
        self.pool_cols = (pool.obj, pool.local, pool.parent, pool.actor,
                          pool.elemc, pool.visible, pool.vis_index,
                          pool.tpos, pool.pos_sorted, pool.pos_row)
        self.pool_n = (pool.n_of.copy(), pool.max_elem_of.copy(),
                       pool.max_tree, pool.max_elem,
                       pool.idx_ok.copy(), pool._tpos_epoch,
                       pool.idx_linear.copy())
        # digest fold is copy-on-fold and reads never interleave an
        # apply, so the array REFERENCE plus the pending length is a
        # complete rollback record — no per-apply copy
        self.digest = store._digest
        self.n_digest_pending = len(store._digest_pending)

    def rollback(self, store):
        pool = store.pool
        # restore the deferred-commit record alongside the entry refs:
        # the store returns to "previous apply dispatched, uncommitted",
        # and the (idempotent) commit replays on the next entry read
        store._pending_commit = self.pending
        # the device mirror is only replaced AFTER the (raise-free)
        # dispatch, but restore it — and the sync epochs — anyway so a
        # partially-staged apply leaves the resident state exactly as
        # found (an intervening pool.sync() was committed-state
        # materialization and stays correct under the restored refs)
        store.pool.mirror = self.pool_mirror
        store.pool._epoch, store.pool._host_epoch = self.pool_epochs
        store.queue = self.queue
        # undo the journaled in-place clock scatters (each entry
        # carries its own array refs, so undo is correct even after
        # the miss path replaced the store's arrays), then restore
        for ph, old_seq, old_pure, arr_seq, arr_pure in \
                reversed(store._c_journal):
            arr_seq[ph] = old_seq
            arr_pure[ph] = old_pure
        store._c_journal = []
        store.c_doc, store.c_actor, store.c_seq = (self.c_doc,
                                                   self.c_actor,
                                                   self.c_seq)
        store.c_pure = self.c_pure
        (store.l_key, store.l_order, store._l_sorted, store._l_pending,
         store.l_dep_ptr, store.l_dep_actor, store.l_dep_seq) = self.log
        del store.retained[self.n_retained:]
        store._body_index_cache = (0, None)
        for s in store.actors[self.n_actors:]:
            del store.actor_of[s]
        del store.actors[self.n_actors:]
        for s in store.keys[self.n_keys:]:
            del store.key_of[s]
        del store.keys[self.n_keys:]
        store.values._restore(self.v_mark)
        for d, u in zip(store.obj_doc[self.n_objs:],
                        store.obj_uuid[self.n_objs:]):
            del store.obj_of[(d, u)]
        del store.obj_uuid[self.n_objs:]
        del store.obj_doc[self.n_objs:]
        del store.obj_type[self.n_objs:]
        store._root_row = self.root_row
        store._obj_arr_cache = (0, None, None)
        store._wire_obj_cache = None
        (store.e_doc, store.e_obj, store.e_key, store.e_actor,
         store.e_seq, store.e_value, store.e_link,
         store.e_change) = self.entries
        (pool.obj, pool.local, pool.parent, pool.actor, pool.elemc,
         pool.visible, pool.vis_index, pool.tpos, pool.pos_sorted,
         pool.pos_row) = self.pool_cols
        (pool.n_of, pool.max_elem_of, pool.max_tree,
         pool.max_elem, pool.idx_ok, pool._tpos_epoch,
         pool.idx_linear) = self.pool_n
        # the staging caches may hold nodes the rollback just unminted
        # — drop them wholesale (cold rebuild on next touch)
        pool._elem_cache.clear()
        store._digest = self.digest
        store._e_sorted = None
        del store._digest_pending[self.n_digest_pending:]


class GeneralStore(BlockStore):
    """Struct-of-arrays state for a batch of FULL documents (maps,
    lists, text, nested objects). Extends the flat BlockStore's
    admission machinery (clock, queue, retained log) with an object
    table, packed general field keys and the pooled insertion trees
    (:class:`_SeqPool`)."""

    def __init__(self, n_docs, retain_log=True):
        super().__init__(n_docs, retain_log=retain_log)
        self.e_key = np.zeros(0, np.int64)       # packed general keys
        self.e_obj = np.zeros(0, np.int32)       # store object row
        self.e_link = np.zeros(0, bool)          # entry value is a link
        # object table
        self.obj_of = {}                         # (doc, uuid) -> row
        self.obj_uuid = []
        self.obj_doc = []
        self.obj_type = []
        self.obj_inbound = {}                    # row -> [(parent_row, key)]
        self.pool = _SeqPool()                   # all insertion trees
        self._host_lock = self.pool._lock        # one lock, store-wide
        self._root_row = np.full(n_docs, -1, np.int64)
        self._obj_arr_cache = (0, None, None)
        self._wire_obj_cache = None
        # per-document applied version: bumped for exactly the doc
        # indexes an apply touched (the dirty-doc signal view caches
        # key on — see GeneralDocSet materialization). Monotone per
        # store; a failed apply rolls back BEFORE the bump, so cached
        # views stay valid across the rollback path.
        self._doc_version = np.zeros(n_docs, np.int64)
        self._apply_seq = 0
        # deferred survivor commit of the LAST apply: the entry update
        # waits on a 33KB device fetch, so it is postponed until the
        # next reader of the entry columns — host staging of block n+1
        # overlaps device resolution of block n (the async
        # frontend/backend overlap of SURVEY §2 P3, engine-side)
        self._pending_commit = None
        # sorted packed-field index over the entry columns:
        # (e_obj ref anchor, field keys ascending, entry rows aligned).
        # The prior-entry match consults it in O(touched log E) instead
        # of re-packing every entry's field key per tick; the commit
        # maintains it in O(delta log E) and drops it (None) whenever
        # a cheap in-place update isn't possible — next apply rebuilds.
        # The ref anchor invalidates it for free on rollback/restore
        # (those replace e_obj wholesale).
        self._e_sorted = None

    def _commit_pending(self, _surv_u8=None):
        """Fetch the pending apply's survivor bits and fold its entry
        update into the store (idempotent; replayable after rollback).
        ``_surv_u8`` lets a reader that already fetched the survivor
        bytes (batched into its own round trip) pass them in."""
        with self._host_lock:
            return self._commit_pending_locked(_surv_u8)

    def _commit_pending_locked(self, _surv_u8=None):
        pc = self._pending_commit
        if pc is None:
            return
        self._pending_commit = None
        n_rows = pc['n_rows']
        surviving = np.unpackbits(np.asarray(
            _surv_u8 if _surv_u8 is not None
            else jax.device_get(pc['surv_u8_dev'])))[:n_rows] \
            .astype(bool)
        s_rows = np.flatnonzero(surviving)
        patch = pc['patch']
        raw = patch._raw
        if raw is not None:
            raw['surviving'] = surviving
            raw['s_rows'] = s_rows
        cat, order = pc['cat'], pc['order']
        if cat['link'].any():       # link bookkeeping: rare
            _update_inbound(self, patch, pc['touched_fields'], surviving,
                            pc['r_seg'], cat['link'][order],
                            cat['value'][order], s_rows)
        prior_rows = pc['prior_rows']
        n_e0 = pc['n_entries']
        sel = order[s_rows]          # survivor rows, in cat coordinates
        n_drop = len(prior_rows)
        if n_drop == 0:
            def upd(col, tail):
                return np.concatenate([col, tail])
        elif n_drop > 512:
            # bulk replace (resync-scale): one boolean pass
            keep_e = np.ones(n_e0, bool)
            keep_e[prior_rows] = False

            def upd(col, tail):
                return np.concatenate([col[keep_e], tail])
        else:
            # warm tick: a handful of dropped rows — kept-segment
            # slices instead of an O(entries) boolean gather per column
            starts = np.concatenate([[0], prior_rows + 1]).tolist()
            ends = np.append(prior_rows, n_e0).tolist()

            def upd(col, tail):
                parts = [col[s:e] for s, e in zip(starts, ends)]
                parts.append(tail)
                return np.concatenate(parts)
        self.e_doc = upd(self.e_doc, cat['doc'][sel])
        old_e_obj = self.e_obj
        self.e_obj = upd(self.e_obj, cat['obj'][sel])
        self.e_key = upd(self.e_key, cat['key'][sel])
        self.e_actor = upd(self.e_actor, cat['actor'][sel])
        self.e_seq = upd(self.e_seq, cat['seq'][sel])
        self.e_value = upd(self.e_value, cat['value'][sel])
        self.e_link = upd(self.e_link, cat['link'][sel])
        self.e_change = upd(self.e_change, cat['change'][sel])

        # sorted field-index upkeep in O(delta log E): drop the prior
        # entries at their (already known) sorted positions, compact
        # the surviving row ids, insert the appended entries. Any
        # shape this can't do cheaply drops the index — the next
        # commit rebuilds it once.
        srt = self._e_sorted
        drop_pos = pc.get('srt_drop_pos')
        if (srt is not None and drop_pos is not None
                and srt[0] is old_e_obj
                and n_drop <= 4096 and len(sel) <= 65536):
            if n_drop:
                vals_k = np.delete(srt[1], drop_pos)
                rows_k = np.delete(srt[2], drop_pos)
                rows_k = rows_k - np.searchsorted(prior_rows, rows_k)
            else:
                vals_k, rows_k = srt[1], srt[2]
            new_vals = (cat['obj'][sel].astype(np.int64) << 32) | \
                cat['key'][sel]
            new_rows = (n_e0 - n_drop) + \
                np.arange(len(sel), dtype=np.int64)
            if len(new_vals) and len(vals_k) \
                    and new_vals[0] > vals_k[-1] \
                    and (len(new_vals) == 1
                         or (new_vals[1:] >= new_vals[:-1]).all()):
                # fresh fields sort past every resident one (interned
                # key ids grow monotonically) — pure tail extension
                self._e_sorted = (self.e_obj,
                                  np.concatenate([vals_k, new_vals]),
                                  np.concatenate([rows_k, new_rows]))
            else:
                p = np.searchsorted(vals_k, new_vals)
                self._e_sorted = (self.e_obj,
                                  np.insert(vals_k, p, new_vals),
                                  np.insert(rows_k, p, new_rows))
        elif _blocks._delta_host_on():
            ef = (self.e_obj.astype(np.int64) << 32) | self.e_key
            ordv = np.argsort(ef, kind='stable')
            self._e_sorted = (self.e_obj, ef[ordv],
                              ordv.astype(np.int64))
        else:
            self._e_sorted = None

    # -- packed snapshot -----------------------------------------------------

    def save_snapshot(self):
        """Serialize the WHOLE store — entries, object table, pooled
        insertion trees (host-synced visibility), clock, closure CSR,
        interned tables, causal buffer — to bytes. Resume is
        replay-free (O(state)); change bodies are dropped, so a
        resumed store serves peers forward from here only (same
        contract as the dense-store snapshot and the per-doc
        device snapshot — SURVEY §5 checkpoint/resume)."""
        import io
        import json as _json2
        self._commit_pending()
        self.pool.sync()
        self.pool.sync_index()       # order index rides the snapshot:
        #                              resume skips the per-object
        #                              _rga_order rebuild
        self.log_sorted_keys()       # fold pending appends into l_order
        self._fold_digests()         # change bodies are dropped below —
        #                              the digest must be folded NOW
        pool = self.pool
        meta = {'format': 'automerge-tpu-general-snapshot@1',
                'n_docs': self.n_docs,
                'retain_log': self.retain_log,
                'actors': self.actors, 'keys': self.keys,
                'values': list(self.values), 'queue': self.queue,
                'obj_uuid': self.obj_uuid, 'obj_doc': self.obj_doc,
                'obj_type': self.obj_type,
                'obj_inbound': {str(k): v for k, v in
                                self.obj_inbound.items()}}
        extra = {}
        if self.horizon:
            # tiered container (v2): the compaction horizon records
            # (per-doc state snapshots + clocks + digests) and the
            # retained TAIL bodies ride along, so a resumed store is
            # `state + tail` — fully servable and evictable, never
            # blunt-truncated. The format string stays @1 (older
            # readers load the state columns and simply remain
            # truncated — the v-stamp is meta['tiers']).
            meta['tiers'] = 2
            meta['horizon'] = {
                str(d): {'clock': rec['clock'],
                         'digest': rec['digest']}
                for d, rec in self.horizon.items()}
            hdocs = sorted(self.horizon)
            blobs = [self.horizon[d].get('state') or b''
                     for d in hdocs]
            offsets = np.zeros(len(blobs) + 1, np.int64)
            if blobs:
                np.cumsum([len(b) for b in blobs], out=offsets[1:])
            extra['hz_doc'] = np.asarray(hdocs, np.int64)
            extra['hz_off'] = offsets
            extra['hz_blob'] = np.frombuffer(b''.join(blobs),
                                             dtype=np.uint8)
            tail = {}
            for block, rows, docs in self.retained:
                for c, d in zip(rows.tolist(), docs.tolist()):
                    tail.setdefault(str(d), []).append(
                        block.change_dict(int(c)))
            meta['tail'] = tail
        buf = io.BytesIO()
        np.savez_compressed(
            buf, **extra,
            e_doc=self.e_doc, e_obj=self.e_obj, e_key=self.e_key,
            e_actor=self.e_actor, e_seq=self.e_seq,
            e_value=self.e_value, e_link=self.e_link,
            e_change=self.e_change,
            c_doc=self.c_doc, c_actor=self.c_actor, c_seq=self.c_seq,
            l_key=self.l_key, l_order=self.l_order,
            l_dep_ptr=self.l_dep_ptr, l_dep_actor=self.l_dep_actor,
            l_dep_seq=self.l_dep_seq,
            root_row=self._root_row,
            p_obj=pool.obj, p_local=pool.local, p_parent=pool.parent,
            p_actor=pool.actor, p_elemc=pool.elemc,
            p_visible=pool.visible, p_vis_index=pool.vis_index,
            p_tpos=pool.tpos, p_idx_ok=pool.idx_ok,
            p_pos_sorted=pool.pos_sorted, p_pos_row=pool.pos_row,
            p_n_of=pool.n_of, p_max_elem_of=pool.max_elem_of,
            digest=self._digest,
            meta=np.frombuffer(_json2.dumps(meta).encode(),
                               dtype=np.uint8))
        return buf.getvalue()

    @classmethod
    def load_snapshot(cls, data):
        """Rebuild a store from :meth:`save_snapshot` bytes — no
        replay; the device mirror re-materializes lazily on the next
        apply (zero extra wire bytes: the first resident apply ships
        every node as its own delta)."""
        import io
        import json as _json2
        with np.load(io.BytesIO(data)) as z:
            meta = _json2.loads(bytes(z['meta']).decode())
            if meta.get('format') != \
                    'automerge-tpu-general-snapshot@1':
                raise ValueError('not a general-store snapshot')
            store = cls(meta['n_docs'],
                        retain_log=meta.get('retain_log', True))
            store.actors = list(meta['actors'])
            store.actor_of = {a: i for i, a in
                              enumerate(store.actors)}
            store.keys = list(meta['keys'])
            store.key_of = {k: i for i, k in enumerate(store.keys)}
            store.values = ValueTable()
            store.values.extend(meta['values'])
            store.queue = [(d, ch) for d, ch in meta['queue']]
            store.obj_uuid = list(meta['obj_uuid'])
            store.obj_doc = list(meta['obj_doc'])
            store.obj_type = list(meta['obj_type'])
            store.obj_of = {(d, u): i for i, (d, u) in enumerate(
                zip(store.obj_doc, store.obj_uuid))}
            store.obj_inbound = {
                int(k): [(r, key) for r, key in v]
                for k, v in meta['obj_inbound'].items()}
            for name in ('e_doc', 'e_obj', 'e_key', 'e_actor',
                         'e_seq', 'e_value', 'e_link', 'e_change',
                         'c_doc', 'c_actor', 'c_seq',
                         'l_key', 'l_order', 'l_dep_ptr',
                         'l_dep_actor', 'l_dep_seq'):
                setattr(store, name, z[name])
            # purity is an optimization hint; resumed chains re-derive
            # it conservatively
            store.c_pure = np.zeros(len(store.c_doc), bool)
            store._root_row = z['root_row']
            pool = store.pool
            pool.obj = z['p_obj']
            pool.local = z['p_local']
            pool.parent = z['p_parent']
            pool.actor = z['p_actor']
            pool.elemc = z['p_elemc']
            pool.visible = z['p_visible']
            pool.vis_index = z['p_vis_index']
            # order-index planes: present since the incremental-index
            # format; a pre-index snapshot resumes with idx_ok all
            # False (first touch of each object rebuilds its order)
            if 'p_tpos' in z:
                pool.tpos = z['p_tpos']
                pool.idx_ok = z['p_idx_ok'].astype(bool)
            else:
                pool.tpos = np.zeros(len(pool.obj), np.int32)
                pool.idx_ok = np.zeros(len(z['p_n_of']), bool)
            pool.pos_sorted = z['p_pos_sorted']
            pool.pos_row = z['p_pos_row']
            pool.n_of = z['p_n_of']
            pool.max_elem_of = z['p_max_elem_of']
            # chain-shape bit re-derives from the restored tree
            # columns (not serialized): one O(nodes) pass per resume
            pool.idx_linear = np.zeros(len(pool.n_of), bool)
            if len(pool.obj):
                ok = (pool.local == 0) | (pool.parent == pool.local - 1)
                lin = np.ones(len(pool.n_of), bool)
                np.logical_and.at(lin, pool.obj, ok)
                has = np.zeros(len(pool.n_of), bool)
                has[pool.obj] = True
                pool.idx_linear = lin & has
            pool.max_tree = int(pool.n_of.max()) if len(pool.n_of) \
                else 0
            pool.max_elem = int(pool.elemc.max()) \
                if len(pool.elemc) else 0
            # change bodies are not serialized: peers sync forward
            # from here, not across the snapshot boundary — UNLESS the
            # store was compacted (meta['tiers'] >= 2): then the
            # horizon records + tail bodies restore below and the
            # store stays fully servable (state for peers behind the
            # horizon, tail replay for everyone else)
            store.log_truncated = True
            if meta.get('tiers', 1) >= 2 and 'horizon' in meta:
                hz_meta = meta['horizon']
                hz_doc = z['hz_doc']
                hz_off = z['hz_off']
                hz_blob = z['hz_blob'].tobytes()
                for i, d in enumerate(hz_doc.tolist()):
                    rec = hz_meta[str(d)]
                    blob = hz_blob[int(hz_off[i]):int(hz_off[i + 1])]
                    store.horizon[int(d)] = {
                        'clock': dict(rec['clock']),
                        'digest': rec['digest'],
                        'state': blob or None}
                from .. import compaction as _compaction
                store.retained = _compaction._encode_retained(
                    store, {int(d): ch
                            for d, ch in meta.get('tail',
                                                  {}).items()})
                store.log_truncated = False
                from ..utils.metrics import metrics as _metrics2
                _metrics2.set_gauge('mem_state_snapshot_bytes',
                                    store.state_snapshot_bytes())
            # state digests ride the snapshot (they cannot be refolded
            # once the bodies are gone); a pre-digest snapshot resumes
            # with digests INVALID — it must not advertise zeros
            if 'digest' in z:
                store._digest = z['digest']
            else:
                store._digest_valid = False
            # the device mirror must carry the RESTORED visibility: the
            # lazy first-apply path treats a None mirror as an empty
            # store and would re-stage every node hidden (r5 review:
            # silent loss of pre-resume list/text elements)
            store._materialize_mirror()
        return store

    def _materialize_mirror(self):
        """Build the device-resident mirror from the HOST pool columns
        (pos-ordered) — the resume counterpart of the fused programs'
        incremental mirror updates."""
        pool = self.pool
        n = pool.n_nodes
        if n == 0:
            return
        opts = _engine.as_options(None)
        cap = opts.pad_nodes(max(n, 8))
        rows = pool.pos_row.astype(np.int64)
        n_act = len(self.actors)
        # per-doc actor-slot width from the clock rows (sorted by doc):
        # the apply-time pick packs actor slots into uint8, so a store
        # whose widest document exceeds 256 actors must start on the
        # cols format instead of building a packed mirror the first
        # apply immediately downgrades
        if len(self.c_doc):
            starts = np.searchsorted(self.c_doc,
                                     np.arange(self.n_docs + 1))
            a_width = int(np.diff(starts).max())
        else:
            a_width = 1
        a_pad = opts.pad_actors(max(a_width, 1))
        # the persistent order index rides along for every object whose
        # idx_ok bit survived (snapshot resume / state absorb): those
        # objects skip the whole-object _rga_order rebuild and go
        # straight to incremental updates. tpos is a host column, so
        # this needs no device fetch; objects with idx_ok False carry
        # garbage slots that are never read.
        tp = np.zeros(cap, np.int32)
        tp[:n] = pool.tpos[rows]
        if _packed_mirror_guard(pool, n_act, a_pad):
            ranks = np.asarray(self.actor_str_ranks())
            actor = pool.actor[rows]
            rank1 = np.where(actor >= 0,
                             ranks[np.maximum(actor, 0)] + 1, 0) \
                .astype(np.int32)
            w1 = np.zeros(cap, np.int32)
            w1[:n] = (pool.parent[rows].astype(np.int32) << 16) | rank1
            w2 = np.zeros(cap, np.int32)
            w2[:n] = (pool.visible[rows].astype(np.int32)
                      << _W2_VIS_SHIFT) | \
                ((pool.vis_index[rows].astype(np.int32) + 1)
                 << _W2_IDX_SHIFT) | pool.elemc[rows]
            self.pool.mirror = {
                'fmt': 'packed', 'cap': cap, 'n': n,
                'w1': jnp.asarray(w1), 'w2': jnp.asarray(w2),
                'tp': jnp.asarray(tp),
                'ranks': ranks.copy(), 'pos_row': pool.pos_row}
        elif _wide_mirror_guard(pool, n_act, a_pad):
            # a resumed long-text store builds the wide mirror
            # DIRECTLY — it must not start on cols and upgrade later
            actor1 = pool.actor[rows].astype(np.int32) + 1
            w1 = np.zeros(cap, np.int32)
            w1[:n] = (pool.parent[rows].astype(np.int32)
                      << _WIDE_PARENT_SHIFT) | (actor1 & _WIDE_ALO_MASK)
            w2 = np.zeros(cap, np.int32)
            w2[:n] = ((actor1 >> 10) << _WIDE_AHI_SHIFT) | \
                (pool.visible[rows].astype(np.int32)
                 << _WIDE_VIS_SHIFT) | \
                (pool.vis_index[rows].astype(np.int32) + 1)
            w3 = np.zeros(cap, np.int32)
            w3[:n] = pool.elemc[rows]
            self.pool.mirror = {
                'fmt': 'wide', 'cap': cap, 'n': n,
                'w1': jnp.asarray(w1), 'w2': jnp.asarray(w2),
                'w3': jnp.asarray(w3), 'tp': jnp.asarray(tp),
                'rank_n': n_act, 'rank_table': _rank_table(self, opts),
                'pos_row': pool.pos_row}
        else:
            def col(src, fill, dtype):
                out = np.full(cap, fill, dtype)
                out[:n] = src[rows]
                return jnp.asarray(out)

            # the cols fallback never runs the incremental update — it
            # carries no 'tp' plane, and the idx_ok claims must drop
            # with it
            pool.idx_ok[:] = False
            self.pool.mirror = {
                'fmt': 'cols', 'cap': cap, 'n': n,
                'parent': col(pool.parent, 0, np.int32),
                'elemc': col(pool.elemc, 0, np.int32),
                'actor': col(pool.actor, -1, np.int32),
                'visible': col(pool.visible, False, bool),
                'vis_index': col(pool.vis_index, -1, np.int32),
                'rank_n': n_act,
                'rank_table': _rank_table(self, opts),
                'pos_row': pool.pos_row}

    # -- capacity ------------------------------------------------------------

    def grow_docs(self, n_docs):
        """Widen the document axis in place. The store's per-document
        state is sparse (COO clock rows, doc-tagged entries, per-row
        object table), so growth only extends the root-row table — an
        existing fleet keeps its indexes and its resident mirror."""
        if n_docs <= self.n_docs:
            return
        if n_docs >= (1 << 22):
            raise ValueError('store exceeds the 4M-document key space')
        with self._host_lock:
            pad = n_docs - self.n_docs
            self._root_row = np.concatenate(
                [self._root_row, np.full(pad, -1, np.int64)])
            self._doc_version = np.concatenate(
                [self._doc_version, np.zeros(pad, np.int64)])
            self._digest = np.concatenate(
                [self._digest, np.zeros(pad, np.uint64)])
            self.n_docs = n_docs

    # -- objects -------------------------------------------------------------

    def _bump_doc_versions(self, docs):
        """Mark ``docs`` (sorted/unique doc indexes) dirty for view
        caches — called once per successful apply, after every raise
        point, so a rolled-back apply never invalidates a view."""
        if len(docs):
            self._apply_seq += 1
            self._doc_version[docs] = self._apply_seq

    def doc_version(self, d):
        """The doc's applied version — equal versions guarantee the
        materialized view is unchanged."""
        return int(self._doc_version[d])

    def clocks_all(self):
        """``{doc index: {actor: seq}}`` for every document with a
        non-empty clock, in ONE pass over the sorted clock rows. The
        fleet surfaces (``fleet_status``, anti-entropy heartbeats) want
        every clock at once; looping :meth:`clock_of` per doc pays a
        searchsorted per document instead."""
        out = {}
        d_l = self.c_doc.tolist()
        a_l = self.c_actor.tolist()
        s_l = self.c_seq.tolist()
        actors = self.actors
        for d, a, s in zip(d_l, a_l, s_l):
            if s > 0:
                out.setdefault(d, {})[actors[a]] = s
        return out

    # rough per-row costs for the residency estimate: an entry is 7
    # int32/int64 columns + a bool (~40B host) plus its share of the
    # value table; a pool node is ~11 host columns plus 2-3 packed
    # device mirror words; a retained change body is a small dict of
    # dicts (~128B dominates small ops). The estimate steers the
    # eviction policy — it only needs to be proportional, not exact.
    _EST_ENTRY_BYTES = 48
    _EST_NODE_BYTES = 96
    _EST_CHANGE_BYTES = 128

    def doc_byte_estimates(self):
        """Estimated resident bytes PER DOCUMENT (host columns + device
        mirror + retained change bodies), as an int64 array over the
        doc axis — the signal the serving layer's memory budget and
        ``fleet_status`` residency report key on. One bincount pass per
        state family; O(state), no per-doc loops."""
        self._commit_pending()
        self.pool.sync()
        n = self.n_docs
        est = np.zeros(n, np.int64)
        if len(self.e_doc):
            est += np.bincount(self.e_doc, minlength=n)[:n] * \
                self._EST_ENTRY_BYTES
        pool = self.pool
        if pool.n_nodes:
            obj_doc_arr, _ = self.obj_arrays()
            node_docs = obj_doc_arr[pool.obj[:pool.n_nodes]]
            est += np.bincount(node_docs, minlength=n)[:n] * \
                self._EST_NODE_BYTES
        for _, _, docs in self.retained:
            if len(docs):
                est += np.bincount(docs, minlength=n)[:n] * \
                    self._EST_CHANGE_BYTES
        return est

    def obj_arrays(self):
        """(obj_doc, obj_type) as int32 arrays, cached per table size."""
        n = len(self.obj_uuid)
        if self._obj_arr_cache[0] != n:
            self._obj_arr_cache = (n,
                                   np.asarray(self.obj_doc, np.int32),
                                   np.asarray(self.obj_type, np.int32))
        return self._obj_arr_cache[1], self._obj_arr_cache[2]

    def wire_obj_tables(self):
        """The object tables marshalled for the native wire codec
        (uuid blob + offsets, doc/type arrays), cached per table
        length — the tables are append-only, so a prefix of a given
        length never changes (a rollback truncation resets the cache
        explicitly in ``_Txn.rollback``, like ``_obj_arr_cache``). A
        steady-state receive tick re-parses against a large object
        table; without this the codec edge re-encodes every uuid per
        flush."""
        n = len(self.obj_uuid)
        cache = self._wire_obj_cache
        if cache is not None and cache[0] == n:
            return cache[1:]
        encoded = [u.encode('utf-8') for u in self.obj_uuid]
        blob = b''.join(encoded)
        offsets = np.zeros(n + 1, np.int64)
        if encoded:
            np.cumsum([len(e) for e in encoded], out=offsets[1:])
        doc_arr = np.asarray(self.obj_doc, np.int32) if n else \
            np.zeros(1, np.int32)
        type_arr = np.asarray(self.obj_type, np.int8) if n else \
            np.zeros(1, np.int8)
        self._wire_obj_cache = (n, blob, offsets, doc_arr, type_arr)
        return blob, offsets, doc_arr, type_arr

    def obj_row(self, d, uuid, create_type=None):
        row = self.obj_of.get((d, uuid))
        if row is None:
            if create_type is None:
                return -1
            row = len(self.obj_uuid)
            if row >= (1 << 22):
                raise ValueError('object table exceeds the 4M key space')
            self.obj_of[(d, uuid)] = row
            self.obj_uuid.append(uuid)
            self.obj_doc.append(d)
            self.obj_type.append(create_type)
            if uuid == ROOT_ID:
                self._root_row[d] = row
            if create_type in (_TYPE_LIST, _TYPE_TEXT):
                self.pool.create_heads(np.asarray([row], np.int64))
            else:
                self.pool.grow_objects(row + 1)
        return row

    def root_row(self, d):
        return self.obj_row(d, ROOT_ID, create_type=_TYPE_MAP)

    def is_seq(self, row):
        return self.obj_type[row] in (_TYPE_LIST, _TYPE_TEXT)

    # -- encode (the dict edge) ---------------------------------------------

    def encode_changes(self, changes_per_doc, extra_types=None,
                       n_docs=None):
        """Encode reference-format dict changes into a general
        :class:`~.blocks.ChangeBlock`, resolving key kinds against this
        store's object types (plus objects created within the batch, and
        ``extra_types`` — creations known from elsewhere, e.g. the
        incoming block a queued change is being merged with).

        Ops on objects unknown to all of those (their change is
        necessarily causally unready — the creation has not arrived)
        encode with string keys; such changes buffer in the queue and
        re-encode on retry, when the creation is known.

        ``n_docs`` widens the block's document space beyond
        ``len(changes_per_doc)`` (a sparse tick touching few documents
        of a large store need not materialize one list per document).
        """
        actors, actor_of = [], {}
        keys, key_of = [], {}
        objs, obj_idx = [ROOT_ID], {ROOT_ID: 0}
        values = []
        doc, actor, seq = [], [], []
        dep_ptr, dep_actor, dep_seq = [0], [], []
        op_ptr, action, key, value = [0], [], [], []
        obj_col, kind_col, key_elem, elem_col = [], [], [], []

        # pass 1: objects created anywhere in the batch
        created = dict(extra_types) if extra_types else {}
        for d, changes in enumerate(changes_per_doc):
            for change in changes:
                for op in change['ops']:
                    a = op['action']
                    if a in ('makeMap', 'makeList', 'makeText'):
                        created[(d, op['obj'])] = _MAKE_TYPE[
                            _GEN_ACTION_NAMES[a]]

        def obj_type_of(d, uuid):
            if uuid == ROOT_ID:
                return _TYPE_MAP
            row = self.obj_of.get((d, uuid))
            if row is not None:
                return self.obj_type[row]
            return created.get((d, uuid))       # None = unknown

        def check_seq_i32(v, what):
            if not isinstance(v, int) or isinstance(v, bool) or \
                    not 0 <= v <= 0x7FFFFFFF:
                raise ValueError(
                    f'{what} {v!r} out of range (must fit int32)')
            return v

        dup_keys = False
        for d, changes in enumerate(changes_per_doc):
            for change in changes:
                if 'deps' not in change:
                    raise ValueError('change requires actor, seq and deps')
                doc.append(d)
                actor.append(_intern(actors, actor_of, change['actor']))
                seq.append(check_seq_i32(change['seq'], 'change seq'))
                for da, ds in change['deps'].items():
                    dep_actor.append(_intern(actors, actor_of, da))
                    dep_seq.append(check_seq_i32(ds, 'dep seq'))
                dep_ptr.append(len(dep_actor))
                change_fields = set()
                for op in change['ops']:
                    a = op['action']
                    code = _GEN_ACTION_NAMES.get(a)
                    if code is None:
                        raise ValueError(f'Unknown operation type {a}')
                    uuid = op['obj']
                    action.append(code)
                    obj_col.append(_intern(objs, obj_idx, uuid))
                    if code in (_MAKE_MAP, _MAKE_LIST, _MAKE_TEXT):
                        kind_col.append(_KEY_NONE)
                        key.append(-1)
                        key_elem.append(0)
                        elem_col.append(0)
                        value.append(-1)
                        continue
                    k = op['key']
                    otype = obj_type_of(d, uuid)
                    as_elem = (otype in (_TYPE_LIST, _TYPE_TEXT))
                    if as_elem and k == '_head':
                        if code != _INS:
                            raise ValueError('assignment to _head')
                        kind_col.append(_KEY_HEAD)
                        key.append(-1)
                        key_elem.append(0)
                    elif as_elem:
                        ka, _, ke = k.rpartition(':')
                        try:
                            ke = int(ke)
                        except ValueError:
                            raise ValueError(
                                f'malformed element id {k!r}') from None
                        kind_col.append(_KEY_ELEM)
                        key.append(_intern(actors, actor_of, ka))
                        key_elem.append(ke)
                    else:
                        kind_col.append(_KEY_STR)
                        key.append(_intern(keys, key_of, k))
                        key_elem.append(0)
                    if code == _INS:
                        elem_col.append(op['elem'])
                        value.append(-1)
                    else:
                        elem_col.append(0)
                        if code in (_SET, _LINK):
                            value.append(len(values))
                            values.append(op.get('value'))
                        else:
                            value.append(-1)
                        fk = (uuid, k)
                        if fk in change_fields:
                            dup_keys = True
                        change_fields.add(fk)
                op_ptr.append(len(action))

        return ChangeBlock(
            n_docs if n_docs is not None else len(changes_per_doc),
            np.asarray(doc, np.int32), np.asarray(actor, np.int32),
            np.asarray(seq, np.int32), np.asarray(dep_ptr, np.int32),
            np.asarray(dep_actor, np.int32), np.asarray(dep_seq, np.int32),
            np.asarray(op_ptr, np.int32), np.asarray(action, np.int8),
            np.asarray(key, np.int32), np.asarray(value, np.int32),
            actors, keys, values, dup_keys=dup_keys,
            obj=np.asarray(obj_col, np.int32),
            key_kind=np.asarray(kind_col, np.int8),
            key_elem=np.asarray(key_elem, np.int32),
            elem=np.asarray(elem_col, np.int32), objs=objs)

    def merge_queued_into(self, block):
        """Re-encode the buffered queue (kinds resolve against the
        now-current object table PLUS the incoming block's creations)
        and concatenate column-wise."""
        extra = {}
        if block.is_general() and block.n_ops:
            mk = np.flatnonzero(block.action >= _MAKE_MAP)
            if len(mk):
                op_doc = np.repeat(block.doc, np.diff(block.op_ptr))
                for j in mk.tolist():
                    extra[(int(op_doc[j]), block.objs[block.obj[j]])] = \
                        _MAKE_TYPE[int(block.action[j])]
        per_doc = [[] for _ in range(self.n_docs)]
        for d, change in self.queue:
            per_doc[d].append(change)
        qblock = self.encode_changes(per_doc, extra_types=extra)
        return _concat_general(block, qblock)

    # -- inspection ----------------------------------------------------------

    def doc_fields(self, d):
        """{(obj uuid, key string): [(actor, value), ...]} winner first —
        the test/inspection surface (general-key aware)."""
        self._commit_pending()
        pool = self.pool
        out = {}
        for j in np.flatnonzero(self.e_doc == d):
            obj_row = int(self.e_obj[j])
            packed = int(self.e_key[j])
            if packed & (1 << 31):
                node = packed & 0x7FFFFFFF
                row = pool.row_at(obj_row, node)
                key = (f'{self.actors[pool.actor[row]]}:'
                       f'{int(pool.elemc[row])}')
            else:
                key = self.keys[packed & 0x7FFFFFFF]
            out.setdefault((self.obj_uuid[obj_row], key), []).append(
                (self.actors[self.e_actor[j]],
                 self.values[self.e_value[j]] if self.e_value[j] >= 0
                 else None))
        return {k: sorted(v, key=lambda t: t[0], reverse=True)
                for k, v in out.items()}


def _concat_general(a, b):
    """Column-wise concatenation of two general blocks (b's table
    references remapped into a's tables)."""
    if not b.n_changes:
        return a
    if not a.is_general():
        a = _upgrade_to_general(a)
    actors = list(a.actors)
    actor_of = {s: i for i, s in enumerate(actors)}
    keys = list(a.keys)
    key_of = {s: i for i, s in enumerate(keys)}
    objs = list(a.objs)
    obj_of = {s: i for i, s in enumerate(objs)}
    amap = np.asarray([_intern(actors, actor_of, s) for s in b.actors]
                      or [0], np.int32)
    kmap = np.asarray([_intern(keys, key_of, s) for s in b.keys]
                      or [0], np.int32)
    omap = np.asarray([_intern(objs, obj_of, s) for s in b.objs]
                      or [0], np.int32)
    values = ValueTable()
    values.extend(a.values)
    v_base = len(values)
    values.extend(b.values)

    def col(xa, xb):
        return np.concatenate([xa, xb])

    new_key = np.full(b.n_ops, -1, np.int32)
    if b.n_ops:
        str_m = b.key_kind == _KEY_STR
        elem_m = b.key_kind == _KEY_ELEM
        new_key[str_m] = kmap[b.key[str_m]]
        new_key[elem_m] = amap[b.key[elem_m]]

    if a._dup_keys or b._dup_keys:
        dup_keys = True
    elif a._dup_keys is None or b._dup_keys is None:
        dup_keys = None
    else:
        dup_keys = False

    return ChangeBlock(
        a.n_docs, col(a.doc, b.doc), col(a.actor, amap[b.actor]),
        col(a.seq, b.seq),
        col(a.dep_ptr, a.dep_ptr[-1] + b.dep_ptr[1:]),
        col(a.dep_actor, amap[b.dep_actor] if len(b.dep_actor)
            else b.dep_actor),
        col(a.dep_seq, b.dep_seq),
        col(a.op_ptr, a.op_ptr[-1] + b.op_ptr[1:]),
        col(a.action, b.action),
        col(a.key, new_key),
        col(a.value, np.where(b.value >= 0, b.value + v_base, -1)
            .astype(np.int32) if b.n_ops else b.value),
        actors, keys, values, dup_keys=dup_keys,
        obj=col(a.obj, omap[b.obj] if b.n_ops else b.obj),
        key_kind=col(a.key_kind, b.key_kind),
        key_elem=col(a.key_elem, b.key_elem),
        elem=col(a.elem, b.elem), objs=objs)


def _upgrade_to_general(block):
    """A flat root-map block viewed through the general schema."""
    n = block.n_ops
    return ChangeBlock(
        block.n_docs, block.doc, block.actor, block.seq, block.dep_ptr,
        block.dep_actor, block.dep_seq, block.op_ptr, block.action,
        block.key, block.value, block.actors, block.keys, block.values,
        dup_keys=block._dup_keys,
        obj=np.zeros(n, np.int32),
        key_kind=np.full(n, _KEY_STR, np.int8),
        key_elem=np.zeros(n, np.int32),
        elem=np.zeros(n, np.int32), objs=[ROOT_ID])


def init_store(n_docs):
    return GeneralStore(n_docs)


# -- fused device step -------------------------------------------------------

def _unpack_bits(u8, n):
    """MSB-first bit unpack (matches np.packbits) to bool[n]."""
    i = jnp.arange(n)
    return ((u8[i >> 3] >> (7 - (i & 7))) & 1).astype(bool)


# shared staging idioms of the two fused programs (packed + cols) —
# one definition so the variants stay in lockstep by construction

def _insert_counts(d_pos, cap):
    """cnt[i] = #new nodes at insert positions <= i, for non-decreasing
    d_pos (cap-padded): one scatter-max + cummax — a searchsorted here
    is a 19-round binary-search gather at block scale (~65 ms)."""
    return jax.lax.cummax(
        jnp.zeros(cap, jnp.int32).at[d_pos].max(
            jnp.arange(1, d_pos.shape[0] + 1, dtype=jnp.int32),
            mode='drop'))


def _build_clock(actor, seq, a_pad, coo_row, coo_col, coo_val):
    """Dense [n, a_pad] closure clock: the own-actor entry is always
    seq-1 (elementwise — no scatter), cross-actor exceptions overlay
    from COO."""
    clock = jnp.where(
        actor[:, None] == jnp.arange(a_pad, dtype=jnp.int32)[None, :],
        (seq - 1)[:, None], 0)
    return clock.at[coo_row, coo_col.astype(jnp.int32)].set(
        coo_val.astype(jnp.int32), mode='drop')


def _vis_grid(row_slot, valid, surviving, k, m_pad):
    """(touched, vis_hit) planes from the per-row slots with ONE packed
    scatter: max over {0, 2, 3} of valid<<1|surviving recovers both
    bits (surviving implies valid)."""
    flat = jnp.where(row_slot >= 0, row_slot, k * m_pad)
    packed = (valid.astype(jnp.uint8) << 1) | \
        surviving.astype(jnp.uint8)
    grid = jnp.zeros(k * m_pad + 1, jnp.uint8).at[flat].max(
        packed, mode='drop')[:k * m_pad].reshape(k, m_pad)
    return grid >= 2, grid == 3


@partial(jax.jit, static_argnames=('num_segments', 'a_pad', 'm_pad'))
def _fused_general_resident(m_parent, m_elemc, m_actor, m_visible,
                            m_visidx, d_parent, d_elemc, d_actor, d_pos,
                            n_old, job_start, job_n, rank_table,
                            ops_actor, ops_seq, ops_slot, flags_u8,
                            n_rows, coo_row, coo_col, coo_val, *,
                            num_segments, a_pad, m_pad):
    """One apply of the general engine against DEVICE-RESIDENT trees:
    fold this apply's new nodes into the pos-ordered mirror, gather the
    dirty objects' job planes from it, resolve every touched field,
    derive element visibility, re-order every dirty sequence, and
    scatter the new visibility back into the mirror — one program.

    Wire-lean inputs (the link is the binding constraint): only NEW
    nodes ship (columns + insert positions; a growing collab session
    pays O(block), not O(tree)); rows arrive FIELD-SORTED so segment
    ids are ONE boundary bit per row; actor slots/seq counters ride the
    narrowest dtype that fits; validity masks derive from counts; the
    clock plane is rebuilt on device from sparse COO exceptions (the
    own-actor entry is always seq-1). Outputs: the updated mirror
    columns (resident), bit-packed survivors, the per-field winner, and
    the prior/new visibility+order planes (device-resident for lazy
    patch materialization).
    """
    from .merge import _resolve_sorted
    from .sequence import _rga_order_batched
    cap = m_parent.shape[0]

    # ---- fold the new nodes in (pos-order preserving insert) ----
    i = jnp.arange(cap, dtype=jnp.int32)
    cnt = _insert_counts(d_pos, cap)
    tgt_old = jnp.where(i < n_old, i + cnt, cap)
    tgt_new = d_pos + jnp.arange(d_pos.shape[0], dtype=jnp.int32)

    def fold(col, dcol, fill):
        out = jnp.full((cap,), fill, col.dtype)
        out = out.at[tgt_old].set(col, mode='drop')
        return out.at[tgt_new].set(dcol.astype(col.dtype), mode='drop')

    parent_p = fold(m_parent, d_parent, 0)
    elemc_p = fold(m_elemc, d_elemc, 0)
    actor_p = fold(m_actor, d_actor, -1)
    visible_p = fold(m_visible, jnp.zeros_like(d_parent, bool), False)
    visidx_p = fold(m_visidx, jnp.full_like(d_parent, -1), -1)

    # ---- job planes gathered from the resident columns: an object's
    # nodes are one contiguous pos slice, local-ascending ----
    l = jnp.arange(m_pad, dtype=jnp.int32)
    pos_mat = job_start[:, None] + l[None, :]
    valid_plane = l[None, :] < job_n[:, None]
    pos_c = jnp.minimum(jnp.where(valid_plane, pos_mat, 0), cap - 1)
    s_parent = jnp.take(parent_p, pos_c)
    s_elem = jnp.take(elemc_p, pos_c)
    s_rank = jnp.take(rank_table, jnp.take(actor_p, pos_c) + 1)
    prior_vis = jnp.take(visible_p, pos_c) & valid_plane
    prior_idx = jnp.where(valid_plane, jnp.take(visidx_p, pos_c), -1)

    # ---- field resolution (scan-based; rows arrive field-sorted) ----
    n = ops_slot.shape[0]
    nb = n >> 3
    boundary = _unpack_bits(flags_u8[:nb], n)
    is_del = _unpack_bits(flags_u8[nb:], n)
    valid = jnp.arange(n) < n_rows
    actor = ops_actor.astype(jnp.int32)
    seq = ops_seq.astype(jnp.int32)
    clock = _build_clock(actor, seq, a_pad, coo_row, coo_col, coo_val)
    out = _resolve_sorted(boundary, actor, seq, clock, is_del, valid,
                          num_segments)

    # ---- element visibility + RGA ordering ----
    k = job_start.shape[0]
    touched, vis_hit = _vis_grid(ops_slot, valid, out['surviving'],
                                 k, m_pad)
    visible = jnp.where(touched, vis_hit, prior_vis) & valid_plane
    ordered = _rga_order_batched(s_parent, s_elem, s_rank, visible,
                                 valid_plane)

    # ---- scatter the new visibility/order back into the mirror ----
    scatter_pos = jnp.where(valid_plane, pos_mat, cap).reshape(-1)
    visible_p = visible_p.at[scatter_pos].set(visible.reshape(-1),
                                              mode='drop')
    visidx_p = visidx_p.at[scatter_pos].set(
        ordered['vis_index'].reshape(-1), mode='drop')

    # survivors return bit-packed (MSB-first, np.unpackbits-compatible)
    surv_u8 = jnp.sum(
        out['surviving'].reshape(-1, 8).astype(jnp.uint8)
        * (jnp.uint8(1) << (7 - jnp.arange(8, dtype=jnp.uint8))),
        axis=1, dtype=jnp.uint8)
    return (parent_p, elemc_p, actor_p, visible_p, visidx_p,
            surv_u8, out['winner'], prior_vis, visible, prior_idx,
            ordered['vis_index'])


# -- packed fused step -------------------------------------------------------
#
# The wire-packed variants of the resident program: the binding costs at
# block scale are (a) tunnel H2D bytes and per-array transfer overhead,
# (b) the count of million-element gathers/scatters on device (~4ns/elem
# on v5e, ~100x an elementwise op). So the mirror packs into a few int32
# words per node, every staged input rides ONE uint8 buffer (sliced +
# bitcast on device — elementwise, fuses), the field resolution rides
# segmented associative scans instead of segment_max scatters, and the
# small-tree RGA one-hots run in bf16 (exact: all values <= 256).
#
# TWO packed layouts share that design; the host pick is per apply:
#
# 'packed' — 2 words/node, the small-tree fast path:
#   W1 = parent << 16 | (rank+1)      rank = actor string rank; head = 0
#   W2 = visible << 30 | (vis_index+1) << 15 | elemc
#   Guards: tree size <= 32767 nodes, elemc < 32768, actor count
#   < 65535, per-doc actor slots <= 256, seq < 32768, coo seq < 32768.
#
# 'wide' — 3 words/node, the long-text format (the bounds lift): trees
# to 2^22 - 1 nodes, elemc and seq bounded only by int32. The words
# carry the STABLE actor id (+1; 0 = head) split 10/6 across W1/W2
# instead of the rank, so a growing actor table never remaps the
# mirror — the RGA rank comes from the small rank_table gather instead:
#   W1 = parent << 10 | (actor+1) & 0x3FF
#   W2 = ((actor+1) >> 10) << 23 | visible << 22 | (vis_index+1)
#   W3 = elemc
#   Guards: tree size <= 2^22 - 1 nodes, actor count < 65535, per-doc
#   actor slots <= 256 (the u8 row-staging dtype). seq/coo seq ride
#   int32 wire sections, elemc is a full int32 word.
#
# The unpacked `_fused_general_resident` (cols) remains the fallback
# for shapes past both (>4M-node trees, >65535 actors, >256 per-doc
# actor slots), and the independent cross-check of the packed FORMATS
# (bit fields, wire layout, dtype narrowing). A store crossing a bound
# mid-stream converts its resident mirror in place (`_mirror_convert`)
# — packed -> wide is the boundary a long text document crosses.

_W2_ELEM = 0x7FFF
_W2_VIS_SHIFT = 30
_W2_IDX_SHIFT = 15

# wide-format bit layout (see the module comment above)
_WIDE_IDX_MASK = (1 << 22) - 1       # vis_index+1 (W2) / parent width
_WIDE_VIS_SHIFT = 22
_WIDE_AHI_SHIFT = 23
_WIDE_AHI_BITS = 0x3F << _WIDE_AHI_SHIFT
_WIDE_ALO_MASK = (1 << 10) - 1
_WIDE_PARENT_SHIFT = 10
_WIDE_MAX_TREE = (1 << 22) - 1

_NO_REMAP = np.zeros(1, np.int32)     # placeholder when has_remap=False


def unpack_vis_word(v_u32):
    """Host-side unpack of the packed vis output plane
    (`_fused_general_packed`'s vis_packed, viewed as uint32):
    (prior_vis, visible, prior_idx, new_idx)."""
    pv = (v_u32 >> 31).astype(bool)
    nv = ((v_u32 >> _W2_VIS_SHIFT) & 1).astype(bool)
    pi = (((v_u32 >> _W2_IDX_SHIFT) & _W2_ELEM).astype(np.int64) - 1)
    ni = (v_u32 & _W2_ELEM).astype(np.int64) - 1
    return pv, nv, pi, ni


def unpack_w2_word(w2):
    """Host-side unpack of a mirror W2 word: (visible, vis_index)."""
    vis = ((w2 >> _W2_VIS_SHIFT) & 1).astype(bool)
    idx = (((w2 >> _W2_IDX_SHIFT) & _W2_ELEM) - 1).astype(np.int32)
    return vis, idx


def unpack_wide_word(w):
    """Host-side unpack of a WIDE visibility word — the mirror W2 and
    the wide program's vis output planes share the layout
    ``visible << 22 | (idx + 1)``: (visible, vis_index)."""
    vis = ((w >> _WIDE_VIS_SHIFT) & 1).astype(bool)
    idx = ((w & _WIDE_IDX_MASK) - 1).astype(np.int32)
    return vis, idx

# test/dryrun hook: called once per apply with the staged planes and the
# fused outputs (whichever variant ran) — the sharded-step equality
# gates consume this instead of monkeypatching a program symbol
_STAGE_CAPTURE = None

# native-staging switch: None = auto (use the C++ stager when the
# library loads and the block is fully admitted), False = numpy only,
# True = REQUIRE native (tests: fail loudly instead of silently
# falling back)
_NATIVE_STAGING = None

# incremental-index switch: None = auto (take the incremental path
# whenever the eligibility gate holds), 'rebuild' = always run the
# whole-object rebuild variant (the A/B arm of bench_incremental_order
# and the parity oracle in tests/test_sequence_index.py), 'require' =
# raise when an apply with dirty sequences cannot go incremental
# (tests: an invalidation path that silently falls back is a bug)
_INDEX_MODE = None

# edit-stream read switch (GeneralPatch._ensure): one fused device
# dispatch compacts the tick's edits into pre-ordered delta-sized
# buffers (pallas_view.edit_stream) and the read fetches THOSE
# instead of the full O(doc) vis planes. None = auto (on for real
# accelerator backends, where the link fetch is the binding cost; the
# CPU backend keeps the host path — there is no link to save, and
# XLA-CPU scatters lose to a memcpy-sized fetch), True = force on,
# False = host path always.
_EDIT_STREAM = None

# suffix-window switch for the incremental index update: None = auto
# (bound each eligible chain-shaped job's renumber to the suffix
# window containing every delta anchor and touched node), 'off' =
# always renumber the whole plane (the whole-plane A/B arm of the
# host_tick bench band), 'require' = raise when an incremental apply
# with dirty sequences cannot window (tests: a silent fallback on the
# end-typing shape is a bug)
_WINDOW_MODE = None

# staging-cache switch (delta admit/stage): None = auto (keep per-
# object sorted elemId -> local indexes across applies and let both
# stagers consult them), False = off (cold-stage every tick — the
# whole-plane A/B arm / parity oracle)
_STAGE_CACHE = None


def _edit_stream_on():
    if _EDIT_STREAM is None:
        return jax.default_backend() != 'cpu'
    return bool(_EDIT_STREAM)


def _packed_mirror_guard(pool, n_act, a_pad=None):
    """The packed 2-word mirror format's bit-field bounds — ONE
    definition shared by the apply-time variant pick and the resume-
    time `_materialize_mirror`, so a store the apply path would
    immediately downgrade (e.g. >256 per-doc actors) never builds a
    packed mirror it cannot keep. ``a_pad`` is the padded per-doc
    actor-slot width when known (must fit the uint8 staging dtype)."""
    return (pool.max_tree <= 0x7FFF
            and pool.max_elem < (1 << 15)
            and n_act < 65535
            and (a_pad is None or a_pad <= 256))


def _wide_mirror_guard(pool, n_act, a_pad=None):
    """The WIDE 3-word mirror format's bounds — the packed program for
    everything the 2-word format cannot hold short of the cols
    fallback: trees to 2^22 - 1 nodes; elemc, seq and closure seqs
    bounded only by int32 (they ride full int32 wire sections). Shared
    by the apply-time pick, `_materialize_mirror` (a resumed long-text
    store builds the wide mirror DIRECTLY) and `_mirror_convert`."""
    return (pool.max_tree <= _WIDE_MAX_TREE
            and n_act < 65535
            and (a_pad is None or a_pad <= 256))


def _wire_sizes(d_pad, n_pad, K, nnz_pad):
    """Total byte count of the single staged wire buffer. Section
    offsets are not centralized: the host packing loop in
    `_apply_general` and the device slicing in `_fused_general_packed`
    must list the sections in THIS order (4-byte-aligned first):
    i32: w1_new[d_pad] d_pos[d_pad] row_slot[n_pad] coo_row[nnz_pad]
         job_start[K] job_n[K]
    i16: w2e[d_pad] seq[n_pad] coo_val[nnz_pad]
    u8:  actor[n_pad] flags[2*(n_pad>>3)] coo_col[nnz_pad]
    """
    i32_n = 2 * d_pad + n_pad + nnz_pad + 2 * K
    i16_n = d_pad + n_pad + nnz_pad
    u8_n = n_pad + 2 * (n_pad >> 3) + nnz_pad
    return 4 * i32_n + 2 * i16_n + u8_n


def _wire_sizes_wide(d_pad, n_pad, K, nnz_pad):
    """Byte count of the WIDE program's wire buffer. Same contract as
    `_wire_sizes`: the host packing loop, the C++ `amst_fill_wire_wide`
    and the device slicing in `_fused_general_wide` must list the
    sections in THIS order (seq/coo_val widen to int32 — a long-lived
    actor's seq exceeds 32767 at exactly the history length whose tree
    needs this format):
    i32: w1_new[d_pad] w3_new[d_pad] d_pos[d_pad] row_slot[n_pad]
         seq[n_pad] coo_row[nnz_pad] coo_val[nnz_pad]
         job_start[K] job_n[K]
    u8:  ahi_new[d_pad] actor[n_pad] flags[2*(n_pad>>3)]
         coo_col[nnz_pad]
    """
    i32_n = 3 * d_pad + 2 * n_pad + 2 * nnz_pad + 2 * K
    u8_n = d_pad + n_pad + 2 * (n_pad >> 3) + nnz_pad
    return 4 * i32_n + u8_n


def _wire_cut(vec, state, cnt):
    o = state[0]
    state[0] = o + cnt
    return vec[o:o + cnt]


def _parse_wire_packed(wire, sizes):
    """Slice the PACKED wire buffer into its typed sections — ONE
    definition of the section order shared by the rebuild
    (`_fused_general_packed`) and incremental (`_fused_general_incr`)
    programs; must stay in lockstep with `_wire_sizes`, the host
    packing loop and the C++ `amst_fill_wire`. Returns
    (w1d, d_pos, row_slot, coo_row, job_start, job_n,
     w2e, seq, coo_val, actor, flags_u8, coo_col)."""
    d_pad, n_pad, K, nnz_pad = sizes
    i32_n = 2 * d_pad + n_pad + nnz_pad + 2 * K
    i16_n = d_pad + n_pad + nnz_pad
    i32v = jax.lax.bitcast_convert_type(
        wire[:4 * i32_n].reshape(i32_n, 4), jnp.int32)
    i16v = jax.lax.bitcast_convert_type(
        wire[4 * i32_n:4 * i32_n + 2 * i16_n].reshape(i16_n, 2),
        jnp.int16)
    u8v = wire[4 * i32_n + 2 * i16_n:]
    s32, s16, s8 = [0], [0], [0]
    w1d = _wire_cut(i32v, s32, d_pad)
    d_pos = _wire_cut(i32v, s32, d_pad)
    row_slot = _wire_cut(i32v, s32, n_pad)
    coo_row = _wire_cut(i32v, s32, nnz_pad)
    job_start = _wire_cut(i32v, s32, K)
    job_n = _wire_cut(i32v, s32, K)
    w2e = _wire_cut(i16v, s16, d_pad).astype(jnp.int32)
    seq = _wire_cut(i16v, s16, n_pad).astype(jnp.int32)
    coo_val = _wire_cut(i16v, s16, nnz_pad).astype(jnp.int32)
    actor = _wire_cut(u8v, s8, n_pad).astype(jnp.int32)
    flags_u8 = _wire_cut(u8v, s8, 2 * (n_pad >> 3))
    coo_col = _wire_cut(u8v, s8, nnz_pad).astype(jnp.int32)
    return (w1d, d_pos, row_slot, coo_row, job_start, job_n, w2e, seq,
            coo_val, actor, flags_u8, coo_col)


def _parse_wire_wide(wire, sizes):
    """The WIDE counterpart of `_parse_wire_packed` (section order of
    `_wire_sizes_wide` / `amst_fill_wire_wide`). Returns
    (w1d, w3d, d_pos, row_slot, seq, coo_row, coo_val, job_start,
     job_n, d_ahi, actor, flags_u8, coo_col)."""
    d_pad, n_pad, K, nnz_pad = sizes
    i32_n = 3 * d_pad + 2 * n_pad + 2 * nnz_pad + 2 * K
    i32v = jax.lax.bitcast_convert_type(
        wire[:4 * i32_n].reshape(i32_n, 4), jnp.int32)
    u8v = wire[4 * i32_n:]
    s32, s8 = [0], [0]
    w1d = _wire_cut(i32v, s32, d_pad)
    w3d = _wire_cut(i32v, s32, d_pad)
    d_pos = _wire_cut(i32v, s32, d_pad)
    row_slot = _wire_cut(i32v, s32, n_pad)
    seq = _wire_cut(i32v, s32, n_pad)
    coo_row = _wire_cut(i32v, s32, nnz_pad)
    coo_val = _wire_cut(i32v, s32, nnz_pad)
    job_start = _wire_cut(i32v, s32, K)
    job_n = _wire_cut(i32v, s32, K)
    d_ahi = _wire_cut(u8v, s8, d_pad).astype(jnp.int32)
    actor = _wire_cut(u8v, s8, n_pad).astype(jnp.int32)
    flags_u8 = _wire_cut(u8v, s8, 2 * (n_pad >> 3))
    coo_col = _wire_cut(u8v, s8, nnz_pad).astype(jnp.int32)
    return (w1d, w3d, d_pos, row_slot, seq, coo_row, coo_val,
            job_start, job_n, d_ahi, actor, flags_u8, coo_col)


@partial(jax.jit, static_argnames=('sizes', 'num_segments', 'a_pad',
                                   'm_pad', 'has_remap', 'has_old'))
def _fused_general_packed(w1m, w2m, tpm, wire, n_old, n_rows,
                          rank_remap, *, sizes, num_segments, a_pad,
                          m_pad, has_remap, has_old):
    """One apply against the PACKED device-resident mirror — the
    whole-object REBUILD variant: every dirty sequence re-orders from
    scratch via `_rga_order_batched`, and the fresh tree positions
    (re)initialize the persistent 'tp' index plane that the
    incremental variant (`_fused_general_incr`) maintains afterwards.
    Outputs: (w1', w2', tp', surv_u8, winner[S], vis_packed[K, m_pad])
    where vis_packed = prior_vis<<31 | visible<<30 | (prior_idx+1)<<15
    | (new_idx+1) — the host unpacks via a uint32 view."""
    from .merge import _resolve_sorted
    from .sequence import _rga_order_batched
    d_pad, n_pad, K, nnz_pad = sizes
    cap = w1m.shape[0]
    nb = n_pad >> 3

    (w1d, d_pos, row_slot, coo_row, job_start, job_n, w2e, seq,
     coo_val, actor, flags_u8, coo_col) = _parse_wire_packed(wire,
                                                            sizes)

    if has_remap:
        w1m = (w1m & ~0xFFFF) | jnp.take(rank_remap, w1m & 0xFFFF) \
            .astype(jnp.int32)

    # ---- fold the new nodes into the pos-ordered mirror ----
    tgt_new = d_pos + jnp.arange(d_pad, dtype=jnp.int32)
    if has_old:
        i = jnp.arange(cap, dtype=jnp.int32)
        cnt = _insert_counts(d_pos, cap)
        tgt_old = jnp.where(i < n_old, i + cnt, cap)

        def fold(col, dcol):
            out = jnp.zeros((cap,), jnp.int32)
            out = out.at[tgt_old].set(col, mode='drop')
            return out.at[tgt_new].set(dcol, mode='drop')
    else:
        # first resident apply: the mirror is empty, nothing merges
        def fold(col, dcol):
            return jnp.zeros((cap,), jnp.int32) \
                .at[tgt_new].set(dcol, mode='drop')

    w1f = fold(w1m, w1d)
    w2f = fold(w2m, w2e)             # new nodes: hidden, vis word = elemc
    tpf = fold(tpm, jnp.zeros(d_pad, jnp.int32))

    # ---- job planes ----
    l = jnp.arange(m_pad, dtype=jnp.int32)
    pos_mat = job_start[:, None] + l[None, :]
    valid_plane = l[None, :] < job_n[:, None]
    pos_c = jnp.minimum(jnp.where(valid_plane, pos_mat, 0), cap - 1)
    w1p = jnp.take(w1f, pos_c)
    w2p = jnp.take(w2f, pos_c)
    s_parent = w1p >> 16
    s_rank = w1p & 0xFFFF            # rank+1 — same order as rank
    s_elem = w2p & _W2_ELEM
    prior_vis = ((w2p >> _W2_VIS_SHIFT) & 1).astype(bool) & valid_plane
    prior_idx = jnp.where(valid_plane,
                          ((w2p >> _W2_IDX_SHIFT) & _W2_ELEM) - 1, -1)

    # ---- field resolution (scan-based; rows arrive field-sorted) ----
    boundary = _unpack_bits(flags_u8[:nb], n_pad)
    is_del = _unpack_bits(flags_u8[nb:], n_pad)
    valid = jnp.arange(n_pad) < n_rows
    clock = _build_clock(actor, seq, a_pad, coo_row, coo_col, coo_val)
    out = _resolve_sorted(boundary, actor, seq, clock, is_del, valid,
                          num_segments)

    # ---- element visibility ----
    touched, vis_hit = _vis_grid(row_slot, valid, out['surviving'],
                                 K, m_pad)
    visible = jnp.where(touched, vis_hit, prior_vis) & valid_plane

    ordered = _rga_order_batched(s_parent, s_elem, s_rank, visible,
                                 valid_plane)
    new_idx = ordered['vis_index']

    # ---- scatter the updated vis word + tree positions back ----
    w2n = (visible.astype(jnp.int32) << _W2_VIS_SHIFT) | \
        ((new_idx + 1) << _W2_IDX_SHIFT) | s_elem
    scatter_pos = jnp.where(valid_plane, pos_mat, cap).reshape(-1)
    w2f = w2f.at[scatter_pos].set(w2n.reshape(-1), mode='drop')
    tpf = tpf.at[scatter_pos].set(
        ordered['tree_pos'].reshape(-1), mode='drop')

    surv_u8 = jnp.sum(
        out['surviving'].reshape(-1, 8).astype(jnp.uint8)
        * (jnp.uint8(1) << (7 - jnp.arange(8, dtype=jnp.uint8))),
        axis=1, dtype=jnp.uint8)
    vis_packed = (prior_vis.astype(jnp.int32) << 31) | \
        (visible.astype(jnp.int32) << 30) | \
        ((prior_idx + 1) << _W2_IDX_SHIFT) | (new_idx + 1)
    return w1f, w2f, tpf, surv_u8, out['winner'], vis_packed


@partial(jax.jit, static_argnames=('sizes', 'num_segments', 'a_pad',
                                   'm_pad', 'has_old'))
def _fused_general_wide(w1m, w2m, w3m, tpm, wire, n_old, n_rows,
                        rank_table, *, sizes, num_segments, a_pad,
                        m_pad, has_old):
    """One apply against the WIDE 3-word packed mirror (trees to
    2^22 - 1 nodes; elemc/seq bounded only by int32). Same program
    shape as `_fused_general_packed` with the wide bit layout, int32
    seq/coo wire sections and actor ids (stable) in the words instead
    of ranks — the RGA rank rides the small `rank_table` gather, so a
    growing actor table never remaps the mirror. The whole-object
    REBUILD variant: fresh tree positions (re)initialize the
    persistent 'tp' index plane. Outputs: (w1', w2', w3', tp',
    surv_u8, winner[S], vis_prior[K, m_pad], vis_new[K, m_pad]);
    each vis plane word is ``visible << 22 | (idx + 1)``
    (`unpack_wide_word`)."""
    from .merge import _resolve_sorted
    from .sequence import _rga_order_batched
    d_pad, n_pad, K, nnz_pad = sizes
    cap = w1m.shape[0]
    nb = n_pad >> 3

    (w1d, w3d, d_pos, row_slot, seq, coo_row, coo_val, job_start,
     job_n, d_ahi, actor, flags_u8, coo_col) = _parse_wire_wide(wire,
                                                                sizes)

    # ---- fold the new nodes into the pos-ordered mirror ----
    tgt_new = d_pos + jnp.arange(d_pad, dtype=jnp.int32)
    if has_old:
        i = jnp.arange(cap, dtype=jnp.int32)
        cnt = _insert_counts(d_pos, cap)
        tgt_old = jnp.where(i < n_old, i + cnt, cap)

        def fold(col, dcol):
            out = jnp.zeros((cap,), jnp.int32)
            out = out.at[tgt_old].set(col, mode='drop')
            return out.at[tgt_new].set(dcol, mode='drop')
    else:
        def fold(col, dcol):
            return jnp.zeros((cap,), jnp.int32) \
                .at[tgt_new].set(dcol, mode='drop')

    w1f = fold(w1m, w1d)
    # new nodes: hidden, vis_index+1 = 0, actor-hi bits ride along
    w2f = fold(w2m, d_ahi << _WIDE_AHI_SHIFT)
    w3f = fold(w3m, w3d)
    tpf = fold(tpm, jnp.zeros(d_pad, jnp.int32))

    # ---- job planes ----
    l = jnp.arange(m_pad, dtype=jnp.int32)
    pos_mat = job_start[:, None] + l[None, :]
    valid_plane = l[None, :] < job_n[:, None]
    pos_c = jnp.minimum(jnp.where(valid_plane, pos_mat, 0), cap - 1)
    w1p = jnp.take(w1f, pos_c)
    w2p = jnp.take(w2f, pos_c)
    s_elem = jnp.take(w3f, pos_c)
    s_parent = (w1p >> _WIDE_PARENT_SHIFT) & _WIDE_IDX_MASK
    actor1 = (w1p & _WIDE_ALO_MASK) | \
        (((w2p >> _WIDE_AHI_SHIFT) & 0x3F) << 10)
    s_rank = jnp.take(rank_table, actor1)
    prior_vis = ((w2p >> _WIDE_VIS_SHIFT) & 1).astype(bool) & valid_plane
    prior_idx = jnp.where(valid_plane, (w2p & _WIDE_IDX_MASK) - 1, -1)

    # ---- field resolution (scan-based; rows arrive field-sorted) ----
    boundary = _unpack_bits(flags_u8[:nb], n_pad)
    is_del = _unpack_bits(flags_u8[nb:], n_pad)
    valid = jnp.arange(n_pad) < n_rows
    clock = _build_clock(actor, seq, a_pad, coo_row, coo_col, coo_val)
    out = _resolve_sorted(boundary, actor, seq, clock, is_del, valid,
                          num_segments)

    # ---- element visibility ----
    touched, vis_hit = _vis_grid(row_slot, valid, out['surviving'],
                                 K, m_pad)
    visible = jnp.where(touched, vis_hit, prior_vis) & valid_plane

    ordered = _rga_order_batched(s_parent, s_elem, s_rank, visible,
                                 valid_plane)
    new_idx = ordered['vis_index']

    # ---- scatter the updated vis word + tree positions back ----
    w2n = (w2p & _WIDE_AHI_BITS) | \
        (visible.astype(jnp.int32) << _WIDE_VIS_SHIFT) | (new_idx + 1)
    scatter_pos = jnp.where(valid_plane, pos_mat, cap).reshape(-1)
    w2f = w2f.at[scatter_pos].set(w2n.reshape(-1), mode='drop')
    tpf = tpf.at[scatter_pos].set(
        ordered['tree_pos'].reshape(-1), mode='drop')

    surv_u8 = jnp.sum(
        out['surviving'].reshape(-1, 8).astype(jnp.uint8)
        * (jnp.uint8(1) << (7 - jnp.arange(8, dtype=jnp.uint8))),
        axis=1, dtype=jnp.uint8)
    vis_prior = (prior_vis.astype(jnp.int32) << _WIDE_VIS_SHIFT) | \
        (prior_idx + 1)
    vis_new = (visible.astype(jnp.int32) << _WIDE_VIS_SHIFT) | \
        (new_idx + 1)
    return w1f, w2f, w3f, tpf, surv_u8, out['winner'], vis_prior, \
        vis_new


@partial(jax.jit, static_argnames=('fmt', 'sizes', 'num_segments',
                                   'a_pad', 'm_pad', 'dm_pad',
                                   'has_remap'))
def _fused_general_incr(w1m, w2m, w3m, tpm, wire, jd_base, ws, n_old,
                        n_rows, aux, *, fmt, sizes, num_segments,
                        a_pad, m_pad, dm_pad, has_remap):
    """One apply as an INCREMENTAL index update (Jiffy-style batch
    insert) against the packed/WIDE resident mirror: instead of
    re-deriving every dirty sequence's order from scratch
    (`_rga_order_batched` — one lexsort plus ~2·log2(m) dependent
    gather rounds over the whole tree), this merges the tick's delta
    into the PERSISTENT tree-position plane ('tp'):

    1. the delta nodes order among THEMSELVES with
       `_rga_delta_order_batched` over [K, dm_pad+1] planes — O(delta
       log delta), independent of tree size;
    2. ONE prefix-sum pass over the [K, m_pad] planes splices them in:
       old node at position p shifts by #{delta anchors < p}, delta
       node with group anchor a and delta rank r lands at a + r + 1;
    3. the visibility index rebuilds with the same scatter + cumsum +
       gather the rebuild path uses (deletes/sets are pure visibility
       flips — zero sort work).

    Valid only under the host-checked FRONT-INSERT precondition (every
    delta root's elem exceeds its object's pre-tick max elem) and only
    for objects whose 'tp' plane is current (`pool.idx_ok`); the host
    falls back to the rebuild variant otherwise. ``aux`` is the packed
    format's rank_remap (`has_remap`) or the wide format's rank_table.

    SUFFIX WINDOW (``ws``, int32[K]): for chain-shaped objects
    (``pool.idx_linear`` — tree position == local index) the host may
    bound each job to the suffix window [ws_j, n_j) that contains
    every delta anchor and every touched node: ``job_start`` arrives
    rebased by ws_j, ``jd_base`` arrives window-RELATIVE, m_pad is the
    padded WINDOW width, and the plane holds only the window's nodes.
    Inside the program tp VALUES stay absolute while plane INDICES are
    window-relative (offset by ws_j); the visible count the window
    skips (``pvis``) reads from the folded mirror's own vis bits —
    below-window nodes are untouched by construction, so their
    pre-update bits are exact. ``ws = 0`` (the non-windowed dispatch)
    reduces every rebase to the identity. Below-window mirror words
    are never rewritten (the write-back covers exactly the window),
    which is what makes the renumber O(window) end to end.

    Same wire layout, resolution pipeline and output contract as the
    matching rebuild variant — the parity suite
    (tests/test_sequence_index.py) pins incremental == rebuild ==
    host oracle. Returns the uniform 8-tuple (w1', w2', w3', tp',
    surv_u8, winner, visA, visB): packed sets w3' = w3m (dummy) and
    visA = visB = vis_packed; wide returns vis_prior/vis_new."""
    from .merge import _resolve_sorted
    from .sequence import _rga_delta_order_batched
    d_pad, n_pad, K, nnz_pad = sizes
    cap = w1m.shape[0]
    nb = n_pad >> 3

    # ---- wire parse: byte-identical section layouts to the rebuild
    # variants (the host builds ONE wire buffer either way) ----
    if fmt == 'packed':
        (w1d, d_pos, row_slot, coo_row, job_start, job_n, w2e, seq,
         coo_val, actor, flags_u8, coo_col) = \
            _parse_wire_packed(wire, sizes)
        if has_remap:
            w1m = (w1m & ~0xFFFF) | jnp.take(aux, w1m & 0xFFFF) \
                .astype(jnp.int32)
    else:
        (w1d, w3d, d_pos, row_slot, seq, coo_row, coo_val, job_start,
         job_n, d_ahi, actor, flags_u8, coo_col) = \
            _parse_wire_wide(wire, sizes)

    # ---- fold the new nodes in (an existing mirror is a
    # precondition of the incremental path, so always has_old).
    # Inverse-gather formulation: a cap-sized SCATTER costs ~40x a
    # gather on the XLA backends (it materializes a fresh array per
    # update set), so instead of scattering every old slot to its
    # shifted position, each output slot GATHERS its source — the
    # shift is one shared prefix sum over the delta-slot marks, and
    # only the d_pad delta values scatter (O(delta) updates). ----
    i = jnp.arange(cap, dtype=jnp.int32)
    tgt_new = d_pos + jnp.arange(d_pad, dtype=jnp.int32)
    in_new = jnp.zeros((cap + 1,), bool).at[tgt_new].set(
        True, mode='drop')[:cap]
    d_before = jnp.cumsum(in_new.astype(jnp.int32))
    src = jnp.minimum(jnp.maximum(i - d_before, 0), cap - 1)

    def fold(col, dcol):
        base = jnp.where(in_new, 0, jnp.take(col, src))
        return base.at[tgt_new].set(dcol, mode='drop')

    w1f = fold(w1m, w1d)
    if fmt == 'packed':
        w2f = fold(w2m, w2e)
        w3f = w3m
    else:
        w2f = fold(w2m, d_ahi << _WIDE_AHI_SHIFT)
        w3f = fold(w3m, w3d)
    tpf = fold(tpm, jnp.zeros(d_pad, jnp.int32))

    # ---- suffix-window prefix: #visible nodes each job skips below
    # its window, straight off the folded mirror (positions
    # [job_start - ws, job_start) hold exactly the skipped locals
    # [0, ws); new nodes splice above them and carry vis bit 0, and
    # below-window visibility cannot change this tick). ws = 0 gives
    # pvis = 0 — the non-windowed dispatch pays one cumsum, nothing
    # else. ----
    vshift = _W2_VIS_SHIFT if fmt == 'packed' else _WIDE_VIS_SHIFT
    visbit = ((w2f >> vshift) & 1).astype(jnp.int32)
    vcum = jnp.concatenate([jnp.zeros(1, jnp.int32),
                            jnp.cumsum(visbit, dtype=jnp.int32)])
    pvis = jnp.take(vcum, jnp.clip(job_start, 0, cap)) - \
        jnp.take(vcum, jnp.clip(job_start - ws, 0, cap))

    # ---- job planes ----
    l = jnp.arange(m_pad, dtype=jnp.int32)
    rowi = jnp.arange(K, dtype=jnp.int32)[:, None]
    pos_mat = job_start[:, None] + l[None, :]
    valid_plane = l[None, :] < job_n[:, None]
    pos_c = jnp.minimum(jnp.where(valid_plane, pos_mat, 0), cap - 1)
    w1p = jnp.take(w1f, pos_c)
    w2p = jnp.take(w2f, pos_c)
    tpp = jnp.take(tpf, pos_c)
    if fmt == 'packed':
        s_parent = w1p >> 16
        s_rank = w1p & 0xFFFF
        s_elem = w2p & _W2_ELEM
        prior_vis = ((w2p >> _W2_VIS_SHIFT) & 1).astype(bool) \
            & valid_plane
        prior_idx = jnp.where(
            valid_plane, ((w2p >> _W2_IDX_SHIFT) & _W2_ELEM) - 1, -1)
    else:
        s_elem = jnp.take(w3f, pos_c)
        s_parent = (w1p >> _WIDE_PARENT_SHIFT) & _WIDE_IDX_MASK
        actor1 = (w1p & _WIDE_ALO_MASK) | \
            (((w2p >> _WIDE_AHI_SHIFT) & 0x3F) << 10)
        s_rank = jnp.take(aux, actor1)
        prior_vis = ((w2p >> _WIDE_VIS_SHIFT) & 1).astype(bool) \
            & valid_plane
        prior_idx = jnp.where(valid_plane,
                              (w2p & _WIDE_IDX_MASK) - 1, -1)

    # ---- field resolution (identical to the rebuild variants) ----
    boundary = _unpack_bits(flags_u8[:nb], n_pad)
    is_del = _unpack_bits(flags_u8[nb:], n_pad)
    valid = jnp.arange(n_pad) < n_rows
    clock = _build_clock(actor, seq, a_pad, coo_row, coo_col, coo_val)
    out = _resolve_sorted(boundary, actor, seq, clock, is_del, valid,
                          num_segments)

    # ---- element visibility ----
    touched, vis_hit = _vis_grid(row_slot, valid, out['surviving'],
                                 K, m_pad)
    visible = jnp.where(touched, vis_hit, prior_vis) & valid_plane

    # ---- incremental order update: delta ordering + ONE prefix-sum
    # merge against the persistent 'tp' plane ----
    is_old_node = (l[None, :] < jd_base[:, None]) & valid_plane
    dj = jnp.arange(dm_pad, dtype=jnp.int32)
    dcols = jd_base[:, None] + dj[None, :]
    dvalid = dj[None, :] < (job_n - jd_base)[:, None]
    dcols_c = jnp.minimum(jnp.where(dvalid, dcols, 0), m_pad - 1)
    dparent = jnp.take_along_axis(s_parent, dcols_c, axis=1)
    delem = jnp.take_along_axis(s_elem, dcols_c, axis=1)
    drank = jnp.take_along_axis(s_rank, dcols_c, axis=1)
    # a delta node whose parent pre-existed is a delta ROOT; its
    # anchor is the parent's OLD tree position (front-insert: the
    # whole group splices immediately after the anchor). dparent is an
    # absolute local index; the window plane rebases it by ws.
    dparent_rel = dparent - ws[:, None]
    p_old = dvalid & (dparent_rel < jd_base[:, None])
    anchor = jnp.take_along_axis(
        tpp, jnp.clip(dparent_rel, 0, m_pad - 1), axis=1)

    def pad1(x, fill):
        return jnp.concatenate(
            [jnp.full((K, 1), fill, x.dtype), x], axis=1)

    dpos = _rga_delta_order_batched(
        pad1(jnp.where(p_old, 0,
                       dparent_rel - jd_base[:, None] + 1), 0),
        pad1(jnp.where(p_old, anchor, 0), 0),
        pad1(delem, 0), pad1(drank, 0), pad1(dvalid, True))
    dm1 = dm_pad + 1
    is_root1 = pad1(p_old, False)
    dvalid1 = pad1(dvalid, False)
    anch1 = pad1(jnp.where(p_old, anchor, 0), 0)
    dpos_c = jnp.minimum(jnp.maximum(dpos, 0), dm1 - 1)
    # group anchor per delta DFS position: roots scatter theirs, the
    # running max propagates it over each root's (contiguous) subtree
    # — anchors ascend across groups by construction of the sort
    anch_at = jnp.zeros((K, dm1), jnp.int32).at[
        rowi, jnp.where(is_root1, dpos_c, 0)].max(
        jnp.where(is_root1, anch1, 0), mode='drop')
    a_pos = jax.lax.cummax(anch_at, axis=1)
    a_of = jnp.take_along_axis(a_pos, dpos_c, axis=1)
    d_tp = a_of + dpos                 # final position: a + r + 1
    # old-node shift = #{delta anchors < old position}: scatter-add
    # the anchors, one cumsum — THE merge prefix-sum (anchor tp values
    # are absolute; the plane index is window-relative)
    cnt_a = jnp.zeros((K, m_pad), jnp.int32).at[
        rowi, jnp.where(dvalid1,
                        jnp.clip(a_of - ws[:, None], 0, m_pad - 1),
                        0)].add(dvalid1.astype(jnp.int32), mode='drop')
    cum_a = jnp.cumsum(cnt_a, axis=1)
    tpp_c = jnp.clip(tpp - ws[:, None], 0, m_pad - 1)
    shift = jnp.take_along_axis(cum_a, tpp_c, axis=1) - \
        jnp.take_along_axis(cnt_a, tpp_c, axis=1)
    tp_new = jnp.where(is_old_node, tpp + shift, 0)
    dslot = jnp.where(dvalid1, pad1(dcols, 0), m_pad)
    tp_new = tp_new.at[rowi, dslot].set(d_tp, mode='drop')

    # ---- visibility index over the updated order (one flat
    # permutation scatter + cumsum + gather, as the rebuild's step 4;
    # tp_new is injective per job over the chain, so a plain set
    # suffices). Windowed jobs renumber only the suffix: relative
    # positions start at 0 (the node AT tp == ws is included) and the
    # skipped prefix re-enters as the pvis offset. ----
    on_chain = valid_plane & (tp_new > 0) & (tp_new >= ws[:, None])
    tp_rel = jnp.where(on_chain, tp_new - ws[:, None], 0)
    flat_tp = jnp.where(on_chain, rowi * m_pad + tp_rel, K * m_pad) \
        .reshape(-1)
    vis_ord = jnp.zeros((K * m_pad + 1,), bool).at[flat_tp].set(
        (visible & on_chain).reshape(-1),
        mode='drop')[:K * m_pad].reshape(K, m_pad)
    vis_rank = (jnp.cumsum(vis_ord, axis=1) - vis_ord) \
        .astype(jnp.int32)
    new_idx = jnp.take_along_axis(
        vis_rank, jnp.minimum(tp_rel, m_pad - 1), axis=1) + \
        pvis[:, None]
    new_idx = jnp.where(visible & on_chain, new_idx, -1)

    # ---- write the updated vis word + tree positions back. Same
    # inverse-gather idiom as the fold: every job's nodes are ONE
    # contiguous pos window, so window membership and the owning job
    # come from K-sized mark scatters + one prefix max, and each
    # mirror slot gathers its updated value — no plane-sized scatter.
    if fmt == 'packed':
        w2n = (visible.astype(jnp.int32) << _W2_VIS_SHIFT) | \
            ((new_idx + 1) << _W2_IDX_SHIFT) | s_elem
    else:
        w2n = (w2p & _WIDE_AHI_BITS) | \
            (visible.astype(jnp.int32) << _WIDE_VIS_SHIFT) | \
            (new_idx + 1)
    real_job = job_n > 0
    marks = jnp.zeros((cap + 1,), jnp.int32).at[
        jnp.where(real_job, job_start, cap)].add(
        real_job.astype(jnp.int32), mode='drop')
    marks = marks.at[jnp.where(real_job, job_start + job_n, cap)].add(
        -real_job.astype(jnp.int32), mode='drop')
    in_win = jnp.cumsum(marks[:cap]) > 0
    job_mark = jnp.zeros((cap + 1,), jnp.int32).at[
        jnp.where(real_job, job_start, cap)].max(
        jnp.arange(K, dtype=jnp.int32) + 1, mode='drop')
    job_at = jax.lax.cummax(job_mark[:cap]) - 1
    job_c = jnp.maximum(job_at, 0)
    l_at = jnp.minimum(
        jnp.maximum(i - jnp.take(job_start, job_c), 0), m_pad - 1)
    flat_at = job_c * m_pad + l_at

    def write_back(col, plane):
        return jnp.where(in_win, jnp.take(plane.reshape(-1), flat_at),
                         col)

    w2f = write_back(w2f, w2n)
    tpf = write_back(tpf, tp_new)

    surv_u8 = jnp.sum(
        out['surviving'].reshape(-1, 8).astype(jnp.uint8)
        * (jnp.uint8(1) << (7 - jnp.arange(8, dtype=jnp.uint8))),
        axis=1, dtype=jnp.uint8)
    if fmt == 'packed':
        vis_packed = (prior_vis.astype(jnp.int32) << 31) | \
            (visible.astype(jnp.int32) << 30) | \
            ((prior_idx + 1) << _W2_IDX_SHIFT) | (new_idx + 1)
        vis_a = vis_b = vis_packed
    else:
        vis_a = (prior_vis.astype(jnp.int32) << _WIDE_VIS_SHIFT) | \
            (prior_idx + 1)
        vis_b = (visible.astype(jnp.int32) << _WIDE_VIS_SHIFT) | \
            (new_idx + 1)
    return w1f, w2f, w3f, tpf, surv_u8, out['winner'], vis_a, vis_b


# dummy W3 operand for the packed incremental dispatch (the program's
# static fmt branch never reads it; one shared constant keeps the jit
# signature stable)
_NO_W3 = np.zeros(1, np.int32)


def _mirror_tp_in(mir, cap, n_total):
    """The persistent 'tp' plane as this apply's input: grown with the
    mirror capacity; zeros when absent (first mirror, pre-index
    resume) — the rebuild variant then (re)writes the dirty objects'
    slots and validates them."""
    if mir is None or 'tp' not in mir:
        return jnp.zeros(cap, jnp.int32)
    if mir['cap'] < n_total:
        return jnp.concatenate(
            [mir['tp'], jnp.zeros(cap - mir['cap'], jnp.int32)])
    return mir['tp']


def _pick_incremental(pool, mir, dirty, n_j, nof_pre, mel_pre, n_old,
                      n_total, m_pad, opts, parent_d, elemc_d):
    """Mode switch + eligibility + counters for one packed/wide apply.
    Returns the eligibility tuple or None (rebuild)."""
    incr = None
    if (_INDEX_MODE != 'rebuild' and mir is not None
            and 'tp' in mir and n_old > 0 and len(dirty)):
        incr = _incr_eligibility(pool, dirty, n_j, nof_pre, mel_pre,
                                 n_old, n_total, m_pad, parent_d,
                                 elemc_d, opts)
    if incr is not None:
        metrics.bump('device_idx_incremental_applies')
        metrics.bump('device_idx_delta_nodes', int(n_total - n_old))
    else:
        if len(dirty):
            metrics.bump('device_idx_rebuild_applies')
        if _INDEX_MODE == 'require' and len(dirty):
            # loud, with store rollback via the apply txn: an
            # invalidation path that silently falls back is a bug the
            # tests must see
            raise RuntimeError(
                "incremental index path required (_INDEX_MODE="
                "'require') but this apply is ineligible")
    return incr


def _incr_eligibility(pool, dirty, n_j, nof_pre, mel_pre, n_old,
                      n_total, m_pad, parent_d, elemc_d, opts):
    """Host gate of the incremental-index path: O(delta) checks that
    every dirty object's persistent 'tp' plane is current
    (``pool.idx_ok``) and that every delta node with a PRE-EXISTING
    parent is a front insert (elem strictly above the object's
    pre-tick max elem, hence above every existing sibling — the
    sequential-typing and concurrent-append shape). A late/concurrent
    interleaving insert, a first-sight object or an oversized delta
    returns None: the apply takes the whole-object rebuild variant,
    which re-validates the index for its dirty set. Returns
    ``(dm_pad, jd_base, min_rp)`` on success, where ``min_rp[j]`` is
    the smallest PRE-EXISTING parent local any of job j's delta nodes
    anchors to (``jd_base[j]`` when none) — the anchor bound the
    suffix-window pick (`_apply_window`) intersects with the touched
    rows."""
    K_jobs = len(dirty)
    if K_jobs == 0:
        return None
    hi_obj = int(dirty.max())
    if hi_obj >= len(pool.idx_ok) or hi_obj >= len(nof_pre):
        return None
    if not pool.idx_ok[dirty].all():
        metrics.bump('device_idx_invalidations')
        return None
    old_nof = nof_pre[dirty]
    if (old_nof < 1).any():
        return None
    jd_n = n_j - old_nof
    if (jd_n < 0).any():
        return None
    dm = int(jd_n.max()) if K_jobs else 0
    if dm and 2 * dm > int(n_j.max()):
        # the delta approaches the tree size (bulk load, first fill):
        # the rebuild is no more work and re-validates the index
        return None
    dm_pad = opts.pad_nodes(max(dm, 8))
    d_n = n_total - n_old
    min_rp = old_nof.astype(np.int64).copy()
    if d_n:
        # delta obj column in pos order == the sorted append-order
        # column (pos order sorts by (obj, local); within one object
        # the values are identical, so alignment with the d planes
        # holds rowwise)
        obj_d = np.sort(pool.obj[n_old:n_total]).astype(np.int64)
        pos = np.searchsorted(dirty, obj_d)
        safe = np.minimum(pos, K_jobs - 1)
        in_dirty = (pos < K_jobs) & (dirty[safe] == obj_d)
        if in_dirty.any():
            par = np.asarray(parent_d[:d_n])[in_dirty]
            base = old_nof[safe[in_dirty]]
            rooted = par < base
            if rooted.any():
                mel = mel_pre[obj_d[in_dirty][rooted]]
                el = np.asarray(elemc_d[:d_n])[in_dirty][rooted] \
                    .astype(np.int64)
                if (el <= mel).any():
                    metrics.bump('device_idx_invalidations')
                    return None
                np.minimum.at(min_rp, safe[in_dirty][rooted],
                              par[rooted].astype(np.int64))
    return dm_pad, old_nof.astype(np.int32), min_rp


def _apply_window(lin_pre, dirty, n_j, jd_base, min_rp, row_slot_v,
                  job_start_v, job_n_v, m_pad, n_rows, K, opts):
    """Suffix-window gate + in-place wire rewrite for an incremental
    apply. A job windows when its pre-append tree is a pure chain
    (``pool.idx_linear``: parent[local] == local-1 for every real
    node, so tree position == local and any suffix of locals is a
    suffix of tree positions) — then nothing below
    ``ws = min(min rooted delta parent, min touched node local)``
    can change visibility or index, and the device only needs the
    plane columns [ws, n). Rewrites the wire's job_start (+= ws),
    job_n (-= ws) and row_slot (rebased to window columns with the
    shrunk per-job stride ``w_pad``) sections IN PLACE — the byte
    layout has no m_pad dependence, so native- and numpy-assembled
    wires take the identical rewrite. Returns
    ``(w_pad, ws_k, jd_rel, win_n)`` or None (dispatch whole-plane):
    only engages when the windowed plane is a strictly smaller jit
    bucket than the full one, so ``ws = 0`` never reaches a program
    specialised for windows — zeros in ``ws_k`` padding rows keep the
    program's math an identity there."""
    kj = len(dirty)
    if kj == 0 or int(dirty.max()) >= len(lin_pre):
        return None
    if not lin_pre[dirty].all():
        return None
    ws = np.minimum(jd_base.astype(np.int64), min_rp)
    rs = np.asarray(row_slot_v[:n_rows])
    ok = rs >= 0
    loc = nd = None
    if ok.any():
        loc = rs[ok].astype(np.int64) // m_pad
        nd = rs[ok].astype(np.int64) % m_pad
        np.minimum.at(ws, loc, nd)
    ws = np.maximum(ws, 0)
    win_n = n_j.astype(np.int64) - ws
    w_pad = opts.pad_nodes(max(int(win_n.max()), 8))
    if w_pad >= m_pad:
        return None
    if loc is not None:
        row_slot_v[:n_rows][ok] = \
            (loc * w_pad + (nd - ws[loc])).astype(np.int32)
    job_start_v[:kj] = job_start_v[:kj] + ws.astype(np.int32)
    job_n_v[:kj] = win_n.astype(job_n_v.dtype)
    jd_rel = (jd_base.astype(np.int64) - ws).astype(np.int32)
    ws_k = np.zeros(K, np.int32)
    ws_k[:kj] = ws
    return w_pad, ws_k, jd_rel, win_n


@jax.jit
def _mirror_pack(parent, elemc, actor, visible, visidx, rank_table):
    """cols -> packed mirror (format upgrade when the guards pass)."""
    rank1 = jnp.take(rank_table, actor + 1) + 1
    rank1 = jnp.where(actor < 0, 0, rank1)
    w1 = (parent << 16) | rank1
    w2 = (visible.astype(jnp.int32) << _W2_VIS_SHIFT) | \
        ((visidx + 1) << _W2_IDX_SHIFT) | elemc
    return w1, w2


@jax.jit
def _mirror_unpack(w1, w2, rank_to_actor):
    """packed -> cols mirror (format downgrade before a fallback
    apply). `rank_to_actor[rank+1]` = actor id (-1 at 0/head)."""
    parent = w1 >> 16
    actor = jnp.take(rank_to_actor, w1 & 0xFFFF)
    elemc = w2 & _W2_ELEM
    visible = ((w2 >> _W2_VIS_SHIFT) & 1).astype(bool)
    visidx = ((w2 >> _W2_IDX_SHIFT) & _W2_ELEM) - 1
    return parent, elemc, actor, visible, visidx


@jax.jit
def _mirror_pack_wide(parent, elemc, actor, visible, visidx):
    """cols -> WIDE mirror words (stable actor ids, no rank table)."""
    actor1 = actor + 1                       # head (-1) -> 0
    w1 = (parent << _WIDE_PARENT_SHIFT) | (actor1 & _WIDE_ALO_MASK)
    w2 = ((actor1 >> 10) << _WIDE_AHI_SHIFT) | \
        (visible.astype(jnp.int32) << _WIDE_VIS_SHIFT) | (visidx + 1)
    return w1, w2, elemc


@jax.jit
def _mirror_unpack_wide(w1, w2, w3):
    """WIDE -> cols mirror pieces."""
    parent = (w1 >> _WIDE_PARENT_SHIFT) & _WIDE_IDX_MASK
    actor1 = (w1 & _WIDE_ALO_MASK) | \
        (((w2 >> _WIDE_AHI_SHIFT) & 0x3F) << 10)
    actor = actor1 - 1
    visible = ((w2 >> _WIDE_VIS_SHIFT) & 1).astype(bool)
    visidx = (w2 & _WIDE_IDX_MASK) - 1
    return parent, w3, actor, visible, visidx


def _rank_table(store, opts):
    """actor-id -> string-rank device table, 1-BASED (slot 0 is the
    head sentinel) — the layout `_mirror_pack`/the cols program index
    with `actor + 1`."""
    n_act = len(store.actors)
    rt = np.zeros(opts.pad_actors(n_act + 1), np.int32)
    rt[1:n_act + 1] = store.actor_str_ranks()
    return jnp.asarray(rt)


def _mirror_convert(mir, to_fmt, store, opts):
    """Convert a resident mirror between the packed/wide/cols formats
    (a store crossing a format guard mid-stream — e.g. a text document
    growing past 32767 nodes upgrades packed -> wide IN PLACE and keeps
    riding a fused packed program). One or two elementwise device
    programs plus small-table gathers; same cap/n/pos_row. Every
    conversion bumps a `general_mirror_convert_<from>_to_<to>` counter
    so a fleet silently living on a slower format is visible."""
    n_act = len(store.actors)
    from_fmt = mir.get('fmt', 'cols')
    metrics.bump('general_mirror_converts')
    metrics.bump(f'general_mirror_convert_{from_fmt}_to_{to_fmt}')
    if from_fmt == 'packed':
        old_ranks = mir['ranks']
        inv = np.full(opts.pad_actors(len(old_ranks) + 2), -1, np.int32)
        inv[old_ranks + 1] = np.arange(len(old_ranks))
        parent, elemc, actor, visible, visidx = _mirror_unpack(
            mir['w1'], mir['w2'], jnp.asarray(inv))
    elif from_fmt == 'wide':
        parent, elemc, actor, visible, visidx = _mirror_unpack_wide(
            mir['w1'], mir['w2'], mir['w3'])
    else:
        parent, elemc, actor, visible, visidx = (
            mir['parent'], mir['elemc'], mir['actor'], mir['visible'],
            mir['vis_index'])
    base = {'cap': mir['cap'], 'n': mir['n'], 'pos_row': mir['pos_row']}
    # the order index is format-independent (tree_pos per node): it
    # carries through packed<->wide conversions untouched, so idx_ok
    # claims survive a format crossing; the cols fallback drops it
    # (no incremental program there — the caller resets idx_ok)
    if to_fmt in ('packed', 'wide') and 'tp' in mir:
        base['tp'] = mir['tp']
    if to_fmt == 'packed':
        ranks = np.asarray(store.actor_str_ranks())
        w1, w2 = _mirror_pack(parent, elemc, actor, visible, visidx,
                              _rank_table(store, opts))
        return {'fmt': 'packed', 'w1': w1, 'w2': w2,
                'ranks': ranks.copy(), **base}
    if to_fmt == 'wide':
        w1, w2, w3 = _mirror_pack_wide(parent, elemc, actor, visible,
                                       visidx)
        return {'fmt': 'wide', 'w1': w1, 'w2': w2, 'w3': w3,
                'rank_n': n_act, 'rank_table': _rank_table(store, opts),
                **base}
    return {'fmt': 'cols',
            'parent': parent, 'elemc': elemc, 'actor': actor,
            'visible': visible, 'vis_index': visidx,
            'rank_n': n_act, 'rank_table': _rank_table(store, opts),
            **base}


# Estimated device bytes per resident mirror row, by format: packed =
# two int32 words + the int32 tree_pos index plane, wide = three + the
# index plane, cols = parent/elemc/actor/vis_index int32 + visible
# bool (no index plane — the cols fallback always rebuilds). Host
# arithmetic only — memory accounting must never force a device sync.
_MIRROR_ROW_BYTES = {'packed': 12, 'wide': 16, 'cols': 17}


def mirror_bytes(mir):
    """Estimated device-plane bytes of a resident mirror dict (0 when
    no mirror has materialized) — the per-store read behind the
    ``fleet_status()['memory']`` block and the process-wide
    ``mem_device_plane_bytes`` gauge."""
    if not mir:
        return 0
    return _MIRROR_ROW_BYTES.get(mir.get('fmt'), 17) * \
        int(mir.get('cap', 0))


def _update_mirror_gauges(fmt, cap):
    """Refresh the device-plane memory gauges after an apply installed
    a mirror of ``fmt`` at capacity ``cap`` (last-applied store wins —
    the gauges are process-level; per-store truth lives in
    ``fleet_status()['memory']``). The non-active formats read 0 so a
    dashboard sees format transitions, and the peak watermark only
    ratchets up."""
    total = _MIRROR_ROW_BYTES[fmt] * cap
    metrics.set_gauge('mem_device_plane_bytes', total)
    metrics.set_gauge('mem_device_packed_bytes',
                      total if fmt == 'packed' else 0)
    metrics.set_gauge('mem_device_wide_bytes',
                      total if fmt == 'wide' else 0)
    metrics.set_gauge('mem_device_cols_bytes',
                      total if fmt == 'cols' else 0)
    metrics.ratchet('mem_device_plane_peak_bytes', total)


# -- apply -------------------------------------------------------------------

class GeneralPatch:
    """Patches from one general apply. The winner/visibility-dependent
    columns live on DEVICE until first use (`_ensure`) — an apply-only
    pipeline (the DocSet ingestion hot path) never fetches them;
    `diffs(d)` / `to_patches()` materialize reference-format dicts."""

    __slots__ = ('store', 'n_docs', 'creates', 'f_doc', 'f_obj', 'f_key',
                 'f_kind', 'f_has_winner', 'f_value', 'f_actor', 'f_link',
                 's_ptr', 's_actor', 's_value', 's_link', 'seq_edits',
                 'clock_rows', 'keys', 'values', 'actors', '_raw',
                 '_ready', '__weakref__')

    def __init__(self, store):
        self.store = store
        self.n_docs = store.n_docs
        self.creates = []        # (doc, obj uuid, type name) in op order
        self.seq_edits = {}      # obj_row -> dict of edit columns
        self.keys = store.keys
        self.values = store.values
        self.actors = store.actors
        # apply-time clock snapshot by REFERENCE: clock_merge only
        # replaces these arrays (miss path) or, while this patch is
        # alive (the weak registration below), copies c_seq before its
        # in-place scatter — so the hot path, which drops the patch
        # before the next tick, never pays an O(clock table) copy
        self.clock_rows = (store.c_doc, store.c_actor, store.c_seq)
        sharers = getattr(store, '_c_sharers', None)
        if sharers is None:
            import weakref
            sharers = store._c_sharers = weakref.WeakSet()
        sharers.add(self)
        self._raw = None
        self._ready = True       # empty patches need no device fetch

    def block_until_ready(self):
        """Wait for the full apply: device program AND the deferred
        entry commit (so timed one-shot applies pay everything)."""
        if self._raw is not None:
            self.store._commit_pending()
            jax.block_until_ready(self._raw['winner_dev'])
        return self

    def _ensure(self):
        """Fetch the device outputs and build the winner-dependent patch
        columns + sequence edit columns (once)."""
        if self._ready:
            return
        self._ready = True
        import time
        _t0 = time.perf_counter()
        store = self.store
        raw = self._raw
        F = len(self.f_obj)
        # ONE device_get for everything this read needs — each fetch
        # pays a full link round trip (~100 ms floor on the tunnel).
        # When the pending commit is THIS apply's, its survivor bytes
        # join the same trip. The fetch itself runs OUTSIDE the host
        # lock (device handles are immutable) so an async apply keeps
        # staging while this thread waits on the link; only the commit
        # and the pool-ref capture lock, briefly.
        with store._host_lock:
            pc = store._pending_commit
            own_pc = pc is not None and pc.get('patch') is self
            surv_dev = pc['surv_u8_dev'] if own_pc else None
        # edit-stream read: ONE extra device dispatch compacts the
        # tick's sequence edits into pre-ordered [K, e_pad] buffers
        # (e_pad bounded by the tick's row count, never the tree
        # size) — the fetch below then moves O(delta) bytes instead
        # of the full O(doc) vis planes, and the per-object host
        # argsorts disappear
        # element-field index (field rows keyed by a sequence node),
        # shared by the edit-stream dispatch and both read branches
        elem_fi = np.flatnonzero(self.f_kind)
        ef_obj = self.f_obj[elem_fi] if len(elem_fi) else \
            np.zeros(0, np.int32)
        ef_node = (self.f_key[elem_fi] & 0x7FFFFFFF) \
            .astype(np.int64) if len(elem_fi) else \
            np.zeros(0, np.int64)
        es_dev = None
        if raw['vis_planes'] is not None and _edit_stream_on() \
                and raw.get('e_pad'):
            from . import pallas_view as _pview
            dirty_a = raw['dirty']
            m_pad = raw['m_pad']
            if raw['vis_fmt'] == 'packed':
                k_pl = int(raw['vis_planes'].shape[0])
            elif raw['vis_fmt'] == 'wide':
                k_pl = int(raw['vis_planes'][0].shape[0])
            else:
                k_pl = int(raw['vis_planes'][0].shape[0])
            tb = np.zeros((k_pl, m_pad), bool)
            if len(elem_fi) and len(dirty_a):
                ji_t = np.searchsorted(dirty_a, ef_obj)
                ji_c = np.minimum(ji_t, len(dirty_a) - 1)
                ok_t = dirty_a[ji_c] == ef_obj
                tb[ji_c[ok_t], ef_node[ok_t]] = True
            es_dev = _pview.dispatch_edit_stream(
                raw['vis_fmt'], raw['vis_planes'],
                np.packbits(tb, axis=1), raw['e_pad'])
        fetch = [raw['winner_dev']]
        if es_dev is not None:
            fetch.append(es_dev)
        elif raw['vis_planes'] is not None:
            fetch.append(raw['vis_planes'])
        if own_pc:
            fetch.append(surv_dev)
        fetched = jax.device_get(tuple(fetch))
        w_row = np.asarray(fetched[0])[:F]
        fetched_planes = fetched[1] if raw['vis_planes'] is not None \
            else None
        if own_pc:
            with store._host_lock:
                # re-check under the lock: an async apply may have
                # committed OUR pending while we waited on the fetch and
                # installed ITS OWN — feeding it our survivor bytes
                # would fold the wrong mask into the entry columns
                if store._pending_commit is pc:
                    store._commit_pending_locked(_surv_u8=fetched[-1])
        # else: this patch's commit already ran — the pending apply (if
        # any) is a LATER one and committing it here would block on ITS
        # device program for no benefit
        surviving = raw['surviving']
        cat, rorder = raw['cat'], raw['order']
        r_value = cat['value'][rorder]
        r_actor = cat['actor'][rorder]
        r_link = cat['link'][rorder]
        r_seg = raw['r_seg']

        has_winner = w_row >= 0
        w_safe = np.maximum(w_row, 0)
        self.f_has_winner = has_winner
        self.f_value = np.where(has_winner, r_value[w_safe], -1) \
            .astype(np.int32)
        self.f_actor = np.where(has_winner, r_actor[w_safe], -1) \
            .astype(np.int32)
        self.f_link = np.where(has_winner, r_link[w_safe], False)

        s_rows = raw['s_rows']
        ent_is_loser = s_rows != w_row[r_seg[s_rows]]
        loser_rows = s_rows[ent_is_loser]
        loser_rows = loser_rows[np.argsort(r_seg[loser_rows],
                                           kind='stable')]
        s_counts = np.bincount(r_seg[loser_rows], minlength=F) if F \
            else np.zeros(0, np.int64)
        self.s_ptr = np.zeros(F + 1, np.int32)
        np.cumsum(s_counts, out=self.s_ptr[1:])
        self.s_actor = r_actor[loser_rows]
        self.s_value = r_value[loser_rows]
        self.s_link = r_link[loser_rows]

        # sequence edit columns per dirty object. Preferred path: the
        # edit-stream kernel already compacted each class in document
        # order on device — the loop below just slices delta-sized
        # buffers (no per-object argsorts, no O(doc) node-row gather).
        # Legacy path (cols-scale stores with _EDIT_STREAM off, A/B
        # tests): unpack the full vis planes and re-derive on host.
        def fis_of(nodes, lo, span):
            # node ids -> field-row ids within one object's ef span
            # (-1 = node has no field row)
            if not len(nodes):
                return np.zeros(0, np.int64)
            if not len(span):
                return np.full(len(nodes), -1, np.int64)
            p = np.minimum(np.searchsorted(span, nodes),
                           len(span) - 1)
            return np.where(span[p] == nodes,
                            elem_fi[lo + p], -1)

        planes = fetched_planes
        if planes is not None and es_dev is not None:
            pool = store.pool
            with store._host_lock:
                pool_actor, pool_elemc = pool.actor, pool.elemc
            (rm_b, insn_b, insi_b, setn_b, seti_b,
             cnts_b) = [np.asarray(x) for x in planes]
            dirty = raw['dirty']
            gained = raw['gained_max_elem']
            ps_sorted, ps_row = raw['pos_snap']
            e_cap = rm_b.shape[1]
            for ji, obj_row in enumerate(dirty.tolist()):
                nrm, nins, nset = cnts_b[ji].tolist()
                if max(nrm, nins, nset) > e_cap:
                    raise RuntimeError(
                        'edit-stream buffer overflow (e_pad '
                        f'{e_cap} < {max(nrm, nins, nset)} edits)')
                ins_nodes = insn_b[ji, :nins].astype(np.int64)
                set_nodes = setn_b[ji, :nset].astype(np.int64)
                lo, hi = np.searchsorted(ef_obj,
                                         [obj_row, obj_row + 1])
                span = ef_node[lo:hi]
                rowsq = ps_row[np.searchsorted(
                    ps_sorted, (np.int64(obj_row) << 32) | ins_nodes)]
                self.seq_edits[obj_row] = {
                    'max_elem': gained.get(obj_row),
                    # device order is prior-idx ASC; the emit wants
                    # descending — one reversed view, no sort
                    'removes': rm_b[ji, :nrm][::-1].astype(np.int64),
                    'ins_idx': insi_b[ji, :nins].astype(np.int32),
                    'ins_fis': fis_of(ins_nodes, lo, span),
                    'ins_actor': pool_actor[rowsq],
                    'ins_elemc': pool_elemc[rowsq],
                    'set_idx': seti_b[ji, :nset].astype(np.int32),
                    'set_fis': fis_of(set_nodes, lo, span),
                }
        elif planes is not None:
            # host read path (CPU backend, forced-off edit stream):
            # ONE plane fetch, then O(m) vectorized masks + O(delta)
            # sorts/lookups per dirty object — no more O(doc)
            # node-row gathers or full field_at tables (the pre-index
            # read rebuilt both per tick)
            pool = store.pool
            with store._host_lock:
                pool_actor, pool_elemc = pool.actor, pool.elemc
            if raw.get('vis_fmt') == 'packed':
                pv, nv, pi, ni = unpack_vis_word(
                    np.asarray(planes).view(np.uint32))
            elif raw.get('vis_fmt') == 'wide':
                pv, pi = unpack_wide_word(np.asarray(planes[0]))
                nv, ni = unpack_wide_word(np.asarray(planes[1]))
            else:
                pv, nv, pi, ni = [np.asarray(x) for x in planes]
            dirty, n_j = raw['dirty'], raw['dirty_n']
            gained = raw['gained_max_elem']
            ps_sorted, ps_row = raw['pos_snap']
            win_ws = raw.get('win_ws')
            for ji, obj_row in enumerate(dirty.tolist()):
                n = int(n_j[ji])
                # windowed apply: plane column c is node local ws + c
                # (the renumber only shipped the suffix window; the
                # indexes IN the plane words stay absolute)
                wsj = int(win_ws[ji]) if win_ws is not None else 0
                new_vis = nv[ji, :n]
                new_idx = ni[ji, :n].astype(np.int32)
                prev_idx = pi[ji, :n].astype(np.int32)
                was_vis = pv[ji, :n]
                lo, hi = np.searchsorted(ef_obj, [obj_row, obj_row + 1])
                span = ef_node[lo:hi]
                sp = span - wsj if wsj else span
                removes = np.flatnonzero(was_vis & ~new_vis)
                rm_old = -np.sort(-prev_idx[removes])
                ins_cols = np.flatnonzero(new_vis & ~was_vis)
                ins_cols = ins_cols[np.argsort(new_idx[ins_cols],
                                               kind='stable')]
                ins_nodes = ins_cols + wsj
                # sets only exist among TOUCHED nodes: intersect the
                # delta-sized touched span instead of a full mask
                tn = sp[(new_vis[sp] & was_vis[sp])] \
                    if len(sp) else sp
                set_cols = tn[np.argsort(new_idx[tn],
                                         kind='stable')]
                set_nodes = set_cols + wsj
                rowsq = ps_row[np.searchsorted(
                    ps_sorted,
                    (np.int64(obj_row) << 32) | ins_nodes)]
                self.seq_edits[obj_row] = {
                    'max_elem': gained.get(obj_row),
                    'removes': rm_old.astype(np.int64),
                    'ins_idx': new_idx[ins_cols],
                    'ins_fis': fis_of(ins_nodes, lo, span),
                    'ins_actor': pool_actor[rowsq],
                    'ins_elemc': pool_elemc[rowsq],
                    'set_idx': new_idx[set_cols],
                    'set_fis': fis_of(set_nodes, lo, span),
                }
        # patch-read closes the tick path: one device fetch + the
        # winner-dependent column build, measured as a completed span
        # (the read may run on a different thread than the apply —
        # span_event parents it under whatever span that thread holds)
        dt_ms = (time.perf_counter() - _t0) * 1e3
        metrics.observe('general_patch_read_ms', dt_ms)
        # the device-phase series fleet_status()['latency'] reports
        # alongside admit/pack/dispatch/run — same value, the phase
        # taxonomy name (general_patch_read_ms stays for back-compat)
        metrics.observe('device_patch_read_ms', dt_ms)
        if metrics.active:
            metrics.span_event('device.patch_read', dt_ms,
                               fields=F)

    def _plain_mask(self, fis):
        """Fields whose payload is a bare value (no link flag, no
        conflict entries) — the ONE definition of what the vectorized
        emit fast path may skip; `_field_payload` is its per-field
        counterpart and any new payload-shaping field flag must join
        this mask."""
        return ~(self.f_link[fis]
                 | (self.s_ptr[fis + 1] > self.s_ptr[fis]))

    def _field_payload(self, fi):
        """(value, link, conflicts) of field fi from the patch columns."""
        value = self.values[self.f_value[fi]] if self.f_value[fi] >= 0 \
            else None
        lo, hi = self.s_ptr[fi], self.s_ptr[fi + 1]
        losers = [(self.actors[self.s_actor[j]],
                   self.values[self.s_value[j]]
                   if self.s_value[j] >= 0 else None,
                   bool(self.s_link[j]))
                  for j in range(lo, hi)]
        losers.sort(key=lambda t: t[0], reverse=True)
        conflicts = None
        if losers:
            conflicts = []
            for a, v, is_link in losers:
                entry = {'actor': a, 'value': v}
                if is_link:
                    entry['link'] = True
                conflicts.append(entry)
        return value, bool(self.f_link[fi]), conflicts

    def _path(self, obj_row):
        store = self.store
        pool = store.pool
        path = []
        seen = set()
        with store._host_lock:
            return self._path_locked(store, pool, obj_row, path, seen)

    def _path_locked(self, store, pool, obj_row, path, seen):
        while store.obj_uuid[obj_row] != ROOT_ID:
            if obj_row in seen:
                return None
            seen.add(obj_row)
            inbound = store.obj_inbound.get(obj_row)
            if not inbound:
                return None
            parent_row, key = inbound[0]
            if store.is_seq(parent_row):
                pool.sync()
                node = int(key) & 0x7FFFFFFF
                idx = int(pool.vis_index[pool.row_at(parent_row, node)])
                if idx < 0:
                    return None
                path.insert(0, idx)
            else:
                path.insert(0, store.keys[int(key) & 0x7FFFFFFF])
            obj_row = parent_row
        return path

    def diffs(self, d):
        self._ensure()
        store = self.store
        out = []
        for doc, uuid, tname, max_elem in self.creates:
            if doc == d:
                diff = {'action': 'create', 'obj': uuid, 'type': tname}
                out.append(diff)
        # map-field diffs
        for fi in np.flatnonzero(self.f_doc == d):
            obj_row = int(self.f_obj[fi])
            if self.f_kind[fi]:
                continue                      # element fields: seq edits
            obj_uuid = store.obj_uuid[obj_row]
            key = store.keys[int(self.f_key[fi]) & 0x7FFFFFFF]
            path = self._path(obj_row)
            if self.f_has_winner[fi]:
                value, link, conflicts = self._field_payload(fi)
                edit = {'action': 'set', 'type': 'map', 'obj': obj_uuid,
                        'key': key, 'path': path, 'value': value}
                if link:
                    edit['link'] = True
                if conflicts:
                    edit['conflicts'] = conflicts
            else:
                edit = {'action': 'remove', 'type': 'map',
                        'obj': obj_uuid, 'key': key, 'path': path}
            out.append(edit)
        # sequence edits
        for obj_row, ed in self.seq_edits.items():
            if store.obj_doc[obj_row] != d:
                continue
            out.extend(self._seq_diffs(obj_row, ed))
        return out

    def _seq_diffs(self, obj_row, ed):
        store = self.store
        obj_uuid = store.obj_uuid[obj_row]
        tname = _TYPE_NAME[store.obj_type[obj_row]]
        path = self._path(obj_row)
        diffs = []
        if ed['max_elem'] is not None:
            diffs.append({'action': 'maxElem', 'type': tname,
                          'obj': obj_uuid, 'value': ed['max_elem'],
                          'path': path})
        for idx in ed['removes']:
            diffs.append({'action': 'remove', 'type': tname,
                          'obj': obj_uuid, 'index': int(idx),
                          'path': path})
        actors = store.actors

        def emit(fis, idxs, action, e_actor=None, e_elemc=None):
            """Edits for one pre-ordered batch: winner values fetched
            with ONE vectorized ValueTable pass; the rare
            link/conflict rows fall back to the per-field payload.
            ``e_actor``/``e_elemc`` (ins only) carry the elemId
            source columns, aligned with the batch."""
            vals = self.values.take(self.f_value[fis])
            plain = self._plain_mask(fis)
            for k, idx in enumerate(idxs.tolist()):
                if plain[k]:
                    value, link, conflicts = vals[k], False, None
                else:
                    value, link, conflicts = self._field_payload(
                        int(fis[k]))
                edit = {'action': action, 'type': tname,
                        'obj': obj_uuid, 'index': int(idx),
                        'value': value, 'path': path}
                if e_actor is not None:
                    edit['elemId'] = (f'{actors[e_actor[k]]}:'
                                      f'{int(e_elemc[k])}')
                if link:
                    edit['link'] = True
                if conflicts:
                    edit['conflicts'] = conflicts
                diffs.append(edit)

        emit(ed['ins_fis'], ed['ins_idx'], 'insert',
             ed['ins_actor'], ed['ins_elemc'])
        emit(ed['set_fis'], ed['set_idx'], 'set')
        return diffs

    def clock_of(self, d):
        c_doc, c_actor, c_seq = self.clock_rows
        lo, hi = np.searchsorted(c_doc, [d, d + 1])
        return {self.actors[c_actor[j]]: int(c_seq[j])
                for j in range(lo, hi) if c_seq[j] > 0}

    def patch(self, d):
        clock = self.clock_of(d)
        return {'clock': clock, 'deps': dict(clock), 'canUndo': False,
                'canRedo': False, 'diffs': self.diffs(d)}

    def to_patches(self):
        return [self.patch(d) for d in range(self.n_docs)]


def apply_general_block(store, block, options=None, return_timing=False):
    """`applyChanges` for general blocks: one fused device program
    resolves every touched field and re-orders every dirty sequence of
    every document in the batch. Mutates `store`; returns a
    :class:`GeneralPatch`. On a validation error the store rolls back to
    its pre-apply state (clock, log, queue, tables, trees).

    The whole host phase runs under the store's host lock, so patch
    extraction of an EARLIER apply may proceed on another thread
    (:func:`apply_general_block_async`) while this one stages."""
    with store._host_lock:
        txn = _Txn(store)
        try:
            # the fused-apply span covers admit+stage+dispatch; the
            # stage/dispatch split is emitted as completed child spans
            # from the timing points _apply_general already records
            with metrics.trace_span('device.fused_apply'):
                return _apply_general(store, block, options,
                                      return_timing, txn=txn)
        except BaseException:
            # validation errors (ValueError/TypeError) AND unexpected
            # failures (a MemoryError in the native stager, the forced
            # _NATIVE_STAGING=True RuntimeError) can fire after
            # admission/object creation mutated the store — the
            # store-intact-on-error contract holds for all of them
            txn.rollback(store)
            metrics.bump('apply_rollbacks')
            raise


class AsyncGeneralPatch:
    """Future over an applier-thread apply: resolves to the real
    :class:`GeneralPatch` (or re-raises the apply's error — the store
    itself rolled back and stays usable). Read methods proxy through
    :meth:`result`."""

    __slots__ = ('_event', '_patch', '_error')

    def __init__(self):
        self._event = threading.Event()
        self._patch = None
        self._error = None

    def result(self):
        self._event.wait()
        if self._error is not None:
            raise self._error
        return self._patch

    def block_until_ready(self):
        return self.result().block_until_ready()

    def diffs(self, d):
        return self.result().diffs(d)

    def patch(self, d):
        return self.result().patch(d)

    def to_patches(self):
        return self.result().to_patches()


def apply_general_block_async(store, block, options=None):
    """Apply on the store's applier thread: the caller overlaps patch
    EXTRACTION of earlier applies (diff materialization is the
    remaining host cost once staging went native) with the staging +
    dispatch of this block — the chip never idles behind a host that is
    busy reading patches.

    Returns an :class:`AsyncGeneralPatch`. Successive async applies are
    serialized by the applier queue; a failed apply rolls the store
    back (same contract as the sync path) and surfaces its error on the
    future. Synchronous `apply_general_block` calls interleave safely
    (the host lock serializes store mutation) but their ordering
    relative to queued async applies is the queue's; drain first
    (:func:`drain_general`) when order matters. Whole-store readers
    (materialize, snapshots) should also drain first."""
    import queue
    out = AsyncGeneralPatch()
    with store._host_lock:       # two first-callers must not both init
        if getattr(store, '_applier', None) is None:
            jobs = store._jobs = queue.Queue()

            def run(jobs=jobs):
                # closes over the QUEUE, not the store: a dropped store
                # is collectable even while its idle applier lingers
                while True:
                    j = jobs.get()
                    if j is None:
                        return
                    j()

            store._applier = threading.Thread(target=run, daemon=True)
            store._applier.start()

    def job():
        try:
            out._patch = apply_general_block(store, block, options)
        except BaseException as e:     # surfaced on result()
            out._error = e
        finally:
            out._event.set()

    with store._host_lock:
        if getattr(store, '_jobs', None) is None:
            # a concurrent close_general stopped the applier between
            # our init check and this put: restart it
            return apply_general_block_async(store, block, options)
        store._jobs.put(job)
        store._last_async = out
    return out


def drain_general(store):
    """Wait for every queued async apply (queue order; waiting on the
    last suffices). Does NOT raise for failed applies — each failure
    belongs to its own future, and the store rolled back past it.
    Safe to call from several threads: everyone waits; the record
    clears only after the wait completes."""
    p = getattr(store, '_last_async', None)
    if p is not None:
        p._event.wait()
        if getattr(store, '_last_async', None) is p:
            store._last_async = None


def close_general(store):
    """Drain and stop the store's applier thread. The store remains
    fully usable synchronously; a later async apply restarts it."""
    drain_general(store)
    with store._host_lock:
        applier = getattr(store, '_applier', None)
        if applier is None:
            return
        store._jobs.put(None)
        store._applier = None
        store._jobs = None
    applier.join()


def _apply_general(store, block, options, return_timing, txn=None):
    import time
    opts = _engine.as_options(options)
    if not block.is_general():
        block = _upgrade_to_general(block)
    t0 = time.perf_counter()
    pool = store.pool
    st = _admit_and_stage(store, block)
    block = st.block
    keep, oc = st.keep, st.oc
    t1 = time.perf_counter()

    patch = GeneralPatch(store)
    if len(oc) == 0:
        _finish_empty(patch)
        return (patch, {'admit': t1 - t0}) if return_timing else patch

    # ---- admitted op columns (no copies when every row is kept —
    # the common fully-admitted block saves 5 full-column passes) ----
    o_act = st.o_action
    o_doc = st.o_doc
    if keep.all():
        o_obj_blk = block.obj
        o_kind = block.key_kind
        o_key_raw = block.key
        o_key_elem = block.key_elem
        o_elem = block.elem
    else:
        o_obj_blk = block.obj[keep]
        o_kind = block.key_kind[keep]
        o_key_raw = block.key[keep]
        o_key_elem = block.key_elem[keep]
        o_elem = block.elem[keep]

    # pre-apply per-object tree geometry: the incremental-index
    # eligibility gate compares this apply's delta against what
    # existed BEFORE any node minting (create_heads/append_batch
    # mutate n_of/max_elem_of in place). The enclosing _Txn already
    # took these exact copies for rollback — alias them (read-only
    # here) instead of copying O(n_objects) again per apply
    if txn is not None:
        nof_pre, mel_pre = txn.pool_n[0], txn.pool_n[1]
        lin_pre = txn.pool_n[6]
    else:
        nof_pre = pool.n_of.copy()
        mel_pre = pool.max_elem_of.copy()
        lin_pre = pool.idx_linear.copy()

    # ---- object creation, whole batch (make ops + missing roots) ----
    make_rows = np.flatnonzero(o_act >= _MAKE_MAP)
    if len(make_rows):
        objs_list = block.objs
        mk_uuid = [objs_list[i] for i in o_obj_blk[make_rows].tolist()]
        mk_doc = o_doc[make_rows].tolist()
        mk_type = [_MAKE_TYPE[a] for a in o_act[make_rows].tolist()]
        base = len(store.obj_uuid)
        if base + len(make_rows) > (1 << 22):
            raise ValueError('object table exceeds the 4M key space')
        new_seq_rows = []
        row = base
        for u, d, t in zip(mk_uuid, mk_doc, mk_type):
            ok = (d, u)
            if ok in store.obj_of:
                raise ValueError('Duplicate creation of object ' + u)
            store.obj_of[ok] = row
            store.obj_uuid.append(u)
            store.obj_doc.append(d)
            store.obj_type.append(t)
            if u == ROOT_ID:
                store._root_row[d] = row
            if t != _TYPE_MAP:
                new_seq_rows.append(row)
            patch.creates.append((d, u, _TYPE_NAME[t], None))
            row += 1
        pool.grow_objects(row)
        pool.create_heads(np.asarray(new_seq_rows, np.int64))

    root_ops = o_obj_blk == 0
    if root_ops.any():
        docs = np.unique(o_doc[root_ops]).astype(np.int64)
        missing = docs[store._root_row[docs] < 0]
        if len(missing):
            base = len(store.obj_uuid)
            if base + len(missing) > (1 << 22):
                raise ValueError('object table exceeds the 4M key space')
            for i, d in enumerate(missing.tolist()):
                store.obj_of[(d, ROOT_ID)] = base + i
                store.obj_uuid.append(ROOT_ID)
                store.obj_doc.append(d)
                store.obj_type.append(_TYPE_MAP)
            store._root_row[missing] = base + np.arange(len(missing))
            pool.grow_objects(len(store.obj_uuid))

    # block obj table -> store rows. Non-root uuids are globally unique,
    # so the block obj index determines the row; ROOT is per document.
    # First-use doc per table entry comes from one reversed scatter
    # (last write wins = first occurrence) instead of a million-row
    # np.unique sort.
    first_doc = np.full(len(block.objs), -1, np.int64)
    if len(o_obj_blk):
        first_doc[o_obj_blk[::-1]] = o_doc[::-1]
    omap = np.full(len(block.objs), -1, np.int64)
    get_row = store.obj_of.get
    objs_list = block.objs
    for bo in range(1, len(objs_list)):
        if first_doc[bo] < 0:
            continue                     # unreferenced table entry
        r = get_row((int(first_doc[bo]), objs_list[bo]))
        if r is None:
            raise ValueError('Modification of unknown object '
                             + objs_list[bo])
        omap[bo] = r
    obj_doc_arr, obj_type_arr = store.obj_arrays()

    # ---- op partition; make-only batches finish here ----
    ins_rows = np.flatnonzero(o_act == _INS)
    a_rows = np.flatnonzero((o_act == _SET) | (o_act == _DEL)
                            | (o_act == _LINK))
    if len(a_rows) == 0 and not len(ins_rows):
        # make-only batch: object creation still counts as a touch
        # (conservative — a created-but-unlinked object is invisible,
        # but the root creation rides the same path)
        _finish_empty(patch)
        store._bump_doc_versions(np.unique(o_doc))
        return (patch, {'admit': t1 - t0}) if return_timing else patch

    la = st.la
    # per-CHANGE local actor slots (C << n ops); the native stager and
    # the clock-exception builder both gather from this
    chg_local = la.local_of(block.doc, st.b_actor) \
        if block.n_changes else np.zeros(0, np.int32)

    # ---- op resolution: the native stager computes the ins grouping,
    # node minting, elemId resolution (peepholes + duplicate check),
    # packed field keys and the STABLE field sort in one C++ pass for
    # fully-admitted blocks; `_resolve_ops_numpy` is the byte-identical
    # fallback (no native library, queued/dropped changes at admission,
    # late-bound string elemIds) ----
    ns = None
    if _NATIVE_STAGING is not False and st.keep.all() and block.n_ops:
        from .. import native as _amnative
        use_ec = (_STAGE_CACHE is not False and _blocks._delta_host_on()
                  and pool._elem_cache)
        ns = _amnative.stage_general_block(
            block, chg_local, st.a_tab, st.k_tab, omap,
            store._root_row, obj_doc_arr, obj_type_arr, pool,
            st.b_actor,
            pool.mirror['n'] if pool.mirror is not None else 0,
            obj_uuid=store.obj_uuid,
            elem_cache=pool._elem_cache if use_ec else None)
    if _NATIVE_STAGING is True and ns is None:
        raise RuntimeError('native staging required but unavailable')
    if ns is not None:
        a_rows = ns.a_rows
        f_new = ns.o_field
        a_node = ns.a_node
        a_objr = ns.a_objrow
        dirty = ns.dirty
        ins_objs = ns.dirty[ns.new_cnt > 0]
        if ns.n_ins:
            pool.append_batch(ns.g_obj, ns.g_local, ns.g_parent,
                              ns.g_actor, ns.g_elem)
    else:
        f_new, a_node, a_objr, dirty, ins_objs = _resolve_ops_numpy(
            store, block, st, omap, root_ops, obj_doc_arr,
            obj_type_arr, o_act, o_doc, o_obj_blk, o_kind, o_key_raw,
            o_key_elem, o_elem, ins_rows, a_rows)

    # ---- deferred-commit point: everything ABOVE here is independent
    # of the entry columns, so it ran while the PREVIOUS apply's device
    # program was still in flight; now fold that apply in (the wait, if
    # any, is the PREVIOUS device program still running — metered
    # separately from this block's staging time)
    tc0 = time.perf_counter()
    store._commit_pending()
    tc1 = time.perf_counter()

    # ---- touched fields + prior entries ----
    # one stable int64 field sort serves BOTH the unique-field
    # derivation and the field-sorted row order; the native stager
    # already ran it (radix) — numpy recomputes it otherwise
    if ns is not None:
        touched_fields = ns.touched
        seg_new = ns.seg_new
        order_new = ns.order
        r_seg_new = ns.r_seg
    else:
        order_new = np.argsort(f_new, kind='stable')
        f_sorted = f_new[order_new]
        n_new0 = len(f_sorted)
        bnd_new = np.empty(n_new0, bool)
        if n_new0:
            bnd_new[0] = True
            bnd_new[1:] = f_sorted[1:] != f_sorted[:-1]
        touched_fields = f_sorted[bnd_new]
        seg_sorted_new = np.cumsum(bnd_new) - 1
        seg_new = np.empty(n_new0, np.int64)
        seg_new[order_new] = seg_sorted_new
        r_seg_new = seg_sorted_new.astype(np.int32)
    # prior-entry match. Fast path: the store's sorted field index
    # (maintained across commits) answers "which entries hold a
    # touched field" in O(touched log E); the legacy path re-packs
    # every entry's field key and scans O(E) per tick. Both produce
    # prior_rows ASCENDING and seg_prior aligned — byte-identical
    # downstream row ordering.
    srt = store._e_sorted
    if srt is not None and srt[0] is not store.e_obj:
        srt = None
        store._e_sorted = None
    srt_drop_pos = None
    if srt is not None and _blocks._delta_host_on():
        vals_s, rows_s = srt[1], srt[2]
        if len(touched_fields):
            lo_s = np.searchsorted(vals_s, touched_fields, 'left')
            cnt_s = np.searchsorted(vals_s, touched_fields,
                                    'right') - lo_s
            srt_drop_pos = _span_indices(lo_s, cnt_s)
            pru = rows_s[srt_drop_pos]
            sgu = np.repeat(np.arange(len(touched_fields),
                                      dtype=np.int64), cnt_s)
            ordp2 = np.argsort(pru, kind='stable')
            prior_rows = pru[ordp2]
            seg_prior = sgu[ordp2]
        else:
            srt_drop_pos = np.zeros(0, np.int64)
            prior_rows = np.zeros(0, np.int64)
            seg_prior = np.zeros(0, np.int64)
    else:
        # packed (obj << 32 | key) per store entry, cached per
        # entry-table identity (columns are replaced at commit)
        cache = getattr(store, '_e_field_cache', None)
        if cache is not None and cache[0] is store.e_obj:
            e_field = cache[1]
        else:
            e_field = (store.e_obj.astype(np.int64) << 32) | \
                store.e_key
            store._e_field_cache = (store.e_obj, e_field)
        if len(e_field):
            pos = np.minimum(np.searchsorted(touched_fields, e_field),
                             max(len(touched_fields) - 1, 0))
            prior_mask = (touched_fields[pos] == e_field) \
                if len(touched_fields) else \
                np.zeros(len(e_field), bool)
            prior_rows = np.flatnonzero(prior_mask)
            seg_prior = pos[prior_rows]
        else:
            prior_rows = np.zeros(0, np.int64)
            seg_prior = np.zeros(0, np.int64)
    F = len(touched_fields)
    S = opts.pad_segments(max(F, 1))

    n_new, n_prior = len(a_rows), len(prior_rows)
    n_rows = n_new + n_prior
    n_pad = opts.pad_ops(max(n_rows, 8))    # >= 8: masks ride bit-packed
    A = opts.pad_actors(max(la.width, 1))

    # canonical row order: FIELD-SORTED (segment-grouped) — the seg ids
    # then ship as one boundary BIT per row, and every r_* column below
    # (and the kernel's winner row ids) lives in these coordinates.
    # With no prior rows the field sort IS the order.
    p_doc = store.e_doc[prior_rows]
    if n_prior:
        seg_cat = np.concatenate([seg_new, seg_prior]).astype(np.int32)
        order = np.argsort(seg_cat, kind='stable')
        r_seg = seg_cat[order]
    else:
        order = order_new
        r_seg = r_seg_new
    inv_order = np.empty(n_rows, np.int64)
    inv_order[order] = np.arange(n_rows)
    prior_local = la.local_of(p_doc, store.e_actor[prior_rows]) \
        if n_prior else np.zeros(0, np.int32)

    # staged row columns: when the native stager wrote the wire buffer
    # (no prior rows, packed program) these never materialize on host —
    # build the numpy forms only for the fallback plane paths
    native_rows = ns is not None and n_prior == 0
    if native_rows:
        local_cat = seq_cat_store = isdel_cat = None
        max_seq = ns.max_seq if n_rows else 0
    else:
        local_cat = np.concatenate([chg_local[oc[a_rows]],
                                    prior_local]) \
            if n_prior else chg_local[oc[a_rows]]
        seq_cat_store = np.concatenate(
            [st.o_seq[a_rows], store.e_seq[prior_rows]]) if n_prior \
            else st.o_seq[a_rows]
        isdel_cat = np.concatenate(
            [o_act[a_rows] == _DEL, np.zeros(n_prior, bool)]) \
            if n_prior else (o_act[a_rows] == _DEL)
        max_seq = int(seq_cat_store.max()) if n_rows else 0

    # narrowest dtypes that fit (each distinct signature compiles once)
    a_dtype = np.uint8 if A <= 256 else np.int32
    s_dtype = np.int16 if max_seq < (1 << 15) else np.int32

    # clock exceptions as COO: clock[i, actor_i] = seq_i - 1 always (the
    # fold's final SET), so only cross-actor closure entries ship
    coo = []
    R = st.R
    if R.any():
        rows_clock = R[oc[a_rows]]
        nz_r, nz_c = np.nonzero(rows_clock)
        new_local = chg_local[oc[a_rows]]
        own = nz_c == new_local[nz_r]
        coo.append((inv_order[nz_r[~own]], nz_c[~own],
                    rows_clock[nz_r[~own], nz_c[~own]]))
    if n_prior:
        e_log = store.e_change[prior_rows]
        prior_counts = (store.l_dep_ptr[e_log + 1]
                        - store.l_dep_ptr[e_log])
        if prior_counts.sum():
            idx = _span_indices(store.l_dep_ptr[e_log], prior_counts)
            rows_rep = np.repeat(
                np.arange(n_new, n_rows, dtype=np.int64), prior_counts)
            doc_rep = np.repeat(p_doc, prior_counts)
            cols = la.local_of(doc_rep, store.l_dep_actor[idx])
            vals = store.l_dep_seq[idx]
            own = cols == prior_local[rows_rep - n_new]
            # the own-column closure of a PRIOR entry is its seq-1 by
            # the same invariant, so dropping own rows stays exact
            coo.append((inv_order[rows_rep[~own]], cols[~own],
                        vals[~own]))
    if coo:
        coo_row = np.concatenate([c[0] for c in coo]).astype(np.int32)
        coo_col_v = np.concatenate([c[1] for c in coo])
        coo_val_v = np.concatenate([c[2] for c in coo])
    else:
        coo_row = np.zeros(0, np.int32)
        coo_col_v = coo_val_v = np.zeros(0, np.int32)
    c_dtype = np.int16 if (len(coo_val_v) == 0
                           or int(coo_val_v.max()) < (1 << 15)) \
        else np.int32
    nnz_pad = opts.pad_ops(max(len(coo_row), 1))
    coo_col = np.zeros(nnz_pad, a_dtype)
    coo_col[:len(coo_col_v)] = coo_col_v
    coo_val = np.zeros(nnz_pad, c_dtype)
    coo_val[:len(coo_val_v)] = coo_val_v
    coo_row = np.concatenate(
        [coo_row, np.full(nnz_pad - len(coo_row), n_pad, np.int32)])

    # ---- device-resident trees: ship only this apply's NEW nodes ----
    # the job axis is BUCKETED like every other padded axis: a serving
    # fleet's dirty-set size drifts tick to tick, and an unpadded K
    # minted a fresh jit signature (a retrace) at every new count —
    # the job table pads with job_n = 0 rows, which every plane op
    # masks out
    K = opts._pad(None, max(len(dirty), 1), 'job_pad')
    if ns is not None:
        n_j = ns.n_j
    else:
        n_j = pool.n_of[dirty] if len(dirty) else np.zeros(0, np.int64)
    m_pad = opts.pad_nodes(int(max(n_j.max() if len(n_j) else 1, 8)))
    n_total = pool.n_nodes
    n_act = len(store.actors)

    # variant pick: the 2-word packed program wherever its bit-field
    # guards hold, the 3-word WIDE packed program for everything up to
    # 2^22-node trees / int32 elemc+seq, and `_fused_general_resident`
    # (cols) as the last fallback (>4M-node trees, wide actor sets).
    # All three share the staging idioms (_insert_counts/_build_clock/
    # _vis_grid and the scan resolve) — the cross-check for those is
    # the host oracle and the sharded-step equality gates, while the
    # cols fallback remains the independent check of the packed mirror
    # FORMATS (bit fields, wire layout, dtype narrowing). A mirror
    # already on 'wide' stays there even when the 2-word guards pass
    # again (a seq-width oscillation must not convert per block); the
    # tree/elemc bounds are monotone, so packed-eligibility never
    # genuinely returns once crossed.
    mir = pool.mirror
    cur_fmt = mir.get('fmt', 'cols') if mir is not None else None
    if (_packed_mirror_guard(pool, n_act, A)
            and s_dtype is np.int16 and c_dtype is np.int16
            and cur_fmt != 'wide'):
        fmt = 'packed'
    elif _wide_mirror_guard(pool, n_act, A):
        fmt = 'wide'
    else:
        fmt = 'cols'
    if mir is not None and cur_fmt != fmt:
        mir = pool.mirror = _mirror_convert(mir, fmt, store, opts)
        if fmt == 'cols' and pool.idx_ok.any():
            # converting down to cols drops the 'tp' plane
            pool.idx_ok[:] = False
            metrics.bump('device_idx_invalidations')
    use_packed = fmt == 'packed'
    incr = None                  # set by the packed/wide dispatches

    if mir is None:
        # first resident apply: EVERY node is this apply's delta — the
        # mirror materializes on device with zero extra wire bytes
        cap = opts.pad_nodes(max(n_total, 8))
        n_old = 0
    elif mir['cap'] < n_total:
        # capacity growth ON DEVICE (2x headroom so block-sized growth
        # amortizes): pad each resident column; nothing ships
        cap = opts.pad_nodes(max(2 * mir['cap'], n_total))
        n_old = mir['n']
    else:
        cap = mir['cap']
        n_old = mir['n']

    d_n = n_total - n_old
    d_pad = opts.pad_nodes(max(d_n, 8))
    native_wire = native_rows and fmt != 'cols'

    if not native_wire:
        # host-built planes: d columns + job table + row slots + the
        # staged row arrays (the native stager still provides the d
        # planes and job table when it ran — exact for any admission)
        if ns is not None:
            d_parent = np.zeros(d_pad, np.int32)
            d_elemc = np.zeros(d_pad, np.int32)
            d_actor = np.zeros(d_pad, np.int32)
            d_pos = np.full(d_pad, cap, np.int32)
            job_start = np.zeros(K, np.int32)
            n_j_arr = np.zeros(K, np.int32)
            ns.fill_dplanes(d_parent, d_elemc, d_actor, d_pos,
                            job_start, n_j_arr)
        else:
            new_glob = np.arange(n_old, n_total, dtype=np.int64)
            keys = (pool.obj[new_glob].astype(np.int64) << 32) | \
                pool.local[new_glob]
            final_pos = np.searchsorted(pool.pos_sorted, keys)
            if d_n > 1 and not (final_pos[1:] >= final_pos[:-1]).all():
                ordp = np.argsort(final_pos, kind='stable')
                final_pos = final_pos[ordp]
            else:
                ordp = None     # appends landed in pos order (common)

            def dcol(col):
                out = np.zeros(d_pad, np.int32)
                new = col[new_glob]
                out[:d_n] = new if ordp is None else new[ordp]
                return out

            d_parent = dcol(pool.parent)
            d_elemc = dcol(pool.elemc)
            d_actor = dcol(pool.actor)
            d_pos = np.full(d_pad, cap, np.int32)
            d_pos[:d_n] = final_pos - np.arange(d_n)

            # job table: each dirty object's contiguous pos slice
            # (bucket-padded rows keep job_n = 0 and mask out)
            job_start = np.zeros(K, np.int32)
            n_j_arr = np.zeros(K, np.int32)
            if len(dirty):
                job_start[:len(dirty)] = np.searchsorted(
                    pool.pos_sorted, dirty << np.int64(32))
                n_j_arr[:len(dirty)] = n_j

        # per-row (job, node) slots, in the field-sorted coordinates
        row_slot = np.full(n_pad, -1, np.int32)
        if len(dirty):
            slot_cat = np.full(n_rows, -1, np.int64)
            dirty_lookup = np.full(len(store.obj_uuid), -1, np.int64)
            dirty_lookup[dirty] = np.arange(len(dirty))
            if n_new:
                loc = dirty_lookup[a_objr]
                nd = a_node
                slot_cat[:n_new] = np.where((loc >= 0) & (nd >= 0),
                                            loc * m_pad + nd, -1)
            if n_prior:
                p_loc = dirty_lookup[store.e_obj[prior_rows]]
                p_elem_key = store.e_key[prior_rows]
                p_node = np.where(p_elem_key & _ELEM_BIT,
                                  p_elem_key & 0x7FFFFFFF, -1)
                slot_cat[n_new:n_rows] = np.where(
                    (p_loc >= 0) & (p_node >= 0),
                    p_loc * m_pad + p_node, -1)
            row_slot[:n_rows] = slot_cat[order]

        if local_cat is None:    # native rows but the cols program
            local_cat = chg_local[oc[a_rows]]
            seq_cat_store = st.o_seq[a_rows]
            isdel_cat = o_act[a_rows] == _DEL
        actor_arr = np.zeros(n_pad, a_dtype)
        actor_arr[:n_rows] = local_cat[order]
        seq_arr = np.zeros(n_pad, s_dtype)
        seq_arr[:n_rows] = seq_cat_store[order]
        boundary = np.zeros(n_pad, bool)
        if n_rows:
            boundary[0] = True
            boundary[1:n_rows] = r_seg[1:] != r_seg[:-1]
        del_arr = np.zeros(n_pad, bool)
        del_arr[:n_rows] = isdel_cat[order]
        flags_u8 = np.concatenate([np.packbits(boundary),
                                   np.packbits(del_arr)])
    t2 = time.perf_counter()

    # suffix-window state: set by the incr dispatch branches when the
    # renumber was bounded to per-job suffix windows (m_eff < m_pad)
    m_eff = m_pad
    win_ws = None
    win_nj = None

    if use_packed:
        ranks = np.asarray(store.actor_str_ranks())
        if mir is None:
            w1m = jnp.zeros(cap, jnp.int32)
            w2m = jnp.zeros(cap, jnp.int32)
            remap_dev, has_remap = _NO_REMAP, False
        else:
            if mir['cap'] < n_total:
                pad = cap - mir['cap']
                w1m = jnp.concatenate(
                    [mir['w1'], jnp.zeros(pad, jnp.int32)])
                w2m = jnp.concatenate(
                    [mir['w2'], jnp.zeros(pad, jnp.int32)])
            else:
                w1m, w2m = mir['w1'], mir['w2']
            old_ranks = mir['ranks']
            if np.array_equal(old_ranks, ranks[:len(old_ranks)]):
                remap_dev, has_remap = _NO_REMAP, False
            else:
                # existing actors shifted rank (new actors landed in
                # the sorted order): remap the mirror's rank field
                rm = np.zeros(opts.pad_actors(len(old_ranks) + 2),
                              np.int32)
                rm[old_ranks + 1] = \
                    ranks[:len(old_ranks)].astype(np.int32) + 1
                remap_dev, has_remap = jnp.asarray(rm), True

        sizes = (d_pad, n_pad, K, nnz_pad)
        wire = np.empty(_wire_sizes(*sizes), np.uint8)
        i32_n = 2 * d_pad + n_pad + nnz_pad + 2 * K
        i16_n = d_pad + n_pad + nnz_pad
        if native_wire:
            # C++ writes every section except the three admission-clock
            # COO sections, which only the admission layer knows
            ns.fill_wire(wire, cap, d_pad, n_pad, K, nnz_pad, m_pad,
                         ranks)
            o = 4 * (2 * d_pad + n_pad)
            wire[o:o + 4 * nnz_pad].view(np.int32)[:] = coo_row
            o = 4 * i32_n + 2 * (d_pad + n_pad)
            wire[o:o + 2 * nnz_pad].view(np.int16)[:] = coo_val
            o = 4 * i32_n + 2 * i16_n + n_pad + 2 * (n_pad >> 3)
            wire[o:o + nnz_pad] = coo_col.view(np.uint8)
        else:
            rank1_new = np.where(
                d_actor >= 0, ranks[np.maximum(d_actor, 0)] + 1, 0) \
                .astype(np.int32)
            w1_new = (d_parent << 16) | rank1_new
            o = 0
            for arr, width in ((w1_new, 4), (d_pos, 4), (row_slot, 4),
                               (coo_row, 4), (job_start, 4),
                               (n_j_arr, 4)):
                nb_ = width * len(arr)
                wire[o:o + nb_].view(np.int32)[:] = arr
                o += nb_
            for arr in (d_elemc, seq_arr, coo_val):
                nb_ = 2 * len(arr)
                wire[o:o + nb_].view(np.int16)[:] = arr
                o += nb_
            for arr in (actor_arr, flags_u8, coo_col):
                wire[o:o + len(arr)] = arr.view(np.uint8)
                o += len(arr)
            assert o == len(wire)

        tpm = _mirror_tp_in(mir, cap, n_total)
        incr = _pick_incremental(
            pool, mir, dirty, n_j, nof_pre, mel_pre, n_old, n_total,
            m_pad, opts,
            parent_d=(wire[:4 * d_pad].view(np.int32) >> 16)
            if native_wire else d_parent,
            elemc_d=wire[4 * i32_n:4 * i32_n + 2 * d_pad]
            .view(np.int16) if native_wire else d_elemc)
        if incr is not None:
            dm_pad, jd_base, min_rp = incr
            ob = 4 * 2 * d_pad
            rs_v = wire[ob:ob + 4 * n_pad].view(np.int32)
            ob = 4 * (2 * d_pad + n_pad + nnz_pad)
            js_v = wire[ob:ob + 4 * K].view(np.int32)
            jn_v = wire[ob + 4 * K:ob + 8 * K].view(np.int32)
            win = None
            if _WINDOW_MODE != 'off' and _blocks._delta_host_on():
                win = _apply_window(lin_pre, dirty, n_j, jd_base,
                                    min_rp, rs_v, js_v, jn_v, m_pad,
                                    n_rows, K, opts)
            if win is not None:
                m_eff, ws_k, jd_base, win_nj = win
                win_ws = ws_k[:len(dirty)].copy()
                metrics.bump('device_idx_window_applies')
                if not native_wire:
                    # the rewrite went through the wire views; keep the
                    # numpy staging arrays (capture parity) in step
                    row_slot[:] = rs_v
                    job_start[:] = js_v
                    n_j_arr[:] = jn_v
            else:
                if _WINDOW_MODE == 'require' and len(dirty):
                    raise RuntimeError(
                        "suffix-window path required (_WINDOW_MODE="
                        "'require') but this apply cannot window")
                ws_k = np.zeros(K, np.int32)
            jd = np.zeros(K, np.int32)
            jd[:len(dirty)] = jd_base
            _profiler.note_dispatch(
                'general.fused_incr',
                ('packed', cap, sizes, S, A, m_eff, dm_pad, has_remap,
                 int(remap_dev.shape[0])),
                rows=n_pad)
            # numpy operands go straight to the jit C++ fast path — an
            # explicit jnp.asarray per operand costs a Python-level
            # device_put (~0.25 ms each on CPU), ~1 ms/tick of pure
            # dispatch overhead for these tiny arrays
            outs = _fused_general_incr(
                w1m, w2m, _NO_W3, tpm, wire,
                jd, ws_k, np.int32(n_old),
                np.int32(n_rows), remap_dev,
                fmt='packed', sizes=sizes, num_segments=S, a_pad=A,
                m_pad=m_eff, dm_pad=dm_pad, has_remap=has_remap)
            w1o, w2o, tpo = outs[0], outs[1], outs[3]
            surv_u8_dev, winner_dev = outs[4], outs[5]
            vis_planes = outs[6] if len(dirty) else None
        else:
            # shape-signature registry: every distinct signature here
            # is one XLA compile of the packed program (retraces
            # counted, flight-recorded — device/profiler.py)
            _profiler.note_dispatch(
                'general.fused_packed',
                (cap, sizes, S, A, m_pad, has_remap,
                 int(remap_dev.shape[0]), n_old > 0),
                rows=n_pad)
            outs = _fused_general_packed(
                w1m, w2m, tpm, wire, np.int32(n_old),
                np.int32(n_rows), remap_dev,
                sizes=sizes, num_segments=S, a_pad=A, m_pad=m_pad,
                has_remap=has_remap, has_old=n_old > 0)
            w1o, w2o, tpo = outs[0], outs[1], outs[2]
            surv_u8_dev, winner_dev = outs[3], outs[4]
            vis_planes = outs[5] if len(dirty) else None
            if len(dirty):
                # the rebuild just (re)wrote these objects' index
                pool.idx_ok[dirty] = True
        pool.mirror = {
            'fmt': 'packed', 'cap': cap, 'n': n_total,
            'w1': w1o, 'w2': w2o, 'tp': tpo, 'ranks': ranks.copy(),
            'pos_row': pool.pos_row,  # replaced-on-append: stable ref
        }
        vis_fmt = 'packed'
    elif fmt == 'wide':
        if mir is None:
            w1m = jnp.zeros(cap, jnp.int32)
            w2m = jnp.zeros(cap, jnp.int32)
            w3m = jnp.zeros(cap, jnp.int32)
        elif mir['cap'] < n_total:
            pad = cap - mir['cap']

            def grow_w(col):
                return jnp.concatenate([col, jnp.zeros(pad, jnp.int32)])

            w1m, w2m, w3m = (grow_w(mir['w1']), grow_w(mir['w2']),
                             grow_w(mir['w3']))
        else:
            w1m, w2m, w3m = mir['w1'], mir['w2'], mir['w3']
        # actor -> string-rank table, re-shipped only when it grew (the
        # wide words carry stable actor ids, never ranks)
        if mir is None or mir.get('rank_n') != n_act:
            rank_table_dev = _rank_table(store, opts)
        else:
            rank_table_dev = mir['rank_table']

        sizes = (d_pad, n_pad, K, nnz_pad)
        wire = np.empty(_wire_sizes_wide(*sizes), np.uint8)
        i32_n = 3 * d_pad + 2 * n_pad + 2 * nnz_pad + 2 * K
        if native_wire:
            # C++ writes every section except the three admission-clock
            # COO sections, which only the admission layer knows
            ns.fill_wire_wide(wire, cap, d_pad, n_pad, K, nnz_pad,
                              m_pad)
            o = 4 * (3 * d_pad + 2 * n_pad)
            wire[o:o + 4 * nnz_pad].view(np.int32)[:] = coo_row
            o += 4 * nnz_pad
            wire[o:o + 4 * nnz_pad].view(np.int32)[:] = coo_val
            o = 4 * i32_n + d_pad + n_pad + 2 * (n_pad >> 3)
            wire[o:o + nnz_pad] = coo_col.view(np.uint8)
        else:
            actor1_new = d_actor + 1          # head (-1) -> 0
            w1_new = (d_parent << _WIDE_PARENT_SHIFT) | \
                (actor1_new & _WIDE_ALO_MASK)
            seq32 = seq_arr.astype(np.int32)
            coo_val32 = coo_val.astype(np.int32)
            o = 0
            for arr in (w1_new, d_elemc, d_pos, row_slot, seq32,
                        coo_row, coo_val32, job_start, n_j_arr):
                nb_ = 4 * len(arr)
                wire[o:o + nb_].view(np.int32)[:] = arr
                o += nb_
            for arr in ((actor1_new >> 10).astype(np.uint8), actor_arr,
                        flags_u8, coo_col):
                wire[o:o + len(arr)] = arr.view(np.uint8)
                o += len(arr)
            assert o == len(wire)

        tpm = _mirror_tp_in(mir, cap, n_total)
        incr = _pick_incremental(
            pool, mir, dirty, n_j, nof_pre, mel_pre, n_old, n_total,
            m_pad, opts,
            parent_d=((wire[:4 * d_pad].view(np.int32)
                       >> _WIDE_PARENT_SHIFT) & _WIDE_IDX_MASK)
            if native_wire else d_parent,
            elemc_d=wire[4 * d_pad:8 * d_pad].view(np.int32)
            if native_wire else d_elemc)
        if incr is not None:
            dm_pad, jd_base, min_rp = incr
            ob = 4 * 3 * d_pad
            rs_v = wire[ob:ob + 4 * n_pad].view(np.int32)
            ob = 4 * (3 * d_pad + 2 * n_pad + 2 * nnz_pad)
            js_v = wire[ob:ob + 4 * K].view(np.int32)
            jn_v = wire[ob + 4 * K:ob + 8 * K].view(np.int32)
            win = None
            if _WINDOW_MODE != 'off' and _blocks._delta_host_on():
                win = _apply_window(lin_pre, dirty, n_j, jd_base,
                                    min_rp, rs_v, js_v, jn_v, m_pad,
                                    n_rows, K, opts)
            if win is not None:
                m_eff, ws_k, jd_base, win_nj = win
                win_ws = ws_k[:len(dirty)].copy()
                metrics.bump('device_idx_window_applies')
                if not native_wire:
                    row_slot[:] = rs_v
                    job_start[:] = js_v
                    n_j_arr[:] = jn_v
            else:
                if _WINDOW_MODE == 'require' and len(dirty):
                    raise RuntimeError(
                        "suffix-window path required (_WINDOW_MODE="
                        "'require') but this apply cannot window")
                ws_k = np.zeros(K, np.int32)
            jd = np.zeros(K, np.int32)
            jd[:len(dirty)] = jd_base
            _profiler.note_dispatch(
                'general.fused_incr',
                ('wide', cap, sizes, S, A, m_eff, dm_pad,
                 int(rank_table_dev.shape[0])),
                rows=n_pad)
            outs = _fused_general_incr(
                w1m, w2m, w3m, tpm, wire,
                jd, ws_k, np.int32(n_old),
                np.int32(n_rows), rank_table_dev,
                fmt='wide', sizes=sizes, num_segments=S, a_pad=A,
                m_pad=m_eff, dm_pad=dm_pad, has_remap=False)
            w1o, w2o, w3o, tpo = outs[0], outs[1], outs[2], outs[3]
            surv_u8_dev, winner_dev = outs[4], outs[5]
            vis_planes = (outs[6], outs[7]) if len(dirty) else None
        else:
            _profiler.note_dispatch(
                'general.fused_wide',
                (cap, sizes, S, A, m_pad, int(rank_table_dev.shape[0]),
                 n_old > 0),
                rows=n_pad)
            outs = _fused_general_wide(
                w1m, w2m, w3m, tpm, wire, np.int32(n_old),
                np.int32(n_rows), rank_table_dev,
                sizes=sizes, num_segments=S, a_pad=A, m_pad=m_pad,
                has_old=n_old > 0)
            w1o, w2o, w3o, tpo = outs[0], outs[1], outs[2], outs[3]
            surv_u8_dev, winner_dev = outs[4], outs[5]
            vis_planes = (outs[6], outs[7]) if len(dirty) else None
            if len(dirty):
                pool.idx_ok[dirty] = True
        pool.mirror = {
            'fmt': 'wide', 'cap': cap, 'n': n_total,
            'w1': w1o, 'w2': w2o, 'w3': w3o, 'tp': tpo,
            'rank_n': n_act, 'rank_table': rank_table_dev,
            'pos_row': pool.pos_row,  # replaced-on-append: stable ref
        }
        vis_fmt = 'wide'
    else:
        if mir is None:
            m_cols = (jnp.zeros(cap, jnp.int32),
                      jnp.zeros(cap, jnp.int32),
                      jnp.full(cap, -1, jnp.int32),
                      jnp.zeros(cap, bool),
                      jnp.full(cap, -1, jnp.int32))
        elif mir['cap'] < n_total:
            def grow(col, fill):
                return jnp.concatenate(
                    [col, jnp.full(cap - mir['cap'], fill, col.dtype)])

            m_cols = (grow(mir['parent'], 0), grow(mir['elemc'], 0),
                      grow(mir['actor'], -1),
                      grow(mir['visible'], False),
                      grow(mir['vis_index'], -1))
        else:
            m_cols = (mir['parent'], mir['elemc'], mir['actor'],
                      mir['visible'], mir['vis_index'])

        # actor -> string-rank table, re-shipped only when it grew
        if mir is None or mir.get('rank_n') != n_act:
            rank_table_dev = _rank_table(store, opts)
        else:
            rank_table_dev = mir['rank_table']

        _profiler.note_dispatch(
            'general.fused_cols',
            (cap, d_pad, n_pad, K, nnz_pad, S, A, m_pad,
             int(rank_table_dev.shape[0]), seq_arr.dtype.str,
             actor_arr.dtype.str, coo_val.dtype.str),
            rows=n_pad)
        outs = _fused_general_resident(
            *m_cols, d_parent, d_elemc,
            d_actor, d_pos, np.int32(n_old),
            job_start, n_j_arr,
            rank_table_dev,
            actor_arr, seq_arr,
            row_slot, flags_u8,
            np.int32(n_rows), coo_row,
            coo_col, coo_val,
            num_segments=S, a_pad=A, m_pad=m_pad)
        pool.mirror = {
            'fmt': 'cols', 'cap': cap, 'n': n_total,
            'parent': outs[0], 'elemc': outs[1], 'actor': outs[2],
            'visible': outs[3], 'vis_index': outs[4],
            'rank_n': n_act, 'rank_table': rank_table_dev,
            'pos_row': pool.pos_row,  # replaced-on-append: stable ref
        }
        # the cols fallback maintains no 'tp' plane; any index claims
        # drop with it (a cols-scale store always rebuilds)
        if pool.idx_ok.any():
            pool.idx_ok[:] = False
            metrics.bump('device_idx_invalidations')
        if len(dirty):
            metrics.bump('device_idx_rebuild_applies')
        surv_u8_dev, winner_dev = outs[5], outs[6]
        vis_planes = outs[7:11] if len(dirty) else None
        vis_fmt = 'cols'
    pool._epoch += 1
    _update_mirror_gauges(fmt, cap)
    if _STAGE_CAPTURE is not None:
        if native_wire and use_packed:
            # the staged planes live in the wire buffer — expose them
            # through views at the layout offsets
            o_rs = 4 * (2 * d_pad)
            cap_slot = wire[o_rs:o_rs + 4 * n_pad].view(np.int32)
            o_sq = 4 * i32_n + 2 * d_pad
            cap_seq = wire[o_sq:o_sq + 2 * n_pad].view(np.int16)
            o_ac = 4 * i32_n + 2 * i16_n
            cap_actor = wire[o_ac:o_ac + n_pad]
            cap_flags = wire[o_ac + n_pad:
                             o_ac + n_pad + 2 * (n_pad >> 3)]
        elif native_wire:                      # wide wire layout
            o_rs = 4 * (3 * d_pad)
            cap_slot = wire[o_rs:o_rs + 4 * n_pad].view(np.int32)
            cap_seq = wire[o_rs + 4 * n_pad:
                           o_rs + 8 * n_pad].view(np.int32)
            o_ac = 4 * i32_n + d_pad
            cap_actor = wire[o_ac:o_ac + n_pad]
            cap_flags = wire[o_ac + n_pad:
                             o_ac + n_pad + 2 * (n_pad >> 3)]
        else:
            cap_slot = row_slot
            # the wide wire carries seq as int32 — expose the same
            # dtype so the native/numpy parity gate compares like
            cap_seq = seq_arr.astype(np.int32) if fmt == 'wide' \
                else seq_arr
            cap_actor, cap_flags = actor_arr, flags_u8
        _STAGE_CAPTURE({
            'ops_actor': cap_actor, 'ops_seq': cap_seq,
            'ops_slot': cap_slot, 'flags_u8': cap_flags,
            'n_rows': n_rows, 'coo_row': coo_row, 'coo_col': coo_col,
            'coo_val': coo_val, 'num_segments': S, 'a_pad': A,
            'm_pad': m_eff, 'surv_u8': surv_u8_dev,
            'winner': winner_dev, 'vis_fmt': vis_fmt,
            'vis_planes': vis_planes, 'variant': fmt})
    t3 = time.perf_counter()

    # sampled per-phase device-time attribution: every Nth apply
    # fences on the fused program and splits its wall time into the
    # admit/pack/dispatch/device histogram series — one pipeline
    # bubble per sample, amortized by the cadence; off-sample applies
    # paid exactly the integer check above the fence
    if _profiler.should_sample():
        jax.block_until_ready(winner_dev)
        t_dev = (time.perf_counter() - t3) * 1e3
        _profiler.record_phases(
            (t1 - t0) * 1e3, (t2 - t1 - (tc1 - tc0)) * 1e3,
            (t3 - t2) * 1e3, t_dev,
            (time.perf_counter() - t0) * 1e3,
            # the index update is FUSED into the apply program, so its
            # attribution is the fenced run time of the incremental
            # variant (its own series + Perfetto lane; rebuild-path
            # run time stays out, which is what makes the before/after
            # comparable)
            idx_ms=t_dev if incr is not None else None)

    # ---- unpack: lazy patch wiring + DEFERRED entry commit ----
    # `cat` holds the UNPERMUTED row columns plus `order` (the
    # field-sorted permutation matching the kernel's winner row ids);
    # consumers gather lazily — commit fetches only the survivor rows,
    # conflict columns materialize on first diff read. Nothing blocks
    # here: the 33KB survivor fetch and the entry update wait in
    # _pending_commit until the next entry reader (usually the next
    # apply's prior-entry match), so host staging of block n+1 overlaps
    # this block's device program.
    # columns build LAZILY on first access (8 half-million-row gathers
    # + concatenates off the dispatch path — the commit or a diff read
    # pays them, overlapping the device program). The e_* refs snapshot
    # NOW: the store's entry columns are replaced (never mutated) at
    # commit, so the captured arrays stay the pre-commit state.
    e_snap = (store.e_value, store.e_link, store.e_actor,
              store.e_change, store.e_obj, store.e_key)

    def seq_thunk():
        if seq_cat_store is not None:
            return seq_cat_store, None
        return st.o_seq[a_rows], None

    cat = _LazyCat({
        'value': lambda: (st.o_value[a_rows], e_snap[0][prior_rows]),
        'link': lambda: (o_act[a_rows] == _LINK,
                         e_snap[1][prior_rows]),
        'actor': lambda: (st.o_actor[a_rows], e_snap[2][prior_rows]),
        'doc': lambda: (o_doc[a_rows], p_doc),
        'seq': seq_thunk,
        'change': lambda: (st.cmap[oc[a_rows]].astype(np.int32),
                           e_snap[3][prior_rows]),
        'obj': lambda: (a_objr.astype(np.int32),
                        e_snap[4][prior_rows]),
        'key': lambda: (f_new & 0xFFFFFFFF,
                        e_snap[5][prior_rows]),
    }, n_prior)

    f_obj = (touched_fields >> 32).astype(np.int32)
    patch.f_obj = f_obj
    patch.f_doc = obj_doc_arr[f_obj] if len(obj_doc_arr) \
        else np.zeros(0, np.int32)
    patch.f_key = touched_fields & 0xFFFFFFFF
    patch.f_kind = (patch.f_key & _ELEM_BIT) != 0

    # ---- lazy wiring: winner columns, conflicts, sequence edits ----
    pos_snap = (pool.pos_sorted, pool.pos_row)

    def rows_flat_thunk(d=dirty, nj=n_j, ps=pos_snap):
        # the flat node-row gather of every dirty object is paid by the
        # first patch READ, not the apply dispatch; the pos snapshot
        # pins this apply's tree extent (later applies append more)
        if not len(d):
            return np.zeros(0, np.int64)
        lo = np.searchsorted(ps[0], d << np.int64(32))
        return ps[1][_span_indices(lo, nj)]

    patch._raw = {
        'winner_dev': winner_dev, 'surviving': None,   # set at commit
        'cat': cat, 'order': order, 'vis_fmt': vis_fmt,
        'r_seg': r_seg, 's_rows': None, 'vis_planes': vis_planes,
        'dirty': dirty, 'rows_flat': rows_flat_thunk,
        # windowed applies hand the patch read the suffix planes: the
        # per-job window base maps plane column c to absolute node
        # local win_ws[j] + c, and dirty_n shrinks to the window
        # sizes. e_pad = 0 pins the read to the host-unpack branch
        # (the edit-stream program renumbers whole planes).
        'dirty_n': n_j if win_ws is None else win_nj,
        'win_ws': win_ws,
        # edit-stream read geometry: the fused patch-read kernel
        # compacts this tick's edits into [K, e_pad] buffers (edits
        # are bounded by the resolved row count, never the tree size)
        'm_pad': m_eff, 'e_pad': 0 if win_ws is not None else opts._pad(
            None, max(min(m_pad, n_rows), 1), 'edit_pad'),
        'pos_snap': pos_snap,
        # per-object maxElem SNAPSHOT at apply time: a pipelined reader
        # may materialize this patch after apply N+1 has grown the pool,
        # and the reference reports the per-apply maxElem
        # (/root/reference/backend/op_set.js:118-125)
        'gained_max_elem': {int(o): int(pool.max_elem_of[o])
                            for o in ins_objs.tolist()},
    }
    patch._ready = False
    store._pending_commit = {
        'surv_u8_dev': surv_u8_dev, 'n_rows': n_rows,
        'prior_rows': prior_rows, 'n_entries': len(store.e_key),
        'srt_drop_pos': srt_drop_pos,
        'touched_fields': touched_fields,
        'r_seg': r_seg, 'cat': cat, 'order': order, 'patch': patch,
    }
    t4 = time.perf_counter()

    # dirty-doc signal for view caches: every raise point is behind us
    # (the dispatch succeeded, the pending commit is installed), so the
    # bump cannot leak through a rollback
    store._bump_doc_versions(np.unique(o_doc))

    # staging-cache upkeep: each dirty sequence object keeps a sorted
    # elemId -> local index the NEXT tick's stagers (numpy and native)
    # consult in O(delta). Population sits AFTER every raise point, so
    # a rolled-back apply never caches unminted nodes; append_batch
    # already extended resident entries with this tick's nodes.
    if _STAGE_CACHE is not False and _blocks._delta_host_on():
        ec = pool._elem_cache
        for o in dirty.tolist():
            if int(o) in ec:
                metrics.bump('device_stage_cache_hits')
            else:
                metrics.bump('device_stage_cache_misses')
                pool.elem_index(int(o))

    metrics.bump('general_batches')
    metrics.bump('general_ops', int(keep.sum()))
    metrics.bump('general_stage_native_batches' if ns is not None
                 else 'general_stage_numpy_batches')
    # per-variant apply counts: a fleet quietly living on the cols
    # fallback (or stuck converting) shows up in the bench summary
    metrics.bump(f'general_variant_{fmt}_applies')
    metrics.observe('general_stage_ms',
                    (t2 - t1 - (tc1 - tc0)) * 1e3)
    metrics.observe('general_commit_wait_ms', (tc1 - tc0) * 1e3)
    if metrics.active:
        # tick-path taxonomy: admit → stage → dispatch, as completed
        # child spans of device.fused_apply (explicit durations — the
        # phases are measured in-line above)
        metrics.span_event('device.admit', (t1 - t0) * 1e3)
        metrics.span_event('device.stage',
                           (t2 - t1 - (tc1 - tc0)) * 1e3,
                           native=ns is not None)
        metrics.span_event('device.dispatch', (t3 - t2) * 1e3)
        if incr is not None:
            # the incremental index update gets its own Perfetto lane
            # (device.* names each map to a dedicated track) — the
            # dispatch wall of the merge-pass program, with the delta
            # size attached
            metrics.span_event('device.idx_update', (t3 - t2) * 1e3,
                               delta=int(n_total - n_old),
                               jobs=len(dirty))
    if return_timing:
        return patch, {'admit': t1 - t0, 'pack': t2 - t1,
                       'commit_wait': tc1 - tc0,
                       'device': t3 - t2, 'unpack': t4 - t3}
    return patch


def _resolve_ops_numpy(store, block, st, omap, root_ops, obj_doc_arr,
                       obj_type_arr, o_act, o_doc, o_obj_blk, o_kind,
                       o_key_raw, o_key_elem, o_elem, ins_rows, a_rows):
    """The numpy op-resolution path of `_apply_general`: per-op store
    object rows, ins grouping + local node minting, elemId resolution
    with the duplicate check, packed field keys. Mutates the pool
    (append_batch). The native stager (`native.stage_general_block`)
    computes exactly these outputs in C++; this remains the fallback
    for partially-admitted blocks and late-bound string elemIds, and
    the parity oracle for the native path.

    Returns (f_new, a_node, a_objr, dirty, ins_objs): per-assignment-
    row packed field keys / target nodes / object rows, plus the dirty
    sequence objects and the objects that gained nodes."""
    pool = store.pool
    o_objrow = np.where(root_ops, store._root_row[o_doc],
                        omap[o_obj_blk])
    # cross-document object reuse is malformed input, not a crash
    if not (obj_doc_arr[o_objrow] == o_doc).all():
        bad = int(np.flatnonzero(obj_doc_arr[o_objrow] != o_doc)[0])
        raise ValueError('Modification of unknown object '
                         + block.objs[int(o_obj_blk[bad])])
    o_node = np.full(len(o_act), -1, np.int64)   # local node of each op
    ins_objs = np.zeros(0, np.int64)

    # ---- ins prep: group by object, mint local node ids ----
    g_rows = g_obj = g_actor = g_elem = local_new = None
    if len(ins_rows):
        i_obj = o_objrow[ins_rows]
        bad_t = obj_type_arr[i_obj] == _TYPE_MAP
        if bad_t.any():
            bad_row = int(i_obj[np.flatnonzero(bad_t)[0]])
            raise ValueError('Insertion into non-sequence object '
                             + store.obj_uuid[bad_row])
        if len(i_obj) > 1 and (i_obj[1:] >= i_obj[:-1]).all():
            # block emitted docs/objects in order (the common case):
            # the stable object grouping is the identity
            g_rows = ins_rows
            g_obj = i_obj
            g_actor = st.o_actor[ins_rows]
            g_elem = o_elem[ins_rows].astype(np.int64)
        else:
            iord = np.argsort(i_obj, kind='stable')
            g_rows = ins_rows[iord]
            g_obj = i_obj[iord]
            g_actor = st.o_actor[ins_rows][iord]
            g_elem = o_elem[ins_rows][iord].astype(np.int64)
        run_start = np.concatenate([[True], g_obj[1:] != g_obj[:-1]])
        starts = np.flatnonzero(run_start)
        ins_objs = g_obj[starts]
        counts = np.append(starts[1:], len(g_obj)) - starts
        n_old = pool.n_of[ins_objs]
        within = np.arange(len(g_obj)) - np.repeat(starts, counts)
        local_new = np.repeat(n_old, counts) + within
        new_key = (g_actor.astype(np.int64) << 32) | g_elem

        # parent keys (head = -1 sentinel -> node 0, no lookup)
        kinds = o_kind[g_rows]
        p_key = np.full(len(g_rows), -1, np.int64)
        ek = kinds == _KEY_ELEM
        if ek.any():
            p_actor = st.a_tab[o_key_raw[g_rows[ek]]]
            p_key[ek] = (p_actor.astype(np.int64) << 32) | \
                o_key_elem[g_rows[ek]].astype(np.int64)
        sk = kinds == _KEY_STR           # late-bound parent elemIds
        for i in np.flatnonzero(sk).tolist():
            s_key = block.keys[o_key_raw[g_rows[i]]]
            if s_key == '_head':
                continue
            ka, _, ke = s_key.rpartition(':')
            aid = store.actor_of.get(ka, -1)
            if aid < 0 or not ke.isdigit():
                raise ValueError(
                    'List element insertion after unknown element '
                    + s_key)
            p_key[i] = (aid << 32) | int(ke)
    else:
        ins_objs = np.zeros(0, np.int64)
        new_key = p_key = np.zeros(0, np.int64)

    # ---- assignment prep (kinds, late-bound elemIds) ----
    assign_objs = np.zeros(0, np.int64)
    o_field = np.zeros(len(o_act), np.int64)
    e_sel = np.zeros(0, bool)
    if len(a_rows):
        kinds = o_kind[a_rows].copy()
        objr = o_objrow[a_rows]
        is_seq_obj = obj_type_arr[objr] != _TYPE_MAP
        t_actor = np.zeros(len(a_rows), np.int64)
        t_elem = np.zeros(len(a_rows), np.int64)
        e_sel0 = kinds == _KEY_ELEM
        if e_sel0.any():
            t_actor[e_sel0] = st.a_tab[o_key_raw[a_rows[e_sel0]]]
            t_elem[e_sel0] = o_key_elem[a_rows[e_sel0]]
        # string-addressed rows that target a sequence: late-bound
        # elemIds (the op was encoded before the creation was known —
        # possible only across a queue retry; rare)
        conv = (kinds == _KEY_STR) & is_seq_obj
        for i in np.flatnonzero(conv).tolist():
            s_key = block.keys[o_key_raw[a_rows[i]]]
            ka, _, ke = s_key.rpartition(':')
            aid = store.actor_of.get(ka, -1)
            if aid < 0 or not ke.isdigit():
                raise TypeError(
                    'Missing index entry for list element ' + s_key)
            t_actor[i] = aid
            t_elem[i] = int(ke)
        kinds[conv] = _KEY_ELEM
        if (kinds == _KEY_HEAD).any():
            raise ValueError('assignment to _head')
        s_sel = kinds == _KEY_STR
        fkey = np.zeros(len(a_rows), np.int64)
        if s_sel.any():
            fkey[s_sel] = st.k_tab[o_key_raw[a_rows[s_sel]]]
        e_sel = kinds == _KEY_ELEM
        if e_sel.any():
            if not is_seq_obj[e_sel].all():
                raise TypeError('Missing index entry for list element')
            assign_objs = np.unique(objr[e_sel])

    # dirty sequence objects: ins targets + element-assignment targets
    dirty = np.union1d(ins_objs, assign_objs).astype(np.int64)

    # ---- elemId resolution: peephole first, tables for the rest ----
    # The overwhelmingly common shapes are SEQUENTIAL: an ins whose
    # parent is the elemId minted by the nearest PRECEDING ins of the
    # same object (collaborative typing), and a set/del whose target
    # was minted by the op immediately before it in the same change.
    # Both resolve with one vectorized compare; only the residue pays
    # a sorted-table lookup, and the dup check rides the same sorted
    # key arrays. (Replaces a whole-union composite sort that cost
    # ~70 ms per 1M-op block.)
    if len(dirty):
        q_sel = p_key != -1
        if len(ins_rows):
            o_node[g_rows] = local_new     # minted ids, pre-validation
            # peephole A: parent == previous ins of the same object
            # (g is object-grouped, block-order within an object)
            matchA = np.zeros(len(g_rows), bool)
            if len(g_rows) > 1:
                matchA[1:] = (g_obj[1:] == g_obj[:-1]) & \
                    (p_key[1:] == new_key[:-1])
            matchA &= q_sel
            parent_local = np.zeros(len(g_rows), np.int64)
            mA = np.flatnonzero(matchA)
            parent_local[mA] = local_new[mA - 1]
        else:
            matchA = np.zeros(0, bool)
            parent_local = np.zeros(0, np.int64)

        if e_sel.any():
            # peephole B: target minted by the immediately preceding
            # kept op (same object, an ins) — o_node already holds the
            # minted local ids
            er = a_rows[e_sel]
            tgt_key = (t_actor[e_sel] << 32) | t_elem[e_sel]
            prev_r = er - 1
            okB = prev_r >= 0
            pr = np.maximum(prev_r, 0)
            okB &= (o_act[pr] == _INS) & (o_objrow[pr] == objr[e_sel])
            prev_key = (st.o_actor[pr].astype(np.int64) << 32) | \
                o_elem[pr].astype(np.int64)
            matchB = okB & (prev_key == tgt_key)
            nodes = np.full(len(er), -1, np.int64)
            nodes[matchB] = o_node[pr[matchB]]
        else:
            tgt_key = np.zeros(0, np.int64)
            matchB = np.zeros(0, bool)
            nodes = np.zeros(0, np.int64)

        residA = q_sel & ~matchA
        residB = ~matchB if e_sel.any() else np.zeros(0, bool)
        need_dup = len(ins_rows) > 0
        if need_dup or residA.any() or (e_sel.any() and residB.any()):
            ins_job = np.searchsorted(dirty, g_obj) \
                if len(ins_rows) else np.zeros(0, np.int64)
            # staging cache: warm dirty objects keep a sorted elemId
            # index (pool.elem_index) — consult it in O(delta log n)
            # instead of re-tabulating every node of every dirty
            # object. Heads are excluded from the cache; no query or
            # dup comp can equal a head comp (real keys shift +1), so
            # the sorted arrays are interchangeable with the legacy
            # table's.
            ec = pool._elem_cache
            use_cache = (_STAGE_CACHE is not False
                         and _blocks._delta_host_on()
                         and all(int(o) in ec for o in dirty.tolist()))
            if use_cache:
                ents = [ec[int(o)] for o in dirty.tolist()]
                t_counts = np.asarray([len(e[0]) for e in ents],
                                      np.int64)
                t_keys = np.concatenate([e[0] for e in ents])
                t_local = np.concatenate([e[1] for e in ents])
                t_rows = None
            else:
                t_rows, t_counts = pool.rows_of_objs(dirty)
                t_keys = pool.node_keys(t_rows)
                t_local = None
            # shift keys >= 0 (head sentinel -> 0) and pack (job, key)
            # into one int64 when it fits; else the union fallback
            jb = max(int(np.ceil(np.log2(max(len(dirty), 2)))), 1)
            new_k1 = new_key + 1
            t_k1 = np.where(t_keys == _HEAD_KEY, 0, t_keys + 1)
            # the overflow guard must cover QUERY keys too (an unknown
            # elemId with a huge key would otherwise alias into another
            # job's packed range instead of raising — r5 review)
            kmax = max(int(new_k1.max()) if len(new_k1) else 0,
                       int(t_k1.max()) if len(t_k1) else 0,
                       int(p_key[residA].max()) + 1
                       if residA.any() else 0,
                       int(tgt_key[residB].max()) + 1
                       if len(residB) and residB.any() else 0)
            if kmax < (1 << (63 - jb)):
                t_job = np.repeat(np.arange(len(dirty),
                                            dtype=np.int64), t_counts)
                new_comp = (ins_job << (63 - jb)) | new_k1
                old_comp = (t_job << (63 - jb)) | t_k1
                need_lookup = residA.any() or (len(residB)
                                               and residB.any())
                if use_cache:
                    # per-job sorted keys + ascending job bits: the
                    # concatenation is already globally sorted
                    old_comp_s = old_comp
                    old_val_s = t_local
                elif need_lookup:
                    ordo = np.argsort(old_comp, kind='stable')
                    old_comp_s = old_comp[ordo]
                    old_val_s = pool.local[t_rows[ordo]] \
                        .astype(np.int64)
                else:
                    old_comp_s = np.sort(old_comp)
                    old_val_s = None
                ordn = np.argsort(new_comp, kind='stable')
                new_comp_s = new_comp[ordn]
                if need_dup:
                    if len(new_comp_s) > 1 and \
                            (new_comp_s[1:] == new_comp_s[:-1]).any():
                        raise ValueError('Duplicate list element ID')
                    pos = np.searchsorted(old_comp_s, new_comp_s)
                    pos = np.minimum(pos, max(len(old_comp_s) - 1, 0))
                    if len(old_comp_s) and \
                            (old_comp_s[pos] == new_comp_s).any():
                        raise ValueError('Duplicate list element ID')

                def lookup(job, key):
                    """(job, key) -> local id, -1 miss: new first,
                    then the pool's existing nodes."""
                    comp = (job << (63 - jb)) | (key + 1)
                    out = np.full(len(comp), -1, np.int64)
                    if len(new_comp_s):
                        p = np.minimum(
                            np.searchsorted(new_comp_s, comp),
                            len(new_comp_s) - 1)
                        hit = new_comp_s[p] == comp
                        out[hit] = local_new[ordn[p[hit]]]
                    miss = out < 0
                    if miss.any() and len(old_comp_s):
                        p = np.minimum(
                            np.searchsorted(old_comp_s, comp[miss]),
                            len(old_comp_s) - 1)
                        hit = old_comp_s[p] == comp[miss]
                        mi = np.flatnonzero(miss)
                        out[mi[hit]] = old_val_s[p[hit]]
                    return out

                if residA.any():
                    got = lookup(ins_job[residA], p_key[residA])
                    if (got < 0).any():
                        raise ValueError(
                            'List element insertion after unknown '
                            'element')
                    parent_local[residA] = got
                if e_sel.any() and residB.any():
                    ejob = np.searchsorted(dirty, objr[e_sel])
                    got = lookup(ejob[residB], tgt_key[residB])
                    if (got < 0).any():
                        raise TypeError(
                            'Missing index entry for list element')
                    nodes[residB] = got
            else:
                # wide keys: the whole-union composite lookup (exact;
                # overwrites the peephole results with equal values).
                # Needs the full row table — rebuild it if the cache
                # path skipped it (rare: >2^21 actors or >2^31 elems)
                if t_rows is None:
                    t_rows, t_counts = pool.rows_of_objs(dirty)
                    t_keys = pool.node_keys(t_rows)
                t_job = np.repeat(np.arange(len(dirty),
                                            dtype=np.int64), t_counts)
                ejob = np.searchsorted(dirty, objr[e_sel]) \
                    if e_sel.any() else np.zeros(0, np.int64)
                n_pq = int(q_sel.sum())
                res, dup = _exact_lookup(
                    np.concatenate([t_job, ins_job]),
                    np.concatenate([t_keys, new_key]),
                    np.concatenate([pool.local[t_rows]
                                    .astype(np.int64),
                                    local_new if local_new is not None
                                    else np.zeros(0, np.int64)]),
                    np.concatenate([ins_job[q_sel], ejob]),
                    np.concatenate([p_key[q_sel], tgt_key]),
                    len(dirty))
                if dup:
                    raise ValueError('Duplicate list element ID')
                if len(ins_rows):
                    parent_local[q_sel] = res[:n_pq]
                    if (parent_local < 0).any():
                        raise ValueError(
                            'List element insertion after unknown '
                            'element')
                if e_sel.any():
                    nodes = res[n_pq:]

        if e_sel.any():
            if (nodes < 0).any():
                raise TypeError('Missing index entry for list element')
            fkey[e_sel] = _ELEM_BIT | nodes
            o_node[a_rows[e_sel]] = nodes
        if len(ins_rows):
            pool.append_batch(g_obj, local_new, parent_local, g_actor,
                              g_elem)
    if len(a_rows):
        o_field[a_rows] = (objr << 32) | fkey


    f_new = o_field[a_rows]
    return f_new, o_node[a_rows], o_objrow[a_rows], dirty, ins_objs


class _LazyCat:
    """The apply's row-column dict, built per key on FIRST access:
    `thunks[k]()` returns (new_part, prior_part); prior_part of None
    means the column is already concatenated."""

    __slots__ = ('_thunks', '_n_prior', '_cols', '_lock')

    def __init__(self, thunks, n_prior):
        self._thunks = thunks
        self._n_prior = n_prior
        self._cols = {}
        # the applier thread (deferred commit) and a patch reader can
        # both force a column; builds are idempotent but the thunk-drop
        # below is not
        self._lock = threading.Lock()

    def __getitem__(self, k):
        c = self._cols.get(k)
        if c is not None:
            return c
        with self._lock:
            return self._build(k)

    def _build(self, k):
        c = self._cols.get(k)
        if c is None:
            new_part, prior_part = self._thunks[k]()
            if prior_part is None:
                c = np.asarray(new_part)
            elif self._n_prior:
                c = np.concatenate([new_part, prior_part])
            else:
                c = np.asarray(new_part)
            self._cols[k] = c
            # drop the thunk: its closure pins the whole staged block
            # (st + op columns); once every column is built the apply's
            # working set becomes collectable
            self._thunks[k] = None
        return c


def _finish_empty(patch):
    z32 = np.zeros(0, np.int32)
    patch.f_doc = z32
    patch.f_obj = z32
    patch.f_key = np.zeros(0, np.int64)
    patch.f_kind = np.zeros(0, bool)
    patch.f_has_winner = np.zeros(0, bool)
    patch.f_value = z32
    patch.f_actor = z32
    patch.f_link = np.zeros(0, bool)
    patch.s_ptr = np.zeros(1, np.int32)
    patch.s_actor = z32
    patch.s_value = z32
    patch.s_link = np.zeros(0, bool)


def _update_inbound(store, patch, touched_fields, surviving, r_seg,
                    r_link, r_value, s_rows):
    """Link bookkeeping: survivors' targets gain an inbound ref, links
    that dropped out lose theirs (op_set.js:194-208). Link rows are rare
    — plain python over them."""
    link_rows = np.flatnonzero(r_link[:len(r_seg)])
    if not len(link_rows):
        return
    surv_set = set(s_rows.tolist())
    for j in link_rows.tolist():
        fi = int(r_seg[j])
        field = int(touched_fields[fi])
        obj_row = field >> 32
        key = field & 0xFFFFFFFF
        d = int(store.obj_doc[obj_row])
        target_uuid = store.values[int(r_value[j])]
        target = store.obj_of.get((d, target_uuid))
        if target is None:
            continue
        refs = store.obj_inbound.setdefault(target, [])
        ref = (obj_row, key)
        if j in surv_set:
            if ref not in refs:
                refs.append(ref)
        else:
            if ref in refs:
                refs.remove(ref)


# camelCase aliases (reference API style)
applyGeneralBlock = apply_general_block
