"""GeneralBackendState: the per-document backend surface served by the
general bulk engine.

The per-doc device backend (:mod:`.backend`) stages changes with
Python-per-change loops — right for interactive edits, dispatch-bound
for bulk ingestion (a 20k-op merge measured ~0.29s vs the bulk engine's
~0.15s at the same size). This module lets ``DeviceBackend
.apply_changes`` route LARGE ingests through
:func:`~.general.apply_general_block` while keeping the unchanged
backend protocol (`backend/index.js:161-163`): wire changes in,
reference-format patch out, persistent-state semantics preserved.

State model: a token over a (mutable) :class:`~.general.GeneralStore`.
The newest token applies in place; applying to a STALE token (a held
snapshot) forks a fresh store by replaying the retained log up to the
token's clock — correct for every history, fast for the overwhelmingly
common linear case. Reads that the sync protocol performs on old
tokens (``clock``, ``get_missing_changes``) are served exactly from
the append-only retained log filtered by the token clock.

Local changes and undo/redo run NATIVELY on the token: inverse-op
capture reads the store columns through the ``fields`` view (the same
surface `backend._capture_undo_ops` stages against), and the token
carries the undo/redo stacks — so a document ingested at bulk scale
keeps the full per-doc surface without ever converting. The per-doc
conversion (:func:`to_device_state`) remains available for callers
that want the staged representation.
"""

import numpy as np

from ..common import ROOT_ID
from . import general as _general

_ELEM_BIT = int(_general._ELEM_BIT)
_TYPE_NAME = _general._TYPE_NAME
_TYPE_MAP = _general._TYPE_MAP


class GeneralBackendState:
    """Persistent-token view of a one-document general store."""

    __slots__ = ('store', '_version', 'clock', 'deps', '_all_deps',
                 '_device_state', 'undo_pos', 'undo_stack',
                 'redo_stack')

    def __init__(self, store, version, clock, deps, all_deps):
        self.store = store
        self._version = version
        self.clock = clock
        self.deps = deps
        self._all_deps = all_deps      # (actor, seq) -> transitive deps
        self._device_state = None
        self.undo_pos = 0
        self.undo_stack = []
        self.redo_stack = []

    def _is_current(self):
        return self._version == getattr(self.store, '_gb_version', 0)

    @property
    def fields(self):
        """Read-only (obj uuid, key) -> surviving entries view — the
        surface the per-doc undo capture reads
        (`backend._field_ops_or_del`), served from the store columns."""
        return _FieldsView(self)


class _FieldsView:
    """Lazy field lookup over the general store's entry columns:
    ``get((obj_uuid, key))`` returns the field's surviving entries,
    winner first, as the per-doc backend's entry dicts. O(doc entries)
    per lookup — local-change undo capture touches a handful of
    fields."""

    __slots__ = ('_state',)

    def __init__(self, state):
        self._state = state

    def get(self, field, default=()):
        state = self._state
        store = state.store
        store._commit_pending()
        obj_uuid, key = field
        row = store.obj_of.get((0, obj_uuid))
        if row is None:
            return default
        if store.is_seq(row):
            # elemId key 'actor:counter' -> local node index
            actor_s, _, counter = str(key).rpartition(':')
            aid = store.actor_of.get(actor_s, -1)
            if aid < 0 or not counter.isdigit():
                return default
            pool = store.pool
            prows, _ = pool.rows_of_objs(np.asarray([row]))
            hit = np.flatnonzero((pool.actor[prows] == aid)
                                 & (pool.elemc[prows] == int(counter)))
            if not len(hit):
                return default
            fkey = _ELEM_BIT | int(pool.local[prows[hit[0]]])
        else:
            kid = store.key_of.get(key)
            if kid is None:
                return default
            fkey = kid
        js = np.flatnonzero((store.e_obj == row)
                            & (store.e_key == fkey))
        if not len(js):
            return default
        # winner ordering through the one shared rule
        by = doc_fields_sorted(store, 0, rows=js.tolist())
        entries = next(iter(by.values()))
        out = []
        for j in entries:
            v = store.e_value[j]
            out.append({'action': 'link' if store.e_link[j] else 'set',
                        'actor': store.actors[store.e_actor[j]],
                        'value': store.values[v] if v >= 0 else None})
        return out


def init():
    store = _general.init_store(1)
    store._gb_version = 0
    return GeneralBackendState(store, 0, {}, {}, {})


def _fork(state):
    """Replay the retained log up to the token's clock into a fresh
    store (applying to a held snapshot — the rare path). Causally
    buffered changes carry over: they were delivered, just not yet
    ready (dropping them would silently lose data — r5 review)."""
    try:
        changes = [c for c in state.store.get_missing_changes(0, {})
                   if c['seq'] <= state.clock.get(c['actor'], 0)]
    except ValueError as err:
        raise ValueError(
            'cannot branch from a stale token of a snapshot-resumed '
            'store: its pre-resume history is not replayable — '
            'continue from the newest token instead') from err
    changes += [c for _, c in state.store.queue]
    new = init()
    if changes:
        new, _ = apply_changes(new, changes)
    return new


def current_token(state):
    """The state itself if it is the store's newest token, else a fork
    of its lineage (undo history carried over) — readers that must see
    EXACTLY the token's history (undo capture, snapshots) call this
    before touching the shared columns."""
    if state._is_current():
        return state
    fork = _fork(state)
    fork.undo_pos = state.undo_pos
    fork.undo_stack = list(state.undo_stack)
    fork.redo_stack = list(state.redo_stack)
    return fork


def _advance_deps(deps, all_deps_tab, applied, pre_clock):
    """Fold the applied changes into the dependency frontier, in causal
    order, with the oracle's transitive-closure rule
    (backend/op_set.py:512-523, op_set.js:293-305)."""
    deps = dict(deps)
    clk = dict(pre_clock)
    pend = list(applied)
    while pend:
        progress = False
        rest = []
        for c in pend:
            actor, seq = c['actor'], c['seq']
            ready = seq == clk.get(actor, 0) + 1 and all(
                clk.get(a, 0) >= s for a, s in c['deps'].items())
            if not ready:
                rest.append(c)
                continue
            base = dict(c['deps'])
            base[actor] = seq - 1
            all_deps = {}
            for da, ds in base.items():
                trans = all_deps_tab.get((da, ds), {})
                for a, s in trans.items():
                    all_deps[a] = max(all_deps.get(a, 0), s)
                all_deps[da] = max(all_deps.get(da, 0), ds)
            all_deps.pop(None, None)
            deps = {a: s for a, s in deps.items()
                    if s > all_deps.get(a, 0)}
            deps[actor] = seq
            all_deps_this = dict(all_deps)
            all_deps_this[actor] = seq
            all_deps_tab[(actor, seq)] = all_deps_this
            clk[actor] = seq
            progress = True
        pend = rest
        if not progress:
            break
    return deps


def apply_changes(state, changes, options=None):
    """applyChanges through the bulk engine; returns
    (new token, reference-format patch)."""
    changes = list(changes)      # consumed more than once below
    orig = state                 # undo history survives a stale fork
    if not state._is_current():
        state = _fork(state)
    store = state.store
    pre_clock = dict(state.clock)
    pre_queue = [c for _, c in store.queue]
    block = store.encode_changes([changes])
    gpatch = _general.apply_general_block(store, block,
                                          options=options)
    clock = store.clock_of(0)
    applied = [c for c in changes + pre_queue
               if pre_clock.get(c['actor'], 0) < c['seq']
               <= clock.get(c['actor'], 0)]
    all_deps_tab = dict(state._all_deps)
    deps = _advance_deps(state.deps, all_deps_tab, applied, pre_clock)
    store._gb_version = state._version + 1
    new = GeneralBackendState(store, store._gb_version, clock, deps,
                              all_deps_tab)
    # local-change history carries across remote applies (the per-doc
    # backend and the reference both keep it) — from the CALLER's
    # token, which a stale fork must not reset. COPIED, matching
    # DeviceBackendState.clone's convention: a future in-place append
    # on either token must not corrupt the other's history.
    new.undo_pos = orig.undo_pos
    new.undo_stack = list(orig.undo_stack)
    new.redo_stack = list(orig.redo_stack)
    patch = {'clock': dict(clock), 'deps': dict(deps),
             'canUndo': new.undo_pos > 0,
             'canRedo': bool(new.redo_stack),
             'diffs': _LazyDiffs(gpatch)}
    return new, patch


class _LazyDiffs:
    """Diff list that materializes on first read: an ingestion
    pipeline (DocSet apply, merge loops) never pays the Python diff
    emission; a frontend iterating ``patch['diffs']`` pays exactly
    once. Survives dict copies (it is a value, not a missing key)."""

    __slots__ = ('_gpatch', '_diffs')

    def __init__(self, gpatch):
        self._gpatch = gpatch
        self._diffs = None

    def _mat(self):
        if self._diffs is None:
            self._diffs = self._gpatch.diffs(0)
            self._gpatch = None
        return self._diffs

    def __len__(self):
        return len(self._mat())

    def __iter__(self):
        return iter(self._mat())

    def __getitem__(self, i):
        return self._mat()[i]

    def __bool__(self):
        return bool(self._mat())

    def __eq__(self, other):
        return self._mat() == other

    def __repr__(self):
        return repr(self._mat())


def get_missing_changes(state, have_deps):
    """Served from the append-only retained log, filtered by the
    TOKEN's clock (old tokens never leak newer changes)."""
    out = state.store.get_missing_changes(0, dict(have_deps))
    clock = state.clock
    return [c for c in out if c['seq'] <= clock.get(c['actor'], 0)]


def get_changes_for_actor(state, for_actor, after_seq=0):
    return [c for c in get_missing_changes(state, {})
            if c['actor'] == for_actor and c['seq'] > after_seq]


def get_missing_deps(state):
    return state.store.get_missing_deps()


def to_device_state(state):
    """Convert (lazily, cached per token) to the per-doc
    DeviceBackendState — the continuation path for local changes and
    undo/redo."""
    if state._device_state is None:
        from . import backend as DeviceBackend
        from ..config import Options
        no_route = Options(bulk_route_min_ops=None)  # else it loops
        dev = DeviceBackend.init()
        changes = get_missing_changes(state, {})
        if changes:
            dev, _ = DeviceBackend.apply_changes(dev, changes,
                                                 options=no_route)
        queued = [c for _, c in state.store.queue]
        if queued:
            dev, _ = DeviceBackend.apply_changes(dev, queued,
                                                 options=no_route)
        state._device_state = dev
    return state._device_state


# native-view switch: None = auto (use the C++ view gather when the
# library loads), False = numpy only, True = REQUIRE native (tests:
# fail loudly instead of silently falling back) — the read-side twin
# of general._NATIVE_STAGING
_NATIVE_VIEW = None


def winner_select(field, rank):
    """The batched read path's winner index: one stable sort of the
    packed ``(obj_row << 32) | key`` field keys plus a per-segment
    winner pick. Returns ``(fields, winner_pos)`` — the sorted distinct
    field keys and, per field, the position IN THE INPUT ARRAYS of its
    winning entry (max actor string rank = op_set.js:211's highest
    actor; first-in-entry-order on ties = the stable first-applied
    tie-break ``doc_fields_sorted`` implements per doc).

    Runs on the native gather (``amst_view_winners``) when available;
    the numpy path below is the byte-identical fallback."""
    n = len(field)
    if n == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    if _NATIVE_VIEW is not False:
        from .. import native as _amnative
        out = _amnative.view_winners(field, rank)
        if out is not None:
            return out
    if _NATIVE_VIEW is True:
        raise RuntimeError('native view path required but unavailable')
    order = np.argsort(field, kind='stable')
    fs = field[order]
    bnd = np.empty(n, bool)
    bnd[0] = True
    bnd[1:] = fs[1:] != fs[:-1]
    starts = np.flatnonzero(bnd)
    seg = np.cumsum(bnd) - 1
    r = rank[order]
    seg_max = np.maximum.reduceat(r, starts)
    # first max-rank row of each segment; within a segment the stable
    # field sort preserved entry order, so min position = first applied
    cand = np.where(r == seg_max[seg], np.arange(n), n)
    winner_pos = order[np.minimum.reduceat(cand, starts)]
    return fs[starts], winner_pos


def visible_walk(pool, objs):
    """Visible elements of ALL of ``objs`` (ascending sequence object
    rows) in one sweep: returns ``(seg, local, counts)`` where ``seg``
    names the object (index into ``objs``), ``local`` the node's local
    index, both grouped per object in document (vis_index) order, and
    ``counts[k]`` the visible length of ``objs[k]`` — the fleet-wide
    generalization of :func:`visible_seq_rows` (requires
    ``pool.sync()``). Native (``amst_view_walk``) when available; the
    numpy path is the byte-identical fallback."""
    n_objs = len(objs)
    if n_objs == 0:
        return (np.zeros(0, np.int64), np.zeros(0, np.int64),
                np.zeros(0, np.int64))
    if _NATIVE_VIEW is not False:
        from .. import native as _amnative
        out = _amnative.view_walk(objs, pool)
        if out is not None:
            return out
    if _NATIVE_VIEW is True:
        raise RuntimeError('native view path required but unavailable')
    prows, counts_n = pool.rows_of_objs(objs)
    seg_all = np.repeat(np.arange(n_objs, dtype=np.int64), counts_n)
    vis = pool.visible[prows]
    seg_v = seg_all[vis]
    loc_v = pool.local[prows[vis]].astype(np.int64)
    # the resident order is already materialized as a DENSE rank per
    # object (vis_index = 0..count-1), so the walk is one O(n)
    # scatter to position — byte-identical to the old composite
    # argsort, without the O(n log n) sort
    counts = np.bincount(seg_v, minlength=n_objs).astype(np.int64)
    starts = np.zeros(n_objs + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    tgt = starts[seg_v] + pool.vis_index[prows[vis]]
    out_seg = np.empty(len(seg_v), np.int64)
    out_loc = np.empty(len(loc_v), np.int64)
    out_seg[tgt] = seg_v
    out_loc[tgt] = loc_v
    return out_seg, out_loc, counts


def doc_fields_sorted(store, idx, rows=None):
    """{packed field key: [entry rows, winner first]} for one document
    — entries sorted STABLE actor-descending (op_set.js:211: winner =
    highest actor string, first-applied on ties). The one shared
    reading of the conflict-winner rule (get_patch, DocSet
    materialization)."""
    if rows is None:
        rows = np.flatnonzero(store.e_doc == idx)
    by_field = {}
    for j in (rows.tolist() if hasattr(rows, 'tolist') else rows):
        fkey = (int(store.e_obj[j]) << 32) | int(store.e_key[j])
        by_field.setdefault(fkey, []).append(j)
    for js in by_field.values():
        js.sort(key=lambda j: store.actors[store.e_actor[j]],
                reverse=True)
    return by_field


def visible_seq_rows(store, obj_row):
    """Pool rows of one sequence object's VISIBLE elements, in
    document order (requires pool.sync())."""
    pool = store.pool
    prows, _ = pool.rows_of_objs(np.asarray([obj_row]))
    vis = pool.visible[prows]
    order = np.argsort(pool.vis_index[prows][vis])
    return prows[vis][order]


def get_patch(state):
    """Whole-document patch from empty — create diffs child-first,
    then sets/inserts (parity with device backend get_patch,
    backend/index.js:201-207), built from the store columns."""
    store = state.store
    store._commit_pending()
    store.pool.sync()
    if not state._is_current():
        # historical token: replay through the per-doc backend; the
        # undo flags are the TOKEN's (the replayed state has none)
        from . import backend as DeviceBackend
        p = DeviceBackend.get_patch(to_device_state(state))
        p['canUndo'] = state.undo_pos > 0
        p['canRedo'] = bool(state.redo_stack)
        return p
    root = int(store._root_row[0]) if len(store._root_row) else -1
    diffs = []
    if root < 0:
        return {'clock': dict(state.clock), 'deps': dict(state.deps),
                'canUndo': state.undo_pos > 0,
                'canRedo': bool(state.redo_stack), 'diffs': diffs}

    by_field = doc_fields_sorted(store, 0)

    def value_link(j):
        if store.e_link[j]:
            return store.values[store.e_value[j]], True
        v = store.e_value[j]
        return (store.values[v] if v >= 0 else None), False

    emitted = set()

    def emit_object(obj_row):
        if obj_row in emitted:
            return
        emitted.add(obj_row)
        t = store.obj_type[obj_row]
        uuid = store.obj_uuid[obj_row]
        if t != _TYPE_MAP:
            # sequence create carries maxElem (parity with the per-doc
            # backend's get_patch emission)
            diffs.append({'action': 'create', 'obj': uuid,
                          'type': _TYPE_NAME[t],
                          'maxElem': int(
                              store.pool.max_elem_of[obj_row])})
        elif uuid != ROOT_ID:
            diffs.append({'action': 'create', 'obj': uuid,
                          'type': 'map'})
        if t == _TYPE_MAP:
            for fkey in sorted(k for k in by_field
                               if (k >> 32) == obj_row
                               and not (k & _ELEM_BIT)):
                js = by_field[fkey]          # winner first (sorted)
                # children first
                for j in js:
                    if store.e_link[j]:
                        row = store.obj_of.get(
                            (0, store.values[store.e_value[j]]))
                        if row is not None:
                            emit_object(row)
                w = js[0]
                value, link = value_link(w)
                edit = {'action': 'set', 'type': 'map', 'obj': uuid,
                        'key': store.keys[fkey & 0x7FFFFFFF],
                        'value': value}
                if link:
                    edit['link'] = True
                if len(js) > 1:
                    edit['conflicts'] = _conflicts(store, js[1:])
                diffs.append(edit)
            return
        # sequence: visible inserts in document order
        pool = store.pool
        vrows = visible_seq_rows(store, obj_row)
        for idx, r in enumerate(vrows.tolist()):
            node = int(pool.local[r])
            js = by_field.get(
                (obj_row << 32) | _ELEM_BIT | node, [])
            for j in js:
                if store.e_link[j]:
                    row = store.obj_of.get(
                        (0, store.values[store.e_value[j]]))
                    if row is not None:
                        emit_object(row)
            elem_id = (f'{store.actors[pool.actor[r]]}:'
                       f'{int(pool.elemc[r])}')
            edit = {'action': 'insert', 'type': _TYPE_NAME[t],
                    'obj': uuid, 'index': idx, 'elemId': elem_id}
            if js:
                w = js[0]
                value, link = value_link(w)
                edit['value'] = value
                if link:
                    edit['link'] = True
                if len(js) > 1:
                    edit['conflicts'] = _conflicts(store, js[1:])
            else:
                edit['value'] = None
            diffs.append(edit)

    emit_object(root)
    return {'clock': dict(state.clock), 'deps': dict(state.deps),
            'canUndo': state.undo_pos > 0,
            'canRedo': bool(state.redo_stack), 'diffs': diffs}


def _conflicts(store, js):
    out = []
    for j in js:
        v = store.e_value[j]
        entry = {'actor': store.actors[store.e_actor[j]],
                 'value': store.values[v] if v >= 0 else None}
        if store.e_link[j]:
            entry['link'] = True
        out.append(entry)
    return out
