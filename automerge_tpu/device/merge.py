"""Batched map-field conflict resolution kernel.

TPU-native replacement for the reference's per-op assignment loop
(`applyAssign`, op_set.js:180-219): instead of walking ops one at a time
through an Immutable.js map, ALL assignment ops touching a document (new
ops plus the prior surviving field state) are resolved in one shot with
segment reductions.

Semantics (equivalent to the sequential reference loop under causal
delivery):

* An op is **superseded** iff some other op on the same (obj, key) causally
  follows it — i.e. that op's transitive-deps clock includes it
  (`isConcurrent`, op_set.js:7-16). Because a superseding op is always
  applied later under causal delivery, the sequential "partition by
  concurrency" loop and this order-independent fixpoint agree.
* Surviving non-delete ops form the field's op set; the **winner** is the
  op with the highest actor rank (op_set.js:211 sorts actor-descending);
  remaining survivors are the conflicts. Ties on actor rank (only possible
  for multiple assignments within ONE change — same actor, same seq) go to
  the LOWEST op index: the reference's sort is stable, so the first-applied
  op stays in front and later ops of the change become self-conflicts.

The key observation making this one segment-reduction instead of an
all-pairs test: ``superseded[i] = (max_{j in segment} clock_j[actor_i])
>= seq_i``. An op's own clock row never includes itself
(clock_i[actor_i] = seq_i - 1), so self-comparison is harmless.

Shapes are static; documents batch via ``vmap`` on the leading axis.
"""

from functools import partial

import jax
import jax.numpy as jnp


def _resolve(seg_id, actor, seq, clock, is_del, valid, num_segments):
    n = actor.shape[0]

    # Padding ops must not influence the segment maxima.
    masked_clock = jnp.where(valid[:, None], clock, -1)
    seg_clock_max = jax.ops.segment_max(
        masked_clock, seg_id, num_segments=num_segments)      # [S, A]
    seen = jnp.take_along_axis(
        seg_clock_max[seg_id], actor[:, None], axis=1)[:, 0]  # [N]
    superseded = seen >= seq

    surviving = valid & ~superseded & ~is_del

    # Winner per segment = surviving op with max actor rank, MIN index on
    # rank ties (stable actor-descending sort, op_set.js:211). Two
    # reductions (max actor, then min index at that actor) avoid packing
    # (actor, index) into one word, which could overflow int32 on
    # million-op batches.
    actor_score = jnp.where(surviving, actor, -1)
    seg_max_actor = jax.ops.segment_max(actor_score, seg_id,
                                        num_segments=num_segments)  # [S]
    at_winner_actor = surviving & (actor == seg_max_actor[seg_id])
    idx_score = jnp.where(at_winner_actor, -jnp.arange(n, dtype=jnp.int32),
                          -n - 1)
    neg_winner = jax.ops.segment_max(idx_score, seg_id,
                                     num_segments=num_segments)
    winner = jnp.where(neg_winner < -n, -1, -neg_winner)

    return {'surviving': surviving, 'winner': winner,
            'seg_max_actor': seg_max_actor}


def _seg_scan_max(flags, vals):
    """Inclusive SEGMENTED cummax along axis 0: ``flags[i]`` marks the
    first row of a segment; rows only see rows of their own segment.
    Associative combine: a right block that starts fresh discards the
    left block's running max."""
    def op(a, c):
        af, av = a
        cf, cv = c
        return af | cf, jnp.where(cf, cv, jnp.maximum(av, cv))

    _, out = jax.lax.associative_scan(op, (flags, vals), axis=0)
    return out


def _seg_row_max(boundary, vals):
    """Per-row max of ``vals`` over the row's whole (contiguous)
    segment: forward + backward segmented scans. ``vals`` is [n] or
    [n, C] (columns reduce independently)."""
    b = boundary if vals.ndim == 1 else \
        jnp.broadcast_to(boundary[:, None], vals.shape)
    fwd = _seg_scan_max(b, vals)
    b_rev = jnp.concatenate([boundary[1:], jnp.ones(1, bool)])[::-1]
    br = b_rev if vals.ndim == 1 else \
        jnp.broadcast_to(b_rev[:, None], vals.shape)
    bwd = _seg_scan_max(br, vals[::-1])[::-1]
    return jnp.maximum(fwd, bwd)


def _resolve_sorted(boundary, actor, seq, clock, is_del, valid,
                    num_segments):
    """`_resolve` for rows already SORTED by segment (the general
    engine's field-sorted staging): contiguous segments are marked by
    one boundary bit per row, and both segment reductions ride
    associative scans instead of scatters — on TPU a segmented cummax
    is ~5x cheaper than `segment_max` at the million-row scale.

    Bit-identical semantics to `_resolve` (same superseded rule, same
    actor-descending winner with min-index tie-break). Returns the same
    dict; `winner`/`seg_max_actor` materialize to [S] with one scatter
    at the boundary rows."""
    n = actor.shape[0]

    # scan 1: clock-column maxima AND the surviving-actor maximum ride
    # one [n, A+1] scan (independent per-column maxima)... except
    # `surviving` depends on the clock maxima, so the actor reduction
    # genuinely sequences after: two scan pairs total.
    masked_clock = jnp.where(valid[:, None], clock, -1)
    seen_cols = _seg_row_max(boundary, masked_clock)          # [n, A]
    seen = jnp.take_along_axis(seen_cols, actor[:, None], axis=1)[:, 0]
    superseded = seen >= seq
    surviving = valid & ~superseded & ~is_del

    # scan 2: winner = surviving row with max actor rank, min row index
    # on ties — (actor, n-1-idx) reduce as two int32 columns in one
    # scan with a lexicographic combine (int64 packing would need x64).
    idx = jnp.arange(n, dtype=jnp.int32)
    a_score = jnp.where(surviving, actor, -1)
    i_score = jnp.where(surviving, n - 1 - idx, -1)

    def lex_op(a, c):
        af, aa, ai = a
        cf, ca, ci = c
        take_c = cf | (ca > aa) | ((ca == aa) & (ci > ai))
        return (af | cf,
                jnp.where(cf, ca, jnp.maximum(aa, ca)),
                jnp.where(take_c, ci, ai))

    b = boundary
    _, fa, fi = jax.lax.associative_scan(lex_op, (b, a_score, i_score),
                                         axis=0)
    b_rev = jnp.concatenate([b[1:], jnp.ones(1, bool)])[::-1]
    _, ba, bi = jax.lax.associative_scan(
        lex_op, (b_rev, a_score[::-1], i_score[::-1]), axis=0)
    ba, bi = ba[::-1], bi[::-1]
    pick_b = (ba > fa) | ((ba == fa) & (bi > fi))
    seg_max_actor_row = jnp.maximum(fa, ba)
    best_i = jnp.where(pick_b, bi, fi)
    winner_row = jnp.where(seg_max_actor_row >= 0, (n - 1) - best_i, -1)

    # [S] materialization: one scatter at the boundary rows
    seg_of = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    tgt = jnp.where(boundary, seg_of, num_segments)
    winner = jnp.full((num_segments,), -1, jnp.int32) \
        .at[tgt].set(winner_row, mode='drop')
    # empty (padding) segments match _resolve's segment_max identity
    seg_max_actor = jnp.full((num_segments,), jnp.iinfo(jnp.int32).min,
                             jnp.int32).at[tgt].set(seg_max_actor_row,
                                                    mode='drop')
    return {'surviving': surviving, 'winner': winner,
            'seg_max_actor': seg_max_actor}


@partial(jax.jit, static_argnames=('num_segments',))
def resolve_assignments(seg_id, actor, seq, clock, is_del, valid, *, num_segments):
    """Resolve a batch of assignment ops grouped by field.

    Args:
      seg_id: int32[N]    field group id per op (padding ops carry any
                          in-range seg_id with valid=False)
      actor:  int32[N]    actor rank per op (rank order == actor string order)
      seq:    int32[N]    change seq per op
      clock:  int32[N,A]  transitive-deps clock row per op
      is_del: bool[N]     deletion ops
      valid:  bool[N]     padding mask
      num_segments: static segment count (>= max seg_id + 1)

    Returns dict of:
      surviving:     bool[N]   op remains in the field's op set
      winner:        int32[S]  index of the winning op per segment (-1 if none)
      seg_max_actor: int32[S]  actor rank of the winner (-1 if none)
    """
    return _resolve(seg_id, actor, seq, clock, is_del, valid, num_segments)


@partial(jax.jit, static_argnames=('num_segments',))
def resolve_assignments_batch(seg_id, actor, seq, clock, is_del, valid, *, num_segments):
    """vmap over a leading document axis: one program, N docs (the 'DP'
    axis of the framework — each document is an independent replica of the
    same engine)."""
    return jax.vmap(partial(_resolve, num_segments=num_segments))(
        seg_id, actor, seq, clock, is_del, valid)
