"""Host-side interning and struct-of-arrays packing.

The device sees only dense integers; this module owns the string<->int
boundary: actor UUIDs -> ranks (rank order preserves string order, which
the conflict-resolution kernel relies on — op_set.js:211), object UUIDs
and map keys -> segment ids, elemIds -> node indexes. Values never travel
to the device: ops reference them by row index and the winners map back to
host-side value lists, so arbitrary JSON payloads ride along for free.
"""

import numpy as np


def closure_clocks(changes, prior_states=None):
    """Transitive-deps clock per change for a self-contained batch.

    Port of the reference's per-change `transitiveDeps` accumulation
    (op_set.js:29-37, :250) over a whole batch at once: changes are
    processed in causal order (fixed-point over readiness, mirroring
    applyQueuedOps, op_set.js:267-283).

    Args:
      changes: list of {'actor','seq','deps',...}
      prior_states: optional {(actor, seq): all_deps_dict} for changes
        already applied before this batch.

    Returns:
      (ordered_changes, all_deps_list) — changes in an applicable causal
      order with their transitive-deps clocks. Raises if the batch is not
      causally self-contained w.r.t. prior_states.
    """
    states = dict(prior_states or {})
    clock = {}
    for (actor, seq) in states:
        clock[actor] = max(clock.get(actor, 0), seq)

    pending = list(changes)
    ordered, all_deps_list = [], []
    while pending:
        progress = False
        remaining = []
        for change in pending:
            actor, seq = change['actor'], change['seq']
            deps = dict(change['deps'])
            deps[actor] = seq - 1
            if all(clock.get(a, 0) >= s for a, s in deps.items()):
                all_deps = {}
                for dep_actor, dep_seq in deps.items():
                    if dep_seq <= 0:
                        continue
                    transitive = states.get((dep_actor, dep_seq), {})
                    for a, s in transitive.items():
                        all_deps[a] = max(all_deps.get(a, 0), s)
                    all_deps[dep_actor] = dep_seq
                states[(actor, seq)] = all_deps
                clock[actor] = max(clock.get(actor, 0), seq)
                ordered.append(change)
                all_deps_list.append(all_deps)
                progress = True
            else:
                remaining.append(change)
        if not progress:
            raise ValueError(
                f'Batch is not causally self-contained; {len(remaining)} '
                'changes have unmet dependencies')
        pending = remaining
    return ordered, all_deps_list


class PackedAssignments:
    """One document's assignment ops as dense numpy columns plus the host
    metadata needed to unpack kernel results back to JSON."""

    __slots__ = ('seg_id', 'actor', 'seq', 'clock', 'is_del', 'valid',
                 'segments', 'op_meta', 'actor_names', 'n_segments')

    def __init__(self, seg_id, actor, seq, clock, is_del, valid,
                 segments, op_meta, actor_names):
        self.seg_id = seg_id
        self.actor = actor
        self.seq = seq
        self.clock = clock
        self.is_del = is_del
        self.valid = valid
        self.segments = segments      # list of (obj, key) per segment id
        self.op_meta = op_meta        # per-op (action, value) for unpacking
        self.actor_names = actor_names
        self.n_segments = len(segments)


def pack_assignments(changes, prior_states=None):
    """Pack every map-assignment op ('set'/'del'/'link') of a change batch.

    Returns a :class:`PackedAssignments`. Non-assignment ops (makeX, ins)
    are ignored here — they are structural and handled by the sequence
    kernel / host.
    """
    ordered, all_deps_list = closure_clocks(changes, prior_states)

    actor_names = sorted({c['actor'] for c in ordered})
    rank = {a: i for i, a in enumerate(actor_names)}
    n_actors = max(len(actor_names), 1)

    seg_of = {}
    segments = []
    rows = []
    op_meta = []
    for change, all_deps in zip(ordered, all_deps_list):
        actor, seq = change['actor'], change['seq']
        crow = np.zeros(n_actors, dtype=np.int32)
        for a, s in all_deps.items():
            if a in rank:
                crow[rank[a]] = s
        for op in change['ops']:
            if op['action'] not in ('set', 'del', 'link'):
                continue
            field = (op['obj'], op['key'])
            if field not in seg_of:
                seg_of[field] = len(segments)
                segments.append(field)
            rows.append((seg_of[field], rank[actor], seq, crow,
                         op['action'] == 'del'))
            op_meta.append((op['action'], op.get('value')))

    n = len(rows)
    seg_id = np.fromiter((r[0] for r in rows), np.int32, n)
    actor = np.fromiter((r[1] for r in rows), np.int32, n)
    seq = np.fromiter((r[2] for r in rows), np.int32, n)
    clock = (np.stack([r[3] for r in rows])
             if rows else np.zeros((0, n_actors), np.int32))
    is_del = np.fromiter((r[4] for r in rows), bool, n)
    valid = np.ones(n, dtype=bool)
    return PackedAssignments(seg_id, actor, seq, clock, is_del, valid,
                             segments, op_meta, actor_names)


def pad_and_stack(packed_docs, n_ops=None, n_actors=None,
                  index_dtype=np.int32, clock_dtype=np.int32):
    """Stack per-doc :class:`PackedAssignments` into padded [D, ...] arrays.

    With `n_ops`/`n_actors` unset, pads to the next power of two (shared
    jit cache across batches — avoids the recompilation storm of truly
    dynamic shapes). A caller-fixed size is used EXACTLY (one pinned jit
    bucket, the Options contract) and overflow is a clear error.
    """
    d = len(packed_docs)
    need_n = max((p.seg_id.shape[0] for p in packed_docs), default=1)
    need_a = max((p.clock.shape[1] for p in packed_docs), default=1)
    if n_ops is not None and need_n > n_ops:
        raise ValueError(f'batch needs {need_n} op rows but op_pad is '
                         f'fixed at {n_ops}')
    if n_actors is not None and need_a > n_actors:
        raise ValueError(f'batch needs {need_a} actors but actor_pad is '
                         f'fixed at {n_actors}')
    n = n_ops if n_ops is not None else max(_next_pow2(need_n), 1)
    a = n_actors if n_actors is not None else max(_next_pow2(need_a), 1)

    seg_id = np.zeros((d, n), index_dtype)
    actor = np.zeros((d, n), index_dtype)
    seq = np.zeros((d, n), clock_dtype)
    clock = np.zeros((d, n, a), clock_dtype)
    is_del = np.zeros((d, n), bool)
    valid = np.zeros((d, n), bool)
    for i, p in enumerate(packed_docs):
        k = p.seg_id.shape[0]
        seg_id[i, :k] = p.seg_id
        actor[i, :k] = p.actor
        seq[i, :k] = p.seq
        clock[i, :k, :p.clock.shape[1]] = p.clock
        is_del[i, :k] = p.is_del
        valid[i, :k] = p.valid
    return seg_id, actor, seq, clock, is_del, valid, n


def _next_pow2(n):
    p = 1
    while p < n:
        p <<= 1
    return p
