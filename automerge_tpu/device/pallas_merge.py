"""Pallas TPU kernel for batched map-field conflict resolution.

Hand-scheduled counterpart of :mod:`.merge` (hot loop 1 of the reference —
`applyAssign`, op_set.js:180-219). Where the XLA path expresses the
resolution as segment reductions (sort/scatter under the hood), this
kernel keeps a block of documents' op arrays resident in VMEM and
resolves every field with dense 128x128 tiles:

* the "which ops causally saw op i" test becomes a **one-hot matmul on the
  MXU**: ``C[i, j] = clock[j, actor[i]] = onehot(actor_i) @ clock_j^T``
  (float32 is exact — clock entries are small sequence counters);
* the per-field maxima become masked row-max reductions on the **VPU**
  over ``same_segment`` compare tiles;
* two passes (survivorship, then winner election among survivors) run
  back-to-back with the intermediate mask held in a VMEM scratch buffer,
  so each op's metadata is read from HBM exactly once.

Semantics are identical to `merge._resolve` (differentially tested); the
public wrapper returns the same dict so the two paths are drop-in
interchangeable.

Layout: ops are padded to OPS_TILE=128 lanes; documents ride the grid in
blocks of DOC_BLOCK=8 (sublane alignment). All loops are static Python
loops, so Mosaic sees straight-line code.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

OPS_TILE = 128
DOC_BLOCK = 8


def _round_up(n, m):
    return -(-n // m) * m


def _make_kernel(n_tiles, n_actors):
    def kernel(seg_ref, actor_ref, seq_ref, clock_ref, is_del_ref, valid_ref,
               surv_ref, wactor_ref, widx_ref, surv_scratch):
        neg = jnp.int32(-1)

        def tile(ref, d, t):
            return ref[d, pl.ds(t * OPS_TILE, OPS_TILE)]

        for d in range(DOC_BLOCK):
            # ---- pass 1: survivorship ------------------------------------
            # seen[i] = max over j in i's segment of clock[j, actor[i]]
            for ti in range(n_tiles):
                seg_i = tile(seg_ref, d, ti)
                actor_i = tile(actor_ref, d, ti)
                a_iota = jax.lax.broadcasted_iota(
                    jnp.int32, (OPS_TILE, n_actors), 1)
                onehot_i = (actor_i[:, None] == a_iota).astype(jnp.float32)
                seen_i = jnp.full((OPS_TILE,), neg)
                for tj in range(n_tiles):
                    seg_j = tile(seg_ref, d, tj)
                    valid_j = tile(valid_ref, d, tj)
                    clock_j = clock_ref[d, pl.ds(tj * OPS_TILE, OPS_TILE), :]
                    # C[i, j] = clock[j, actor[i]]  — MXU one-hot gather
                    # HIGHEST precision keeps the MXU at true f32 (default
                    # TPU matmul precision truncates operands to bf16,
                    # which is integer-exact only to 256); f32 is exact to
                    # 2^24, far above any realistic seq counter.
                    c = jax.lax.dot_general(
                        onehot_i, clock_j.astype(jnp.float32),
                        dimension_numbers=(((1,), (1,)), ((), ())),
                        precision=jax.lax.Precision.HIGHEST,
                        preferred_element_type=jnp.float32).astype(jnp.int32)
                    mask = (seg_i[:, None] == seg_j[None, :]) & \
                        (valid_j != 0)[None, :]
                    seen_i = jnp.maximum(
                        seen_i, jnp.max(jnp.where(mask, c, neg), axis=1))
                seq_i = tile(seq_ref, d, ti)
                valid_i = tile(valid_ref, d, ti)
                is_del_i = tile(is_del_ref, d, ti)
                surv_i = (valid_i != 0) & ~(seen_i >= seq_i) & (is_del_i == 0)
                surv_scratch[d, pl.ds(ti * OPS_TILE, OPS_TILE)] = \
                    surv_i.astype(jnp.int32)

            # ---- pass 2: winner election among survivors -----------------
            # winner_actor[i] = max actor among surviving ops in i's
            # segment; winner_idx[i] = MIN op index at that actor (the
            # reference's STABLE actor-descending conflict sort,
            # op_set.js:211 — rank ties, possible only for multiple
            # assignments within one change, keep the first-applied op).
            big = jnp.int32(n_tiles * OPS_TILE + 1)
            for ti in range(n_tiles):
                seg_i = tile(seg_ref, d, ti)
                wa_i = jnp.full((OPS_TILE,), neg)
                for tj in range(n_tiles):
                    seg_j = tile(seg_ref, d, tj)
                    actor_j = tile(actor_ref, d, tj)
                    surv_j = tile(surv_scratch, d, tj)
                    mask = (seg_i[:, None] == seg_j[None, :]) & \
                        (surv_j != 0)[None, :]
                    wa_i = jnp.maximum(wa_i, jnp.max(
                        jnp.where(mask, actor_j[None, :], neg), axis=1))
                wi_i = jnp.full((OPS_TILE,), big)
                for tj in range(n_tiles):
                    seg_j = tile(seg_ref, d, tj)
                    actor_j = tile(actor_ref, d, tj)
                    surv_j = tile(surv_scratch, d, tj)
                    j_idx = jax.lax.broadcasted_iota(
                        jnp.int32, (OPS_TILE, OPS_TILE), 1) + tj * OPS_TILE
                    at_w = (seg_i[:, None] == seg_j[None, :]) & \
                        (surv_j != 0)[None, :] & \
                        (actor_j[None, :] == wa_i[:, None])
                    wi_i = jnp.minimum(wi_i, jnp.min(
                        jnp.where(at_w, j_idx, big), axis=1))
                wi_i = jnp.where(wi_i == big, neg, wi_i)
                wactor_ref[d, pl.ds(ti * OPS_TILE, OPS_TILE)] = wa_i
                widx_ref[d, pl.ds(ti * OPS_TILE, OPS_TILE)] = wi_i
                surv_ref[d, pl.ds(ti * OPS_TILE, OPS_TILE)] = \
                    tile(surv_scratch, d, ti)

    return kernel


def _resolve_pallas_padded(seg_id, actor, seq, clock, is_del, valid,
                           interpret=False):
    """Core pallas_call on pre-padded [D(=k*8), N(=T*128)] int32 inputs."""
    n_docs, n_pad = seg_id.shape
    n_tiles = n_pad // OPS_TILE
    n_actors = clock.shape[2]

    spec1 = pl.BlockSpec((DOC_BLOCK, n_pad), lambda d: (d, 0),
                         memory_space=pltpu.VMEM)
    spec2 = pl.BlockSpec((DOC_BLOCK, n_pad, n_actors), lambda d: (d, 0, 0),
                         memory_space=pltpu.VMEM)

    surv, wactor, widx = pl.pallas_call(
        _make_kernel(n_tiles, n_actors),
        grid=(n_docs // DOC_BLOCK,),
        in_specs=[spec1, spec1, spec1, spec2, spec1, spec1],
        out_specs=[spec1, spec1, spec1],
        out_shape=[jax.ShapeDtypeStruct((n_docs, n_pad), jnp.int32)] * 3,
        scratch_shapes=[pltpu.VMEM((DOC_BLOCK, n_pad), jnp.int32)],
        interpret=interpret,
    )(seg_id, actor, seq, clock, is_del, valid)
    return {'surviving': surv != 0,
            'winner_actor_per_op': wactor, 'winner_per_op': widx}


@partial(jax.jit, static_argnames=('num_segments', 'interpret'))
def resolve_assignments_batch_pallas(seg_id, actor, seq, clock, is_del, valid,
                                     *, num_segments, interpret=False):
    """Drop-in replacement for `merge.resolve_assignments_batch`.

    Same inputs (see merge.resolve_assignments) with a leading document
    axis, same outputs (surviving bool[D,N], winner int32[D,S],
    seg_max_actor int32[D,S]); the per-segment arrays are derived from the
    kernel's per-op outputs with two cheap segment maxes.
    """
    n_docs, n = seg_id.shape
    n_pad = _round_up(max(n, OPS_TILE), OPS_TILE)
    d_pad = _round_up(max(n_docs, DOC_BLOCK), DOC_BLOCK)
    pad_n, pad_d = n_pad - n, d_pad - n_docs

    def pad1(x, fill):
        return jnp.pad(x.astype(jnp.int32), ((0, pad_d), (0, pad_n)),
                       constant_values=fill)

    seg_p = pad1(seg_id, -2)  # never matches a real segment
    actor_p = pad1(actor, 0)
    seq_p = pad1(seq, jnp.iinfo(jnp.int32).max)
    is_del_p = pad1(is_del, 1)
    valid_p = pad1(valid, 0)
    clock_p = jnp.pad(clock.astype(jnp.int32),
                      ((0, pad_d), (0, pad_n), (0, 0)))

    out = _resolve_pallas_padded(seg_p, actor_p, seq_p, clock_p, is_del_p,
                                 valid_p, interpret=interpret)
    surviving = out['surviving'][:n_docs, :n] & valid
    wactor = out['winner_actor_per_op'][:n_docs, :n]
    widx = out['winner_per_op'][:n_docs, :n]

    # Per-op → per-segment (every real segment contains >= 1 op, and ops of
    # the same segment agree on these values, so the max is just a select).
    def to_seg(per_op):
        return jax.vmap(lambda v, s: jax.ops.segment_max(
            v, s, num_segments=num_segments))(per_op, seg_id)

    # clamp: segment_max fills op-less segments with INT32_MIN; the
    # contract (like merge._resolve) is -1 for "no winner"
    winner = jnp.maximum(to_seg(jnp.where(valid, widx, -1)), -1)
    seg_max_actor = to_seg(jnp.where(valid, wactor, -1))
    return {'surviving': surviving, 'winner': winner,
            'seg_max_actor': seg_max_actor}
