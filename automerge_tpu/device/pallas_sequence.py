"""Pallas TPU kernel for RGA sequence ordering (the flagship kernel).

Hand-scheduled counterpart of :mod:`.sequence` — the skip-list
replacement of SURVEY §5, scheduled for the MXU the way
:mod:`.pallas_merge` schedules field resolution.

The XLA variants pay their pointer-doubling rounds in HBM: the gather
path issues ~2·log2(m) dependent cross-lane gathers per batch, and the
one-hot MXU path (`sequence._rga_order_mxu`) materializes a [K, m, m]
one-hot plane in HBM EVERY round. This kernel keeps a block of jobs'
node planes resident in VMEM and runs the whole pipeline — tree
threading, ancestor climb, list ranking, visibility scan — as
straight-line Mosaic code:

* every gather/scatter is a **one-hot matmul on the MXU** over [m, m]
  f32 tiles (exact: all values < 2^24). The two doubled quantities of
  the ranking loop ride one [m, 2] right-hand side, so each doubling
  round is ONE dot;
* the child-priority sort stays in XLA (measured free — sorting 128-lane
  segments is nothing next to the doubling rounds); the kernel takes the
  sorted permutation `order` and sorted parents `p_sorted` as inputs;
* the visibility prefix-sum is log2(m) shifted adds on the VPU.

Chain ends terminate with SELF-LOOPS instead of the XLA path's (n+1)-slot
terminator, which changes nothing for valid on-chain nodes (tree_pos is
anchored to the head's distance) — vis_index/length are bit-identical to
`vmap(_rga_order)`; tree_pos of PADDING rows differs and is not emitted.

Layout: node axis padded to a multiple of 128 lanes (m <= 512 is the
intended regime, matching the MXU variant's dispatch bound); jobs ride
the grid in blocks of 8.

Why the one-hot schedules STOP at m ~= 512 (measured, r5): every
doubling round must materialize a [*, m, m] one-hot — O(m^2) VPU
compares — before the MXU sees it. On a v5e, ONE such build at
[64, 4096] costs ~45 ms while the ENTIRE gather-variant pipeline
(26 rounds, all phases) runs in ~123 ms; at [8, 16384] one build is
~31 ms vs ~66 ms for the whole gather pipeline. Any one-hot schedule
— Pallas-tiled or XLA — is therefore >= ~10x WORSE than the gather
variant for m >= ~2048, and the crossover sits near the MXU
variant's m <= 512 bound. Scalar in-kernel pointer chasing is no
rescue either: ~m * 2log2(m) dependent scalar loads put a 180k-node
tree at best near the gather variant's time, with none of its
batching. The 3-way dispatch in `sequence._rga_order_batched` (and
the A/B the bench captures) encodes exactly this measured boundary;
large single trees ride the gather variant by design, not omission.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_merge import _round_up
from .sequence import _ceil_log2

JOB_BLOCK = 8
NODE_TILE = 128


def _make_kernel(m, rounds):
    f32 = jnp.float32
    i32 = jnp.int32

    def dot(a, b):
        return jax.lax.dot_general(
            a, b, dimension_numbers=(((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=f32)

    def kernel(order_ref, psort_ref, parent_ref, vis_ref, valid_ref,
               visidx_ref, len_ref):
        iota_r = jax.lax.broadcasted_iota(i32, (m, 1), 0)[:, 0]   # [m]
        iota_col = jax.lax.broadcasted_iota(i32, (m, m), 1)       # [m, m]
        for j in range(JOB_BLOCK):
            order = order_ref[j, :]
            p_sorted = psort_ref[j, :]
            parent = parent_ref[j, :]
            visible = vis_ref[j, :] != 0
            valid = valid_ref[j, :] != 0

            # ---- thread the tree from the sorted order ----------------
            # (1-D bool concats hit Mosaic vreg-cast limits: shift in i32)
            seg_start = jnp.concatenate(
                [jnp.ones((1,), i32),
                 (p_sorted[1:] != p_sorted[:-1]).astype(i32)])
            # first_child[p] = order at the first sorted slot under p
            A = (p_sorted[None, :] == iota_col.T).astype(f32) \
                * seg_start.astype(f32)[None, :]            # [p, s]
            fc_val = dot(A, order.astype(f32)[:, None])[:, 0]
            fc_has = dot(A, jnp.ones((m, 1), f32))[:, 0] > 0
            first_child = jnp.where(fc_has, fc_val.astype(i32), -1)
            # next_sibling via the inverse permutation
            same_next = jnp.concatenate(
                [(p_sorted[1:] == p_sorted[:-1]).astype(i32),
                 jnp.zeros((1,), i32)])
            ns_sorted = jnp.where(
                same_next != 0,
                jnp.concatenate([order[1:], -jnp.ones((1,), i32)]), -1)
            O = (order[:, None] == iota_col).astype(f32)    # [s, node]
            next_sibling = dot(O.T, ns_sorted.astype(f32)[:, None])[:, 0] \
                .astype(i32)
            next_sibling = jnp.where(iota_r == 0, -1, next_sibling)
            has_sib = next_sibling >= 0

            # ---- climb to the nearest ancestor with a sibling ---------
            climb = jnp.where(has_sib | (iota_r == 0), iota_r, parent) \
                .astype(f32)
            for _ in range(rounds):
                G = (climb.astype(i32)[:, None] == iota_col).astype(f32)
                climb = dot(G, climb[:, None])[:, 0]
            G = (climb.astype(i32)[:, None] == iota_col).astype(f32)
            pair = jnp.stack([next_sibling.astype(f32),
                              has_sib.astype(f32)], axis=1)   # [m, 2]
            up2 = dot(G, pair)
            up = jnp.where(up2[:, 1] > 0, up2[:, 0].astype(i32), -1)
            succ = jnp.where(first_child >= 0, first_child, up)
            succ = jnp.where(valid, succ, -1)

            # ---- list-rank the successor chain (self-loop ends) -------
            nxt = jnp.where(succ >= 0, succ, iota_r)
            dist = (succ >= 0).astype(f32)
            nxt_f = nxt.astype(f32)
            for _ in range(rounds):
                G = (nxt_f.astype(i32)[:, None] == iota_col).astype(f32)
                g2 = dot(G, jnp.stack([dist, nxt_f], axis=1))
                dist = dist + g2[:, 0]
                nxt_f = g2[:, 1]
            tree_pos = (dist[0] - dist).astype(i32)

            # ---- visibility scan --------------------------------------
            on_chain = valid & (tree_pos > 0)
            # bool minor-dim inserts are unsupported in Mosaic: build the
            # mask product in f32
            N = (tree_pos[:, None] == iota_col).astype(f32) \
                * on_chain.astype(f32)[:, None]             # [node, pos]
            vis_ordered = dot(N.T, (visible & on_chain)
                              .astype(f32)[:, None])[:, 0]
            run = vis_ordered
            for k in range(rounds):                     # inclusive scan
                s = 1 << k
                if s >= m:
                    break
                run = run + jnp.concatenate(
                    [jnp.zeros((s,), f32), run[:m - s]])
            vis_rank = run - vis_ordered                 # exclusive
            vis_index = dot(N, vis_rank[:, None])[:, 0].astype(i32)
            vis_index = jnp.where(visible & on_chain, vis_index, -1)
            visidx_ref[j, :] = vis_index
            len_ref[j, :] = jnp.broadcast_to(
                jnp.sum((visible & on_chain).astype(i32)), (m,))

    return kernel


def _rga_pallas_padded(order, p_sorted, parent, visible, valid,
                       interpret=False):
    """Core pallas_call on pre-padded [K(=k*8), m(=t*128)] inputs."""
    K, m = order.shape
    rounds = _ceil_log2(m) + 1
    spec = pl.BlockSpec((JOB_BLOCK, m), lambda d: (d, 0),
                        memory_space=pltpu.VMEM)
    visidx, length = pl.pallas_call(
        _make_kernel(m, rounds),
        grid=(K // JOB_BLOCK,),
        in_specs=[spec] * 5,
        out_specs=[spec] * 2,
        out_shape=[jax.ShapeDtypeStruct((K, m), jnp.int32)] * 2,
        interpret=interpret,
    )(order, p_sorted, parent, visible, valid)
    return visidx, length[:, 0]


@partial(jax.jit, static_argnames=('interpret',))
def rga_order_batch_pallas(parent, elem, actor, visible, valid,
                           interpret=False):
    """Batched RGA ordering with the doubling pipeline in one Pallas
    kernel. Returns {'vis_index', 'length'} — bit-identical to the XLA
    variants for valid nodes (differentially tested)."""
    K, m = parent.shape
    K_pad = _round_up(max(K, 1), JOB_BLOCK)
    m_pad = _round_up(max(m, 2), NODE_TILE)

    def pad(a, fill):
        out = jnp.full((K_pad, m_pad), fill, jnp.int32)
        return out.at[:K, :m].set(a.astype(jnp.int32))

    visible = visible.astype(bool)
    valid = valid.astype(bool)
    idx = jnp.arange(m, dtype=jnp.int32)[None, :]
    # child-priority sort in XLA (free next to the doubling rounds);
    # head and padding bucket together at parent m_pad
    parent_adj = jnp.where(valid & (idx != 0), parent, m_pad)
    parent_adj = jnp.concatenate(
        [parent_adj,
         jnp.full((K, m_pad - m), m_pad, jnp.int32)], axis=1)
    parent_adj = jnp.concatenate(
        [parent_adj, jnp.full((K_pad - K, m_pad), m_pad, jnp.int32)])
    order = jax.vmap(lambda a, e, p: jnp.lexsort((-a, -e, p)))(
        pad(actor, 0), pad(elem, 0), parent_adj)
    p_sorted = jnp.take_along_axis(parent_adj, order, axis=1)
    out_vi, out_len = _rga_pallas_padded(
        order.astype(jnp.int32), p_sorted.astype(jnp.int32),
        pad(parent, 0), pad(visible, 0), pad(valid, 0),
        interpret=interpret)
    return {'vis_index': out_vi[:K, :m], 'length': out_len[:K]}
