"""Fused patch-read kernel: winner/visibility diff -> pre-ordered,
delta-sized edit buffers, in ONE device dispatch.

The read side of the tick used to undo the apply side's batching:
``GeneralPatch._ensure`` fetched the FULL [K, m] visibility/order
planes (O(doc) bytes per tick for a 1-op edit) and then re-derived the
edit order on host — per dirty object, an argsort over the prior
indexes for removes and over the new indexes for inserts/sets. This
module is the device twin of the host read trio (the winner-gated edit
classification of ``winner_select``, the visible-order walk of
``visible_walk``, and the order gather): one dispatch classifies every
node of every dirty object as remove/insert/set, ranks each class in
document order with a prefix sum (no sort — vis indexes are already
dense ranks), and compacts the results into ``[K, e_pad]`` buffers
where ``e_pad`` is bounded by the tick's RESOLVED ROW COUNT, never the
tree size. ``GeneralPatch._ensure`` then reads one pre-ordered,
delta-sized buffer: a 1-op append to a 100k-element text fetches a few
hundred bytes instead of half a megabyte, and the host argsorts
disappear.

Two implementations, byte-identical by construction and pinned against
each other in CI:

* :func:`edit_stream` — the ``jax.lax`` fallback (scatter + cumsum +
  gather), the production path on CPU and for large planes;
* :func:`edit_stream_pallas` — the hand-fused Pallas variant next to
  :mod:`.pallas_sequence`: each job's planes stay resident in VMEM and
  every scatter/gather rides a one-hot MXU matmul. Like the RGA MXU
  variant, the one-hot build is O(m^2) VPU compares, so the intended
  regime is m <= ~512 (the measured one-hot crossover documented in
  pallas_sequence's module docstring); the CPU CI lane runs it in
  interpret mode.

The ``_FUSED_VIEW`` switch mirrors the native-path conventions:
``None`` = auto (Pallas on a real TPU backend inside the small-plane
regime, lax otherwise), ``False`` = lax always, ``True`` = REQUIRE the
Pallas kernel — raising instead of silently falling back (tests assert
this; ``_INTERPRET = True`` lets the forced path run on CPU).
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

# force switch: None = auto, False = lax only, True = require Pallas
# (raise instead of silently falling back)
_FUSED_VIEW = None
# run the Pallas kernel in interpret mode (CPU CI lane)
_INTERPRET = False
# auto-dispatch bound: the one-hot MXU schedule is only profitable for
# small planes (see pallas_sequence's measured crossover)
_PALLAS_MAX_M = 512

_W2_ELEM = 0x7FFF
_W2_VIS_SHIFT = 30
_W2_IDX_SHIFT = 15
_WIDE_IDX_MASK = (1 << 22) - 1
_WIDE_VIS_SHIFT = 22


def _unpack_touch(touched_u8, m):
    """MSB-first bit unpack of the host-built touched plane (one bit
    per (job, node) slot; np.packbits layout along the node axis)."""
    i = jnp.arange(m)
    return ((touched_u8[:, i >> 3] >> (7 - (i & 7))) & 1).astype(bool)


def _edit_core(pv, nv, pi, ni, touched, e_pad):
    """The lax edit-stream pipeline over [K, m] planes. Returns
    (rm_idx, ins_node, ins_idx, set_node, set_idx, cnts[K, 3]); the
    [K, e_pad] buffers are -1 padded, each class compacted in document
    order (removes ascending by PRIOR index — the host reads them
    reversed for the descending emit; inserts/sets ascending by NEW
    index)."""
    K, m = pv.shape
    rowi = jnp.arange(K, dtype=jnp.int32)[:, None]
    iota_l = jnp.broadcast_to(jnp.arange(m, dtype=jnp.int32)[None, :],
                              (K, m))
    removed = pv & ~nv
    inserted = nv & ~pv
    setd = nv & pv & touched

    def compact(mask, key, vals):
        # ranks via presence-grid prefix sum: `key` is a dense
        # per-object rank (a vis index), unique among mask rows — no
        # sort needed, one scatter + cumsum + gather
        key_c = jnp.minimum(jnp.maximum(key, 0), m - 1)
        grid = jnp.zeros((K, m), bool).at[
            rowi, jnp.where(mask, key_c, 0)].max(mask, mode='drop')
        rank_g = (jnp.cumsum(grid, axis=1) - grid).astype(jnp.int32)
        rank = jnp.take_along_axis(rank_g, key_c, axis=1)
        tgt = jnp.where(mask, rank, e_pad)
        outs = tuple(
            jnp.full((K, e_pad), -1, jnp.int32).at[rowi, tgt].set(
                v.astype(jnp.int32), mode='drop') for v in vals)
        return outs, jnp.sum(mask, axis=1, dtype=jnp.int32)

    (rm_idx,), rm_cnt = compact(removed, pi, (pi,))
    (ins_node, ins_idx), ins_cnt = compact(inserted, ni, (iota_l, ni))
    (set_node, set_idx), set_cnt = compact(setd, ni, (iota_l, ni))
    cnts = jnp.stack([rm_cnt, ins_cnt, set_cnt], axis=1)
    return rm_idx, ins_node, ins_idx, set_node, set_idx, cnts


@partial(jax.jit, static_argnames=('e_pad',))
def edit_stream(pv, nv, pi, ni, touched_u8, *, e_pad):
    """lax edit stream over unpacked planes (cols vis format)."""
    m = pv.shape[1]
    return _edit_core(pv.astype(bool), nv.astype(bool),
                      pi.astype(jnp.int32), ni.astype(jnp.int32),
                      _unpack_touch(touched_u8, m), e_pad)


@partial(jax.jit, static_argnames=('e_pad',))
def edit_stream_packed(vis_packed, touched_u8, *, e_pad):
    """lax edit stream over the packed apply's vis word plane
    (prior_vis<<31 | visible<<30 | (prior_idx+1)<<15 | (new_idx+1))."""
    v = vis_packed
    m = v.shape[1]
    pv = ((v >> 31) & 1).astype(bool)
    nv = ((v >> _W2_VIS_SHIFT) & 1).astype(bool)
    pi = ((v >> _W2_IDX_SHIFT) & _W2_ELEM) - 1
    ni = (v & _W2_ELEM) - 1
    return _edit_core(pv, nv, pi, ni, _unpack_touch(touched_u8, m),
                      e_pad)


@partial(jax.jit, static_argnames=('e_pad',))
def edit_stream_wide(vis_prior, vis_new, touched_u8, *, e_pad):
    """lax edit stream over the wide apply's two vis word planes
    (``visible << 22 | (idx + 1)`` each)."""
    m = vis_prior.shape[1]
    pv = ((vis_prior >> _WIDE_VIS_SHIFT) & 1).astype(bool)
    nv = ((vis_new >> _WIDE_VIS_SHIFT) & 1).astype(bool)
    pi = (vis_prior & _WIDE_IDX_MASK) - 1
    ni = (vis_new & _WIDE_IDX_MASK) - 1
    return _edit_core(pv, nv, pi, ni, _unpack_touch(touched_u8, m),
                      e_pad)


# -- hand-fused Pallas variant ------------------------------------------------

def _make_edit_kernel(m, e_pad, rounds):
    from jax.experimental import pallas as pl  # noqa: F401
    f32 = jnp.float32
    i32 = jnp.int32

    def dot(a, b):
        return jax.lax.dot_general(
            a, b, dimension_numbers=(((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=f32)

    def kernel(pv_ref, nv_ref, pi_ref, ni_ref, tch_ref,
               rm_ref, insn_ref, insi_ref, setn_ref, seti_ref,
               cnt_ref):
        iota_m = jax.lax.broadcasted_iota(i32, (m, m), 1)      # [m, m]
        iota_e = jax.lax.broadcasted_iota(i32, (m, e_pad), 1)  # [m, e]
        pv = pv_ref[0, :] != 0
        nv = nv_ref[0, :] != 0
        pi = pi_ref[0, :]
        ni = ni_ref[0, :]
        touched = tch_ref[0, :] != 0
        removed = pv & ~nv
        inserted = nv & ~pv
        setd = nv & pv & touched
        iota_l = jax.lax.broadcasted_iota(i32, (m, 1), 0)[:, 0]

        def compact(mask, key, vals, out_refs):
            # presence grid via one-hot matmul (keys unique per mask
            # row, so the sum IS the presence bit), prefix sum via
            # log-shifted adds, rank gather + e-space scatter as two
            # more one-hot dots — the whole class pipeline stays on
            # the MXU/VPU, no sort anywhere
            key_c = jnp.minimum(jnp.maximum(key, 0), m - 1)
            G = (key_c[:, None] == iota_m).astype(f32) \
                * mask.astype(f32)[:, None]                 # [l, p]
            grid = dot(G.T, jnp.ones((m, 1), f32))[:, 0]    # [p]
            run = grid
            for k in range(rounds):                 # inclusive scan
                s = 1 << k
                if s >= m:
                    break
                run = run + jnp.concatenate(
                    [jnp.zeros((s,), f32), run[:m - s]])
            rank_g = run - grid                      # exclusive
            rank = dot(G, rank_g[:, None])[:, 0].astype(i32)
            E = (rank[:, None] == iota_e).astype(f32) \
                * mask.astype(f32)[:, None]                 # [l, e]
            present = dot(E.T, jnp.ones((m, 1), f32))[:, 0] > 0
            for v, ref in zip(vals, out_refs):
                got = dot(E.T, v.astype(f32)[:, None])[:, 0] \
                    .astype(i32)
                ref[0, :] = jnp.where(present, got, -1)
            return jnp.sum(mask.astype(i32))

        n_rm = compact(removed, pi, (pi,), (rm_ref,))
        n_in = compact(inserted, ni, (iota_l, ni),
                       (insn_ref, insi_ref))
        n_st = compact(setd, ni, (iota_l, ni), (setn_ref, seti_ref))
        # scalar element sets hit Mosaic limits: lay the three counts
        # out with iota selects instead
        iota_c = jax.lax.broadcasted_iota(i32, (e_pad, 1), 0)[:, 0]
        cnt_ref[0, :] = jnp.where(
            iota_c == 0, n_rm,
            jnp.where(iota_c == 1, n_in,
                      jnp.where(iota_c == 2, n_st, 0)))

    return kernel


@partial(jax.jit, static_argnames=('e_pad', 'interpret'))
def _edit_stream_pallas_core(pv, nv, pi, ni, touched, *, e_pad,
                             interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from .pallas_merge import _round_up
    from .sequence import _ceil_log2
    K, m = pv.shape
    m_pad = _round_up(max(m, 2), 128)
    e_out = _round_up(max(e_pad, 8), 128)

    def pad(a, fill):
        out = jnp.full((K, m_pad), fill, jnp.int32)
        return out.at[:, :m].set(a.astype(jnp.int32))

    spec_in = pl.BlockSpec((1, m_pad), lambda d: (d, 0),
                           memory_space=pltpu.VMEM)
    spec_out = pl.BlockSpec((1, e_out), lambda d: (d, 0),
                            memory_space=pltpu.VMEM)
    outs = pl.pallas_call(
        _make_edit_kernel(m_pad, e_out, _ceil_log2(m_pad) + 1),
        grid=(K,),
        in_specs=[spec_in] * 5,
        out_specs=[spec_out] * 6,
        out_shape=[jax.ShapeDtypeStruct((K, e_out), jnp.int32)] * 6,
        interpret=interpret,
    )(pad(pv, 0), pad(nv, 0), pad(pi, -1), pad(ni, -1),
      pad(touched, 0))
    rm, insn, insi, setn, seti, cnt = outs
    return (rm[:, :e_pad], insn[:, :e_pad], insi[:, :e_pad],
            setn[:, :e_pad], seti[:, :e_pad], cnt[:, :3])


@partial(jax.jit, static_argnames=('e_pad', 'interpret'))
def edit_stream_pallas(pv, nv, pi, ni, touched_u8, *, e_pad,
                       interpret=False):
    """Hand-fused Pallas edit stream — bit-identical to
    :func:`edit_stream` (differentially tested in the interpret-mode
    CI lane)."""
    m = pv.shape[1]
    return _edit_stream_pallas_core(
        pv.astype(jnp.int32), nv.astype(jnp.int32),
        pi.astype(jnp.int32), ni.astype(jnp.int32),
        _unpack_touch(touched_u8, m).astype(jnp.int32),
        e_pad=e_pad, interpret=interpret)


def _use_pallas(m):
    if _FUSED_VIEW is False:
        return False
    if _FUSED_VIEW is True:
        if not _INTERPRET and jax.default_backend() != 'tpu':
            raise RuntimeError(
                'Pallas fused view required (_FUSED_VIEW=True) but no '
                'TPU backend is available (set _INTERPRET=True for '
                'the CPU interpret lane)')
        return True
    return (jax.default_backend() == 'tpu' and m <= _PALLAS_MAX_M)


def dispatch_edit_stream(vis_fmt, planes, touched_u8, e_pad):
    """Dispatch the edit-stream kernel over one apply's vis planes
    (device-resident outputs of the fused apply) — the entry point
    ``GeneralPatch._ensure`` calls. Returns the device output tuple
    (fetch with one ``jax.device_get``)."""
    t_u8 = jnp.asarray(touched_u8)
    if vis_fmt == 'packed':
        v = planes
        if _use_pallas(int(v.shape[1])):
            pv = ((v >> 31) & 1)
            nv = ((v >> _W2_VIS_SHIFT) & 1)
            pi = ((v >> _W2_IDX_SHIFT) & _W2_ELEM) - 1
            ni = (v & _W2_ELEM) - 1
            return edit_stream_pallas(pv, nv, pi, ni, t_u8,
                                      e_pad=e_pad,
                                      interpret=_INTERPRET)
        return edit_stream_packed(v, t_u8, e_pad=e_pad)
    if vis_fmt == 'wide':
        vp, vn = planes
        if _use_pallas(int(vp.shape[1])):
            pv = (vp >> _WIDE_VIS_SHIFT) & 1
            nv = (vn >> _WIDE_VIS_SHIFT) & 1
            pi = (vp & _WIDE_IDX_MASK) - 1
            ni = (vn & _WIDE_IDX_MASK) - 1
            return edit_stream_pallas(pv, nv, pi, ni, t_u8,
                                      e_pad=e_pad,
                                      interpret=_INTERPRET)
        return edit_stream_wide(vp, vn, t_u8, e_pad=e_pad)
    pv, nv, pi, ni = planes
    if _use_pallas(int(np.shape(pv)[1])):
        return edit_stream_pallas(pv, nv, pi, ni, t_u8, e_pad=e_pad,
                                  interpret=_INTERPRET)
    return edit_stream(pv, nv, pi, ni, t_u8, e_pad=e_pad)
