"""Device-path performance observatory: retrace tracking + sampled
per-phase device-time attribution.

The kernel/e2e gap (ROADMAP: 26.6M ops/s kernel vs 1.3M general
engine) can only be attacked with attribution, and the two classic
silent killers in a jit-heavy serving stack are invisible by default:

- **Recompiles.** XLA compiles one program per distinct shape
  signature; a workload that keeps crossing padding buckets (or a
  refactor that leaks a new static argument) spends seconds per tick
  in the compiler while every counter still reads "healthy".
  :func:`note_dispatch` wraps every jit entry point in a
  shape-signature registry: each dispatch records its signature, the
  first sighting counts as a compile (``device_compiles_total``), a
  new signature on an already-compiled function counts as a RETRACE
  (``device_retraces_total``), emits a ``recompile`` event into the
  flight recorder, and feeds the ``recompile_storm`` health signal
  (``GeneralDocSet._health_signals`` grades the per-quantum retrace
  delta). Per-function slices land under ``jit/<fn>/...`` scope keys,
  which the Prometheus exporter re-expresses as labels
  (``device_compiles{jit="general.fused_packed"}``); the padded row
  count of every dispatch feeds the ``device_dispatch_rows`` histogram
  — the shape-bucket distribution.

- **Unattributed device time.** Between ``doc_set.apply`` and the
  fused program, production ticks are a black box: the dispatch
  returns immediately (JAX async), so host timing points cannot see
  where the device time went. :func:`should_sample` +
  :func:`record_phases` promote the bench-only admit/pack/dispatch/
  device split to an always-on SAMPLED profiler: every Nth apply
  (``AUTOMERGE_TPU_PROFILE_SAMPLE``, default 16; 0 disables) fences
  with ``block_until_ready`` and records real per-phase time into the
  shared 96-bucket histogram series (``device_admit_ms`` /
  ``device_pack_ms`` / ``device_dispatch_ms`` / ``device_run_ms``;
  ``device_patch_read_ms`` closes the read side), plus a
  ``device_utilization`` gauge (device ms / wall ms of the sampled
  apply). Off-sample applies pay ONE integer check — the idle-observer
  smoke guard (``bench.py --smoke``) asserts it stays inside the
  existing ns/site budget.

Sampled ticks also emit a ``counter`` event (utilization, device
memory, retrace total) when a subscriber is attached — the Perfetto
exporter (:func:`automerge_tpu.telemetry.dump_chrome_trace`) renders
those as counter tracks alongside the per-phase device lanes.

Everything here is process-wide by design: jit caches are process
state, so the signature registry must be too (two doc sets dispatching
the same shapes share one compile). ``reset()`` exists for tests.
"""

import os
import threading

from ..utils.metrics import metrics

# Sampling cadence for the per-phase device profiler: every Nth apply
# fences and attributes; 0 disables sampling entirely. The default of
# 16 keeps the fence cost (one pipeline bubble per sample) under a few
# percent of wall clock on the 10k-doc sync bench.
SAMPLE_EVERY = int(os.environ.get('AUTOMERGE_TPU_PROFILE_SAMPLE',
                                  '16'))

_lock = threading.Lock()
_signatures = {}           # fn -> set of shape signatures seen
_tick = 0                  # dispatch counter driving the sampler


def set_sample_every(n):
    """Set the sampling cadence (0 disables). Returns the previous
    value — tests force 1 and restore."""
    global SAMPLE_EVERY
    prev = SAMPLE_EVERY
    SAMPLE_EVERY = int(n)
    return prev


def should_sample():
    """True on every ``SAMPLE_EVERY``-th call — the off-sample fast
    path is one integer add + modulo (no lock: a rare lost increment
    under thread races shifts a sample point, never corrupts)."""
    global _tick
    if SAMPLE_EVERY <= 0:
        return False
    _tick = t = _tick + 1
    return t % SAMPLE_EVERY == 0


def shape_bucket(n):
    """Next power of two >= n — the padding-style bucket used to
    signature host-side vectorized entry points (winner select,
    visible walk), whose 'retrace' analog is a new size class."""
    return 1 << max(int(n) - 1, 0).bit_length()


def note_dispatch(fn, signature, rows=None, jit=True):
    """Record one dispatch of tracked entry point ``fn`` with shape
    ``signature`` (any hashable — static args + operand shape/dtype
    tuple). For jit entries (the default), the first sighting of a
    signature is a compile (``device_compiles_total``) and a new
    signature on an already-compiled function is a retrace (counted,
    flight-recorded, feeds ``recompile_storm``). With ``jit=False``
    (the host-side vectorized view gathers, whose size-class growth
    is worth tracking but costs NO XLA compile), the signature set
    and the per-fn ``device_signatures`` gauge still grow but the
    compile/retrace totals and the storm signal are untouched.
    ``rows`` (the padded leading row count) feeds the shape-bucket
    distribution histogram. Returns True when the signature was
    new."""
    with _lock:
        seen = _signatures.get(fn)
        if seen is None:
            seen = _signatures[fn] = set()
        fresh = signature not in seen
        if fresh:
            seen.add(signature)
        n_sigs = len(seen)
    metrics.bump('device_dispatches_total')
    if rows is not None:
        metrics.observe('device_dispatch_rows', float(rows))
    if not fresh:
        return False
    metrics.set_gauge(f'jit/{fn}/device_signatures', n_sigs)
    if not jit:
        return True
    metrics.bump('device_compiles_total')
    metrics.bump(f'jit/{fn}/device_compiles')
    if n_sigs > 1:
        # beyond the first compile of fn: a RETRACE — the silent perf
        # killer this registry exists to surface
        metrics.bump('device_retraces_total')
        metrics.bump(f'jit/{fn}/device_retraces')
        if metrics.active:
            metrics.emit('recompile', fn=fn, signatures=n_sigs,
                         signature=repr(signature))
    return True


def signature_counts():
    """{fn: distinct signatures seen} — the live registry view."""
    with _lock:
        return {fn: len(sigs) for fn, sigs in _signatures.items()}


def record_phases(admit_ms, pack_ms, dispatch_ms, run_ms, wall_ms,
                  idx_ms=None):
    """Fold one SAMPLED apply's per-phase attribution into the shared
    histogram series and the utilization gauge; with a subscriber
    attached, also emit a ``counter`` event for the Perfetto counter
    tracks (utilization, device-plane bytes, retraces). ``idx_ms``
    (when the sampled apply took the incremental index-update path)
    additionally feeds ``device_idx_update_ms`` — the fused merge
    pass's fenced run time, separable from rebuild-path samples."""
    metrics.observe('device_admit_ms', admit_ms)
    metrics.observe('device_pack_ms', pack_ms)
    metrics.observe('device_dispatch_ms', dispatch_ms)
    metrics.observe('device_run_ms', run_ms)
    if idx_ms is not None:
        metrics.observe('device_idx_update_ms', idx_ms)
    util = run_ms / wall_ms if wall_ms > 0 else 0.0
    metrics.set_gauge('device_utilization', util)
    if metrics.active:
        counters = metrics.counters
        metrics.emit(
            'counter',
            device_utilization=round(util, 4),
            device_run_ms=round(run_ms, 4),
            mem_device_plane_bytes=counters.get(
                'mem_device_plane_bytes', 0),
            device_retraces_total=counters.get(
                'device_retraces_total', 0))


def retraces_total():
    """The process-wide retrace count — what the ``recompile_storm``
    health signal differentiates per serving quantum."""
    return metrics.counters.get('device_retraces_total', 0)


def reset():
    """Clear the signature registry and the sample counter (tests
    only — in production the registry mirrors the process's jit
    caches, which never forget either)."""
    global _tick
    with _lock:
        _signatures.clear()
        _tick = 0
