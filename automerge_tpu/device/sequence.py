"""RGA sequence ordering kernel: sort + pointer doubling.

TPU-native replacement for BOTH the reference's insertion-tree walk
(`insertionsAfter`/`getNext`/`getPrevious`, op_set.js:379-425 — sequential
pointer chasing per element) and its SkipList order-statistic index
(backend/skip_list.js — O(log n) per lookup, but inherently serial).

The document order of list/text elements is the depth-first traversal of
the insertion tree where each node's children sort Lamport-descending by
(elem, actor) (op_set.js:371-390). This kernel computes the positions of
ALL n elements at once in O(log n) parallel rounds:

1. **Sort** nodes by (parent, elem desc, actor desc) — children end up
   grouped per parent in priority order (one ``lexsort``).
2. **Thread the tree**: first-child and next-sibling links fall out of the
   sorted order; the DFS successor is ``first_child`` if present, else the
   next sibling of the nearest ancestor that has one. That ancestor is
   found with pointer doubling over parent links (log n gathers).
3. **List-rank** the successor chain with pointer doubling (log n gathers)
   to turn links into integer positions — the parallel prefix-sum
   replacement for the skip list's order statistics.
4. **Visibility scan**: a cumulative sum over tombstone flags maps tree
   positions to user-visible list indexes.

Everything is gathers/scatters/sorts/cumsums on static shapes — no
data-dependent control flow, so XLA compiles one fused program and the
same code vmaps across documents.

Node 0 is the virtual ``'_head'`` element; padding slots carry
``valid=False`` and sort to the end.
"""



import jax
import jax.numpy as jnp


def _ceil_log2(n):
    bits = 0
    while (1 << bits) < n:
        bits += 1
    return max(bits, 1)


def _rga_order(parent, elem, actor, visible, valid):
    n = parent.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    rounds = _ceil_log2(n) + 1

    # --- 1. sort children into (parent asc, elem desc, actor desc) ---------
    # The head (node 0) is nobody's child: bucket it with the padding so it
    # never receives sibling links of its own.
    parent_adj = jnp.where(valid & (idx != 0), parent, n)
    order = jnp.lexsort((-actor, -elem, parent_adj))  # [n] node id per sorted pos
    p_sorted = parent_adj[order]

    # --- 2. thread the tree -------------------------------------------------
    pos = jnp.arange(n, dtype=jnp.int32)
    is_seg_start = jnp.concatenate([
        jnp.array([True]), p_sorted[1:] != p_sorted[:-1]])
    # first_child[p] = first sorted node whose parent is p (-1 if none)
    first_child = jnp.full((n + 1,), -1, dtype=jnp.int32)
    first_child = first_child.at[jnp.where(is_seg_start, p_sorted, n)].set(
        jnp.where(is_seg_start, order, -1), mode='drop')
    first_child = first_child[:n]
    # next_sibling[node] = next sorted node under the same parent (-1 if none)
    same_parent_next = jnp.concatenate([
        p_sorted[1:] == p_sorted[:-1], jnp.array([False])])
    nxt_in_sort = jnp.concatenate([order[1:], jnp.array([-1], dtype=jnp.int32)])
    next_sibling = jnp.full((n,), -1, dtype=jnp.int32)
    next_sibling = next_sibling.at[order].set(
        jnp.where(same_parent_next, nxt_in_sort, -1))
    # Head and padding share a sort bucket; sever any accidental link so the
    # chain of the last list element terminates instead of entering padding.
    next_sibling = next_sibling.at[0].set(-1)

    # nearest ancestor-or-self with a next sibling (head terminates the climb)
    has_sib = next_sibling >= 0
    is_head = idx == 0
    climb = jnp.where(has_sib | is_head, idx, parent)
    for _ in range(rounds):
        climb = climb[climb]
    up = jnp.where(has_sib[climb], next_sibling[climb], -1)

    succ = jnp.where(first_child[idx] >= 0, first_child[idx], up)
    succ = jnp.where(valid, succ, -1)

    # --- 3. list-rank the successor chain (pointer doubling) ---------------
    # Work in an (n+1)-slot space where slot n is the chain terminator.
    nxt = jnp.where(succ >= 0, succ, n)
    nxt = jnp.concatenate([nxt, jnp.array([n], dtype=jnp.int32)])
    dist = jnp.where(jnp.arange(n + 1) == n, 0, 1)
    for _ in range(rounds):
        dist = dist + dist[nxt]
        nxt = nxt[nxt]
    dist = dist[:n]                       # steps from node to end of chain
    tree_pos = dist[0] - dist              # head = 0, then 1..chain_len

    # --- 4. visibility scan -------------------------------------------------
    on_chain = valid & (tree_pos > 0)      # head and padding excluded
    node_at_pos = jnp.full((n,), n - 1, dtype=jnp.int32)
    node_at_pos = node_at_pos.at[jnp.where(on_chain, tree_pos, 0)].set(
        jnp.where(on_chain, idx, 0), mode='drop')
    vis_ordered = jnp.where(on_chain[node_at_pos], visible[node_at_pos], False)
    vis_rank_ordered = jnp.cumsum(vis_ordered) - vis_ordered  # index among visible
    vis_index = vis_rank_ordered[tree_pos]
    vis_index = jnp.where(visible & on_chain, vis_index, -1)

    return {'tree_pos': tree_pos, 'vis_index': vis_index,
            'node_at_pos': node_at_pos,
            'length': jnp.sum(jnp.where(on_chain, visible, False))}


@jax.jit
def rga_order(parent, elem, actor, visible, valid):
    """Total document order of an insertion tree.

    Args:
      parent:  int32[n] parent node index per node (node 0 = '_head')
      elem:    int32[n] Lamport counter per node
      actor:   int32[n] actor rank per node
      visible: bool[n]  node currently has a value (not a tombstone)
      valid:   bool[n]  padding mask (node 0 must be valid)

    Returns dict of:
      tree_pos:    int32[n] DFS position (head = 0, elements 1..)
      vis_index:   int32[n] index among visible elements (-1 if hidden)
      node_at_pos: int32[n] inverse permutation (node id at each position)
      length:      int32    number of visible elements
    """
    return _rga_order(parent, elem, actor, visible, valid)


@jax.jit
def rga_order_batch(parent, elem, actor, visible, valid):
    """vmap over a leading document axis."""
    return jax.vmap(_rga_order)(parent, elem, actor, visible, valid)
