"""RGA sequence ordering kernel: sort + pointer doubling.

TPU-native replacement for BOTH the reference's insertion-tree walk
(`insertionsAfter`/`getNext`/`getPrevious`, op_set.js:379-425 — sequential
pointer chasing per element) and its SkipList order-statistic index
(backend/skip_list.js — O(log n) per lookup, but inherently serial).

The document order of list/text elements is the depth-first traversal of
the insertion tree where each node's children sort Lamport-descending by
(elem, actor) (op_set.js:371-390). This kernel computes the positions of
ALL n elements at once in O(log n) parallel rounds:

1. **Sort** nodes by (parent, elem desc, actor desc) — children end up
   grouped per parent in priority order (one ``lexsort``).
2. **Thread the tree**: first-child and next-sibling links fall out of the
   sorted order; the DFS successor is ``first_child`` if present, else the
   next sibling of the nearest ancestor that has one. That ancestor is
   found with pointer doubling over parent links (log n gathers).
3. **List-rank** the successor chain with pointer doubling (log n gathers)
   to turn links into integer positions — the parallel prefix-sum
   replacement for the skip list's order statistics.
4. **Visibility scan**: a cumulative sum over tombstone flags maps tree
   positions to user-visible list indexes.

Everything is gathers/scatters/sorts/cumsums on static shapes — no
data-dependent control flow, so XLA compiles one fused program and the
same code vmaps across documents.

Node 0 is the virtual ``'_head'`` element; padding slots carry
``valid=False`` and sort to the end.
"""



import jax
import jax.numpy as jnp


def _ceil_log2(n):
    bits = 0
    while (1 << bits) < n:
        bits += 1
    return max(bits, 1)


def _thread_and_rank(parent, parent_adj, order, valid):
    """Tree threading + list ranking — the shared middle of
    :func:`_rga_order` (steps 2-3) and :func:`_rga_delta_order`: from
    a child-sorted order, derive first-child / next-sibling links,
    resolve each node's DFS successor by pointer-doubling the ancestor
    climb, then list-rank the successor chain. Returns int32[n]
    ``tree_pos`` (head = 0, then 1..chain_len; padding carries
    garbage)."""
    n = parent.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    rounds = _ceil_log2(n) + 1
    p_sorted = parent_adj[order]

    # --- thread the tree ----------------------------------------------------
    is_seg_start = jnp.concatenate([
        jnp.array([True]), p_sorted[1:] != p_sorted[:-1]])
    # first_child[p] = first sorted node whose parent is p (-1 if none)
    first_child = jnp.full((n + 1,), -1, dtype=jnp.int32)
    first_child = first_child.at[jnp.where(is_seg_start, p_sorted, n)].set(
        jnp.where(is_seg_start, order, -1), mode='drop')
    first_child = first_child[:n]
    # next_sibling[node] = next sorted node under the same parent (-1 if none)
    same_parent_next = jnp.concatenate([
        p_sorted[1:] == p_sorted[:-1], jnp.array([False])])
    nxt_in_sort = jnp.concatenate([order[1:], jnp.array([-1], dtype=jnp.int32)])
    next_sibling = jnp.full((n,), -1, dtype=jnp.int32)
    next_sibling = next_sibling.at[order].set(
        jnp.where(same_parent_next, nxt_in_sort, -1))
    # Head and padding share a sort bucket; sever any accidental link so the
    # chain of the last list element terminates instead of entering padding.
    next_sibling = next_sibling.at[0].set(-1)

    # nearest ancestor-or-self with a next sibling (head terminates the climb)
    has_sib = next_sibling >= 0
    is_head = idx == 0
    climb = jnp.where(has_sib | is_head, idx, parent)
    for _ in range(rounds):
        climb = climb[climb]
    up = jnp.where(has_sib[climb], next_sibling[climb], -1)

    succ = jnp.where(first_child[idx] >= 0, first_child[idx], up)
    succ = jnp.where(valid, succ, -1)

    # --- list-rank the successor chain (pointer doubling) -------------------
    # Work in an (n+1)-slot space where slot n is the chain terminator.
    nxt = jnp.where(succ >= 0, succ, n)
    nxt = jnp.concatenate([nxt, jnp.array([n], dtype=jnp.int32)])
    dist = jnp.where(jnp.arange(n + 1) == n, 0, 1)
    for _ in range(rounds):
        dist = dist + dist[nxt]
        nxt = nxt[nxt]
    dist = dist[:n]                       # steps from node to end of chain
    return (dist[0] - dist).astype(jnp.int32)


def _rga_order(parent, elem, actor, visible, valid):
    n = parent.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)

    # --- 1. sort children into (parent asc, elem desc, actor desc) ---------
    # The head (node 0) is nobody's child: bucket it with the padding so it
    # never receives sibling links of its own.
    parent_adj = jnp.where(valid & (idx != 0), parent, n)
    order = jnp.lexsort((-actor, -elem, parent_adj))  # [n] node id per sorted pos

    # --- 2-3. thread + list-rank (shared with the delta orderer) -----------
    tree_pos = _thread_and_rank(parent, parent_adj, order, valid)

    # --- 4. visibility scan -------------------------------------------------
    on_chain = valid & (tree_pos > 0)      # head and padding excluded
    node_at_pos = jnp.full((n,), n - 1, dtype=jnp.int32)
    node_at_pos = node_at_pos.at[jnp.where(on_chain, tree_pos, 0)].set(
        jnp.where(on_chain, idx, 0), mode='drop')
    vis_ordered = jnp.where(on_chain[node_at_pos], visible[node_at_pos], False)
    vis_rank_ordered = jnp.cumsum(vis_ordered) - vis_ordered  # index among visible
    vis_index = vis_rank_ordered[tree_pos]
    vis_index = jnp.where(visible & on_chain, vis_index, -1)

    return {'tree_pos': tree_pos, 'vis_index': vis_index,
            'node_at_pos': node_at_pos,
            'length': jnp.sum(jnp.where(on_chain, visible, False))}


def _mxu_gather2(val_a, val_b, idx, m):
    """Batched gather of TWO [K, m] f32 planes by one [K, m] int32 index
    plane, as a one-hot matmul — the pointer-doubling gathers ride the
    MXU (systolic array) instead of the scalar gather path, which is the
    TPU bottleneck of the doubling loops (~6 ms per [2048, 128] gather
    round measured through XLA's native gather).

    When every gathered value AND every index is <= 256 the one-hot and
    the operands are exact in bfloat16 (8-bit mantissa: all integers up
    to 2^8), so the matmul runs at native MXU width with half the HBM
    traffic for the [K, m, m] one-hot plane; otherwise f32 operands at
    Precision.HIGHEST (default TPU matmul precision rounds f32 inputs
    to bf16, which corrupts node indexes > 256 — r4 advisor, measured
    3992/4000 wrong orderings at m=500)."""
    if m <= 257:  # values/indexes <= 256: exact in bf16 (2^8)
        onehot = (idx[:, :, None] ==
                  jnp.arange(m, dtype=jnp.int32)[None, None, :]) \
            .astype(jnp.bfloat16)
        both = jnp.stack([val_a, val_b], axis=-1).astype(jnp.bfloat16)
        g = jnp.einsum('jik,jkc->jic', onehot, both,
                       preferred_element_type=jnp.float32)
        return g[..., 0], g[..., 1]
    onehot = (idx[:, :, None] ==
              jnp.arange(m, dtype=jnp.int32)[None, None, :]) \
        .astype(jnp.float32)
    both = jnp.stack([val_a, val_b], axis=-1)         # [K, m, 2]
    g = jnp.einsum('jik,jkc->jic', onehot, both,
                   preferred_element_type=jnp.float32,
                   precision=jax.lax.Precision.HIGHEST)
    return g[..., 0], g[..., 1]


def _rga_order_mxu(parent, elem, actor, visible, valid):
    """Batched [K, m] RGA ordering with the two pointer-doubling loops
    expressed as one-hot MXU matmuls (exact: all values < 2^24, f32).

    Bit-identical to ``vmap(_rga_order)`` — the child sort, tree
    threading and visibility scan are the same program; only the
    dependent-gather rounds change execution engine. Intended for the
    common small-tree regime (m <= ~256) where the [K, m, m] one-hot
    traffic is cheap; :func:`_rga_order_batched` picks the variant by
    static shape."""
    K, n = parent.shape
    visible = visible.astype(bool)       # uint8 0/1 planes are welcome,
    valid = valid.astype(bool)           # but cumsums must see bool
    idx = jnp.arange(n, dtype=jnp.int32)[None, :]
    rowi = jnp.arange(K, dtype=jnp.int32)[:, None]
    rounds = _ceil_log2(n) + 1

    parent_adj = jnp.where(valid & (idx != 0), parent, n)
    order = jax.vmap(lambda a, e, p: jnp.lexsort((-a, -e, p)))(
        actor, elem, parent_adj)
    p_sorted = jnp.take_along_axis(parent_adj, order, axis=1)

    is_seg_start = jnp.concatenate(
        [jnp.ones((K, 1), bool), p_sorted[:, 1:] != p_sorted[:, :-1]],
        axis=1)
    first_child = jnp.full((K, n + 1), -1, jnp.int32)
    first_child = first_child.at[
        rowi, jnp.where(is_seg_start, p_sorted, n)].set(
        jnp.where(is_seg_start, order, -1), mode='drop')
    first_child = first_child[:, :n]
    same_parent_next = jnp.concatenate(
        [p_sorted[:, 1:] == p_sorted[:, :-1], jnp.zeros((K, 1), bool)],
        axis=1)
    nxt_in_sort = jnp.concatenate(
        [order[:, 1:], jnp.full((K, 1), -1, jnp.int32)], axis=1)
    next_sibling = jnp.full((K, n), -1, jnp.int32)
    next_sibling = next_sibling.at[rowi, order].set(
        jnp.where(same_parent_next, nxt_in_sort, -1))
    next_sibling = next_sibling.at[:, 0].set(-1)

    has_sib = next_sibling >= 0
    is_head = idx == 0
    climb = jnp.where(has_sib | is_head, idx, parent) \
        .astype(jnp.float32)
    for _ in range(rounds):
        climb, _ = _mxu_gather2(climb, climb, climb.astype(jnp.int32), n)
    # the two `up` lookups ride the same one-hot matmul as the rounds
    # (a take_along_axis pair costs ~2x one fused gather2 at this shape)
    sibv, sibf = _mxu_gather2(next_sibling.astype(jnp.float32),
                              has_sib.astype(jnp.float32),
                              climb.astype(jnp.int32), n)
    up = jnp.where(sibf > 0.5, sibv.astype(jnp.int32), -1)
    succ = jnp.where(first_child >= 0, first_child, up)
    succ = jnp.where(valid, succ, -1)

    nxt = jnp.where(succ >= 0, succ, n)
    nxt = jnp.concatenate([nxt, jnp.full((K, 1), n, jnp.int32)], axis=1)
    dist = jnp.broadcast_to(
        jnp.where(jnp.arange(n + 1)[None, :] == n, 0., 1.),
        (K, n + 1)).astype(jnp.float32)
    nxt_f = nxt.astype(jnp.float32)
    for _ in range(rounds):
        d_at_nxt, nxt_f = _mxu_gather2(dist, nxt_f, nxt, n + 1)
        dist = dist + d_at_nxt
        nxt = nxt_f.astype(jnp.int32)
    dist = dist[:, :n].astype(jnp.int32)
    tree_pos = dist[:, :1] - dist

    on_chain = valid & (tree_pos > 0)
    node_at_pos = jnp.full((K, n), n - 1, jnp.int32)
    node_at_pos = node_at_pos.at[
        rowi, jnp.where(on_chain, tree_pos, 0)].set(
        jnp.where(on_chain, jnp.broadcast_to(idx, (K, n)), 0),
        mode='drop')
    # visibility in position order SCATTERS directly (off-chain rows
    # contribute False via max), and the rank maps back through one
    # fused gather2 — replacing three take_along_axis passes
    vis_ordered = jnp.zeros((K, n), bool).at[
        rowi, jnp.where(on_chain, tree_pos, 0)].max(
        visible & on_chain, mode='drop')
    vis_rank = (jnp.cumsum(vis_ordered, axis=1) - vis_ordered) \
        .astype(jnp.float32)
    vis_index, _ = _mxu_gather2(vis_rank, vis_rank, tree_pos, n)
    vis_index = vis_index.astype(jnp.int32)
    vis_index = jnp.where(visible & on_chain, vis_index, -1)
    return {'tree_pos': tree_pos, 'vis_index': vis_index,
            'node_at_pos': node_at_pos,
            'length': jnp.sum(jnp.where(on_chain, visible, False),
                              axis=1).astype(jnp.int32)}


def _rga_delta_order(parent, anchor, elem, actor, valid):
    """DFS order of ONE tick's delta forest — the small companion of
    :func:`_rga_order` behind the incremental index update (Jiffy-style
    batch insert: the whole tick's new nodes order among THEMSELVES
    here, then splice into the persistent index with one prefix-sum
    merge pass — see ``general._fused_general_incr``).

    Slot 0 is a virtual head standing in for the ENTIRE existing tree;
    a delta node whose parent already existed before this tick (a
    "delta root") is a child of that head, carrying the OLD tree
    position of its anchor (its real parent) as ``anchor``. Head
    children therefore sort by (anchor asc, elem desc, actor desc) —
    groups land in anchor order, each group in RGA priority order —
    while children of real delta parents sort by the ordinary RGA
    (elem desc, actor desc) key (their ``anchor`` must be 0).

    Only valid under the FRONT-INSERT precondition the caller checks on
    host: every delta root's elem exceeds every pre-existing elem of
    its object, so the root precedes all existing children of its
    parent and the group splices immediately after the anchor.

    Returns ``tree_pos`` int32[n]: 0 for the virtual head, 1..count for
    delta nodes in final relative order (padding rows carry garbage).
    """
    n = parent.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)

    parent_adj = jnp.where(valid & (idx != 0), parent, n)
    anchor_k = jnp.where(parent_adj == 0, anchor, 0)
    order = jnp.lexsort((-actor, -elem, anchor_k, parent_adj))

    # tree threading + list ranking: identical to _rga_order steps 2-3
    return _thread_and_rank(parent, parent_adj, order, valid)


def _rga_delta_order_batched(parent, anchor, elem, actor, valid):
    """Batched [K, dm] delta ordering (vmapped gather variant — delta
    planes are block-delta sized, so the doubling rounds are cheap by
    construction; no MXU pick needed)."""
    return jax.vmap(_rga_delta_order)(parent, anchor, elem, actor,
                                      valid)


def _rga_order_batched(parent, elem, actor, visible, valid):
    """Batched RGA over [K, m] job planes: MXU one-hot doubling when the
    one-hot plane is small enough to be cheap traffic, vmapped gather
    doubling otherwise. Shapes are static under jit, so the pick is a
    plain Python branch; both variants are integer-exact equal.

    The m <= 512 bound is the MEASURED crossover, not a limitation:
    every one-hot round costs O(m^2) VPU compares, and past m ~= 2048
    a single one-hot build exceeds a third of the whole gather
    pipeline (see pallas_sequence module docstring for the numbers).
    Large single trees (long text documents) are gather-scheduled by
    design."""
    K, m = parent.shape
    if m <= 512 and K * m * m <= (1 << 28):
        return _rga_order_mxu(parent, elem, actor, visible, valid)
    return jax.vmap(_rga_order)(parent, elem, actor, visible, valid)


@jax.jit
def rga_order(parent, elem, actor, visible, valid):
    """Total document order of an insertion tree.

    Args:
      parent:  int32[n] parent node index per node (node 0 = '_head')
      elem:    int32[n] Lamport counter per node
      actor:   int32[n] actor rank per node
      visible: bool[n]  node currently has a value (not a tombstone)
      valid:   bool[n]  padding mask (node 0 must be valid)

    Returns dict of:
      tree_pos:    int32[n] DFS position (head = 0, elements 1..)
      vis_index:   int32[n] index among visible elements (-1 if hidden)
      node_at_pos: int32[n] inverse permutation (node id at each position)
      length:      int32    number of visible elements
    """
    return _rga_order(parent, elem, actor, visible, valid)


@jax.jit
def rga_order_batch(parent, elem, actor, visible, valid):
    """Batched ordering over a leading document axis (auto-picks the
    MXU one-hot variant for small trees; bit-identical either way)."""
    return _rga_order_batched(parent, elem, actor, visible, valid)
