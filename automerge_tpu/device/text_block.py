"""Bulk text replay: columnar editing traces resolved in one device call.

The reference replays an editing trace one keystroke at a time through
the skip list (~k ops/s); the per-document device backend batches the
protocol work but still stages each op dict in Python. This module is
the long-context bulk path: a :class:`TextBlock` encodes a whole text
editing history as columns — elemIds are STRUCTURED (actor index, elem
counter) pairs, so there is no string interning at all, and values are
unicode codepoints — and :func:`replay_text_block` turns it into the
final document text with vectorized numpy staging plus one RGA kernel
call (:mod:`.sequence`).

Scope (checked): single text object per document, changes with empty
deps — i.e. independent per-actor chains, every cross-actor pair
concurrent. That is exactly the automerge-perf trace shape and the
"N authors type concurrently" merge; histories with cross-actor deps
take the per-document backend, which shares the same wire format.

CRDT semantics under that scope, vectorized:

* same-actor ops on one element are causally ordered by seq — the
  element's fate per actor is its LATEST op (scatter-max of seq);
* cross-actor ops are concurrent — an element is visible iff ANY
  actor's latest op on it is a set (concurrent assignment beats
  delete, op_set.js:180-219), and the winning value comes from the
  highest such actor (rank order = string order);
* ordering is the RGA insertion-tree traversal (sort + pointer
  doubling, replacing 180k sequential skip-list edits with one call).
"""

import numpy as np
import jax.numpy as jnp

from ..common import ROOT_ID
from .sequence import rga_order
from .engine import as_options


class TextBlock:
    """One document's text-editing history as columns.

    Change columns (length C): ``actor`` (index into ``actors``),
    ``seq``. Op columns (length N, CSR via ``op_ptr``): ``kind``
    (0 ins / 1 set / 2 del), ``ref_actor``/``ref_elem`` — the referenced
    elemId as a structured pair (ins: the parent, -1/0 for ``'_head'``;
    set/del: the target), ``elem`` (ins: the new counter), ``value``
    (set: a unicode codepoint).
    """

    INS, SET, DEL = 0, 1, 2

    __slots__ = ('actors', 'obj', 'actor', 'seq', 'op_ptr', 'kind',
                 'ref_actor', 'ref_elem', 'elem', 'value', 'root_key',
                 'creator', 'linker')

    def __init__(self, actors, obj, actor, seq, op_ptr, kind, ref_actor,
                 ref_elem, elem, value, root_key=None, creator=None,
                 linker=None):
        self.actors = actors
        self.obj = obj
        self.actor = actor
        self.seq = seq
        self.op_ptr = op_ptr
        self.kind = kind
        self.ref_actor = ref_actor
        self.ref_elem = ref_elem
        self.elem = elem
        self.value = value
        self.root_key = root_key       # key linking the text at the root
        self.creator = creator         # (actor name, seq) of makeText
        self.linker = linker           # (actor name, seq) of the root link

    @property
    def n_changes(self):
        return len(self.actor)

    @property
    def n_ops(self):
        return len(self.kind)

    @classmethod
    def from_changes(cls, changes):
        """Encode wire changes for ONE text document (the compatibility
        edge, O(ops) Python). The first change must create the text
        object (makeText [+ link]); deps must be empty (see module
        scope)."""
        actors, actor_of = [], {}

        def intern(a):
            i = actor_of.get(a)
            if i is None:
                i = len(actors)
                actor_of[a] = i
                actors.append(a)
            return i

        def parse_elem_id(eid):
            if eid == '_head':
                return -1, 0
            a, _, e = eid.rpartition(':')
            return intern(a), int(e)

        obj = None
        root_key = creator = linker = None
        actor, seq = [], []
        op_ptr = [0]
        kind, ref_a, ref_e, elem, value = [], [], [], [], []
        for change in changes:
            if change['deps']:
                raise ValueError(
                    'TextBlock requires empty deps (independent actor '
                    'chains); use the per-document backend otherwise')
            actor.append(intern(change['actor']))
            seq.append(change['seq'])
            for op in change['ops']:
                action = op['action']
                if action == 'makeText':
                    if obj is not None:
                        raise ValueError('multiple text objects in trace')
                    obj = op['obj']
                    creator = (change['actor'], change['seq'])
                    continue
                if action == 'link' and op['obj'] == ROOT_ID:
                    root_key = op['key']          # structural root link
                    linker = (change['actor'], change['seq'])
                    continue
                if obj is None or op['obj'] != obj or action == 'link':
                    raise ValueError(
                        'TextBlock holds exactly one text object of '
                        'plain characters'
                        if action != 'link' else
                        'TextBlock does not support object links inside '
                        'the text; use the per-document backend')
                if action == 'ins':
                    ra, re = parse_elem_id(op['key'])
                    kind.append(cls.INS)
                    ref_a.append(ra)
                    ref_e.append(re)
                    elem.append(op['elem'])
                    value.append(0)
                elif action in ('set', 'del'):
                    ra, re = parse_elem_id(op['key'])
                    if ra < 0:
                        raise ValueError('assignment to _head')
                    kind.append(cls.SET if action == 'set' else cls.DEL)
                    ref_a.append(ra)
                    ref_e.append(re)
                    elem.append(0)
                    v = op.get('value') if action == 'set' else None
                    if action == 'set' and (not isinstance(v, str)
                                            or len(v) != 1):
                        raise ValueError(
                            'TextBlock values are single characters')
                    value.append(ord(v) if action == 'set' else 0)
                else:
                    raise ValueError(f'unsupported op {action!r} in '
                                     'a text trace')
            op_ptr.append(len(kind))

        if obj is None:
            raise ValueError('trace does not create a text object')
        return cls(actors, obj,
                   np.asarray(actor, np.int32), np.asarray(seq, np.int32),
                   np.asarray(op_ptr, np.int32), np.asarray(kind, np.int8),
                   np.asarray(ref_a, np.int32), np.asarray(ref_e, np.int32),
                   np.asarray(elem, np.int32), np.asarray(value, np.int32),
                   root_key=root_key, creator=creator, linker=linker)


class TextReplay:
    """Result of one bulk replay: the ordered document."""

    __slots__ = ('block', 'nodes_actor', 'nodes_elem', 'visible',
                 'codepoint', 'order', 'n_nodes', 'parent', 'win_actor',
                 'win_seq', 'survivors')

    def __init__(self, block, nodes_actor, nodes_elem, visible, codepoint,
                 order, n_nodes, parent, win_actor, win_seq, survivors):
        self.block = block
        self.nodes_actor = nodes_actor   # per node (incl. head): actor idx
        self.nodes_elem = nodes_elem
        self.visible = visible
        self.codepoint = codepoint
        self.order = order               # rga_order outputs (padded)
        self.n_nodes = n_nodes
        self.parent = parent
        self.win_actor = win_actor       # per node: winning actor idx / -1
        self.win_seq = win_seq
        self.survivors = survivors       # (node, actor, seq, cp) alive sets

    def text(self):
        """The final visible text (fetches only vis_index — the other
        kernel outputs stay on device unless asked for)."""
        vi = np.asarray(self.order['vis_index'])[:self.n_nodes]
        vis_nodes = np.flatnonzero(vi >= 0)
        out = np.zeros(len(vis_nodes), np.uint32)
        out[vi[vis_nodes]] = self.codepoint[vis_nodes]
        return ''.join(map(chr, out.tolist()))

    def elem_ids(self):
        """Visible elemIds in document order (the order-statistic index)."""
        vi = np.asarray(self.order['vis_index'])[:self.n_nodes]
        vis_nodes = np.flatnonzero(vi >= 0)
        ordered = np.zeros(len(vis_nodes), np.int64)
        ordered[vi[vis_nodes]] = vis_nodes
        actors = self.block.actors
        return [f'{actors[self.nodes_actor[n]]}:{self.nodes_elem[n]}'
                for n in ordered]

    def to_state(self):
        """A live :class:`~automerge_tpu.device.backend.DeviceBackendState`
        continuing from this replay — bulk-load a 180k-op history in one
        device call, then keep editing through the normal change/patch
        protocol. Change bodies are not retained (same contract as a
        packed-snapshot resume: the log is truncated; peers behind this
        point need the history or a snapshot)."""
        from .backend import DeviceBackendState, _ObjRecord
        block = self.block
        if block.root_key is None or block.creator is None:
            raise ValueError(
                'block lacks the creation/link ops (built without the '
                'creating change); cannot build a document state')
        actors = block.actors
        state = DeviceBackendState()

        rec = _ObjRecord('makeText')
        eids = [f'{actors[self.nodes_actor[i]]}:{self.nodes_elem[i]}'
                for i in range(1, self.n_nodes)]
        rec.nodes = ['_head'] + eids
        rec.node_of = {e: i for i, e in enumerate(rec.nodes)}
        rec.node_parent = self.parent.tolist()
        rec.node_elem = self.nodes_elem.tolist()
        rec.node_actor = ['' if i == 0 else actors[self.nodes_actor[i]]
                          for i in range(self.n_nodes)]
        rec.elem_ids = self.elem_ids()
        state.objects[block.obj] = rec
        state._owned.add(block.obj)

        # ALL surviving entries per visible node (winner first, actor
        # string descending — concurrent sets stay as conflicts)
        s_node, s_actor, s_seq, s_cp = self.survivors
        per_node = {}
        for n, a, s, cp in zip(s_node.tolist(), s_actor.tolist(),
                               s_seq.tolist(), s_cp.tolist()):
            per_node.setdefault(n, []).append(
                {'actor': actors[a], 'seq': s,
                 'all_deps': {actors[a]: s - 1} if s > 1 else {},
                 'action': 'set', 'value': chr(cp)})
        for n, entries in per_node.items():
            entries.sort(key=lambda e: e['actor'], reverse=True)
            state.fields[(block.obj, rec.nodes[n])] = tuple(entries)

        # root link: the op identity of the LINK change, not makeText
        l_actor, l_seq = block.linker if block.linker else block.creator
        rec.inbound = [(ROOT_ID, block.root_key)]
        state.fields[(ROOT_ID, block.root_key)] = (
            {'actor': l_actor, 'seq': l_seq,
             'all_deps': {l_actor: l_seq - 1} if l_seq > 1 else {},
             'action': 'link', 'value': block.obj},)

        # clocks + body-less change log (snapshot-resume contract)
        heads = {}
        for i in range(block.n_changes):
            a = actors[block.actor[i]]
            heads[a] = max(heads.get(a, 0), int(block.seq[i]))
        for who in (block.creator, block.linker):
            if who:
                heads[who[0]] = max(heads.get(who[0], 0), who[1])
        state.clock = dict(heads)
        state.deps = dict(heads)
        for a, top in heads.items():
            state.states[a] = [
                {'change': None, 'all_deps': {a: s - 1} if s > 1 else {}}
                for s in range(1, top + 1)]
            state.state_lens[a] = top
        state.log_truncated = True
        state.rebuild_link_fields()
        return state

    def to_doc(self, actor_id=None):
        """A frontend document over :meth:`to_state` (ready to edit)."""
        from .. import frontend as Frontend
        from . import backend as DeviceBackend
        state = self.to_state()
        options = {'backend': DeviceBackend}
        if actor_id is not None:
            options['actorId'] = actor_id
        doc = Frontend.init(options)
        patch = DeviceBackend.get_patch(state)
        patch['state'] = state
        return Frontend.apply_patch(doc, patch)


def replay_text_block(block, options=None):
    """Resolve a whole text history: vectorized staging, one RGA call.

    Validates per-actor seq chains (contiguous from 1 — causal delivery
    for independent chains), derives element visibility and winners with
    scatter-maxes, and orders the insertion tree on device.
    """
    opts = as_options(options)
    A = len(block.actors)
    if A == 0:
        raise ValueError('empty block')
    # per-actor chains must be contiguous from 1 (causally complete)
    order = np.lexsort((block.seq, block.actor))
    a_s, s_s = block.actor[order], block.seq[order]
    starts = np.concatenate([[True], a_s[1:] != a_s[:-1]])
    run = s_s - np.concatenate([[0], s_s[:-1]])
    ok = np.where(starts, s_s == 1, run == 1)
    if not ok.all():
        bad = int(np.flatnonzero(~ok)[0])
        raise ValueError(
            f'actor {block.actors[a_s[bad]]} has a non-contiguous seq '
            f'chain at seq {int(s_s[bad])}')

    # ---- node table: one node per ins op, in op order; node 0 = head ----
    is_ins = block.kind == TextBlock.INS
    ins_rows = np.flatnonzero(is_ins)
    n_nodes = len(ins_rows) + 1
    op_change = np.repeat(np.arange(block.n_changes, dtype=np.int64),
                          np.diff(block.op_ptr))
    nodes_actor = np.concatenate(
        [[0], block.actor[op_change[ins_rows]]]).astype(np.int32)
    nodes_elem = np.concatenate([[0], block.elem[ins_rows]]) \
        .astype(np.int32)

    # elemId (actor, elem) -> node id, via sorted composite keys; the
    # stride must cover REFERENCED counters too, or a dangling reference
    # could alias another actor's real node instead of raising
    max_elem = int(nodes_elem.max()) if n_nodes > 1 else 0
    if block.n_ops:
        max_elem = max(max_elem, int(block.ref_elem.max()))
    stride = np.int64(max_elem + 2)
    node_key = nodes_actor.astype(np.int64) * stride + nodes_elem
    node_key[0] = -1                                  # head sentinel
    key_order = np.argsort(node_key, kind='stable')
    sorted_keys = node_key[key_order]
    if len(sorted_keys) > 1 and (np.diff(sorted_keys) == 0).any():
        raise ValueError('duplicate list element ID in trace')

    def node_of(ra, re):
        probe = np.where(ra < 0, -1, ra.astype(np.int64) * stride + re)
        pos = np.searchsorted(sorted_keys, probe)
        pos = np.minimum(pos, n_nodes - 1)
        found = sorted_keys[pos] == probe
        if not found.all():
            raise ValueError('reference to unknown list element')
        return key_order[pos].astype(np.int32)

    parent = np.zeros(n_nodes, np.int32)
    parent[1:] = node_of(block.ref_actor[ins_rows],
                         block.ref_elem[ins_rows])

    # ---- element fate: latest op per (node, actor); visible iff any
    # actor's latest is a set; winner = highest such actor ----
    as_rows = np.flatnonzero(block.kind != TextBlock.INS)
    tgt_node = node_of(block.ref_actor[as_rows], block.ref_elem[as_rows])
    op_actor = block.actor[op_change[as_rows]]
    op_seq = block.seq[op_change[as_rows]]
    is_set = (block.kind[as_rows] == TextBlock.SET).astype(np.int64)
    # packed per (node, actor): (seq << 1 | is_set); scatter-max picks
    # the causally-latest op, ties impossible (one op per field per seq
    # in a well-formed trace; the frontend dedupes same-key ops)
    cell = tgt_node.astype(np.int64) * A + op_actor
    packed = (op_seq.astype(np.int64) << 1) | is_set
    fate = np.zeros(n_nodes * A, np.int64)
    np.maximum.at(fate, cell, packed)
    fate = fate.reshape(n_nodes, A)
    set_alive = (fate != 0) & ((fate & 1) == 1)        # latest op is a set
    visible = set_alive.any(axis=1)
    visible[0] = False

    # winning codepoint: the set from the highest alive actor (by STRING
    # rank, op_set.js:211) at its latest seq — recovered by matching
    # (node, actor, seq) against the set rows
    str_rank = np.argsort(np.argsort(np.asarray(block.actors,
                                                dtype=object)))
    by_rank = np.argsort(np.asarray(block.actors, dtype=object))
    rank_alive = np.where(set_alive, str_rank[None, :], -1)
    win_rank = rank_alive.max(axis=1)
    win_actor = np.where(visible, by_rank[np.maximum(win_rank, 0)], -1)
    win_seq = np.where(visible,
                       fate[np.arange(n_nodes),
                            np.maximum(win_actor, 0)] >> 1, 0)
    codepoint = np.zeros(n_nodes, np.int32)
    set_rows = as_rows[is_set.astype(bool)]
    sn = node_of(block.ref_actor[set_rows], block.ref_elem[set_rows])
    sa = block.actor[op_change[set_rows]]
    ss = block.seq[op_change[set_rows]]
    mine = (win_actor[sn] == sa) & (win_seq[sn] == ss)
    codepoint[sn[mine]] = block.value[set_rows[mine]]
    # ALL surviving set entries (each alive actor's latest set) — the
    # conflict metadata a continued document state must carry
    alive_row = set_alive[sn, sa] & ((fate[sn, sa] >> 1) == ss)
    survivors = (sn[alive_row], sa[alive_row], ss[alive_row],
                 block.value[set_rows[alive_row]])

    # ---- one device call: RGA order over the whole tree ----
    n_pad = opts.pad_nodes(n_nodes)

    def pad(x, fill=0):
        out = np.full(n_pad, fill, x.dtype)
        out[:len(x)] = x
        return out
    # actor RANKS must follow string order
    rank_col = pad(str_rank[nodes_actor].astype(np.int32))
    valid = np.zeros(n_pad, bool)
    valid[:n_nodes] = True
    out = rga_order(jnp.asarray(pad(parent)), jnp.asarray(pad(nodes_elem)),
                    jnp.asarray(rank_col), jnp.asarray(pad(visible)),
                    jnp.asarray(valid))
    # outputs stay device-resident; consumers fetch what they use
    return TextReplay(block, nodes_actor, nodes_elem, visible, codepoint,
                      out, n_nodes, parent, win_actor, win_seq, survivors)
