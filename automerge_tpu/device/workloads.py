"""Synthetic device-kernel workload generators (shared by bench + tests).

The merge kernels assume causal delivery, which the generator encodes as
an invariant: each op's clock row covers exactly its own actor's prior ops
(``clock[i, actor_i] = seq_i - 1``), optionally plus a causally-consistent
prefix of other actors' ops. Keeping the construction in one place keeps
bench.py and the Pallas differential tests on the same op distribution.
"""

import numpy as np


def gen_docset_workload(n_docs=10240, n_ops=128, n_actors=8, n_keys=32,
                        seed=0, del_p=0.05, invalid_p=0.0, cross_clock=False):
    """A DocSet batch: per doc, ``n_ops`` concurrent 'set' ops from
    ``n_actors`` actors spread over ``n_keys`` root fields.

    Each actor's ops are sequential for itself and (by default) fully
    concurrent across actors — the worst case for conflict resolution.
    With ``cross_clock`` some ops additionally cover a prefix of other
    actors' ops, exercising supersession.

    Returns (seg_id, actor, seq, clock, is_del, valid) numpy arrays with
    shapes [D,N] / [D,N,A].
    """
    rng = np.random.default_rng(seed)
    seg_id = rng.integers(0, n_keys, size=(n_docs, n_ops)).astype(np.int32)
    actor = rng.integers(0, n_actors, size=(n_docs, n_ops)).astype(np.int32)
    # validity is drawn BEFORE seq/clock construction so that both count
    # only ops that exist — clocks never reference masked-out (phantom) ops
    valid = rng.random((n_docs, n_ops)) >= invalid_p
    # seq numbers: per (doc, actor) running count of VALID ops in op order
    seq = np.ones((n_docs, n_ops), dtype=np.int32)
    for a in range(n_actors):
        mask = actor == a
        running = np.cumsum(mask & valid, axis=1)
        seq[mask] = running[mask]
    seq = np.maximum(seq, 1)  # invalid ops before an actor's first valid op
    clock = np.zeros((n_docs, n_ops, n_actors), dtype=np.int32)
    d_idx, o_idx = np.indices((n_docs, n_ops))
    if cross_clock:
        # Causally valid cross-actor coverage via knowledge frontiers: op i
        # (column o) covers every valid op in columns < f_i, with f_i drawn
        # in [f_prev_own, o] (monotone per actor). Monotonicity makes the
        # clocks transitively closed — if i covers j then f_i > o_j >= f_j,
        # so i covers everything j covers — and counts tally only valid
        # ops, so no phantom dependencies exist even with invalid_p > 0.
        onehot = np.zeros((n_docs, n_ops, n_actors), dtype=np.int32)
        onehot[d_idx, o_idx, actor] = valid.astype(np.int32)
        # counts[d, o, b] = number of valid b-ops in columns < o
        counts = np.zeros((n_docs, n_ops + 1, n_actors), dtype=np.int32)
        counts[:, 1:] = np.cumsum(onehot, axis=1)
        f_prev = np.zeros((n_docs, n_actors), dtype=np.int64)
        docs = np.arange(n_docs)
        for o in range(n_ops):
            a = actor[:, o]
            lo = f_prev[docs, a]
            f = lo + (rng.random(n_docs) * (o - lo + 1)).astype(np.int64)
            f_prev[docs, a] = f
            clock[:, o, :] = counts[docs, f, :]
    clock[d_idx, o_idx, actor] = seq - 1
    is_del = rng.random((n_docs, n_ops)) < del_p
    return seg_id, actor, seq, clock, is_del, valid


def gen_block_workload(n_docs=10240, n_actors=10, ops_per_change=10,
                       n_keys=40, seed=0, del_p=0.0, seq0=1):
    """The BASELINE config-5 workload as wire changes: a ChangeBlock with
    one change per (doc, actor), all cross-actor concurrent (seq =
    ``seq0``, empty deps), each change carrying ``ops_per_change`` set
    ops on distinct root keys. ``seq0`` > 1 produces the k-th block of a
    STREAM of such batches (each actor's chain advancing one seq per
    block) — apply blocks seq0=1..k in order.

    Total ops = n_docs * n_actors * ops_per_change. With the defaults this
    is the 1M-op / 10k-doc north-star shape, expressed in the columnar
    wire encoding (the JSON dict encoding of the same changes is
    ``block.to_changes()``).
    """
    from .blocks import ChangeBlock
    rng = np.random.default_rng(seed)
    n_changes = n_docs * n_actors
    n_ops = n_changes * ops_per_change
    doc = np.repeat(np.arange(n_docs, dtype=np.int32), n_actors)
    actor = np.tile(np.arange(n_actors, dtype=np.int32), n_docs)
    seq = np.full(n_changes, seq0, np.int32)
    dep_ptr = np.zeros(n_changes + 1, np.int32)
    op_ptr = np.arange(n_changes + 1, dtype=np.int32) * ops_per_change
    # distinct keys per change (first ops_per_change of a random key perm)
    key = rng.random((n_changes, n_keys)).argsort(axis=1) \
        [:, :ops_per_change].astype(np.int32).ravel()
    action = (rng.random(n_ops) < del_p).astype(np.int8)
    value = np.where(action == 0, np.arange(n_ops, dtype=np.int32), -1)
    values = rng.integers(0, 1 << 20, n_ops).tolist()
    z32 = np.zeros(0, np.int32)
    return ChangeBlock(
        n_docs, doc, actor, seq, dep_ptr, z32, z32, op_ptr, action,
        key, value.astype(np.int32),
        [f'peer-{i:03d}' for i in range(n_actors)],
        [f'field{i:02d}' for i in range(n_keys)], values,
        dup_keys=False)          # keys are distinct per change by draw
