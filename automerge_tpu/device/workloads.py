"""Synthetic device-kernel workload generators (shared by bench + tests).

The merge kernels assume causal delivery, which the generator encodes as
an invariant: each op's clock row covers exactly its own actor's prior ops
(``clock[i, actor_i] = seq_i - 1``), optionally plus a causally-consistent
prefix of other actors' ops. Keeping the construction in one place keeps
bench.py and the Pallas differential tests on the same op distribution.
"""

import numpy as np


def gen_docset_workload(n_docs=10240, n_ops=128, n_actors=8, n_keys=32,
                        seed=0, del_p=0.05, invalid_p=0.0, cross_clock=False):
    """A DocSet batch: per doc, ``n_ops`` concurrent 'set' ops from
    ``n_actors`` actors spread over ``n_keys`` root fields.

    Each actor's ops are sequential for itself and (by default) fully
    concurrent across actors — the worst case for conflict resolution.
    With ``cross_clock`` some ops additionally cover a prefix of other
    actors' ops, exercising supersession.

    Returns (seg_id, actor, seq, clock, is_del, valid) numpy arrays with
    shapes [D,N] / [D,N,A].
    """
    rng = np.random.default_rng(seed)
    seg_id = rng.integers(0, n_keys, size=(n_docs, n_ops)).astype(np.int32)
    actor = rng.integers(0, n_actors, size=(n_docs, n_ops)).astype(np.int32)
    # seq numbers: per (doc, actor) running count in op order
    seq = np.ones((n_docs, n_ops), dtype=np.int32)
    for a in range(n_actors):
        mask = actor == a
        running = np.cumsum(mask, axis=1)
        seq[mask] = running[mask]
    clock = np.zeros((n_docs, n_ops, n_actors), dtype=np.int32)
    d_idx, o_idx = np.indices((n_docs, n_ops))
    clock[d_idx, o_idx, actor] = seq - 1
    if cross_clock:
        extra = rng.integers(0, 2, size=(n_docs, n_ops, n_actors))
        clock = np.maximum(clock, np.minimum(extra.astype(np.int32),
                                             seq[:, :, None] - 1))
    is_del = rng.random((n_docs, n_ops)) < del_p
    valid = rng.random((n_docs, n_ops)) >= invalid_p
    return seg_id, actor, seq, clock, is_del, valid
