"""Crash-consistent persistence: atomic checksummed snapshots + a WAL.

`snapshot.py` gives O(state) resume artifacts but leaves the file layer
to the caller — a process killed mid-`write()` leaves a truncated JSON
blob that used to die in a ``KeyError`` on the next boot, and every
change applied since the last checkpoint was simply gone. This module
is the database-grade split the snapshot docstring already cites
(checkpoint + WAL, Demers-style anti-entropy repairs the network side):

- :func:`atomic_write_bytes` — tmp file + fsync + rename (+ directory
  fsync), so a snapshot file is either the complete old artifact or the
  complete new one, never a torn mix.
- A checksummed container (:func:`pack_snapshot` /
  :func:`unpack_snapshot`): magic + length + CRC32 header over the
  payload; truncation and bit-rot both surface as a clean
  :class:`~automerge_tpu.snapshot.SnapshotCorruptError` (and bump the
  ``snapshot_checksum_failures`` counter), never a decode crash.
- :class:`ChangeJournal` — an append-only change log with per-record
  length + CRC framing. Appends are fsync'd; replay stops cleanly at a
  torn tail (the record a crash interrupted), so recovery is snapshot +
  journal-tail replay. Replayed changes that the snapshot already
  covers are dropped by the engines' duplicate tolerance — the replay
  is idempotent, so "journal first, then apply" needs no two-phase
  bookkeeping.
- :class:`DurableDocSet` — the wiring: wraps a snapshot-capable DocSet
  (e.g. :class:`~automerge_tpu.sync.general_doc_set.GeneralDocSet`),
  journals every applied batch before applying, checkpoints the fleet
  atomically, and :meth:`recover`\\ s from snapshot + tail after a
  crash. The chaos suite kills a peer mid-run and resumes it from this
  path (`tests/test_chaos.py`).
"""

import json
import os
import re
import struct
import time
import zlib

from .snapshot import SnapshotCorruptError
from .utils.metrics import metrics

SNAP_MAGIC = b'AMTPU-SNAP1\n'
_REC_HEADER = struct.Struct('>II')           # payload length, CRC32


def _fsync_dir(path):
    """fsync the directory entry so a rename survives power loss (a
    no-op on platforms without directory fds)."""
    try:
        fd = os.open(os.path.dirname(os.path.abspath(path)) or '.',
                     os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path, data):
    """Write ``data`` to ``path`` atomically: tmp file in the same
    directory + fsync + rename + directory fsync. Readers see either
    the previous complete file or the new complete file."""
    tmp = f'{path}.tmp.{os.getpid()}'
    with open(tmp, 'wb') as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(path)


def pack_snapshot(payload):
    """Frame snapshot ``payload`` (bytes or str) in the checksummed
    container: magic, big-endian length, CRC32, payload."""
    if isinstance(payload, str):
        payload = payload.encode()
    return (SNAP_MAGIC +
            _REC_HEADER.pack(len(payload), zlib.crc32(payload)) +
            payload)


def unpack_snapshot(data):
    """Validate a :func:`pack_snapshot` container and return the
    payload bytes. Truncation, bad magic and checksum mismatch each
    raise :class:`SnapshotCorruptError` naming the failure; checksum
    mismatches also bump ``snapshot_checksum_failures``."""
    head = len(SNAP_MAGIC) + _REC_HEADER.size
    if len(data) < head:
        raise SnapshotCorruptError(
            f'snapshot container truncated: {len(data)} bytes, header '
            f'needs {head}')
    if data[:len(SNAP_MAGIC)] != SNAP_MAGIC:
        raise SnapshotCorruptError('snapshot container has bad magic '
                                   '(not an AMTPU-SNAP1 file)')
    length, crc = _REC_HEADER.unpack_from(data, len(SNAP_MAGIC))
    payload = data[head:head + length]
    if len(payload) < length:
        raise SnapshotCorruptError(
            f'snapshot container truncated: payload {len(payload)} of '
            f'{length} bytes')
    if zlib.crc32(payload) != crc:
        metrics.bump('snapshot_checksum_failures')
        raise SnapshotCorruptError(
            'snapshot payload checksum mismatch (bit rot or torn '
            'write)')
    return payload


def write_snapshot_file(path, payload):
    """Atomically persist a snapshot payload in the checksummed
    container."""
    atomic_write_bytes(path, pack_snapshot(payload))


# -- parked-doc shards (cold-doc eviction / quarantine parking) ---------------

PARK_FORMAT = 'automerge-tpu-parked-docs@1'
# tiered container (ISSUE 12): a v2 shard's payloads may carry a
# base64-armored per-doc STATE snapshot ('state') in place of — or, for
# a compacted doc, instead of — the full change history ('changes').
# v1 shards (full-log payloads only) keep writing byte-identically, and
# the reader accepts both versions.
PARK_FORMAT_V2 = 'automerge-tpu-parked-docs@2'


def write_park_shard(path, docs):
    """Persist one eviction batch's parked docs as a checksummed shard:
    ``docs`` is ``{doc_id: payload}`` where each payload carries the
    doc's ``clock``, buffered ``queued`` changes, an optional
    ``quarantine`` record and either its full change history
    (``changes``) or a base64-armored state snapshot (``state`` — the
    tiered form for compacted docs). Written atomically — a parked
    doc's shard is the doc's ONLY durable copy once a checkpoint
    snapshots the fleet without it. Full-log-only shards keep the v1
    format stamp (byte-compatible with pre-tier readers)."""
    tiered = any(isinstance(p, dict) and p.get('state') is not None
                 for p in docs.values())
    atomic_write_bytes(path, pack_snapshot(json.dumps(
        {'format': PARK_FORMAT_V2 if tiered else PARK_FORMAT,
         'docs': docs},
        separators=(',', ':'))))


def read_park_shard(path):
    """Load a :func:`write_park_shard` artifact (either container
    version); returns the ``{doc_id: payload}`` map. Raises
    :class:`~automerge_tpu.snapshot.SnapshotCorruptError` naming the
    failure on truncation/bit rot/format mismatch."""
    with open(path, 'rb') as f:
        payload = unpack_snapshot(f.read())
    try:
        obj = json.loads(payload)
    except ValueError as err:
        raise SnapshotCorruptError(
            f'park shard is not valid JSON ({err})') from None
    if not isinstance(obj, dict) or \
            obj.get('format') not in (PARK_FORMAT, PARK_FORMAT_V2):
        raise SnapshotCorruptError('not a parked-docs shard')
    docs = obj.get('docs')
    if not isinstance(docs, dict):
        raise SnapshotCorruptError(
            "park shard: missing field 'docs'")
    return docs


def read_snapshot_file(path):
    """Read + validate a :func:`write_snapshot_file` artifact."""
    with open(path, 'rb') as f:
        return unpack_snapshot(f.read())


# -- flight-recorder incident files -------------------------------------------

def dump_incident(recorder, dir_path, kind, **meta):
    """Dump a :class:`~automerge_tpu.utils.metrics.FlightRecorder`'s
    retained events to ``<dir_path>/incidents/incident-<seq>-<kind>.
    jsonl`` — ONE file per incident, written through
    :func:`atomic_write_bytes` like any snapshot, so an incident file
    is never torn. A trigger record (``event='incident'`` with
    ``kind`` + ``meta``) is the file's guaranteed LAST line, so the
    file itself names what fired it. Returns the path."""
    inc_dir = os.path.join(dir_path, 'incidents')
    os.makedirs(inc_dir, exist_ok=True)
    # max existing seq + 1, NOT file count + 1: an operator pruning an
    # old incident must never make the next dump overwrite a newer one
    seq = 1 + max(
        (int(m.group(1)) for m in
         (re.match(r'incident-(\d+)-.*\.jsonl$', n)
          for n in os.listdir(inc_dir)) if m),
        default=0)
    path = os.path.join(inc_dir, f'incident-{seq:04d}-{kind}.jsonl')
    trigger = {'event': 'incident', 'kind': kind, 'ts': time.time(),
               'mono': time.perf_counter(), **meta}
    # the trigger rides to the file as dump()'s locally-appended last
    # line — appending it to the shared ring FIRST would let a
    # concurrent emit (the async applier thread) land after it and
    # displace it from the tail. The ring still gets the mark (below)
    # so later incidents' files show this one in their history.
    recorder.dump(path, trigger=trigger)
    recorder(trigger)
    metrics.bump('incidents_dumped')
    return path


def load_incident(path):
    """Read a :func:`dump_incident` file back: ``(events, trigger)``
    where ``trigger`` is the final ``event='incident'`` record naming
    what fired the dump (None for a pre-trigger or hand-made file).
    The inverse operators and tools consume — ``tools/trace_report.py``
    turns the same lines into a Chrome-trace file."""
    events = []
    with open(path, 'r', encoding='utf-8') as f:
        for line in f:
            line = line.strip()
            if line:
                events.append(json.loads(line))
    trigger = events[-1] if events and \
        events[-1].get('event') == 'incident' else None
    return events, trigger


class ChangeJournal:
    """Append-only change journal with per-record length+CRC framing.

    One record per applied batch: ``{'changes': {doc_id: [change,
    ...]}}`` as JSON, preceded by an 8-byte length+CRC header. Appends
    fsync by default (crash consistency is the point; pass
    ``fsync=False`` to trade safety for throughput). :meth:`replay`
    yields the decoded records and STOPS at the first invalid one — a
    crash can only tear the tail, so everything before it is intact; a
    mid-file CRC mismatch (bit rot) also stops replay but is counted
    under ``snapshot_checksum_failures``."""

    def __init__(self, path, fsync=True):
        self.path = path
        self.fsync = fsync
        self._f = open(path, 'ab')
        # memory accounting: journal file bytes (gauge + watermark) —
        # an append-only WAL that never checkpoints is a disk leak a
        # dashboard should see long before the filesystem does
        self.bytes = self._f.tell()
        self._publish_bytes()

    def _publish_bytes(self):
        metrics.set_gauge('mem_journal_bytes', self.bytes)
        metrics.ratchet('mem_journal_peak_bytes', self.bytes)

    def append(self, record):
        payload = json.dumps(record, separators=(',', ':')).encode()
        self._f.write(_REC_HEADER.pack(len(payload),
                                       zlib.crc32(payload)) + payload)
        self.bytes += _REC_HEADER.size + len(payload)
        self._publish_bytes()
        self._f.flush()
        if self.fsync:
            # journal fsync is the durable write path's latency floor:
            # the observe series feeds quantile('journal_fsync_ms')
            # for fleet_status() and the bench's p50/p99 keys
            t0 = time.perf_counter()
            os.fsync(self._f.fileno())
            metrics.observe('journal_fsync_ms',
                            (time.perf_counter() - t0) * 1e3)

    def close(self):
        self._f.close()

    def reset(self):
        """Truncate after a checkpoint: the snapshot now covers every
        journaled record."""
        self._f.truncate(0)
        self._f.seek(0)
        self.bytes = 0
        self._publish_bytes()
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())

    @classmethod
    def replay(cls, path):
        """Yield every intact record of the journal at ``path`` in
        append order, tolerating a torn tail."""
        for record, _ in cls._scan(path):
            yield record

    @classmethod
    def _scan(cls, path):
        """Yield ``(record, end_offset)`` for every intact record — the
        offset lets recovery TRUNCATE a torn/corrupt tail, so records
        appended after a recovery are not stranded behind it."""
        try:
            with open(path, 'rb') as f:
                data = f.read()
        except FileNotFoundError:
            return
        pos = 0
        while pos + _REC_HEADER.size <= len(data):
            length, crc = _REC_HEADER.unpack_from(data, pos)
            payload = data[pos + _REC_HEADER.size:
                           pos + _REC_HEADER.size + length]
            if len(payload) < length:
                return                       # torn tail: crash mid-append
            if zlib.crc32(payload) != crc:
                metrics.bump('snapshot_checksum_failures')
                return                       # bit rot: stop before it
            try:
                record = json.loads(payload)
            except ValueError:
                metrics.bump('snapshot_checksum_failures')
                return
            pos += _REC_HEADER.size + length
            yield record, pos


class DurableDocSet:
    """Crash-consistent wrapper around a snapshot-capable DocSet.

    Every :meth:`apply_changes_batch` appends the batch to the journal
    BEFORE applying (WAL ordering; replay is idempotent thanks to the
    engines' duplicate tolerance), :meth:`checkpoint` writes the
    fleet's packed snapshot atomically and truncates the journal, and
    :meth:`recover` rebuilds snapshot + journal tail after a crash.
    Everything else (``get_doc``, ``register_handler``, materialize,
    ...) proxies to the wrapped DocSet, so a
    :class:`~automerge_tpu.sync.connection.Connection` can be handed
    the durable wrapper directly."""

    SNAPSHOT_FILE = 'snapshot.amtpu'
    JOURNAL_FILE = 'journal.amtpu'

    def __init__(self, doc_set, dir_path, fsync=True):
        os.makedirs(dir_path, exist_ok=True)
        self.doc_set = doc_set
        self.dir_path = dir_path
        self.journal = ChangeJournal(
            os.path.join(dir_path, self.JOURNAL_FILE), fsync=fsync)

    # -- the durable write path ---------------------------------------------

    def apply_changes_batch(self, changes_by_doc, **kwargs):
        self.journal.append({'changes': changes_by_doc})
        return self.doc_set.apply_changes_batch(changes_by_doc,
                                                **kwargs)

    applyChangesBatch = apply_changes_batch

    def apply_changes(self, doc_id, changes):
        self.journal.append({'changes': {doc_id: changes}})
        return self.doc_set.apply_changes(doc_id, changes)

    applyChanges = apply_changes

    def apply_wire(self, data, doc_ids=None):
        """WAL the wire path too, so it replays byte-identically
        (without this, changes acknowledged over a WireConnection
        would vanish in a crash — the dict path was journaled, the
        columnar path was not). v1 payloads are UTF-8 JSON and journal
        as text; columnar v2/v3 containers are binary and journal
        base64-armored (the journal record framing is JSON)."""
        from .wire import COLUMNAR_MAGIC, COLUMNAR_MAGIC_V3
        if isinstance(data, (bytes, bytearray)) and \
                bytes(data[:4]) in (COLUMNAR_MAGIC, COLUMNAR_MAGIC_V3):
            import base64
            self.journal.append(
                {'wireb64': base64.b64encode(bytes(data)).decode(
                    'ascii'), 'docs': doc_ids})
        else:
            if isinstance(data, (bytes, bytearray)):
                text = bytes(data).decode('utf-8')
            else:
                text = data
            self.journal.append({'wire': text, 'docs': doc_ids})
        return self.doc_set.apply_wire(data, doc_ids=doc_ids)

    applyWire = apply_wire

    def apply_states(self, payload_by_doc):
        """WAL the state-bootstrap path (tiered doc storage): an
        absorbed state snapshot must survive a crash exactly like an
        acknowledged change — the binary payloads journal
        base64-armored and replay through ``apply_states`` on
        recover."""
        import base64
        self.journal.append(
            {'states': {doc_id: base64.b64encode(
                bytes(payload)).decode('ascii')
                for doc_id, payload in payload_by_doc.items()}})
        return self.doc_set.apply_states(payload_by_doc)

    applyStates = apply_states

    def apply_state(self, doc_id, payload):
        return self.apply_states({doc_id: payload}).get(doc_id)

    applyState = apply_state

    def checkpoint(self):
        """Atomic fleet checkpoint: packed snapshot to a tmp file,
        fsync, rename, THEN journal truncate — a crash between the two
        replays already-checkpointed changes, which the duplicate
        tolerance drops."""
        write_snapshot_file(
            os.path.join(self.dir_path, self.SNAPSHOT_FILE),
            self.doc_set.save_snapshot())
        self.journal.reset()

    def close(self):
        self.journal.close()

    @classmethod
    def recover(cls, dir_path, doc_set_factory, load_snapshot=None,
                fsync=True, flight_recorder=None):
        """Rebuild after a crash: load the checkpoint if one exists
        (``load_snapshot(payload_bytes)``), else start from
        ``doc_set_factory()``, then replay the journal tail through
        ``apply_changes_batch``. Returns the new :class:`DurableDocSet`
        (its journal keeps the replayed tail until the next
        :meth:`checkpoint`). With a ``flight_recorder`` (subscribed to
        the metrics bus before the call), the recovery dumps the
        recorder's retained pre-crash/replay events as an incident
        file under ``<dir_path>/incidents/`` — the black box of what
        happened in the seconds before the crash."""
        snap_path = os.path.join(dir_path, cls.SNAPSHOT_FILE)
        doc_set = None
        if load_snapshot is not None and os.path.exists(snap_path):
            doc_set = load_snapshot(read_snapshot_file(snap_path))
        if doc_set is None:
            doc_set = doc_set_factory()
        journal_path = os.path.join(dir_path, cls.JOURNAL_FILE)
        # journaled batches may include a poisoned doc (the journal is
        # written BEFORE the apply): replay under per-doc isolation
        # when the doc set supports it, so recovery re-quarantines the
        # poison instead of dying on it
        kwargs = {'isolate': True} \
            if hasattr(doc_set, 'quarantined') else {}
        valid_end = 0
        n_replayed = 0
        for record, end in ChangeJournal._scan(journal_path):
            n_replayed += 1
            if 'states' in record:
                # state-bootstrap records (tiered doc storage) replay
                # through the same absorb path; apply_states isolates
                # per doc internally
                import base64
                doc_set.apply_states(
                    {doc_id: base64.b64decode(b64)
                     for doc_id, b64 in record['states'].items()})
            elif 'wire' in record or 'wireb64' in record:
                # wire-path record: replay the raw payload through the
                # fused path; a poisoned doc falls back to the dict
                # batch under per-doc isolation (the fused apply rolls
                # back store-intact), exactly like WireConnection
                if 'wireb64' in record:
                    import base64
                    raw = base64.b64decode(record['wireb64'])
                else:
                    raw = record['wire'].encode('utf-8')
                try:
                    doc_set.apply_wire(raw, doc_ids=record['docs'])
                except Exception:
                    if 'wireb64' in record:
                        from .wire import columnar_container_to_changes
                        per_doc = columnar_container_to_changes(raw)
                    else:
                        per_doc = json.loads(record['wire'])
                    doc_set.apply_changes_batch(
                        dict(zip(record['docs'] or
                                 [f'doc-{i}'
                                  for i in range(len(per_doc))],
                                 per_doc)), **kwargs)
            else:
                doc_set.apply_changes_batch(record['changes'],
                                            **kwargs)
            valid_end = end
        # drop the torn/corrupt tail NOW: appends after recovery must
        # land on a replayable journal, not be stranded behind garbage
        # a second crash would stop the next replay at
        try:
            if os.path.getsize(journal_path) > valid_end:
                with open(journal_path, 'r+b') as f:
                    f.truncate(valid_end)
                    f.flush()
                    os.fsync(f.fileno())
        except FileNotFoundError:
            pass
        out = cls.__new__(cls)
        out.doc_set = doc_set
        out.dir_path = dir_path
        out.journal = ChangeJournal(journal_path, fsync=fsync)
        if flight_recorder is not None:
            dump_incident(flight_recorder, dir_path, 'recovery',
                          replayed_records=n_replayed)
        return out

    # -- proxy --------------------------------------------------------------

    def __getattr__(self, name):
        return getattr(self.doc_set, name)
