"""Fleet workload simulator: named production traffic shapes, scored
by SLO scorecards, driving the closed-loop controller.

The chaos harness (:mod:`~.sync.chaos`) proves correctness under
transport FAULTS; nothing before this module proved behavior under
production TRAFFIC SHAPES — heavy-tailed popularity, diurnal load,
flash crowds, reconnect storms, actor churn. This simulator turns the
ROADMAP's "handles as many scenarios as you can imagine" into a
regression-tested matrix:

- **Deterministic, seeded schedules** — :func:`build_schedule` is a
  pure function of (scenario, seed, scale): every write (actor, seq,
  deps, ops), every partition/heal event, laid out per tick and
  digestible (``schedule_digest``). Two runs from one seed replay the
  byte-identical schedule and land byte-identical per-doc state
  digests — across the numpy and forced-native lanes too
  (tests/test_fleetsim.py).
- **Serving-stack fleets** — each node is a
  :class:`~.sync.serving.ServingDocSet` over a
  :class:`~.sync.general_doc_set.GeneralDocSet`, wired full-mesh
  through :class:`~.sync.chaos.ChaosFleet`'s
  :class:`~.sync.resilient.ResilientConnection` fabric on the
  columnar wire path — the exact production stack, logical time only.
- **SLO scorecards from the telemetry surface ONLY** — every check
  reads what an operator could read: ``fleet_status()`` health/
  latency/memory/convergence blocks, the replication-lag gauges, the
  ``sync_convergence_ms`` histogram, admission debt and backpressure
  depth, quarantine/divergence totals, and the heartbeat digest maps
  (replica-equality proof from the divergence-audit surface). The
  simulator's own bookkeeping (it knows every write it made) is
  deliberately never consulted for a verdict.
- **Closed-loop control** — with ``controller=True`` each node gets a
  :class:`~.sync.control.FleetController`; the acceptance matrix
  (``ADAPTIVE_SCENARIOS``) contains scenarios that demonstrably end
  RED with the controller disabled and GREEN with it enabled: the
  flash crowd (memory pressure the controller relieves by lowering
  the eviction watermark and scheduling compaction) and the diurnal
  peak (admission backpressure the controller relieves by widening
  token rates under sustained busy + low debt utilization).

``bench_fleet_sim`` (bench.py) runs the matrix as perf-gate lanes —
per-scenario ``fleet_sim_*`` JSON keys banded in PERF_BUDGETS.json —
and ``--trace-out`` dumps the load curve, health transitions and
controller actions as one Perfetto track set
(``tools/trace_report.py --scenario`` prints the same artifacts as a
per-scenario table).
"""

import hashlib
import json
import os
import shutil
import tempfile
import time
from bisect import bisect_left

from .common import ROOT_ID
from .sync.chaos import ChaosFleet, canonical, doc_set_view
from .sync.control import FleetController
from .sync.general_doc_set import GeneralDocSet
from .sync.serving import ServingDocSet
from .utils.metrics import metrics

DEFAULT_SEED = 1307
_HEALTH_RANK = {'green': 0, 'degraded': 1, 'critical': 2}

# The scenario catalog. Each entry carries a 'smoke' scale (CI: small
# fleets, seconds per scenario) and a 'full' scale (bench lanes —
# actor churn crosses 100k simulated actors there). 'slo' overrides
# the scorecard defaults per scenario; 'admission' meters every
# node's shared inbound valve; 'budget_factor' arms a serving memory
# budget at that multiple of the post-seed resident bytes.
SCENARIOS = {
    'zipf': {
        'desc': 'heavy-tailed (Zipf) doc popularity, steady load',
        'smoke': dict(n_nodes=2, n_docs=48, ticks=24, drain=60,
                      ops_per_tick=16, alpha=1.1),
        'full': dict(n_nodes=3, n_docs=1024, ticks=40, drain=120,
                     ops_per_tick=256, alpha=1.1,
                     slo={'convergence_ms_p99_max': 600_000.0}),
    },
    'diurnal': {
        'desc': 'diurnal load curve over a metered admission valve; '
                'the peak overruns the configured token rate',
        'smoke': dict(n_nodes=2, n_docs=32, ticks=56, drain=24,
                      base_ops=3, peak_ops=36, peak_start=8,
                      peak_end=48,
                      admission={'changes_per_tick': 8,
                                 'burst_ticks': 2}),
        # the full scale keeps the SMOKE tick structure (verified
        # red-uncontrolled / green-controlled) and scales the op and
        # doc axes: same peak/rate overrun ratio, same backlog-vs-
        # drain shape, so the verdict dynamics carry over
        'full': dict(n_nodes=2, n_docs=512, ticks=56, drain=24,
                     base_ops=18, peak_ops=216, peak_start=8,
                     peak_end=48,
                     admission={'changes_per_tick': 48,
                                'burst_ticks': 2},
                     slo={'convergence_ms_p99_max': 600_000.0}),
    },
    'flash_crowd': {
        'desc': 'one doc goes viral: update-heavy hot writes under a '
                'serving memory budget (background traffic stays on '
                'a small resident working set; the cold tail parks '
                'once and stays parked)',
        'smoke': dict(n_nodes=2, n_docs=24, ticks=36, drain=24,
                      base_ops=4, resident_docs=6, crowd_ops=12,
                      crowd_start=8, crowd_end=32, hot_actors=8,
                      budget_factor=1.8,
                      slo={'peak_memory_pressure': 1.2,
                           'non_green_polls_max': 4},
                      controller_kwargs=dict(
                          hold=2, cooldown=4, mem_high=0.75,
                          compact_cooldown=6)),
        # full scale = the smoke tick structure with the op/doc axes
        # scaled (see diurnal note)
        'full': dict(n_nodes=2, n_docs=256, ticks=36, drain=24,
                     base_ops=16, resident_docs=12, crowd_ops=96,
                     crowd_start=8, crowd_end=32, hot_actors=32,
                     budget_factor=1.8,
                     slo={'peak_memory_pressure': 1.2,
                          'non_green_polls_max': 8,
                          'convergence_ms_p99_max': 600_000.0},
                     controller_kwargs=dict(
                         hold=2, cooldown=4, mem_high=0.75,
                         compact_cooldown=6)),
    },
    'reconnect_storm': {
        'desc': 'a node partitions mid-load and heals: the reconnect '
                'storm must converge through the normal protocol',
        'smoke': dict(n_nodes=3, n_docs=48, ticks=40, drain=120,
                      ops_per_tick=10, alpha=1.1, partition_at=10,
                      heal_at=28),
        'full': dict(n_nodes=3, n_docs=256, ticks=48, drain=160,
                     ops_per_tick=64, alpha=1.1, partition_at=10,
                     heal_at=32,
                     slo={'convergence_ms_p99_max': 600_000.0}),
    },
    'actor_churn': {
        'desc': 'every tick mints fresh actors that write once and '
                'vanish (100k+ actors at full scale)',
        'smoke': dict(n_nodes=2, n_docs=48, ticks=24, drain=60,
                      spawn_per_tick=40),
        # 16 ticks x 6400 spawns + 512 seed actors = 102,912 distinct
        # actors (node choice does NOT multiply the count): big fused
        # batches amortize the per-tick overhead far better than many
        # small ticks at this scale
        'full': dict(n_nodes=2, n_docs=512, ticks=16, drain=80,
                     spawn_per_tick=6400,
                     slo={'convergence_ms_p99_max': 600_000.0}),
    },
    'hot_shard': {
        'desc': 'zipf load over a sharded fleet whose docs all start '
                'pinned on shard 0 (worst-case initial placement): '
                'red without the placement knob, green once the '
                'controller drains hot docs to the cold shards',
        'smoke': dict(n_nodes=1, n_docs=32, ticks=22, drain=4,
                      ops_per_tick=32, alpha=1.1, n_shards=4,
                      slo={'shard_imbalance_max': 2.2,
                           'min_migrations': 1},
                      controller_kwargs=dict(
                          hold=2, cooldown=3, placement_min_ops=16,
                          placement_ratio=1.5, migrate_batch=3)),
        # full scale widens the doc/op axes and the mesh cut; the
        # skew/verdict dynamics are the smoke shape scaled up
        'full': dict(n_nodes=1, n_docs=256, ticks=36, drain=4,
                     ops_per_tick=256, alpha=1.1, n_shards=8,
                     slo={'shard_imbalance_max': 2.2,
                          'min_migrations': 1},
                     controller_kwargs=dict(
                         hold=2, cooldown=3, placement_min_ops=64,
                         placement_ratio=1.5, migrate_batch=8)),
    },
}

# Scenarios whose SLO verdict flips red -> green when the controller
# is enabled (the acceptance matrix bench_fleet_sim gates as
# fleet_sim_adaptive_wins).
ADAPTIVE_SCENARIOS = ('flash_crowd', 'diurnal', 'hot_shard')

# Scorecard defaults; per-scenario 'slo' entries override. Every
# bound grades a value read from the telemetry surface. The
# convergence bound detects STUCK convergence, not wall speed: the
# sim runs logical quanta whose wall cost is dominated by host jit
# dispatch, so the bound is generous (and is also installed as each
# node's convergence health threshold — a healthy simulated fleet
# must not read degraded just because the machine is slow).
DEFAULT_SLO = {
    'quarantined_max': 0,
    'diverged_max': 0,
    'final_health': 'green',
    'critical_polls_max': 0,
    'convergence_ms_p99_max': 120_000.0,
}


def _zipf_cdf(n, alpha):
    acc = 0.0
    out = []
    for i in range(n):
        acc += 1.0 / (i + 1) ** alpha
        out.append(acc)
    return out


def _mk_change(seqs, doc_id, actor, ops):
    seq = seqs.get((doc_id, actor), 0) + 1
    seqs[(doc_id, actor)] = seq
    return {'actor': actor, 'seq': seq,
            'deps': {actor: seq - 1} if seq > 1 else {}, 'ops': ops}


def _seed_changes(spec, seqs):
    """Tick-0 seed: every doc is born at its home node with a small
    list + a meta key (the bench's mixed-doc idiom, scaled down) —
    the fleet converges on this before the measured load starts."""
    writes = {}
    for d in range(spec['n_docs']):
        node = d % spec['n_nodes']
        doc_id = f'doc{d}'
        obj = f'00000000-0000-4000-8000-{d:012x}'
        ops = [{'action': 'makeList', 'obj': obj},
               {'action': 'link', 'obj': ROOT_ID, 'key': 'items',
                'value': obj},
               {'action': 'ins', 'obj': obj, 'key': '_head',
                'elem': 1},
               {'action': 'set', 'obj': obj, 'key': f's{d}:1',
                'value': d},
               {'action': 'set', 'obj': ROOT_ID, 'key': 'meta',
                'value': d}]
        writes.setdefault(node, {})[doc_id] = [
            _mk_change(seqs, doc_id, f's{d}', ops)]
    return writes


def build_schedule(scenario, seed=DEFAULT_SEED, scale='smoke'):
    """The full event schedule of one scenario run, as a pure
    function of (scenario, seed, scale): ``{'scenario', 'seed',
    'spec', 'ticks': [{'writes': [[node, doc_id, [change, ...]],
    ...], 'partition': [[a, b], ...], 'heal': [...]}, ...],
    'n_ops', 'n_actors', 'digest'}``. Tick 0 is the seed phase (the
    fleet converges on it before measurement starts); the digest is
    blake2b over the canonical JSON of everything else — the
    determinism comparand."""
    import random
    if scenario not in SCENARIOS:
        raise ValueError(f'unknown scenario {scenario!r} (have: '
                         f'{", ".join(sorted(SCENARIOS))})')
    spec = dict(SCENARIOS[scenario][scale]) if isinstance(scale, str) \
        else dict(scale)
    spec.setdefault('heartbeat_every', 8)
    # seeding from a string is PYTHONHASHSEED-independent (random
    # hashes the bytes), so the schedule is identical across processes
    rng = random.Random(f'{seed}:{scenario}')
    seqs = {}
    actors = set()
    n_ops = 0
    n_docs, n_nodes = spec['n_docs'], spec['n_nodes']
    cdf = _zipf_cdf(n_docs, spec.get('alpha', 1.1))

    def zipf_doc():
        return bisect_left(cdf, rng.random() * cdf[-1])

    ticks = [{'writes': _seed_changes(spec, seqs)}]
    for a in range(n_docs):
        actors.add(f's{a}')

    def add_write(tick, node, doc_id, actor, ops):
        nonlocal n_ops
        tick['writes'].setdefault(node, {}).setdefault(
            doc_id, []).append(_mk_change(seqs, doc_id, actor, ops))
        actors.add(actor)
        n_ops += len(ops)

    for t in range(1, spec['ticks'] + 1):
        tick = {'writes': {}}
        if scenario in ('zipf', 'reconnect_storm', 'hot_shard'):
            if scenario == 'reconnect_storm':
                if t == spec['partition_at']:
                    # sever node 0 from everyone: an isolated writer
                    tick['partition'] = [[0, b]
                                         for b in range(1, n_nodes)]
                if t == spec['heal_at']:
                    tick['heal'] = [[0, b] for b in range(1, n_nodes)]
            for i in range(spec['ops_per_tick']):
                d = zipf_doc()
                node = d % n_nodes
                add_write(tick, node, f'doc{d}', f'w{node}d{d}',
                          [{'action': 'set', 'obj': ROOT_ID,
                            'key': f'k{rng.randrange(8)}',
                            'value': f'v{t}x{i}'}])
        elif scenario == 'diurnal':
            lo, hi = spec['peak_start'], spec['peak_end']
            base, peak = spec['base_ops'], spec['peak_ops']
            if lo <= t < hi:
                mid = (lo + hi) / 2
                frac = 1.0 - abs(t - mid) / (mid - lo)
                ops = base + int((peak - base) * frac)
            else:
                ops = base
            for i in range(ops):
                d = rng.randrange(n_docs)
                node = d % n_nodes
                add_write(tick, node, f'doc{d}', f'w{node}d{d}',
                          [{'action': 'set', 'obj': ROOT_ID,
                            'key': f'k{rng.randrange(8)}',
                            'value': f'v{t}x{i}'}])
        elif scenario == 'flash_crowd':
            # background traffic cycles a SMALL resident working set
            # (docs 1..resident_docs stay hot and pinned); the seeded
            # cold tail beyond it is written once and never again, so
            # the budget squeeze parks it exactly once — the pressure
            # that remains is the viral doc itself, which only
            # compaction can shrink
            for i in range(spec['base_ops']):
                d = 1 + (t * spec['base_ops'] + i) % \
                    spec['resident_docs']
                node = d % n_nodes
                add_write(tick, node, f'doc{d}', f'w{node}d{d}',
                          [{'action': 'set', 'obj': ROOT_ID,
                            'key': f'k{rng.randrange(8)}',
                            'value': f'v{t}x{i}'}])
            if spec['crowd_start'] <= t < spec['crowd_end']:
                # the viral doc: update-heavy hot writes from a small
                # rotating actor set — history grows per tick while
                # the surviving state stays bounded (the compaction-
                # friendly shape the controller exploits)
                for i in range(spec['crowd_ops']):
                    j = (t * spec['crowd_ops'] + i) % \
                        spec['hot_actors']
                    add_write(tick, 0, 'doc0', f'h{j}',
                              [{'action': 'set', 'obj': ROOT_ID,
                                'key': f'c{i % 6}',
                                'value': f'{"pay" * 12}-{t}-{i}'}])
        elif scenario == 'actor_churn':
            for i in range(spec['spawn_per_tick']):
                d = rng.randrange(n_docs)
                node = rng.randrange(n_nodes)
                add_write(tick, node, f'doc{d}', f'c{t}x{i}',
                          [{'action': 'set', 'obj': ROOT_ID,
                            'key': f'u{i % 16}',
                            'value': f'{t}.{i}'}])
        ticks.append(tick)

    # canonical form: writes as sorted lists, not dicts keyed by int
    out_ticks = []
    for tick in ticks:
        rec = {'writes': [
            [node, doc_id, changes]
            for node in sorted(tick['writes'])
            for doc_id, changes in sorted(
                tick['writes'][node].items())]}
        for k in ('partition', 'heal'):
            if tick.get(k):
                rec[k] = tick[k]
        out_ticks.append(rec)
    body = {'scenario': scenario, 'seed': seed, 'spec': spec,
            'ticks': out_ticks}
    digest = hashlib.blake2b(
        json.dumps(body, sort_keys=True).encode(),
        digest_size=16).hexdigest()
    body['n_ops'] = n_ops
    body['n_actors'] = len(actors)
    body['digest'] = digest
    return body


class FleetSim:
    """One scenario run over the production serving stack.

    ``schedule`` — a :func:`build_schedule` result (or pass
    ``scenario``/``seed``/``scale`` to build one).
    ``controller`` — attach a :class:`FleetController` per node.
    ``collect_views`` — include each node's canonical materialized
    views in the result (the regression tests' comparand; never part
    of the SLO verdict).
    """

    def __init__(self, scenario=None, seed=DEFAULT_SEED,
                 scale='smoke', controller=True, schedule=None,
                 collect_views=False, controller_kwargs=None):
        self.schedule = schedule if schedule is not None else \
            build_schedule(scenario, seed, scale)
        self.controller = controller
        self.collect_views = collect_views
        self.controller_kwargs = dict(controller_kwargs or {})
        self._events = []              # health/control event collector

    # -- telemetry event collection ------------------------------------------

    def _collect(self, event):
        if event.get('event') in ('health_transition',
                                  'control_action'):
            self._events.append(dict(event))

    # -- the run -------------------------------------------------------------

    def run(self):
        spec = self.schedule['spec']
        scenario = self.schedule['scenario']
        if spec.get('n_shards'):
            return self._run_sharded(spec, scenario)
        n_nodes = spec['n_nodes']
        hb = spec['heartbeat_every']
        # per-link counter slices of earlier fleets in this process
        # would bleed into health deltas under the same node names —
        # the peer-churn hook wipes them; the convergence series is
        # scoped to this run like the bench lanes scope theirs
        metrics.drop_scope('node/')
        metrics.reset_series('sync_convergence_ms')
        metrics.bump('sim_scenario_runs')
        metrics.bump('sim_actors_spawned', self.schedule['n_actors'])
        tmp = tempfile.mkdtemp(prefix=f'amtpu-fleetsim-{scenario}-')
        capacity = spec['n_docs'] + 8
        doc_sets = [
            ServingDocSet(GeneralDocSet(capacity),
                          os.path.join(tmp, f'node{i}'))
            for i in range(n_nodes)]
        admission = spec.get('admission')
        fleet = ChaosFleet(
            doc_sets, seed=self.schedule['seed'] + 1, batching=True,
            wire=True, heartbeat_every=hb,
            admission=dict(admission) if admission else None)
        conv_bound = spec.get('slo', {}).get(
            'convergence_ms_p99_max',
            DEFAULT_SLO['convergence_ms_p99_max'])
        for ds in doc_sets:
            ds.inner.health_thresholds['convergence_ms_p99'] = \
                (conv_bound, None)
        metrics.subscribe(self._collect)
        try:
            return self._run_traced(spec, scenario, doc_sets, fleet)
        finally:
            metrics.unsubscribe(self._collect)
            fleet.close()
            for ds in doc_sets:
                ds.close()
            shutil.rmtree(tmp, ignore_errors=True)

    def _apply_tick(self, tick, doc_sets, fleet):
        for pair in tick.get('partition', ()):
            fleet.partition(*pair)
        for pair in tick.get('heal', ()):
            fleet.heal(*pair)
        load = 0
        by_node = {}
        for node, doc_id, changes in tick['writes']:
            by_node.setdefault(node, {})[doc_id] = changes
            load += sum(len(c['ops']) for c in changes)
        for node, batch in by_node.items():
            doc_sets[node].apply_changes_batch(batch)
        metrics.bump('sim_ticks')
        if load:
            metrics.bump('sim_ops_injected', load)
        if metrics.active:
            # the load curve as a Perfetto counter track: one sample
            # per scheduling quantum
            metrics.emit('counter', sim_load_ops=load)
        fleet.tick()
        return load

    def _run_traced(self, spec, scenario, doc_sets, fleet):
        ticks = self.schedule['ticks']
        if metrics.active:
            metrics.emit('sim_scenario_start', scenario=scenario,
                         seed=self.schedule['seed'],
                         n_nodes=spec['n_nodes'],
                         n_docs=spec['n_docs'],
                         controller=self.controller)
        # seed phase: converge tick 0 before anything is measured
        self._apply_tick(ticks[0], doc_sets, fleet)
        fleet.run(max_ticks=4000)
        metrics.reset_series('sync_convergence_ms')
        self._events.clear()
        # arm the memory budgets off the POST-SEED resident estimate
        # (a telemetry read, deterministic from the schedule)
        factor = spec.get('budget_factor')
        if factor:
            for ds in doc_sets:
                resident = ds.fleet_status(
                    docs=False)['memory']['resident_bytes']
                ds.memory_budget_bytes = max(1, int(resident * factor))
        if self.controller:
            # per-scenario controller tuning from the spec; explicit
            # constructor kwargs win. Each controller attaches itself
            # to its serving node (ds.controller), which is where the
            # scorecard reads the action tallies back.
            kwargs = dict(spec.get('controller_kwargs', {}))
            kwargs.update(self.controller_kwargs)
            for ds in doc_sets:
                FleetController(ds, **kwargs)

        peak_resident = 0
        peak_pressure = 0.0
        non_green_polls = 0
        critical_polls = 0
        polls = 0
        t0 = time.perf_counter()

        def poll():
            nonlocal peak_resident, peak_pressure, non_green_polls, \
                critical_polls, polls
            polls += 1
            worst = 'green'
            for ds in doc_sets:
                st = ds.fleet_status(docs=False)
                peak_resident = max(peak_resident,
                                    st['memory']['resident_bytes'])
                p = st['health']['signals'].get('memory_pressure')
                if p:
                    peak_pressure = max(peak_pressure, p)
                if _HEALTH_RANK[st['health']['state']] > \
                        _HEALTH_RANK[worst]:
                    worst = st['health']['state']
            if worst != 'green':
                non_green_polls += 1
            if worst == 'critical':
                critical_polls += 1

        for i, tick in enumerate(ticks[1:]):
            self._apply_tick(tick, doc_sets, fleet)
            if i % 2 == 1:
                poll()
        # drain: logical time keeps running with zero load until no
        # DATA envelope is unacked anywhere for a few quanta and at
        # least two heartbeat periods have passed (the periodic beats
        # themselves never go quiet, so raw fabric silence is not the
        # signal) — or the scenario's drain budget runs out: an
        # unconverged end is a legitimate RED outcome, not a harness
        # failure
        quiet = 0
        hb = spec['heartbeat_every']
        empty = {'writes': []}
        for i in range(spec['drain']):
            self._apply_tick(empty, doc_sets, fleet)
            quiet = 0 if any(c.in_flight
                             for c in fleet.conns.values()) \
                else quiet + 1
            if i >= 2 * hb and quiet >= 4:
                break
        poll()
        dt = time.perf_counter() - t0
        return self._score(spec, scenario, doc_sets, dt,
                           dict(peak_resident=peak_resident,
                                peak_pressure=peak_pressure,
                                non_green_polls=non_green_polls,
                                critical_polls=critical_polls,
                                polls=polls))

    # -- the sharded-fleet lane (hot_shard) ----------------------------------

    def _run_sharded(self, spec, scenario):
        """A sharded-fleet scenario: one
        :class:`~.sync.sharded.ShardedGeneralDocSet` node whose docs
        all start PINNED on shard 0 (the deliberate worst-case
        placement), driven tick-by-tick with the controller's
        placement knob attached (or not — the red lane). The verdict
        reads only the telemetry surface: the placement block's
        imbalance, the migration tallies, quarantine/divergence totals
        and the health rollup."""
        from .sync.sharded import ShardedGeneralDocSet
        metrics.drop_scope('node/')
        metrics.reset_series('sync_convergence_ms')
        metrics.bump('sim_scenario_runs')
        metrics.bump('sim_actors_spawned', self.schedule['n_actors'])
        sharded = ShardedGeneralDocSet(spec['n_docs'] + 8,
                                       n_shards=spec['n_shards'])
        for d in range(spec['n_docs']):
            sharded.placement.pin(f'doc{d}', 0)
        if self.controller:
            kwargs = dict(spec.get('controller_kwargs', {}))
            kwargs.update(self.controller_kwargs)
            FleetController(sharded, **kwargs)
        metrics.subscribe(self._collect)
        try:
            ticks = self.schedule['ticks']
            if metrics.active:
                metrics.emit('sim_scenario_start', scenario=scenario,
                             seed=self.schedule['seed'],
                             n_shards=spec['n_shards'],
                             n_docs=spec['n_docs'],
                             controller=self.controller)

            def apply_tick(tick):
                by_doc = {}
                load = 0
                for _, doc_id, changes in tick['writes']:
                    by_doc.setdefault(doc_id, []).extend(changes)
                    load += sum(len(c['ops']) for c in changes)
                if by_doc:
                    sharded.apply_changes_batch(by_doc)
                metrics.bump('sim_ticks')
                if load:
                    metrics.bump('sim_ops_injected', load)
                if metrics.active:
                    metrics.emit('counter', sim_load_ops=load)
                sharded.tick()

            apply_tick(ticks[0])       # seed phase
            self._events.clear()
            imbalances = []            # per loaded tick, from telemetry
            non_green_polls = 0
            critical_polls = 0
            polls = 0
            peak_resident = 0
            t0 = time.perf_counter()
            for i, tick in enumerate(ticks[1:]):
                apply_tick(tick)
                load = sharded.shard_load()
                if sum(load['apply_ops']):
                    imbalances.append(load['imbalance'])
                if i % 2 == 1:
                    polls += 1
                    st = sharded.fleet_status(docs=False)
                    peak_resident = max(
                        peak_resident,
                        st['memory']['device_plane_bytes'])
                    state = st['health']['state']
                    if state != 'green':
                        non_green_polls += 1
                    if state == 'critical':
                        critical_polls += 1
            for _ in range(spec.get('drain', 0)):
                apply_tick({'writes': []})
            dt = time.perf_counter() - t0
            return self._score_sharded(
                spec, scenario, sharded, dt, imbalances,
                dict(non_green_polls=non_green_polls,
                     critical_polls=critical_polls, polls=polls,
                     peak_resident=peak_resident))
        finally:
            metrics.unsubscribe(self._collect)

    def _score_sharded(self, spec, scenario, sharded, dt, imbalances,
                       polled):
        slo = dict(DEFAULT_SLO)
        slo.update(spec.get('slo', {}))
        status = sharded.fleet_status(docs=False)
        placement = status['placement']
        # the settled operating point: mean imbalance over the last
        # few LOADED quanta (the gauge the dashboards graph)
        tail = imbalances[-5:] if imbalances else [1.0]
        settled = sum(tail) / len(tail)
        final_health = status['health']['state']

        checks = {}

        def check(name, value, ok, bound):
            checks[name] = {'value': value, 'bound': bound,
                            'ok': bool(ok)}

        check('quarantined', status['totals']['quarantined'],
              status['totals']['quarantined'] <=
              slo['quarantined_max'], slo['quarantined_max'])
        check('diverged', status['totals']['diverged'],
              status['totals']['diverged'] <= slo['diverged_max'],
              slo['diverged_max'])
        check('final_health', final_health,
              _HEALTH_RANK[final_health] <=
              _HEALTH_RANK[slo['final_health']], slo['final_health'])
        check('critical_polls', polled['critical_polls'],
              polled['critical_polls'] <= slo['critical_polls_max'],
              slo['critical_polls_max'])
        check('shard_imbalance', round(settled, 3),
              settled <= slo['shard_imbalance_max'],
              slo['shard_imbalance_max'])
        if 'min_migrations' in slo:
            check('migrations', placement['migrations'],
                  placement['migrations'] >= slo['min_migrations'],
                  slo['min_migrations'])

        verdict = 'green' if all(c['ok'] for c in checks.values()) \
            else 'red'
        actions = dict(sharded.controller.actions) \
            if sharded.controller is not None else {}
        result = {
            'scenario': scenario,
            'seed': self.schedule['seed'],
            'controller': self.controller,
            'verdict': verdict,
            'checks': checks,
            'n_ops': self.schedule['n_ops'],
            'n_actors': self.schedule['n_actors'],
            'ops_per_sec': round(self.schedule['n_ops'] /
                                 max(dt, 1e-9), 1),
            'wall_s': round(dt, 3),
            'convergence_ms_p99': None,
            'peak_resident_bytes': polled['peak_resident'],
            'peak_memory_pressure': 0.0,
            'non_green_polls': polled['non_green_polls'],
            'polls': polled['polls'],
            'final_health': final_health,
            'shard_imbalance': round(settled, 3),
            'migrations': placement['migrations'],
            'per_shard': placement['per_shard'],
            'control_actions': actions,
            'control_action_total': sum(actions.values()),
            'schedule_digest': self.schedule['digest'],
            'state_digests': sharded.heartbeat_digests(),
            'events': list(self._events),
        }
        if self.collect_views:
            result['views'] = [canonical(doc_set_view(sharded))]
        if metrics.active:
            metrics.emit(
                'sim_scenario', scenario=scenario, verdict=verdict,
                controller=self.controller,
                ops_per_sec=result['ops_per_sec'],
                shard_imbalance=result['shard_imbalance'],
                migrations=result['migrations'],
                control_action_total=result['control_action_total'],
                failed=[n for n, c in checks.items()
                        if not c['ok']])
        return result

    # -- the SLO scorecard (telemetry surface only) --------------------------

    def _score(self, spec, scenario, doc_sets, dt, polled):
        slo = dict(DEFAULT_SLO)
        slo.update(spec.get('slo', {}))
        statuses = [ds.fleet_status(docs=False) for ds in doc_sets]
        quarantined = sum(s['totals']['quarantined']
                          for s in statuses)
        diverged = sum(s['totals']['diverged'] for s in statuses)
        lag = sum(s['convergence']['replication_lag_ops']
                  for s in statuses)
        births = sum(s['convergence']['pending_births']
                     for s in statuses)
        backpressure = sum(s['health']['signals']
                           .get('backpressure_depth', 0)
                           for s in statuses)
        final_health = max((s['health']['state'] for s in statuses),
                           key=lambda h: _HEALTH_RANK[h])
        conv_p99 = metrics.quantile('sync_convergence_ms', 0.99)
        # replica equality straight off the divergence-audit surface:
        # every node's heartbeat digest map must be PRESENT and
        # identical — a fleet whose digests are unavailable has not
        # proved anything, so None maps fail the check rather than
        # comparing vacuously equal
        digest_maps = [ds.heartbeat_digests() for ds in doc_sets]
        digests_ok = all(m is not None for m in digest_maps) and \
            all(m == digest_maps[0] for m in digest_maps[1:])

        checks = {}

        def check(name, value, ok, bound):
            checks[name] = {'value': value, 'bound': bound,
                            'ok': bool(ok)}

        check('quarantined', quarantined,
              quarantined <= slo['quarantined_max'],
              slo['quarantined_max'])
        check('diverged', diverged,
              diverged <= slo['diverged_max'], slo['diverged_max'])
        check('replicas_digest_equal', digests_ok, digests_ok, True)
        check('replication_lag_ops', lag, lag == 0, 0)
        check('pending_births', births, births == 0, 0)
        check('backpressure_depth', backpressure, backpressure == 0,
              0)
        check('final_health', final_health,
              _HEALTH_RANK[final_health] <=
              _HEALTH_RANK[slo['final_health']], slo['final_health'])
        check('critical_polls', polled['critical_polls'],
              polled['critical_polls'] <= slo['critical_polls_max'],
              slo['critical_polls_max'])
        if conv_p99 is not None:
            check('convergence_ms_p99', round(conv_p99, 2),
                  conv_p99 <= slo['convergence_ms_p99_max'],
                  slo['convergence_ms_p99_max'])
        if 'peak_memory_pressure' in slo:
            check('peak_memory_pressure',
                  round(polled['peak_pressure'], 4),
                  polled['peak_pressure'] <=
                  slo['peak_memory_pressure'],
                  slo['peak_memory_pressure'])
        if 'non_green_polls_max' in slo:
            check('non_green_polls', polled['non_green_polls'],
                  polled['non_green_polls'] <=
                  slo['non_green_polls_max'],
                  slo['non_green_polls_max'])

        verdict = 'green' if all(c['ok'] for c in checks.values()) \
            else 'red'
        actions = {}
        for ds in doc_sets:
            if ds.controller is not None:
                for name, n in ds.controller.actions.items():
                    actions[name] = actions.get(name, 0) + n
        result = {
            'scenario': scenario,
            'seed': self.schedule['seed'],
            'controller': self.controller,
            'verdict': verdict,
            'checks': checks,
            'n_ops': self.schedule['n_ops'],
            'n_actors': self.schedule['n_actors'],
            'ops_per_sec': round(self.schedule['n_ops'] /
                                 max(dt, 1e-9), 1),
            'wall_s': round(dt, 3),
            'convergence_ms_p99': round(conv_p99, 2)
            if conv_p99 is not None else None,
            'peak_resident_bytes': polled['peak_resident'],
            'peak_memory_pressure': round(polled['peak_pressure'], 4),
            'non_green_polls': polled['non_green_polls'],
            'polls': polled['polls'],
            'final_health': final_health,
            'control_actions': actions,
            'control_action_total': sum(actions.values()),
            'schedule_digest': self.schedule['digest'],
            # node-0's digest map: the determinism comparand of the
            # replay tests (all nodes' maps are equal when
            # replicas_digest_equal holds)
            'state_digests': digest_maps[0],
            'events': list(self._events),
        }
        if self.collect_views:
            result['views'] = [canonical(doc_set_view(ds))
                               for ds in doc_sets]
        if metrics.active:
            metrics.emit(
                'sim_scenario', scenario=scenario, verdict=verdict,
                controller=self.controller,
                ops_per_sec=result['ops_per_sec'],
                convergence_ms_p99=result['convergence_ms_p99'],
                peak_resident_bytes=result['peak_resident_bytes'],
                control_action_total=result['control_action_total'],
                failed=[n for n, c in checks.items()
                        if not c['ok']])
        return result


def run_scenario(scenario, seed=DEFAULT_SEED, scale='smoke',
                 controller=True, collect_views=False,
                 controller_kwargs=None):
    """Build the schedule and run it once; returns the scorecard."""
    return FleetSim(scenario, seed=seed, scale=scale,
                    controller=controller,
                    collect_views=collect_views,
                    controller_kwargs=controller_kwargs).run()


def run_oracle(schedule):
    """The clean dict-path oracle: the SAME schedule replayed over
    plain :class:`GeneralDocSet` nodes on a fault-free
    dict-protocol fabric (no serving layer, no wire format, no
    admission, no partitions) — the byte-identity comparand of the
    scenario regression tests. Returns each node's canonical
    materialized views."""
    spec = schedule['spec']
    doc_sets = [GeneralDocSet(spec['n_docs'] + 8)
                for _ in range(spec['n_nodes'])]
    fleet = ChaosFleet(doc_sets, seed=schedule['seed'] + 1,
                       batching=True,
                       heartbeat_every=spec['heartbeat_every'])
    try:
        for tick in schedule['ticks']:
            by_node = {}
            for node, doc_id, changes in tick['writes']:
                by_node.setdefault(node, {})[doc_id] = changes
            for node, batch in by_node.items():
                doc_sets[node].apply_changes_batch(batch)
            fleet.tick()
        fleet.run(max_ticks=8000)
        return [canonical(doc_set_view(ds)) for ds in doc_sets]
    finally:
        fleet.close()
