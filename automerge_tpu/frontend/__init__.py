"""Frontend: document lifecycle, change requests, patch application.

Parity with `/root/reference/frontend/index.js` (public surface at
frontend/index.js:438-443). The frontend holds the materialized document as
frozen objects, turns mutations made in ``change()`` callbacks into change
requests, and applies backend patches. It can run **with** an immediate
in-process backend (``init({'backend': ...})``) or **without** one, in
which case requests queue up optimistically and are reconciled against
remote patches with a deliberately-approximate operational transform
(frontend/index.js:131-192).
"""

from ..common import ROOT_ID, is_object
from ..text import Text
from ..uuid import uuid as _uuid
from .apply_patch import apply_diffs, update_parent_objects, clone_root_object
from .context import Context
from .datatypes import AmMap
from .proxies import root_object_proxy, MapProxy, ListProxy

__all__ = [
    'init', 'change', 'empty_change', 'apply_patch', 'can_undo', 'undo',
    'can_redo', 'redo', 'get_object_id', 'get_actor_id', 'set_actor_id',
    'get_conflicts', 'get_backend_state', 'get_element_ids', 'Text',
]


def _freeze_tree(updated):
    for obj in updated.values():
        if hasattr(obj, '_freeze'):
            obj._freeze()


def update_root_object(doc, updated, inbound, state):
    """Build a new frozen root object incorporating `updated`
    (frontend/index.js:15-39)."""
    new_doc = updated.get(ROOT_ID)
    if new_doc is None:
        new_doc = clone_root_object(doc._cache[ROOT_ID])
        updated[ROOT_ID] = new_doc

    for object_id in doc._cache:
        if object_id not in updated:
            updated[object_id] = doc._cache[object_id]

    object.__setattr__(new_doc, '_actor_id', get_actor_id(doc))
    object.__setattr__(new_doc, '_options', doc._options)
    object.__setattr__(new_doc, '_cache', updated)
    object.__setattr__(new_doc, '_inbound', inbound)
    object.__setattr__(new_doc, '_state', state)
    _freeze_tree(updated)
    return new_doc


def ensure_single_assignment(ops):
    """Keep only the most recent assignment per (obj, key)
    (frontend/index.js:46-64)."""
    assignments = {}
    result = []
    for op in reversed(ops):
        if op['action'] in ('set', 'del', 'link'):
            seen = assignments.setdefault(op['obj'], {})
            if not seen.get(op['key']):
                seen[op['key']] = True
                result.append(op)
        else:
            result.append(op)
    return list(reversed(result))


def make_change(doc, request_type, context, message):
    """Create a change request; apply immediately if a backend is attached,
    else queue it optimistically (frontend/index.js:73-105)."""
    actor = get_actor_id(doc)
    if not actor:
        raise ValueError('Actor ID must be initialized with set_actor_id() '
                         'before making a change')
    state = dict(doc._state)
    state['seq'] += 1
    deps = dict(state['deps'])
    deps.pop(actor, None)

    request = {'requestType': request_type, 'actor': actor, 'seq': state['seq'],
               'deps': deps}
    if message is not None:
        request['message'] = message
    if context is not None:
        request['ops'] = ensure_single_assignment(context.ops)

    backend = doc._options.get('backend')
    if backend:
        backend_state, patch = backend.apply_local_change(state['backendState'], request)
        state['backendState'] = backend_state
        state['requests'] = []
        return apply_patch_to_doc(doc, patch, state, True), request

    queued_request = dict(request)
    queued_request['before'] = doc
    if context is not None:
        queued_request['diffs'] = context.diffs
    state['requests'] = state['requests'] + [queued_request]
    updated = context.updated if context is not None else {}
    inbound = context.inbound if context is not None else dict(doc._inbound)
    return update_root_object(doc, updated, inbound, state), request


def apply_patch_to_doc(doc, patch, state, from_backend):
    """(frontend/index.js:114-129)"""
    actor = get_actor_id(doc)
    inbound = dict(doc._inbound)
    updated = {}
    # Queued undo/redo requests replayed through this path carry no diffs.
    # Replayed request diffs (not from_backend) are OT-transformed
    # approximations and get lenient index handling; authoritative
    # backend diffs stay strict.
    apply_diffs(patch.get('diffs', []), doc._cache, updated, inbound,
                lenient=not from_backend)
    update_parent_objects(doc._cache, updated, inbound)

    if from_backend:
        seq = patch.get('clock', {}).get(actor)
        if seq and seq > state['seq']:
            state['seq'] = seq
        # Patches may omit deps/undo state; the reference sets state.deps
        # to undefined in that case, which its next makeChange treats as
        # {} (frontend/index.js:114-129, :79) — the {} defaults here are
        # that exact behavior, not a loosening. Both real backends always
        # populate these fields.
        state['deps'] = patch.get('deps', {})
        state['canUndo'] = patch.get('canUndo', False)
        state['canRedo'] = patch.get('canRedo', False)
    return update_root_object(doc, updated, inbound, state)


def transform_request(request, patch):
    """Transform a pending local request past a remote patch — a simple,
    deliberately-approximate operational transform used only while waiting
    for the backend's authoritative reply (frontend/index.js:131-192)."""
    transformed = []
    for local in request.get('diffs', []):
        local = dict(local)
        drop = False
        for remote in patch['diffs']:
            if (local['obj'] == remote['obj'] and local['type'] == 'list'
                    and local['action'] in ('insert', 'set', 'remove')):
                if remote['action'] == 'insert' and remote['index'] <= local['index']:
                    local['index'] += 1
                if remote['action'] == 'remove' and remote['index'] < local['index']:
                    local['index'] -= 1
                if remote['action'] == 'remove' and remote['index'] == local['index']:
                    if local['action'] == 'set':
                        local['action'] = 'insert'
                    if local['action'] == 'remove':
                        drop = True
                        break
        if not drop:
            transformed.append(local)
    request['diffs'] = transformed


def init(options=None):
    """Create an empty document (frontend/index.js:197-222)."""
    if isinstance(options, str):
        options = {'actorId': options}
    elif options is None:
        options = {}
    elif not isinstance(options, dict):
        raise TypeError(f'Unsupported value for init() options: {options}')
    if options.get('actorId') is None and not options.get('deferActorId'):
        options = dict(options)
        options['actorId'] = _uuid()

    root = AmMap(ROOT_ID)
    cache = {ROOT_ID: root}
    state = {'seq': 0, 'requests': [], 'deps': {}, 'canUndo': False, 'canRedo': False}
    backend = options.get('backend')
    if backend:
        state['backendState'] = backend.init()
    object.__setattr__(root, '_actor_id', options.get('actorId'))
    object.__setattr__(root, '_options', options)
    object.__setattr__(root, '_cache', cache)
    object.__setattr__(root, '_inbound', {})
    object.__setattr__(root, '_state', state)
    root._freeze()
    return root


def change(doc, message=None, callback=None):
    """Make local edits inside a callback receiving a mutable proxy; returns
    (new_doc, request) (frontend/index.js:233-261)."""
    if isinstance(doc, (MapProxy, ListProxy)):
        raise TypeError('Calls to change() cannot be nested')
    if doc._object_id != ROOT_ID:
        raise TypeError('The first argument to change() must be the document root')
    if callable(message) and callback is None:
        message, callback = None, message
    if message is not None and not isinstance(message, str):
        raise TypeError('Change message must be a string')

    actor_id = get_actor_id(doc)
    if not actor_id:
        raise ValueError('Actor ID must be initialized with set_actor_id() '
                         'before making a change')
    context = Context(doc, actor_id)
    callback(root_object_proxy(context))

    if not context.updated:
        return doc, None
    update_parent_objects(doc._cache, context.updated, context.inbound)
    return make_change(doc, 'change', context, message)


def empty_change(doc, message=None):
    """A change with no ops — used to acknowledge receipt of changes by
    incorporating them into `deps` (frontend/index.js:271-281)."""
    if message is not None and not isinstance(message, str):
        raise TypeError('Change message must be a string')
    actor_id = get_actor_id(doc)
    if not actor_id:
        raise ValueError('Actor ID must be initialized with set_actor_id() '
                         'before making a change')
    return make_change(doc, 'change', Context(doc, actor_id), message)


def apply_patch(doc, patch):
    """Apply a backend patch, replaying any still-pending local requests on
    top (frontend/index.js:289-324)."""
    state = dict(doc._state)

    if state['requests']:
        base_doc = state['requests'][0]['before']
        if patch.get('actor') == get_actor_id(doc) and patch.get('seq') is not None:
            if state['requests'][0]['seq'] != patch['seq']:
                raise ValueError(
                    f"Mismatched sequence number: patch {patch['seq']} does not "
                    f"match next request {state['requests'][0]['seq']}")
            state['requests'] = [dict(req) for req in state['requests'][1:]]
        else:
            state['requests'] = [dict(req) for req in state['requests']]
    else:
        base_doc = doc
        state['requests'] = []

    if doc._options.get('backend'):
        if patch.get('state') is None:
            raise ValueError('When an immediate backend is used, a patch must '
                             'contain the new backend state')
        state['backendState'] = patch['state']
        state['requests'] = []
        return apply_patch_to_doc(doc, patch, state, True)

    new_doc = apply_patch_to_doc(base_doc, patch, state, True)
    for request in state['requests']:
        request['before'] = new_doc
        transform_request(request, patch)
        new_doc = apply_patch_to_doc(request['before'], request, state, False)
    return new_doc


def _is_undo_redo_in_flight(doc):
    return any(req['requestType'] in ('undo', 'redo')
               for req in doc._state['requests'])


def can_undo(doc):
    return bool(doc._state['canUndo']) and not _is_undo_redo_in_flight(doc)


def undo(doc, message=None):
    """(frontend/index.js:349-360)"""
    if message is not None and not isinstance(message, str):
        raise TypeError('Change message must be a string')
    if not doc._state['canUndo']:
        raise ValueError('Cannot undo: there is nothing to be undone')
    if _is_undo_redo_in_flight(doc):
        raise ValueError('Can only have one undo in flight at any one time')
    return make_change(doc, 'undo', None, message)


def can_redo(doc):
    return bool(doc._state['canRedo']) and not _is_undo_redo_in_flight(doc)


def redo(doc, message=None):
    """(frontend/index.js:379-390)"""
    if message is not None and not isinstance(message, str):
        raise TypeError('Change message must be a string')
    if not doc._state['canRedo']:
        raise ValueError('Cannot redo: there is no prior undo')
    if _is_undo_redo_in_flight(doc):
        raise ValueError('Can only have one redo in flight at any one time')
    return make_change(doc, 'redo', None, message)


def get_object_id(obj):
    return getattr(obj, '_object_id', None)


def get_actor_id(doc):
    return doc._state.get('actorId') or doc._options.get('actorId')


def set_actor_id(doc, actor_id):
    state = dict(doc._state)
    state['actorId'] = actor_id
    return update_root_object(doc, {}, doc._inbound, state)


def get_conflicts(obj):
    return obj._conflicts


def get_backend_state(doc):
    return doc._state.get('backendState')


def get_element_ids(lst):
    if isinstance(lst, Text):
        return [e['elemId'] for e in lst.elems]
    return lst._elem_ids


# camelCase aliases (reference API parity)
emptyChange = empty_change
applyPatch = apply_patch
canUndo = can_undo
canRedo = can_redo
getObjectId = get_object_id
getActorId = get_actor_id
setActorId = set_actor_id
getConflicts = get_conflicts
getBackendState = get_backend_state
getElementIds = get_element_ids
