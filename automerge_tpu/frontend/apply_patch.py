"""Applies backend diffs to the materialized document tree.

Parity with `/root/reference/frontend/apply_patch.js`: documents are trees
of frozen :class:`AmMap` / :class:`AmList` / :class:`Text` objects with
structure sharing — applying a patch clones only the objects on the path
from each modified object to the root (``update_parent_objects``), leaving
everything else aliased to the previous version.
"""

import re

from ..common import ROOT_ID, is_object
from ..text import Text
from .datatypes import AmMap, AmList

_ELEMID_RE = re.compile(r'^(.*):(\d+)$')


def parse_elem_id(elem_id):
    """'actor:counter' -> (counter, actor) (apply_patch.js:10-16)."""
    match = _ELEMID_RE.match(elem_id or '')
    if not match:
        raise ValueError(f'Not a valid elemId: {elem_id}')
    return int(match.group(2)), match.group(1)


def _child_references_map(obj, key):
    refs = {}
    conflicts = obj._conflicts.get(key, {})
    children = [obj.get(key)] + list(conflicts.values())
    for child in children:
        if is_object(child):
            refs[child._object_id] = True
    return refs


def _child_references_list(lst, index):
    refs = {}
    conflicts = (lst._conflicts[index] if index < len(lst._conflicts) else None) or {}
    children = ([lst[index]] if index < len(lst) else []) + list(conflicts.values())
    for child in children:
        if is_object(child):
            refs[child._object_id] = True
    return refs


def update_inbound(object_id, refs_before, refs_after, inbound):
    """Maintain the child->parent index (apply_patch.js:40-51)."""
    for ref in refs_before:
        if ref not in refs_after:
            inbound.pop(ref, None)
    for ref in refs_after:
        if inbound.get(ref) is not None and inbound[ref] != object_id:
            raise ValueError(f'Object {ref} has multiple parents')
        if ref not in inbound:
            inbound[ref] = object_id


def clone_map_object(original, object_id):
    """Writable copy of an immutable map object (apply_patch.js:57-66)."""
    if original is not None and original._object_id != object_id:
        raise ValueError(
            f'cloneMapObject ID mismatch: {original._object_id} != {object_id}')
    obj = AmMap(object_id)
    if original is not None:
        dict.update(obj, original)
        object.__setattr__(obj, '_conflicts', dict(original._conflicts))
    return obj


def _resolve(value, link, cache, updated):
    if link:
        resolved = updated.get(value)
        return resolved if resolved is not None else cache.get(value)
    return value


def update_map_object(diff, cache, updated, inbound):
    """Apply one diff to a map object (apply_patch.js:74-106)."""
    if diff['obj'] not in updated:
        updated[diff['obj']] = clone_map_object(cache.get(diff['obj']), diff['obj'])
    obj = updated[diff['obj']]
    conflicts = obj._conflicts
    refs_before, refs_after = {}, {}

    if diff['action'] == 'create':
        pass
    elif diff['action'] == 'set':
        refs_before = _child_references_map(obj, diff['key'])
        dict.__setitem__(obj, diff['key'],
                         _resolve(diff.get('value'), diff.get('link'), cache, updated))
        if diff.get('conflicts'):
            conflicts[diff['key']] = {
                c['actor']: _resolve(c.get('value'), c.get('link'), cache, updated)
                for c in diff['conflicts']}
        else:
            conflicts.pop(diff['key'], None)
        refs_after = _child_references_map(obj, diff['key'])
    elif diff['action'] == 'remove':
        refs_before = _child_references_map(obj, diff['key'])
        dict.pop(obj, diff['key'], None)
        conflicts.pop(diff['key'], None)
    else:
        raise ValueError('Unknown action type: ' + diff['action'])

    update_inbound(diff['obj'], refs_before, refs_after, inbound)


def parent_map_object(object_id, cache, updated):
    """Point a map at the updated versions of its children (apply_patch.js:113-141)."""
    if object_id not in updated:
        updated[object_id] = clone_map_object(cache.get(object_id), object_id)
    obj = updated[object_id]

    for key in list(obj.keys()):
        value = obj[key]
        if is_object(value) and value._object_id in updated:
            dict.__setitem__(obj, key, updated[value._object_id])

        conflicts = obj._conflicts.get(key)
        if conflicts:
            new_conflicts = None
            for actor_id, value in conflicts.items():
                if is_object(value) and value._object_id in updated:
                    if new_conflicts is None:
                        new_conflicts = dict(conflicts)
                        obj._conflicts[key] = new_conflicts
                    new_conflicts[actor_id] = updated[value._object_id]


def clone_list_object(original, object_id):
    """Writable copy of an immutable list object (apply_patch.js:147-160)."""
    if original is not None and original._object_id != object_id:
        raise ValueError(
            f'cloneListObject ID mismatch: {original._object_id} != {object_id}')
    lst = AmList(object_id)
    if original is not None:
        list.extend(lst, original)
        object.__setattr__(lst, '_conflicts', list(original._conflicts))
        object.__setattr__(lst, '_elem_ids', list(original._elem_ids))
        object.__setattr__(lst, '_max_elem', original._max_elem)
    return lst


def update_list_object(diff, cache, updated, inbound, lenient=False):
    """Apply one diff to a list object (apply_patch.js:168-210).

    ``lenient`` is set ONLY when replaying in-flight local request diffs:
    they pass through the deliberately-approximate OT
    (frontend/index.js:131-192, documented there as "incomplete and
    incorrect"), which can produce out-of-range indexes and inserts
    without elemIds. The reference survives because JS arrays tolerate
    both; here lenient mode clamps indexes (a remove past the end is a
    no-op) — the backend's authoritative patch replaces every transient
    approximation. Authoritative patches stay strict: a bad index there
    is a backend bug and must fail loudly, not diverge silently.
    """
    if diff['obj'] not in updated:
        updated[diff['obj']] = clone_list_object(cache.get(diff['obj']), diff['obj'])
    lst = updated[diff['obj']]
    conflicts, elem_ids = lst._conflicts, lst._elem_ids
    value, conflict = None, None

    if diff['action'] in ('insert', 'set'):
        value = _resolve(diff.get('value'), diff.get('link'), cache, updated)
        if diff.get('conflicts'):
            conflict = {c['actor']: _resolve(c.get('value'), c.get('link'), cache, updated)
                        for c in diff['conflicts']}

    refs_before, refs_after = {}, {}
    if diff['action'] == 'create':
        # a create may carry the true maxElem — visible inserts alone
        # under-count it past tombstones (see backend get_patch)
        if diff.get('maxElem'):
            object.__setattr__(lst, '_max_elem',
                               max(lst._max_elem, diff['maxElem']))
    elif diff['action'] == 'maxElem':
        # batched device patches net out insert+delete within one apply;
        # this diff keeps the local elemId counter truthful anyway
        object.__setattr__(lst, '_max_elem',
                           max(lst._max_elem, diff['value']))
    elif diff['action'] == 'insert':
        index = diff['index']
        elem_id = diff.get('elemId')
        if lenient:
            index = min(index, len(lst))
        if elem_id is not None:
            object.__setattr__(lst, '_max_elem',
                               max(lst._max_elem, parse_elem_id(elem_id)[0]))
        elif not lenient:
            raise ValueError('List insert diff requires an elemId')
        if index > len(lst):
            raise IndexError(f'List insert index {index} out of range')
        list.insert(lst, index, value)
        conflicts.insert(index, conflict)
        elem_ids.insert(index, elem_id)
        refs_after = _child_references_list(lst, index)
    elif diff['action'] == 'set':
        if lenient and diff['index'] >= len(lst):  # transient OT overshoot
            list.append(lst, value)
            conflicts.append(conflict)
            elem_ids.append(None)
            refs_after = _child_references_list(lst, len(lst) - 1)
        else:
            refs_before = _child_references_list(lst, diff['index'])
            list.__setitem__(lst, diff['index'], value)
            conflicts[diff['index']] = conflict
            refs_after = _child_references_list(lst, diff['index'])
    elif diff['action'] == 'remove':
        if lenient and diff['index'] >= len(lst):
            pass                                   # transient OT overshoot
        else:
            refs_before = _child_references_list(lst, diff['index'])
            list.__delitem__(lst, diff['index'])
            del conflicts[diff['index']]
            del elem_ids[diff['index']]
    else:
        raise ValueError('Unknown action type: ' + diff['action'])

    update_inbound(diff['obj'], refs_before, refs_after, inbound)


def parent_list_object(object_id, cache, updated):
    """Point a list at the updated versions of its children (apply_patch.js:217-245)."""
    if object_id not in updated:
        updated[object_id] = clone_list_object(cache.get(object_id), object_id)
    lst = updated[object_id]

    for index in range(len(lst)):
        value = lst[index]
        if is_object(value) and value._object_id in updated:
            list.__setitem__(lst, index, updated[value._object_id])

        conflicts = lst._conflicts[index] if index < len(lst._conflicts) else None
        if conflicts:
            new_conflicts = None
            for actor_id, value in conflicts.items():
                if is_object(value) and value._object_id in updated:
                    if new_conflicts is None:
                        new_conflicts = dict(conflicts)
                        lst._conflicts[index] = new_conflicts
                    new_conflicts[actor_id] = updated[value._object_id]


def update_text_object(diffs, start_index, end_index, cache, updated):
    """Apply a run of text diffs with run-coalesced splices
    (apply_patch.js:253-316)."""
    object_id = diffs[start_index]['obj']
    if object_id not in updated:
        if object_id in cache:
            elems = list(cache[object_id].elems)
            max_elem = cache[object_id]._max_elem
            updated[object_id] = Text(object_id, elems, max_elem)
        else:
            updated[object_id] = Text(object_id)

    elems = updated[object_id].elems
    max_elem = updated[object_id]._max_elem
    splice_pos, deletions, insertions = -1, 0, []

    i = start_index
    while i <= end_index:
        diff = diffs[i]
        if diff['action'] == 'create':
            # true maxElem may exceed the visible inserts' (tombstones)
            max_elem = max(max_elem, diff.get('maxElem', 0))
        elif diff['action'] == 'maxElem':
            max_elem = max(max_elem, diff['value'])
        elif diff['action'] == 'insert':
            if splice_pos < 0:
                splice_pos, deletions, insertions = diff['index'], 0, []
            max_elem = max(max_elem, parse_elem_id(diff['elemId'])[0])
            insertions.append({'elemId': diff['elemId'], 'value': diff.get('value'),
                               'conflicts': diff.get('conflicts')})
            if (i == end_index or diffs[i + 1]['action'] != 'insert'
                    or diffs[i + 1]['index'] != diff['index'] + 1):
                elems[splice_pos:splice_pos + deletions] = insertions
                splice_pos = -1
        elif diff['action'] == 'set':
            elems[diff['index']] = {'elemId': elems[diff['index']]['elemId'],
                                    'value': diff.get('value'),
                                    'conflicts': diff.get('conflicts')}
        elif diff['action'] == 'remove':
            if splice_pos < 0:
                splice_pos, deletions, insertions = diff['index'], 0, []
            deletions += 1
            if (i == end_index or diffs[i + 1]['action'] not in ('insert', 'remove')
                    or diffs[i + 1]['index'] != diff['index']):
                elems[splice_pos:splice_pos + deletions] = []
                splice_pos = -1
        else:
            raise ValueError('Unknown action type: ' + diff['action'])
        i += 1

    updated[object_id] = Text(object_id, elems, max_elem)


def update_parent_objects(cache, updated, inbound):
    """Propagate updated children up to the root (apply_patch.js:326-344)."""
    affected = updated
    while affected:
        parents = {}
        for child_id in list(affected.keys()):
            parent_id = inbound.get(child_id)
            if parent_id:
                parents[parent_id] = True
        affected = parents

        for object_id in parents:
            existing = updated.get(object_id)
            if existing is None:
                existing = cache.get(object_id)
            if isinstance(existing, list):
                parent_list_object(object_id, cache, updated)
            else:
                parent_map_object(object_id, cache, updated)


def apply_diffs(diffs, cache, updated, inbound, lenient=False):
    """Dispatch diffs to the per-type appliers; text diffs are grouped into
    runs per object (apply_patch.js:353-373). ``lenient`` applies only to
    replayed in-flight request diffs (see update_list_object)."""
    start_index = 0
    for end_index, diff in enumerate(diffs):
        if diff['type'] == 'map':
            update_map_object(diff, cache, updated, inbound)
            start_index = end_index + 1
        elif diff['type'] == 'list':
            update_list_object(diff, cache, updated, inbound, lenient)
            start_index = end_index + 1
        elif diff['type'] == 'text':
            if end_index == len(diffs) - 1 or diffs[end_index + 1]['obj'] != diff['obj']:
                update_text_object(diffs, start_index, end_index, cache, updated)
                start_index = end_index + 1
        else:
            raise TypeError(f"Unknown object type: {diff['type']}")


def clone_root_object(root):
    if root._object_id != ROOT_ID:
        raise ValueError(f'Not the root object: {root._object_id}')
    return clone_map_object(root, ROOT_ID)
