"""Mutation context: translates proxy mutations into CRDT operations.

Parity with `/root/reference/frontend/context.js`. A :class:`Context` is
created per ``change()`` callback; proxy mutations call into it, and it
records both the operation list for the backend (``ops``) and the
optimistic local diffs (``diffs``) applied immediately to the document.
"""

from ..common import is_object
from ..text import Text, get_elem_id
from ..uuid import uuid
from .apply_patch import apply_diffs
from .datatypes import AmList


def _is_primitive(value):
    return value is None or isinstance(value, (str, bool, int, float))


def _valid_value(value):
    return _is_primitive(value) or is_object(value)


class Context:
    def __init__(self, doc, actor_id):
        self.actor_id = actor_id
        self.cache = doc._cache
        self.updated = {}
        self.inbound = dict(doc._inbound)
        self.ops = []
        self.diffs = []
        self.instantiate_object = None  # installed by root_object_proxy()

    def add_op(self, operation):
        self.ops.append(operation)

    def apply(self, diff):
        """Optimistically apply a local diff (context.js:32-35)."""
        self.diffs.append(diff)
        apply_diffs([diff], self.cache, self.updated, self.inbound)

    def get_object(self, object_id):
        obj = self.updated.get(object_id)
        if obj is None:
            obj = self.cache.get(object_id)
        if obj is None:
            raise ValueError(f'Target object does not exist: {object_id}')
        return obj

    def get_object_field(self, object_id, key):
        obj = self.get_object(object_id)
        if isinstance(obj, Text):
            if not isinstance(key, int) or key < 0 or key >= len(obj):
                return None
            value = obj.elems[key]['value']
        elif isinstance(obj, AmList):
            if not isinstance(key, int) or key < 0 or key >= len(obj):
                return None
            value = obj[key]
        else:
            value = obj.get(key)
        if is_object(value):
            return self.instantiate_object(value._object_id)
        return value

    def create_nested_objects(self, value):
        """Recursively create CRDT objects for a nested value; returns the
        root objectId (context.js:65-94)."""
        existing_id = getattr(value, '_object_id', None)
        if isinstance(existing_id, str):
            return existing_id
        object_id = uuid()

        if isinstance(value, Text):
            if len(value) > 0:
                raise ValueError('Assigning a non-empty Text object is not supported')
            self.apply({'action': 'create', 'type': 'text', 'obj': object_id})
            self.add_op({'action': 'makeText', 'obj': object_id})
        elif isinstance(value, (list, tuple)):
            self.apply({'action': 'create', 'type': 'list', 'obj': object_id})
            self.add_op({'action': 'makeList', 'obj': object_id})
            self.splice(object_id, 0, 0, list(value))
        else:
            self.apply({'action': 'create', 'type': 'map', 'obj': object_id})
            self.add_op({'action': 'makeMap', 'obj': object_id})
            for key in value:
                self.set_map_key(object_id, key, value[key])
        return object_id

    def set_map_key(self, object_id, key, value):
        """(context.js:100-126)"""
        if not isinstance(key, str):
            raise ValueError(f'The key of a map entry must be a string, not {type(key).__name__}')
        if key == '':
            raise ValueError('The key of a map entry must not be an empty string')
        if key.startswith('_'):
            raise ValueError(f'Map entries starting with underscore are not allowed: {key}')

        obj = self.get_object(object_id)
        if not _valid_value(value):
            raise TypeError(f'Unsupported type of value: {type(value).__name__}')
        if is_object(value):
            child_id = self.create_nested_objects(value)
            self.apply({'action': 'set', 'type': 'map', 'obj': object_id,
                        'key': key, 'value': child_id, 'link': True})
            self.add_op({'action': 'link', 'obj': object_id, 'key': key, 'value': child_id})
        else:
            # No-op if the assigned value strictly equals the existing one and
            # the assignment does not resolve a conflict (context.js:120-122).
            same = (key in obj and obj[key] == value
                    and isinstance(obj[key], bool) == isinstance(value, bool))
            if not same or obj._conflicts.get(key):
                self.apply({'action': 'set', 'type': 'map', 'obj': object_id,
                            'key': key, 'value': value})
                self.add_op({'action': 'set', 'obj': object_id, 'key': key, 'value': value})

    def delete_map_key(self, object_id, key):
        """(context.js:131-137)"""
        obj = self.get_object(object_id)
        if key in obj:
            self.apply({'action': 'remove', 'type': 'map', 'obj': object_id, 'key': key})
            self.add_op({'action': 'del', 'obj': object_id, 'key': key})

    def insert_list_item(self, object_id, index, value):
        """(context.js:143-167)"""
        lst = self.get_object(object_id)
        if index < 0 or index > len(lst):
            raise IndexError(
                f'List index {index} is out of bounds for list of length {len(lst)}')
        if not _valid_value(value):
            raise TypeError(f'Unsupported type of value: {type(value).__name__}')

        max_elem = lst._max_elem + 1
        obj_type = 'text' if isinstance(lst, Text) else 'list'
        prev_id = '_head' if index == 0 else get_elem_id(lst, index - 1)
        elem_id = f'{self.actor_id}:{max_elem}'
        self.add_op({'action': 'ins', 'obj': object_id, 'key': prev_id, 'elem': max_elem})

        if is_object(value):
            child_id = self.create_nested_objects(value)
            self.apply({'action': 'insert', 'type': obj_type, 'obj': object_id,
                        'index': index, 'value': child_id, 'link': True, 'elemId': elem_id})
            self.add_op({'action': 'link', 'obj': object_id, 'key': elem_id, 'value': child_id})
        else:
            self.apply({'action': 'insert', 'type': obj_type, 'obj': object_id,
                        'index': index, 'value': value, 'elemId': elem_id})
            self.add_op({'action': 'set', 'obj': object_id, 'key': elem_id, 'value': value})
        obj = self.get_object(object_id)
        object.__setattr__(obj, '_max_elem', max_elem)

    def set_list_index(self, object_id, index, value):
        """(context.js:173-199)"""
        lst = self.get_object(object_id)
        if index == len(lst):
            self.insert_list_item(object_id, index, value)
            return
        if index < 0 or index > len(lst):
            raise IndexError(
                f'List index {index} is out of bounds for list of length {len(lst)}')
        if not _valid_value(value):
            raise TypeError(f'Unsupported type of value: {type(value).__name__}')

        elem_id = get_elem_id(lst, index)
        obj_type = 'text' if isinstance(lst, Text) else 'list'

        if is_object(value):
            child_id = self.create_nested_objects(value)
            self.apply({'action': 'set', 'type': obj_type, 'obj': object_id,
                        'index': index, 'value': child_id, 'link': True})
            self.add_op({'action': 'link', 'obj': object_id, 'key': elem_id, 'value': child_id})
        else:
            if isinstance(lst, Text):
                current = lst.elems[index]['value']
                conflict = lst.elems[index].get('conflicts')
            else:
                current = lst[index]
                conflict = lst._conflicts[index] if index < len(lst._conflicts) else None
            same = current == value and isinstance(current, bool) == isinstance(value, bool)
            if not same or conflict:
                self.apply({'action': 'set', 'type': obj_type, 'obj': object_id,
                            'index': index, 'value': value})
                self.add_op({'action': 'set', 'obj': object_id, 'key': elem_id, 'value': value})

    def splice(self, object_id, start, deletions, insertions):
        """(context.js:206-228)"""
        lst = self.get_object(object_id)
        obj_type = 'text' if isinstance(lst, Text) else 'list'

        if deletions > 0:
            if start < 0 or start > len(lst) - deletions:
                raise IndexError(
                    f'{deletions} deletions starting at index {start} are out of '
                    f'bounds for list of length {len(lst)}')
            for i in range(deletions):
                self.add_op({'action': 'del', 'obj': object_id,
                             'key': get_elem_id(lst, start)})
                self.apply({'action': 'remove', 'type': obj_type, 'obj': object_id,
                            'index': start})
                if i == 0:
                    lst = self.get_object(object_id)

        for i, value in enumerate(insertions):
            self.insert_list_item(object_id, start + i, value)
