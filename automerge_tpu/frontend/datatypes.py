"""Materialized document object types.

The reference materializes documents as frozen plain JS objects/arrays with
hidden metadata properties (frontend/constants.js, frontend/index.js:15-39).
Here the equivalents are ``AmMap`` (a dict subclass) and ``AmList`` (a list
subclass) carrying the same metadata as Python attributes:

* ``_object_id``   — the CRDT object ID (reference OBJECT_ID)
* ``_conflicts``   — per-key / per-index conflict sets (reference CONFLICTS)
* ``_elem_ids``    — list only: elemId per index (reference ELEM_IDS)
* ``_max_elem``    — list only: max elem counter (reference MAX_ELEM)

The root map additionally carries ``_options``, ``_cache``, ``_inbound``,
``_state`` and ``_actor_id``. Objects are frozen after materialization:
mutation must go through ``change()`` callbacks.
"""


class FrozenError(TypeError):
    pass


class AmMap(dict):
    """A materialized map object. Supports attribute-style reads
    (``doc.cards``) in addition to item access (``doc['cards']``)."""

    _am_attrs = ('_object_id', '_conflicts', '_options', '_cache', '_inbound',
                 '_state', '_actor_id', '_frozen', '_change')

    def __init__(self, object_id=None, *args, **kwargs):
        super().__init__(*args, **kwargs)
        object.__setattr__(self, '_object_id', object_id)
        object.__setattr__(self, '_conflicts', {})
        object.__setattr__(self, '_frozen', False)

    # -- attribute-style access --------------------------------------------

    def __getattr__(self, name):
        if name.startswith('_'):
            raise AttributeError(name)
        try:
            return self[name]
        except KeyError:
            raise AttributeError(name) from None

    def __setattr__(self, name, value):
        if name in AmMap._am_attrs:
            if getattr(self, '_frozen', False) and name not in ('_state',):
                raise FrozenError('Cannot modify a frozen document object')
            object.__setattr__(self, name, value)
        else:
            self[name] = value

    # -- freeze enforcement -------------------------------------------------

    def _check_frozen(self):
        if getattr(self, '_frozen', False):
            raise FrozenError(
                'This object is frozen; use change() to modify an Automerge document')

    def __setitem__(self, key, value):
        self._check_frozen()
        super().__setitem__(key, value)

    def __delitem__(self, key):
        self._check_frozen()
        super().__delitem__(key)

    def update(self, *args, **kwargs):
        self._check_frozen()
        super().update(*args, **kwargs)

    def pop(self, *args):
        self._check_frozen()
        return super().pop(*args)

    def popitem(self):
        self._check_frozen()
        return super().popitem()

    def clear(self):
        self._check_frozen()
        super().clear()

    def setdefault(self, *args):
        self._check_frozen()
        return super().setdefault(*args)

    def _freeze(self):
        object.__setattr__(self, '_frozen', True)


class AmList(list):
    """A materialized list object."""

    def __init__(self, object_id=None, *args):
        super().__init__(*args)
        object.__setattr__(self, '_object_id', object_id)
        object.__setattr__(self, '_conflicts', [])
        object.__setattr__(self, '_elem_ids', [])
        object.__setattr__(self, '_max_elem', 0)
        object.__setattr__(self, '_frozen', False)

    def _check_frozen(self):
        if getattr(self, '_frozen', False):
            raise FrozenError(
                'This object is frozen; use change() to modify an Automerge document')

    def __setitem__(self, key, value):
        self._check_frozen()
        super().__setitem__(key, value)

    def __delitem__(self, key):
        self._check_frozen()
        super().__delitem__(key)

    def append(self, value):
        self._check_frozen()
        super().append(value)

    def extend(self, values):
        self._check_frozen()
        super().extend(values)

    def insert(self, index, value):
        self._check_frozen()
        super().insert(index, value)

    def pop(self, *args):
        self._check_frozen()
        return super().pop(*args)

    def remove(self, value):
        self._check_frozen()
        super().remove(value)

    def sort(self, **kwargs):
        self._check_frozen()
        super().sort(**kwargs)

    def reverse(self):
        self._check_frozen()
        super().reverse()

    def clear(self):
        self._check_frozen()
        super().clear()

    def _freeze(self):
        object.__setattr__(self, '_frozen', True)
