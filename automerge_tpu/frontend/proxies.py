"""Mutable proxy objects handed to ``change()`` callbacks.

Parity with `/root/reference/frontend/proxies.js`: inside a change callback
the document looks like ordinary mutable maps/lists, but every mutation is
routed through the :class:`~automerge_tpu.frontend.context.Context`, which
records CRDT ops and optimistic diffs. Reads always reflect mutations made
earlier in the same callback.

``MapProxy`` supports both attribute style (``doc.cards``) and item style
(``doc['cards']``). ``ListProxy`` supports Python list idioms (``append``,
``insert``, ``pop``, slicing reads) plus the reference's array surface
(``insert_at``/``delete_at``/``push``/``splice``/``unshift``/``fill`` with
camelCase aliases).
"""

from ..common import ROOT_ID
from ..text import Text


def _parse_list_index(key):
    if isinstance(key, str) and key.isdigit():
        key = int(key)
    if not isinstance(key, int) or isinstance(key, bool):
        raise TypeError(f'A list index must be a number, but you passed {key!r}')
    if key < 0:
        raise IndexError(f'A list index must be positive, but you passed {key}')
    return key


class MapProxy:
    __slots__ = ('_context', '_obj_id')

    def __init__(self, context, object_id):
        object.__setattr__(self, '_context', context)
        object.__setattr__(self, '_obj_id', object_id)

    # -- metadata ----------------------------------------------------------

    @property
    def _object_id(self):
        return self._obj_id

    @property
    def _type(self):
        return 'map'

    @property
    def _change(self):
        return self._context

    # -- reads -------------------------------------------------------------

    def __getitem__(self, key):
        return self._context.get_object_field(self._obj_id, key)

    def __getattr__(self, name):
        if name.startswith('_'):
            raise AttributeError(name)
        return self._context.get_object_field(self._obj_id, name)

    def get(self, key, default=None):
        value = self._context.get_object_field(self._obj_id, key)
        if value is None and key not in self:
            return default
        return value

    def __contains__(self, key):
        return key in self._context.get_object(self._obj_id)

    def __len__(self):
        return len(self._context.get_object(self._obj_id))

    def __iter__(self):
        return iter(list(self._context.get_object(self._obj_id).keys()))

    def keys(self):
        return list(self._context.get_object(self._obj_id).keys())

    def values(self):
        return [self[k] for k in self.keys()]

    def items(self):
        return [(k, self[k]) for k in self.keys()]

    def __repr__(self):
        return f'MapProxy({self._obj_id})'

    # -- writes ------------------------------------------------------------

    def __setitem__(self, key, value):
        self._context.set_map_key(self._obj_id, key, _unproxy(value))

    def __setattr__(self, name, value):
        self._context.set_map_key(self._obj_id, name, _unproxy(value))

    def update(self, other=(), /, **kwargs):
        """Bulk assignment (the reference's Object.assign support,
        proxies_test.js:68-73).

        Like every method name on this proxy (``get``/``keys``/...), a
        document field literally named ``update`` must be read with item
        access (``doc['update']``) — attribute access resolves the method.
        """
        items = other.items() if hasattr(other, 'items') else other
        for k, v in items:
            self[k] = v
        for k, v in kwargs.items():
            self[k] = v

    def __delitem__(self, key):
        self._context.delete_map_key(self._obj_id, key)

    def __delattr__(self, name):
        self._context.delete_map_key(self._obj_id, name)


class ListProxy:
    __slots__ = ('_context', '_obj_id')

    def __init__(self, context, object_id):
        object.__setattr__(self, '_context', context)
        object.__setattr__(self, '_obj_id', object_id)

    @property
    def _object_id(self):
        return self._obj_id

    @property
    def _type(self):
        return 'list'

    @property
    def _change(self):
        return self._context

    # -- reads -------------------------------------------------------------

    def _target(self):
        return self._context.get_object(self._obj_id)

    @property
    def length(self):
        return len(self._target())

    def __len__(self):
        return len(self._target())

    def __getitem__(self, key):
        if isinstance(key, slice):
            return [self[i] for i in range(*key.indices(len(self)))]
        n = len(self)
        if isinstance(key, int) and key < 0:
            key += n
        key = _parse_list_index(key)
        return self._context.get_object_field(self._obj_id, key)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __contains__(self, value):
        return any(v == value for v in self)

    def index(self, value):
        for i, v in enumerate(self):
            if v == value:
                return i
        raise ValueError(f'{value!r} is not in list')

    def index_of(self, value):
        for i, v in enumerate(self):
            if v == value:
                return i
        return -1

    indexOf = index_of

    def count(self, value):
        return sum(1 for v in self if v == value)

    def __repr__(self):
        return f'ListProxy({self._obj_id})'

    def __eq__(self, other):
        if isinstance(other, (list, tuple, ListProxy)):
            return list(self) == list(other)
        return NotImplemented

    def __hash__(self):
        return id(self)

    # -- writes ------------------------------------------------------------

    def __setitem__(self, key, value):
        n = len(self)
        if isinstance(key, int) and key < 0:
            key += n
        self._context.set_list_index(self._obj_id, _parse_list_index(key), _unproxy(value))

    def __delitem__(self, key):
        n = len(self)
        if isinstance(key, int) and key < 0:
            key += n
        self._context.splice(self._obj_id, _parse_list_index(key), 1, [])

    def append(self, *values):
        self._context.splice(self._obj_id, len(self), 0, [_unproxy(v) for v in values])

    def push(self, *values):
        self.append(*values)
        return len(self)

    def extend(self, values):
        self.append(*values)

    def insert(self, index, *values):
        self._context.splice(self._obj_id, _parse_list_index(index), 0,
                             [_unproxy(v) for v in values])
        return self

    insert_at = insert
    insertAt = insert

    def delete_at(self, index, num_delete=1):
        self._context.splice(self._obj_id, _parse_list_index(index), num_delete, [])
        return self

    deleteAt = delete_at

    def pop(self, index=None):
        n = len(self)
        if n == 0:
            if index is None:
                return None
            raise IndexError('pop from empty list')
        if index is None:
            index = n - 1
        elif index < 0:
            index += n
        value = self[index]
        self._context.splice(self._obj_id, index, 1, [])
        return value

    def shift(self):
        if len(self) == 0:
            return None
        value = self[0]
        self._context.splice(self._obj_id, 0, 1, [])
        return value

    def unshift(self, *values):
        self._context.splice(self._obj_id, 0, 0, [_unproxy(v) for v in values])
        return len(self)

    def splice(self, start, delete_count=None, *values):
        start = _parse_list_index(start)
        if delete_count is None:
            delete_count = len(self) - start
        deleted = [self[start + n] for n in range(delete_count)]
        self._context.splice(self._obj_id, start, delete_count,
                             [_unproxy(v) for v in values])
        return deleted

    def remove(self, value):
        self._context.splice(self._obj_id, self.index(value), 1, [])

    def fill(self, value, start=0, end=None):
        if end is None:
            end = len(self)
        for index in range(_parse_list_index(start), _parse_list_index(end)):
            self._context.set_list_index(self._obj_id, index, _unproxy(value))
        return self


def _unproxy(value):
    """Resolve proxies to their materialized objects so nested assignment of
    an existing Automerge object links by ID (context.js:66)."""
    if isinstance(value, (MapProxy, ListProxy)):
        return value._context.get_object(value._obj_id)
    if getattr(value, '_object_id', None) is not None:
        return value  # existing materialized CRDT object: link by ID
    if isinstance(value, dict):
        return {k: _unproxy(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_unproxy(v) for v in value]
    return value


def instantiate_proxy(context, object_id):
    obj = context.get_object(object_id)
    if isinstance(obj, (list, Text)):
        return ListProxy(context, object_id)
    return MapProxy(context, object_id)


def root_object_proxy(context):
    context.instantiate_object = lambda object_id: instantiate_proxy(context, object_id)
    return MapProxy(context, ROOT_ID)
