"""Async backend worker: the frontend/backend split, actually split.

The reference introduced the frontend/backend separation "so that some
of the work can be moved to a background thread" (CHANGELOG.md:39-41)
and the frontend's request queue + operational transform exist precisely
to tolerate a backend that answers LATER (frontend/index.js:91-104,
131-192). This module runs that architecture for real: a
:class:`BackendWorker` owns the backend state on its own thread; the UI
thread keeps a backend-less (split-mode) frontend document, submits
change requests and remote changes to the worker, and applies the
patches whenever they come back — local edits stay optimistic in
between, reconciled by the frontend's OT when lagging patches land.

Wire discipline matches the reference worker model: ONLY plain-JSON
requests/changes flow in and patches flow out; the backend state never
crosses the thread boundary. Works with either backend (the host oracle
or the device backend — both expose apply_local_change/apply_changes).
"""

import queue
import threading


class BackendWorker:
    """A backend living on a worker thread, speaking the request/patch
    protocol.

    Args:
      backend: the backend MODULE (``automerge_tpu.backend`` or
        ``automerge_tpu.device.backend``).
      on_patch: optional callback invoked ON THE WORKER THREAD with each
        patch; when omitted, patches queue for :meth:`poll_patches`.
    """

    def __init__(self, backend, on_patch=None):
        self._backend = backend
        self._state = backend.init()
        self._on_patch = on_patch
        self._in = queue.Queue()
        self._out = queue.Queue()
        self._error = None
        self._lock = threading.Lock()
        self._pending = 0
        self._idle = threading.Event()
        self._idle.set()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # -- UI-thread surface ---------------------------------------------------

    def submit_request(self, request):
        """Queue one local change request (the dict `Frontend.change`
        returns in split mode)."""
        self._check_poisoned()
        self._push(('request', request))

    def submit_changes(self, changes):
        """Queue remote wire changes (network deliveries)."""
        self._check_poisoned()
        self._push(('changes', list(changes)))

    def _check_poisoned(self):
        if self._error is not None:
            raise RuntimeError(
                'backend worker failed on an earlier item; frontend and '
                'backend are out of sync — discard and rebuild') \
                from self._error

    def poll_patches(self, timeout=0.0):
        """Patches ready so far (possibly empty). With a timeout, waits
        up to that long for the FIRST patch."""
        out = []
        if self._error is not None:
            raise self._error
        try:
            out.append(self._out.get(timeout=timeout)
                       if timeout else self._out.get_nowait())
            while True:
                out.append(self._out.get_nowait())
        except queue.Empty:
            pass
        if self._error is not None:
            raise self._error
        return out

    def _wait_idle(self, timeout):
        if not self._idle.wait(timeout):
            raise TimeoutError('backend worker did not drain')
        if self._error is not None:
            raise self._error

    def drain(self, timeout=10.0):
        """Wait until every queued item has been processed; returns the
        patches produced meanwhile."""
        self._wait_idle(timeout)
        return self.poll_patches()

    def get_changes(self, have_deps, timeout=10.0):
        """Changes a peer with clock `have_deps` lacks (waits for the
        queue to drain first — the log must include everything
        submitted — WITHOUT consuming queued patches: the frontend
        still needs them to reconcile its request queue)."""
        self._wait_idle(timeout)
        return self._backend.get_missing_changes(self._state, have_deps)

    def close(self):
        self._in.put(None)
        self._thread.join()

    # -- worker thread -------------------------------------------------------

    def _push(self, item):
        with self._lock:
            self._pending += 1
            self._idle.clear()
        self._in.put(item)

    def _run(self):
        while True:
            item = self._in.get()
            if item is None:
                return
            kind, payload = item
            try:
                if self._error is not None:
                    # poisoned: refuse to advance past the failure so
                    # the backend state stays at a known point
                    continue
                if kind == 'request':
                    self._state, patch = self._backend.apply_local_change(
                        self._state, payload)
                else:
                    self._state, patch = self._backend.apply_changes(
                        self._state, payload)
                if self._on_patch is not None:
                    self._on_patch(patch)
                else:
                    self._out.put(patch)
            except BaseException as e:     # surfaced on poll/drain
                self._error = e
            finally:
                with self._lock:
                    self._pending -= 1
                    if self._pending == 0:
                        self._idle.set()
