"""Read-only save-file interop: reference transit-JSON saves -> changes.

The reference lineage serializes a document with ``Automerge.save(doc)``
as the transit-JS encoding of its Immutable.js change history (a List of
change Maps) — this framework's own save format is different by design
(packed columnar snapshots + a JSON change log; see README "Snapshots &
persistence"). The WIRE format is shared (per-change JSON, proven by the
conformance suite), so interop only needs the container decoded:
:func:`load_reference_save` turns a reference save blob into the plain
change list the existing replay edges consume
(``GeneralDocSet.apply_changes`` / the per-doc backend) — a one-way
door, import only.

The decoder covers the transit subset transit-immutable-js actually
emits for a change history: ground JSON values, ``["^ ", k, v, ...]``
maps, tagged values ``["~#tag", rep]`` for Immutable List/Map/Set
(``iL``/``iM``/``iO``/``iS``), ``~``-escaped scalar strings
(``~~``/``~:keyword``/``~i<int>``/``~d<float>``), and the write cache
(``"^<code>"`` back-references over cacheable strings: map keys and
``~``-prefixed strings of length >= 4, in first-occurrence order).
Anything outside that subset raises :class:`ReferenceSaveError` naming
the construct — a corrupt or newer-format save fails loudly, never as a
silently wrong document.
"""

import json

_CACHE_DIGITS = 44          # transit-js CACHE_CODE_DIGITS
_BASE_CHAR = 48             # codes start at '0'


class ReferenceSaveError(ValueError):
    """A reference save blob failed to decode (not transit-JSON, an
    unsupported transit construct, or not a change history)."""


def _code_to_index(code):
    """Inverse of transit-js indexToCode: '^X' -> cache index."""
    if len(code) == 1:
        return ord(code) - _BASE_CHAR
    if len(code) == 2:
        return (ord(code[0]) - _BASE_CHAR) * _CACHE_DIGITS + \
            (ord(code[1]) - _BASE_CHAR)
    raise ReferenceSaveError(f'malformed transit cache code ^{code}')


class _TransitReader:
    """One-pass transit-JSON decoder (read cache included)."""

    def __init__(self):
        self.cache = []

    def _resolve(self, s, as_key):
        """Cache machinery for one string as written: back-references
        resolve, cacheable first occurrences append. The reader must
        mirror the writer's cache EXACTLY or every later ^code is
        off-by-N — transit-js isCacheable: length >= 4 AND (map key,
        or one of the '~:' keyword / '~$' symbol / '~#' tag prefixes;
        typed scalars like '~i<long int>' are NOT cached)."""
        if s.startswith('^') and s != '^ ':
            idx = _code_to_index(s[1:])
            if idx >= len(self.cache):
                raise ReferenceSaveError(
                    f'transit cache reference ^{s[1:]} before '
                    f'definition')
            return self.cache[idx]
        if len(s) >= 4 and (as_key or
                            (s[0] == '~' and s[1] in ':$#')):
            self.cache.append(s)
        return s

    def _decode_str(self, s):
        if not s.startswith('~'):
            return s
        tag = s[1] if len(s) > 1 else ''
        if tag in ('~', '^', '`'):
            return s[1:]
        if tag in (':', '$'):
            return s[2:]                 # keyword/symbol -> plain str
        if tag == 'i':
            return int(s[2:])
        if tag in ('d', 'f'):
            return float(s[2:])
        if tag == '_':
            return None
        if tag == '?':
            return s[2:] == 't'
        if tag == '#':
            return s                     # tag heads handled by read()
        raise ReferenceSaveError(
            f'unsupported transit scalar {s!r}')

    def _read_scalar(self, s, as_key):
        return self._decode_str(self._resolve(s, as_key))

    def _tagged(self, tag, rep):
        if tag in ('iL', 'iS', 'iOS', 'list', 'set'):
            return list(rep)
        if tag in ('iM', 'iO', 'iOM'):
            if len(rep) % 2:
                raise ReferenceSaveError(
                    f'transit map rep of odd length {len(rep)}')
            return {rep[i]: rep[i + 1] for i in range(0, len(rep), 2)}
        if tag == "'":
            return rep                   # top-level scalar quote
        raise ReferenceSaveError(f'unsupported transit tag ~#{tag}')

    def read(self, node, as_key=False):
        if isinstance(node, str):
            return self._read_scalar(node, as_key)
        if isinstance(node, list):
            if not node:
                return []
            head = node[0]
            if isinstance(head, str):
                if head == '^ ':
                    items = node[1:]
                    if len(items) % 2:
                        raise ReferenceSaveError(
                            'transit map-as-array of odd length')
                    out = {}
                    for i in range(0, len(items), 2):
                        k = self.read(items[i], as_key=True)
                        out[k] = self.read(items[i + 1])
                    return out
                resolved = self._resolve(head, as_key=False)
                if resolved.startswith('~#'):
                    if len(node) != 2:
                        raise ReferenceSaveError(
                            f'tagged value {resolved!r} without a '
                            f'single rep')
                    return self._tagged(resolved[2:],
                                        self.read(node[1]))
                return [self._decode_str(resolved)] + \
                    [self.read(x) for x in node[1:]]
            return [self.read(x) for x in node]
        if isinstance(node, dict):
            # verbose-mode map (writer('json-verbose')): accepted too
            return {self._read_scalar(k, True): self.read(v)
                    for k, v in node.items()}
        return node                      # number / bool / null


_SUPPORTED_ACTIONS = {'set', 'del', 'ins', 'link',
                      'makeMap', 'makeList', 'makeText'}


def _normalize_change(change, i):
    if not isinstance(change, dict):
        raise ReferenceSaveError(
            f'change {i} decoded to {type(change).__name__}, not a '
            f'map')
    for field in ('actor', 'seq', 'ops'):
        if field not in change:
            raise ReferenceSaveError(
                f"change {i} is missing '{field}'")
    ops = change['ops']
    if not isinstance(ops, list):
        raise ReferenceSaveError(f'change {i} ops is not a list')
    for op in ops:
        if not isinstance(op, dict):
            raise ReferenceSaveError(f'change {i} op is not a map')
        action = op.get('action')
        if action not in _SUPPORTED_ACTIONS:
            raise ReferenceSaveError(
                f'change {i} carries unsupported op action '
                f'{action!r} (reference tables/rich-text era saves '
                f'are out of scope)')
    out = {'actor': change['actor'], 'seq': int(change['seq']),
           'deps': dict(change.get('deps') or {}), 'ops': ops}
    if 'message' in change:
        out['message'] = change['message']
    return out


def load_reference_save(blob):
    """Decode a reference-lineage ``Automerge.save`` blob (transit-JSON
    change history) into a plain change list, ready for the existing
    replay edges::

        changes = load_reference_save(open('doc.save').read())
        doc_set.apply_changes('imported', changes)

    Accepts ``str`` or ``bytes``. Raises :class:`ReferenceSaveError`
    on anything that is not a supported save (with the offending
    construct named). Import only — this framework saves its own
    packed snapshot format; see the README compat matrix.
    """
    if isinstance(blob, (bytes, bytearray)):
        try:
            blob = bytes(blob).decode('utf-8')
        except UnicodeDecodeError as err:
            raise ReferenceSaveError(
                f'reference save is not UTF-8 ({err})') from None
    try:
        node = json.loads(blob)
    except ValueError as err:
        raise ReferenceSaveError(
            f'reference save is not valid JSON ({err})') from None
    decoded = _TransitReader().read(node)
    if not isinstance(decoded, list):
        raise ReferenceSaveError(
            f'reference save decoded to {type(decoded).__name__}, '
            f'not a change list')
    return [_normalize_change(c, i) for i, c in enumerate(decoded)]


loadReferenceSave = load_reference_save
