"""Native host runtime: C++ order-statistic sequence index with COW handles.

The reference's L1 native-performance role is played by `SkipList`
(backend/skip_list.js:114-334) — an immutable order-statistic index giving
elemId<->index in O(log n), introduced "for performance" (CHANGELOG.md:140)
to replace an O(n) design. Here that component is a C++ indexable skip
list (`native/seq_index.cpp`) behind refcount-based copy-on-write handles:

* :class:`SeqIndex` quacks like the ``list`` of elemId strings the oracle
  backend otherwise keeps (``index/insert/__delitem__/__getitem__/len``),
  so every call site works with either representation.
* ``clone()`` is O(1): snapshots share one C++ structure. The structure is
  physically copied only when a *shared* snapshot is mutated, via a
  linear-time structural copy in C++. Within one batched apply session
  (the fast path: ``apply_changes(state, many_changes)``) at most one copy
  happens and every subsequent edit is in-place O(log n). Per-change apply
  loops pay one O(n) copy per change — the same asymptotics as the plain
  list fallback's clone, at memcpy-level constants — so batching is where
  the 20-30x replay speedup comes from.
* elemId strings are interned process-wide to int64 keys; only ints cross
  the C boundary.

The C library is compiled on demand with g++ (no pip deps); if a compiler
or the .so is unavailable, callers fall back to plain Python lists.
"""

import ctypes
import os
import subprocess
import tempfile

_LIB = None
_LOAD_ATTEMPTED = False

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_PKG_DIR, '_native', 'libamtpu.so')
_SRC_PATH = os.path.join(os.path.dirname(_PKG_DIR), 'native', 'seq_index.cpp')


def _bind(lib):
    lib.amsl_new.argtypes = [ctypes.c_uint64]
    lib.amsl_new.restype = ctypes.c_void_p
    lib.amsl_copy.argtypes = [ctypes.c_void_p]
    lib.amsl_copy.restype = ctypes.c_void_p
    lib.amsl_free.argtypes = [ctypes.c_void_p]
    lib.amsl_free.restype = None
    lib.amsl_len.argtypes = [ctypes.c_void_p]
    lib.amsl_len.restype = ctypes.c_int64
    lib.amsl_insert.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64]
    lib.amsl_insert.restype = ctypes.c_int
    lib.amsl_remove.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.amsl_remove.restype = ctypes.c_int64
    lib.amsl_index_of.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.amsl_index_of.restype = ctypes.c_int64
    lib.amsl_key_at.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.amsl_key_at.restype = ctypes.c_int64
    lib.amsl_fill_keys.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_int64)]
    lib.amsl_fill_keys.restype = None
    return lib


def _compile():
    os.makedirs(os.path.dirname(_SO_PATH), exist_ok=True)
    fd, tmp = tempfile.mkstemp(suffix='.so', dir=os.path.dirname(_SO_PATH))
    os.close(fd)
    try:
        subprocess.run(
            ['g++', '-O2', '-shared', '-fPIC', '-std=c++17',
             _SRC_PATH, '-o', tmp],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO_PATH)  # atomic: concurrent builders both succeed
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load():
    global _LIB, _LOAD_ATTEMPTED
    if _LOAD_ATTEMPTED:
        return _LIB
    _LOAD_ATTEMPTED = True
    if os.environ.get('AUTOMERGE_TPU_NATIVE', '1') == '0':
        return None
    have_src = os.path.exists(_SRC_PATH)
    stale = (have_src and os.path.exists(_SO_PATH)
             and os.path.getmtime(_SO_PATH) < os.path.getmtime(_SRC_PATH))
    if not os.path.exists(_SO_PATH) or stale:
        if not have_src or not _compile():
            if not os.path.exists(_SO_PATH):
                return None
    try:
        _LIB = _bind(ctypes.CDLL(_SO_PATH))
    except OSError:
        _LIB = None
    return _LIB


def available():
    return _load() is not None


# Process-wide elemId interner. elemIds ("actor:counter" strings) are
# append-only over a process lifetime; the table is shared by all indexes.
#
# Growth contract: one entry per distinct elemId ever seen, never pruned
# automatically — integer ids baked into live C++ skip lists must stay
# valid, so entries can only be dropped when no SeqIndex is alive. A
# long-lived process churning through many documents should call
# `reset_intern_table()` at a point where it holds no SeqIndex instances.
_INTERN = {}
_STRS = []


def _intern(key):
    i = _INTERN.get(key)
    if i is None:
        i = len(_STRS)
        _INTERN[key] = i
        _STRS.append(key)
    return i


def intern_table_size():
    """Number of distinct elemIds interned so far (observability hook)."""
    return len(_STRS)


def reset_intern_table():
    """Drop every interned elemId. ONLY safe when no SeqIndex instances
    are alive: live indexes hold the old integer ids and would resolve
    them against the new table."""
    _INTERN.clear()
    _STRS.clear()


_seed_counter = [0]


class SeqIndex:
    """COW handle over one C++ skip list; list-compatible surface."""

    __slots__ = ('_lib', '_h', '_rc')

    def __init__(self, _h=None, _rc=None, _lib=None):
        self._lib = _lib or _load()
        if _h is not None:
            self._h = _h
            self._rc = _rc
        else:
            _seed_counter[0] += 1
            self._h = self._lib.amsl_new(_seed_counter[0])
            if not self._h:
                raise MemoryError('seq index allocation failed')
            self._rc = [1]

    def clone(self):
        """O(1) snapshot: share the structure, bump the refcount."""
        self._rc[0] += 1
        return SeqIndex(_h=self._h, _rc=self._rc, _lib=self._lib)

    def _own(self):
        """Ensure exclusive ownership before a mutation (copy if shared)."""
        if self._rc[0] > 1:
            h = self._lib.amsl_copy(self._h)
            if not h:
                raise MemoryError('seq index copy failed')
            self._rc[0] -= 1
            self._h = h
            self._rc = [1]

    def __del__(self):
        rc = getattr(self, '_rc', None)
        if rc is None:
            return
        rc[0] -= 1
        if rc[0] == 0 and self._h:
            self._lib.amsl_free(self._h)
        self._h = None
        self._rc = None

    def __len__(self):
        return self._lib.amsl_len(self._h)

    def __getitem__(self, index):
        n = len(self)
        if index < 0:
            index += n
        k = self._lib.amsl_key_at(self._h, index)
        if k < 0:
            raise IndexError('seq index out of range')
        return _STRS[k]

    def index(self, key):
        i = self._lib.amsl_index_of(self._h, _INTERN.get(key, -1))
        if i < 0:
            raise ValueError(f'{key!r} is not in seq index')
        return i

    def insert(self, index, key):
        self._own()
        n = len(self)
        if index < 0:
            index = max(n + index, 0)
        if index > n:
            index = n
        rc = self._lib.amsl_insert(self._h, index, _intern(key))
        if rc == -2:
            raise MemoryError('seq index node allocation failed')
        if rc != 0:
            raise ValueError(f'duplicate elemId {key!r}')

    def __delitem__(self, index):
        self._own()
        if index < 0:
            index += len(self)
        if self._lib.amsl_remove(self._h, index) < 0:
            raise IndexError('seq index out of range')

    def __iter__(self):
        n = len(self)
        buf = (ctypes.c_int64 * n)()
        self._lib.amsl_fill_keys(self._h, buf)
        return iter([_STRS[k] for k in buf])

    def __eq__(self, other):
        if isinstance(other, (list, SeqIndex)):
            return list(self) == list(other)
        return NotImplemented

    def __repr__(self):
        return f'SeqIndex({list(self)!r})'


def make_seq_index():
    """A fresh sequence index: native if available, else a plain list."""
    if _load() is not None:
        return SeqIndex()
    return []


def clone_index(idx):
    """Snapshot an index produced by :func:`make_seq_index`."""
    if isinstance(idx, SeqIndex):
        return idx.clone()
    return list(idx)
