"""Native host runtime: C++ order-statistic sequence index with COW handles.

The reference's L1 native-performance role is played by `SkipList`
(backend/skip_list.js:114-334) — an immutable order-statistic index giving
elemId<->index in O(log n), introduced "for performance" (CHANGELOG.md:140)
to replace an O(n) design. Here that component is a C++ indexable skip
list (`native/seq_index.cpp`) behind refcount-based copy-on-write handles:

* :class:`SeqIndex` quacks like the ``list`` of elemId strings the oracle
  backend otherwise keeps (``index/insert/__delitem__/__getitem__/len``),
  so every call site works with either representation.
* ``clone()`` is O(1): snapshots share one C++ structure. The structure is
  physically copied only when a *shared* snapshot is mutated, via a
  linear-time structural copy in C++. Within one batched apply session
  (the fast path: ``apply_changes(state, many_changes)``) at most one copy
  happens and every subsequent edit is in-place O(log n). Per-change apply
  loops pay one O(n) copy per change — the same asymptotics as the plain
  list fallback's clone, at memcpy-level constants — so batching is where
  the 20-30x replay speedup comes from.
* elemId strings are interned process-wide to int64 keys; only ints cross
  the C boundary.

The C library is compiled on demand with g++ (no pip deps); if a compiler
or the .so is unavailable, callers fall back to plain Python lists.
"""

import ctypes
import os
import subprocess
import tempfile

_LIB = None
_LOAD_ATTEMPTED = False

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_PKG_DIR, '_native', 'libamtpu.so')
_SRC_PATH = os.path.join(os.path.dirname(_PKG_DIR), 'native', 'seq_index.cpp')


def _bind(lib):
    lib.amsl_new.argtypes = [ctypes.c_uint64]
    lib.amsl_new.restype = ctypes.c_void_p
    lib.amsl_copy.argtypes = [ctypes.c_void_p]
    lib.amsl_copy.restype = ctypes.c_void_p
    lib.amsl_free.argtypes = [ctypes.c_void_p]
    lib.amsl_free.restype = None
    lib.amsl_len.argtypes = [ctypes.c_void_p]
    lib.amsl_len.restype = ctypes.c_int64
    lib.amsl_insert.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64]
    lib.amsl_insert.restype = ctypes.c_int
    lib.amsl_remove.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.amsl_remove.restype = ctypes.c_int64
    lib.amsl_index_of.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.amsl_index_of.restype = ctypes.c_int64
    lib.amsl_key_at.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.amsl_key_at.restype = ctypes.c_int64
    lib.amsl_fill_keys.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_int64)]
    lib.amsl_fill_keys.restype = None
    return lib


def _compile():
    os.makedirs(os.path.dirname(_SO_PATH), exist_ok=True)
    fd, tmp = tempfile.mkstemp(suffix='.so', dir=os.path.dirname(_SO_PATH))
    os.close(fd)
    try:
        subprocess.run(
            ['g++', '-O2', '-shared', '-fPIC', '-std=c++17',
             _SRC_PATH, '-o', tmp],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO_PATH)  # atomic: concurrent builders both succeed
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load():
    global _LIB, _LOAD_ATTEMPTED
    if _LOAD_ATTEMPTED:
        return _LIB
    _LOAD_ATTEMPTED = True
    if os.environ.get('AUTOMERGE_TPU_NATIVE', '1') == '0':
        return None
    have_src = os.path.exists(_SRC_PATH)
    stale = (have_src and os.path.exists(_SO_PATH)
             and os.path.getmtime(_SO_PATH) < os.path.getmtime(_SRC_PATH))
    if not os.path.exists(_SO_PATH) or stale:
        if not have_src or not _compile():
            if not os.path.exists(_SO_PATH):
                return None
    try:
        _LIB = _bind(ctypes.CDLL(_SO_PATH))
    except OSError:
        _LIB = None
    return _LIB


def available():
    return _load() is not None


# Process-wide elemId interner. elemIds ("actor:counter" strings) are
# append-only over a process lifetime; the table is shared by all indexes.
#
# Growth contract: one entry per distinct elemId ever seen, never pruned
# automatically — integer ids baked into live C++ skip lists must stay
# valid, so entries can only be dropped when no SeqIndex is alive. A
# long-lived process churning through many documents should call
# `reset_intern_table()` at a point where it holds no SeqIndex instances.
_INTERN = {}
_STRS = []


def _intern(key):
    i = _INTERN.get(key)
    if i is None:
        i = len(_STRS)
        _INTERN[key] = i
        _STRS.append(key)
    return i


def intern_table_size():
    """Number of distinct elemIds interned so far (observability hook)."""
    return len(_STRS)


def reset_intern_table():
    """Drop every interned elemId. ONLY safe when no SeqIndex instances
    are alive: live indexes hold the old integer ids and would resolve
    them against the new table."""
    _INTERN.clear()
    _STRS.clear()


_seed_counter = [0]


class SeqIndex:
    """COW handle over one C++ skip list; list-compatible surface."""

    __slots__ = ('_lib', '_h', '_rc')

    def __init__(self, _h=None, _rc=None, _lib=None):
        self._lib = _lib or _load()
        if _h is not None:
            self._h = _h
            self._rc = _rc
        else:
            _seed_counter[0] += 1
            self._h = self._lib.amsl_new(_seed_counter[0])
            if not self._h:
                raise MemoryError('seq index allocation failed')
            self._rc = [1]

    def clone(self):
        """O(1) snapshot: share the structure, bump the refcount."""
        self._rc[0] += 1
        return SeqIndex(_h=self._h, _rc=self._rc, _lib=self._lib)

    def _own(self):
        """Ensure exclusive ownership before a mutation (copy if shared)."""
        if self._rc[0] > 1:
            h = self._lib.amsl_copy(self._h)
            if not h:
                raise MemoryError('seq index copy failed')
            self._rc[0] -= 1
            self._h = h
            self._rc = [1]

    def __del__(self):
        rc = getattr(self, '_rc', None)
        if rc is None:
            return
        rc[0] -= 1
        if rc[0] == 0 and self._h:
            self._lib.amsl_free(self._h)
        self._h = None
        self._rc = None

    def __len__(self):
        return self._lib.amsl_len(self._h)

    def __getitem__(self, index):
        n = len(self)
        if index < 0:
            index += n
        k = self._lib.amsl_key_at(self._h, index)
        if k < 0:
            raise IndexError('seq index out of range')
        return _STRS[k]

    def index(self, key):
        i = self._lib.amsl_index_of(self._h, _INTERN.get(key, -1))
        if i < 0:
            raise ValueError(f'{key!r} is not in seq index')
        return i

    def insert(self, index, key):
        self._own()
        n = len(self)
        if index < 0:
            index = max(n + index, 0)
        if index > n:
            index = n
        rc = self._lib.amsl_insert(self._h, index, _intern(key))
        if rc == -2:
            raise MemoryError('seq index node allocation failed')
        if rc != 0:
            raise ValueError(f'duplicate elemId {key!r}')

    def __delitem__(self, index):
        self._own()
        if index < 0:
            index += len(self)
        if self._lib.amsl_remove(self._h, index) < 0:
            raise IndexError('seq index out of range')

    def __iter__(self):
        n = len(self)
        buf = (ctypes.c_int64 * n)()
        self._lib.amsl_fill_keys(self._h, buf)
        return iter([_STRS[k] for k in buf])

    def __eq__(self, other):
        if isinstance(other, (list, SeqIndex)):
            return list(self) == list(other)
        return NotImplemented

    def __repr__(self):
        return f'SeqIndex({list(self)!r})'


def make_seq_index():
    """A fresh sequence index: native if available, else a plain list."""
    if _load() is not None:
        return SeqIndex()
    return []


def clone_index(idx):
    """Snapshot an index produced by :func:`make_seq_index`."""
    if isinstance(idx, SeqIndex):
        return idx.clone()
    return list(idx)


# ---------------------------------------------------------------------------
# Native general-block staging (the amst_* entry points of libamwire.so).
#
# `device/general._apply_general` turns an admitted block into the staged
# planes the fused device program consumes. The heavy per-op passes —
# object-row mapping, ins grouping + local node minting, elemId
# resolution with the duplicate check, packed field keys, the stable
# field sort, the new-node d-planes and the single packed wire buffer —
# run here in one C++ call, byte-identical to the numpy staging (which
# remains the fallback whenever the library is unavailable, a change was
# queued/dropped at admission, or a late-bound string elemId appears).

import numpy as _np

_STAGE_LIB = None
_STAGE_ATTEMPTED = False

_i64 = ctypes.c_int64
_P8 = ctypes.POINTER(ctypes.c_int8)
_P32 = ctypes.POINTER(ctypes.c_int32)
_P64 = ctypes.POINTER(ctypes.c_int64)
_PU8 = ctypes.POINTER(ctypes.c_uint8)


def _bind_stage(lib):
    lib.amst_stage_general.argtypes = [
        _i64, _P8, _P32, _P8, _P32, _P32, _P32,          # op columns
        _i64, _P32, _P32, _P32, _P32, _P32,              # change columns
        _P32, _P32,                                      # a_tab, k_tab
        _P64, _P64, _P32, _P32, _i64,                    # omap/root/obj
        _P64, _P64, _P64, _P64, _i64,                    # pool tables
        _P32, _P32, _P32, _P32, _P32,                    # pool columns
        _i64,                                            # n_old_mirror
        _i64, _P64, _P64, _P64, _P64]                    # staging cache
    lib.amst_stage_general.restype = ctypes.c_void_p
    for name in ('amst_err', 'amst_err_payload', 'amst_fallback',
                 'amst_n_ins', 'amst_n_arows', 'amst_n_dirty',
                 'amst_n_fields', 'amst_max_seq', 'amst_max_nj',
                 'amst_d_n'):
        fn = getattr(lib, name)
        fn.argtypes = [ctypes.c_void_p]
        fn.restype = _i64
    lib.amst_free.argtypes = [ctypes.c_void_p]
    lib.amst_free.restype = None
    lib.amst_fill_append.argtypes = [ctypes.c_void_p, _P64, _P64, _P64,
                                     _P32, _P64]
    lib.amst_fill_append.restype = None
    lib.amst_fill_res.argtypes = [ctypes.c_void_p] + [_P64] * 5
    lib.amst_fill_res.restype = None
    lib.amst_fill_order.argtypes = [ctypes.c_void_p, _P64, _P32]
    lib.amst_fill_order.restype = None
    lib.amst_fill_fields.argtypes = [ctypes.c_void_p, _P64]
    lib.amst_fill_fields.restype = None
    lib.amst_fill_dirty.argtypes = [ctypes.c_void_p, _P64, _P64, _P64]
    lib.amst_fill_dirty.restype = None
    lib.amst_fill_dplanes.argtypes = [ctypes.c_void_p] + [_P32] * 6
    lib.amst_fill_dplanes.restype = None
    lib.amst_fill_wire.argtypes = [ctypes.c_void_p, _PU8, _i64, _i64,
                                   _i64, _i64, _i64, _i64, _P64]
    lib.amst_fill_wire.restype = None
    lib.amst_fill_wire_wide.argtypes = [ctypes.c_void_p, _PU8, _i64,
                                        _i64, _i64, _i64, _i64, _i64]
    lib.amst_fill_wire_wide.restype = None
    return lib


def stage_lib():
    """The staging library, or None (no native codec / stale binary
    without the amst_* symbols / AUTOMERGE_TPU_NATIVE_STAGE=0)."""
    global _STAGE_LIB, _STAGE_ATTEMPTED
    if _STAGE_ATTEMPTED:
        return _STAGE_LIB
    _STAGE_ATTEMPTED = True
    if os.environ.get('AUTOMERGE_TPU_NATIVE_STAGE', '1') == '0':
        return None
    from . import wire as _wire
    lib = _wire._load()
    if lib is None:
        return None
    try:
        _STAGE_LIB = _bind_stage(lib)
    except AttributeError:
        _STAGE_LIB = None            # stale .so predating the stager
    return _STAGE_LIB


def stage_available():
    return stage_lib() is not None


# ---------------------------------------------------------------------------
# Native view gather (the amst_view_* entry points of libamwire.so):
# the batched materialization's field-sort + winner select and the
# visible-element walk, byte-identical to the numpy fallbacks in
# `device/general_backend.winner_select` / `visible_walk`.

_VIEW_LIB = None
_VIEW_ATTEMPTED = False


def _bind_view(lib):
    lib.amst_view_winners.argtypes = [_i64, _P64, _P64]
    lib.amst_view_winners.restype = ctypes.c_void_p
    lib.amst_view_walk.argtypes = [_i64, _P64, _P64, _P64, _i64, _P64,
                                   _P32, _PU8, _P32]
    lib.amst_view_walk.restype = ctypes.c_void_p
    lib.amst_view_n.argtypes = [ctypes.c_void_p]
    lib.amst_view_n.restype = _i64
    lib.amst_view_fill.argtypes = [ctypes.c_void_p, _P64, _P64, _P64]
    lib.amst_view_fill.restype = None
    lib.amst_view_free.argtypes = [ctypes.c_void_p]
    lib.amst_view_free.restype = None
    return lib


def view_lib():
    """The view-gather library, or None (no native codec / stale
    binary without the amst_view_* symbols /
    AUTOMERGE_TPU_NATIVE_VIEW=0)."""
    global _VIEW_LIB, _VIEW_ATTEMPTED
    if _VIEW_ATTEMPTED:
        return _VIEW_LIB
    _VIEW_ATTEMPTED = True
    if os.environ.get('AUTOMERGE_TPU_NATIVE_VIEW', '1') == '0':
        return None
    from . import wire as _wire
    lib = _wire._load()
    if lib is None:
        return None
    try:
        _VIEW_LIB = _bind_view(lib)
    except AttributeError:
        _VIEW_LIB = None             # stale .so predating the views
    return _VIEW_LIB


def view_available():
    return view_lib() is not None


def view_winners(field, rank):
    """Native field-sort + winner select: ``(fields, winner_pos)`` for
    packed int64 field keys and per-entry actor string ranks, or None
    when the library is unavailable (caller falls back to numpy)."""
    lib = view_lib()
    if lib is None:
        return None
    field = _np.ascontiguousarray(field, _np.int64)
    rank = _np.ascontiguousarray(rank, _np.int64)
    h = lib.amst_view_winners(len(field), _p64(field), _p64(rank))
    if not h:
        raise MemoryError('native view allocation failed')
    try:
        m = int(lib.amst_view_n(h))
        fields = _np.empty(m, _np.int64)
        wpos = _np.empty(m, _np.int64)
        lib.amst_view_fill(h, _p64(fields), _p64(wpos), None)
    finally:
        lib.amst_view_free(h)
    return fields, wpos


def view_walk(objs, pool):
    """Native visible-element walk over ``objs`` (ascending sequence
    object rows): ``(seg, local, counts)`` in per-object document
    order, or None when the library is unavailable."""
    lib = view_lib()
    if lib is None:
        return None
    objs = _np.ascontiguousarray(objs, _np.int64)
    n_of = _np.ascontiguousarray(pool.n_of, _np.int64)
    pos_sorted = _np.ascontiguousarray(pool.pos_sorted, _np.int64)
    pos_row = _np.ascontiguousarray(pool.pos_row, _np.int64)
    local = _np.ascontiguousarray(pool.local, _np.int32)
    visible = _np.ascontiguousarray(pool.visible, _np.uint8)
    vis_index = _np.ascontiguousarray(pool.vis_index, _np.int32)
    h = lib.amst_view_walk(
        len(objs), _p64(objs), _p64(pos_sorted), _p64(pos_row),
        pool.n_nodes, _p64(n_of), _p32(local),
        visible.ctypes.data_as(_PU8), _p32(vis_index))
    if not h:
        raise MemoryError('native view allocation failed')
    try:
        m = int(lib.amst_view_n(h))
        seg = _np.empty(m, _np.int64)
        loc = _np.empty(m, _np.int64)
        counts = _np.empty(len(objs), _np.int64)
        lib.amst_view_fill(h, _p64(seg), _p64(loc), _p64(counts))
    finally:
        lib.amst_view_free(h)
    return seg, loc, counts


# ---------------------------------------------------------------------------
# Native wire-blob emit (the amwe_* entry points of libamwire.so): change
# rows of a retained ChangeBlock -> compact canonical JSON bytes, the
# encode half of the zero-re-encode sync tick. Byte-identical to the
# Python fallback in `wire._emit_change_py` by construction — the host
# pre-escapes every string literal; C++ only splices spans and formats
# integers.

_EMIT_LIB = None
_EMIT_ATTEMPTED = False


def _bind_emit(lib):
    lib.amwe_emit_general.argtypes = [
        _i64, _P64,                                  # rows
        _P32, _P32, _P32, _P32, _P32,                # change columns
        _P32, _P8, _P32, _P8, _P32, _P32, _P32,      # op columns
        _P32,                                        # val_local
        ctypes.c_char_p, _P64, ctypes.c_char_p, _P64,
        ctypes.c_char_p, _P64, ctypes.c_char_p, _P64]
    lib.amwe_emit_general.restype = ctypes.c_void_p
    lib.amwe_bytes.argtypes = [ctypes.c_void_p]
    lib.amwe_bytes.restype = _i64
    lib.amwe_fill.argtypes = [ctypes.c_void_p, ctypes.c_char_p, _P64]
    lib.amwe_fill.restype = None
    lib.amwe_free.argtypes = [ctypes.c_void_p]
    lib.amwe_free.restype = None
    return lib


def emit_lib():
    """The wire-emit library, or None (no native codec / stale binary
    without the amwe_* symbols / AUTOMERGE_TPU_NATIVE_EMIT=0)."""
    global _EMIT_LIB, _EMIT_ATTEMPTED
    if _EMIT_ATTEMPTED:
        return _EMIT_LIB
    _EMIT_ATTEMPTED = True
    if os.environ.get('AUTOMERGE_TPU_NATIVE_EMIT', '1') == '0':
        return None
    from . import wire as _wire
    lib = _wire._load()
    if lib is None:
        return None
    try:
        _EMIT_LIB = _bind_emit(lib)
    except AttributeError:
        _EMIT_LIB = None             # stale .so predating the emitter
    return _EMIT_LIB


def emit_available():
    return emit_lib() is not None


def _lit_blob(lits):
    """(concatenated bytes, int64 offsets) of a literal table."""
    blob = b''.join(lits)
    off = _np.zeros(len(lits) + 1, _np.int64)
    if lits:
        _np.cumsum([len(x) for x in lits], out=off[1:])
    return blob, off


def emit_change_rows(block, rows_arr, lits, vlits, sel, use, v):
    """Native batch emit of general-block change rows: one ``bytes``
    per row, or None when the library is unavailable (the caller falls
    back to the Python emitter). ``lits`` are the block's pre-escaped
    (actors, keys, objs) literal tables; ``vlits`` maps referenced
    value rows to their literal bytes; ``sel``/``use``/``v`` is the
    caller's op selection (``wire._op_selection`` — computed once,
    shared with the value-literal build)."""
    lib = emit_lib()
    if lib is None:
        return None
    # joined table blobs cache on the block next to the literal lists
    # (wire._block_lits) — a fleet serve must not re-join per call
    cacheobj = block._wire_lits if isinstance(block._wire_lits, dict) \
        else None
    blobs = cacheobj.get('blobs') if cacheobj is not None else None
    if blobs is None:
        actors_l, keys_l, objs_l = lits
        blobs = (_lit_blob(actors_l), _lit_blob(keys_l),
                 _lit_blob(objs_l))
        if cacheobj is not None:
            cacheobj['blobs'] = blobs
    (a_b, a_off), (k_b, k_off), (o_b, o_off) = blobs
    vids = _np.asarray(sorted(vlits), _np.int64)
    v_b, v_off = _lit_blob([vlits[int(i)] for i in vids])
    # per-op local value index (-1 none), filled for the selected ops
    # only — one vectorized remap, no per-op Python
    val_local = _np.full(block.n_ops, -1, _np.int32)
    if len(vids) and len(sel):
        val_local[sel[use]] = _np.searchsorted(
            vids, v[use]).astype(_np.int32)
    h = lib.amwe_emit_general(
        len(rows_arr), _p64(rows_arr),
        _p32(block.actor), _p32(block.seq),
        _p32(block.dep_ptr), _p32(block.dep_actor),
        _p32(block.dep_seq),
        _p32(block.op_ptr), _p8(block.action), _p32(block.obj),
        _p8(block.key_kind), _p32(block.key), _p32(block.key_elem),
        _p32(block.elem), _p32(val_local),
        a_b, _p64(a_off), k_b, _p64(k_off),
        o_b, _p64(o_off), v_b, _p64(v_off))
    if not h:
        raise MemoryError('native wire emit allocation failed')
    try:
        nbytes = int(lib.amwe_bytes(h))
        buf = ctypes.create_string_buffer(max(nbytes, 1))
        offsets = _np.empty(len(rows_arr) + 1, _np.int64)
        lib.amwe_fill(h, buf, _p64(offsets))
        raw = buf.raw[:nbytes]
    finally:
        lib.amwe_free(h)
    return [raw[offsets[i]:offsets[i + 1]]
            for i in range(len(rows_arr))]


def _p32(a):
    return a.ctypes.data_as(_P32)


def _p64(a):
    return a.ctypes.data_as(_P64)


def _p8(a):
    return a.ctypes.data_as(_P8)


# staging error codes (wire_codec.cpp ErrCode) -> exception builders;
# messages match the numpy staging exactly
_STAGE_ERRORS = {
    1: (ValueError, 'Modification of unknown object {obj}'),
    2: (ValueError, 'Insertion into non-sequence object {uuid}'),
    3: (ValueError, 'Duplicate list element ID'),
    4: (ValueError, 'List element insertion after unknown element'),
    5: (TypeError, 'Missing index entry for list element'),
    6: (ValueError, 'assignment to _head'),
}


class GeneralStagedPlanes:
    """Handle over one native staging result. Numpy views of the
    resolution columns materialize on construction; the plane fills
    (`fill_wire`, `fill_dplanes`) stream straight from the C++ buffers
    into caller-allocated arrays. Keeps every borrowed input array
    alive until freed."""

    __slots__ = ('_lib', '_h', '_keep', 'n_ins', 'n_arows', 'n_fields',
                 'n_dirty', 'max_seq', 'max_nj', 'd_n',
                 'a_rows', 'o_field', 'seg_new', 'a_node', 'a_objrow',
                 'g_obj', 'g_local', 'g_parent', 'g_actor', 'g_elem',
                 'order', 'r_seg', 'touched', 'dirty', 'n_j', 'new_cnt')

    def __init__(self, lib, h, keep):
        self._lib = lib
        self._h = h
        self._keep = keep            # borrowed-arrays lifeline
        self.n_ins = int(lib.amst_n_ins(h))
        self.n_arows = int(lib.amst_n_arows(h))
        self.n_fields = int(lib.amst_n_fields(h))
        self.n_dirty = int(lib.amst_n_dirty(h))
        self.max_seq = int(lib.amst_max_seq(h))
        self.max_nj = int(lib.amst_max_nj(h))
        self.d_n = int(lib.amst_d_n(h))
        n_a, n_i, K, F = self.n_arows, self.n_ins, self.n_dirty, \
            self.n_fields
        self.a_rows = _np.empty(n_a, _np.int64)
        self.o_field = _np.empty(n_a, _np.int64)
        self.seg_new = _np.empty(n_a, _np.int64)
        self.a_node = _np.empty(n_a, _np.int64)
        self.a_objrow = _np.empty(n_a, _np.int64)
        lib.amst_fill_res(h, _p64(self.a_rows), _p64(self.o_field),
                          _p64(self.seg_new), _p64(self.a_node),
                          _p64(self.a_objrow))
        self.g_obj = _np.empty(n_i, _np.int64)
        self.g_local = _np.empty(n_i, _np.int64)
        self.g_parent = _np.empty(n_i, _np.int64)
        self.g_actor = _np.empty(n_i, _np.int32)
        self.g_elem = _np.empty(n_i, _np.int64)
        lib.amst_fill_append(h, _p64(self.g_obj), _p64(self.g_local),
                             _p64(self.g_parent), _p32(self.g_actor),
                             _p64(self.g_elem))
        self.order = _np.empty(n_a, _np.int64)
        self.r_seg = _np.empty(n_a, _np.int32)
        lib.amst_fill_order(h, _p64(self.order), _p32(self.r_seg))
        self.touched = _np.empty(F, _np.int64)
        lib.amst_fill_fields(h, _p64(self.touched))
        self.dirty = _np.empty(K, _np.int64)
        self.n_j = _np.empty(K, _np.int64)
        self.new_cnt = _np.empty(K, _np.int64)
        lib.amst_fill_dirty(h, _p64(self.dirty), _p64(self.n_j),
                            _p64(self.new_cnt))

    def fill_dplanes(self, d_parent, d_elemc, d_actor, d_pos,
                     job_start, n_j_arr):
        """Write the new-node planes + job table into pre-padded
        caller arrays (d_pos must be pre-filled with the cap
        sentinel)."""
        self._lib.amst_fill_dplanes(
            self._h, _p32(d_parent), _p32(d_elemc), _p32(d_actor),
            _p32(d_pos), _p32(job_start), _p32(n_j_arr))

    def fill_wire(self, wire, cap, d_pad, n_pad, K, nnz_pad, m_pad,
                  ranks):
        """Write the packed program's wire buffer (all sections except
        the three admission-clock COO sections, which the caller
        owns)."""
        self._lib.amst_fill_wire(
            self._h, wire.ctypes.data_as(_PU8), cap, d_pad, n_pad, K,
            nnz_pad, m_pad, _p64(ranks))

    def fill_wire_wide(self, wire, cap, d_pad, n_pad, K, nnz_pad,
                       m_pad):
        """Write the WIDE packed program's wire buffer (same contract
        as :meth:`fill_wire`; the wide words carry stable actor ids,
        so no rank table crosses the boundary)."""
        self._lib.amst_fill_wire_wide(
            self._h, wire.ctypes.data_as(_PU8), cap, d_pad, n_pad, K,
            nnz_pad, m_pad)

    def __del__(self):
        h = getattr(self, '_h', None)
        if h:
            self._lib.amst_free(h)
            self._h = None


def stage_general_block(block, chg_local, a_tab, k_tab, omap, root_row,
                        obj_doc, obj_type, pool, b_actor, n_old_mirror,
                        obj_uuid=None, elem_cache=None):
    """Run the native stager over an admitted general block.

    Returns a :class:`GeneralStagedPlanes`, ``None`` when the library
    is unavailable or the stager requests the numpy fallback
    (late-bound string elemIds), or raises exactly the staging error
    the numpy path would raise (same type, same message).
    ``obj_uuid`` is the store's object-uuid table (error messages).
    ``elem_cache`` is the pool's persistent elem index (obj ->
    [sorted int64 keys, aligned int64 locals]); cached objects skip
    the stager's per-object pos_sorted tabulation."""
    lib = stage_lib()
    if lib is None:
        return None
    n_of = _np.ascontiguousarray(pool.n_of, _np.int64)
    max_elem_of = _np.ascontiguousarray(pool.max_elem_of, _np.int64)
    keep = (block, chg_local, a_tab, k_tab, omap, root_row, obj_doc,
            obj_type, n_of, max_elem_of, pool.pos_sorted, pool.pos_row,
            pool.obj, pool.local, pool.actor, pool.elemc, pool.parent,
            b_actor)
    n_cache = 0
    c_objs = c_lens = c_keys = c_locs = _np.empty(0, _np.int64)
    if elem_cache and len(elem_cache) <= 4096:
        objs = sorted(elem_cache)
        ents = [elem_cache[o] for o in objs]
        c_objs = _np.asarray(objs, _np.int64)
        c_lens = _np.asarray([len(e[0]) for e in ents], _np.int64)
        c_keys = _np.asarray([e[0].ctypes.data for e in ents], _np.int64)
        c_locs = _np.asarray([e[1].ctypes.data for e in ents], _np.int64)
        n_cache = len(objs)
        keep = keep + (ents, c_objs, c_lens, c_keys, c_locs)
    h = lib.amst_stage_general(
        block.n_ops, _p8(block.action), _p32(block.obj),
        _p8(block.key_kind), _p32(block.key), _p32(block.key_elem),
        _p32(block.elem),
        block.n_changes, _p32(block.op_ptr), _p32(block.doc),
        _p32(block.seq), _p32(b_actor), _p32(chg_local),
        _p32(a_tab), _p32(k_tab),
        _p64(omap), _p64(root_row), _p32(obj_doc), _p32(obj_type),
        len(obj_doc),
        _p64(n_of), _p64(max_elem_of),
        _p64(pool.pos_sorted), _p64(pool.pos_row), pool.n_nodes,
        _p32(pool.obj), _p32(pool.local), _p32(pool.actor),
        _p32(pool.elemc), _p32(pool.parent),
        n_old_mirror,
        n_cache, _p64(c_objs), _p64(c_lens), _p64(c_keys),
        _p64(c_locs))
    if not h:
        raise MemoryError('native staging allocation failed')
    err = int(lib.amst_err(h))
    if err:
        payload = int(lib.amst_err_payload(h))
        lib.amst_free(h)
        exc, msg = _STAGE_ERRORS[err]
        if err == 1:        # payload = block obj table index
            msg = msg.format(obj=block.objs[payload])
        elif err == 2:      # payload = store object row
            msg = msg.format(
                uuid=obj_uuid[payload] if obj_uuid is not None
                else '<object>')
        raise exc(msg)
    if lib.amst_fallback(h):
        lib.amst_free(h)
        return None
    return GeneralStagedPlanes(lib, h, keep)


# ---------------------------------------------------------------------------
# Native columnar v2/v3 codec (the amwe_emit_columnar[_v3] /
# amst_parse_columnar[_v3] entry points of libamwire.so): the JSON-free
# binary wire format. Emit returns varint column bodies plus per-change
# global ref lists — the host maps refs to tagged literal bytes
# (wire.py), so the Python fallback is byte-identical by construction.
# Parse fills the same Parsed struct the JSON parsers fill (extracted
# via the amwc_* accessors in wire._extract_block). v3 adds RLE on the
# action and obj columns; the session string-table layer lives entirely
# host-side (wire.py), so the C boundary is unchanged beyond the two
# extra symbols.

_COLUMNAR_LIB = None
_COLUMNAR_ATTEMPTED = False

_COL_EMIT_ARGTYPES = [
    _i64, _P64,                                  # rows
    _P32, _P32, _P32, _P32, _P32,                # change columns
    _P32, _P8, _P32, _P8, _P32, _P32, _P32,      # op columns
    _P32]                                        # value column


def _bind_columnar(lib):
    for emit in (lib.amwe_emit_columnar, lib.amwe_emit_columnar_v3):
        emit.argtypes = _COL_EMIT_ARGTYPES
        emit.restype = ctypes.c_void_p
    lib.amwe_col_bytes.argtypes = [ctypes.c_void_p]
    lib.amwe_col_bytes.restype = _i64
    lib.amwe_col_refs.argtypes = [ctypes.c_void_p]
    lib.amwe_col_refs.restype = _i64
    lib.amwe_col_fill.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  _P64, _P64, _P64]
    lib.amwe_col_fill.restype = None
    lib.amwe_col_free.argtypes = [ctypes.c_void_p]
    lib.amwe_col_free.restype = None
    for parse in (lib.amst_parse_columnar, lib.amst_parse_columnar_v3):
        parse.argtypes = [ctypes.c_char_p, _i64]
        parse.restype = ctypes.c_void_p
    return lib


def columnar_lib():
    """The columnar v2/v3 codec library, or None (no native codec /
    stale binary without the columnar symbols /
    AUTOMERGE_TPU_NATIVE_COLUMNAR=0)."""
    global _COLUMNAR_LIB, _COLUMNAR_ATTEMPTED
    if _COLUMNAR_ATTEMPTED:
        return _COLUMNAR_LIB
    _COLUMNAR_ATTEMPTED = True
    if os.environ.get('AUTOMERGE_TPU_NATIVE_COLUMNAR', '1') == '0':
        return None
    from . import wire as _wire
    lib = _wire._load()
    if lib is None:
        return None
    try:
        _COLUMNAR_LIB = _bind_columnar(lib)
    except AttributeError:
        _COLUMNAR_LIB = None         # stale .so predating the codec
    return _COLUMNAR_LIB


def columnar_available():
    return columnar_lib() is not None


def columnar_v3_available():
    """_bind_columnar binds the v2 and v3 symbols together (a stale
    .so missing either fails the whole bind), so v3 availability is
    the same predicate — kept distinct so CI can assert the v3 emit/
    parse arms by name."""
    lib = columnar_lib()
    return lib is not None and \
        hasattr(lib, 'amwe_emit_columnar_v3') and \
        hasattr(lib, 'amst_parse_columnar_v3')


def emit_columnar_rows(block, rows_arr):
    """Native columnar v2 emit of general-block change rows: one
    ``(body bytes, global ref list)`` per row, or None when the library
    is unavailable (the caller falls back to the Python emitter)."""
    return _emit_columnar_rows(block, rows_arr, 'amwe_emit_columnar')


def emit_columnar_rows_v3(block, rows_arr):
    """Native columnar v3 emit (RLE action/obj columns) — same contract
    as :func:`emit_columnar_rows`."""
    return _emit_columnar_rows(block, rows_arr, 'amwe_emit_columnar_v3')


def _emit_columnar_rows(block, rows_arr, sym):
    lib = columnar_lib()
    if lib is None:
        return None
    h = getattr(lib, sym)(
        len(rows_arr), _p64(rows_arr),
        _p32(block.actor), _p32(block.seq),
        _p32(block.dep_ptr), _p32(block.dep_actor),
        _p32(block.dep_seq),
        _p32(block.op_ptr), _p8(block.action), _p32(block.obj),
        _p8(block.key_kind), _p32(block.key), _p32(block.key_elem),
        _p32(block.elem), _p32(block.value))
    if not h:
        raise MemoryError('native columnar emit allocation failed')
    try:
        nbytes = int(lib.amwe_col_bytes(h))
        n_refs = int(lib.amwe_col_refs(h))
        buf = ctypes.create_string_buffer(max(nbytes, 1))
        body_off = _np.empty(len(rows_arr) + 1, _np.int64)
        refs = _np.empty(max(n_refs, 1), _np.int64)
        refs_off = _np.empty(len(rows_arr) + 1, _np.int64)
        lib.amwe_col_fill(h, buf, _p64(body_off), _p64(refs),
                          _p64(refs_off))
        raw = buf.raw[:nbytes]
    finally:
        lib.amwe_col_free(h)
    return [(raw[body_off[i]:body_off[i + 1]],
             refs[refs_off[i]:refs_off[i + 1]].tolist())
            for i in range(len(rows_arr))]
