"""Distributed layer: document sharding over a device mesh.

The reference scales by replicating documents over a network of peers
(`src/connection.js`); within a TPU pod this framework instead shards the
*document axis* of a DocSet over the mesh and lets XLA collectives ride
the ICI:

* **dp (documents)** — independent docs partitioned across devices; each
  device resolves its shard with the same program (`shard_map` over the
  leading axis), global statistics via ``psum``.
* **sp (sequence)** — very long Text documents shard their node axis; the
  pointer-doubling rounds become sharded gathers (XLA inserts the
  all-gathers automatically from the sharding annotations).
* **peer sync over ICI** — mesh replicas of one document converge by
  collective (`ici_sync`): clock advertisement = ``pmax``, change
  shipping = ``all_gather`` (or ``ppermute`` ring gossip), convergent
  apply = the merge kernel on the union.
* **DCN** — between hosts/pods the Connection wire protocol is unchanged:
  vector-clock advertisement + change shipping, with the host feeding
  device batches.
"""

from .mesh import make_mesh, shard_docs
from .docset_engine import sharded_merge_step, ShardedDocSetEngine
from .ici_sync import (make_peer_mesh, shard_peers, sync_step,
                       ring_sync_step)

__all__ = ['make_mesh', 'shard_docs', 'sharded_merge_step',
           'ShardedDocSetEngine', 'make_peer_mesh', 'shard_peers',
           'sync_step', 'ring_sync_step']
