"""Sharded batched DocSet engine: the multi-chip applyChanges step.

One program resolves every document in a DocSet; the document axis is
partitioned across the mesh with ``shard_map``, per-shard work is the same
vmap'd kernels as the single-chip path, and global statistics (ops
applied, conflicts detected — the observability counters of §5) reduce
over the ICI with ``psum``.

This composes the parallelism axes of the framework:

* dp: documents sharded over the mesh (this module)
* tp: all ops of a batch resolved as packed arrays in one kernel
  (:mod:`automerge_tpu.device.merge`)
* sp: sequence-axis sharding for long texts
  (:mod:`automerge_tpu.device.sequence` under sharded inputs)
"""

from functools import lru_cache, partial

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from ..device.merge import _resolve
from ..device import packing
from .mesh import shard_docs, DOC_AXIS


def _merge_step(seg_id, actor, seq, clock, is_del, valid, num_segments):
    """Per-shard body: resolve local docs, then psum global counters."""
    out = jax.vmap(partial(_resolve, num_segments=num_segments))(
        seg_id, actor, seq, clock, is_del, valid)

    def seg_counts(surviving, seg):
        return jax.ops.segment_sum(surviving.astype(jnp.int32), seg,
                                   num_segments=num_segments)
    counts = jax.vmap(seg_counts)(out['surviving'], seg_id)   # [d, S]
    stats = {
        'ops_applied': jax.lax.psum(jnp.sum(valid), DOC_AXIS),
        'ops_surviving': jax.lax.psum(jnp.sum(out['surviving']), DOC_AXIS),
        'conflicts': jax.lax.psum(jnp.sum(counts > 1), DOC_AXIS),
    }
    return out, stats


@lru_cache(maxsize=64)
def _merge_step_fn(mesh, num_segments):
    spec = P(DOC_AXIS)
    return jax.jit(shard_map(
        partial(_merge_step, num_segments=num_segments),
        mesh=mesh,
        in_specs=(spec, spec, spec, spec, spec, spec),
        out_specs=({'surviving': spec, 'winner': spec, 'seg_max_actor': spec},
                   {'ops_applied': P(), 'ops_surviving': P(), 'conflicts': P()}),
    ))


def sharded_merge_step(mesh, seg_id, actor, seq, clock, is_del, valid, *,
                       num_segments):
    """Run one batched merge step with the doc axis sharded over `mesh`.

    Returns (kernel outputs with doc-sharded leading axis, replicated
    stats). The compiled step is cached per (mesh, num_segments).
    """
    return _merge_step_fn(mesh, num_segments)(
        seg_id, actor, seq, clock, is_del, valid)


class ShardedDocSetEngine:
    """Batched merges for a whole DocSet across a device mesh.

    The device-count divisibility constraint is handled by padding the doc
    axis; padded docs carry valid=False ops and resolve to nothing.
    """

    def __init__(self, mesh=None, options=None):
        from ..device.engine import as_options
        self.options = as_options(options)
        if self.options.kernel == 'pallas':
            # the shard_map body runs the XLA resolver; failing beats
            # silently benchmarking the wrong kernel
            raise ValueError('ShardedDocSetEngine runs the XLA resolve '
                             'kernel; kernel="pallas" is single-chip only')
        if mesh is None:
            mesh = self.options.make_mesh()
        self.mesh = mesh

    def apply_changes_batch(self, docs_changes):
        """docs_changes: list (per doc) of change lists. Returns the same
        per-doc resolved field maps as
        :func:`automerge_tpu.device.engine.batch_merge_docs`, computed with
        the doc axis sharded over this engine's mesh."""
        n_dev = self.mesh.devices.size
        packed = [packing.pack_assignments(c) for c in docs_changes]
        d_real = len(packed)
        d_pad = -(-d_real // n_dev) * n_dev
        arrays = packing.pad_and_stack(
            packed, n_ops=self.options.op_pad,
            n_actors=self.options.actor_pad,
            index_dtype=self.options.index_dtype,
            clock_dtype=self.options.clock_dtype)
        seg_id, actor, seq, clock, is_del, valid, n_pad = arrays
        if d_pad != d_real:
            def pad_docs(a):
                widths = [(0, d_pad - d_real)] + [(0, 0)] * (a.ndim - 1)
                return np.pad(a, widths)
            seg_id, actor, seq, clock, is_del, valid = map(
                pad_docs, (seg_id, actor, seq, clock, is_del, valid))

        arrays = shard_docs(self.mesh, seg_id, actor, seq, clock, is_del, valid)
        n_segs = self.options.pad_segments(
            max((p.n_segments for p in packed), default=1))
        out, stats = sharded_merge_step(self.mesh, *arrays,
                                        num_segments=n_segs)
        surviving = np.asarray(out['surviving'])
        winner = np.asarray(out['winner'])

        from ..device.engine import unpack_resolved
        results = [unpack_resolved(p, surviving[i], winner[i])
                   for i, p in enumerate(packed)]
        return results, {k: int(v) for k, v in stats.items()}
