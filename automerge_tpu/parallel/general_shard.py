"""Sharded sequence ordering for the general engine: dirty objects
across the mesh.

The general bulk engine's heavy device work is the per-dirty-object RGA
ordering pass (:mod:`automerge_tpu.device.sequence` vmapped over the
[K, m] job planes). Jobs are independent documents' insertion trees —
embarrassingly parallel — so the job axis partitions over a device mesh
with ``shard_map``: each chip orders its slice of the dirty objects,
global length statistics reduce over the ICI with ``psum``, and the
result is bit-identical to the single-chip vmap (equality-gated in the
multichip dryrun and the virtual-mesh tests).

This is the sp/dp axis for FULL documents (the flat-map engines shard in
:mod:`.docset_engine`); a production multi-host deployment partitions
GeneralStores per host and syncs via :mod:`automerge_tpu.sync` over DCN,
with this module covering the chips within each host.
"""

from functools import lru_cache

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from ..device.sequence import _rga_order
from .mesh import DOC_AXIS, shard_docs


def _rga_body(parent, elem, actor, visible, valid):
    out = jax.vmap(_rga_order)(parent, elem, actor, visible, valid)
    stats = {
        'visible_total': jax.lax.psum(jnp.sum(out['length']), DOC_AXIS),
        'jobs': jax.lax.psum(jnp.asarray(parent.shape[0]), DOC_AXIS),
    }
    return out, stats


@lru_cache(maxsize=16)
def _sharded_rga_fn(mesh):
    spec = P(DOC_AXIS, None)
    return jax.jit(shard_map(
        _rga_body, mesh=mesh,
        in_specs=(spec,) * 5,
        out_specs=({'tree_pos': spec, 'vis_index': spec,
                    'node_at_pos': spec, 'length': P(DOC_AXIS)},
                   {'visible_total': P(), 'jobs': P()})))


def sharded_rga_jobs(mesh, parent, elem, actor, visible, valid):
    """Order a batch of insertion trees with the job axis sharded over
    `mesh`. Pads the job axis to the mesh size; padded jobs are a lone
    valid head node and order to nothing.

    Returns (rga outputs for the REAL jobs, replicated stats).
    """
    n_dev = mesh.devices.size
    k = parent.shape[0]
    k_pad = -(-max(k, 1) // n_dev) * n_dev
    if k_pad != k:
        def pad_jobs(a, head_valid=False):
            out = np.zeros((k_pad,) + a.shape[1:], a.dtype)
            out[:k] = a
            if head_valid:
                out[k:, 0] = 1       # node 0 valid (a lone head)
            return out
        parent = pad_jobs(np.asarray(parent))
        elem = pad_jobs(np.asarray(elem))
        actor = pad_jobs(np.asarray(actor))
        visible = pad_jobs(np.asarray(visible))
        valid = pad_jobs(np.asarray(valid).astype(bool), head_valid=True)
    placed = shard_docs(mesh, *(jnp.asarray(a) for a in
                                (parent, elem, actor, visible, valid)))
    out, stats = _sharded_rga_fn(mesh)(*placed)
    out = {name: arr[:k] for name, arr in out.items()}
    return out, {name: int(v) for name, v in stats.items()}
