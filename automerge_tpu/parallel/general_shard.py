"""Sharded sequence ordering for the general engine: dirty objects
across the mesh.

The general bulk engine's heavy device work is the per-dirty-object RGA
ordering pass (:mod:`automerge_tpu.device.sequence` vmapped over the
[K, m] job planes). Jobs are independent documents' insertion trees —
embarrassingly parallel — so the job axis partitions over a device mesh
with ``shard_map``: each chip orders its slice of the dirty objects,
global length statistics reduce over the ICI with ``psum``, and the
result is bit-identical to the single-chip vmap (equality-gated in the
multichip dryrun and the virtual-mesh tests).

This is the sp/dp axis for FULL documents (the flat-map engines shard in
:mod:`.docset_engine`); a production multi-host deployment partitions
GeneralStores per host and syncs via :mod:`automerge_tpu.sync` over DCN,
with this module covering the chips within each host.
"""

from functools import lru_cache

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from ..device.merge import _resolve
from ..device.sequence import _rga_order
from .mesh import DOC_AXIS, shard_docs


def _rga_body(parent, elem, actor, visible, valid):
    out = jax.vmap(_rga_order)(parent, elem, actor, visible, valid)
    stats = {
        'visible_total': jax.lax.psum(jnp.sum(out['length']), DOC_AXIS),
        'jobs': jax.lax.psum(jnp.asarray(parent.shape[0]), DOC_AXIS),
    }
    return out, stats


@lru_cache(maxsize=16)
def _sharded_rga_fn(mesh):
    spec = P(DOC_AXIS, None)
    return jax.jit(shard_map(
        _rga_body, mesh=mesh,
        in_specs=(spec,) * 5,
        out_specs=({'tree_pos': spec, 'vis_index': spec,
                    'node_at_pos': spec, 'length': P(DOC_AXIS)},
                   {'visible_total': P(), 'jobs': P()})))


def sharded_general_step(mesh, ops_actor, ops_seq, ops_slot, boundary,
                         is_del, valid, coo_row, coo_col, coo_val,
                         seq_planes, seq_nj, seq_prior_vis, *,
                         num_segments, a_pad):
    """The general engine's FUSED step (field resolution + element
    visibility + RGA ordering) over a device mesh: assignment ROWS
    shard across chips for the resolve phase, the per-object visibility
    contributions reduce over the ICI (pmax), and the dirty-object JOBS
    shard for the ordering phase — dp over ops and over documents'
    objects, in one two-phase program.

    Inputs are exactly the wire-lean staged planes
    :func:`automerge_tpu.device.general._fused_general` consumes (rows
    FIELD-SORTED, so row ranges partition cleanly); outputs are
    bit-identical to the single-device program — the multichip dryrun
    gates on that equality with real staged blocks.
    """
    n_dev = mesh.devices.size
    boundary = np.asarray(boundary).astype(bool)
    valid = np.asarray(valid).astype(bool)
    n = len(boundary)
    K, m = seq_planes[0].shape

    # host split: row ranges SNAPPED to segment boundaries (rows are
    # field-sorted), so no segment straddles a shard — each shard's
    # resolve is then collective-free, and the per-segment winners
    # combine with one pmax
    bpos = np.flatnonzero(boundary)
    targets = (np.arange(1, n_dev) * n) // n_dev
    cuts = bpos[np.minimum(np.searchsorted(bpos, targets),
                           max(len(bpos) - 1, 0))] if len(bpos) else \
        np.zeros(n_dev - 1, np.int64)
    starts = np.concatenate([[0], cuts])
    ends = np.concatenate([cuts, [n]])
    n_shard = int(np.maximum(ends - starts, 1).max())
    # boundaries strictly BEFORE each start (a snapped start of row 0 has
    # zero preceding boundaries even though boundary[0] is set)
    seg_base = np.where(
        starts > 0, np.cumsum(boundary)[np.maximum(starts - 1, 0)], 0) \
        .astype(np.int32)

    def shardify(a, fill=0):
        out = np.full((n_dev, n_shard) + a.shape[1:], fill, a.dtype)
        for s, (lo, hi) in enumerate(zip(starts, ends)):
            out[s, :hi - lo] = a[lo:hi]
        return out

    s_actor_r = shardify(np.asarray(ops_actor))
    s_seq_r = shardify(np.asarray(ops_seq))
    s_slot_r = shardify(np.asarray(ops_slot), fill=-1)
    s_bnd_r = shardify(boundary)
    s_del_r = shardify(np.asarray(is_del).astype(bool))
    s_val_r = shardify(valid)
    # COO rows land in their owning shard, in local coordinates
    coo_row = np.asarray(coo_row)
    live = coo_row < n
    shard_of = np.searchsorted(ends, coo_row, side='right')
    shard_of = np.minimum(shard_of, n_dev - 1)
    nnz_shard = max(int(np.bincount(shard_of[live],
                                    minlength=n_dev).max())
                    if live.any() else 0, 1)
    c_row = np.full((n_dev, nnz_shard), n_shard, np.int32)
    c_col = np.zeros((n_dev, nnz_shard), np.asarray(coo_col).dtype)
    c_val = np.zeros((n_dev, nnz_shard), np.asarray(coo_val).dtype)
    for s in range(n_dev):
        sel = live & (shard_of == s)
        cnt = int(sel.sum())
        c_row[s, :cnt] = coo_row[sel] - starts[s]
        c_col[s, :cnt] = np.asarray(coo_col)[sel]
        c_val[s, :cnt] = np.asarray(coo_val)[sel]
    row_starts = starts.astype(np.int32)

    shard_spec = P(DOC_AXIS)
    rep = P()

    def phase_a(actor_l, seq_l, slot_l, bnd_l, del_l, val_l, base_l,
                start_l, cr, cc, cv):
        actor32 = actor_l[0].astype(jnp.int32)
        seq32 = seq_l[0].astype(jnp.int32)
        bnd = bnd_l[0]
        val = val_l[0]
        seg_id = base_l[0] + jnp.cumsum(bnd.astype(jnp.int32)) - 1
        seg_id = jnp.maximum(seg_id, 0)          # padding-only prefixes
        nl = actor32.shape[0]
        clock = jnp.zeros((nl, a_pad), jnp.int32)
        clock = clock.at[jnp.arange(nl), actor32].set(seq32 - 1)
        clock = clock.at[cr[0], cc[0].astype(jnp.int32)].set(
            cv[0].astype(jnp.int32), mode='drop')
        out = _resolve(seg_id, actor32, seq32, clock, del_l[0], val,
                       num_segments)
        # winner ids are LOCAL row indexes; lift to global coordinates
        winner = jnp.where(out['winner'] >= 0,
                           out['winner'] + start_l[0], -1)
        winner = jax.lax.pmax(winner, DOC_AXIS)
        # per-object visibility contributions reduce over the ICI
        flat = jnp.where(slot_l[0] >= 0, slot_l[0], K * m)
        vis_hit = jnp.zeros(K * m, bool).at[flat].max(
            out['surviving'], mode='drop')
        touched = jnp.zeros(K * m, bool).at[flat].max(val, mode='drop')
        vis_hit = jax.lax.pmax(vis_hit.astype(jnp.int32), DOC_AXIS)
        touched = jax.lax.pmax(touched.astype(jnp.int32), DOC_AXIS)
        return (out['surviving'][None], winner, vis_hit.astype(bool),
                touched.astype(bool))

    fa = jax.jit(shard_map(
        phase_a, mesh=mesh,
        in_specs=(shard_spec,) * 11,
        out_specs=(shard_spec, rep, rep, rep)))
    surviving, winner, vis_hit, touched = fa(
        jnp.asarray(s_actor_r), jnp.asarray(s_seq_r),
        jnp.asarray(s_slot_r), jnp.asarray(s_bnd_r),
        jnp.asarray(s_del_r), jnp.asarray(s_val_r),
        jnp.asarray(seg_base), jnp.asarray(row_starts),
        jnp.asarray(c_row), jnp.asarray(c_col), jnp.asarray(c_val))

    # reassemble the row-sharded survivors into flat row order
    surv2 = np.asarray(surviving)
    surviving_flat = np.zeros(n, bool)
    for s, (lo, hi) in enumerate(zip(starts, ends)):
        surviving_flat[lo:hi] = surv2[s, :hi - lo]

    s_parent, s_elem, s_actor = (np.asarray(seq_planes[0]),
                                 np.asarray(seq_planes[1]),
                                 np.asarray(seq_planes[2]))
    s_valid = (np.arange(m, dtype=np.int32)[None, :]
               < np.asarray(seq_nj)[:, None])
    visible = (np.where(
        np.asarray(touched).reshape(K, m),
        np.asarray(vis_hit).reshape(K, m),
        np.asarray(seq_prior_vis).astype(bool)) & s_valid).astype(bool)
    ordered, _ = sharded_rga_jobs(
        mesh, s_parent.astype(np.int32), s_elem.astype(np.int32),
        s_actor.astype(np.int32), visible, s_valid)
    return {'surviving': surviving_flat,
            'winner': np.asarray(winner),
            'visible': visible,
            'vis_index': np.asarray(ordered['vis_index'])}


@lru_cache(maxsize=16)
def _fleet_rollup_fn(mesh):
    spec = P(DOC_AXIS, None)

    def body(stats):
        return jax.lax.psum(jnp.sum(stats, axis=0), DOC_AXIS)

    return jax.jit(shard_map(body, mesh=mesh, in_specs=(spec,),
                             out_specs=P()))


def fleet_rollup(mesh, per_shard):
    """Cross-shard fleet-statistic reduction: ``per_shard`` is an
    ``[S, k]`` matrix of per-shard stat vectors (doc counts, dirty
    totals, byte estimates, digest-valid flags — whatever the caller
    stacks); the return is the length-``k`` fleet total.

    Over a real multi-device mesh the reduction runs as a ``psum``
    under ``shard_map`` — the collective form of the rollup
    ``ShardedGeneralDocSet.fleet_status()`` serves, so a pod-scale
    fleet aggregates over the ICI instead of hauling every shard's
    stats to one host. On a single device (or when the shard axis does
    not divide over the mesh) it degrades to the numerically identical
    numpy sum. Values ride as int64 host-side; the device path clips
    to int32 lanes (JAX x64 is off), which bounds each STAT at 2 GiB
    per shard — fine for counts/estimates, callers with wider values
    keep the numpy path."""
    arr = np.asarray(per_shard, np.int64)
    if arr.ndim != 2:
        raise ValueError('per_shard must be [n_shards, k]')
    n_dev = 0 if mesh is None else mesh.devices.size
    if n_dev <= 1 or (np.abs(arr) >= 2**31).any():
        return arr.sum(axis=0)
    s = arr.shape[0]
    s_pad = -(-max(s, 1) // n_dev) * n_dev
    padded = np.zeros((s_pad, arr.shape[1]), np.int32)
    padded[:s] = arr
    placed = shard_docs(mesh, jnp.asarray(padded))
    return np.asarray(_fleet_rollup_fn(mesh)(placed), np.int64)


def sharded_fleet_order(mesh, shard_jobs):
    """The BATCHED-apply ordering entry for a sharded fleet: every
    shard's dirty-object job planes (``(parent, elem, actor, visible,
    valid)`` per shard, each ``[k_i, m_i]``) pack into one job plane
    with the job axis aligned so each mesh device orders one shard's
    jobs, then ONE :func:`sharded_rga_jobs` dispatch runs the RGA pass
    for the whole fleet — S per-shard vmap dispatches collapse into a
    single shard_map program with psum'd fleet stats.

    Returns ``(per-shard output list, stats)`` where each output dict
    slices back to that shard's real jobs — bit-identical to running
    :func:`~automerge_tpu.device.sequence._rga_order` per shard
    (equality-gated in tests/test_sharded_fleet.py)."""
    n_shards = len(shard_jobs)
    if n_shards == 0:
        return [], {'visible_total': 0, 'jobs': 0}
    ks = [max(p[0].shape[0], 1) for p in shard_jobs]
    ms = [p[0].shape[1] if p[0].ndim == 2 else 1 for p in shard_jobs]
    k_align = max(ks)
    m = max(max(ms), 1)

    def pack(field, fill=0, head_valid=False):
        out = np.full((n_shards * k_align, m), fill,
                      np.asarray(shard_jobs[0][field]).dtype
                      if shard_jobs else np.int32)
        if head_valid:
            out[:, :] = 0
            out[:, 0] = 1              # padded jobs: lone valid head
        for s, planes in enumerate(shard_jobs):
            a = np.asarray(planes[field])
            if a.ndim == 1:
                a = a[:, None]
            out[s * k_align:s * k_align + a.shape[0], :a.shape[1]] = a
        return out

    parent = pack(0).astype(np.int32)
    elem = pack(1).astype(np.int32)
    actor = pack(2).astype(np.int32)
    visible = pack(3).astype(bool)
    valid = pack(4, head_valid=True).astype(bool)
    out, stats = sharded_rga_jobs(mesh, parent, elem, actor, visible,
                                  valid)
    per_shard = []
    for s, planes in enumerate(shard_jobs):
        k_s, m_s = np.asarray(planes[0]).shape
        per_shard.append({
            name: np.asarray(arr)[s * k_align:s * k_align + k_s]
            [..., :m_s] if np.asarray(arr).ndim == 2
            else np.asarray(arr)[s * k_align:s * k_align + k_s]
            for name, arr in out.items()})
    return per_shard, stats


def sharded_rga_jobs(mesh, parent, elem, actor, visible, valid):
    """Order a batch of insertion trees with the job axis sharded over
    `mesh`. Pads the job axis to the mesh size; padded jobs are a lone
    valid head node and order to nothing.

    Returns (rga outputs for the REAL jobs, replicated stats).
    """
    n_dev = mesh.devices.size
    k = parent.shape[0]
    k_pad = -(-max(k, 1) // n_dev) * n_dev
    if k_pad != k:
        def pad_jobs(a, head_valid=False):
            out = np.zeros((k_pad,) + a.shape[1:], a.dtype)
            out[:k] = a
            if head_valid:
                out[k:, 0] = 1       # node 0 valid (a lone head)
            return out
        parent = pad_jobs(np.asarray(parent))
        elem = pad_jobs(np.asarray(elem))
        actor = pad_jobs(np.asarray(actor))
        visible = pad_jobs(np.asarray(visible))
        valid = pad_jobs(np.asarray(valid).astype(bool), head_valid=True)
    placed = shard_docs(mesh, *(jnp.asarray(a) for a in
                                (parent, elem, actor, visible, valid)))
    out, stats = _sharded_rga_fn(mesh)(*placed)
    out = {name: arr[:k] for name, arr in out.items()}
    return out, {name: int(v) for name, v in stats.items()}


def sharded_step_from_capture(mesh, store, patch, captured):
    """Re-run a captured general apply through the sharded step and
    return (sharded outputs, fused reference outputs) for equality
    gating.

    `captured` is the dict the engine hands to
    ``general._STAGE_CAPTURE`` (staged wire planes + the fused
    program's outputs, whichever variant ran); the job planes rebuild
    HOST-side from the pool, whose host visibility columns are still
    the PRE-apply state (the mirror has not been synced). Shared by the
    multichip dryrun (``__graft_entry__``) and the CPU-mesh tests.
    """
    from ..device import general
    from ..device.blocks import _span_indices

    ops_slot = captured['ops_slot']
    n_pad = len(ops_slot)
    bits = np.unpackbits(captured['flags_u8'])
    bnd = bits[:n_pad].astype(bool)
    isdel = bits[n_pad:2 * n_pad].astype(bool)
    vmask = np.arange(n_pad) < int(captured['n_rows'])

    raw = patch._raw
    dirty, n_j = raw['dirty'], raw['dirty_n']
    rows_flat = raw['rows_flat']()   # lazy node-row gather
    mj = captured['m_pad']
    Kj = max(len(dirty), 1)
    pool = store.pool
    seq_planes = np.zeros((3, Kj, mj), np.int32)
    prior_vis = np.zeros((Kj, mj), bool)
    if len(dirty):
        flat = _span_indices(np.arange(Kj, dtype=np.int64) * mj, n_j)
        seq_planes[0].reshape(-1)[flat] = pool.parent[rows_flat]
        seq_planes[1].reshape(-1)[flat] = pool.elemc[rows_flat]
        ranks = np.zeros(len(rows_flat), np.int64)
        real = pool.actor[rows_flat] >= 0
        ranks[real] = store.actor_str_ranks()[pool.actor[rows_flat][real]]
        seq_planes[2].reshape(-1)[flat] = ranks
        prior_vis.reshape(-1)[flat] = pool.visible[rows_flat]
    n_j_arr = np.zeros(Kj, np.int32)
    n_j_arr[:len(n_j)] = n_j

    sharded = sharded_general_step(
        mesh, captured['ops_actor'], captured['ops_seq'], ops_slot,
        bnd, isdel, vmask, captured['coo_row'], captured['coo_col'],
        captured['coo_val'], seq_planes, n_j_arr, prior_vis,
        num_segments=captured['num_segments'],
        a_pad=captured['a_pad'])

    if captured['vis_planes'] is None:     # no dirty sequence objects
        vis_ref = np.zeros((Kj, mj), bool)
        idx_ref = np.full((Kj, mj), -1, np.int64)
    elif captured['vis_fmt'] == 'packed':
        _, vis_ref, _, idx_ref = general.unpack_vis_word(
            np.asarray(jax.device_get(captured['vis_planes']))
            .view(np.uint32))
    elif captured['vis_fmt'] == 'wide':
        vis_ref, idx_ref = general.unpack_wide_word(
            np.asarray(jax.device_get(captured['vis_planes'][1])))
    else:
        pl = [np.asarray(x)
              for x in jax.device_get(captured['vis_planes'])]
        vis_ref, idx_ref = pl[1], pl[3].astype(np.int64)
    fused = {
        'surviving': np.unpackbits(np.asarray(
            jax.device_get(captured['surv_u8']))).astype(bool)[:n_pad],
        'winner': np.asarray(jax.device_get(captured['winner'])),
        # the fused planes carry the BUCKETED job axis (padding jobs
        # are all-masked rows); the equality gate compares real jobs
        'visible': np.asarray(vis_ref)[:Kj],
        'vis_index': np.asarray(idx_ref, np.int64)[:Kj],
    }
    sharded['vis_index'] = np.asarray(sharded['vis_index'], np.int64)
    return sharded, fused
