"""ICI replica synchronization: the Connection protocol as collectives.

The reference's distributed story is `Connection` (src/connection.js:33-109):
peers advertise vector clocks, ship the changes the other side is missing,
and converge because the CRDT engine is order-insensitive. Between hosts
this framework keeps that exact host-side protocol (sync/connection.py,
over DCN). *Within* a pod, peers sit on one device mesh, so the protocol's
three primitives become XLA collectives over ICI instead of messages:

=====================  =======================================
Connection primitive   ICI equivalent (mesh axis ``'peers'``)
=====================  =======================================
clock advertisement    ``lax.pmax`` of the [n_actors] clock
change shipping        ``lax.all_gather`` of packed op columns
(ring alternative)     ``lax.ppermute`` neighbor gossip rounds
convergent apply       the merge kernel on the gathered union
=====================  =======================================

Every peer resolves the identical op union with the identical
deterministic kernel, so all replicas converge in one step — the
collective IS the sync round. The ring variant ships ops hop-by-hop
(P-1 rounds) and bounds per-step ICI traffic at 1/P of the all-gather,
the same bandwidth shape as ring attention for long-sequence work.

All functions are shard_map'd SPMD bodies: local shapes carry a leading
peer-local axis of 1; gathered unions have leading axis P.
"""

from functools import lru_cache, partial

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from ..device.merge import _resolve

PEER_AXIS = 'peers'


def make_peer_mesh(n_peers=None, devices=None):
    """A 1-D mesh whose axis enumerates replica peers (one device each)."""
    if devices is None:
        devices = jax.devices()
    if n_peers is not None:
        if n_peers > len(devices):
            raise ValueError(
                f'need {n_peers} devices for {n_peers} peers, '
                f'have {len(devices)}')
        devices = devices[:n_peers]
    from jax.sharding import Mesh
    return Mesh(np.asarray(devices), (PEER_AXIS,))


def _sync_body(seg_id, actor, seq, clock, is_del, valid, peer_clock,
               num_segments):
    """One all-gather sync round (SPMD body; local leading axis = 1).

    Args are this peer's locally-held ops ([1, n] columns, [1, n, A] op
    clocks) and its replica vector clock [1, A]. Returns the resolved
    union (identical on every peer) and the converged replica clock.
    """
    # One peer per device: a local peer axis > 1 would silently scope the
    # collectives to co-located peers only (wrong clocks, partial unions).
    assert seg_id.shape[0] == 1, \
        f'{seg_id.shape[0]} peers share one device; use one device per peer'
    # -- change shipping: union of every peer's ops over ICI ---------------
    def gather(x):
        g = jax.lax.all_gather(x, PEER_AXIS, axis=0, tiled=True)  # [P, n,...]
        return g.reshape((1, -1) + g.shape[2:])                   # [1, P*n]
    u_seg, u_actor, u_seq, u_is_del, u_valid = map(
        gather, (seg_id, actor, seq, is_del, valid))
    u_clock = gather(clock)

    # -- clock advertisement: converged replica clock = elementwise max ----
    new_clock = jax.lax.pmax(peer_clock, PEER_AXIS)

    # -- convergent apply: deterministic resolve of the identical union ----
    out = jax.vmap(partial(_resolve, num_segments=num_segments))(
        u_seg, u_actor, u_seq, u_clock, u_is_del, u_valid)

    stats = {
        'ops_exchanged': jax.lax.psum(jnp.sum(valid), PEER_AXIS),
        # every peer resolves the identical union; pmax of identical values
        # certifies the replication to shard_map
        'ops_surviving': jax.lax.pmax(jnp.sum(out['surviving']), PEER_AXIS),
    }
    return out, new_clock, stats


@lru_cache(maxsize=64)
def _sync_step_fn(mesh, num_segments):
    spec = P(PEER_AXIS)
    return jax.jit(shard_map(
        partial(_sync_body, num_segments=num_segments),
        mesh=mesh,
        in_specs=(spec,) * 7,
        out_specs=({'surviving': spec, 'winner': spec, 'seg_max_actor': spec},
                   spec, {'ops_exchanged': P(), 'ops_surviving': P()}),
    ))


def sync_step(mesh, seg_id, actor, seq, clock, is_del, valid, peer_clock, *,
              num_segments):
    """Synchronize P mesh replicas in one collective round.

    Inputs have a leading peer axis of size P (sharded over the mesh):
    ``seg_id/actor/seq/is_del/valid``: int32/bool[P, n] — each peer's
    locally-generated packed ops; ``clock``: int32[P, n, A] per-op causal
    clocks; ``peer_clock``: int32[P, A] per-replica vector clocks.

    Returns (union kernel outputs [P, P*n] — identical rows, proving
    convergence —, converged clocks int32[P, A], stats). The compiled
    round is cached per (mesh, num_segments), so repeated rounds pay
    dispatch cost only.
    """
    return _sync_step_fn(mesh, num_segments)(
        seg_id, actor, seq, clock, is_del, valid, peer_clock)


def _ring_body(seg_id, actor, seq, clock, is_del, valid, n_peers,
               num_segments):
    """(P-1)-round neighbor gossip; each round ships one peer-slot of ops
    to the next ring neighbor with ``ppermute`` and accumulates it.

    Equivalent result to the all-gather round, but per-step ICI traffic is
    1/P of the union — the ring-attention bandwidth shape.
    """
    # One peer per device (same invariant as _sync_body): a local peer axis
    # > 1 would gossip whole co-located blocks and produce partial unions.
    assert seg_id.shape[0] == 1, \
        f'{seg_id.shape[0]} peers share one device; use one device per peer'
    perm = [(i, (i + 1) % n_peers) for i in range(n_peers)]

    def ship(x):
        return jax.lax.ppermute(x, PEER_AXIS, perm)

    acc = (seg_id, actor, seq, clock, is_del, valid)
    hop = acc
    for _ in range(n_peers - 1):
        hop = tuple(ship(x) for x in hop)
        acc = tuple(jnp.concatenate([a, h], axis=1) for a, h in zip(acc, hop))

    u_seg, u_actor, u_seq, u_clock, u_is_del, u_valid = acc
    out = jax.vmap(partial(_resolve, num_segments=num_segments))(
        u_seg, u_actor, u_seq, u_clock, u_is_del, u_valid)
    return out


@lru_cache(maxsize=64)
def _ring_step_fn(mesh, num_segments):
    n_peers = mesh.devices.size
    spec = P(PEER_AXIS)
    return jax.jit(shard_map(
        partial(_ring_body, n_peers=n_peers, num_segments=num_segments),
        mesh=mesh,
        in_specs=(spec,) * 6,
        out_specs={'surviving': spec, 'winner': spec, 'seg_max_actor': spec},
    ))


def ring_sync_step(mesh, seg_id, actor, seq, clock, is_del, valid, *,
                   num_segments):
    """Ring-gossip variant of :func:`sync_step` (same convergent result)."""
    return _ring_step_fn(mesh, num_segments)(
        seg_id, actor, seq, clock, is_del, valid)


def shard_peers(mesh, *arrays):
    """Place arrays with their leading (peer) axis split over the mesh."""
    sharding = NamedSharding(mesh, P(PEER_AXIS))
    placed = tuple(jax.device_put(np.asarray(a), sharding) for a in arrays)
    return placed if len(placed) != 1 else placed[0]
