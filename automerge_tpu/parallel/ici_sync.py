"""ICI replica synchronization: the Connection protocol as collectives.

The reference's distributed story is `Connection` (src/connection.js:33-109):
peers advertise vector clocks, ship the changes the other side is missing,
and converge because the CRDT engine is order-insensitive. Between hosts
this framework keeps that exact host-side protocol (sync/connection.py,
over DCN). *Within* a pod, peers sit on one device mesh, so the protocol's
three primitives become XLA collectives over ICI instead of messages:

=====================  =======================================
Connection primitive   ICI equivalent (mesh axis ``'peers'``)
=====================  =======================================
clock advertisement    ``lax.pmax`` of the [n_actors] clock
change shipping        ``lax.all_gather`` of packed op columns
(ring alternative)     ``lax.ppermute`` neighbor gossip rounds
convergent apply       the merge kernel on the gathered union
=====================  =======================================

Every peer resolves the identical op union with the identical
deterministic kernel, so all replicas converge in one step — the
collective IS the sync round. The ring variant ships ops hop-by-hop
(P-1 rounds) and bounds per-step ICI traffic at 1/P of the all-gather,
the same bandwidth shape as ring attention for long-sequence work.

All functions are shard_map'd SPMD bodies: local shapes carry a leading
peer-local axis of 1; gathered unions have leading axis P.
"""

from functools import lru_cache, partial

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from ..device.merge import _resolve

PEER_AXIS = 'peers'


def make_peer_mesh(n_peers=None, devices=None):
    """A 1-D mesh whose axis enumerates replica peers (one device each)."""
    if devices is None:
        devices = jax.devices()
    if n_peers is not None:
        if n_peers > len(devices):
            raise ValueError(
                f'need {n_peers} devices for {n_peers} peers, '
                f'have {len(devices)}')
        devices = devices[:n_peers]
    from jax.sharding import Mesh
    return Mesh(np.asarray(devices), (PEER_AXIS,))


def _sync_body(seg_id, actor, seq, clock, is_del, valid, peer_clock,
               num_segments):
    """One all-gather sync round (SPMD body; local leading axis = 1).

    Args are this peer's locally-held ops ([1, n] columns, [1, n, A] op
    clocks) and its replica vector clock [1, A]. Returns the resolved
    union (identical on every peer) and the converged replica clock.
    """
    # One peer per device: a local peer axis > 1 would silently scope the
    # collectives to co-located peers only (wrong clocks, partial unions).
    assert seg_id.shape[0] == 1, \
        f'{seg_id.shape[0]} peers share one device; use one device per peer'
    # -- change shipping: union of every peer's ops over ICI ---------------
    def gather(x):
        g = jax.lax.all_gather(x, PEER_AXIS, axis=0, tiled=True)  # [P, n,...]
        return g.reshape((1, -1) + g.shape[2:])                   # [1, P*n]
    u_seg, u_actor, u_seq, u_is_del, u_valid = map(
        gather, (seg_id, actor, seq, is_del, valid))
    u_clock = gather(clock)

    # -- clock advertisement: converged replica clock = elementwise max ----
    new_clock = jax.lax.pmax(peer_clock, PEER_AXIS)

    # -- convergent apply: deterministic resolve of the identical union ----
    out = jax.vmap(partial(_resolve, num_segments=num_segments))(
        u_seg, u_actor, u_seq, u_clock, u_is_del, u_valid)

    stats = {
        'ops_exchanged': jax.lax.psum(jnp.sum(valid), PEER_AXIS),
        # every peer resolves the identical union; pmax of identical values
        # certifies the replication to shard_map
        'ops_surviving': jax.lax.pmax(jnp.sum(out['surviving']), PEER_AXIS),
    }
    return out, new_clock, stats


@lru_cache(maxsize=64)
def _sync_step_fn(mesh, num_segments):
    spec = P(PEER_AXIS)
    return jax.jit(shard_map(
        partial(_sync_body, num_segments=num_segments),
        mesh=mesh,
        in_specs=(spec,) * 7,
        out_specs=({'surviving': spec, 'winner': spec, 'seg_max_actor': spec},
                   spec, {'ops_exchanged': P(), 'ops_surviving': P()}),
    ))


def sync_step(mesh, seg_id, actor, seq, clock, is_del, valid, peer_clock, *,
              num_segments):
    """Synchronize P mesh replicas in one collective round.

    Inputs have a leading peer axis of size P (sharded over the mesh):
    ``seg_id/actor/seq/is_del/valid``: int32/bool[P, n] — each peer's
    locally-generated packed ops; ``clock``: int32[P, n, A] per-op causal
    clocks; ``peer_clock``: int32[P, A] per-replica vector clocks.

    Returns (union kernel outputs [P, P*n] — identical rows, proving
    convergence —, converged clocks int32[P, A], stats). The compiled
    round is cached per (mesh, num_segments), so repeated rounds pay
    dispatch cost only.
    """
    return _sync_step_fn(mesh, num_segments)(
        seg_id, actor, seq, clock, is_del, valid, peer_clock)


def _ring_body(seg_id, actor, seq, clock, is_del, valid, n_peers,
               num_segments):
    """(P-1)-round neighbor gossip; each round ships one peer-slot of ops
    to the next ring neighbor with ``ppermute`` and accumulates it.

    Equivalent result to the all-gather round, but per-step ICI traffic is
    1/P of the union — the ring-attention bandwidth shape.
    """
    # One peer per device (same invariant as _sync_body): a local peer axis
    # > 1 would gossip whole co-located blocks and produce partial unions.
    assert seg_id.shape[0] == 1, \
        f'{seg_id.shape[0]} peers share one device; use one device per peer'
    perm = [(i, (i + 1) % n_peers) for i in range(n_peers)]

    def ship(x):
        return jax.lax.ppermute(x, PEER_AXIS, perm)

    acc = (seg_id, actor, seq, clock, is_del, valid)
    hop = acc
    for _ in range(n_peers - 1):
        hop = tuple(ship(x) for x in hop)
        acc = tuple(jnp.concatenate([a, h], axis=1) for a, h in zip(acc, hop))

    u_seg, u_actor, u_seq, u_clock, u_is_del, u_valid = acc
    out = jax.vmap(partial(_resolve, num_segments=num_segments))(
        u_seg, u_actor, u_seq, u_clock, u_is_del, u_valid)
    return out


@lru_cache(maxsize=64)
def _ring_step_fn(mesh, num_segments):
    n_peers = mesh.devices.size
    spec = P(PEER_AXIS)
    return jax.jit(shard_map(
        partial(_ring_body, n_peers=n_peers, num_segments=num_segments),
        mesh=mesh,
        in_specs=(spec,) * 6,
        out_specs={'surviving': spec, 'winner': spec, 'seg_max_actor': spec},
    ))


def ring_sync_step(mesh, seg_id, actor, seq, clock, is_del, valid, *,
                   num_segments):
    """Ring-gossip variant of :func:`sync_step` (same convergent result)."""
    return _ring_step_fn(mesh, num_segments)(
        seg_id, actor, seq, clock, is_del, valid)


def shard_peers(mesh, *arrays):
    """Place arrays with their leading (peer) axis split over the mesh."""
    sharding = NamedSharding(mesh, P(PEER_AXIS))
    placed = tuple(jax.device_put(np.asarray(a), sharding) for a in arrays)
    return placed if len(placed) != 1 else placed[0]


# -- delta shipping: clock-diff windows ---------------------------------------
#
# The all-gather round above ships every peer's whole buffer every round —
# fine for a one-shot union, wrong bandwidth shape for repeated sync. The
# reference ships only what the peer is missing, derived from clocks
# (`maybeSendChanges`, src/connection.js:58-66). The static-shape ICI
# version: each round every peer advertises its replica clock (tiny
# all_gather), selects up to `window` of its locally-held ops that some
# other peer's clock does NOT cover, and ships just that window. Receivers
# accept an op iff it extends their per-actor contiguous prefix (clock
# semantics preserved under partial windows), append it to their buffer,
# and advance their clock. Shipped-op counts shrink to zero at
# convergence — the per-round traffic is the clock diff, not the union.


def _accept_incoming(in_actor, in_seq, in_clock, in_seg, in_del, in_valid,
                     buf, peer_clock, count, n_cap):
    """Fold incoming window rows into the local buffer.

    Dedups identical (actor, seq) rows (several peers may ship the same
    op), then accepts each actor's rows only as a contiguous seq prefix
    beyond the local clock — exactly `causallyReady` for per-actor op
    rows — and appends them at `count`.
    """
    f_actor = jnp.where(in_valid, in_actor, 0)
    f_seq = jnp.where(in_valid, in_seq, 0)         # seq 0 = never accepted
    order = jnp.lexsort((f_seq, f_actor))
    s_actor, s_seq = f_actor[order], f_seq[order]
    s_valid = in_valid[order]

    prev_same = jnp.concatenate([
        jnp.array([False]),
        (s_actor[1:] == s_actor[:-1]) & (s_seq[1:] == s_seq[:-1])])
    cand = s_valid & ~prev_same & (s_seq > peer_clock[s_actor])

    # rank within each actor's candidate run (segmented cumsum)
    new_actor = jnp.concatenate([
        jnp.array([True]), s_actor[1:] != s_actor[:-1]])
    r = jnp.cumsum(cand.astype(jnp.int32))
    base = jax.lax.cummax(
        jnp.where(new_actor, r - cand.astype(jnp.int32), 0))
    rank = r - base                                # 1-based among accepted
    accept = cand & (s_seq == peer_clock[s_actor] + rank)

    # append accepted rows at the end of the buffer; rows past capacity
    # are rejected outright (clock must not advance past stored ops).
    # Rejections are a suffix of each actor's accepted run, so per-actor
    # prefix contiguity survives.
    acc32 = accept.astype(jnp.int32)
    pos = count + jnp.cumsum(acc32) - acc32
    accept = accept & (pos < n_cap)
    acc32 = accept.astype(jnp.int32)
    slot = jnp.where(accept, pos, n_cap)
    seg_b, actor_b, seq_b, clock_b, del_b, valid_b = buf
    actor_b = actor_b.at[slot].set(s_actor, mode='drop')
    seq_b = seq_b.at[slot].set(s_seq, mode='drop')
    seg_b = seg_b.at[slot].set(in_seg[order], mode='drop')
    clock_b = clock_b.at[slot].set(in_clock[order], mode='drop')
    del_b = del_b.at[slot].set(in_del[order], mode='drop')
    valid_b = valid_b.at[slot].set(True, mode='drop')

    new_count = count + jnp.sum(acc32)
    new_clock = peer_clock.at[s_actor].add(acc32)
    accepted_total = jnp.sum(acc32)
    return (seg_b, actor_b, seq_b, clock_b, del_b, valid_b), \
        new_clock, new_count, accepted_total


def _delta_round_body(seg_id, actor, seq, clock, is_del, valid, count,
                      peer_clock, *, window, n_peers, ring):
    """One delta-sync round (SPMD body; local leading axis = 1)."""
    assert seg_id.shape[0] == 1, \
        f'{seg_id.shape[0]} peers share one device; use one device per peer'
    me = jax.lax.axis_index(PEER_AXIS)
    n_cap = seg_id.shape[1]
    ac, sq, vd = actor[0], seq[0], valid[0]

    clocks_all = jax.lax.all_gather(peer_clock[0], PEER_AXIS)   # [P, A]
    if ring:
        # ship to the next ring neighbor only, against ITS clock
        nxt = (me + 1) % n_peers
        target_clock = clocks_all[nxt]
        uncovered = target_clock[ac] < sq
    else:
        covered = clocks_all[:, ac] >= sq[None, :]              # [P, n]
        mine = jnp.arange(n_peers)[:, None] == me
        uncovered = ~jnp.all(covered | mine, axis=0)
    needed = vd & uncovered

    # select up to `window` needed ops in (actor, seq) order, so a
    # truncated window still ships contiguous per-actor prefixes
    order = jnp.lexsort((sq, ac, ~needed))
    take = order[:window]
    w_valid = needed[take]
    w_actor, w_seq, w_seg = ac[take], sq[take], seg_id[0][take]
    w_clock, w_del = clock[0][take], is_del[0][take]

    if ring:
        perm = [(i, (i + 1) % n_peers) for i in range(n_peers)]
        ship = lambda x: jax.lax.ppermute(x, PEER_AXIS, perm)  # noqa: E731
        in_actor, in_seq, in_seg = map(ship, (w_actor, w_seq, w_seg))
        in_clock, in_del, in_valid = map(ship, (w_clock, w_del, w_valid))
    else:
        g = lambda x: jax.lax.all_gather(x, PEER_AXIS)         # noqa: E731
        from_others = jnp.arange(n_peers) != me
        in_actor, in_seq, in_seg = (g(w_actor).reshape(-1),
                                    g(w_seq).reshape(-1),
                                    g(w_seg).reshape(-1))
        in_clock = g(w_clock).reshape(-1, w_clock.shape[-1])
        in_del = g(w_del).reshape(-1)
        in_valid = (g(w_valid) & from_others[:, None]).reshape(-1)

    buf = (seg_id[0], ac, sq, clock[0], is_del[0], vd)
    buf, new_clock, new_count, accepted = _accept_incoming(
        in_actor, in_seq, in_clock, in_seg, in_del, in_valid,
        buf, peer_clock[0], count[0], n_cap)

    shipped = jax.lax.psum(jnp.sum(w_valid), PEER_AXIS)
    accepted = jax.lax.psum(accepted, PEER_AXIS)
    seg_b, actor_b, seq_b, clock_b, del_b, valid_b = buf
    return (seg_b[None], actor_b[None], seq_b[None], clock_b[None],
            del_b[None], valid_b[None], new_count[None],
            new_clock[None], shipped, accepted)


@lru_cache(maxsize=64)
def _delta_round_fn(mesh, window, ring):
    n_peers = mesh.devices.size
    spec = P(PEER_AXIS)
    return jax.jit(shard_map(
        partial(_delta_round_body, window=window, n_peers=n_peers,
                ring=ring),
        mesh=mesh,
        in_specs=(spec,) * 8,
        out_specs=(spec,) * 8 + (P(), P()),
    ))


def delta_sync_round(mesh, state, *, window=64, ring=False):
    """One clock-diff delta round. `state` is the 8-tuple
    (seg_id, actor, seq, clock, is_del, valid, count, peer_clock) with a
    leading peer axis; returns (new_state, shipped, accepted)."""
    out = _delta_round_fn(mesh, window, ring)(*state)
    return out[:8], int(out[8]), int(out[9])


def delta_sync_converge(mesh, state, *, window=64, ring=False,
                        max_rounds=1000):
    """Run delta rounds until a round ships nothing. Returns
    (state, shipped_per_round) — the last entry is always 0, certifying
    convergence; per-round traffic is bounded by P * window ops."""
    shipped_log = []
    for _ in range(max_rounds):
        state, shipped, _ = delta_sync_round(mesh, state, window=window,
                                             ring=ring)
        shipped_log.append(shipped)
        if shipped == 0:
            return state, shipped_log
    raise RuntimeError(f'no convergence after {max_rounds} delta rounds')


def make_delta_state(mesh, seg_id, actor, seq, clock, is_del, valid,
                     n_cap):
    """Build + place the per-peer delta-sync state from each peer's
    locally-generated ops ([P, n] columns). Buffers are padded to
    ``n_cap`` (capacity for the converged union); replica clocks start
    as each peer's own contribution.

    Preconditions (validated): each peer's ``valid`` rows form a
    contiguous prefix (accepted ops append at ``count``), and each
    (peer, actor)'s held seqs are contiguous from 1 — the clock-prefix
    model the acceptance logic relies on. Holes would silently corrupt
    buffers or stall convergence, so they are rejected here.
    """
    p, n = seg_id.shape
    a = clock.shape[-1]

    def pad(x, fill=0):
        out = np.full((p, n_cap) + x.shape[2:], fill, x.dtype)
        out[:, :n] = x
        return out

    counts = valid.sum(axis=1).astype(np.int32)
    peer_clock = np.zeros((p, a), np.int32)
    for i in range(p):
        if valid[i].any() and not valid[i][:counts[i]].all():
            raise ValueError(
                f'peer {i}: valid rows must form a contiguous prefix')
        acts, sqs = actor[i][valid[i]], seq[i][valid[i]]
        np.maximum.at(peer_clock[i], acts, sqs)
        held = np.bincount(acts, minlength=a)
        if (peer_clock[i] != held[:a]).any():
            bad = int(np.flatnonzero(peer_clock[i] != held[:a])[0])
            raise ValueError(
                f'peer {i}, actor {bad}: held seqs must be contiguous '
                f'from 1 (max seq {peer_clock[i][bad]}, '
                f'{held[bad]} ops held)')
    state = (pad(np.asarray(seg_id, np.int32)),
             pad(np.asarray(actor, np.int32)),
             pad(np.asarray(seq, np.int32)),
             pad(np.asarray(clock, np.int32)),
             pad(np.asarray(is_del, bool)),
             pad(np.asarray(valid, bool)),
             counts, peer_clock)
    return tuple(shard_peers(mesh, x) for x in state)
