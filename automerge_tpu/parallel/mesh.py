"""Mesh construction and document-axis sharding helpers."""

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DOC_AXIS = 'docs'


def make_mesh(n_devices=None, axis=DOC_AXIS, devices=None):
    """A 1-D mesh over the available devices.

    Documents are embarrassingly parallel (independent CRDT replicas), so a
    single mesh axis suffices for the doc dimension; collectives are only
    needed for global statistics and cross-doc rebalancing.
    """
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis,))


def shard_docs(mesh, *arrays, axis=DOC_AXIS):
    """Place arrays with their leading (document) axis split over the mesh."""
    sharding = NamedSharding(mesh, P(axis))
    placed = tuple(jax.device_put(a, sharding) for a in arrays)
    return placed if len(placed) != 1 else placed[0]
