"""Mesh construction and document-axis sharding helpers."""

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DOC_AXIS = 'docs'


def make_mesh(n_devices=None, axis=DOC_AXIS, devices=None):
    """A 1-D mesh over the available devices.

    Documents are embarrassingly parallel (independent CRDT replicas), so a
    single mesh axis suffices for the doc dimension; collectives are only
    needed for global statistics and cross-doc rebalancing.
    """
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis,))


def doc_sharding(mesh, ndim=1, axis=None):
    """The canonical doc-axis :class:`NamedSharding`: leading axis split
    over the mesh, trailing axes replicated within the shard. This is
    the ONE place a doc-major placement spec is constructed — the dense
    store's plane placement, :func:`shard_docs` and the sharded doc set
    all route through it, so doc-locality (whole documents per device)
    cannot drift between call sites.
    """
    name = axis if axis is not None else mesh.axis_names[0]
    return NamedSharding(mesh, P(name, *([None] * (ndim - 1))))


def shard_docs(mesh, *arrays, axis=DOC_AXIS):
    """Place arrays with their leading (document) axis split over the mesh."""
    sharding = doc_sharding(mesh, axis=axis)
    placed = tuple(jax.device_put(a, sharding) for a in arrays)
    return placed if len(placed) != 1 else placed[0]


def shard_device(mesh, shard, n_shards=None):
    """The device owning logical shard ``shard`` of an ``n_shards``-way
    doc partition over ``mesh`` (round-robin when there are more shards
    than devices). Returns None for an empty mesh."""
    devices = mesh.devices.reshape(-1)
    if devices.size == 0:
        return None
    return devices[shard % devices.size]
