"""Packed-state checkpoints: O(state) resume alongside the change log.

The reference's only durability format is the change log — ``save()``
serializes every change ever applied and ``load()`` replays them
(src/automerge.js:45-52), so resume cost is a full CRDT replay of the
history. This module adds the SURVEY §5 "packed device-state snapshot":
the CRDT state itself — field entries with their closure clocks,
sequence insertion trees as columnar node arrays, vector clock, dep
frontier, causal buffer, and the per-change closure table — WITHOUT op
payloads or change bodies. Loading rebuilds a working backend with NO
replay: cost is the size of the live state, which includes O(history)
closure *metadata* (one actor->seq clock per applied change — the same
table the engine keeps in memory, and what keeps future concurrency
checks exact) but none of the op/value payloads, so snapshots are much
smaller than the log and resume skips all resolution work.

What a snapshot preserves: the document (bit-identical materialization),
convergence behavior for all future changes (closure table keeps
concurrency checks exact, even against pre-snapshot entries), duplicate
tolerance, causal buffering. What it drops: the replayable change bodies
— ``get_missing_changes`` for a peer whose clock predates the snapshot
raises (such a peer needs the snapshot or the full log), and ``save()``
of a resumed doc carries only post-resume changes. Keep the log for
archival; use snapshots for fast resume — the same split as a database
checkpoint + WAL.
"""

import json as _json

from .common import ROOT_ID
from .device.backend import DeviceBackendState, _ObjRecord, get_patch
from . import frontend as Frontend
from .device import backend as DeviceBackend

FORMAT = 'automerge-tpu-snapshot@1'


class SnapshotCorruptError(ValueError):
    """A snapshot payload failed validation: truncated bytes, non-JSON
    text, a checksum mismatch, or a missing/mistyped field. Every
    load path raises this (naming what failed) instead of leaking a
    bare ``KeyError``/``JSONDecodeError`` from deep inside
    reconstruction — a corrupt checkpoint must be a clean, catchable
    condition, not a crash."""


def _require(payload, fields, what):
    """Validate that ``payload`` is a dict carrying every name in
    ``fields``; raise :class:`SnapshotCorruptError` naming the first
    missing field."""
    if not isinstance(payload, dict):
        raise SnapshotCorruptError(
            f'{what}: payload is {type(payload).__name__}, not a dict')
    for name in fields:
        if name not in payload:
            raise SnapshotCorruptError(
                f"{what}: missing field '{name}' (truncated or "
                f"corrupt snapshot)")


def _corrupt_guard(fn, what):
    """Run reconstruction ``fn``; fold any mistyped-field crash
    (AttributeError/TypeError/ValueError/KeyError/...) into the
    documented :class:`SnapshotCorruptError` contract — presence checks
    alone cannot cover every corruption shape, and a load path must
    never leak a bare reconstruction traceback."""
    try:
        return fn()
    except SnapshotCorruptError:
        raise
    except Exception as err:
        raise SnapshotCorruptError(
            f'{what}: payload failed to reconstruct '
            f'({type(err).__name__}: {err})') from err


def snapshot_state(state):
    """DeviceBackendState -> JSON-ready dict (no op payload duplication:
    field entries reference values inline, change bodies are dropped)."""
    objects = []
    for obj_id, rec in state.objects.items():
        entry = {'obj': obj_id, 'type': rec.type, 'inbound': rec.inbound}
        if rec.is_sequence():
            entry['nodes'] = rec.nodes
            entry['parent'] = rec.node_parent
            entry['elem'] = rec.node_elem
            entry['actor'] = rec.node_actor
            entry['elem_ids'] = rec.elem_ids
        objects.append(entry)

    fields = [[obj, key, list(entries)]
              for (obj, key), entries in state.fields.items() if entries]

    closures = {actor: [e['all_deps'] for e in lst[:n]]
                for actor, (lst, n) in
                ((a, state.actor_states(a)) for a in state.states)}

    return {'format': FORMAT,
            'objects': objects,
            'fields': fields,
            'clock': state.clock,
            'deps': state.deps,
            'queue': state.queue,
            'closures': closures,
            # undo/redo stacks are plain op lists — cheap to carry, and
            # a resumed document keeps canUndo/canRedo working
            'undo_pos': state.undo_pos,
            'undo_stack': state.undo_stack,
            'redo_stack': state.redo_stack}


def restore_state(payload):
    """JSON dict -> DeviceBackendState (O(state)). Raises
    :class:`SnapshotCorruptError` (naming what failed) on a truncated,
    field-missing or mistyped payload."""
    _require(payload, ('format',), 'snapshot')
    if payload['format'] != FORMAT:
        raise SnapshotCorruptError(f'not a {FORMAT} snapshot')
    _require(payload, ('objects', 'fields', 'clock', 'deps', 'queue',
                       'closures'), 'snapshot')
    return _corrupt_guard(lambda: _restore_state_unchecked(payload),
                          'snapshot')


def _restore_state_unchecked(payload):
    state = DeviceBackendState()
    state.objects = {}
    for entry in payload['objects']:
        _require(entry, ('obj', 'type', 'inbound'), 'snapshot object')
        rec = _ObjRecord(entry['type'])
        rec.inbound = [tuple(ref) for ref in entry['inbound']]
        if rec.is_sequence():
            _require(entry, ('nodes', 'parent', 'elem', 'actor',
                             'elem_ids'), 'snapshot sequence object')
            rec.nodes = list(entry['nodes'])
            rec.node_of = {e: i for i, e in enumerate(rec.nodes)}
            rec.node_parent = list(entry['parent'])
            rec.node_elem = list(entry['elem'])
            rec.node_actor = list(entry['actor'])
            rec.elem_ids = list(entry['elem_ids'])
        state.objects[entry['obj']] = rec
    if ROOT_ID not in state.objects:
        state.objects[ROOT_ID] = _ObjRecord(None)
    state._owned = set(state.objects)

    state.fields = {(obj, key): tuple(entries)
                    for obj, key, entries in payload['fields']}
    state.rebuild_link_fields()
    state.clock = dict(payload['clock'])
    state.deps = dict(payload['deps'])
    state.queue = list(payload['queue'])
    # closure table: per (actor, seq) transitive deps, change bodies gone.
    # 'change': None marks a snapshot-era entry (duplicate deliveries are
    # dropped unverified; get_missing_changes refuses the range).
    for actor, rows in payload['closures'].items():
        state.states[actor] = [{'change': None, 'all_deps': deps}
                               for deps in rows]
        state.state_lens[actor] = len(rows)
    state.history = []
    state.history_len = 0
    state.log_truncated = True
    # absent in pre-undo snapshots: default to empty stacks
    state.undo_pos = payload.get('undo_pos', 0)
    state.undo_stack = [list(ops) for ops in payload.get('undo_stack', [])]
    state.redo_stack = [list(ops) for ops in payload.get('redo_stack', [])]
    return state


GENERAL_FORMAT = 'automerge-tpu-general-doc-snapshot@1'


def _snapshot_general(state):
    """GeneralBackendState -> JSON string: the packed store bytes plus
    the token's protocol state (clock, dep frontier, closure table,
    undo/redo)."""
    import base64
    from .device import general_backend as _gb
    # a held old token must snapshot ITS history, not the store's
    # newer content (r5 review: clock/content divergence)
    state = _gb.current_token(state)
    store_bytes = state.store.save_snapshot()
    return _json.dumps({
        'format': GENERAL_FORMAT,
        'store': base64.b64encode(store_bytes).decode('ascii'),
        'clock': state.clock,
        'deps': state.deps,
        'all_deps': [[a, s, d] for (a, s), d in
                     state._all_deps.items()],
        'undo_pos': state.undo_pos,
        'undo_stack': state.undo_stack,
        'redo_stack': state.redo_stack,
    })


def _restore_general(payload, actor_id=None):
    import base64
    import binascii
    from .device import general as _general
    from .device import general_backend as _gb
    _require(payload, ('store', 'clock', 'deps', 'all_deps'),
             'general snapshot')
    try:
        store_bytes = base64.b64decode(payload['store'])
    except (binascii.Error, TypeError, ValueError) as err:
        raise SnapshotCorruptError(
            f"general snapshot: field 'store' is not valid base64 "
            f'({err})') from None
    try:
        store = _general.GeneralStore.load_snapshot(store_bytes)
    except SnapshotCorruptError:
        raise
    except Exception as err:
        raise SnapshotCorruptError(
            f"general snapshot: field 'store' failed to decode "
            f'({type(err).__name__}: {err})') from err
    store._gb_version = 0

    def build():
        state = _gb.GeneralBackendState(
            store, 0, dict(payload['clock']), dict(payload['deps']),
            {(a, s): d for a, s, d in payload['all_deps']})
        state.undo_pos = payload.get('undo_pos', 0)
        state.undo_stack = [list(ops) for ops
                            in payload.get('undo_stack', [])]
        state.redo_stack = [list(ops) for ops
                            in payload.get('redo_stack', [])]
        return state
    state = _corrupt_guard(build, 'general snapshot')
    options = {'backend': DeviceBackend}
    if actor_id is not None:
        options['actorId'] = actor_id
    doc = Frontend.init(options)
    patch = _gb.get_patch(state)
    patch['state'] = state
    return Frontend.apply_patch(doc, patch)


def save_snapshot(doc):
    """Serialize a device-backed document's packed state (the fast-resume
    artifact; `save()` remains the archival change log). Covers both
    the per-doc device backend and bulk-routed
    (:class:`~.device.general_backend.GeneralBackendState`)
    documents."""
    from .device.general_backend import GeneralBackendState
    state = Frontend.get_backend_state(doc)
    if isinstance(state, GeneralBackendState):
        return _snapshot_general(state)
    if not isinstance(state, DeviceBackendState):
        raise TypeError(
            'save_snapshot requires a device-backed document; host-oracle '
            'documents use save() (the change log)')
    return _json.dumps(snapshot_state(state))


def load_snapshot(data, actor_id=None):
    """Materialize a document from a packed snapshot in O(state).

    Every corruption mode — truncated bytes, non-JSON text, missing
    fields — surfaces as a :class:`SnapshotCorruptError` naming what
    failed, never a bare ``JSONDecodeError``/``KeyError``."""
    try:
        payload = _json.loads(data)
    except (ValueError, TypeError) as err:
        raise SnapshotCorruptError(
            f'snapshot payload is not valid JSON (truncated or '
            f'corrupt): {err}') from None
    if not isinstance(payload, dict):
        raise SnapshotCorruptError(
            f'snapshot payload decodes to {type(payload).__name__}, '
            f'not an object')
    if payload.get('format') == GENERAL_FORMAT:
        return _restore_general(payload, actor_id=actor_id)
    state = restore_state(payload)
    options = {'backend': DeviceBackend}
    if actor_id is not None:
        options['actorId'] = actor_id
    doc = Frontend.init(options)
    patch = get_patch(state)
    patch['state'] = state
    return Frontend.apply_patch(doc, patch)


# camelCase aliases (reference API style)
saveSnapshot = save_snapshot
loadSnapshot = load_snapshot
