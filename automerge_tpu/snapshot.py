"""Packed-state checkpoints: O(state) resume alongside the change log.

The reference's only durability format is the change log — ``save()``
serializes every change ever applied and ``load()`` replays them
(src/automerge.js:45-52), so resume cost is a full CRDT replay of the
history. This module adds the SURVEY §5 "packed device-state snapshot":
the CRDT state itself — field entries with their closure clocks,
sequence insertion trees as columnar node arrays, vector clock, dep
frontier, causal buffer, and the per-change closure table — WITHOUT op
payloads or change bodies. Loading rebuilds a working backend with NO
replay: cost is the size of the live state, which includes O(history)
closure *metadata* (one actor->seq clock per applied change — the same
table the engine keeps in memory, and what keeps future concurrency
checks exact) but none of the op/value payloads, so snapshots are much
smaller than the log and resume skips all resolution work.

What a snapshot preserves: the document (bit-identical materialization),
convergence behavior for all future changes (closure table keeps
concurrency checks exact, even against pre-snapshot entries), duplicate
tolerance, causal buffering. What it drops: the replayable change bodies
— ``get_missing_changes`` for a peer whose clock predates the snapshot
raises (such a peer needs the snapshot or the full log), and ``save()``
of a resumed doc carries only post-resume changes. Keep the log for
archival; use snapshots for fast resume — the same split as a database
checkpoint + WAL.
"""

import json as _json

from .common import ROOT_ID
from .device.backend import DeviceBackendState, _ObjRecord, get_patch
from . import frontend as Frontend
from .device import backend as DeviceBackend

FORMAT = 'automerge-tpu-snapshot@1'


def snapshot_state(state):
    """DeviceBackendState -> JSON-ready dict (no op payload duplication:
    field entries reference values inline, change bodies are dropped)."""
    objects = []
    for obj_id, rec in state.objects.items():
        entry = {'obj': obj_id, 'type': rec.type, 'inbound': rec.inbound}
        if rec.is_sequence():
            entry['nodes'] = rec.nodes
            entry['parent'] = rec.node_parent
            entry['elem'] = rec.node_elem
            entry['actor'] = rec.node_actor
            entry['elem_ids'] = rec.elem_ids
        objects.append(entry)

    fields = [[obj, key, list(entries)]
              for (obj, key), entries in state.fields.items() if entries]

    closures = {actor: [e['all_deps'] for e in lst[:n]]
                for actor, (lst, n) in
                ((a, state.actor_states(a)) for a in state.states)}

    return {'format': FORMAT,
            'objects': objects,
            'fields': fields,
            'clock': state.clock,
            'deps': state.deps,
            'queue': state.queue,
            'closures': closures,
            # undo/redo stacks are plain op lists — cheap to carry, and
            # a resumed document keeps canUndo/canRedo working
            'undo_pos': state.undo_pos,
            'undo_stack': state.undo_stack,
            'redo_stack': state.redo_stack}


def restore_state(payload):
    """JSON dict -> DeviceBackendState (O(state))."""
    if payload.get('format') != FORMAT:
        raise ValueError(f'not a {FORMAT} snapshot')
    state = DeviceBackendState()
    state.objects = {}
    for entry in payload['objects']:
        rec = _ObjRecord(entry['type'])
        rec.inbound = [tuple(ref) for ref in entry['inbound']]
        if rec.is_sequence():
            rec.nodes = list(entry['nodes'])
            rec.node_of = {e: i for i, e in enumerate(rec.nodes)}
            rec.node_parent = list(entry['parent'])
            rec.node_elem = list(entry['elem'])
            rec.node_actor = list(entry['actor'])
            rec.elem_ids = list(entry['elem_ids'])
        state.objects[entry['obj']] = rec
    if ROOT_ID not in state.objects:
        state.objects[ROOT_ID] = _ObjRecord(None)
    state._owned = set(state.objects)

    state.fields = {(obj, key): tuple(entries)
                    for obj, key, entries in payload['fields']}
    state.rebuild_link_fields()
    state.clock = dict(payload['clock'])
    state.deps = dict(payload['deps'])
    state.queue = list(payload['queue'])
    # closure table: per (actor, seq) transitive deps, change bodies gone.
    # 'change': None marks a snapshot-era entry (duplicate deliveries are
    # dropped unverified; get_missing_changes refuses the range).
    for actor, rows in payload['closures'].items():
        state.states[actor] = [{'change': None, 'all_deps': deps}
                               for deps in rows]
        state.state_lens[actor] = len(rows)
    state.history = []
    state.history_len = 0
    state.log_truncated = True
    # absent in pre-undo snapshots: default to empty stacks
    state.undo_pos = payload.get('undo_pos', 0)
    state.undo_stack = [list(ops) for ops in payload.get('undo_stack', [])]
    state.redo_stack = [list(ops) for ops in payload.get('redo_stack', [])]
    return state


GENERAL_FORMAT = 'automerge-tpu-general-doc-snapshot@1'


def _snapshot_general(state):
    """GeneralBackendState -> JSON string: the packed store bytes plus
    the token's protocol state (clock, dep frontier, closure table,
    undo/redo)."""
    import base64
    from .device import general_backend as _gb
    # a held old token must snapshot ITS history, not the store's
    # newer content (r5 review: clock/content divergence)
    state = _gb.current_token(state)
    store_bytes = state.store.save_snapshot()
    return _json.dumps({
        'format': GENERAL_FORMAT,
        'store': base64.b64encode(store_bytes).decode('ascii'),
        'clock': state.clock,
        'deps': state.deps,
        'all_deps': [[a, s, d] for (a, s), d in
                     state._all_deps.items()],
        'undo_pos': state.undo_pos,
        'undo_stack': state.undo_stack,
        'redo_stack': state.redo_stack,
    })


def _restore_general(payload, actor_id=None):
    import base64
    from .device import general as _general
    from .device import general_backend as _gb
    store = _general.GeneralStore.load_snapshot(
        base64.b64decode(payload['store']))
    store._gb_version = 0
    state = _gb.GeneralBackendState(
        store, 0, dict(payload['clock']), dict(payload['deps']),
        {(a, s): d for a, s, d in payload['all_deps']})
    state.undo_pos = payload.get('undo_pos', 0)
    state.undo_stack = [list(ops) for ops
                        in payload.get('undo_stack', [])]
    state.redo_stack = [list(ops) for ops
                        in payload.get('redo_stack', [])]
    options = {'backend': DeviceBackend}
    if actor_id is not None:
        options['actorId'] = actor_id
    doc = Frontend.init(options)
    patch = _gb.get_patch(state)
    patch['state'] = state
    return Frontend.apply_patch(doc, patch)


def save_snapshot(doc):
    """Serialize a device-backed document's packed state (the fast-resume
    artifact; `save()` remains the archival change log). Covers both
    the per-doc device backend and bulk-routed
    (:class:`~.device.general_backend.GeneralBackendState`)
    documents."""
    from .device.general_backend import GeneralBackendState
    state = Frontend.get_backend_state(doc)
    if isinstance(state, GeneralBackendState):
        return _snapshot_general(state)
    if not isinstance(state, DeviceBackendState):
        raise TypeError(
            'save_snapshot requires a device-backed document; host-oracle '
            'documents use save() (the change log)')
    return _json.dumps(snapshot_state(state))


def load_snapshot(data, actor_id=None):
    """Materialize a document from a packed snapshot in O(state)."""
    payload = _json.loads(data)
    if payload.get('format') == GENERAL_FORMAT:
        return _restore_general(payload, actor_id=actor_id)
    state = restore_state(payload)
    options = {'backend': DeviceBackend}
    if actor_id is not None:
        options['actorId'] = actor_id
    doc = Frontend.init(options)
    patch = get_patch(state)
    patch['state'] = state
    return Frontend.apply_patch(doc, patch)


# camelCase aliases (reference API style)
saveSnapshot = save_snapshot
loadSnapshot = load_snapshot
