"""Sync/session layer: DocSet, WatchableDoc, Connection.

The replication protocol is network-agnostic (parity with reference
src/connection.js): a Connection exchanges vector-clock advertisements and
missing changes per docId over a user-supplied send callback. The batched
TPU path for whole-DocSet merges lives in
:mod:`automerge_tpu.parallel.docset_engine`.
"""

from .doc_set import DocSet
from .device_doc_set import DeviceDocSet
from .dense_doc_set import DenseDocSet
from .general_doc_set import GeneralDocSet
from .serving import ServingDocSet
from .watchable_doc import WatchableDoc
from .connection import (Connection, BatchingConnection, WireConnection,
                         MessageRejected, validate_msg,
                         validate_wire_msg)
from .resilient import (ResilientConnection, AdmissionControl,
                        TokenBucket)
from .control import FleetController
from .transport import (TransportEndpoint, FrameDecoder, FrameError,
                        encode_frame)

__all__ = ['DocSet', 'DeviceDocSet', 'DenseDocSet', 'GeneralDocSet',
           'ServingDocSet', 'WatchableDoc', 'Connection',
           'BatchingConnection', 'WireConnection', 'MessageRejected',
           'validate_msg', 'validate_wire_msg', 'ResilientConnection',
           'AdmissionControl', 'TokenBucket', 'FleetController',
           'TransportEndpoint', 'FrameDecoder', 'FrameError',
           'encode_frame']
