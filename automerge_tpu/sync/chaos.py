"""ChaosTransport: a seeded adversarial message fabric for sync fleets.

The convergence claim of the whole system — replicas that exchange
messages end up byte-identical — is only worth something if it holds
when the transport misbehaves. This module is the harness that proves
it: a deterministic (seeded) in-process network between N peers that
drops, duplicates, delays/reorders, corrupts and partitions envelopes
on schedule, plus a fleet driver that wires
:class:`~.resilient.ResilientConnection` endpoints over it, ticks
logical time, and checks byte-identical convergence of every peer's
materialized state against a clean run.

Used by ``tests/test_chaos.py`` (the chaos convergence suite, pinned
seeds in CI) and ``bench.py``'s ``bench_degraded_link`` (the config-5
10k-doc fleet under 5%/20% loss).

Everything is logical-time and seeded — a failing schedule replays
exactly from its seed, which is what makes transport bugs debuggable.
"""

import copy
import json
import random
from collections import Counter

from .resilient import ResilientConnection


def doc_view(doc):
    """Plain-JSON materialization of one document (frontend docs and
    GeneralDocHandles alike) — the byte-identity comparand."""
    if hasattr(doc, 'materialize'):
        return doc.materialize()

    def conv(obj):
        name = type(obj).__name__
        if name == 'Text':
            return ''.join(str(c) for c in obj)
        if name == 'AmList':
            return [conv(v) for v in obj]
        if hasattr(obj, '_conflicts') or hasattr(obj, 'items'):
            return {k: conv(v) for k, v in obj.items()}
        return obj
    return conv(doc)


def doc_set_view(doc_set):
    """``{doc_id: plain tree}`` for a whole doc set (uses the batched
    read path when the doc set has one)."""
    if hasattr(doc_set, 'materialize_all'):
        return dict(doc_set.materialize_all())
    return {doc_id: doc_view(doc_set.get_doc(doc_id))
            for doc_id in doc_set.doc_ids}


def canonical(view):
    """Canonical byte encoding of a view — equality here IS
    byte-identical convergence."""
    return json.dumps(view, sort_keys=True, default=str)


def assert_digest_parity(doc_set):
    """Assert the incremental per-doc state digests equal an O(doc)
    recompute over the retained log, for every doc of a general-store
    doc set — the maintenance-correctness oracle the chaos schedules
    run after converging (no-op for doc sets without digests, or for
    snapshot-truncated stores whose history cannot be recomputed).
    COMPACTED stores stay checkable: the recompute starts from the
    digest recorded at each doc's horizon and folds only the retained
    tail, so the oracle survives the bodies being folded away."""
    store = getattr(doc_set, 'store', None)
    if store is None or not hasattr(store, 'digests_all'):
        return
    if store.log_truncated or not store._digest_valid:
        return
    digs = store.digests_all()
    for doc_id in doc_set.doc_ids:
        idx = doc_set.id_of[doc_id]
        got = int(digs[idx])
        want = store.digest_recompute(idx)
        assert got == want, (
            f'digest drift on {doc_id!r}: incremental {got:#x} != '
            f'recomputed {want:#x}')


class ChaosFleet:
    """N peers over a full-mesh adversarial fabric.

    ``doc_sets`` is a list of DocSet-like objects (one per peer); each
    directed link gets a :class:`ResilientConnection` endpoint. Per-tick
    scheduling: deliver every envelope whose delay expired, advance
    every endpoint's logical clock (retransmits + heartbeats), then
    flush batching endpoints. Fault injection happens at SEND time from
    one seeded RNG, so a schedule is a pure function of the seed.

    Fault knobs: ``drop``/``dup``/``corrupt`` are per-envelope
    probabilities; ``delay`` is the max extra ticks of random delivery
    delay (0 = in-order); :meth:`partition`/:meth:`heal` sever and
    restore node pairs (severed links drop everything, like a dead
    cable, not like a polite shutdown).
    """

    def __init__(self, doc_sets, seed=0, drop=0.0, dup=0.0, delay=0,
                 corrupt=0.0, batching=True, wire=False,
                 heartbeat_every=8, conn_kwargs=None, admission=None,
                 wire_version=None):
        self.doc_sets = list(doc_sets)
        self.rng = random.Random(seed)
        self.drop = drop
        self.dup = dup
        self.delay = delay
        self.corrupt = corrupt
        self.batching = batching
        self.wire = wire                 # columnar wire data path
        self.now = 0
        self._order = 0
        self.stats = Counter()
        self.queues = {}                 # (frm, to) -> [[due, order, env]]
        self.conns = {}                  # (owner, peer) -> endpoint
        self.partitioned = set()         # frozenset({a, b})
        self._conn_kwargs = dict(conn_kwargs or {})
        self._conn_kwargs.setdefault('heartbeat_every', heartbeat_every)
        if wire:
            self._conn_kwargs['wire'] = True
        # per-node wire-format version: an int pins every node, a list
        # pins per node (None entries = the build default) — the
        # mixed-version interop schedules run v1/v2/v3 peers in ONE
        # fleet and must still converge byte-identically (a pair
        # speaks min(sides), so one pinned node downgrades its links)
        if wire_version is None or isinstance(wire_version, int):
            self.node_wire_version = [wire_version] * len(self.doc_sets)
        else:
            self.node_wire_version = list(wire_version)
        # node-wide admission: ONE AdmissionControl shared by all of a
        # node's endpoints (the fleet-wide valve; the per-link valve
        # rides conn_kwargs['admission']). `admission` is kwargs for
        # every node, or a per-node list (None entries = unmetered)
        from .resilient import AdmissionControl
        n_nodes = len(self.doc_sets)
        if admission is None:
            self.node_admission = [None] * n_nodes
        elif isinstance(admission, dict):
            self.node_admission = [AdmissionControl(**admission)
                                   for _ in range(n_nodes)]
        else:
            self.node_admission = [
                cfg if cfg is None or
                isinstance(cfg, AdmissionControl)
                else AdmissionControl(**cfg) for cfg in admission]
        nodes = range(len(self.doc_sets))
        for a in nodes:
            for b in nodes:
                if a != b:
                    self.queues[(a, b)] = []
        for a in nodes:
            for b in nodes:
                if a != b:
                    self._make_conn(a, b)
        for conn in self.conns.values():
            conn.open()

    def _make_conn(self, owner, peer):
        # every endpoint is peer-scoped (peer_id=node<N>): its counters
        # land process-wide AND under a per-LINK scope, and a doc set
        # with a connection registry reports it per-connection in
        # fleet_status() — the chaos suite exercises the same operator
        # surface a real deployment reads. The scope carries the owner
        # node too (node/node<owner>/peer/node<peer>/): every fleet
        # node shares this one process's registry, so two links
        # targeting the same node (0->2 and 1->2) must not merge into
        # one peer/node2/ slice the way they never would across real
        # hosts
        from ..utils.metrics import metrics
        kwargs = dict(self._conn_kwargs)
        if self.wire and self.node_wire_version[owner] is not None:
            kwargs['wire_version'] = self.node_wire_version[owner]
        conn = ResilientConnection(
            self.doc_sets[owner], self._sender(owner, peer),
            batching=self.batching,
            shared_admission=self.node_admission[owner],
            seed=self.rng.randrange(1 << 30),
            peer_id=f'node{peer}',
            scope=metrics.scoped(node=f'node{owner}',
                                 peer=f'node{peer}'),
            **kwargs)
        self.conns[(owner, peer)] = conn
        return conn

    # -- the adversarial link ------------------------------------------------

    def _sender(self, frm, to):
        def send(env):
            self.stats['sent'] += 1
            if frozenset((frm, to)) in self.partitioned:
                self.stats['partition_dropped'] += 1
                return
            copies = 1
            if self.drop and self.rng.random() < self.drop:
                self.stats['dropped'] += 1
                copies = 0
            elif self.dup and self.rng.random() < self.dup:
                self.stats['duplicated'] += 1
                copies = 2
            for _ in range(copies):
                e = env
                if self.corrupt and self.rng.random() < self.corrupt:
                    self.stats['corrupted'] += 1
                    e = self._corrupt_env(env)
                due = self.now + 1 + (self.rng.randrange(self.delay + 1)
                                      if self.delay else 0)
                self._order += 1
                self.queues[(frm, to)].append([due, self._order, e])
        return send

    def _corrupt_env(self, env):
        """One seeded mutation: flipped checksum, bogus version, mangled
        seq/kind, a field torn out of the payload, or a bit flipped in
        a wire blob — every shape the receiver must survive (and count)
        without crashing. Blob corruption targets the CRC32-over-bytes
        path: the flipped byte must be caught BEFORE the codec parses,
        never quarantine a doc."""
        env = copy.deepcopy(env)
        mode = self.rng.randrange(6)
        if mode == 0:
            env['sum'] = env.get('sum', 0) ^ 0x5A5A5A5A
        elif mode == 1:
            env['v'] = 99
        elif mode == 2:
            env['seq'] = 'corrupt'
        elif mode == 3:
            env['kind'] = 'garbage'
        elif mode == 4:
            payload = env.get('payload')
            # flip one bit in a binary payload section — blob, the v2
            # literal tab or the v3 session-definition tab, all under
            # the CRC32-over-bytes checksum (a flipped v3 tab must be
            # caught by the envelope sum and repaired by retransmit,
            # never poison the receiver's session table)
            field = self.rng.choice(('blob', 'tab'))
            part = payload.get(field) if isinstance(payload, dict) \
                else None
            if not isinstance(part, (bytes, bytearray)) or not part:
                field = 'blob'
                part = payload.get(field) if isinstance(payload, dict) \
                    else None
            if isinstance(part, (bytes, bytearray)) and len(part):
                i = self.rng.randrange(len(part))
                payload[field] = part[:i] + \
                    bytes([part[i] ^ (1 << self.rng.randrange(8))]) + \
                    part[i + 1:]
            else:
                env['sum'] = -1
        else:
            body = env.get('payload') if isinstance(
                env.get('payload'), dict) else env.get('clocks')
            if isinstance(body, dict) and body:
                del body[self.rng.choice(sorted(body, key=str))]
            else:
                env['sum'] = -1
        return env

    # -- partitions ----------------------------------------------------------

    def partition(self, a, b):
        """Sever the (bidirectional) link between peers a and b; queued
        traffic on the link is lost too (a dead cable, not a drain)."""
        self.partitioned.add(frozenset((a, b)))
        self.queues[(a, b)].clear()
        self.queues[(b, a)].clear()

    def heal(self, a, b):
        self.partitioned.discard(frozenset((a, b)))

    # -- time ----------------------------------------------------------------

    def tick(self):
        """One network quantum: deliver due envelopes (per-link, in due
        order), advance every endpoint's clock, flush batching
        endpoints."""
        self.now += 1
        for (frm, to), q in self.queues.items():
            if not q:
                continue
            due = [m for m in q if m[0] <= self.now]
            if not due:
                continue
            q[:] = [m for m in q if m[0] > self.now]
            for _, _, env in sorted(due):
                self.stats['delivered'] += 1
                self.conns[(to, frm)].receive_msg(env)
        for conn in self.conns.values():
            conn.tick()
        for ctrl in self.node_admission:
            if ctrl is not None:
                ctrl.tick()            # the shared valve refills ONCE
                #                        per quantum, not once per link
        if self.batching or self.wire:
            for conn in self.conns.values():
                conn.flush()
        # serving doc sets advance their residency clock (last-touch
        # aging, memory-budget enforcement, quarantine parking)
        for ds in self.doc_sets:
            t = getattr(ds, 'tick', None)
            if t is not None:
                t()

    def pending(self):
        """Traffic still in flight: queued envelopes or unacked sends
        awaiting retransmission."""
        return any(self.queues.values()) or \
            any(c.in_flight for c in self.conns.values())

    # -- convergence ---------------------------------------------------------

    def views(self):
        return [doc_set_view(ds) for ds in self.doc_sets]

    def converged(self):
        views = [canonical(v) for v in self.views()]
        return all(v == views[0] for v in views[1:])

    def run(self, max_ticks=2000, min_ticks=0):
        """Tick until every peer's materialization is byte-identical
        and the fabric is quiet; returns the tick count. Raises if the
        fleet has not converged by ``max_ticks`` (a chaos schedule that
        defeats the resilience layer is a test failure, not a hang)."""
        while self.now < max_ticks:
            self.tick()
            if self.now >= min_ticks and not self.pending() \
                    and self.converged():
                return self.now
        raise RuntimeError(
            f'fleet failed to converge within {max_ticks} ticks '
            f'(stats: {dict(self.stats)})')

    def close(self):
        """Detach every endpoint from its doc set (so a doc set can be
        reused across fleets, e.g. by the bench's loss-rate sweep)."""
        for conn in self.conns.values():
            conn.close()

    # -- fault injection beyond the transport --------------------------------

    def inject_silent_divergence(self, node, doc_id, changes):
        """Mutate ONE replica's store out-of-band: apply ``changes``
        directly to ``node``'s doc set, bypassing the fabric entirely
        (no envelope, no checksum — exactly the logic-level corruption
        the transport layer cannot see). The injection is SILENT end
        to end: the node's endpoints never see the apply (their
        ``doc_changed`` handlers are detached around it) and are then
        told the peer already covers the new clock — so injecting an
        "evil twin" of a change another replica holds (same ``(actor,
        seq)``, other content) leaves every clock EQUAL, the normal
        protocol ships nothing, and the replicas stay silently
        diverged forever. Only the heartbeat digest audit can catch
        it."""
        from .connection import clock_union
        ds = self.doc_sets[node]
        owned = [c for (o, _p), c in self.conns.items() if o == node]
        inners = [getattr(c, '_conn', c) for c in owned]
        for inner in inners:
            ds.unregister_handler(inner.doc_changed)
        try:
            out = ds.apply_changes(doc_id, changes)
        finally:
            for inner in inners:
                ds.register_handler(inner.doc_changed)
        clock = ds.clock_of_id(doc_id) if \
            hasattr(ds, 'clock_of_id') else {}
        for conn, inner in zip(owned, inners):
            clock_union(inner._their_clock, doc_id, clock)
            clock_union(inner._our_clock, doc_id, clock)
            pend = getattr(inner, '_pending_send', None)
            if pend is not None:
                pend.pop(doc_id, None)
            acked = getattr(conn, '_peer_acked', None)
            if acked is not None:
                clock_union(acked, doc_id, clock)
        return out

    # -- crash/restart -------------------------------------------------------

    def reconnect(self, node, doc_set=None):
        """Crash-restart peer ``node``: all its in-flight traffic is
        lost, its doc set is replaced (e.g. recovered from snapshot +
        journal), and every adjacent link re-establishes with FRESH
        envelope sessions on both ends — exactly what a process restart
        does to a connection."""
        if doc_set is not None:
            self.doc_sets[node] = doc_set
        for (owner, peer), conn in list(self.conns.items()):
            if node not in (owner, peer):
                continue
            try:
                conn.close()
            except Exception:
                pass                     # the crashed side's handler is gone
            self.queues[(owner, peer)].clear()
            self._make_conn(owner, peer).open()


# -- socket-level chaos (PR 19) ------------------------------------------------
#
# Everything above injects faults on an IN-PROCESS fabric: envelopes
# are Python objects and a "partition" is a list clear. The classes
# below move the same seeded adversity to REAL loopback TCP: a
# fault-injecting proxy per peer pair (latency, jitter, chunk drop /
# duplicate — which corrupt the byte stream and exercise the frame
# codec's CRC reset path — mid-frame cuts, hard partitions) under a
# SocketChaosFleet that mirrors ChaosFleet's driver API, so the PR 13
# scenario schedules replay unchanged over actual sockets and compare
# byte-identical against the clean in-process oracle.

import asyncio  # noqa: E402


class ChaosProxy:
    """A fault-injecting TCP proxy for ONE peer pair: the dialing
    endpoint connects here instead of to its peer, and every byte
    stream crossing the proxy suffers the configured faults.

    Chunk-level drop/duplicate deliberately CORRUPT the framed stream
    (TCP itself never loses bytes mid-connection) — that is the point:
    the frame codec must catch the damage by CRC, reset the session
    and let the envelope layer repair by retransmit. ``cut`` forwards
    half a chunk then kills the pipe (a mid-frame connection reset —
    the torn-tail path). ``partition()`` stops the listener and aborts
    live pipes (a dead cable: re-dials get ECONNREFUSED and back off)
    until ``heal()`` re-opens the same port.

    ``target_port_of`` is a callable so a restarted peer (new server
    port) is re-routable without rebuilding the proxy."""

    def __init__(self, target_port_of, host='127.0.0.1', seed=0,
                 latency_ms=0.0, jitter_ms=0.0, drop=0.0, dup=0.0,
                 cut=0.0, corrupt=0.0):
        self.target_port_of = target_port_of
        self.host = host
        self.rng = random.Random(seed)
        self.latency_ms = latency_ms
        self.jitter_ms = jitter_ms
        self.drop = drop
        self.dup = dup
        self.cut = cut
        self.corrupt = corrupt
        self.partitioned = False
        self.port = None
        self._server = None
        self.pipes = set()
        self.stats = Counter()

    async def start(self):
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port or 0)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def _handle(self, creader, cwriter):
        if self.partitioned:
            cwriter.close()
            return
        try:
            sreader, swriter = await asyncio.open_connection(
                self.host, self.target_port_of())
        except OSError:
            cwriter.close()
            return
        pipe = (cwriter, swriter)
        self.pipes.add(pipe)
        pumps = (asyncio.ensure_future(self._pump(creader, swriter)),
                 asyncio.ensure_future(self._pump(sreader, cwriter)))
        try:
            await asyncio.wait(pumps,
                               return_when=asyncio.FIRST_COMPLETED)
        finally:
            for task in pumps:
                if not task.done():
                    task.cancel()
            self.pipes.discard(pipe)
            for writer in pipe:
                try:
                    writer.close()
                except Exception:
                    pass

    async def _pump(self, reader, writer):
        try:
            while True:
                data = await reader.read(4096)
                if not data or self.partitioned:
                    return
                if self.latency_ms or self.jitter_ms:
                    await asyncio.sleep(
                        (self.latency_ms +
                         self.rng.random() * self.jitter_ms) / 1e3)
                roll = self.rng.random()
                if roll < self.drop:
                    self.stats['dropped'] += 1
                    continue
                if roll < self.drop + self.cut:
                    self.stats['cut'] += 1
                    writer.write(data[:max(1, len(data) // 2)])
                    await writer.drain()
                    return              # mid-frame reset
                if roll < self.drop + self.cut + self.corrupt:
                    # flip one byte: whole-chunk drop/dup usually
                    # stays FRAME-aligned (TCP coalesces writes), so
                    # this is the fault that reliably exercises the
                    # codec's CRC reject -> stream reset -> re-dial
                    # path at the socket level
                    self.stats['corrupted'] += 1
                    i = self.rng.randrange(len(data))
                    data = data[:i] + bytes([data[i] ^ 0x40]) \
                        + data[i + 1:]
                writer.write(data)
                if self.dup and self.rng.random() < self.dup:
                    self.stats['dupped'] += 1
                    writer.write(data)
                await writer.drain()
        except (ConnectionError, OSError):
            return

    def _kill_pipes(self):
        for cwriter, swriter in list(self.pipes):
            for writer in (cwriter, swriter):
                try:
                    writer.transport.abort()
                except Exception:
                    pass

    async def sever(self):
        """Dead cable: stop listening (new dials are refused — the
        endpoints' re-dial backoff takes over) and abort live pipes."""
        self.partitioned = True
        if self._server is not None:
            self._server.close()
            try:
                await self._server.wait_closed()
            except Exception:
                pass
            self._server = None
        self._kill_pipes()

    async def heal(self):
        self.partitioned = False
        if self._server is None:
            await self.start()         # same recorded port

    async def close(self):
        self.partitioned = True
        if self._server is not None:
            self._server.close()
            try:
                await self._server.wait_closed()
            except Exception:
                pass
            self._server = None
        self._kill_pipes()


class SocketChaosFleet:
    """:class:`ChaosFleet`'s driver API over REAL loopback sockets:
    one :class:`~.transport.TransportEndpoint` per node (each hosting
    its doc set under one mux key), every pair joined through a
    :class:`ChaosProxy`, ticked synchronously — the fleet owns a
    private event loop, so the callers (tests, bench, schedule
    replay) stay plain synchronous code.

    Unlike the in-process fabric there is no seeded delivery ORDER:
    TCP + asyncio schedule delivery. The comparand is unchanged
    anyway — CRDT convergence makes the FINAL state byte-identical
    regardless of arrival order, which is exactly what the schedule
    replays assert against the clean oracle."""

    def __init__(self, doc_sets, seed=0, drop=0.0, dup=0.0, cut=0.0,
                 corrupt=0.0, latency_ms=0.0, jitter_ms=0.0,
                 heartbeat_every=8, conn_kwargs=None,
                 suspect_after=24, dead_after=48, max_queue=1024,
                 resume=True, dset='fleet', eager=True):
        self.loop = asyncio.new_event_loop()
        self.doc_sets = list(doc_sets)
        self.dset = dset
        self.seed = seed
        self.now = 0
        self._latency = latency_ms + jitter_ms
        ck = dict(conn_kwargs or {})
        ck.setdefault('heartbeat_every', heartbeat_every)
        self._conn_kwargs = ck
        self._ep_kwargs = dict(suspect_after=suspect_after,
                               dead_after=dead_after,
                               max_queue=max_queue, resume=resume,
                               redial_backoff=(1, 8), eager=eager)
        self._fault_kwargs = dict(latency_ms=latency_ms,
                                  jitter_ms=jitter_ms, drop=drop,
                                  dup=dup, cut=cut, corrupt=corrupt)
        self.endpoints = []
        self.proxies = {}              # (a, b) with a < b
        self._run(self._start())

    def _run(self, coro):
        return self.loop.run_until_complete(coro)

    def _make_endpoint(self, node, **overrides):
        from .transport import TransportEndpoint
        kwargs = dict(self._ep_kwargs)
        kwargs.update(overrides)
        return TransportEndpoint(
            f'node{node}', {self.dset: self.doc_sets[node]},
            conn_kwargs=dict(self._conn_kwargs), **kwargs)

    async def _start(self):
        n = len(self.doc_sets)
        for i in range(n):
            ep = self._make_endpoint(i)
            await ep.start()
            self.endpoints.append(ep)
        for a in range(n):
            for b in range(a + 1, n):
                proxy = ChaosProxy(
                    (lambda b=b: self.endpoints[b].port),
                    seed=self.seed * 1009 + a * 37 + b,
                    **self._fault_kwargs)
                await proxy.start()
                self.proxies[(a, b)] = proxy
                await self.endpoints[a].connect(
                    f'node{b}', '127.0.0.1', proxy.port)
        await self._pump(8)            # let the HELLOs land

    async def _pump(self, rounds):
        for _ in range(rounds):
            await asyncio.sleep(0)
        if self._latency:
            # real latency faults are wall-clock: give the delayed
            # chunks time to clear their timers
            await asyncio.sleep(self._latency * 1.5 / 1e3)

    # -- ChaosFleet driver API ----------------------------------------------

    def tick(self):
        self.now += 1
        self._run(self._tick_async())

    async def _tick_async(self):
        for ep in self.endpoints:
            if not ep.closed:
                await ep.tick()
        for ds in self.doc_sets:
            t = getattr(ds, 'tick', None)
            if t is not None:
                t()
        await self._pump(6)

    def partition(self, a, b):
        self._run(self.proxies[(min(a, b), max(a, b))].sever())

    def heal(self, a, b):
        self._run(self.proxies[(min(a, b), max(a, b))].heal())

    def kill(self, node):
        """Abrupt process death: sockets abort, nothing closes
        cleanly — peers only find out from their failure detectors."""
        self._run(self.endpoints[node].kill())

    def restart(self, node, doc_set=None, resume=True):
        """Bring a killed node back: a NEW endpoint (new epoch — the
        surviving peers rebuild their links through the wire-session
        resume path) hosting ``doc_set`` (default: the node's previous
        doc set, the recovered-from-durable-state posture). Pairs
        where the restarted node dials reconnect here; pairs dialing
        INTO it re-dial on their own backoff, routed by the proxies'
        late-bound target ports."""
        if doc_set is not None:
            self.doc_sets[node] = doc_set
        ep = self._make_endpoint(node, resume=resume)
        self.endpoints[node] = ep

        async def go():
            await ep.start()
            for (a, b), proxy in self.proxies.items():
                if a == node:
                    await ep.connect(f'node{b}', '127.0.0.1',
                                     proxy.port)
            await self._pump(8)
        self._run(go())

    def pending(self):
        return any(not ep.closed and ep.pending()
                   for ep in self.endpoints)

    def views(self):
        return [doc_set_view(ds) for ds in self.doc_sets]

    def converged(self):
        views = [canonical(v) for v in self.views()]
        return all(v == views[0] for v in views[1:])

    def run(self, max_ticks=2000, min_ticks=0):
        """Tick until byte-identical convergence and a quiet fabric;
        raises past ``max_ticks`` (a schedule that defeats the
        transport is a failure, not a hang)."""
        start = self.now
        while self.now - start < max_ticks:
            self.tick()
            if self.now - start >= min_ticks and not self.pending() \
                    and self.converged():
                return self.now
        raise RuntimeError(
            f'socket fleet failed to converge within {max_ticks} '
            f'ticks')

    def settle(self, max_rounds=400):
        """Event-driven drain to convergence: poke every endpoint
        once (flushing whatever the sync side staged), then just let
        the event loop run — receives kick their own eager flushes,
        acks ship inline, and the fleet quiesces WITHOUT a single
        tick quantum. This is the eager fast path's convergence
        driver: the time :meth:`settle` takes is the transport's real
        link floor, where :meth:`run` pays the tick schedule. Returns
        the number of pump rounds used; raises past ``max_rounds``.
        Heartbeats/keepalives/failure detection do NOT advance here —
        chaos schedules that need them still drive :meth:`tick`."""
        async def go():
            for ep in self.endpoints:
                if not ep.closed:
                    await ep.poke()
            quiet = 0
            for i in range(max_rounds):
                await self._pump(2)
                # pending() dips false between conversation legs while
                # bytes are still in flight, and converged() is a full
                # materialize — only pay for it after the fabric has
                # been quiet for a few consecutive rounds
                quiet = 0 if self.pending() else quiet + 1
                if quiet >= 3:
                    if self.converged():
                        return i + 1
                    quiet = 0
                    for ep in self.endpoints:   # quiet but divergent:
                        if not ep.closed:       # nudge staged work out
                            await ep.poke()
            return None
        rounds = self._run(go())
        if rounds is None:
            raise RuntimeError(
                f'socket fleet failed to settle within {max_rounds} '
                f'pump rounds')
        return rounds

    def close(self):
        async def go():
            for ep in self.endpoints:
                if not ep.closed:
                    await ep.close()
            for proxy in self.proxies.values():
                await proxy.close()
            await asyncio.sleep(0)
        self._run(go())
        self._run(asyncio.sleep(0.01))  # unwind cancellations
        self.loop.close()


def replay_schedule_over_sockets(schedule, chaos=None, doc_sets=None,
                                 max_ticks=4000, **fleet_kwargs):
    """Re-run a fleetsim scenario schedule (``build_schedule``) over
    real loopback sockets through the fault-injecting proxies, then
    converge. Returns the canonical per-node views plus the
    quarantine/divergence totals — the byte-identity comparand
    against :func:`~automerge_tpu.fleetsim.run_oracle`."""
    spec = schedule['spec']
    if doc_sets is None:
        from .general_doc_set import GeneralDocSet
        doc_sets = [GeneralDocSet(spec['n_docs'] + 8)
                    for _ in range(spec['n_nodes'])]
    fleet = SocketChaosFleet(
        doc_sets, seed=schedule['seed'] + 7,
        heartbeat_every=spec['heartbeat_every'],
        **dict(chaos or {}), **fleet_kwargs)
    try:
        for tick in schedule['ticks']:
            for a, b in tick.get('partition', ()):
                fleet.partition(a, b)
            for a, b in tick.get('heal', ()):
                fleet.heal(a, b)
            by_node = {}
            for node, doc_id, changes in tick['writes']:
                by_node.setdefault(node, {})[doc_id] = changes
            for node, batch in by_node.items():
                doc_sets[node].apply_changes_batch(batch)
            fleet.tick()
        ticks = fleet.run(max_ticks=max_ticks)
        return {
            'views': [canonical(v) for v in fleet.views()],
            'ticks': ticks,
            'quarantined': sum(len(getattr(ds, 'quarantined', ()) or
                                   ()) for ds in doc_sets),
            'diverged': sum(len(getattr(ds, 'diverged', ()) or ())
                            for ds in doc_sets),
        }
    finally:
        fleet.close()
