"""ChaosTransport: a seeded adversarial message fabric for sync fleets.

The convergence claim of the whole system — replicas that exchange
messages end up byte-identical — is only worth something if it holds
when the transport misbehaves. This module is the harness that proves
it: a deterministic (seeded) in-process network between N peers that
drops, duplicates, delays/reorders, corrupts and partitions envelopes
on schedule, plus a fleet driver that wires
:class:`~.resilient.ResilientConnection` endpoints over it, ticks
logical time, and checks byte-identical convergence of every peer's
materialized state against a clean run.

Used by ``tests/test_chaos.py`` (the chaos convergence suite, pinned
seeds in CI) and ``bench.py``'s ``bench_degraded_link`` (the config-5
10k-doc fleet under 5%/20% loss).

Everything is logical-time and seeded — a failing schedule replays
exactly from its seed, which is what makes transport bugs debuggable.
"""

import copy
import json
import random
from collections import Counter

from .resilient import ResilientConnection


def doc_view(doc):
    """Plain-JSON materialization of one document (frontend docs and
    GeneralDocHandles alike) — the byte-identity comparand."""
    if hasattr(doc, 'materialize'):
        return doc.materialize()

    def conv(obj):
        name = type(obj).__name__
        if name == 'Text':
            return ''.join(str(c) for c in obj)
        if name == 'AmList':
            return [conv(v) for v in obj]
        if hasattr(obj, '_conflicts') or hasattr(obj, 'items'):
            return {k: conv(v) for k, v in obj.items()}
        return obj
    return conv(doc)


def doc_set_view(doc_set):
    """``{doc_id: plain tree}`` for a whole doc set (uses the batched
    read path when the doc set has one)."""
    if hasattr(doc_set, 'materialize_all'):
        return dict(doc_set.materialize_all())
    return {doc_id: doc_view(doc_set.get_doc(doc_id))
            for doc_id in doc_set.doc_ids}


def canonical(view):
    """Canonical byte encoding of a view — equality here IS
    byte-identical convergence."""
    return json.dumps(view, sort_keys=True, default=str)


def assert_digest_parity(doc_set):
    """Assert the incremental per-doc state digests equal an O(doc)
    recompute over the retained log, for every doc of a general-store
    doc set — the maintenance-correctness oracle the chaos schedules
    run after converging (no-op for doc sets without digests, or for
    snapshot-truncated stores whose history cannot be recomputed).
    COMPACTED stores stay checkable: the recompute starts from the
    digest recorded at each doc's horizon and folds only the retained
    tail, so the oracle survives the bodies being folded away."""
    store = getattr(doc_set, 'store', None)
    if store is None or not hasattr(store, 'digests_all'):
        return
    if store.log_truncated or not store._digest_valid:
        return
    digs = store.digests_all()
    for doc_id in doc_set.doc_ids:
        idx = doc_set.id_of[doc_id]
        got = int(digs[idx])
        want = store.digest_recompute(idx)
        assert got == want, (
            f'digest drift on {doc_id!r}: incremental {got:#x} != '
            f'recomputed {want:#x}')


class ChaosFleet:
    """N peers over a full-mesh adversarial fabric.

    ``doc_sets`` is a list of DocSet-like objects (one per peer); each
    directed link gets a :class:`ResilientConnection` endpoint. Per-tick
    scheduling: deliver every envelope whose delay expired, advance
    every endpoint's logical clock (retransmits + heartbeats), then
    flush batching endpoints. Fault injection happens at SEND time from
    one seeded RNG, so a schedule is a pure function of the seed.

    Fault knobs: ``drop``/``dup``/``corrupt`` are per-envelope
    probabilities; ``delay`` is the max extra ticks of random delivery
    delay (0 = in-order); :meth:`partition`/:meth:`heal` sever and
    restore node pairs (severed links drop everything, like a dead
    cable, not like a polite shutdown).
    """

    def __init__(self, doc_sets, seed=0, drop=0.0, dup=0.0, delay=0,
                 corrupt=0.0, batching=True, wire=False,
                 heartbeat_every=8, conn_kwargs=None, admission=None,
                 wire_version=None):
        self.doc_sets = list(doc_sets)
        self.rng = random.Random(seed)
        self.drop = drop
        self.dup = dup
        self.delay = delay
        self.corrupt = corrupt
        self.batching = batching
        self.wire = wire                 # columnar wire data path
        self.now = 0
        self._order = 0
        self.stats = Counter()
        self.queues = {}                 # (frm, to) -> [[due, order, env]]
        self.conns = {}                  # (owner, peer) -> endpoint
        self.partitioned = set()         # frozenset({a, b})
        self._conn_kwargs = dict(conn_kwargs or {})
        self._conn_kwargs.setdefault('heartbeat_every', heartbeat_every)
        if wire:
            self._conn_kwargs['wire'] = True
        # per-node wire-format version: an int pins every node, a list
        # pins per node (None entries = the build default) — the
        # mixed-version interop schedules run v1/v2/v3 peers in ONE
        # fleet and must still converge byte-identically (a pair
        # speaks min(sides), so one pinned node downgrades its links)
        if wire_version is None or isinstance(wire_version, int):
            self.node_wire_version = [wire_version] * len(self.doc_sets)
        else:
            self.node_wire_version = list(wire_version)
        # node-wide admission: ONE AdmissionControl shared by all of a
        # node's endpoints (the fleet-wide valve; the per-link valve
        # rides conn_kwargs['admission']). `admission` is kwargs for
        # every node, or a per-node list (None entries = unmetered)
        from .resilient import AdmissionControl
        n_nodes = len(self.doc_sets)
        if admission is None:
            self.node_admission = [None] * n_nodes
        elif isinstance(admission, dict):
            self.node_admission = [AdmissionControl(**admission)
                                   for _ in range(n_nodes)]
        else:
            self.node_admission = [
                cfg if cfg is None or
                isinstance(cfg, AdmissionControl)
                else AdmissionControl(**cfg) for cfg in admission]
        nodes = range(len(self.doc_sets))
        for a in nodes:
            for b in nodes:
                if a != b:
                    self.queues[(a, b)] = []
        for a in nodes:
            for b in nodes:
                if a != b:
                    self._make_conn(a, b)
        for conn in self.conns.values():
            conn.open()

    def _make_conn(self, owner, peer):
        # every endpoint is peer-scoped (peer_id=node<N>): its counters
        # land process-wide AND under a per-LINK scope, and a doc set
        # with a connection registry reports it per-connection in
        # fleet_status() — the chaos suite exercises the same operator
        # surface a real deployment reads. The scope carries the owner
        # node too (node/node<owner>/peer/node<peer>/): every fleet
        # node shares this one process's registry, so two links
        # targeting the same node (0->2 and 1->2) must not merge into
        # one peer/node2/ slice the way they never would across real
        # hosts
        from ..utils.metrics import metrics
        kwargs = dict(self._conn_kwargs)
        if self.wire and self.node_wire_version[owner] is not None:
            kwargs['wire_version'] = self.node_wire_version[owner]
        conn = ResilientConnection(
            self.doc_sets[owner], self._sender(owner, peer),
            batching=self.batching,
            shared_admission=self.node_admission[owner],
            seed=self.rng.randrange(1 << 30),
            peer_id=f'node{peer}',
            scope=metrics.scoped(node=f'node{owner}',
                                 peer=f'node{peer}'),
            **kwargs)
        self.conns[(owner, peer)] = conn
        return conn

    # -- the adversarial link ------------------------------------------------

    def _sender(self, frm, to):
        def send(env):
            self.stats['sent'] += 1
            if frozenset((frm, to)) in self.partitioned:
                self.stats['partition_dropped'] += 1
                return
            copies = 1
            if self.drop and self.rng.random() < self.drop:
                self.stats['dropped'] += 1
                copies = 0
            elif self.dup and self.rng.random() < self.dup:
                self.stats['duplicated'] += 1
                copies = 2
            for _ in range(copies):
                e = env
                if self.corrupt and self.rng.random() < self.corrupt:
                    self.stats['corrupted'] += 1
                    e = self._corrupt_env(env)
                due = self.now + 1 + (self.rng.randrange(self.delay + 1)
                                      if self.delay else 0)
                self._order += 1
                self.queues[(frm, to)].append([due, self._order, e])
        return send

    def _corrupt_env(self, env):
        """One seeded mutation: flipped checksum, bogus version, mangled
        seq/kind, a field torn out of the payload, or a bit flipped in
        a wire blob — every shape the receiver must survive (and count)
        without crashing. Blob corruption targets the CRC32-over-bytes
        path: the flipped byte must be caught BEFORE the codec parses,
        never quarantine a doc."""
        env = copy.deepcopy(env)
        mode = self.rng.randrange(6)
        if mode == 0:
            env['sum'] = env.get('sum', 0) ^ 0x5A5A5A5A
        elif mode == 1:
            env['v'] = 99
        elif mode == 2:
            env['seq'] = 'corrupt'
        elif mode == 3:
            env['kind'] = 'garbage'
        elif mode == 4:
            payload = env.get('payload')
            # flip one bit in a binary payload section — blob, the v2
            # literal tab or the v3 session-definition tab, all under
            # the CRC32-over-bytes checksum (a flipped v3 tab must be
            # caught by the envelope sum and repaired by retransmit,
            # never poison the receiver's session table)
            field = self.rng.choice(('blob', 'tab'))
            part = payload.get(field) if isinstance(payload, dict) \
                else None
            if not isinstance(part, (bytes, bytearray)) or not part:
                field = 'blob'
                part = payload.get(field) if isinstance(payload, dict) \
                    else None
            if isinstance(part, (bytes, bytearray)) and len(part):
                i = self.rng.randrange(len(part))
                payload[field] = part[:i] + \
                    bytes([part[i] ^ (1 << self.rng.randrange(8))]) + \
                    part[i + 1:]
            else:
                env['sum'] = -1
        else:
            body = env.get('payload') if isinstance(
                env.get('payload'), dict) else env.get('clocks')
            if isinstance(body, dict) and body:
                del body[self.rng.choice(sorted(body, key=str))]
            else:
                env['sum'] = -1
        return env

    # -- partitions ----------------------------------------------------------

    def partition(self, a, b):
        """Sever the (bidirectional) link between peers a and b; queued
        traffic on the link is lost too (a dead cable, not a drain)."""
        self.partitioned.add(frozenset((a, b)))
        self.queues[(a, b)].clear()
        self.queues[(b, a)].clear()

    def heal(self, a, b):
        self.partitioned.discard(frozenset((a, b)))

    # -- time ----------------------------------------------------------------

    def tick(self):
        """One network quantum: deliver due envelopes (per-link, in due
        order), advance every endpoint's clock, flush batching
        endpoints."""
        self.now += 1
        for (frm, to), q in self.queues.items():
            if not q:
                continue
            due = [m for m in q if m[0] <= self.now]
            if not due:
                continue
            q[:] = [m for m in q if m[0] > self.now]
            for _, _, env in sorted(due):
                self.stats['delivered'] += 1
                self.conns[(to, frm)].receive_msg(env)
        for conn in self.conns.values():
            conn.tick()
        for ctrl in self.node_admission:
            if ctrl is not None:
                ctrl.tick()            # the shared valve refills ONCE
                #                        per quantum, not once per link
        if self.batching or self.wire:
            for conn in self.conns.values():
                conn.flush()
        # serving doc sets advance their residency clock (last-touch
        # aging, memory-budget enforcement, quarantine parking)
        for ds in self.doc_sets:
            t = getattr(ds, 'tick', None)
            if t is not None:
                t()

    def pending(self):
        """Traffic still in flight: queued envelopes or unacked sends
        awaiting retransmission."""
        return any(self.queues.values()) or \
            any(c.in_flight for c in self.conns.values())

    # -- convergence ---------------------------------------------------------

    def views(self):
        return [doc_set_view(ds) for ds in self.doc_sets]

    def converged(self):
        views = [canonical(v) for v in self.views()]
        return all(v == views[0] for v in views[1:])

    def run(self, max_ticks=2000, min_ticks=0):
        """Tick until every peer's materialization is byte-identical
        and the fabric is quiet; returns the tick count. Raises if the
        fleet has not converged by ``max_ticks`` (a chaos schedule that
        defeats the resilience layer is a test failure, not a hang)."""
        while self.now < max_ticks:
            self.tick()
            if self.now >= min_ticks and not self.pending() \
                    and self.converged():
                return self.now
        raise RuntimeError(
            f'fleet failed to converge within {max_ticks} ticks '
            f'(stats: {dict(self.stats)})')

    def close(self):
        """Detach every endpoint from its doc set (so a doc set can be
        reused across fleets, e.g. by the bench's loss-rate sweep)."""
        for conn in self.conns.values():
            conn.close()

    # -- fault injection beyond the transport --------------------------------

    def inject_silent_divergence(self, node, doc_id, changes):
        """Mutate ONE replica's store out-of-band: apply ``changes``
        directly to ``node``'s doc set, bypassing the fabric entirely
        (no envelope, no checksum — exactly the logic-level corruption
        the transport layer cannot see). The injection is SILENT end
        to end: the node's endpoints never see the apply (their
        ``doc_changed`` handlers are detached around it) and are then
        told the peer already covers the new clock — so injecting an
        "evil twin" of a change another replica holds (same ``(actor,
        seq)``, other content) leaves every clock EQUAL, the normal
        protocol ships nothing, and the replicas stay silently
        diverged forever. Only the heartbeat digest audit can catch
        it."""
        from .connection import clock_union
        ds = self.doc_sets[node]
        owned = [c for (o, _p), c in self.conns.items() if o == node]
        inners = [getattr(c, '_conn', c) for c in owned]
        for inner in inners:
            ds.unregister_handler(inner.doc_changed)
        try:
            out = ds.apply_changes(doc_id, changes)
        finally:
            for inner in inners:
                ds.register_handler(inner.doc_changed)
        clock = ds.clock_of_id(doc_id) if \
            hasattr(ds, 'clock_of_id') else {}
        for conn, inner in zip(owned, inners):
            clock_union(inner._their_clock, doc_id, clock)
            clock_union(inner._our_clock, doc_id, clock)
            pend = getattr(inner, '_pending_send', None)
            if pend is not None:
                pend.pop(doc_id, None)
            acked = getattr(conn, '_peer_acked', None)
            if acked is not None:
                clock_union(acked, doc_id, clock)
        return out

    # -- crash/restart -------------------------------------------------------

    def reconnect(self, node, doc_set=None):
        """Crash-restart peer ``node``: all its in-flight traffic is
        lost, its doc set is replaced (e.g. recovered from snapshot +
        journal), and every adjacent link re-establishes with FRESH
        envelope sessions on both ends — exactly what a process restart
        does to a connection."""
        if doc_set is not None:
            self.doc_sets[node] = doc_set
        for (owner, peer), conn in list(self.conns.items()):
            if node not in (owner, peer):
                continue
            try:
                conn.close()
            except Exception:
                pass                     # the crashed side's handler is gone
            self.queues[(owner, peer)].clear()
            self._make_conn(owner, peer).open()
