"""Connection: per-peer replication protocol, multiplexing many documents.

Parity with `/root/reference/src/connection.js`. The protocol is
network-agnostic: construct with a DocSet and a ``send_msg`` callback;
call :meth:`receive_msg` when the network delivers a message. Messages are
``{docId, clock}`` (advertisement/ack/request) or ``{docId, clock, changes}``
(data). ``their_clock`` tracks what we believe the peer has; ``our_clock``
what we've advertised.

On a TPU pod the same logical protocol runs between hosts over DCN, while
replicas sharing a mesh sync by collective instead of message
(:mod:`automerge_tpu.parallel`).
"""

import time

from .. import frontend as Frontend
from ..common import less_or_equal
from ..utils.metrics import metrics
from .doc_set import backend_of as _backend_of


# wire-v3 session warm-up from 'state' bootstraps (ISSUE 20): a peer
# bootstrapping from a state snapshot pre-seeds its session string
# table with the snapshot's actor/key literals, so its first warm
# flush ships bare refs instead of redefining strings the serving
# peer demonstrably holds. Module-level so the bench can A/B the
# definition-byte savings; correctness never depends on it (a missed
# warm-up just means normal define-on-first-use).
SESSION_WARMUP = True


class MessageRejected(ValueError):
    """An incoming sync message failed envelope/schema validation.

    Raised by :meth:`Connection.receive_msg` BEFORE any state mutation
    — a rejected message never pollutes ``_their_clock`` or reaches an
    apply path. Counted under ``sync_msgs_rejected``; the message names
    the offending field so a hostile or buggy peer is diagnosable from
    the log line alone."""


def _reject(reason):
    metrics.bump('sync_msgs_rejected')
    raise MessageRejected(reason)


def validate_msg(msg):
    """Validate the logical sync-message schema (advertisement, ack,
    request, data or snapshot): ``docId`` a string, ``clock`` a dict of
    ``str -> non-negative int`` seqs, ``changes`` (when present) a list
    of change dicts each carrying ``actor``/``seq``/``deps``/``ops``
    with sane types. Raises :class:`MessageRejected` (and bumps
    ``sync_msgs_rejected``) on the first violation; returns ``msg``."""
    if not isinstance(msg, dict):
        _reject(f'message is {type(msg).__name__}, not a dict')
    doc_id = msg.get('docId')
    if not isinstance(doc_id, str):
        _reject(f'docId is missing or not a string: {doc_id!r}')
    clock = msg.get('clock')
    if clock is not None:
        if not isinstance(clock, dict):
            _reject(f'clock is not a dict: {type(clock).__name__}')
        for actor, seq in clock.items():
            if not isinstance(actor, str):
                _reject(f'clock actor is not a string: {actor!r}')
            if not isinstance(seq, int) or isinstance(seq, bool) \
                    or seq < 0:
                _reject(f'clock seq for {actor!r} is not a '
                        f'non-negative int: {seq!r}')
    changes = msg.get('changes')
    if changes is not None:
        if not isinstance(changes, (list, tuple)):
            _reject(f'changes is not a list: '
                    f'{type(changes).__name__}')
        for change in changes:
            if not isinstance(change, dict):
                _reject(f'change is not a dict: '
                        f'{type(change).__name__}')
            if not isinstance(change.get('actor'), str):
                _reject(f'change actor is missing or not a string: '
                        f'{change.get("actor")!r}')
            seq = change.get('seq')
            if not isinstance(seq, int) or isinstance(seq, bool) \
                    or seq <= 0:
                _reject(f'change seq is not a positive int: {seq!r}')
            deps = change.get('deps')
            if not isinstance(deps, dict):
                _reject(f'change deps is missing or not a dict: '
                        f'{deps!r}')
            for actor, dseq in deps.items():
                if not isinstance(actor, str) or \
                        not isinstance(dseq, int) or \
                        isinstance(dseq, bool) or dseq < 0:
                    _reject(f'change dep {actor!r}: {dseq!r} is not '
                            f'str -> non-negative int')
            ops = change.get('ops')
            if not isinstance(ops, (list, tuple)) or \
                    not all(isinstance(op, dict) for op in ops):
                _reject('change ops is not a list of dicts')
    snapshot = msg.get('snapshot')
    if snapshot is not None and not isinstance(snapshot, (str, bytes)):
        _reject(f'snapshot payload is not str/bytes: '
                f'{type(snapshot).__name__}')
    state = msg.get('state')
    if state is not None and \
            not isinstance(state, (bytes, bytearray)):
        _reject(f'state payload is not bytes: '
                f'{type(state).__name__}')
    return msg


def validate_state_msg(msg):
    """Validate the multi-doc STATE bootstrap message (tiered doc
    storage): ``state`` the format version (1); ``docs`` a non-empty
    list of doc-id strings; ``clocks`` the aligned HORIZON clocks;
    ``lens`` the aligned per-doc payload byte lengths (one state
    snapshot per doc); ``blob`` their concatenation. Payload CONTENT
    is covered by the envelope CRC and its own checksummed container;
    a corrupt payload quarantines only its doc at absorb time."""
    if not isinstance(msg, dict):
        _reject(f'state message is {type(msg).__name__}, not a dict')
    if msg.get('state') != 1 or isinstance(msg.get('state'), bool):
        _reject(f"state version is not 1: {msg.get('state')!r}")
    docs = msg.get('docs')
    if not isinstance(docs, (list, tuple)) or not docs:
        _reject(f'state docs is not a non-empty list: {docs!r}')
    for doc_id in docs:
        if not isinstance(doc_id, str):
            _reject(f'state doc id is not a string: {doc_id!r}')
    clocks = msg.get('clocks')
    if not isinstance(clocks, (list, tuple)) or \
            len(clocks) != len(docs):
        _reject('state clocks is not a list aligned with docs')
    for clock in clocks:
        if not isinstance(clock, dict):
            _reject(f'state clock is not a dict: '
                    f'{type(clock).__name__}')
        for actor, seq in clock.items():
            if not isinstance(actor, str) or not isinstance(seq, int) \
                    or isinstance(seq, bool) or seq < 0:
                _reject(f'state clock entry {actor!r}: {seq!r} is '
                        f'not str -> non-negative int')
    lens = msg.get('lens')
    if not isinstance(lens, (list, tuple)) or len(lens) != len(docs):
        _reject('state lens is not a list aligned with docs')
    total = 0
    for ln in lens:
        if not isinstance(ln, int) or isinstance(ln, bool) or ln <= 0:
            _reject(f'state payload length is not a positive int: '
                    f'{ln!r}')
        total += ln
    blob = msg.get('blob')
    if not isinstance(blob, (bytes, bytearray)):
        _reject(f'state blob is not bytes: {type(blob).__name__}')
    if len(blob) != total:
        _reject(f'state blob carries {len(blob)} bytes, lens claim '
                f'{total}')
    return msg


def validate_wire_msg(msg):
    """Validate the multi-doc WIRE data-message schema (the columnar
    counterpart of a per-doc ``{docId, clock, changes}`` dict message):
    ``wire`` the format version (1 = JSON-blob spans, 2 = columnar
    binary spans + a shared ``tab`` literal table, 3 = RLE columnar
    spans referencing a SESSION string table — ``tab`` carries the
    message's new table definitions and ``sid`` the sender's table
    epoch); ``docs`` a
    non-empty list of doc-id strings; ``clocks`` an aligned list of
    ``str -> non-negative int`` clock dicts; ``counts`` an aligned
    list of per-doc change counts; ``lens`` the per-change byte
    lengths (``sum(counts)`` of them); ``blob`` the concatenated change
    encodings (``sum(lens)`` bytes); ``maxv`` (optional) the sender's
    highest spoken format version — the negotiation stamp. Change
    CONTENT is not inspected here — blob and tab ride under a CRC32
    envelope checksum (:func:`~automerge_tpu.sync.resilient.
    payload_checksum`) and parse at flush, where a poisoned document
    lands in quarantine. Raises :class:`MessageRejected` on the first
    violation; returns ``msg``."""
    if not isinstance(msg, dict):
        _reject(f'wire message is {type(msg).__name__}, not a dict')
    version = msg.get('wire')
    if version not in (1, 2, 3) or isinstance(version, bool):
        _reject(f'wire version is not 1, 2 or 3: {version!r}')
    maxv = msg.get('maxv')
    if maxv is not None and (not isinstance(maxv, int)
                             or isinstance(maxv, bool) or maxv < 1):
        _reject(f'wire maxv is not a positive int: {maxv!r}')
    if version >= 2:
        tab = msg.get('tab')
        if not isinstance(tab, (bytes, bytearray)):
            _reject(f'wire v{version} tab is not bytes: '
                    f'{type(tab).__name__}')
    if version >= 3:
        sid = msg.get('sid')
        if not isinstance(sid, int) or isinstance(sid, bool) or sid < 0:
            _reject(f'wire v3 sid is not a non-negative int: {sid!r}')
    docs = msg.get('docs')
    if not isinstance(docs, (list, tuple)) or not docs:
        _reject(f'wire docs is not a non-empty list: {docs!r}')
    for doc_id in docs:
        if not isinstance(doc_id, str):
            _reject(f'wire doc id is not a string: {doc_id!r}')
    clocks = msg.get('clocks')
    if not isinstance(clocks, (list, tuple)) or \
            len(clocks) != len(docs):
        _reject(f'wire clocks is not a list aligned with docs: '
                f'{type(clocks).__name__}')
    for clock in clocks:
        if not isinstance(clock, dict):
            _reject(f'wire clock is not a dict: '
                    f'{type(clock).__name__}')
        for actor, seq in clock.items():
            if not isinstance(actor, str) or not isinstance(seq, int) \
                    or isinstance(seq, bool) or seq < 0:
                _reject(f'wire clock entry {actor!r}: {seq!r} is not '
                        f'str -> non-negative int')
    counts = msg.get('counts')
    if not isinstance(counts, (list, tuple)) or \
            len(counts) != len(docs):
        _reject(f'wire counts is not a list aligned with docs: '
                f'{type(counts).__name__}')
    for count in counts:
        if not isinstance(count, int) or isinstance(count, bool) \
                or count < 0:
            _reject(f'wire change count is not a non-negative int: '
                    f'{count!r}')
    lens = msg.get('lens')
    if not isinstance(lens, (list, tuple)) or \
            len(lens) != sum(counts):
        _reject(f'wire lens does not carry sum(counts)='
                f'{sum(counts)} entries: {lens!r}')
    total = 0
    for ln in lens:
        # zero-length spans can never hold a change encoding — reject
        # them here so a bogus message cannot quarantine a healthy doc
        # at flush (the dict path rejects malformed changes pre-state
        # too)
        if not isinstance(ln, int) or isinstance(ln, bool) or ln <= 0:
            _reject(f'wire change length is not a positive int: '
                    f'{ln!r}')
        total += ln
    blob = msg.get('blob')
    if not isinstance(blob, (bytes, bytearray)):
        _reject(f'wire blob is not bytes: {type(blob).__name__}')
    if len(blob) != total:
        _reject(f'wire blob carries {len(blob)} bytes, lens claim '
                f'{total}')
    return msg


# highest state-bootstrap message version this build speaks (tiered
# doc storage): a peer advertises its own via `maxs` on every wire/
# state message, and 'state' payloads only ship to peers that did —
# un-advertised (old) peers fall back to the legacy snapshot path
STATE_VERSION = 1

# highest wire-blob format this build speaks: 3 = RLE columnar spans +
# session-scoped string tables (actor uuids / hot keys ship once per
# connection); 2 = columnar binary spans + per-message literal tables
# (JSON-free receive path); 1 = the PR 5 JSON-blob spans, kept for
# mixed-fleet interop. Lower versions stay pinnable via
# WireConnection(wire_version=...) and negotiation takes the min of
# both ends' maxv stamps.
WIRE_VERSION = 3

# the flow-control sizing unit for served encode-cache entries — the
# ONE sizing rule, shared with the cache-byte accounting in
# device/blocks.py so the two can never drift
from ..device.blocks import _wire_entry_bytes as _entry_bytes  # noqa: E402,E501


def clock_union(clock_map, doc_id, clock):
    """Merge `clock` into `clock_map[doc_id]`, taking per-actor maxima
    (connection.js:9-12). The reference rebuilds an immutable map; these
    maps are private to one Connection, so updating in place is
    observably identical and keeps a 10k-doc sync O(messages), not
    O(messages * docs)."""
    merged = clock_map.get(doc_id)
    if merged is None:
        merged = clock_map[doc_id] = {}
    for actor, seq in clock.items():
        if seq > merged.get(actor, 0):
            merged[actor] = seq
    return clock_map


class Connection:
    def __init__(self, doc_set, send_msg):
        self._doc_set = doc_set
        self._send_msg = send_msg
        self._their_clock = {}
        self._our_clock = {}
        # per-connection metrics routing: defaults to the process-wide
        # registry; ResilientConnection swaps in a peer-labeled scope
        # (metrics.scoped(peer=...)) so this connection's counters land
        # BOTH process-wide and under peer/<id>/ — the per-connection
        # surface fleet_status() reports
        self.metrics = metrics

    def open(self):
        for doc_id in self._doc_set.doc_ids:
            self.doc_changed(doc_id, self._doc_set.get_doc(doc_id))
        self._doc_set.register_handler(self.doc_changed)

    def close(self):
        self._doc_set.unregister_handler(self.doc_changed)

    def send_msg(self, doc_id, clock, changes=None):
        msg = {'docId': doc_id, 'clock': dict(clock)}
        self._our_clock = clock_union(self._our_clock, doc_id, clock)
        if changes is not None:
            msg['changes'] = changes
        self.metrics.bump('sync_msgs_sent')
        if changes is not None:
            self.metrics.bump('sync_changes_sent', len(changes))
        if self.metrics.active:
            self.metrics.emit('sync_send', doc_id=doc_id,
                              changes=len(changes) if changes else 0)
        # the span is open across the transport callback so a resilient
        # shell stamps this span's id into the envelope's trace field
        # (the cross-peer correlation parent of the receiver's apply)
        with self.metrics.trace_span('sync.send', doc_id=doc_id):
            self._send_msg(msg)

    def maybe_send_changes(self, doc_id):
        """(connection.js:58-73). Extension over the reference: when the
        peer is behind a snapshot-truncated log (get_missing_changes
        raises — the change bodies it needs were dropped by a packed
        resume), the full packed snapshot ships instead, and the normal
        protocol resumes from there."""
        doc = self._doc_set.get_doc(doc_id)
        state = Frontend.get_backend_state(doc)
        clock = state.clock

        if doc_id in self._their_clock:
            try:
                changes = _backend_of(doc).get_missing_changes(
                    state, self._their_clock[doc_id])
            except ValueError as err:
                self._send_snapshot(doc_id, clock, err)
                return
            if changes:
                self._their_clock = clock_union(self._their_clock, doc_id, clock)
                self.send_msg(doc_id, clock, changes)
                return

        if clock != self._our_clock.get(doc_id, {}):
            self.send_msg(doc_id, clock)

    def _send_snapshot(self, doc_id, clock, original_err):
        """Serve a too-far-behind peer the packed state itself: a
        compacted doc's per-doc STATE snapshot when the doc set holds
        one (tiered doc storage — the peer absorbs it and the normal
        protocol serves the tail), else the per-document packed
        snapshot of device-backend documents; for everything else the
        original (clear) error propagates."""
        from .. import snapshot as _snapshot
        serve = getattr(self._doc_set, 'serve_state_payload', None)
        if serve is not None:
            got = serve(doc_id)
            if got is not None:
                payload, h_clock = got
                # assume delivery up to the horizon (the resilient
                # shell rolls this back if the envelope dies); the
                # receiver's next advert pulls the tail
                clock_union(self._their_clock, doc_id, h_clock)
                clock_union(self._our_clock, doc_id, clock)
                self.metrics.bump('sync_msgs_sent')
                self.metrics.bump('sync_state_msgs_sent')
                if self.metrics.active:
                    self.metrics.emit('sync_send', doc_id=doc_id,
                                      changes=0, state=True)
                with self.metrics.trace_span('sync.send',
                                             doc_id=doc_id,
                                             state=True):
                    self._send_msg({'docId': doc_id,
                                    'clock': dict(clock),
                                    'state': payload})
                return
        doc = self._doc_set.get_doc(doc_id)
        try:
            payload = _snapshot.save_snapshot(doc)
        except TypeError:
            raise original_err
        clock_union(self._their_clock, doc_id, clock)
        clock_union(self._our_clock, doc_id, clock)
        self.metrics.bump('sync_snapshots_sent')
        self.metrics.bump('sync_msgs_sent')
        if self.metrics.active:
            self.metrics.emit('sync_send', doc_id=doc_id, changes=0,
                              snapshot=True)
        with self.metrics.trace_span('sync.send', doc_id=doc_id,
                                     snapshot=True):
            self._send_msg({'docId': doc_id, 'clock': dict(clock),
                            'snapshot': payload})

    def doc_changed(self, doc_id, doc):
        """DocSet handler (connection.js:76-89)."""
        state = Frontend.get_backend_state(doc)
        if state is None:
            raise TypeError('This object cannot be used for network sync. '
                            'Are you trying to sync a snapshot from the history?')
        clock = state.clock
        if not less_or_equal(self._our_clock.get(doc_id, {}), clock):
            raise ValueError('Cannot pass an old state object to a connection')
        self.maybe_send_changes(doc_id)

    def receive_msg(self, msg):
        """(connection.js:91-108). The envelope is validated BEFORE any
        state mutation: a malformed message raises
        :class:`MessageRejected` (counted under ``sync_msgs_rejected``)
        and leaves ``_their_clock`` untouched."""
        validate_msg(msg)
        self.metrics.bump('sync_msgs_received')
        if self.metrics.active:
            self.metrics.emit('sync_receive', doc_id=msg.get('docId'),
                              changes=len(msg.get('changes') or ()))
        if 'clock' in msg and msg['clock'] is not None:
            self._their_clock = clock_union(self._their_clock, msg['docId'], msg['clock'])
        if 'snapshot' in msg:
            return self._receive_snapshot(msg)
        if 'state' in msg and msg['state'] is not None:
            return self._receive_state(msg)
        if 'changes' in msg and msg['changes'] is not None:
            return self._doc_set.apply_changes(msg['docId'], msg['changes'])

        if self._doc_set.get_doc(msg['docId']) is not None:
            self.maybe_send_changes(msg['docId'])
        elif msg['docId'] not in self._our_clock:
            # The remote node has a document we don't: request it by
            # advertising an empty clock.
            self.send_msg(msg['docId'], {})

        return self._doc_set.get_doc(msg['docId'])

    def _receive_state(self, msg):
        """Absorb a served per-doc state snapshot (tiered doc
        storage), then advertise the doc's new clock so the sender
        ships the retained tail through the normal protocol."""
        doc_id = msg['docId']
        apply_state = getattr(self._doc_set, 'apply_state', None)
        if apply_state is None:
            _reject(f'state payload for {doc_id!r} but this doc set '
                    f'cannot absorb state snapshots')
        self.metrics.bump('sync_state_msgs_received')
        out = apply_state(doc_id, msg['state'])
        self.maybe_send_changes(doc_id)
        return out

    def _receive_snapshot(self, msg):
        """Resume from a served snapshot, then replay any LOCAL changes
        the snapshot does not cover (concurrent edits survive the
        resync; the peer gets them through the normal protocol)."""
        from .. import snapshot as _snapshot
        doc_id = msg['docId']
        self.metrics.bump('sync_snapshots_received')
        old_doc = self._doc_set.get_doc(doc_id)
        actor_id = Frontend.get_actor_id(old_doc) if old_doc is not None \
            else None
        new_doc = _snapshot.load_snapshot(msg['snapshot'],
                                          actor_id=actor_id)
        if old_doc is not None:
            old_state = Frontend.get_backend_state(old_doc)
            new_state = Frontend.get_backend_state(new_doc)
            try:
                local_only = _backend_of(old_doc).get_missing_changes(
                    old_state, new_state.clock)
            except ValueError:
                raise ValueError(
                    'both replicas hold snapshot-truncated histories '
                    'that diverged before their resume points; they '
                    'cannot merge losslessly — resync one side from a '
                    'full change log or a common snapshot') from None
            if local_only:
                from ..device import backend as DeviceBackend
                new_state, patch = DeviceBackend.apply_changes(
                    new_state, local_only)
                patch['state'] = new_state
                new_doc = Frontend.apply_patch(new_doc, patch)
        self._doc_set.set_doc(doc_id, new_doc)
        return new_doc

    # camelCase aliases (reference API parity)
    sendMsg = send_msg
    maybeSendChanges = maybe_send_changes
    docChanged = doc_changed
    receiveMsg = receive_msg


class BatchingConnection(Connection):
    """A Connection that accumulates incoming data messages and applies
    them in ONE batched call per network tick.

    The reference applies each data message's changes per document as it
    arrives (src/connection.js:95-97); on this framework's batch engines
    that wastes the whole point — a tick's worth of messages across MANY
    documents is exactly one device dispatch. ``receive_msg`` buffers
    data messages (clock bookkeeping still happens immediately, in
    arrival order); :meth:`flush` routes the buffered changes through
    ``doc_set.apply_changes_batch`` (one fused device call on a
    :class:`~automerge_tpu.sync.device_doc_set.DeviceDocSet`) and then
    runs the deferred per-doc protocol follow-ups. Call ``flush()`` at
    the end of each delivery tick; message traffic is identical to the
    eager Connection.
    """

    def __init__(self, doc_set, send_msg):
        super().__init__(doc_set, send_msg)
        self._incoming = []
        # per-doc fault isolation record for doc sets WITHOUT their own
        # quarantine registry (GeneralDocSet carries its own): doc_id
        # -> {'error': repr, 'changes': [...]}. A later successful
        # delivery clears the entry.
        self.quarantined = {}

    def receive_msg(self, msg):
        if isinstance(msg, dict) and 'changes' in msg \
                and msg['changes'] is not None:
            validate_msg(msg)
            self.metrics.bump('sync_msgs_received')
            if 'clock' in msg and msg['clock'] is not None:
                self._their_clock = clock_union(
                    self._their_clock, msg['docId'], msg['clock'])
            self._incoming.append(msg)
            return None                      # applied on flush()
        return super().receive_msg(msg)

    def flush(self):
        """Apply the tick's buffered traffic in one batched call;
        returns {doc_id: doc} for the docs that changed. The timing/
        tracing template for every batched flavor: subclasses override
        :meth:`_flush_pending` (is there work?) and :meth:`_flush_work`
        (do it), never this wrapper, so the ``sync.flush`` span and the
        ``sync_flush_ms`` series stay consistent across protocols.

        Faults are isolated PER DOCUMENT: a doc whose changes raise is
        rolled back (the engines' store-intact-on-error contract) and
        quarantined with its exception — every other doc in the tick
        applies normally. Quarantine lands on the doc set's own
        registry when it has one (``GeneralDocSet.quarantined``), else
        on :attr:`quarantined` here; quarantined docs are retriable (a
        corrected later delivery clears the entry)."""
        if not self._flush_pending():
            # no-op tick: don't let empty flushes pollute the
            # sync_flush_ms quantiles or fill the flight recorder
            return {}
        t0 = time.perf_counter()
        with self.metrics.trace_span('sync.flush'):
            out = self._flush_work()
        self.metrics.observe('sync_flush_ms',
                             (time.perf_counter() - t0) * 1e3)
        return out

    def _flush_pending(self):
        return bool(self._incoming)

    def _flush_work(self):
        return self._flush_data()

    def _flush_data(self):
        """The buffered-dict-message half of :meth:`flush`."""
        if not self._incoming:
            return {}
        changes_by_doc = {}
        for msg in self._incoming:
            changes_by_doc.setdefault(msg['docId'], []) \
                .extend(msg['changes'])
        self._incoming = []
        self.metrics.bump('sync_changes_received',
                          sum(len(c) for c in changes_by_doc.values()))
        apply_batch = getattr(self._doc_set, 'apply_changes_batch', None)
        if apply_batch is not None:
            if hasattr(self._doc_set, 'quarantined'):
                # the doc set isolates internally (one fused apply on
                # the happy path, per-doc fallback on a fault)
                return apply_batch(changes_by_doc, isolate=True)
            try:
                return apply_batch(changes_by_doc)
            except Exception:
                # the batched apply rolled back; isolate per doc below
                pass
        out = {}
        for doc_id, changes in changes_by_doc.items():
            try:
                out[doc_id] = self._doc_set.apply_changes(doc_id,
                                                          changes)
                # clear quarantine only once the STORED changes are
                # accounted for: entries the doc's clock now covers
                # were superseded by a corrected redelivery; the rest
                # re-apply (transient fault) or keep the entry alive
                held = self.quarantined.get(doc_id)
                if held is not None:
                    state = Frontend.get_backend_state(out[doc_id])
                    clock = state.clock if state is not None else {}
                    pending = [c for c in held['changes']
                               if not isinstance(c, dict) or
                               c.get('seq', 0) >
                               clock.get(c.get('actor'), 0)]
                    try:
                        if pending:
                            out[doc_id] = self._doc_set.apply_changes(
                                doc_id, pending)
                        del self.quarantined[doc_id]
                    except Exception as err:
                        held['error'] = repr(err)
            except Exception as err:
                self.quarantined[doc_id] = {'error': repr(err),
                                            'changes': list(changes)}
                self.metrics.bump('sync_docs_quarantined')
                if self.metrics.active:
                    self.metrics.emit('doc_quarantined', doc_id=doc_id,
                                      error=repr(err))
        return out

    receiveMsg = receive_msg


class WireConnection(BatchingConnection):
    """The columnar binary delta path: a BatchingConnection whose DATA
    messages are multi-doc wire blobs instead of per-doc dict lists.

    Sender side, a network tick's ``doc_changed`` follow-ups coalesce
    into ONE multi-doc message per peer (``{'wire': 1, 'docs': [...],
    'clocks': [...], 'counts': [...], 'lens': [...], 'blob': bytes}``):
    each doc's missing changes come from the store's per-change encode
    cache (:meth:`~automerge_tpu.device.blocks.BlockStore.
    get_missing_changes_wire`) as pre-encoded byte spans — with N peers
    a change encodes once and fans out N times, and a zero-change span
    is a bundled clock advertisement. Receive side, the tick's buffered
    blobs merge per doc and ride the native codec -> stager path in one
    fused apply (:meth:`GeneralDocSet.apply_wire
    <automerge_tpu.sync.general_doc_set.GeneralDocSet.apply_wire>`);
    a fused-apply fault falls back to the dict batch path, which
    isolates and quarantines per document.

    Clock bookkeeping and message SEMANTICS are protocol-identical to
    the dict path (same advertisements, same requests, same snapshot
    fallback for truncated logs — those stay dict messages); only data
    transport is columnar. Both endpoints must speak it: pair
    WireConnection with WireConnection, and keep
    Connection/BatchingConnection for dict-path interop. Requires a
    wire-capable doc set (GeneralDocSet).
    """

    def __init__(self, doc_set, send_msg, max_msg_bytes=None,
                 wire_version=WIRE_VERSION):
        super().__init__(doc_set, send_msg)
        store = getattr(doc_set, 'store', None)
        if not hasattr(doc_set, 'apply_wire') or store is None or \
                not hasattr(store, 'get_missing_changes_wire'):
            raise TypeError(
                'WireConnection requires a wire-capable doc set '
                '(GeneralDocSet: apply_wire + a store serving '
                'get_missing_changes_wire); use Connection or '
                'BatchingConnection for other doc sets')
        if wire_version not in (1, 2, 3):
            raise ValueError(
                f'wire_version must be 1, 2 or 3, got {wire_version!r}')
        # per-peer flow control: soft cap on one outgoing message's
        # blob bytes — data spans past the cap carry to the next tick
        # (re-served from the encode cache, so deferral costs no
        # re-encode). None = unbounded.
        self.max_msg_bytes = max_msg_bytes
        # wire-format version negotiation (the PR 7/8 v-stamp pattern:
        # the stamp rides the messages themselves, no extra handshake).
        # `wire_version` is the highest format THIS side speaks; every
        # outgoing wire message from a v2-capable sender carries
        # `maxv`, and data ships in min(ours, the peer's advertised
        # maxv). A v1-only peer never advertises, so it pins the
        # sender to v1 framing; and because data only ever flows to a
        # peer we have HEARD from (their_clock gates the serve), the
        # first data message always follows at least one incoming
        # message — a pure-v2 pair negotiates up before any data
        # ships, costing zero v1 round-trips.
        self.wire_version = wire_version
        self._peer_wire_version = 1
        # state-bootstrap capability (tiered doc storage): `maxs`
        # rides every outgoing wire/state message exactly like `maxv`;
        # a peer that never advertises it (an old build) gets the
        # legacy snapshot fallback instead of 'state' messages
        self._peer_state_version = 0
        self._pending_send = {}       # doc_id -> None (insertion order)
        self._incoming_wire = []
        self._incoming_state = []
        # wire v3 session string tables. Sender: ONE SessionStringTable
        # (lazily created on the first v3 data send — its fresh module-
        # unique `sid` is the session epoch every outgoing v3 message
        # stamps). Receiver: ref -> literal maps keyed by the PEER's
        # sid; at most two epochs stay live (the current one plus the
        # one a reconnecting peer just abandoned), older epochs drop.
        self._tx_table = None
        self._rx_tables = {}
        # wire-v3 warm-up bookkeeping: `_warm_served` is the literal
        # list this side shipped inside a 'state' bootstrap (kept to
        # seed OUR rx map for the peer's warmed session when its first
        # v3 message arrives carrying the 'warm' stamp); fixed once
        # per connection so both ends agree on which snapshot set
        # defines the warm refs. `_warm_announce` stamps outgoing v3
        # messages until one is acked (the peer's seed is then proven
        # applied).
        self._warm_served = None
        self._warm_announce = False
        # delta-clock baseline (v3 warm-link advert compression): per
        # doc, the highest clock PROVEN shared with the peer — folded
        # only from payload clocks the peer explicitly acked (ack =>
        # delivered => the receiver folded that very clock into its
        # view of us, so eliding those entries from a later shipped
        # clock loses it nothing; plain union reconstructs exactly).
        # Outgoing v3 clocks ship only the entries above this
        # baseline; a fresh session (empty baseline) ships full
        # clocks — the session-reset fallback.
        self._adv_acked = {}

    def open(self):
        """Advertise every doc WITHOUT materializing handles: the wire
        ``doc_changed`` only needs the doc id, and a serving doc set
        must not fault its whole evicted tail back in just because a
        connection opened."""
        for doc_id in self._doc_set.doc_ids:
            self._pending_send[doc_id] = None
        self._doc_set.register_handler(self.doc_changed)

    def maybe_send_changes(self, doc_id):
        """Deferred: data sends coalesce into the tick's single
        multi-doc wire message (:meth:`flush`); the data-vs-
        advertisement decision happens there against the then-current
        clocks."""
        self._pending_send[doc_id] = None

    maybeSendChanges = maybe_send_changes

    def doc_changed(self, doc_id, doc):
        """DocSet handler — straight to the pending set. The base
        class's stale-state guard protects against re-registering an
        OLD frontend document object; wire doc sets hand out live
        handles whose state is the store itself, so the per-doc clock
        fetch it costs is pure overhead on a 10k-doc tick."""
        self._pending_send[doc_id] = None

    docChanged = doc_changed

    def receive_msg(self, msg):
        if isinstance(msg, dict) and 'state' in msg \
                and 'docs' in msg:
            # multi-doc state bootstrap: clock bookkeeping now (the
            # horizon clocks are the sender's proven floor), payloads
            # buffered and absorbed at flush BEFORE any buffered data
            # — the tail in the same tick lands on absorbed state
            validate_state_msg(msg)
            self._note_peer_caps(msg)
            self.metrics.bump('sync_msgs_received')
            self.metrics.bump('sync_state_msgs_received')
            for doc_id, clock in zip(msg['docs'], msg['clocks']):
                self._their_clock = clock_union(self._their_clock,
                                                doc_id, clock)
            if SESSION_WARMUP and msg.get('warm') and \
                    min(self.wire_version,
                        self._peer_wire_version) >= 3:
                self._warm_from_state(msg)
            self._incoming_state.append(msg)
            return None
        if isinstance(msg, dict) and 'wire' in msg:
            validate_wire_msg(msg)
            self._note_peer_caps(msg)
            if msg['wire'] > self.wire_version:
                # a peer shipped a format newer than this side speaks —
                # reject loudly (a conforming sender never does this:
                # it pins to the receiver's advertised maxv)
                _reject(f"wire version {msg['wire']} not spoken here "
                        f"(max {self.wire_version})")
            if msg['wire'] >= 3:
                # resolve session refs NOW, in arrival order — the
                # rewrite into per-message-tab form happens before any
                # bookkeeping, so an unresolvable ref (table state
                # lost) aborts the whole delivery cleanly: the
                # envelope is never acked and the sender's retransmit
                # repairs it, exactly like a checksum drop
                msg = self._resolve_session_msg(msg)
            self.metrics.bump('sync_msgs_received')
            self.metrics.bump('sync_wire_msgs_received')
            if msg['wire'] == 2:
                self.metrics.bump('sync_wire_v2_msgs_received')
            elif msg['wire'] >= 3:
                self.metrics.bump('sync_wire_v3_msgs_received')
            # clock bookkeeping happens immediately, in arrival order —
            # exactly the dict data path
            for doc_id, clock in zip(msg['docs'], msg['clocks']):
                self._their_clock = clock_union(self._their_clock,
                                                doc_id, clock)
            self._incoming_wire.append(msg)
            # zero-change spans are advertisements and answer NOW (data
            # spans never trigger replies, like dict data messages);
            # unknown docs mark pending and go out as BATCHED requests
            # — zero-change spans with an empty clock in the next
            # outgoing wire message, not one dict message per doc
            for doc_id, count in zip(msg['docs'], msg['counts']):
                if count:
                    continue
                if self._doc_set.get_doc(doc_id) is not None:
                    self.maybe_send_changes(doc_id)
                elif doc_id not in self._our_clock:
                    self._pending_send[doc_id] = None
            return None
        return super().receive_msg(msg)

    receiveMsg = receive_msg

    def _note_peer_caps(self, msg):
        """Fold the negotiation stamps a peer's message carries:
        ``maxv`` (highest wire-blob format it speaks) and ``maxs``
        (highest state-bootstrap version) — the in-band capability
        advertisement every wire/state message repeats."""
        maxv = msg.get('maxv')
        if isinstance(maxv, int) and not isinstance(maxv, bool) \
                and maxv > self._peer_wire_version:
            self._peer_wire_version = min(maxv, self.wire_version)
        maxs = msg.get('maxs')
        if isinstance(maxs, int) and not isinstance(maxs, bool) \
                and maxs > self._peer_state_version:
            self._peer_state_version = min(maxs, STATE_VERSION)

    def _warm_from_state(self, msg):
        """The bootstrapping peer's half of wire-v3 warm-up: derive
        the served snapshots' actor/key literal list (identical to
        what the sender derived — same bytes, same helper) and
        pre-seed OUR session string table with it, entries acked, so
        the first warm flush back ships bare refs. Outgoing v3
        messages then carry the ``'warm'`` stamp until one acks,
        telling the sender to seed its receive map by enumerating the
        same list. Skipped whenever the table already allocated refs
        (warm refs must never collide with organic ones)."""
        if self._tx_table is not None and len(self._tx_table):
            return
        from .. import wire as _wire
        from ..compaction import state_warm_literals
        blob = memoryview(msg['blob'])
        chunks, pos = [], 0
        for ln in msg['lens']:
            chunks.append(blob[pos:pos + ln])
            pos += ln
        lits = state_warm_literals(chunks)
        if not lits:
            return
        if self._tx_table is None:
            table = self._tx_table = _wire.SessionStringTable()
            register = getattr(self._doc_set.store,
                               'register_wire_session', None)
            if register is not None:
                register(table)
        n = self._tx_table.warm(lits)
        if n:
            self._warm_announce = True
            self.metrics.bump('sync_wire_session_warmups')
            self.metrics.bump('sync_wire_warm_literals', n)

    def _resolve_session_msg(self, msg):
        """Rewrite one incoming v3 message from session-table form
        (spans referencing the peer's session-wide refs, ``tab``
        carrying this message's new defs) into the self-contained
        per-message-tab form the buffered flush consumes. Defs install
        idempotently (dup/retransmit-safe); an unknown ref raises
        ValueError — the sender defines every literal in EVERY message
        until one is acked, so this only happens when the receiver's
        table state is lost (e.g. a restart), and the unacked envelope
        repairs via retransmit, never quarantine."""
        from .. import wire as _wire
        sid = msg['sid']
        refs = self._rx_tables.get(sid)
        if refs is None:
            while len(self._rx_tables) >= 2:
                # drop the oldest epoch (insertion order): a sender
                # only ever speaks its newest sid, and retransmits of
                # a dead session die with their connection
                del self._rx_tables[next(iter(self._rx_tables))]
            refs = self._rx_tables[sid] = {}
        if msg.get('warm') and self._warm_served is not None \
                and not refs:
            # the peer warmed its session from OUR 'state' bootstrap:
            # its refs 0..n-1 are the literal list we recorded when we
            # served it, in enumerate order (setdefault-idempotent —
            # retransmits and organic defs never clash: the peer's
            # organic refs start past the warm block)
            for i, lit in enumerate(self._warm_served):
                refs[i] = lit
            self.metrics.bump('sync_wire_session_warmups')
        for ref, lit in _wire.decode_session_defs(msg['tab']):
            refs[ref] = lit
        try:
            entries = _wire.decode_session_spans(
                msg['blob'], msg['lens'], refs)
        except ValueError:
            self.metrics.bump('sync_wire_table_stale_refs')
            raise
        spans, tab = _wire.assemble_columnar_spans(entries)
        return {**msg, 'tab': tab, 'blob': b''.join(spans),
                'lens': [len(s) for s in spans]}

    def note_wire_acked(self, payload):
        """Envelope-layer feedback (the resilient shell's ack hook):
        a stored v3 wire payload was acknowledged — its defs become
        session-confirmed (bare references from now on) and its ref
        uses unpin. Stateless: the refs re-derive from the payload
        itself, so no per-seq side table exists to leak. Every acked
        payload clock (wire AND state) also advances the delta-clock
        baseline: those entries are proven delivered, so later
        adverts elide them."""
        if isinstance(payload, dict):
            docs = payload.get('docs')
            clocks = payload.get('clocks')
            if isinstance(docs, list) and isinstance(clocks, list) \
                    and len(docs) == len(clocks):
                for doc_id, clock in zip(docs, clocks):
                    if isinstance(clock, dict) and clock:
                        clock_union(self._adv_acked, doc_id, clock)
        if self._tx_table is None or not isinstance(payload, dict) \
                or payload.get('wire') != 3 \
                or payload.get('sid') != self._tx_table.sid:
            return
        from .. import wire as _wire
        def_refs, used = _wire.session_payload_refs(payload)
        self._tx_table.note_acked(def_refs, used)
        if self._warm_announce and payload.get('warm'):
            # a warm-stamped message acked: the peer decoded it, so
            # its receive map is provably seeded — stop stamping
            self._warm_announce = False

    def note_wire_dead(self, payload):
        """Envelope-layer feedback: a stored v3 wire payload died
        permanently (retry budget exhausted) — unpin its ref uses so
        eviction can reclaim them; its defs stay unconfirmed and
        re-define on next use."""
        if self._tx_table is None or not isinstance(payload, dict) \
                or payload.get('wire') != 3 \
                or payload.get('sid') != self._tx_table.sid:
            return
        from .. import wire as _wire
        _, used = _wire.session_payload_refs(payload)
        self._tx_table.note_dead(used)

    def note_clock_regressed(self, doc_id, clock):
        """Membership of the regression heal (resilient.py's
        heartbeat branch): the peer provably lost state down to
        ``clock`` — the delta baseline must regress with it, or later
        adverts would elide entries the peer no longer holds."""
        self._adv_acked[doc_id] = dict(clock)

    def _ship_clock(self, doc_id, clock, version, advert=False):
        """The clock dict actually SHIPPED for a doc: on a warm v3
        link, only the entries above the peer-acked baseline (the
        receiver reconstructs exactly by union — every elided entry
        already reached it inside an acked payload it folded).
        Adverts never collapse to {}: an empty clock on a zero-count
        span is protocol-identical to a REQUEST, so a fully-elided
        advert ships whole instead."""
        if version < 3:
            return dict(clock)
        base = self._adv_acked.get(doc_id)
        if not base:
            return dict(clock)
        delta = {a: s for a, s in clock.items()
                 if s > base.get(a, 0)}
        if advert and not delta and clock:
            return dict(clock)
        elided = len(clock) - len(delta)
        if elided:
            self.metrics.bump('sync_wire_clock_entries_elided',
                              elided)
        return delta

    def _flush_pending(self):
        return bool(self._incoming or self._incoming_wire
                    or self._incoming_state or self._pending_send)

    def _flush_work(self):
        """Apply the tick's buffered data: state bootstraps absorb
        FIRST (the tail buffered in the same tick lands on absorbed
        state), then dict messages through the batched dict path and
        wire blobs through ONE fused apply_wire; finally assemble and
        ship the single outgoing multi-doc wire message the tick's
        ``doc_changed`` follow-ups asked for. Returns {doc_id: doc}
        for the docs that changed — the body
        :meth:`BatchingConnection.flush` times and traces."""
        out = self._flush_state()
        out.update(self._flush_data())
        out.update(self._flush_wire())
        self._flush_outgoing()
        return out

    def _flush_state(self):
        """Absorb the tick's buffered state-bootstrap payloads in one
        batched ``apply_states`` (per-doc fault isolation inside)."""
        if not self._incoming_state:
            return {}
        payloads = {}                  # doc_id -> latest payload
        for msg in self._incoming_state:
            blob, lens = msg['blob'], msg['lens']
            pos = 0
            for doc_id, ln in zip(msg['docs'], msg['lens']):
                payloads[doc_id] = bytes(blob[pos:pos + ln])
                pos += ln
        self._incoming_state = []
        apply_states = getattr(self._doc_set, 'apply_states', None)
        if apply_states is None:
            self.metrics.bump('sync_msgs_rejected')
            return {}
        return apply_states(payloads)

    def _flush_wire(self):
        """Merge the buffered wire blobs per document and apply in one
        fused codec->stager pass per FORMAT: v1 JSON spans concatenate
        into the JSON multi-doc shape, v2 columnar spans (plus their
        messages' shared literal tabs) stitch into one AMW2 container,
        v3 spans (already rewritten to per-message-tab form at receive)
        into one AMW3 container — both zero-``json.loads`` paths. A
        mixed-version tick (v1/v2/v3 peers buffered together) costs at
        most one fused apply per format."""
        if not self._incoming_wire:
            return {}
        segs_by_doc = {}                 # v1: doc_id -> [json bytes]
        spans_by_doc = {}                # v2: doc_id -> [(tab_i, span)]
        spans3_by_doc = {}               # v3: doc_id -> [(tab_i, span)]
        tabs = []
        tabs3 = []
        n_changes = 0
        for msg in self._incoming_wire:
            blob, lens = msg['blob'], msg['lens']
            v = msg['wire']
            if v >= 3:
                tab_i = len(tabs3)
                tabs3.append(bytes(msg['tab']))
                bucket = spans3_by_doc
            elif v == 2:
                tab_i = len(tabs)
                tabs.append(bytes(msg['tab']))
                bucket = spans_by_doc
            else:
                bucket = segs_by_doc
            pos = 0
            k = 0
            for doc_id, count in zip(msg['docs'], msg['counts']):
                if not count:
                    continue
                segs = bucket.setdefault(doc_id, [])
                for ln in lens[k:k + count]:
                    span = blob[pos:pos + ln]
                    segs.append((tab_i, span) if v >= 2 else span)
                    pos += ln
                k += count
                n_changes += count
        self._incoming_wire = []
        if not segs_by_doc and not spans_by_doc and not spans3_by_doc:
            return {}
        self.metrics.bump('sync_changes_received', n_changes)
        out = {}
        if segs_by_doc:
            def decode_v1(segs):
                import json as _json
                return _json.loads(
                    (b'[' + b','.join(segs) + b']').decode('utf-8'))

            data = b'[' + b','.join(
                b'[' + b','.join(segs) + b']'
                for segs in segs_by_doc.values()) + b']'
            out.update(self._apply_wire_isolated(
                data, segs_by_doc, decode_v1))
        if spans_by_doc:
            from .. import wire as _wire

            def decode_v2(spans):
                data_1 = _wire.build_columnar_container(tabs, [spans])
                return _wire.columnar_container_to_changes(data_1)[0]

            data = _wire.build_columnar_container(
                tabs, list(spans_by_doc.values()))
            out.update(self._apply_wire_isolated(
                data, spans_by_doc, decode_v2))
        if spans3_by_doc:
            from .. import wire as _wire

            def decode_v3(spans):
                data_1 = _wire.build_columnar_container(
                    tabs3, [spans], version=3)
                return _wire.columnar_container_to_changes(data_1)[0]

            data = _wire.build_columnar_container(
                tabs3, list(spans3_by_doc.values()), version=3)
            out.update(self._apply_wire_isolated(
                data, spans3_by_doc, decode_v3))
        retry = getattr(self._doc_set, 'retry_quarantined', None)
        if retry is not None:
            held = [d for d in out if d in self._doc_set.quarantined]
            if held:
                retry(held)
        return out

    def _apply_wire_isolated(self, data, segs_by_doc, decode_doc):
        """One fused ``apply_wire`` with the per-document quarantine
        fallback: a fused-apply fault rolls back (store-intact-on-
        error) and the payload re-delivers doc by doc through the dict
        batch path, which isolates and quarantines the poisoned ones.
        ``decode_doc`` turns one doc's raw spans back into dict
        changes; a doc whose spans do not even decode (impossible
        under the checksummed envelope transport) quarantines with no
        retriable body."""
        doc_ids = list(segs_by_doc)
        try:
            handles = self._doc_set.apply_wire(data, doc_ids=doc_ids)
        except Exception:
            changes_by_doc = {}
            for doc_id, segs in segs_by_doc.items():
                try:
                    changes_by_doc[doc_id] = decode_doc(segs)
                except (ValueError, UnicodeDecodeError) as err:
                    registry = getattr(self._doc_set, 'quarantined',
                                       self.quarantined)
                    registry[doc_id] = {'error': repr(err),
                                        'changes': []}
                    self.metrics.bump('sync_docs_quarantined')
            return self._doc_set.apply_changes_batch(
                changes_by_doc, isolate=True)
        return dict(zip(doc_ids, handles))

    def _serve_state_bootstraps(self, served, errors, version):
        """The horizon answer of the serve path: docs whose requester
        clock predates the compaction horizon
        (:class:`~automerge_tpu.device.blocks.HorizonTruncated` in
        ``errors``) ship their recorded per-doc state snapshot in ONE
        ``'state'`` message, and their retained TAIL is re-served
        from the horizon clock into the tick's normal data message —
        cold-peer bootstrap lands in a single tick, O(state +
        divergence). Peers that never advertised ``maxs`` keep the
        legacy snapshot fallback (their error stays put)."""
        from ..device.blocks import HorizonTruncated
        if self._peer_state_version < 1:
            return
        store = self._doc_set.store
        ids = self._doc_set.ids
        horizon = getattr(store, 'horizon', None) or {}
        boot = {}
        for idx, err in list(errors.items()):
            rec = horizon.get(idx)
            if isinstance(err, HorizonTruncated) and rec is not None \
                    and rec.get('state') is not None:
                boot[idx] = rec
                del errors[idx]
        if not boot:
            return
        tail_served, tail_errors = store.get_missing_changes_wire_batch(
            [(idx, rec['clock']) for idx, rec in boot.items()],
            version=version)
        served.update(tail_served)
        errors.update(tail_errors)
        docs, clocks, lens, chunks = [], [], [], []
        for idx, rec in boot.items():
            if idx in tail_errors:
                continue
            doc_id = ids[idx]
            docs.append(doc_id)
            clocks.append(dict(rec['clock']))
            lens.append(len(rec['state']))
            chunks.append(rec['state'])
            # assume delivery up to the horizon (the resilient shell
            # rolls this back when the envelope dies), so the next
            # tick never re-ships the same snapshot
            clock_union(self._their_clock, doc_id, rec['clock'])
            clock_union(self._our_clock, doc_id, rec['clock'])
        if not docs:
            return
        blob = b''.join(chunks)
        msg = {'state': 1, 'docs': docs, 'clocks': clocks,
               'lens': lens, 'blob': blob, 'maxs': STATE_VERSION}
        if self.wire_version >= 2:
            msg['maxv'] = self.wire_version
        if SESSION_WARMUP and self._warm_served is None and \
                min(self.wire_version, self._peer_wire_version) >= 3:
            # wire-v3 warm-up offer: remember the literal list these
            # snapshots define and stamp the message, so the
            # bootstrapping peer may pre-seed its session table with
            # refs we can resolve (enumerating the SAME list)
            from ..compaction import state_warm_literals
            lits = state_warm_literals(chunks)
            if lits:
                self._warm_served = lits
                msg['warm'] = 1
        self.metrics.bump('sync_msgs_sent')
        self.metrics.bump('sync_state_msgs_sent')
        self.metrics.bump('sync_wire_bytes_sent', len(blob))
        if self.metrics.active:
            self.metrics.emit('sync_state_send', docs=len(docs),
                              blob_bytes=len(blob))
        self._send_msg(msg)

    def _flush_outgoing(self):
        """Assemble and ship the tick's single multi-doc wire message:
        cached change encodings for peers behind on data, zero-change
        spans as bundled advertisements. The serve is fleet-grained —
        one clock sweep and one batched cache fill
        (``get_missing_changes_wire_batch``: at most one native emit
        per retained block) regardless of how many docs the tick
        touched."""
        if not self._pending_send:
            return
        with self.metrics.trace_span('sync.flush_send',
                                     pending=len(self._pending_send)):
            self._flush_outgoing_traced()

    def _flush_outgoing_traced(self):
        pending = list(self._pending_send)
        self._pending_send.clear()
        # the negotiated DATA format for this peer: min(ours, their
        # advertised maxv) — v3 session columnar between two v3 ends,
        # v2 per-message columnar against a v2 peer, v1 JSON spans
        # until a peer advertises at all (and forever against v1)
        version = min(self.wire_version, self._peer_wire_version)
        # serving doc sets fault evicted docs back in before the serve
        # (a sync touch); docs the peer's clock already covers stay
        # evicted and report their RECORDED clock instead of the
        # store's (empty) one
        ensure = getattr(self._doc_set, 'ensure_resident', None)
        evicted_clocks = {}
        if ensure is not None:
            evicted_clocks = ensure(pending,
                                    peer_clocks=self._their_clock) \
                or {}
        store = self._doc_set.store
        id_of = self._doc_set.id_of
        if len(pending) > 16 and hasattr(store, 'clocks_all'):
            fleet_clocks = store.clocks_all()
            clock_of = lambda i: fleet_clocks.get(i, {})  # noqa: E731
        else:
            fleet_clocks = None
            clock_of = store.clock_of
        wants = []                       # (idx, have) for known peers
        for doc_id in pending:
            idx = id_of.get(doc_id)
            if idx is None or doc_id in evicted_clocks:
                continue
            if doc_id in self._their_clock:
                wants.append((idx, self._their_clock[doc_id]))
        if wants:
            with self.metrics.trace_span('wire.serve',
                                         docs=len(wants)) as span:
                served, errors = store.get_missing_changes_wire_batch(
                    wants, all_clocks=fleet_clocks, version=version)
                if self.metrics.active:
                    # the serve span carries the byte volume it served
                    # (trace_report's per-tick wire MB/s) — summed only
                    # under an observer, the idle path stays free
                    span.set(bytes=sum(
                        _entry_bytes(e) for blobs in served.values()
                        for e in blobs))
        else:
            served, errors = {}, {}
        if errors:
            self._serve_state_bootstraps(served, errors, version)
        docs, clocks, counts, chunks = [], [], [], []
        blob_bytes = 0
        data_docs = 0
        deferred = []
        for doc_id in pending:
            idx = id_of.get(doc_id)
            if idx is None:
                # a REQUEST: the peer advertised a doc we don't hold.
                # A zero-change span with an empty clock is protocol-
                # identical to the dict path's send_msg(doc_id, {}),
                # and the _our_clock entry (empty) suppresses repeat
                # requests exactly like the dict path
                if doc_id not in self._our_clock:
                    self._our_clock[doc_id] = {}
                    docs.append(doc_id)
                    clocks.append({})
                    counts.append(0)
                continue
            clock = evicted_clocks.get(doc_id)
            if clock is None:
                clock = clock_of(idx)
            if idx in errors:
                self._send_snapshot(doc_id, clock, errors[idx])
                continue
            blobs = served.get(idx)
            if blobs:
                size = sum(_entry_bytes(b) for b in blobs)
                if self.max_msg_bytes is not None and data_docs and \
                        blob_bytes + size > self.max_msg_bytes:
                    # over the per-message byte cap: this doc's data
                    # span (whole — clocks stay trivially exact) waits
                    # for the next tick's message. The first data span
                    # always ships, so an oversize single doc still
                    # makes progress.
                    deferred.append(doc_id)
                    continue
                blob_bytes += size
                data_docs += 1
                clock_union(self._their_clock, doc_id, clock)
                clock_union(self._our_clock, doc_id, clock)
                docs.append(doc_id)
                clocks.append(self._ship_clock(doc_id, clock,
                                               version))
                counts.append(len(blobs))
                chunks.extend(blobs)
                continue
            if clock != self._our_clock.get(doc_id, {}):
                clock_union(self._our_clock, doc_id, clock)
                docs.append(doc_id)
                clocks.append(self._ship_clock(doc_id, clock,
                                               version, advert=True))
                counts.append(0)
        if deferred:
            # carry past the cap to the next tick, in order; the
            # next serve re-reads the SAME cached encodings
            for doc_id in deferred:
                self._pending_send[doc_id] = None
            self.metrics.bump('sync_flow_deferred_docs',
                              len(deferred))
        self.metrics.set_gauge('sync_flow_backlog_docs',
                               len(self._pending_send))
        if not docs:
            return
        # assemble the data payload. Zero-data messages (pure
        # advertisement/request bundles) keep the v1 SHAPE whatever
        # the negotiated version — the v-stamp marks the payload
        # format, exactly the envelope-v pattern; `maxv` rides every
        # message a v2-capable sender ships, which is the whole
        # negotiation.
        tab_hits = tab_misses = 0
        if chunks and version >= 3:
            from .. import wire as _wire
            table = self._tx_table
            if table is None:
                table = self._tx_table = _wire.SessionStringTable()
                register = getattr(self._doc_set.store,
                                   'register_wire_session', None)
                if register is not None:
                    register(table)
            h0, m0, e0 = table.hits, table.misses, table.evictions
            spans, tab, _used = _wire.assemble_session_spans(
                chunks, table)
            tab_hits, tab_misses = table.hits - h0, table.misses - m0
            if table.evictions != e0:
                self.metrics.bump('sync_wire_table_evictions',
                                  table.evictions - e0)
            lens = [len(s) for s in spans]
            blob = b''.join(spans)
            msg = {'wire': 3, 'sid': table.sid, 'docs': docs,
                   'clocks': clocks, 'counts': counts, 'lens': lens,
                   'blob': blob, 'tab': tab}
            if self._warm_announce:
                msg['warm'] = 1
            self.metrics.bump('sync_wire_v3_msgs_sent')
            self.metrics.bump('sync_wire_def_bytes_sent', len(tab))
            self.metrics.bump('sync_wire_table_hits', tab_hits)
            self.metrics.bump('sync_wire_table_misses', tab_misses)
            self.metrics.set_gauge('sync_wire_table_entries',
                                   len(table))
            self.metrics.set_gauge('sync_wire_table_bytes',
                                   table.bytes)
            payload_bytes = len(blob) + len(tab)
        elif chunks and version >= 2:
            from .. import wire as _wire
            spans, tab = _wire.assemble_columnar_spans(chunks)
            lens = [len(s) for s in spans]
            blob = b''.join(spans)
            msg = {'wire': 2, 'docs': docs, 'clocks': clocks,
                   'counts': counts, 'lens': lens, 'blob': blob,
                   'tab': tab}
            self.metrics.bump('sync_wire_v2_msgs_sent')
            payload_bytes = len(blob) + len(tab)
        else:
            lens = [len(b) for b in chunks]
            blob = b''.join(chunks)
            msg = {'wire': 1, 'docs': docs, 'clocks': clocks,
                   'counts': counts, 'lens': lens, 'blob': blob}
            payload_bytes = len(blob)
        if self.wire_version >= 2:
            msg['maxv'] = self.wire_version
        msg['maxs'] = STATE_VERSION
        self.metrics.bump('sync_msgs_sent')
        self.metrics.bump('sync_wire_msgs_sent')
        self.metrics.bump('sync_changes_sent', len(lens))
        self.metrics.bump('sync_wire_bytes_sent', payload_bytes)
        if self.metrics.active:
            self.metrics.emit('sync_wire_send', docs=len(docs),
                              changes=len(lens), v=msg['wire'],
                              blob_bytes=payload_bytes,
                              tab_hits=tab_hits,
                              tab_misses=tab_misses)
        self._send_msg(msg)
